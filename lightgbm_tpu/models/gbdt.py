"""GBDT boosting driver.

Re-creates the reference `GBDT` (`src/boosting/gbdt.cpp`): per-iteration
gradient computation from the objective, bagging (plain + pos/neg balanced,
`gbdt.cpp:159-275`), per-class tree training, boost-from-average with the
bias folded back into the first trees (`gbdt.cpp:343-412`), shrinkage, score
updates for train/valid sets, early stopping, rollback, and model text
serialization (`gbdt_model_text.cpp`).

TPU structure: the host drives iterations (exactly the reference's
one-C-call-per-iteration shape, `basic.py:1846` -> `LGBM_BoosterUpdateOneIter`)
while gradients, histograms, splits, partitions and score updates are jitted
device programs. Scores are kept on device [K, N]; metrics pull them to host
once per eval.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import Dataset
from ..ops.metrics import Metric, create_metrics
from ..ops.objectives import ObjectiveFunction, create_objective
from ..ops.predict import TreePredictor, stack_trees, _predict_binned_stacked
from .device_learner import (DeviceTreeLearner, TreeRecord,
                             add_record_score, traversal_arrays)
from .serial_learner import SerialTreeLearner
from .tree import Tree

K_EPSILON = 1e-15


class LazyTree:
    """A tree still living on device as a TreeRecord; materialized to a host
    `Tree` only when the model surface needs it (export/predict)."""

    __slots__ = ("record", "shrinkage", "bias", "learner", "max_nodes")

    def __init__(self, record: TreeRecord, shrinkage: float, bias: float,
                 learner: DeviceTreeLearner, max_nodes: int) -> None:
        self.record = record
        self.shrinkage = shrinkage
        self.bias = bias
        self.learner = learner
        self.max_nodes = max_nodes

    def materialize(self, rec_host=None) -> Tree:
        rec = rec_host if rec_host is not None else jax.device_get(
            self.record)
        tree = self.learner.record_to_tree(rec, self.shrinkage)
        if abs(self.bias) > K_EPSILON:
            tree.add_bias(self.bias)
        return tree


class LazyAlignedTree(LazyTree):
    """A tree still living as a device AlignedSpec; the host leaf-wise
    replay runs at materialization (deterministically identical to the
    on-device replay that committed the tree)."""

    def materialize(self, rec_host=None) -> Tree:
        from .aligned_builder import replay_spec
        spec = rec_host if rec_host is not None else jax.device_get(
            self.record)
        record, _ = replay_spec(spec, self.learner.cfg.num_leaves)
        tree = self.learner.record_to_tree(record, self.shrinkage)
        if abs(self.bias) > K_EPSILON:
            tree.add_bias(self.bias)
        return tree


class _DeviceScoreView:
    """Duck-typed stand-in for _ScoreUpdater in _eval: a device [K, N]
    score matrix materialized on demand."""

    def __init__(self, score) -> None:
        self.score = score

    def numpy(self) -> np.ndarray:
        return np.asarray(self.score, np.float64)


class _ScoreUpdater:
    """Per-dataset cached raw scores (reference ScoreUpdater,
    score_updater.hpp:27-85)."""

    def __init__(self, num_data: int, num_class: int,
                 init_score: Optional[np.ndarray]) -> None:
        self.num_data = num_data
        self.num_class = num_class
        self.has_init_score = init_score is not None
        if init_score is not None:
            arr = np.asarray(init_score, np.float64).reshape(
                num_class, num_data)
            self.score = jnp.asarray(arr, jnp.float32)
        else:
            self.score = jnp.zeros((num_class, num_data), jnp.float32)

    def add_constant(self, val: float, class_id: int) -> None:
        self.score = self.score.at[class_id].add(jnp.float32(val))

    def multiply_score(self, factor: float, class_id: int) -> None:
        """reference ScoreUpdater::MultiplyScore (used by RF running
        average)."""
        self.score = self.score.at[class_id].multiply(jnp.float32(factor))

    def add_tree_by_leaves(self, leaves: jax.Array, leaf_values: np.ndarray,
                           class_id: int) -> None:
        """leaves: [N] leaf index per row; leaf_values: host array."""
        lv = jnp.asarray(leaf_values, jnp.float32)
        self.score = self.score.at[class_id].add(lv[leaves])

    def numpy(self) -> np.ndarray:
        return np.asarray(self.score, np.float64)


class GBDT:
    """reference `GBDT` (gbdt.h:41+)."""

    def _bundle_arrays(self):
        """(col, boff, bpk) for binned traversal when the training bins
        are EFB-bundled (valid sets share the training bundling)."""
        if getattr(self.learner, "bundled", False):
            lr = self.learner
            return (lr._col_dev, lr._boff_dev, lr._bpk_dev)
        return None

    _fused_ok = True  # subclass hook (no current subclass disables it)

    def __init__(self, cfg: Config, train_data: Dataset,
                 objective: Optional[ObjectiveFunction] = None) -> None:
        from ..utils.log import set_verbosity
        set_verbosity(int(cfg.verbosity))
        self.cfg = cfg
        self.train_data = train_data
        self.num_data = train_data.num_data
        self.objective = (objective if objective is not None
                          else create_objective(cfg))
        if self.objective is not None:
            self.objective.init(train_data.metadata, self.num_data)
        self.num_tree_per_iteration = (
            self.objective.num_model_per_iteration
            if self.objective is not None else max(1, cfg.num_class))
        self.shrinkage_rate = cfg.learning_rate
        self.models: List[Tree] = []
        self.iter = 0
        # fused on-device learner when the objective has no host-side leaf
        # renewal hook; host-driven serial learner otherwise
        # voting-parallel forced splits would read LOCAL histograms
        # against GLOBAL totals, and coupled-CEGB state is serial-only:
        # both route to the host twin (the reference's own learner)
        seq_host = ((bool(cfg.forcedsplits_filename)
                     and cfg.tree_learner == "voting")
                    or (len(cfg.cegb_penalty_feature_coupled) > 0
                        and cfg.tree_learner != "serial"))
        self.use_fused = (
            self._fused_ok
            and not (self.objective is not None
                     and getattr(self.objective, "is_renew_tree_output",
                                 False))
            and not cfg.forces_host_learner
            and not seq_host
            and cfg.tree_learner in ("serial", "data", "feature", "voting"))
        if self.use_fused:
            # the dist runtime owns topology: it resolves the shard
            # count (tpu_dist_devices / num_machines / all devices),
            # builds the mesh, pre-shards the dataset onto it, and
            # routes through parallel.make_parallel_learner. A 1-wide
            # mesh degenerates to the serial device learner.
            from ..dist import runtime as dist_runtime
            if cfg.tree_learner == "serial" or not dist_runtime.active(cfg):
                from ..utils import log
                if (getattr(train_data, "_bins_freed", False)
                        and getattr(train_data, "_bins", None) is None):
                    # stream-to-shard built per-device shards but the run
                    # degenerated to the serial learner (1-wide mesh or
                    # tpu_stream_shard="on" without a parallel learner):
                    # the first host-side bins read below re-gathers the
                    # full matrix from the mesh. Correct, but the O(n)
                    # host copy the sharded ingest avoided comes back.
                    log.warning(
                        "dataset was stream-sharded but the run routes to "
                        "the serial device learner; re-gathering the host "
                        "binned matrix (set tpu_stream_shard=off or widen "
                        "the mesh to avoid the extra copy)")
                self.learner = DeviceTreeLearner(cfg, train_data)
            else:
                self.learner = dist_runtime.make_learner(cfg, train_data)
            self._trav_nb = jnp.asarray(self.learner.meta["num_bin"],
                                        jnp.int32)
            self._trav_db = jnp.asarray(self.learner.meta["default_bin"],
                                        jnp.int32)
            self._trav_mt = jnp.asarray(self.learner.meta["missing_type"],
                                        jnp.int32)
        else:
            self.learner = SerialTreeLearner(cfg, train_data)
        self.train_score = _ScoreUpdater(
            self.num_data, self.num_tree_per_iteration,
            self._reshape_init_score(train_data))
        self.valid_sets: List[Dataset] = []
        self.valid_scores: List[_ScoreUpdater] = []
        self.valid_metrics: List[List[Metric]] = []
        self.train_metrics: List[Metric] = create_metrics(cfg)
        for m in self.train_metrics:
            m.init(train_data.metadata, self.num_data)
        self.best_iter: Dict[str, int] = {}
        self.best_score: Dict[str, float] = {}
        self._bag_rng = np.random.RandomState(cfg.bagging_seed)
        self.bag_data_indices: Optional[np.ndarray] = None
        self.bag_data_cnt = self.num_data
        self._label_np = (np.asarray(train_data.metadata.label, np.float64)
                          if train_data.metadata.label is not None
                          else np.zeros(self.num_data))
        self._weight_np = (np.asarray(train_data.metadata.weight, np.float64)
                           if train_data.metadata.weight is not None else None)
        self._balanced_bagging = (
            cfg.objective == "binary"
            and (cfg.pos_bagging_fraction < 1.0
                 or cfg.neg_bagging_fraction < 1.0))
        self._class_need_train = [True] * self.num_tree_per_iteration
        if self.objective is not None and hasattr(self.objective, "need_train"):
            self._class_need_train = [self.objective.need_train] \
                * self.num_tree_per_iteration
        self._pending_numsplits: List[jax.Array] = []
        self._valid_bins_dev: List[jax.Array] = []
        # telemetry (obs/): None when off — the round loop's ONLY added
        # cost on the default path is this attribute check
        self.telemetry = None
        self._obs_fallbacks_seen = 0
        if cfg.tpu_trace:
            from ..obs import ledger as obs_ledger
            from ..obs import trace as obs_trace
            tdir = cfg.tpu_trace_dir or "lgbt_trace"
            obs_trace.enable(tdir)
            self.telemetry = obs_ledger.RoundLedger.for_training(tdir, cfg)
        # live metrics plane (obs/metrics.py): None when off — the same
        # single-branch discipline as telemetry, and the metered path
        # never fences (host wall + counter deltas only)
        self._metrics = None
        self._obs_trees_seen = 0
        if cfg.tpu_metrics:
            from ..obs import metrics as obs_metrics
            obs_metrics.enable()
            self._metrics = obs_metrics.train_instruments()
        # HBM accountant (obs/memory.py): the training score buffers are
        # a named owner; registration is once-per-booster and read only
        # at snapshot time
        from ..obs import memory as obs_memory
        obs_memory.track(
            "train/scores", self,
            lambda g: int(g.train_score.score.nbytes)
            + sum(int(su.score.nbytes) for su in g.valid_scores))
        # resilience (resilience/): deterministic fault plan (param/env)
        # and the retry wrapper around device dispatches. None/False on
        # the default path — _dispatch_device is then a plain call
        self._fault_plan = None
        if cfg.tpu_fault_spec or os.environ.get("LGBT_FAULTS", ""):
            from ..resilience.faults import FaultPlan
            self._fault_plan = FaultPlan.from_config(
                cfg, telemetry=self.telemetry)
        # in-run bottleneck profiler (obs/profiler.py): None when off —
        # the round loop pays one is-None check, and _prof_round is only
        # non-None DURING a sampled round (the per-site fence seam in
        # _dispatch_device). With the profiler live, compile_cache also
        # starts capturing arg specs so program_costs.json can pair XLA
        # cost_analysis() with measured dispatch wall
        self._profiler = None
        self._prof_round = None
        if cfg.tpu_profile and str(cfg.tpu_profile).lower() != "off":
            from ..obs.profiler import RoundProfiler
            self._profiler = RoundProfiler.from_config(cfg)
            if self._profiler is not None:
                from .. import compile_cache
                compile_cache.enable_arg_capture()
        # unified timeline + watches (obs/timeline.py, obs/straggler.py):
        # off, the round loop pays one bool check and zero fences. On,
        # traced rounds feed the rolling-median anomaly watch (pure
        # host arithmetic over walls the trace fence already measured)
        # and profiler-sampled rounds on a multi-device mesh attribute
        # their fenced drains per shard for the straggler watch
        self._timeline = cfg.tpu_timeline == "on" or (
            cfg.tpu_timeline == "auto" and cfg.tpu_trace)
        self._anomaly = None
        self._straggler = None
        if self._timeline:
            from ..obs.straggler import AnomalyWatch, ImbalanceWatch
            if cfg.tpu_anomaly_factor > 0:
                self._anomaly = AnomalyWatch(
                    factor=cfg.tpu_anomaly_factor,
                    window=cfg.tpu_anomaly_window)
            self._straggler = ImbalanceWatch(
                threshold=cfg.tpu_straggler_threshold,
                rounds=cfg.tpu_straggler_rounds)

    @staticmethod
    def _reshape_init_score(ds: Dataset) -> Optional[np.ndarray]:
        if ds.metadata.init_score is None:
            return None
        return ds.metadata.init_score

    # ------------------------------------------------------------------
    def add_valid_dataset(self, ds: Dataset,
                          metrics: Optional[List[Metric]] = None) -> None:
        """reference GBDT::AddValidDataset (gbdt.cpp:119-147)."""
        self._valid_eval_stash = None   # stash indexed by old set count
        self.valid_sets.append(ds)
        su = _ScoreUpdater(ds.num_data, self.num_tree_per_iteration,
                           self._reshape_init_score(ds))
        if self.use_fused:
            self._valid_bins_dev.append(jnp.asarray(ds.bins))
        # replay existing model onto the new valid set
        if self.models:
            models = self.materialized_models()
            pred = TreePredictor(models)
            leaves = pred.predict_binned_leaves(ds.bins, self._bundle_arrays())
            for i, tree in enumerate(models):
                su.add_tree_by_leaves(leaves[i],
                                      tree.leaf_value[:tree.num_leaves],
                                      i % self.num_tree_per_iteration)
        self.valid_scores.append(su)
        ms = metrics if metrics is not None else create_metrics(self.cfg)
        for m in ms:
            m.init(ds.metadata, ds.num_data)
        self.valid_metrics.append(ms)

    # ------------------------------------------------------------------
    def _bagging(self, iter_idx: int) -> None:
        """reference GBDT::Bagging (gbdt.cpp:209-275) — per-chunk
        hypergeometric-ish sampling replaced by exact-count choice; balanced
        bagging keeps pos/neg fractions separately (gbdt.cpp:177-207)."""
        cfg = self.cfg
        need = (cfg.bagging_freq > 0
                and (cfg.bagging_fraction < 1.0 or self._balanced_bagging))
        if not need or iter_idx % cfg.bagging_freq != 0:
            return
        if self._balanced_bagging:
            pos = self._label_np > 0
            pos_idx = np.nonzero(pos)[0]
            neg_idx = np.nonzero(~pos)[0]
            take_pos = self._bag_rng.rand(len(pos_idx)) \
                < cfg.pos_bagging_fraction
            take_neg = self._bag_rng.rand(len(neg_idx)) \
                < cfg.neg_bagging_fraction
            sel = np.sort(np.concatenate([pos_idx[take_pos],
                                          neg_idx[take_neg]]))
        else:
            cnt = int(cfg.bagging_fraction * self.num_data)
            sel = np.sort(self._bag_rng.choice(self.num_data, cnt,
                                               replace=False))
        self.bag_data_indices = sel.astype(np.int32)
        self.bag_data_cnt = len(sel)

    # ------------------------------------------------------------------
    def boost_from_average(self, class_id: int) -> float:
        """reference GBDT::BoostFromAverage (gbdt.cpp:342-365)."""
        if (not self.models and not self.train_score.has_init_score
                and self.objective is not None
                and self.cfg.boost_from_average):
            init_score = self.objective.boost_from_score(class_id)
            if abs(init_score) > K_EPSILON:
                self.train_score.add_constant(init_score, class_id)
                for su in self.valid_scores:
                    su.add_constant(init_score, class_id)
                return init_score
        return 0.0

    def _gradients(self) -> Tuple[jax.Array, jax.Array]:
        pr = self._prof_round
        if pr is not None:
            return pr.timed(
                "objective.grad",
                lambda: self.objective.get_gradients(
                    self.get_training_score()))
        g, h = self.objective.get_gradients(self.get_training_score())
        return g, h

    def get_training_score(self) -> jax.Array:
        """Hook: DART drops trees from the returned score (dart.hpp:77-86)."""
        self._sync_train_score()
        return self.train_score.score

    def _post_bagging_gradients(self, gdev, hdev):
        """Hook: GOSS re-weights sampled small-gradient rows
        (goss.hpp:102-108)."""
        return gdev, hdev

    def apply_tree_to_score(self, su: "_ScoreUpdater", bins, tree: Tree,
                            class_id: int, scale: float = 1.0) -> None:
        """Add scale * tree(x) into a score updater via binned traversal."""
        pred = TreePredictor([tree])
        leaves = pred.predict_binned_leaves(bins, self._bundle_arrays())[0]
        su.add_tree_by_leaves(
            leaves, tree.leaf_value[:tree.num_leaves] * scale, class_id)

    # ------------------------------------------------------------------
    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """reference GBDT::TrainOneIter (gbdt.cpp:367-448). Returns True when
        training should STOP (no splittable tree), mirroring the C API's
        is_finished flag. With `tpu_trace` on, every round commits one
        ledger record (see _train_one_iter_traced); off, this is a
        single None check."""
        prof = self._profiler
        if prof is not None:
            prof.maybe_capture(self.iter)
            if prof.should_sample(self.iter):
                return self._train_one_iter_profiled(prof, grad, hess)
        if self.telemetry is None:
            if self._metrics is None:
                return self._train_one_iter_impl(grad, hess)
            return self._train_one_iter_metered(grad, hess)
        return self._train_one_iter_traced(grad, hess)

    def _dispatch_device(self, what: str, fn, *args):
        """Every learner/engine device dispatch funnels through here so
        the resilience layer can inject deterministic faults and retry
        transient device errors (resilience/retry.py), and the in-run
        profiler can fence each site on a sampled round (_prof_round is
        non-None only then). With no fault plan, no retries, and no
        active sample this is a plain call."""
        pr = self._prof_round
        plan = self._fault_plan
        if plan is None and self.cfg.tpu_retry_max <= 0:
            if pr is not None:
                return pr.timed(what, fn, *args)
            return fn(*args)
        from ..resilience.retry import call_with_retry
        if pr is not None:
            # fence OUTSIDE the retry wrapper: a retried dispatch's
            # whole recovery cost is device time the round really paid
            return pr.timed(what, lambda: call_with_retry(
                fn, args, what=what, plan=plan,
                max_retries=self.cfg.tpu_retry_max,
                backoff_s=self.cfg.tpu_retry_backoff_s,
                telemetry=self.telemetry))
        return call_with_retry(
            fn, args, what=what, plan=plan,
            max_retries=self.cfg.tpu_retry_max,
            backoff_s=self.cfg.tpu_retry_backoff_s,
            telemetry=self.telemetry)

    def _round_fence_target(self):
        """What to drain to observe this round's device time: the
        aligned engine's newest pending dispatch when the pipelined path
        is active (train_score is synced lazily there and would fence
        stale work), the score buffer otherwise."""
        pend = getattr(self, "_aligned_pending", None) or []
        if pend:
            return pend[-1]
        pend_mc = getattr(self, "_aligned_pending_mc", None)
        if pend_mc is not None:
            return pend_mc[0]
        return self.train_score.score

    def _dist_allreduce_probe(self) -> None:
        """Standalone histogram-shaped all-reduce through the fenced
        dispatch seam, run ONLY inside a profiler-sampled round on a
        mesh-parallel learner. The in-round psums are fused into the
        whole-tree build program, so their cost hides inside the "build"
        term; this probe times one histogram-sized `lax.psum` in
        isolation, giving the ledger a per-round collective floor
        (terms_ms["allreduce"], obs/terms.py) without touching the
        training programs."""
        if self._prof_round is None:
            return
        mesh = getattr(self.learner, "mesh", None)
        ax = getattr(self.learner, "axis_name", None)
        if mesh is None or ax is None or int(mesh.devices.size) < 2:
            return
        fn = getattr(self, "_allreduce_probe_fn", None)
        if fn is None:
            from jax.sharding import PartitionSpec as P

            from ..dist import shard_map as dist_shard_map
            from ..ops.histogram import NUM_HIST_STATS
            f = max(int(len(self.learner.meta["num_bin"])), 1)
            b = max(int(self.cfg.max_bin), 2)
            x = jnp.ones((f, b, NUM_HIST_STATS), jnp.float32)
            mapped = dist_shard_map(lambda h: jax.lax.psum(h, ax),
                                    mesh=mesh, in_specs=P(), out_specs=P())
            jfn = jax.jit(mapped)
            fn = lambda: jfn(x)            # noqa: E731 — tiny closure
            self._allreduce_probe_fn = fn
        self._dispatch_device("dist.allreduce", fn)

    def _train_one_iter_traced(self, grad, hess) -> bool:
        """One traced round: StepTraceAnnotation + span around the
        untouched implementation, ONE fence to split wall time into the
        host-visible part and the residual device drain, then a ledger
        commit. This path only runs when cfg.tpu_trace is set."""
        import time as _time

        from ..compile_cache import trace_count
        from ..obs import trace as obs_trace
        rnd = self.iter
        traces0 = trace_count()
        t0 = _time.perf_counter()
        with obs_trace.step(rnd):
            with obs_trace.span("train.round", round=rnd):
                finished = self._train_one_iter_impl(grad, hess)
                t_host = _time.perf_counter()
                with obs_trace.span("train.round.fence", round=rnd):
                    obs_trace.fence(self._round_fence_target())
        t1 = _time.perf_counter()
        eng = getattr(self, "_aligned_eng_ref", None)
        fb = int(getattr(eng, "fallbacks", 0) or 0) if eng is not None \
            else 0
        path = getattr(self, "_iter_path", "unknown")
        rec = {
            "kind": "round", "round": rnd,
            "wall_ms": round((t1 - t0) * 1e3, 3),
            "device_ms": round((t1 - t_host) * 1e3, 3),
            "traces": trace_count() - traces0,
            "path": path,
            "aligned": path.startswith("aligned"),
            "fallbacks": fb - self._obs_fallbacks_seen,
            "trees": len(self.models),
            "bag_cnt": int(self.bag_data_cnt),
            "finished": bool(finished),
            # raw perf_counter at round start: the timeline's clock
            # anchor (CLOCK_MONOTONIC — shared across processes on the
            # host, so spans/ledger/reqtrace join without alignment)
            "t0": round(t0, 6),
        }
        self._obs_fallbacks_seen = fb
        notes = list(getattr(self, "_gate_notes", ()) or ())
        if notes:
            rec["gate_notes"] = notes
            rec["hist_spill"] = any("spill" in n.lower() for n in notes)
        self.telemetry.commit(rec)
        if self._anomaly is not None:
            # residual-mode walls only: fenced (profiled) rounds
            # serialize the pipeline and would poison the median
            self._note_anomaly(rnd, rec["wall_ms"])
        if self._metrics is not None:
            self._note_round_metrics(rec["wall_ms"], rec["traces"],
                                     rec["fallbacks"])
        return finished

    def _note_anomaly(self, rnd: int, wall_ms: float) -> None:
        """Fold one traced round's wall into the rolling-median anomaly
        watch (obs/straggler.py — pure host arithmetic, zero fences). A
        deviation past tpu_anomaly_factor commits a ``round_anomaly``
        ledger note + event while the run can still react — a bench
        about to blow its budget says WHERE before the driver's kill."""
        hit = self._anomaly.update(wall_ms)
        if hit is None:
            return
        import time as _time

        from ..utils import log
        if self.telemetry is not None:
            self.telemetry.commit(
                {"kind": "note", "note": "round_anomaly", "round": rnd,
                 "wall_ms": round(wall_ms, 3),
                 "t0": round(_time.perf_counter(), 6), **hit})
        log.event("round_anomaly", round=rnd,
                  wall_ms=round(wall_ms, 3), **hit)

    def _note_straggler(self, rnd: int, dev: Dict[str, Any]) -> None:
        """Feed one profiled round's per-device imbalance ratio into
        the gauge + the edge-triggered straggler watch; a raise/clear
        transition commits a ``dist_straggler`` ledger note + event."""
        ratio = dev.get("imbalance")
        if ratio is None:
            return
        from ..obs import metrics as obs_metrics
        if obs_metrics.enabled():
            obs_metrics.registry().gauge(
                "dist_device_imbalance",
                "max/median per-device round time on the last "
                "profiled distributed round").set(float(ratio))
        edge = self._straggler.update(ratio)
        if edge is None:
            return
        import time as _time

        from ..utils import log
        if self.telemetry is not None:
            self.telemetry.commit(
                {"kind": "note", "note": "dist_straggler", "round": rnd,
                 "state": edge, "imbalance": ratio,
                 "t0": round(_time.perf_counter(), 6)})
        log.event("dist_straggler", round=rnd, state=edge,
                  imbalance=ratio,
                  devices=len(dev.get("device_ids", ())))

    def _train_one_iter_profiled(self, prof, grad, hess) -> bool:
        """One profiler-sampled round: drain the pipelined backlog, then
        run the untouched implementation with _prof_round set so every
        dispatch site fences individually (obs/profiler.py RoundSample).
        The resulting record carries timing="fenced" — device_ms is the
        SUM of fenced site times, NOT the residual-drain convention of
        _train_one_iter_traced — plus the canonical terms_ms; it is
        excluded from the train_round_ms histogram so sampled rounds
        cannot pollute p50/p99."""
        import time as _time

        from ..compile_cache import trace_count
        from ..obs import trace as obs_trace
        rnd = self.iter
        # drain queued work from previous (pipelined) rounds BEFORE t0
        # so the first fenced site doesn't absorb the backlog
        obs_trace.force_fence(self._round_fence_target())
        per_dev = False
        if self._timeline:
            mesh = getattr(self.learner, "mesh", None)
            per_dev = mesh is not None and int(mesh.devices.size) >= 2
        sample = prof.begin_round(rnd, per_device=per_dev)
        self._prof_round = sample
        traces0 = trace_count()
        t0 = _time.perf_counter()
        try:
            with obs_trace.step(rnd):
                with obs_trace.span("train.round.profiled", round=rnd):
                    finished = self._train_one_iter_impl(grad, hess)
                    # per-round collective visibility on parallel
                    # learners (terms_ms["allreduce"]); no-op off-mesh
                    self._dist_allreduce_probe()
                    # residual drain: device work not covered by a
                    # fenced site (host-applied trees, lazy syncs)
                    sample.timed("round_tail", self._round_fence_target)
        finally:
            self._prof_round = None
        t1 = _time.perf_counter()
        traces = trace_count() - traces0
        eng = getattr(self, "_aligned_eng_ref", None)
        # finish AFTER reading the trace delta: the one-time build
        # calibration compiles chained-k programs of its own
        terms = prof.finish_round(sample, engine=eng, cfg=self.cfg)
        fb = int(getattr(eng, "fallbacks", 0) or 0) if eng is not None \
            else 0
        path = getattr(self, "_iter_path", "unknown")
        rec = {
            "kind": "round", "round": rnd,
            "wall_ms": round((t1 - t0) * 1e3, 3),
            "device_ms": round(sample.device_total_ms(), 3),
            "traces": traces,
            "path": path,
            "aligned": path.startswith("aligned"),
            "fallbacks": fb - self._obs_fallbacks_seen,
            "trees": len(self.models),
            "bag_cnt": int(self.bag_data_cnt),
            "finished": bool(finished),
            "profiled": True,
            "timing": "fenced",
            "terms_ms": terms,
            "t0": round(t0, 6),
        }
        # per-device attribution (timeline on, multi-device mesh): the
        # fenced wait-attribution columns, their imbalance ratio, and
        # the allreduce compute-vs-wait split
        dev = sample.device_columns(prof.objective) if per_dev else None
        if dev is not None:
            rec.update(dev)
        self._obs_fallbacks_seen = fb
        notes = list(getattr(self, "_gate_notes", ()) or ())
        if notes:
            rec["gate_notes"] = notes
            rec["hist_spill"] = any("spill" in n.lower() for n in notes)
        if self.telemetry is not None:
            if prof.calibration is not None \
                    and not prof.calibration_committed:
                prof.calibration_committed = True
                self.telemetry.commit(
                    {"kind": "note", "note": "profile_calibration",
                     **prof.calibration})
            self.telemetry.commit(rec)
        if dev is not None and self._straggler is not None:
            self._note_straggler(rnd, dev)
        m = self._metrics
        if m is not None:
            # counters advance, but round_ms.observe is deliberately
            # SKIPPED: a fenced round's wall is not a residual-mode wall
            m.rounds.inc()
            if traces > 0:
                m.retraces.inc(traces)
            if rec["fallbacks"] > 0:
                m.fallbacks.inc(rec["fallbacks"])
            trees = len(self.models)
            if trees > self._obs_trees_seen:
                m.trees.inc(trees - self._obs_trees_seen)
            self._obs_trees_seen = trees
            for term, ms in terms.items():
                if ms is not None:
                    m.term_ms.labels(term=term).set(ms)
        return finished

    def _note_round_metrics(self, wall_ms: float, traces: int,
                            fallbacks: int) -> None:
        """Feed one completed round into the live metrics registry."""
        m = self._metrics
        m.rounds.inc()
        m.round_ms.observe(wall_ms)
        if traces > 0:
            m.retraces.inc(traces)
        if fallbacks > 0:
            m.fallbacks.inc(fallbacks)
        trees = len(self.models)
        if trees > self._obs_trees_seen:
            m.trees.inc(trees - self._obs_trees_seen)
        self._obs_trees_seen = trees

    def _train_one_iter_metered(self, grad, hess) -> bool:
        """Metrics-only round wrapper (`tpu_metrics` without
        `tpu_trace`): host wall + trace/fallback counter deltas, NO
        fence — wall_ms here is dispatch wall, not device wall, which is
        what keeps the enabled overhead in the sub-percent range."""
        import time as _time

        from ..compile_cache import trace_count
        traces0 = trace_count()
        t0 = _time.perf_counter()
        finished = self._train_one_iter_impl(grad, hess)
        wall_ms = (_time.perf_counter() - t0) * 1e3
        eng = getattr(self, "_aligned_eng_ref", None)
        fb = int(getattr(eng, "fallbacks", 0) or 0) if eng is not None \
            else 0
        self._note_round_metrics(wall_ms, trace_count() - traces0,
                                 fb - self._obs_fallbacks_seen)
        self._obs_fallbacks_seen = fb
        return finished

    def _train_one_iter_impl(self, grad: Optional[np.ndarray] = None,
                             hess: Optional[np.ndarray] = None) -> bool:
        cfg = self.cfg
        init_scores = [0.0] * self.num_tree_per_iteration
        if grad is None or hess is None:
            for k in range(self.num_tree_per_iteration):
                init_scores[k] = self.boost_from_average(k)
            if self._aligned_eligible():
                self._log_train_path("aligned")
                return self._train_one_iter_aligned(init_scores)
            if self._aligned_mc_eligible():
                self._log_train_path("aligned-mc")
                return self._train_one_iter_aligned_mc(init_scores)
            if self._mega_fused_eligible():
                self._log_train_path("mega-fused")
                return self._train_one_iter_mega(init_scores)
            gdev, hdev = self._gradients()
        else:
            gdev = jnp.asarray(np.asarray(grad, np.float32).reshape(
                self.num_tree_per_iteration, self.num_data))
            hdev = jnp.asarray(np.asarray(hess, np.float32).reshape(
                self.num_tree_per_iteration, self.num_data))
        self._cur_grad, self._cur_hess = gdev, hdev
        self._bagging(self.iter)
        gdev, hdev = self._post_bagging_gradients(gdev, hdev)

        if self.use_fused:
            self._log_train_path("fused")
            return self._train_one_iter_fused(gdev, hdev, init_scores)
        self._log_train_path("per-tree")

        should_continue = False
        for k in range(self.num_tree_per_iteration):
            new_tree = Tree(2)
            leaf_map = {}
            if self._class_need_train[k] and self.train_data.num_features > 0:
                new_tree, leaf_map = self._dispatch_device(
                    "learner.train", self.learner.train,
                    gdev[k], hdev[k], self.bag_data_indices,
                    self.bag_data_cnt)
            if new_tree.num_leaves > 1:
                should_continue = True
                if (self.objective is not None
                        and getattr(self.objective, "is_renew_tree_output",
                                    False)):
                    scores_np = self.train_score.numpy()[k]
                    self.learner.renew_tree_output(
                        new_tree, leaf_map, self.objective, scores_np,
                        self._label_np, self._weight_np)
                new_tree.apply_shrinkage(self.shrinkage_rate)
                self._update_score(new_tree, k)
                if abs(init_scores[k]) > K_EPSILON:
                    new_tree.add_bias(init_scores[k])
                self.models.append(new_tree)
            else:
                self._append_constant_tree(k, init_scores)

        if not should_continue:
            # keep the constant first iteration, drop later no-split ones
            # (gbdt.cpp:436-444)
            if len(self.models) > self.num_tree_per_iteration:
                del self.models[-self.num_tree_per_iteration:]
            return True
        self.iter += 1
        return False

    def _log_train_path(self, path: str) -> None:
        """One-shot INFO naming the chosen per-iteration training path
        (VERDICT r5 #8). When the aligned engine was NOT chosen, name the
        first failing gate so a mis-routed run is diagnosable from the
        log alone."""
        self._iter_path = path          # per-round, telemetry reads it
        if getattr(self, "_path_logged", False):
            return
        self._path_logged = True
        from ..utils import log
        msg = f"training path: {path}"
        notes: List[str] = []
        why = None
        if path.startswith("aligned"):
            # info gate-notes: the path IS aligned, but e.g. the
            # slot-hist store spilled to HBM — a different perf regime
            # the log must name (not a fallback)
            gate_notes = getattr(self.learner, "aligned_gate_notes", None)
            if gate_notes is not None:
                try:
                    for note in gate_notes():
                        notes.append(str(note))
                        msg += f" ({note})"
                except Exception:
                    pass
        if not path.startswith("aligned"):
            gate = getattr(self.learner, "aligned_mode_gate", None)
            if gate is not None:
                try:
                    why = gate(self.objective)
                except Exception:
                    why = None
                if why is None:
                    why = "gbdt-level eligibility (custom hooks, " \
                        "renew-output objective, or multi-tree class gating)"
            if why is not None:
                msg += f" (aligned engine rejected: {why})"
        self._gate_notes = notes
        log.info(msg)
        log.event("train_path", path=path, gate_notes=notes,
                  rejected=why)
        qb = int(getattr(self.learner, "quant_bits", 0) or 0)
        # quantization lives on the fused leaf-wise builders; the aligned
        # engine's packed records keep f32 gradient lanes, so under "auto"
        # an aligned route means the oracle ran
        active = qb > 0 and not path.startswith("aligned")
        if active:
            log.event("quant_hist", bits=qb,
                      dtype="int8" if qb == 8 else "int16", reason=None)
        elif str(self.cfg.tpu_quant_hist).lower() != "off":
            reason = getattr(self.learner, "_quant_why", None) \
                or f"{path} path keeps f32 payloads"
            log.event("quant_hist", bits=0, dtype="f32", reason=reason)

    def _note_aligned_fallback(self, eng, why: str) -> None:
        """Count an aligned exact-replay fallback on the engine and
        surface it on the structured channel; the ledger folds the
        counter delta into the next round record."""
        from ..utils import log
        eng.fallbacks = getattr(eng, "fallbacks", 0) + 1
        log.event("aligned_fallback", count=int(eng.fallbacks), why=why)

    def _append_constant_tree(self, k: int, init_scores) -> Tree:
        """Constant tree carrying the init score (gbdt.cpp:413-433): only the
        first iteration's constant trees hold an output; later no-split
        iterations append blanks."""
        t = Tree(2)
        if len(self.models) < self.num_tree_per_iteration:
            if not self._class_need_train[k] and self.objective is not None:
                output = self.objective.boost_from_score(k)
            else:
                output = init_scores[k]
            t.as_constant_tree(output)
            if abs(output) > K_EPSILON:
                self.train_score.add_constant(output, k)
                for su in self.valid_scores:
                    su.add_constant(output, k)
        self.models.append(t)
        return t

    # ------------------------------------------------------------------
    def _apply_record_to_valid_scores(self, rec, trav=None,
                                      class_id: int = 0):
        """Add one tree record's predictions to every valid-set score
        (shared by the fused/mega/aligned iteration paths)."""
        cfg = self.cfg
        for i, su in enumerate(self.valid_scores):
            if trav is None:
                trav = traversal_arrays(rec, max(cfg.num_leaves - 1, 1))
            vb = self._valid_bins_dev[i]
            bundled = getattr(self.learner, "bundled", False)
            su.score = su.score.at[class_id].set(
                add_record_score(su.score[class_id], vb, trav,
                                 self._trav_nb, self._trav_db,
                                 self._trav_mt,
                                 jnp.float32(self.shrinkage_rate),
                                 self.learner._col_dev if bundled else None,
                                 self.learner._boff_dev if bundled else None,
                                 self.learner._bpk_dev if bundled else None))
        return trav

    def _aligned_eligible(self) -> bool:
        """Chunk-aligned pipeline (models/aligned_builder.py): the fastest
        path — persistent permuted records, Pallas partition + histogram
        kernels, gradients evaluated in permuted order. Restrictions
        mirror _mega_fused_eligible plus the learner's aligned_mode_ok
        (numerical features, pointwise single-class objective)."""
        return (self.use_fused
                and (type(self.learner) is DeviceTreeLearner
                     or getattr(self.learner, "mode", "") == "data")
                and not getattr(self, "_aligned_disabled", False)
                and self.num_tree_per_iteration == 1
                and self._class_need_train[0]
                and self.train_data.num_features > 0
                and self.objective is not None
                and not getattr(self.objective, "is_renew_tree_output",
                                False)
                and self.learner.aligned_mode_ok(self.objective)
                ) and (
                type(self).get_training_score is GBDT.get_training_score
                ) and (
                type(self)._post_bagging_gradients
                is GBDT._post_bagging_gradients)

    def _aligned_mc_eligible(self) -> bool:
        """Multiclass on the aligned engine: K score lanes + per-class
        grad lanes written from pre-iteration scores, one build program
        per class with deferred leaf-value application (VERDICT r3 item
        3; reference trains K trees per iteration, gbdt.cpp:415-444)."""
        return (self.use_fused
                and type(self.learner) is DeviceTreeLearner
                and not getattr(self, "_aligned_disabled", False)
                and self.num_tree_per_iteration > 1
                and all(self._class_need_train)
                and self.train_data.num_features > 0
                and self.objective is not None
                and not getattr(self.objective, "is_renew_tree_output",
                                False)
                and self.learner.aligned_mode_ok(self.objective)
                ) and (
                type(self).get_training_score is GBDT.get_training_score
                ) and (
                type(self)._post_bagging_gradients
                is GBDT._post_bagging_gradients)

    def _train_one_iter_aligned_mc(self, init_scores) -> bool:
        """One multiclass boosting iteration on the aligned engine: K
        chained class-tree dispatches (no sync), exactness resolved one
        iteration behind like the single-class path."""
        cfg = self.cfg
        K = self.num_tree_per_iteration
        eng = getattr(self, "_aligned_eng_ref", None)
        if eng is None:
            eng = self.learner.aligned_engine(
                self.objective,
                init_row_scores=np.asarray(self.train_score.score),
                bagged=self._will_bag(), num_class=K)
            self._aligned_eng_ref = eng
        self._maybe_rebag(eng)
        fmasks = [self.learner.feature_mask() for _ in range(K)]
        outs = [self._dispatch_device(
                    "engine.train_iter_mc",
                    eng.train_iter_mc, k, self.shrinkage_rate, fmasks[k])
                for k in range(K)]
        # resolve the PREVIOUS iteration while this one runs on device
        redo = self._resolve_aligned_pending_mc()
        if redo is not None:
            # an inexact class in the previous iteration: this
            # iteration's dispatches are chain-gated score no-ops —
            # rebuild the failed iteration exactly, then redispatch
            # this one on the SAME masks and bag draw
            stop = self._aligned_mc_fallback(redo)
            if stop:
                return True
            outs = [eng.train_iter_mc(k, self.shrinkage_rate, fmasks[k])
                    for k in range(K)]
        for k, (spec, ncommit, _exact, _applied) in enumerate(outs):
            self.models.append(LazyAlignedTree(
                spec, self.shrinkage_rate, init_scores[k], self.learner,
                max(cfg.num_leaves - 1, 1)))
            self._pending_numsplits.append(ncommit)
        self.iter += 1
        self._train_score_stale = True
        self._aligned_pending_mc = (
            [o[2] for o in outs], [o[0] for o in outs],
            [o[3] for o in outs], list(init_scores), fmasks,
            self.bag_data_indices, self.bag_data_cnt)
        # valid-set scores: committed-tree walks per class, gated by the
        # device-side chain flags (a later-discarded dispatch adds 0)
        pr = self._prof_round
        for i, su in enumerate(self.valid_scores):
            def _walk(su=su, i=i):
                sc = su.score
                for k, (spec, _nc, _ex, applied) in enumerate(outs):
                    sc = eng.apply_spec_to_scores(
                        sc, k, self._valid_bins_dev[i], spec, applied,
                        self.shrinkage_rate)
                return sc
            su.score = (_walk() if pr is None
                        else pr.timed("score_update", _walk))
        if self.valid_scores:
            def _stash_evals():
                st = []
                for su, ms in zip(self.valid_scores,
                                  self.valid_metrics):
                    st.append([m.eval_dev(su.score, self.objective)
                               for m in ms])
                return st
            self._valid_eval_stash = (
                _stash_evals() if pr is None
                else pr.timed("eval", _stash_evals))
        if len(self._pending_numsplits) >= 16 * K:
            res = self._resolve_aligned_pending_mc()
            if res is not None:
                stop = self._aligned_mc_fallback(res)
                if stop:
                    return True
            return self._trim_trailing_empty()
        return False

    def _resolve_aligned_pending_mc(self):
        """Pull the pending multiclass iteration's exact flags (ONE
        device_get). None when clean; otherwise the pending tuple plus
        the first inexact class index, with the iteration's trees
        already discarded."""
        pending = getattr(self, "_aligned_pending_mc", None)
        if pending is None:
            return None
        self._aligned_pending_mc = None
        exact_flags = [bool(x) for x in
                       jax.device_get(jnp.stack(pending[0]))]
        if all(exact_flags):
            return None
        K = self.num_tree_per_iteration
        del self.models[-K:]
        del self._pending_numsplits[-K:]
        self.iter -= 1
        j = exact_flags.index(False)
        return pending + (j,)

    def _aligned_mc_fallback(self, info) -> bool:
        """Exact rebuild of a multiclass iteration whose class j replay
        was inexact. Classes 0..j-1 already applied (train lanes AND
        valid walks, chain gates were true at their application time):
        undo them with the committed-tree walker at -shrinkage, restore
        row scores, rebuild all K trees through the fused whole-tree
        programs on the same bag draw and feature masks, and reset the
        engine lanes + exactness chain."""
        cfg = self.cfg
        (_flags, specs, applieds, init_scores, fmasks,
         bag_idx, bag_cnt, j) = info
        K = self.num_tree_per_iteration
        eng = self._aligned_eng_ref
        self._note_aligned_fallback(eng, "multiclass inexact replay")
        self._valid_eval_stash = None
        self._train_eval_stash = None
        scores = eng.row_scores_mc_dev()               # [K, N], no pull
        train_bins = self.learner.bins_dev
        for k in range(j):
            scores = eng.apply_spec_to_scores(
                scores, k, train_bins, specs[k], applieds[k],
                -self.shrinkage_rate)
            for i, su in enumerate(self.valid_scores):
                su.score = eng.apply_spec_to_scores(
                    su.score, k, self._valid_bins_dev[i], specs[k],
                    applieds[k], -self.shrinkage_rate)
        self.train_score.score = scores
        self._train_score_stale = False
        # exact rebuild (fused whole-tree programs, reference per-class
        # loop gbdt.cpp:415-444) on the restored pre-iteration scores
        gdev, hdev = self.objective.get_gradients(scores)
        bagged = self._will_bag() and bag_idx is not None
        for k in range(K):
            if bagged:
                idxs, count = self.learner.init_root_partition(
                    bag_idx, bag_cnt)
                idxs, rec = self.learner.train(gdev[k], hdev[k], idxs,
                                               count, fmasks[k])
            else:
                idxs, rec = self.learner.train_fresh(gdev[k], hdev[k],
                                                     fmasks[k])
            lazy = LazyTree(rec, self.shrinkage_rate, init_scores[k],
                            self.learner, max(cfg.num_leaves - 1, 1))
            self.models.append(lazy)
            trav = traversal_arrays(rec, max(cfg.num_leaves - 1, 1))
            self.train_score.score = self.train_score.score.at[k].set(
                self.learner.add_score(self.train_score.score[k], trav,
                                       self.shrinkage_rate))
            self._apply_record_to_valid_scores(rec, trav=trav,
                                               class_id=k)
            self._pending_numsplits.append(rec.num_splits)
        eng.reset_mc(self.train_score.score)
        self.iter += 1
        if len(self._pending_numsplits) >= 16 * K:
            return self._trim_trailing_empty()
        return False

    def _train_one_iter_aligned(self, init_scores) -> bool:
        """One boosting iteration on the aligned engine. The engine owns
        the training scores (a record lane, permuted); train_score is
        synced lazily via _sync_train_score().

        PIPELINED: the exactness flag of iteration i-1 is pulled AFTER
        dispatching iteration i, hiding the host round-trip (~120 ms on
        the tunneled runtime) behind device compute. This is safe
        because an inexact program leaves the score lane untouched, so
        the speculatively-dispatched successor deterministically
        rebuilds the same tree and is discarded along with it."""
        cfg = self.cfg
        eng = getattr(self, "_aligned_eng_ref", None)
        if eng is None:
            eng = self.learner.aligned_engine(
                self.objective,
                init_row_scores=np.asarray(self.train_score.score[0]),
                bagged=self._will_bag())
            self._aligned_eng_ref = eng
        stash = getattr(self, "_aligned_next", None)
        if stash is not None:
            # this iteration was dispatched EAGERLY at the end of the
            # previous call (before its blocking metric eval), keeping
            # the device busy through per-iteration valid evals
            self._aligned_next = None
            out, fmask, _rng_snap = stash
        else:
            self._maybe_rebag(eng)
            fmask = self.learner.feature_mask()
            out = self._dispatch_aligned(eng, fmask)
        # resolve PREVIOUS iterations while this one runs on device.
        # With metric rounds / bagging this checks the one pending round
        # (depth 1); on the pure training loop the flags accumulate and
        # are pulled in ONE batched device_get every
        # _aligned_pipeline_depth() rounds — no per-round blocking sync
        redo = self._resolve_aligned_pending(final=False)
        if redo is not None:
            if redo[0] == "caught_up":
                # an older queued round was inexact: it was rebuilt
                # exactly and its successors replayed inside the
                # resolve; only the current dispatch needs a redo
                if redo[1]:
                    return True
                out = self._dispatch_aligned(eng, fmask)
            else:
                # previous tree was inexact: the current dispatch rebuilt
                # the same (failed) tree on unchanged scores — discard
                # it, grow the failed tree exactly, then dispatch this
                # iteration fresh
                self._note_aligned_fallback(
                    eng, "speculative successor discarded")
                stop = self._aligned_fallback_iter(redo[1], eng, redo[2],
                                                   redo[3], redo[4])
                if stop:
                    return True
                out = self._dispatch_aligned(eng, fmask)
        spec, ncommit_dev, exact_dev, applied_dev = out
        self._train_score_stale = True
        lazy = LazyAlignedTree(spec, self.shrinkage_rate, init_scores[0],
                               self.learner, max(cfg.num_leaves - 1, 1))
        self.models.append(lazy)
        self._pending_numsplits.append(ncommit_dev)
        self.iter += 1
        # the bag draw is stashed with the pending iteration: a fallback
        # must rebuild tree i on the SAME bag mask the device build used,
        # not on the next iteration's freshly-resampled one
        q = getattr(self, "_aligned_pending", None) or []
        q.append((exact_dev, list(init_scores),
                  fmask if fmask is None else fmask.copy(),
                  self.bag_data_indices, self.bag_data_cnt))
        self._aligned_pending = q
        # valid-set scores: walk the committed tree ON DEVICE from the
        # spec, still pipelined — the walk is gated by the program's own
        # applied flag, so a dispatch the host later discards (inexact
        # predecessor / fallback) contributed exactly 0 and the exact
        # fallback's host application stays correct
        pr = self._prof_round
        for i, su in enumerate(self.valid_scores):
            # the whole [K, Nv] buffer is donated and updated in place
            # at lane 0 — no gather/scatter copy pair per valid set
            if pr is not None:
                su.score = pr.timed(
                    "score_update", eng.apply_spec_to_scores,
                    su.score, 0, self._valid_bins_dev[i], spec,
                    applied_dev, self.shrinkage_rate)
            else:
                su.score = eng.apply_spec_to_scores(
                    su.score, 0, self._valid_bins_dev[i], spec,
                    applied_dev, self.shrinkage_rate)
        if self.valid_scores:
            # queue the device metric programs for THIS iteration before
            # the eager next build: the device executes in queue order,
            # so eval scalars resolve right after the walks instead of
            # behind the whole next build
            def _stash_evals():
                st = []
                for su, ms in zip(self.valid_scores,
                                  self.valid_metrics):
                    st.append([m.eval_dev(su.score, self.objective)
                               for m in ms])
                return st
            self._valid_eval_stash = (
                _stash_evals() if pr is None
                else pr.timed("eval", _stash_evals))
            # train metrics likewise (valid_sets often include the train
            # set): queue device scalars over the materialized score
            # lane so per-iteration train eval doesn't have to discard
            # the eager dispatch. Gated on eval_train having actually
            # been called (otherwise every iteration would pay a wasted
            # full-N materialization + metric program)
            self._train_eval_stash = None
            if (getattr(self, "_train_eval_wanted", False)
                    and self.train_metrics and all(
                        type(m).eval_dev is not Metric.eval_dev
                        for m in self.train_metrics)):
                view = eng.row_scores_dev()[None, :]
                self._train_eval_stash = [
                    m.eval_dev(view, self.objective)
                    for m in self.train_metrics]
            # per-iteration eval is about to BLOCK on this iteration's
            # completion; dispatch the next build now so the device never
            # idles (if training stops instead, _discard_eager undoes the
            # speculative tree's score-lane contribution AND restores the
            # column/bag sampling RNG state its preparation consumed)
            rng_snap = (self.learner._feat_rng.get_state()
                        if hasattr(self.learner, "_feat_rng") else None,
                        self._bag_rng.get_state(),
                        self.bag_data_indices, self.bag_data_cnt)
            self._maybe_rebag(eng)
            fmask_n = self.learner.feature_mask()
            self._aligned_next = (self._dispatch_aligned(eng, fmask_n),
                                  fmask_n, rng_snap)
        if len(self._pending_numsplits) >= 16 * self.num_tree_per_iteration:
            res = self._resolve_aligned_pending(final=True)
            if res is not None and res[1]:
                return True
            return self._trim_trailing_empty()
        return False

    def _maybe_rebag(self, eng) -> None:
        """Resample on bagging_freq boundaries and re-ingest the 0/1 mask
        into the bag lane (gbdt.cpp:209-275; the engine's histograms and
        gradients honor it, the physical layout keeps ALL rows so
        out-of-bag rows still get scores)."""
        cfg = self.cfg
        if not (self._will_bag() and self.iter % cfg.bagging_freq == 0):
            return
        self._bagging(self.iter)
        mask = np.zeros(self.num_data, np.float32)
        if self.bag_data_indices is not None:
            mask[self.bag_data_indices] = 1.0
        else:
            mask[:] = 1.0
        eng.set_bag(mask)

    def _discard_eager(self) -> None:
        """Drop a speculatively-dispatched next iteration: undo its
        (gated) score-lane contribution so the engine lane is
        authoritative again. f32 add-then-subtract restore is exact to
        metric tolerance; nothing else of the dispatch is visible."""
        stash = getattr(self, "_aligned_next", None)
        if stash is None:
            return
        self._aligned_next = None
        (spec, _nc, _ex, applied_dev), _fmask, rng_snap = stash
        eng = self._aligned_eng_ref
        eng.undo_spec_scores(spec, applied_dev, self.shrinkage_rate)
        # rewind the sampling state the eager preparation consumed so a
        # later re-dispatch draws the same mask/bag as a non-eager run
        feat_state, bag_state, bag_idx, bag_cnt = rng_snap
        if feat_state is not None:
            self.learner._feat_rng.set_state(feat_state)
        self._bag_rng.set_state(bag_state)
        self.bag_data_indices = bag_idx
        self.bag_data_cnt = bag_cnt

    def _dispatch_aligned(self, eng, fmask):
        grads = None
        if eng._pgrad is None:
            # non-pointwise objective (ranking): gradients need ROW order
            # — materialize scores on device, compute, re-ingest by rid
            pr = self._prof_round
            if pr is not None:
                # the materialization exists only to feed the ranking
                # gradient, so both dispatches bill to the grad site
                # (→ rank_grad for ranking objectives)
                gd, hd = pr.timed(
                    "objective.grad",
                    lambda: self.objective.get_gradients(
                        eng.row_scores_dev()[None, :]))
            else:
                scores = eng.row_scores_dev()
                gd, hd = self.objective.get_gradients(scores[None, :])
            grads = (gd[0], hd[0])
        return self._dispatch_device(
            "engine.train_iter",
            lambda: eng.train_iter(self.shrinkage_rate, fmask, grads=grads))

    def _aligned_pipeline_depth(self) -> int:
        """How many dispatched rounds may stay unresolved before the
        host pulls their exactness flags. Per-iteration metric evals,
        bagging, and multiclass sync every round anyway, so they keep
        depth 1 (the classic one-behind pipeline). The pure training
        loop (the bench hot path) batches 8 rounds per pull: one
        device_get per 8 iterations instead of per iteration. Safe
        because an inexact round's successors are chain-gated score
        no-ops — on failure they are discarded and replayed on their
        original column draws, reproducing the depth-1 sequence
        bit-exactly (and fallbacks measure ZERO at the default
        tpu_level_spec=4.5 budget, so the recovery path is cold)."""
        if (self.valid_scores or self._will_bag()
                or self.num_tree_per_iteration > 1):
            return 1
        return 8

    def _resolve_aligned_pending(self, final: bool):
        """Resolve queued speculative rounds' exactness flags (one
        batched device_get — see _aligned_pipeline_depth). Returns:
        - None: queue not full yet, or every queued round was exact;
        - ("redo", init_scores, fmask, bag_idx, bag_cnt): final=False
          and the NEWEST queued round was inexact (popped; the caller
          discards its identical in-flight dispatch, grows the round
          exactly, and re-dispatches);
        - ("caught_up", stop): final=False and an OLDER queued round was
          inexact — it was rebuilt exactly and its discarded successors
          replayed in here; the caller re-dispatches the current round;
        - ("fellback", stop): final=True and a round was inexact: the
          exact fallback (+ successor replays) already ran; `stop` is
          the stop signal."""
        q = getattr(self, "_aligned_pending", None)
        if not q:
            return None
        if not final and len(q) < self._aligned_pipeline_depth():
            return None
        self._aligned_pending = None
        if len(q) == 1:
            flags = [bool(q[0][0])]
        else:
            flags = [bool(v) for v in
                     jax.device_get(jnp.stack([p[0] for p in q]))]
        if all(flags):
            return None
        j = flags.index(False)
        # round j left the score lane untouched, so trees j+1.. were
        # built on stale scores with a false chain gate: discard them
        # all along with tree j
        drop = len(q) - j
        del self.models[-drop:]
        del self._pending_numsplits[-drop:]
        self.iter -= drop
        if not final and j == len(q) - 1:
            return ("redo",) + tuple(q[j][1:])
        eng = self._aligned_eng_ref
        self._note_aligned_fallback(eng, "inexact replay in pending batch")
        stop = self._aligned_fallback_iter(q[j][1], eng, q[j][2],
                                           q[j][3], q[j][4])
        for (_e, init_r, fmask_r, _bi, _bc) in q[j + 1:]:
            if stop:
                break
            stop = self._aligned_replay_round(eng, init_r, fmask_r)
        if final:
            return ("fellback", stop)
        return ("caught_up", stop)

    def _aligned_replay_round(self, eng, init_scores, fmask) -> bool:
        """Re-dispatch one discarded pipeline round on its ORIGINAL
        column draw and resolve it synchronously. Only runs during
        batched-pipeline failure recovery (depth > 1 implies no bagging
        and no valid sets, so there is no bag mask to restore and no
        valid walk to replay)."""
        spec, ncommit_dev, exact_dev, _applied = \
            self._dispatch_aligned(eng, fmask)
        if not bool(exact_dev):
            self._note_aligned_fallback(eng, "inexact replay")
            return self._aligned_fallback_iter(init_scores, eng, fmask)
        self._train_score_stale = True
        lazy = LazyAlignedTree(spec, self.shrinkage_rate, init_scores[0],
                               self.learner,
                               max(self.cfg.num_leaves - 1, 1))
        self.models.append(lazy)
        self._pending_numsplits.append(ncommit_dev)
        self.iter += 1
        return False

    def _aligned_fallback_iter(self, init_scores, eng, fmask,
                               bag_idx=None, bag_cnt=0) -> bool:
        # (callers guarantee no unresolved pending iteration here)
        """Exact leaf-wise tree for an iteration whose speculative build
        could not be replayed exactly (the aligned analogue of the level
        builder's fallback). `bag_idx`/`bag_cnt` = the bag draw the
        failed device build trained on."""
        cfg = self.cfg
        # any stashed metric scalars were computed on pre-fallback scores
        self._valid_eval_stash = None
        self._train_eval_stash = None
        self._sync_train_score()
        gdev, hdev = self._gradients()
        bagged = self._will_bag() and bag_idx is not None
        if bagged:
            # mirror the fused bagged branch: partition over the bagged
            # subset, score update via traversal (covers OOB rows too)
            idxs, count = self.learner.init_root_partition(
                bag_idx, bag_cnt)
            idxs, rec = self.learner.train(gdev[0], hdev[0], idxs, count,
                                           fmask)
        else:
            idxs, rec = self.learner.train_fresh(gdev[0], hdev[0], fmask)
        lazy = LazyTree(rec, self.shrinkage_rate, init_scores[0],
                        self.learner, max(cfg.num_leaves - 1, 1))
        self.models.append(lazy)
        if bagged:
            trav = traversal_arrays(rec, max(cfg.num_leaves - 1, 1))
            self.train_score.score = self.train_score.score.at[0].set(
                self.learner.add_score(self.train_score.score[0], trav,
                                       self.shrinkage_rate))
            self._apply_record_to_valid_scores(rec, trav=trav)
        else:
            self.train_score.score = self.learner.add_score_from_partition(
                self.train_score.score, 0, rec, idxs, self.shrinkage_rate)
            self._apply_record_to_valid_scores(rec)
        eng.set_row_scores(self.train_score.score[0])
        self._train_score_stale = False
        self._pending_numsplits.append(rec.num_splits)
        self.iter += 1
        if len(self._pending_numsplits) >= 16 * self.num_tree_per_iteration:
            return self._trim_trailing_empty()
        return False

    def _sync_train_score(self) -> None:
        """Materialize row-order training scores from the aligned engine
        (lazy: only metrics / renewal / rollback need them)."""
        self._discard_eager()
        self._resolve_aligned_pending(final=True)
        res = self._resolve_aligned_pending_mc()
        if res is not None:
            self._aligned_mc_fallback(res)
        if getattr(self, "_train_score_stale", False):
            eng = getattr(self, "_aligned_eng_ref", None)
            if eng is not None:
                if getattr(eng, "num_class", 1) > 1:
                    self.train_score.score = jnp.asarray(
                        eng.row_scores_mc())
                else:
                    self.train_score.score = jnp.asarray(
                        eng.row_scores())[None, :]
            self._train_score_stale = False

    def _drop_aligned(self) -> None:
        """Leave aligned mode permanently (rollback and other mutations
        the permuted engine state cannot follow)."""
        self._discard_eager()
        self._resolve_aligned_pending(final=True)
        self._sync_train_score()
        self._aligned_disabled = True
        self._aligned_eng_ref = None
        if hasattr(self.learner, "drop_aligned_engine"):
            self.learner.drop_aligned_engine()

    # ------------------------------------------------------------------
    def _mega_fused_eligible(self) -> bool:
        """Whole-iteration single-program path: gradients + tree build +
        score update traced together (per-program launches cost ~100-200ms
        on a tunneled runtime). Requires: fused learner on a single device,
        one tree per iteration, no bagging this iteration, a jit-traceable
        objective (no host-side gradient composition like lambdarank), and
        no DART-style score reshaping."""
        return (self.cfg.tpu_fuse_iteration
                and self.use_fused
                and type(self.learner) is DeviceTreeLearner
                and self.num_tree_per_iteration == 1
                and self._class_need_train[0]
                and self.train_data.num_features > 0
                and not self._will_bag()
                ) and (
                type(self.objective).get_gradients
                is ObjectiveFunction.get_gradients
                ) and (
                type(self).get_training_score is GBDT.get_training_score
                ) and (
                type(self)._post_bagging_gradients
                is GBDT._post_bagging_gradients)

    def _will_bag(self) -> bool:
        cfg = self.cfg
        need = (cfg.bagging_freq > 0
                and (cfg.bagging_fraction < 1.0 or self._balanced_bagging))
        return bool(need)

    def _train_one_iter_mega(self, init_scores) -> bool:
        """One fused device program per boosting iteration."""
        cfg = self.cfg
        fmask = self.learner.feature_mask()
        new_score, idxs, rec = self._dispatch_device(
            "learner.train_iter_fused", self.learner.train_iter_fused,
            self.train_score.score, self.objective, self.shrinkage_rate,
            fmask)
        self.train_score.score = new_score
        lazy = LazyTree(rec, self.shrinkage_rate, init_scores[0],
                        self.learner, max(cfg.num_leaves - 1, 1))
        self.models.append(lazy)
        self._apply_record_to_valid_scores(rec)
        self._pending_numsplits.append(rec.num_splits)
        self.iter += 1
        if len(self._pending_numsplits) >= 16 * self.num_tree_per_iteration:
            return self._trim_trailing_empty()
        return False

    def _trim_trailing_empty(self) -> bool:
        """Deferred empty-tree check shared by the fused paths
        (gbdt.cpp:436-444 batched)."""
        ns = [int(x) for x in jax.device_get(self._pending_numsplits)]
        self._pending_numsplits = []
        k = self.num_tree_per_iteration
        empty_trailing = 0
        for it in range(len(ns) // k - 1, -1, -1):
            if max(ns[it * k:(it + 1) * k]) == 0:
                empty_trailing += 1
            else:
                break
        if empty_trailing and len(self.models) > k:
            drop = min(empty_trailing * k, len(self.models) - k)
            del self.models[-drop:]
            self.iter -= drop // k
            return True
        return False

    def _train_one_iter_fused(self, gdev, hdev, init_scores) -> bool:
        """Fused path: whole-tree device programs, no mid-iteration host
        syncs; empty-tree detection is deferred and batched."""
        cfg = self.cfg
        bagged = self.bag_data_indices is not None
        any_trained = False
        for k in range(self.num_tree_per_iteration):
            # fresh column sample per tree, like SerialTreeLearner
            fmask = self.learner.feature_mask()
            if not self._class_need_train[k] \
                    or self.train_data.num_features == 0:
                self._append_constant_tree(k, init_scores)
                # keep exactly k pending entries per iteration so the
                # batched trim and rollback arithmetic stay aligned
                self._pending_numsplits.append(0)
                continue
            any_trained = True
            if not bagged:
                # fresh identity partition created inside the fused program:
                # contiguous root histogram, no init-partition dispatch
                idxs, rec = self._dispatch_device(
                    "learner.train_fresh", self.learner.train_fresh,
                    gdev[k], hdev[k], fmask)
            else:
                idxs, count = self.learner.init_root_partition(
                    self.bag_data_indices, self.bag_data_cnt)
                idxs, rec = self._dispatch_device(
                    "learner.train", self.learner.train,
                    gdev[k], hdev[k], idxs, count, fmask)
            lazy = LazyTree(rec, self.shrinkage_rate, init_scores[k],
                            self.learner, max(cfg.num_leaves - 1, 1))
            self.models.append(lazy)
            if not bagged:
                # partition-based score update: leaf fill + one key-sort back
                # to row order (no per-level tree traversal); one fused
                # program with the score buffer donated
                self.train_score.score = \
                    self.learner.add_score_from_partition(
                        self.train_score.score, k, rec, idxs,
                        self.shrinkage_rate)
                trav = None
            else:
                # bagged: out-of-bag rows also need scores -> traversal
                trav = traversal_arrays(rec, max(cfg.num_leaves - 1, 1))
                self.train_score.score = self.train_score.score.at[k].set(
                    self.learner.add_score(self.train_score.score[k], trav,
                                           self.shrinkage_rate))
            self._apply_record_to_valid_scores(rec, trav=trav, class_id=k)
            self._pending_numsplits.append(rec.num_splits)
        if not any_trained:
            # nothing trainable this iteration: mirror the non-fused
            # immediate stop (gbdt.cpp:436-444) — keep a constant first
            # iteration, drop later no-op ones
            k = self.num_tree_per_iteration
            del self._pending_numsplits[-k:]
            if len(self.models) > k:
                del self.models[-k:]
            return True
        self.iter += 1
        # deferred empty-tree check: one batched pull every N iterations;
        # trailing all-empty iterations are trimmed like the reference's
        # immediate stop (gbdt.cpp:436-444)
        if len(self._pending_numsplits) >= 16 * self.num_tree_per_iteration:
            return self._trim_trailing_empty()
        return False

    def materialized_models(self) -> List[Tree]:
        """Convert any LazyTree records to host Trees in ONE batched
        device->host transfer."""
        if getattr(self, "_aligned_pending", None) is not None:
            self._resolve_aligned_pending(final=True)
        lazies = [(i, m) for i, m in enumerate(self.models)
                  if isinstance(m, LazyTree)]
        if lazies:
            recs = jax.device_get([m.record for _, m in lazies])
            for (i, m), rec in zip(lazies, recs):
                self.models[i] = m.materialize(rec)
        return self.models

    # ------------------------------------------------------------------
    def _update_score(self, tree: Tree, class_id: int) -> None:
        """reference GBDT::UpdateScore (gbdt.cpp:487-506): train scores via
        one binned traversal (covers in-bag and out-of-bag rows alike), valid
        scores likewise."""
        pred = TreePredictor([tree])
        leaves = pred.predict_binned_leaves(self.train_data.bins, self._bundle_arrays())[0]
        self.train_score.add_tree_by_leaves(
            leaves, tree.leaf_value[:tree.num_leaves], class_id)
        for ds, su in zip(self.valid_sets, self.valid_scores):
            vleaves = pred.predict_binned_leaves(ds.bins, self._bundle_arrays())[0]
            su.add_tree_by_leaves(vleaves,
                                  tree.leaf_value[:tree.num_leaves], class_id)

    def rollback_one_iter(self) -> None:
        """reference GBDT::RollbackOneIter (gbdt.cpp:450-466)."""
        if self.iter <= 0:
            return
        if getattr(self, "_aligned_eng_ref", None) is not None:
            self._drop_aligned()
        # drop the rolled-back iteration's deferred empty-tree records so the
        # batched trim stays aligned with self.models
        if self._pending_numsplits:
            del self._pending_numsplits[-self.num_tree_per_iteration:]
        self.materialized_models()
        start = len(self.models) - self.num_tree_per_iteration
        for k in range(self.num_tree_per_iteration):
            tree = self.models[start + k]
            if tree.num_leaves > 1:
                # subtract the tree's contribution (Shrinkage(-1) + AddScore)
                pred = TreePredictor([tree])
                leaves = pred.predict_binned_leaves(self.train_data.bins, self._bundle_arrays())[0]
                self.train_score.add_tree_by_leaves(
                    leaves, -tree.leaf_value[:tree.num_leaves], k)
                for ds, su in zip(self.valid_sets, self.valid_scores):
                    vleaves = pred.predict_binned_leaves(ds.bins, self._bundle_arrays())[0]
                    su.add_tree_by_leaves(
                        vleaves, -tree.leaf_value[:tree.num_leaves], k)
        del self.models[-self.num_tree_per_iteration:]
        self.iter -= 1

    # ------------------------------------------------------------------
    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        self._train_eval_wanted = True
        # aligned engine: evaluate from a DEVICE score view when every
        # metric supports it — the permuted->row materialization stays on
        # device instead of bouncing [N] f32 through the host
        eng = getattr(self, "_aligned_eng_ref", None)
        stash = getattr(self, "_train_eval_stash", None)
        if eng is not None and stash is not None:
            self._resolve_aligned_pending(final=True)
            st = getattr(self, "_train_eval_stash", None)
            if st is not None:      # no fallback invalidated it
                self._train_eval_stash = None
                out = []
                for m, dev in zip(self.train_metrics, st):
                    for mname, val in dev:
                        out.append(("training", mname, float(val),
                                    m.bigger_is_better))
                return out
        if (eng is not None and self.train_metrics
                and all(type(m).eval_dev is not Metric.eval_dev
                        for m in self.train_metrics)):
            self._discard_eager()
            self._resolve_aligned_pending(final=True)
            if getattr(self, "_train_score_stale", False):
                view = _DeviceScoreView(eng.row_scores_dev()[None, :])
                return self._eval(view, self.train_metrics, "training")
        self._sync_train_score()
        return self._eval(self.train_score, self.train_metrics, "training")

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        # an inexact pending aligned iteration contributed 0 to the valid
        # scores (applied gate): resolve it NOW so the exact fallback tree
        # is applied before its metrics are recorded
        fell_back = self._resolve_aligned_pending(final=True) is not None
        stash = getattr(self, "_valid_eval_stash", None)
        self._valid_eval_stash = None
        out = []
        for i, (su, ms) in enumerate(zip(self.valid_scores,
                                         self.valid_metrics)):
            name = f"valid_{i}"
            if stash is not None and not fell_back:
                # pre-queued device scalars (resolve ahead of the eager
                # next build in the device queue); host-only metrics
                # still evaluate here
                scores = None
                if any(d is None for d in stash[i]):
                    scores = su.numpy()
                for m, dev in zip(ms, stash[i]):
                    pairs = (dev if dev is not None
                             else m.eval(scores, self.objective))
                    for mname, val in pairs:
                        out.append((name, mname, float(val),
                                    m.bigger_is_better))
            else:
                # fallback replaced the tree (stashed scalars were
                # computed on pre-fallback scores) — evaluate fresh
                out.extend(self._eval(su, ms, name))
        return out

    def _eval(self, su, metrics: List[Metric],
              name: str) -> List[Tuple[str, str, float, bool]]:
        if not metrics:
            return []
        # dispatch all device-capable metrics first (async), then emit in
        # the USER'S metric order — first_metric_only early stopping keys
        # on position 0 of the result list
        dev_vals = [m.eval_dev(su.score, self.objective) for m in metrics]
        scores = su.numpy() if any(d is None for d in dev_vals) else None
        out = []
        for m, dev in zip(metrics, dev_vals):
            pairs = (dev if dev is not None
                     else m.eval(scores, self.objective))
            for mname, val in pairs:
                out.append((name, mname, float(val), m.bigger_is_better))
        return out

    # ------------------------------------------------------------------
    @property
    def num_iterations_trained(self) -> int:
        return self.iter

    def predict_raw(self, X: np.ndarray,
                    num_iteration: Optional[int] = None,
                    device: Optional[bool] = None) -> np.ndarray:
        """Raw scores for a dense matrix [N, F_total] -> [N, K]
        (predictor.hpp:66-115 semantics). `device=True` (or
        tpu_predict_device=on) routes through the serve engine's cached
        depth-synchronized traversal; leaf routing there is bit-exact vs
        the host walk, only the value sum runs in f32."""
        self.materialized_models()
        trees = self._trees_for(num_iteration)
        n = len(X)
        k = self.num_tree_per_iteration
        if device is None:
            device = str(getattr(self.cfg, "tpu_predict_device", "auto")
                         ).lower() in ("on", "device", "true", "1")
        if device and trees:
            from ..serve import ForestEngine
            eng = getattr(self, "_serve_eng", None)
            if eng is None:
                eng = ForestEngine(trees, num_class=k)
                self._serve_eng = eng
            else:
                eng.update(trees)
            return eng.predict(X)[0]
        from ..ops.predict import predict_raw_values
        out = np.zeros((n, k), np.float64)
        for cls in range(k):
            cls_trees = trees[cls::k]
            if cls_trees:
                out[:, cls] = predict_raw_values(cls_trees, X)
        return out

    def _trees_for(self, num_iteration: Optional[int]) -> List[Tree]:
        if num_iteration is None or num_iteration < 0:
            return self.models
        return self.models[:num_iteration * self.num_tree_per_iteration]
