"""Fused on-device tree builder: ONE jitted program grows a whole tree.

Why: the host-driven `SerialTreeLearner` issues ~15 host<->device syncs per
split; on a tunneled TPU each sync costs ~100ms, dwarfing compute. This
learner keeps the entire leaf-wise loop (reference
`SerialTreeLearner::Train`, serial_tree_learner.cpp:173-237) inside one
`lax.while_loop`: per-leaf state, the histogram pool
(reference HistogramPool, feature_histogram.hpp:654), the partition, and the
recorded splits all live in device arrays. Dynamic leaf sizes are handled by
a `lax.switch` over power-of-two size buckets — each branch compiles its own
statically-shaped gather + MXU histogram / stable partition.

TPU-profile-driven layout choices (v5e measurements):
- random row gathers are the dominant cost (~10-16 ns/element through XLA's
  gather lowering), so the ROOT histogram reads the binned matrix
  contiguously whenever the partition is the identity (fresh per-tree
  partitions make that the common case), and per-split work is bucketed to
  the smaller child's power-of-two size;
- a TRANSPOSED copy of the bins (`bins_T[F, N]`) makes the split feature's
  column a contiguous `dynamic_slice`, and the stable partition carries row
  ids through the sort network as a payload operand (no argsort+gather);
- the per-leaf best-split/record state lives in a few PACKED [L, 8]-wide
  arrays rather than ~26 scalar arrays — each split updates 6 rows, not 40,
  which keeps the sequential tiny-op chain per split short;
- `lax.while_loop` (not fori_loop+cond) stops the program at the last real
  split, so early-stopped trees don't pay for the remaining leaf budget.

The host pulls nothing during training; a finished tree is a `TreeRecord`
pytree of device arrays, convertible to a host `Tree` (one batched transfer)
only when the model is exported, and convertible to traversal arrays
on-device for score updates.
"""
from __future__ import annotations

import functools
import math
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import compile_cache
from ..config import Config
from ..io.dataset import Dataset
from ..ops.histogram import (NUM_HIST_STATS, histogram_from_gathered_gh,
                             quantize_gh)
from ..ops.partition import (categorical_goes_left, leaf_value_fill,
                             numerical_goes_left, split_partition,
                             unpermute_to_rows)
from ..ops.split import SplitHyper, make_split_finder
from .tree import Tree

NEG_INF = -jnp.inf

# packed per-leaf "best split" float lanes
BF_GAIN, BF_LG, BF_LH, BF_RG, BF_RH, BF_LOUT, BF_ROUT = range(7)
BF_W = 8
# packed per-leaf "best split" int lanes
BI_FEAT, BI_THR, BI_LC, BI_RC, BI_DEFLEFT, BI_ISCAT = range(6)
BI_W = 8
# packed per-leaf float state lanes
LF_SG, LF_SH, LF_MINC, LF_MAXC, LF_VALUE = range(5)
LF_W = 8
# packed per-leaf int state lanes
LI_BEGIN, LI_COUNT, LI_COUNTG, LI_DEPTH = range(4)
LI_W = 8
# packed per-split record float lanes
RF_LOUT, RF_ROUT, RF_GAIN, RF_IVAL = range(4)
RF_W = 4
# packed per-split record int lanes
RI_LEAF, RI_FEAT, RI_THR, RI_DEFLEFT, RI_ISCAT, RI_LC, RI_RC = range(7)
RI_W = 8


class TreeRecord(NamedTuple):
    """Per-split records of one grown tree (device pytree).

    The level builder (level_builder.py) replays speculated splits on the
    host and emits a NumPy TreeRecord whose physical partition is FINER
    than the committed tree; there the block_* fields carry the
    (begin, count, covering committed leaf value) tables that the
    partition score update consumes instead of the leaf_* fields.
    """
    num_splits: jax.Array          # i32 scalar: actual splits made
    leaf: jax.Array                # i32[L-1] leaf id split at step s
    feature: jax.Array             # i32[L-1] inner feature index
    threshold_bin: jax.Array       # i32[L-1]
    default_left: jax.Array        # bool[L-1]
    is_cat: jax.Array              # bool[L-1]
    cat_bitset: jax.Array          # u32[L-1, 8] (bins)
    left_output: jax.Array         # f32[L-1]
    right_output: jax.Array        # f32[L-1]
    left_count: jax.Array          # i32[L-1]
    right_count: jax.Array         # i32[L-1]
    gain: jax.Array                # f32[L-1]
    internal_value: jax.Array      # f32[L-1] (parent output before split)
    leaf_value: jax.Array          # f32[L] final leaf outputs
    leaf_count_arr: jax.Array      # i32[L]
    leaf_begin: jax.Array          # i32[L] partition begins
    leaf_cnt_part: jax.Array       # i32[L]
    block_begin: Optional[jax.Array] = None    # i32[S] physical blocks
    block_cnt: Optional[jax.Array] = None      # i32[S]
    block_value: Optional[jax.Array] = None    # f32[S] covering leaf value


def _pow2ceil(n: int) -> int:
    return 1 << max(0, int(math.ceil(math.log2(max(n, 1)))))


def pack_best_payload(out: Dict, gain: jax.Array):
    """Pack the winning feature's split into (vecF, vecI, bitset) rows —
    shared by the leaf-wise and level builders (BF_*/BI_* lanes)."""
    f = jnp.argmax(gain)
    vecF = jnp.zeros(BF_W, jnp.float32)
    vecF = vecF.at[BF_GAIN].set(gain[f])
    vecF = vecF.at[BF_LG].set(out["left_g"][f])
    vecF = vecF.at[BF_LH].set(out["left_h"][f])
    vecF = vecF.at[BF_RG].set(out["right_g"][f])
    vecF = vecF.at[BF_RH].set(out["right_h"][f])
    vecF = vecF.at[BF_LOUT].set(out["left_output"][f])
    vecF = vecF.at[BF_ROUT].set(out["right_output"][f])
    vecI = jnp.zeros(BI_W, jnp.int32)
    vecI = vecI.at[BI_FEAT].set(f.astype(jnp.int32))
    vecI = vecI.at[BI_THR].set(out["threshold"][f])
    vecI = vecI.at[BI_LC].set(out["left_c"][f])
    vecI = vecI.at[BI_RC].set(out["right_c"][f])
    vecI = vecI.at[BI_DEFLEFT].set(out["default_left"][f].astype(jnp.int32))
    vecI = vecI.at[BI_ISCAT].set(out["is_cat"][f].astype(jnp.int32))
    return vecF, vecI, out["cat_bitset"][f]


def bucket_table(min_pad: int, root_count: int) -> List[int]:
    """~sqrt(2)-spaced leaf-size table (pow2 plus 1.5x midpoints rounded
    up to 512) for the dynamic-leaf switch: the average pad factor on the
    gather/histogram/partition work drops from ~1.5x to ~1.2x for ~2x the
    compiled branches."""
    cands = []
    s = min_pad
    while True:
        cands.append(s)
        mid = (s * 3 // 2 + 511) & ~511
        if mid > s:
            cands.append(mid)
        if s >= root_count:
            break
        s <<= 1
    out = []
    for sz in sorted(set(cands)):
        out.append(sz)
        if sz >= root_count:
            break
    return out


@functools.partial(jax.jit, static_argnames=("max_nodes",))
def record_to_children(leaf_rec: jax.Array, num_splits: jax.Array,
                       max_nodes: int) -> Tuple[jax.Array, jax.Array]:
    """Reconstruct left/right child links from the split sequence.

    Node s split leaf `leaf_rec[s]` into left=same leaf id, right=s+1.
    left_child[s] -> the NEXT step that splits leaf_rec[s] (as a node), else
    ~leaf_rec[s]; right_child[s] -> the next step that splits leaf s+1, else
    ~(s+1).  O(L^2) vectorized — trivial next to histogram work.
    """
    s_idx = jnp.arange(max_nodes)
    later = (s_idx[None, :] > s_idx[:, None]) \
        & (s_idx[None, :] < num_splits)

    def next_split_of(target):  # target: [max_nodes] leaf ids
        hit = later & (leaf_rec[None, :] == target[:, None])
        any_hit = hit.any(axis=1)
        first = jnp.argmax(hit, axis=1)
        return any_hit, first

    l_hit, l_first = next_split_of(leaf_rec)
    left = jnp.where(l_hit, l_first, ~leaf_rec)
    r_leaf = s_idx + 1
    r_hit, r_first = next_split_of(r_leaf)
    right = jnp.where(r_hit, r_first, ~r_leaf)
    return left.astype(jnp.int32), right.astype(jnp.int32)


class DeviceTreeLearner:
    """Drop-in replacement for SerialTreeLearner with zero mid-tree syncs.

    With ``axis_name`` set, the same whole-tree program becomes the
    data-parallel learner (reference `DataParallelTreeLearner`,
    `data_parallel_tree_learner.cpp`): rows are sharded over a mesh axis,
    local histograms are `lax.psum`-reduced (the XLA/ICI analogue of
    `Network::ReduceScatter` + best-split allreduce — since every shard then
    holds the GLOBAL histogram, the best split is computed redundantly and
    identically on all shards, so no separate `SyncUpGlobalBestSplit` is
    needed), and leaf counts split into a LOCAL set driving the per-shard
    partition and a GLOBAL set driving split decisions (the reference's
    `global_data_count_in_leaf_`, data_parallel_tree_learner.cpp:251-257).
    Collectives sit inside the while-loop body, which is safe because every
    shard makes identical split decisions from the identical (global)
    histograms and therefore iterates the loop the same number of times.
    """

    def __init__(self, cfg: Config, dataset: Dataset,
                 axis_name: Optional[str] = None,
                 parallel_mode: Optional[str] = None,
                 feature_pad_to: Optional[int] = None,
                 mesh_size: int = 1) -> None:
        self.cfg = cfg
        self.axis_name = axis_name
        # serial (single program) / data (rows sharded, psum histograms) /
        # feature (rows replicated, feature-block histogram work division) /
        # voting (rows sharded, top-k vote + selected-feature reduce)
        self.parallel_mode = parallel_mode or (
            "data" if axis_name is not None else "serial")
        self.mesh_size = mesh_size
        self.ds = dataset
        self.n = dataset.num_data
        self.num_real_features = dataset.num_features
        meta = dataset.feature_meta_arrays()
        if feature_pad_to and feature_pad_to > len(meta["num_bin"]):
            # pad the feature axis so it divides evenly over the mesh
            # (feature-parallel block slicing); padded features are trivial
            # (num_bin=2, no data) and masked out of every split search
            pad = feature_pad_to - len(meta["num_bin"])
            meta = dict(meta)
            meta["num_bin"] = np.concatenate(
                [meta["num_bin"], np.full(pad, 2, meta["num_bin"].dtype)])
            for key, fill in (("default_bin", 0), ("missing_type", 0),
                              ("bin_type", 0), ("monotone", 0)):
                meta[key] = np.concatenate(
                    [meta[key], np.full(pad, fill, meta[key].dtype)])
            meta["penalty"] = np.concatenate(
                [meta["penalty"], np.ones(pad, meta["penalty"].dtype)])
        self.num_features = len(meta["num_bin"])
        self.meta = meta
        self.max_bin_global = int(meta["num_bin"].max()) \
            if len(meta["num_bin"]) else 2
        self._bins_dev = None  # lazy: the data-parallel wrapper never
        # materializes this second (replicated) device copy of the bins
        self._bins_T_dev = None
        self.hyper = SplitHyper.from_config(cfg)
        self.finder = make_split_finder(self.hyper, meta, self.max_bin_global)
        self.mappers = dataset.used_mappers()
        self._feat_rng = np.random.RandomState(cfg.feature_fraction_seed)
        if cfg.tpu_use_f64_hist:
            # genuine f64 accumulation (ops/histogram.py): exact, hence
            # topology-invariant — required for byte-equal distributed parity
            self.hist_precision = "f64"
        elif cfg.gpu_use_dp:
            self.hist_precision = "f32"
        elif cfg.tpu_use_pallas:
            from ..ops.pallas_hist import pallas_available
            self.hist_precision = ("pallas" if pallas_available()
                                   else "bf16x2")
        else:
            self.hist_precision = "bf16x2"
        self.min_pad = int(cfg.tpu_min_pad)
        self.quant_bits, self._quant_why = self._resolve_quant_bits(cfg)
        self._qseq = 0  # host counter: one fresh quantization key per tree
        # device feature metadata for the partition step
        self._nb_dev = jnp.asarray(meta["num_bin"], jnp.int32)
        self._db_dev = jnp.asarray(meta["default_bin"], jnp.int32)
        self._mt_dev = jnp.asarray(meta["missing_type"], jnp.int32)
        self._mono_any = bool(np.any(meta["monotone"] != 0))
        self._build_cache: Dict[Tuple[int, bool], callable] = {}
        self._depth_limit = cfg.max_depth if cfg.max_depth > 0 else 1 << 30
        # Exclusive Feature Bundling view (io/bundling.py): bins columns
        # are bundles; per-feature histograms are sliced out on device
        bnd = getattr(dataset, "bundles", None)
        self.bundled = bnd is not None
        if self.bundled:
            from ..io.bundling import expansion_map
            self.hist_bins = int(max(self.max_bin_global,
                                     bnd.group_num_bin.max()))
            m_idx, dmask = expansion_map(bnd, meta["num_bin"],
                                         meta["default_bin"],
                                         self.hist_bins)
            self._emap_dev = jnp.asarray(m_idx[:, :self.max_bin_global])
            self._edef_dev = jnp.asarray(
                dmask[:, :self.max_bin_global].astype(np.float32))
            self._col_dev = jnp.asarray(bnd.col, jnp.int32)
            self._boff_dev = jnp.asarray(bnd.off, jnp.int32)
            self._bpk_dev = jnp.asarray(bnd.packed.astype(np.int32))
        else:
            self.hist_bins = self.max_bin_global
            self._col_dev = jnp.arange(self.num_features, dtype=jnp.int32)
            self._boff_dev = jnp.zeros(self.num_features, jnp.int32)
            self._bpk_dev = jnp.zeros(self.num_features, jnp.int32)

    def _resolve_quant_bits(self, cfg: Config) -> Tuple[int, Optional[str]]:
        """Resolve ``tpu_quant_hist`` to active bits (0 = f32 oracle) plus
        the human-readable reason when the oracle runs instead. The f32
        path is bitwise-unchanged when inactive — same discipline as
        ``tpu_rank_fused``; `gbdt._log_train_path` surfaces the outcome as
        a ``quant_hist`` event once the actual train path is known."""
        mode = str(cfg.tpu_quant_hist).lower()
        if mode == "off":
            return 0, "tpu_quant_hist=off"
        bits = 8 if int(cfg.tpu_quant_hist_bits) == 8 else 16
        if self.hist_precision in ("f64", "f32"):
            # exact-f64 distributed parity and the gpu_use_dp double path
            # must keep full-precision payloads
            return 0, f"hist_precision={self.hist_precision} never quantizes"
        if self.parallel_mode != "serial":
            # data_parallel.py wraps build entries in shard_map with
            # fixed-arity in_specs; the quantized entries take an extra
            # qseq operand, so the parallel learners keep the f32 path
            return 0, f"parallel_mode={self.parallel_mode} keeps f32 payloads"
        if cfg.tpu_grow_mode == "level":
            # the level builder's packed-word hist path bypasses
            # _make_build_fn entirely
            return 0, "tpu_grow_mode=level keeps f32 payloads"
        if mode == "on":
            return bits, None
        if jax.default_backend() == "tpu":
            return bits, None
        return 0, "auto: no TPU attached"

    def _next_qseq(self) -> int:
        """Fresh per-tree quantization sequence number (host counter,
        passed as a traced int32 so advancing it never retraces)."""
        self._qseq += 1
        return self._qseq

    def trace_signature(self) -> Tuple:
        """Hashable key covering everything this learner's build-program
        closures bake into a jax trace: the full config, the binning
        metadata (content-hashed — closures capture the device copies as
        constants), bundling tables, data shape, and mesh placement.
        Programs built by learners with equal signatures are shared
        process-wide (see compile_cache.program), so a second Booster on
        the same shapes triggers zero new traces."""
        sig = getattr(self, "_trace_sig_cache", None)
        if sig is None:
            m = self.meta
            bundle_fp = None
            if self.bundled:
                bnd = self.ds.bundles
                bundle_fp = compile_cache.array_fingerprint(
                    bnd.col, bnd.off, bnd.packed, bnd.group_num_bin)
            forced = (tuple(map(tuple, self._forced_nodes()))
                      if self.cfg.forcedsplits_filename else ())
            sig = ("learner", type(self).__name__,
                   compile_cache.config_signature(self.cfg),
                   compile_cache.array_fingerprint(
                       m["num_bin"], m["default_bin"], m["missing_type"],
                       m["bin_type"], m["monotone"], m["penalty"]),
                   bundle_fp, self.n, self.num_features,
                   self.num_real_features, self.max_bin_global,
                   self.hist_bins, self.axis_name, self.parallel_mode,
                   self.mesh_size, self.min_pad, self.hist_precision,
                   self.quant_bits, forced)
            self._trace_sig_cache = sig
        return sig

    def _cached_program(self, key, factory):
        """Two-level program lookup: per-instance memo over the
        process-wide registry (keyed by trace_signature + key)."""
        fn = self._build_cache.get(key)
        if fn is None:
            fn = compile_cache.program(
                self.trace_signature() + ("prog", key), factory)
            self._build_cache[key] = fn
        return fn

    @property
    def bins_dev(self) -> jax.Array:
        if self._bins_dev is None:
            # device_bins() reuses the HBM buffer the streaming ingest
            # left behind (io/stream.py) — no second upload of the full
            # binned matrix at train start
            dev = getattr(self.ds, "device_bins", None)
            self._bins_dev = dev() if dev is not None \
                else jnp.asarray(self.ds.bins)
            from ..obs import memory as obs_memory
            obs_memory.track(
                "train/bins_dev", self,
                lambda lr: 0 if lr._bins_dev is None
                else int(lr._bins_dev.nbytes))
        return self._bins_dev

    # ------------------------------------------------------------------
    def level_mode_ok(self) -> bool:
        """True when the level-batched builder (`level_builder.py`) can grow
        trees for this learner: uint8 bins, serial/data parallelism, and the
        grow mode allows it. Bagged iterations always use the leaf-wise
        path (the level records assume a full fresh root). "auto" now
        selects the aligned pipeline or leafwise — the sort-based level
        builder stays opt-in (measured on par with leafwise on v5e)."""
        return (self.cfg.tpu_grow_mode == "level"
                and not self.cfg.sequential_device_only
                and not self.bundled
                and self.parallel_mode in ("serial", "data")
                and self.ds.bins_dtype() == np.uint8
                and self.num_features > 0
                and self.cfg.num_leaves >= 2)

    @property
    def words_dev(self) -> jax.Array:
        """Packed bin words [ceil(F/4), N] for the level builder (lazy)."""
        if getattr(self, "_words_dev", None) is None:
            from .level_builder import pack_bin_words
            bins = np.asarray(self.ds.bins)
            if self.num_features != self.num_real_features:
                pad = self.num_features - self.num_real_features
                bins = np.pad(bins, ((0, 0), (0, pad)))
            self._words_dev = jnp.asarray(pack_bin_words(bins))
        return self._words_dev

    def _level_fn(self):
        def factory():
            from .level_builder import make_level_build_fn
            return make_level_build_fn(self)
        return self._cached_program("level", factory)

    def _level_train_fresh(self, grad, hess, feature_mask):
        """Speculative level build + host leaf-wise replay; falls back to
        the sequential leaf-wise builder when speculation was too shallow
        for an exact replay."""
        from .level_builder import replay_leafwise
        spec = self._level_fn()(self.words_dev, grad, hess,
                                self._fmask_arr(feature_mask))
        host = jax.device_get(spec._replace(rid=None))
        rec, exact = replay_leafwise(host, self.cfg.num_leaves)
        if not exact:
            self._level_fallbacks = getattr(self, "_level_fallbacks", 0) + 1
            return None
        rec = rec._replace(block_begin=spec.block_begin,
                           block_cnt=spec.block_cnt)
        return spec.rid, rec

    @property
    def bins_T_dev(self) -> jax.Array:
        """Transposed bins [F, N] so a dynamic feature's column is one
        contiguous dynamic_slice (the row-major column read costs a stride-F
        pass over the whole matrix on TPU)."""
        if self._bins_T_dev is None:
            self._bins_T_dev = jnp.asarray(
                np.ascontiguousarray(np.asarray(self.ds.bins).T))
        return self._bins_T_dev

    def add_score(self, score_row: jax.Array, trav: Dict,
                  scale: float) -> jax.Array:
        """score += scale * tree(x) over the training bins."""
        return add_record_score(score_row, self.bins_dev, trav, self._nb_dev,
                                self._db_dev, self._mt_dev,
                                jnp.float32(scale),
                                self._col_dev if self.bundled else None,
                                self._boff_dev if self.bundled else None,
                                self._bpk_dev if self.bundled else None)

    def add_score_from_partition(self, score: jax.Array, class_id: int,
                                 record: "TreeRecord", indices: jax.Array,
                                 scale: float) -> jax.Array:
        """score[class_id] += scale * tree(x) using the final partition:
        each leaf's rows are contiguous in `indices`, so the per-row leaf
        value is a scatter-at-L-boundaries + cumsum fill, and the only
        irregular step is ONE key-sort back to row order — no per-level tree
        traversal. One fused program, score buffer donated. (Replaces the
        reference's Tree::AddPredictionToScore bulk update,
        tree.cpp:112-204.) Valid only for full-data (no bagging) trees.

        Level-built records carry a FINER physical partition than the
        committed tree: score through the block tables instead."""
        if record.block_begin is not None:
            return _partition_score_update(
                score, jnp.int32(class_id), jnp.asarray(record.block_begin),
                jnp.asarray(record.block_cnt),
                jnp.asarray(record.block_value, dtype=jnp.float32), indices,
                jnp.int32(self.n), jnp.float32(scale))
        return _partition_score_update(
            score, jnp.int32(class_id), record.leaf_begin,
            record.leaf_cnt_part, record.leaf_value, indices,
            jnp.int32(self.n), jnp.float32(scale))

    # ------------------------------------------------------------------
    def feature_mask(self) -> Optional[np.ndarray]:
        frac = self.cfg.feature_fraction
        if frac >= 1.0:
            if self.num_features != self.num_real_features:
                mask = np.zeros(self.num_features, bool)
                mask[:self.num_real_features] = True  # padded features off
                return mask
            return None
        used_cnt = max(1, int(round(self.num_real_features * frac)))
        mask = np.zeros(self.num_features, bool)
        mask[self._feat_rng.choice(self.num_real_features, used_cnt,
                                   replace=False)] = True
        return mask

    # ------------------------------------------------------------------
    def _buckets_for(self, root_count: int) -> List[int]:
        return bucket_table(self.min_pad, root_count)

    @staticmethod
    def _bucket_index(count, sizes_list):
        """Smallest bucket size >= count — exact integer comparison against
        the bucket-size table (float log2 would undercount near 2^24 and
        silently drop rows)."""
        sizes = jnp.asarray(sizes_list, jnp.int32)
        b = jnp.sum((count > sizes).astype(jnp.int32))
        return jnp.clip(b, 0, len(sizes_list) - 1)

    # ------------------------------------------------------------------
    def _make_build_fn(self, root_padded: int, root_contiguous: bool):
        """Build the jitted whole-tree program for a given root size.

        root_contiguous: the root partition is the identity permutation
        (fresh per-tree partition, no bagging), so the root histogram and
        root sums read bins/grad/hess contiguously — skipping the single
        biggest random gather of the tree.
        """
        cfg = self.cfg
        L = cfg.num_leaves
        Lm1 = max(L - 1, 1)
        F = self.num_features
        B = self.max_bin_global
        BH = self.hist_bins
        bundled = self.bundled
        if bundled:
            emap, edef = self._emap_dev, self._edef_dev

            def expand_hist(hist_g, sg, sh, cnt):
                """[G, BH, 3] bundle histogram -> [F, B, 3] per-feature
                view; skipped default bins come from leaf totals
                (FixHistogram, dataset.cpp:928-947)."""
                flat = hist_g.reshape(-1, NUM_HIST_STATS)
                safe = jnp.clip(emap, 0, flat.shape[0] - 1)
                out = flat[safe] * (emap >= 0)[:, :, None]
                totals = jnp.stack([sg, sh, cnt.astype(jnp.float32)])
                fix = totals[None, :] - jnp.sum(out, axis=1)
                # the count channel must stay an exact integer or the
                # min_data_in_leaf guards flip on reconstruction noise
                fix = fix.at[:, 2].set(jnp.round(fix[:, 2]))
                return out + edef[:, :, None] * fix[:, None, :]
        buckets = self._buckets_for(root_padded)
        nbk = len(buckets)
        finder = self.finder
        nb_dev, db_dev, mt_dev = self._nb_dev, self._db_dev, self._mt_dev
        chunk = int(cfg.tpu_hist_chunk)
        precision = self.hist_precision
        # ---- quantized histogram payload (tpu_quant_hist): gradients are
        # stochastic-rounded to int8/int16 ONCE per tree, so every per-leaf
        # gather moves quarter/half the f32 bytes; finished histograms and
        # root sums are rescaled back to gradient units by the pack scale.
        # int8 fits a SINGLE bf16 pass exactly (|q| <= 127), so the hi/lo
        # split is dropped too — half the MXU work on top of the bandwidth.
        quant_bits = self.quant_bits
        quant_on = quant_bits > 0
        if quant_on and quant_bits == 8 and precision == "bf16x2":
            precision = "bf16"
        qseed = int(cfg.data_random_seed)
        # mutable closure slot for the per-call pack scale (same pattern as
        # coupled_box below): set when the entry packs the payload, read by
        # the hist/sum rescale sites inside the same trace
        qscale_box = [jnp.ones((2,), jnp.float32)]

        def _gh_payload(grad, hess, opt):
            """Stack (and optionally quantize) the [N, 2] payload; returns
            (gh, remaining_opt) with the qseq operand consumed."""
            gh = jnp.stack([grad, hess], axis=1)
            if not quant_on:
                return gh, opt
            qseq, opt = opt[0], opt[1:]
            key = jax.random.fold_in(jax.random.PRNGKey(qseed), qseq)
            q, scale = quantize_gh(gh, quant_bits, key)
            qscale_box[0] = scale
            return q, opt

        depth_limit = self._depth_limit
        mono_dev = jnp.asarray(self.meta["monotone"], jnp.int32)

        # ---- CEGB on the device path (reference CalculateOndemandCosts,
        # serial_tree_learner.cpp:488-568): split penalty scales with the
        # leaf's (global) row count; coupled penalties charge a feature
        # once per model, tracked by a [F] used-mask carried through the
        # tree loop. Per-(row, feature) LAZY penalties keep the host twin
        # (forces_host_learner).
        cegb_on = (cfg.cegb_penalty_split > 0
                   or len(cfg.cegb_penalty_feature_coupled) > 0)
        cegb_coupled_on = len(cfg.cegb_penalty_feature_coupled) > 0
        cegb_tr = float(cfg.cegb_tradeoff)
        cegb_sp = float(cfg.cegb_penalty_split) * cegb_tr
        # coupled penalties charge a feature once per MODEL: features
        # used by EARLIER trees arrive zeroed in the per-call
        # coupled_eff array (see _cegb_coupled_eff / _cegb_note_record);
        # the in-loop used-mask handles this tree's own first uses
        assert not (cegb_coupled_on and self.parallel_mode != "serial"), \
            "coupled CEGB routes to the host twin off the serial learner"

        # ---- forced splits (reference ForceSplits, serial_tree_learner
        # .cpp:597-755): the JSON prefix flattens to node arrays; a BFS
        # queue rides the tree-loop state, each pop overriding the
        # gain-driven leaf/split choice with the node's (feature,
        # threshold) evaluated AT-threshold from the leaf histogram
        # (GatherInfoForThreshold, feature_histogram.hpp:290+). A node
        # whose forced threshold leaves an empty child is skipped like
        # the host twin does.
        fnodes = self._forced_nodes()
        MF = len(fnodes)
        MFq = max(MF, 1)
        fF_dev = jnp.asarray([x[0] for x in fnodes] or [0], jnp.int32)
        fT_dev = jnp.asarray([x[1] for x in fnodes] or [0], jnp.int32)
        fL_dev = jnp.asarray([x[2] for x in fnodes] or [-1], jnp.int32)
        fR_dev = jnp.asarray([x[3] for x in fnodes] or [-1], jnp.int32)
        l1_hp = float(self.hyper.lambda_l1)
        l2_hp = float(self.hyper.lambda_l2)

        def forced_info(ph, sg, sh, cntg, f, thr):
            """BF/BI payload rows for a forced split AT (f, thr) from the
            parent's [F, B, 3] histogram — mirrors the host twin's
            _forced_split_info bit-for-bit in f32."""
            row = ph[f]                                     # [B, 3]
            nbf = nb_dev[f]
            hi = jnp.minimum(thr + 1, nbf)
            m = (jnp.arange(B, dtype=jnp.int32) < hi)[:, None]
            sums = jnp.sum(jnp.where(m, row, 0.0), axis=0)
            lg, lh, lcf = sums[0], sums[1], sums[2]
            nan_adj = (mt_dev[f] == 2) & (hi > nbf - 1)
            last = row[jnp.clip(nbf - 1, 0, B - 1)]
            lg = lg - jnp.where(nan_adj, last[0], 0.0)
            lh = lh - jnp.where(nan_adj, last[1], 0.0)
            lcf = lcf - jnp.where(nan_adj, last[2], 0.0)
            lc = jnp.round(lcf).astype(jnp.int32)
            rg, rh = sg - lg, sh - lh
            rc = cntg - lc

            def tl1(sv):
                return jnp.sign(sv) * jnp.maximum(jnp.abs(sv) - l1_hp, 0.0)

            def pgain(sv, hv):
                return jnp.where(hv + l2_hp > 0,
                                 tl1(sv) ** 2 / (hv + l2_hp), 0.0)

            def outp(sv, hv):
                return jnp.where(hv + l2_hp > 0,
                                 -tl1(sv) / (hv + l2_hp), 0.0)

            gain = pgain(lg, lh) + pgain(rg, rh) - pgain(sg, sh)
            vF = jnp.zeros(BF_W, jnp.float32)
            vF = vF.at[BF_GAIN].set(gain)
            vF = vF.at[BF_LG].set(lg)
            vF = vF.at[BF_LH].set(lh)
            vF = vF.at[BF_RG].set(rg)
            vF = vF.at[BF_RH].set(rh)
            vF = vF.at[BF_LOUT].set(outp(lg, lh))
            vF = vF.at[BF_ROUT].set(outp(rg, rh))
            vI = jnp.zeros(BI_W, jnp.int32)
            vI = vI.at[BI_FEAT].set(f)
            vI = vI.at[BI_THR].set(thr)
            vI = vI.at[BI_LC].set(lc)
            vI = vI.at[BI_RC].set(rc)
            return vF, vI

        mode = self.parallel_mode
        nd = self.mesh_size if mode == "feature" else 1
        f_block = F // nd if mode == "feature" else F
        if mode == "voting":
            vote_k = max(1, min(int(cfg.top_k), F))
            vote_sel = min(2 * vote_k, F)
            # local searches relax min_data/min_hessian by the machine count
            # (reference voting_parallel_tree_learner.cpp:58-59)
            m = max(1, self.mesh_size)
            hyper_local = self.hyper._replace(
                min_data_in_leaf=max(1, self.hyper.min_data_in_leaf // m),
                min_sum_hessian_in_leaf=(
                    self.hyper.min_sum_hessian_in_leaf / m))
            finder_local = make_split_finder(hyper_local, self.meta, B)


        def _feature_block_hist(rows, gh, valid):
            if mode != "feature":
                h = histogram_from_gathered_gh(rows, gh, valid, BH,
                                               chunk, precision)
                if quant_on:
                    # back to gradient units: grad/hess columns by the pack
                    # scale, count column untouched (exact integers)
                    h = h * jnp.concatenate(
                        [qscale_box[0], jnp.ones((1,), jnp.float32)])
                return h
            # feature-parallel: each shard histograms only its feature block
            # (reference feature_parallel_tree_learner.cpp:33-52 work
            # division); the psum that follows assembles the global
            # histogram, subsuming SyncUpGlobalBestSplit
            start = lax.axis_index(self.axis_name) * f_block
            size = rows.shape[0]
            rows = lax.dynamic_slice(rows, (jnp.int32(0), start),
                                     (size, f_block))
            hb = histogram_from_gathered_gh(rows, gh, valid, BH, chunk,
                                            precision)
            if hb.dtype == jnp.float64:
                with jax.experimental.enable_x64():
                    full = jnp.zeros((F, B, NUM_HIST_STATS), jnp.float64)
                    return lax.dynamic_update_slice(
                        full, hb, (start, jnp.int32(0), jnp.int32(0)))
            full = jnp.zeros((F, B, NUM_HIST_STATS), jnp.float32)
            return lax.dynamic_update_slice(
                full, hb, (start, jnp.int32(0), jnp.int32(0)))

        def hist_bucket(size):
            def fn(bins, indices, gh, begin, count):
                idx = lax.dynamic_slice(indices, (begin,), (size,))
                pos = jnp.arange(size, dtype=jnp.int32)
                valid = pos < count
                safe = jnp.where(valid, idx, 0)
                return _feature_block_hist(bins[safe], gh[safe], valid)
            return fn

        def part_bucket(size):
            def fn(bins_col, indices, begin, count, threshold, default_left,
                   missing_type, default_bin, num_bin, is_cat, bitset,
                   boff, bpk):
                return split_partition(indices, bins_col, begin, count, size,
                                       threshold, default_left, missing_type,
                                       default_bin, num_bin, is_cat, bitset,
                                       boff, bpk)
            return fn

        hist_fns = [hist_bucket(s) for s in buckets]
        part_fns = [part_bucket(s) for s in buckets]
        col_dev = self._col_dev
        boff_dev = self._boff_dev
        bpk_dev = self._bpk_dev
        axis = self.axis_name

        # Collective placement by mode (all ride ICI as XLA all-reduces;
        # every shard takes identical split decisions so the collective
        # schedules never diverge):
        #   data:    histograms psum'd (ReduceScatter analogue); row-local
        #            scalars psum'd (root-sums allreduce)
        #   feature: block histograms psum'd into the global histogram
        #            (subsumes SyncUpGlobalBestSplit); rows replicated so
        #            scalars are already global
        #   voting:  histograms stay LOCAL (only elected features are
        #            reduced, inside eval_leaf); row-local scalars psum'd
        # Under precision == "f64" the partials entering a collective are
        # exact, so psum(partials) == serial total in f64; the single
        # f64→f32 rounding AFTER the reduce makes every downstream value
        # bit-identical across topologies (the byte-equal parity contract
        # of dist/runtime.py).
        def _gsum_hist(x):
            if axis is not None and mode in ("data", "feature"):
                x = lax.psum(x, axis)
            if x.dtype == jnp.float64:
                x = x.astype(jnp.float32)
            return x

        def _gsum_scalar(x):
            if axis is not None and mode in ("data", "voting"):
                x = lax.psum(x, axis)
            if x.dtype == jnp.float64:
                x = x.astype(jnp.float32)
            return x

        # loop budget: num_leaves-1 splits (0 when num_leaves == 1); Lm1 is
        # only the (>=1) record-array length
        split_budget = max(L - 1, 0)

        # local row count for fresh (identity-partition) builds: static for
        # replicated-row modes, per-shard via axis_index for rows-sharded
        rows_sharded = axis is not None and mode in ("data", "voting")
        per_shard_rows = (int(math.ceil(self.n / max(self.mesh_size, 1)))
                          if rows_sharded else self.n)

        coupled_box = [jnp.zeros((F,), jnp.float32)]

        def build_fresh(bins, bins_T, grad, hess, feature_mask_f32, *opt):
            """Fresh-tree entry: creates the identity partition internally
            (one fused program instead of init-partition + build
            dispatches); only valid without bagging.

            Trailing variadic operands, in order: the per-tree qseq (when
            quant_on) then coupled_eff (when coupled CEGB is on) — both
            consumed positionally so the donation/in_specs plumbing never
            sees optional keywords."""
            n_pad = per_shard_rows + max(_pow2ceil(per_shard_rows),
                                         self.min_pad)
            pos = jnp.arange(n_pad, dtype=jnp.int32)
            if rows_sharded:
                s = lax.axis_index(axis)
                cnt = jnp.clip(self.n - s * per_shard_rows, 0,
                               per_shard_rows).astype(jnp.int32)
            else:
                cnt = jnp.int32(per_shard_rows)
            indices = jnp.where(pos < cnt, pos, 0)
            gh, opt = _gh_payload(grad, hess, opt)
            return _build(bins, bins_T, indices, gh, cnt, feature_mask_f32,
                          *opt)

        def build(bins, bins_T, indices, grad, hess, root_count,
                  feature_mask_f32, *opt):
            gh, opt = _gh_payload(grad, hess, opt)
            return _build(bins, bins_T, indices, gh, root_count,
                          feature_mask_f32, *opt)

        def _build(bins, bins_T, indices, gh, root_count, feature_mask_f32,
                   coupled_eff=None):
            compile_cache.note_trace()
            if cegb_coupled_on:
                coupled_box[0] = coupled_eff

            def _mask_gain(gain, depth):
                gain = jnp.where(feature_mask_f32 > 0, gain, NEG_INF)
                return jnp.where(depth >= depth_limit,
                                 jnp.full_like(gain, NEG_INF), gain)

            _payload = pack_best_payload

            def _cegb_pen(cnt, used, coupled_eff):
                """Per-feature CEGB gain penalty for one leaf."""
                pen = cegb_sp * cnt.astype(jnp.float32)
                if cegb_coupled_on:
                    pen = pen + coupled_eff * (1.0 - used)
                return pen

            if mode == "voting":
                # PV-Tree (reference voting_parallel_tree_learner.cpp:
                # 262-400): local top-k vote -> global vote -> reduce only
                # the elected features' histograms -> global best split.
                # `hist` here is this shard's LOCAL histogram of the leaf.
                def eval_leaf(hist, sg, sh, cnt, minc, maxc, depth,
                              used=None):
                    # local leaf sums: every row lands in exactly one bin of
                    # feature 0, so its histogram column sums to the local
                    # totals (no FixHistogram-style bin skipping here)
                    lsg = jnp.sum(hist[0, :, 0])
                    lsh = jnp.sum(hist[0, :, 1])
                    lcnt = jnp.sum(hist[0, :, 2]).astype(jnp.int32)
                    lout = finder_local(hist, lsg, lsh, lcnt, minc, maxc)
                    lgain = _mask_gain(lout["gain"], depth)
                    _, top_idx = lax.top_k(lgain, vote_k)
                    # votes weighted by local data share (GlobalVoting
                    # weighting, voting_parallel_tree_learner.cpp:170-200)
                    votes = jnp.zeros((F,), jnp.float32).at[top_idx].add(
                        1.0 + lcnt.astype(jnp.float32))
                    votes = lax.psum(votes, axis)
                    _, sel_idx = lax.top_k(votes, vote_sel)  # same on all
                    hist_sel = lax.psum(hist[sel_idx], axis)
                    ghist = jnp.zeros_like(hist).at[sel_idx].set(hist_sel)
                    out = finder(ghist, sg, sh, cnt, minc, maxc)
                    selmask = jnp.zeros((F,), bool).at[sel_idx].set(True)
                    gain = jnp.where(selmask, out["gain"], NEG_INF)
                    if cegb_on:
                        gain = gain - _cegb_pen(cnt, used, coupled_box[0])
                    return _payload(out, _mask_gain(gain, depth))
            else:
                def eval_leaf(hist, sg, sh, cnt, minc, maxc, depth,
                              used=None):
                    if bundled:
                        hist = expand_hist(hist, sg, sh, cnt)
                    out = finder(hist, sg, sh, cnt, minc, maxc)
                    gain = out["gain"]
                    if cegb_on:
                        gain = gain - _cegb_pen(cnt, used, coupled_box[0])
                    return _payload(out, _mask_gain(gain, depth))

            # ---------- root ----------
            if root_contiguous:
                # identity partition: read the head of bins/grad/hess
                # directly (static slice, no gather); pow2 padding can
                # exceed the physical row count, so clamp statically
                rp = min(root_padded, bins.shape[0], gh.shape[0])
                pos = jnp.arange(rp, dtype=jnp.int32)
                valid = pos < root_count
                rows = lax.slice(bins, (0, 0), (rp, bins.shape[1]))
                gh0 = lax.slice(gh, (0, 0), (rp, 2))
                root_hist = _feature_block_hist(rows, gh0, valid)
                masked = jnp.where(valid[:, None],
                                   gh0.astype(jnp.float32), 0.0)
                if precision == "f64":
                    # exact root sums: the partials entering the root-sums
                    # allreduce must be order-independent (see _gsum_scalar)
                    with jax.experimental.enable_x64():
                        sums = jnp.sum(masked.astype(jnp.float64), axis=0)
                        root_g, root_h = sums[0], sums[1]
                else:
                    sums = jnp.sum(masked, axis=0)
                    root_g, root_h = sums[0], sums[1]
            else:
                bsel = self._bucket_index(root_count, buckets)
                root_hist = lax.switch(
                    bsel, hist_fns, bins, indices, gh, jnp.int32(0),
                    root_count)
                root_g, root_h = _masked_sums(indices, gh, root_count,
                                              root_padded,
                                              f64=precision == "f64")
            if quant_on:
                qs = qscale_box[0]
                root_g = root_g * qs[0]
                root_h = root_h * qs[1]
            root_hist = _gsum_hist(root_hist)
            # root grad/hess sums (data-parallel: the root-sums allreduce,
            # data_parallel_tree_learner.cpp:120-145)
            root_g, root_h = _gsum_scalar(root_g), _gsum_scalar(root_h)
            root_count_g = _gsum_scalar(root_count)

            # ---------- packed state ----------
            ncols = F if not bundled else len(
                np.asarray(self.ds.bundles.group_num_bin))
            # histogram_pool_size (reference HistogramPool,
            # feature_histogram.hpp:654-829): the reference bounds the
            # per-leaf histogram cache in MB with LRU + recompute. The
            # TPU store is one [L, F, B, 3] array; the budget ladder is
            # f32 store -> bf16 store (subtract upcasts to f32) ->
            # RECOMPUTE mode (no per-leaf store at all: both children
            # are histogrammed directly at each split, the analogue of
            # an always-missing pool — up to ~2x histogram work, O(1)
            # histogram memory).
            store_dtype = jnp.float32
            pool_recompute = False
            pool_mb = float(cfg.histogram_pool_size)
            if pool_mb > 0:
                f32_mb = L * ncols * BH * NUM_HIST_STATS * 4 / 2**20
                if f32_mb > pool_mb:
                    store_dtype = jnp.bfloat16
                    if f32_mb / 2 > pool_mb:
                        pool_recompute = True
            store_L = 1 if pool_recompute else L
            hist_store = jnp.zeros((store_L, ncols, BH, NUM_HIST_STATS),
                                   store_dtype)
            if not pool_recompute:
                hist_store = hist_store.at[0].set(
                    root_hist.astype(store_dtype))
            leafF = jnp.zeros((L, LF_W), jnp.float32)
            leafF = leafF.at[:, LF_MINC].set(-jnp.inf)
            leafF = leafF.at[:, LF_MAXC].set(jnp.inf)
            leafF = leafF.at[0, LF_SG].set(root_g)
            leafF = leafF.at[0, LF_SH].set(root_h)
            leafI = jnp.zeros((L, LI_W), jnp.int32)
            leafI = leafI.at[0, LI_COUNT].set(root_count)
            leafI = leafI.at[0, LI_COUNTG].set(root_count_g)
            bestF = jnp.full((L, BF_W), NEG_INF, jnp.float32)
            bestI = jnp.zeros((L, BI_W), jnp.int32)
            bestB = jnp.zeros((L, 8), jnp.uint32)
            recF = jnp.zeros((Lm1, RF_W), jnp.float32)
            recI = jnp.zeros((Lm1, RI_W), jnp.int32)
            recB = jnp.zeros((Lm1, 8), jnp.uint32)

            used0 = jnp.zeros((F,), jnp.float32)
            rvF, rvI, rvB = eval_leaf(
                root_hist, root_g, root_h, root_count_g,
                jnp.float32(-jnp.inf), jnp.float32(jnp.inf), jnp.int32(0),
                used0)
            bestF = bestF.at[0].set(rvF)
            bestI = bestI.at[0].set(rvI)
            bestB = bestB.at[0].set(rvB)

            # forced-split BFS queue (node 0 seeded at the root leaf) +
            # CEGB used-feature mask ride the loop state; both are tiny
            # and inert when the features are off
            fq_leaf0 = jnp.zeros((MFq + 1,), jnp.int32)
            fq_node0 = jnp.zeros((MFq + 1,), jnp.int32)
            state = (jnp.int32(0), indices, leafF, leafI, hist_store,
                     bestF, bestI, bestB, recF, recI, recB, used0,
                     jnp.int32(0), jnp.int32(1 if MF else 0),
                     fq_leaf0, fq_node0)

            def cond(state):
                s = state[0]
                bestF = state[5]
                forced_pending = state[12] < state[13]
                return (s < split_budget) \
                    & ((jnp.max(bestF[:, BF_GAIN]) > 0.0) | forced_pending)

            def body(state):
                (s, indices, leafF, leafI, hist_store, bestF, bestI, bestB,
                 recF, recI, recB, used, fh, ft, fq_leaf, fq_node) = state
                bl = jnp.argmax(bestF[:, BF_GAIN]).astype(jnp.int32)
                new_leaf = s + 1
                act = jnp.bool_(True)
                forced_mode = jnp.bool_(False)
                nid = jnp.int32(0)
                if MF:
                    # pop the BFS queue ahead of gain-driven selection
                    # (ForceSplits runs before normal growth)
                    forced_mode = fh < ft
                    qp = jnp.clip(fh, 0, MFq)
                    nid = jnp.clip(fq_node[qp], 0, MF - 1)
                    bl = jnp.where(forced_mode, fq_leaf[qp], bl)
                bF = bestF[bl]
                bI = bestI[bl]
                bB = bestB[bl]
                if MF:
                    # AT-threshold split info from the parent histogram,
                    # under lax.cond so split iterations after the queue
                    # drains skip the (possibly recomputed) histogram

                    def _forced_payload(_):
                        sgp = leafF[bl, LF_SG]
                        shp = leafF[bl, LF_SH]
                        cntp = leafI[bl, LI_COUNTG]
                        if pool_recompute:
                            bkp = self._bucket_index(leafI[bl, LI_COUNT],
                                                     buckets)
                            ph = lax.switch(bkp, hist_fns, bins, indices,
                                            gh, leafI[bl, LI_BEGIN],
                                            leafI[bl, LI_COUNT])
                            ph = _gsum_hist(ph)
                        else:
                            ph = hist_store[bl].astype(jnp.float32)
                        if bundled:
                            ph = expand_hist(ph, sgp, shp, cntp)
                        return forced_info(ph, sgp, shp, cntp,
                                           fF_dev[nid], fT_dev[nid])

                    def _no_payload(_):
                        return (jnp.zeros(BF_W, jnp.float32),
                                jnp.zeros(BI_W, jnp.int32))

                    fvF, fvI = lax.cond(forced_mode, _forced_payload,
                                        _no_payload, operand=None)
                    bF = jnp.where(forced_mode, fvF, bF)
                    bI = jnp.where(forced_mode, fvI, bI)
                    bB = jnp.where(forced_mode, jnp.zeros_like(bB), bB)
                    # a forced threshold that empties a child is skipped
                    # (host twin: min(left_c, right_c) < 1 -> continue)
                    act = jnp.where(
                        forced_mode,
                        jnp.minimum(fvI[BI_LC], fvI[BI_RC]) >= 1, True)
                f = bI[BI_FEAT]
                thr = bI[BI_THR]
                dleft = bI[BI_DEFLEFT] != 0
                iscat = bI[BI_ISCAT] != 0
                begin = leafI[bl, LI_BEGIN]
                count = leafI[bl, LI_COUNT]
                # GLOBAL child counts come from the (already psum-reduced)
                # histogram's count channel — exact integers in f32.
                # "Smaller" is decided on GLOBAL counts so every shard
                # histograms the same child (the reference uses
                # GetGlobalDataCountInLeaf the same way,
                # data_parallel_tree_learner.cpp:198-220).
                left_cnt_g = bI[BI_LC]
                right_cnt_g = bI[BI_RC]
                smaller_is_left = left_cnt_g <= right_cnt_g
                # contiguous column read from the transposed bins (the
                # feature's STORAGE column under bundling)
                bins_col = lax.dynamic_slice(
                    bins_T, (col_dev[f], jnp.int32(0)),
                    (1, bins_T.shape[1]))[0]
                bk = self._bucket_index(count, buckets)
                new_indices, left_cnt = lax.switch(
                    bk, part_fns, bins_col, indices, begin, count, thr,
                    dleft, mt_dev[f], db_dev[f], nb_dev[f], iscat, bB,
                    boff_dev[f], bpk_dev[f])
                right_cnt = count - left_cnt

                # ---- packed record row
                rowF = jnp.stack([bF[BF_LOUT], bF[BF_ROUT], bF[BF_GAIN],
                                  leafF[bl, LF_VALUE]])
                rowI = jnp.zeros(RI_W, jnp.int32)
                rowI = rowI.at[RI_LEAF].set(bl)
                rowI = rowI.at[RI_FEAT].set(f)
                rowI = rowI.at[RI_THR].set(thr)
                rowI = rowI.at[RI_DEFLEFT].set(bI[BI_DEFLEFT])
                rowI = rowI.at[RI_ISCAT].set(bI[BI_ISCAT])
                rowI = rowI.at[RI_LC].set(left_cnt_g)
                rowI = rowI.at[RI_RC].set(right_cnt_g)
                recF = recF.at[s].set(jnp.where(act, rowF, recF[s]))
                recI = recI.at[s].set(jnp.where(act, rowI, recI[s]))
                recB = recB.at[s].set(jnp.where(act, bB, recB[s]))

                # ---- children bookkeeping (two packed-row writes)
                depth = leafI[bl, LI_DEPTH] + 1
                # monotone constraint propagation
                if self._mono_any:
                    mono = mono_dev[f]
                    mid = (bF[BF_LOUT] + bF[BF_ROUT]) / 2.0
                    minc0 = leafF[bl, LF_MINC]
                    maxc0 = leafF[bl, LF_MAXC]
                    lmax = jnp.where(mono > 0, jnp.minimum(maxc0, mid), maxc0)
                    rmin = jnp.where(mono > 0, jnp.maximum(minc0, mid), minc0)
                    lmin = jnp.where(mono < 0, jnp.maximum(minc0, mid), minc0)
                    rmax = jnp.where(mono < 0, jnp.minimum(maxc0, mid), maxc0)
                else:
                    lmin = rmin = leafF[bl, LF_MINC]
                    lmax = rmax = leafF[bl, LF_MAXC]
                lrowF = jnp.zeros(LF_W, jnp.float32)
                lrowF = lrowF.at[LF_SG].set(bF[BF_LG])
                lrowF = lrowF.at[LF_SH].set(bF[BF_LH])
                lrowF = lrowF.at[LF_MINC].set(lmin)
                lrowF = lrowF.at[LF_MAXC].set(lmax)
                lrowF = lrowF.at[LF_VALUE].set(bF[BF_LOUT])
                rrowF = jnp.zeros(LF_W, jnp.float32)
                rrowF = rrowF.at[LF_SG].set(bF[BF_RG])
                rrowF = rrowF.at[LF_SH].set(bF[BF_RH])
                rrowF = rrowF.at[LF_MINC].set(rmin)
                rrowF = rrowF.at[LF_MAXC].set(rmax)
                rrowF = rrowF.at[LF_VALUE].set(bF[BF_ROUT])
                leafF = leafF.at[bl].set(jnp.where(act, lrowF, leafF[bl]))
                leafF = leafF.at[new_leaf].set(
                    jnp.where(act, rrowF, leafF[new_leaf]))
                lrowI = jnp.stack([begin, left_cnt, left_cnt_g, depth,
                                   jnp.int32(0), jnp.int32(0), jnp.int32(0),
                                   jnp.int32(0)])
                rrowI = jnp.stack([begin + left_cnt, right_cnt, right_cnt_g,
                                   depth, jnp.int32(0), jnp.int32(0),
                                   jnp.int32(0), jnp.int32(0)])
                leafI = leafI.at[bl].set(jnp.where(act, lrowI, leafI[bl]))
                leafI = leafI.at[new_leaf].set(
                    jnp.where(act, rrowI, leafI[new_leaf]))

                # histogram the smaller child (by GLOBAL counts, so every
                # shard histograms the same child); larger = parent - smaller
                # (FeatureHistogram::Subtract)
                sm_begin = jnp.where(smaller_is_left, begin,
                                     begin + left_cnt)
                sm_count = jnp.where(smaller_is_left, left_cnt, right_cnt)
                bk2 = self._bucket_index(sm_count, buckets)
                sm_hist = lax.switch(bk2, hist_fns, bins, new_indices,
                                     gh, sm_begin, sm_count)
                sm_hist = _gsum_hist(sm_hist)
                if pool_recompute:
                    # pool budget below the bf16 store: no per-leaf
                    # cache — histogram the larger child directly too
                    # (the reference's pool-miss recompute path)
                    lg_begin = jnp.where(smaller_is_left,
                                         begin + left_cnt, begin)
                    lg_count = jnp.where(smaller_is_left, right_cnt,
                                         left_cnt)
                    bk3 = self._bucket_index(lg_count, buckets)
                    lg_hist = lax.switch(bk3, hist_fns, bins,
                                         new_indices, gh, lg_begin,
                                         lg_count)
                    lg_hist = _gsum_hist(lg_hist)
                else:
                    lg_hist = hist_store[bl].astype(jnp.float32) - sm_hist
                left_hist = jnp.where(smaller_is_left, sm_hist, lg_hist)
                right_hist = jnp.where(smaller_is_left, lg_hist, sm_hist)
                if not pool_recompute:
                    hist_store = hist_store.at[bl].set(jnp.where(
                        act, left_hist.astype(hist_store.dtype),
                        hist_store[bl]))
                    hist_store = hist_store.at[new_leaf].set(jnp.where(
                        act, right_hist.astype(hist_store.dtype),
                        hist_store[new_leaf]))

                # CEGB: the committed split's feature becomes "used"
                # (coupled penalty drops to zero from here on)
                if cegb_on:
                    used = used.at[f].set(jnp.where(act, 1.0, used[f]))

                # evaluate both children (global counts)
                lF, lI, lB = eval_leaf(left_hist, bF[BF_LG], bF[BF_LH],
                                       left_cnt_g, lmin, lmax, depth,
                                       used)
                rF, rI, rB = eval_leaf(right_hist, bF[BF_RG], bF[BF_RH],
                                       right_cnt_g, rmin, rmax, depth,
                                       used)
                bestF = bestF.at[bl].set(jnp.where(act, lF, bestF[bl]))
                bestF = bestF.at[new_leaf].set(
                    jnp.where(act, rF, bestF[new_leaf]))
                bestI = bestI.at[bl].set(jnp.where(act, lI, bestI[bl]))
                bestI = bestI.at[new_leaf].set(
                    jnp.where(act, rI, bestI[new_leaf]))
                bestB = bestB.at[bl].set(jnp.where(act, lB, bestB[bl]))
                bestB = bestB.at[new_leaf].set(
                    jnp.where(act, rB, bestB[new_leaf]))

                if MF:
                    # advance the queue: pop, and push surviving children
                    # left-then-right (host BFS order); the left child
                    # keeps leaf bl, the right child is new_leaf
                    acti = act.astype(jnp.int32)
                    nl = fL_dev[nid]
                    nr = fR_dev[nid]
                    p1 = forced_mode & act & (nl >= 0)
                    t1 = jnp.clip(ft, 0, MFq)
                    fq_leaf = fq_leaf.at[t1].set(
                        jnp.where(p1, bl, fq_leaf[t1]))
                    fq_node = fq_node.at[t1].set(
                        jnp.where(p1, nl, fq_node[t1]))
                    ft = ft + p1.astype(jnp.int32)
                    p2 = forced_mode & act & (nr >= 0)
                    t2 = jnp.clip(ft, 0, MFq)
                    fq_leaf = fq_leaf.at[t2].set(
                        jnp.where(p2, new_leaf, fq_leaf[t2]))
                    fq_node = fq_node.at[t2].set(
                        jnp.where(p2, nr, fq_node[t2]))
                    ft = ft + p2.astype(jnp.int32)
                    fh = fh + forced_mode.astype(jnp.int32)
                else:
                    acti = 1

                return (s + acti, new_indices, leafF, leafI, hist_store,
                        bestF, bestI, bestB, recF, recI, recB, used,
                        fh, ft, fq_leaf, fq_node)

            (n_splits, indices, leafF, leafI, hist_store, bestF, bestI,
             bestB, recF, recI, recB, _used, _fh, _ft, _fql, _fqn) = \
                lax.while_loop(cond, body, state)

            record = TreeRecord(
                num_splits=n_splits,
                leaf=recI[:, RI_LEAF], feature=recI[:, RI_FEAT],
                threshold_bin=recI[:, RI_THR],
                default_left=recI[:, RI_DEFLEFT] != 0,
                is_cat=recI[:, RI_ISCAT] != 0,
                cat_bitset=recB,
                left_output=recF[:, RF_LOUT],
                right_output=recF[:, RF_ROUT],
                left_count=recI[:, RI_LC], right_count=recI[:, RI_RC],
                gain=recF[:, RF_GAIN], internal_value=recF[:, RF_IVAL],
                leaf_value=leafF[:, LF_VALUE],
                leaf_count_arr=leafI[:, LI_COUNTG],
                leaf_begin=leafI[:, LI_BEGIN],
                leaf_cnt_part=leafI[:, LI_COUNT])
            return indices, record

        fn = build_fresh if root_contiguous else build
        if self.axis_name is not None:
            return fn  # caller wraps in shard_map + jit
        if root_contiguous:
            return jax.jit(fn)
        return jax.jit(fn, donate_argnums=(2,))

    # ------------------------------------------------------------------
    def aligned_mode_ok(self, objective) -> bool:
        """True when the chunk-aligned pipeline (`aligned_builder.py`) can
        run: TPU pallas (or interpret mode for tests), a pointwise
        single-class objective, serial parallelism; numerical AND
        categorical features, with or without bagging (round 4)."""
        return self.aligned_mode_gate(objective) is None

    def aligned_mode_gate(self, objective):
        """First failing aligned-pipeline gate as a short name, or None
        when every gate passes. The gate rationale (VERDICT r5 #8: path
        observability) lives with each check; `aligned_mode_ok` is the
        boolean view."""
        mode = self.cfg.tpu_grow_mode
        if mode not in ("auto", "aligned"):
            return f"tpu_grow_mode={mode}"
        if self.cfg.sequential_device_only:
            # forced splits / CEGB need the sequential fused loop
            return "sequential-only features (forced splits/CEGB)"
        if (str(self.cfg.tpu_quant_hist).lower() == "on"
                and getattr(self, "quant_bits", 0) > 0):
            # explicit "on" means the user wants the quantized MXU hist
            # path, which lives on the fused leaf-wise builder; under
            # "auto" the aligned engine keeps priority and quantization
            # simply stays inactive there
            return "tpu_quant_hist=on (quantized hist rides the fused path)"
        from ..ops.aligned import aligned_available
        if not (bool(self.cfg.tpu_aligned_interpret) or aligned_available()):
            return "pallas kernels unavailable (no TPU, interpret off)"
        from ..ops.aligned import aligned_num_chunks
        from .level_builder import spec_slots
        S = spec_slots(self.cfg.num_leaves,
                       float(getattr(self.cfg, "tpu_level_spec", 1.5)))
        nc = aligned_num_chunks(self.n, self.cfg, S,
                                self.num_features)
        if self.parallel_mode not in ("serial", "data"):
            return f"parallel_mode={self.parallel_mode}"
        # multiclass deferred-application machinery (and its fallback)
        # stays serial-only for now
        if not (self.parallel_mode == "serial"
                or (objective is not None
                    and objective.num_model_per_iteration == 1)):
            return "multiclass under data-parallel"
        # EFB bundles ride natively (round 5): records pack the <= 256-bin
        # bundle columns, routing unpacks in-kernel, per-feature
        # histograms expand at eval only. packed-prefetch limits: 16-bit
        # destination chunk ids (NC <= 65535 at the EFFECTIVE chunk size,
        # ~67M rows at C=1024) and 8-bit word selectors (features <=
        # 1020). Above 2^24 rows the physical layout switches to the
        # exact i32 count pass (see aligned_builder big_n)
        if nc > 65535:
            return f"chunk count {nc} > 65535"
        if self.num_features > 1020:
            return f"num_features {self.num_features} > 1020"
        if self.ds.bins_dtype() != np.uint8:
            return "bins not uint8"
        if self.num_features <= 0:
            return "no features"
        if self.cfg.num_leaves < 2:
            return "num_leaves < 2"
        if self.max_bin_global > 256 or self.hist_bins > 256:
            return "max_bin > 256"
        if objective is None:
            return "no objective"
        if objective.num_model_per_iteration != 1:
            # multiclass rides K score lanes + lane-wise in-program
            # gradients (compact layout only: the meta-lane rid keeps the
            # 2^24-row cap there)
            if objective.num_model_per_iteration > 127:
                return "num_class > 127"
            if objective.mc_lane_mode() is None:
                return "objective lacks a multiclass lane mode"
            if self.n > (1 << 24):
                return "multiclass above 2^24 rows"
        # non-pointwise objectives pay a row-order gradient round-trip
        # (materialize + gather); the ext record layout (round 5) plus the
        # [K]-compact hist/eval path made this a win at the MSLR shape
        # (2.27M x 137 at 63 bins: 562 vs the fused 1264 ms/iter).
        # The old slot-block VMEM budget clause is GONE: oversized
        # stores (wide-F x 255-bin) now spill to HBM behind the move
        # pass's DMA staging ring instead of faulting (see
        # aligned_gate_notes), so only the row floor remains; forced
        # tpu_grow_mode=aligned bypasses it.
        if not (objective.point_grad_fn() is not None
                or objective.num_model_per_iteration > 1
                or self.n >= 1_000_000
                or mode == "aligned"):
            return "non-pointwise objective below the row floor"
        return None

    def aligned_gate_notes(self):
        """INFO notes about HOW the aligned path will run — distinct
        from aligned_mode_gate, whose non-None return means the path is
        NOT taken. Today: the slot-hist HBM spill. Spilling is not a
        fallback (the kernels still run aligned, the store just streams
        through the 2-deep VMEM DMA ring), so it must not surface as a
        gate failure — but a run whose histograms moved to HBM is a
        different performance regime, and path observability (VERDICT
        r5 #8) requires the log to say so."""
        from ..ops.aligned import hist_layout
        from .level_builder import spec_slots
        notes = []
        try:
            bh = self.hist_bins if self.bundled else self.max_bin_global
            ncols = (len(np.asarray(self.ds.bundles.group_num_bin))
                     if self.bundled else self.num_features)
            import os
            kcap = int(os.environ.get("LGBT_KCAP", "0") or 0) or 256
            S = spec_slots(self.cfg.num_leaves,
                           float(getattr(self.cfg, "tpu_level_spec", 1.5)))
            K = min(max(S - 1, 1), kcap)
            subbin, spill, slot_bytes, budget = hist_layout(
                self.cfg, ncols, bh, K)
            if spill:
                notes.append(
                    f"slot-hist spilled to HBM ({slot_bytes >> 10} KB/"
                    f"slot x {K + 1} slots > {budget >> 20} MB)")
        except Exception:       # notes are best-effort observability
            pass
        return notes

    def aligned_engine(self, objective, init_row_scores=None,
                       bagged=False, num_class=1):
        """The persistent AlignedEngine for (this learner, objective)."""
        eng = getattr(self, "_aligned_eng", None)
        if eng is None or eng.objective is not objective \
                or getattr(eng, "bagged", False) != bagged \
                or getattr(eng, "num_class", 1) != num_class:
            from .aligned_builder import AlignedEngine
            eng = AlignedEngine(
                self, objective,
                interpret=bool(self.cfg.tpu_aligned_interpret),
                init_row_scores=init_row_scores, bagged=bagged,
                num_class=num_class)
            self._aligned_eng = eng
        return eng

    def drop_aligned_engine(self):
        self._aligned_eng = None

    # ------------------------------------------------------------------
    def _forced_nodes(self):
        """Forced-splits JSON flattened to (used_feature, threshold_bin,
        left_node, right_node) tuples (indices into the list; -1 = no
        child). Nodes on unused features drop with their subtrees, like
        the host twin (serial_learner._apply_forced_splits)."""
        if not self.cfg.forcedsplits_filename:
            return []
        import json as _json
        with open(self.cfg.forcedsplits_filename) as fh:
            root = _json.load(fh)
        out = []

        def flat(node):
            if not isinstance(node, dict) or "feature" not in node:
                return -1
            real_f = int(node["feature"])
            fmap = self.ds.used_feature_map
            f = int(fmap[real_f]) if real_f < len(fmap) else -1
            if f < 0:
                return -1
            idx = len(out)
            out.append(None)
            thr = int(self.mappers[f].values_to_bins(
                np.asarray([float(node["threshold"])]))[0])
            lft = flat(node.get("left"))
            rgt = flat(node.get("right"))
            out[idx] = (f, thr, lft, rgt)
            return idx

        flat(root)
        return out

    # ------------------------------------------------------------------
    def init_root_partition(self, bag_indices, bag_cnt: int):
        """Fresh root partition for one boosting iteration (the analogue of
        `DataPartition::Init`, data_partition.hpp:59)."""
        from ..ops.partition import init_partition, init_partition_from
        n_pad = self.n + max(_pow2ceil(self.n), self.min_pad)
        if bag_indices is not None:
            return (init_partition_from(jnp.asarray(bag_indices), n_pad),
                    bag_cnt)
        return init_partition(self.n, n_pad), self.n

    def _fmask_arr(self, feature_mask: Optional[np.ndarray]) -> jax.Array:
        if feature_mask is None:
            return jnp.ones(self.num_features, jnp.float32)
        return jnp.asarray(feature_mask.astype(np.float32))

    # -- coupled-CEGB per-model state -----------------------------------
    @property
    def _cegb_coupled_on(self) -> bool:
        return len(self.cfg.cegb_penalty_feature_coupled) > 0

    def _cegb_coupled_eff(self) -> jax.Array:
        """Per-call coupled penalties with already-used features zeroed
        (the host mirror of the reference's once-per-model charge)."""
        if getattr(self, "_cegb_used_np", None) is None:
            self._cegb_used_np = np.zeros(self.num_features, bool)
        arr = np.asarray(self.cfg.cegb_penalty_feature_coupled, np.float64)
        real = np.asarray(self.ds.real_feature_idx)
        cp = np.zeros(self.num_features, np.float32)
        cp[:len(real)] = arr[real] * float(self.cfg.cegb_tradeoff)
        cp[self._cegb_used_np] = 0.0
        return jnp.asarray(cp)

    def _cegb_note_record(self, rec: TreeRecord) -> None:
        """Mark the tree's committed split features used (one small
        device pull; only coupled-CEGB configs pay it)."""
        if not self._cegb_coupled_on:
            return
        k = int(rec.num_splits)
        feats = np.asarray(rec.feature)[:k]
        if getattr(self, "_cegb_used_np", None) is None:
            self._cegb_used_np = np.zeros(self.num_features, bool)
        self._cegb_used_np[feats] = True

    def train(self, grad: jax.Array, hess: jax.Array,
              indices: jax.Array, root_count: int,
              feature_mask: Optional[np.ndarray] = None
              ) -> Tuple[jax.Array, TreeRecord]:
        """Grow one tree on an explicit (e.g. bagged) partition; returns
        (new partition indices, TreeRecord). `indices` must be padded so
        begin+bucket_size never overflows (length n + pow2ceil(n))."""
        from ..obs import trace as obs_trace
        root_padded = max(_pow2ceil(root_count), self.min_pad)
        fn = self._cached_program(
            (root_padded, False),
            lambda: self._make_build_fn(root_padded, False))
        args = [self.bins_dev, self.bins_T_dev, indices, grad, hess,
                jnp.int32(root_count), self._fmask_arr(feature_mask)]
        if self.quant_bits:
            args.append(jnp.int32(self._next_qseq()))
        if self._cegb_coupled_on:
            args.append(self._cegb_coupled_eff())
        with obs_trace.span("learner.train", root=root_padded):
            idxs, rec = fn(*args)
        self._cegb_note_record(rec) if self._cegb_coupled_on else None
        return idxs, rec

    def train_fresh(self, grad: jax.Array, hess: jax.Array,
                    feature_mask: Optional[np.ndarray] = None
                    ) -> Tuple[jax.Array, TreeRecord]:
        """Grow one tree on the full data with a fresh identity partition
        (created inside the program — fewer dispatches, contiguous root
        histogram)."""
        if self.level_mode_ok():
            out = self._level_train_fresh(grad, hess, feature_mask)
            if out is not None:
                return out
        from ..obs import trace as obs_trace
        root_padded = max(_pow2ceil(self.n), self.min_pad)
        fn = self._cached_program(
            (root_padded, True),
            lambda: self._make_build_fn(root_padded, True))
        args = [self.bins_dev, self.bins_T_dev, grad, hess,
                self._fmask_arr(feature_mask)]
        if self.quant_bits:
            args.append(jnp.int32(self._next_qseq()))
        if self._cegb_coupled_on:
            args.append(self._cegb_coupled_eff())
        with obs_trace.span("learner.train_fresh", root=root_padded):
            idxs, rec = fn(*args)
        if self._cegb_coupled_on:
            self._cegb_note_record(rec)
        return idxs, rec

    def sweep_build_fn(self, root_padded: int, root_contiguous: bool,
                       l1, l2, l2c):
        """Raw (un-jitted) whole-tree build with the split lambdas threaded
        as traced scalars — the sweep trainer's per-model build lane.

        Must be called INSIDE an active trace (the sweep round program)
        with `l1`/`l2`/`l2c` tracers: the split finder is rebuilt around a
        hyper whose lambda fields are those tracers, `_make_build_fn`
        captures it, and `self.finder` is restored before returning. The
        raw python body is returned (not the jitted wrapper) so the
        enable_x64 blocks inside `_build` execute live during the caller's
        vmap trace — vmapping the cached jitted program re-canonicalizes
        the f64 reduce inits to f32, which XLA rejects as mixed precision.
        """
        hyper_t = self.hyper._replace(lambda_l1=l1, lambda_l2=l2,
                                      lambda_l2_cat=l2c)
        old_finder = self.finder
        self.finder = make_split_finder(hyper_t, self.meta,
                                        self.max_bin_global)
        try:
            # _make_build_fn captures self.finder into a local; restoring
            # the static finder afterwards does not disturb the closure
            return self._make_build_fn(root_padded, root_contiguous
                                       ).__wrapped__
        finally:
            self.finder = old_finder

    def train_iter_fused(self, score: jax.Array, objective, scale: float,
                         feature_mask: Optional[np.ndarray] = None
                         ) -> Tuple[jax.Array, jax.Array, TreeRecord]:
        """ONE device program for a whole boosting iteration (single-class,
        no bagging): objective gradients -> fused tree build -> partition
        score update. Per-program launch costs ~100-200ms on a tunneled
        runtime, so the three stages are traced together; the score buffer
        is donated through.

        Returns (new_score [K,N], indices, record).
        """
        if self.level_mode_ok():
            out = self._level_iter_fused(score, objective, scale,
                                         feature_mask)
            if out is not None:
                return out
        root_padded = max(_pow2ceil(self.n), self.min_pad)
        # the fused step closes over the objective's gradient program,
        # which captures label/weight device data — the objective's
        # trace signature (content-hashed data) keys the shared program
        key = (root_padded, "iter_fused", objective.trace_signature())

        def factory():
            build = self._make_build_fn(root_padded, True)
            n_rows = self.n

            def step(score, bins, bins_T, scale, fmask, *opt):
                # bins ride as runtime args (not closure constants) so
                # the program is data-independent and registry-shareable;
                # *opt forwards the (qseq?, coupled_eff?) tail untouched
                compile_cache.note_trace()
                gdev, hdev = objective.gradients_impl(score)
                # nested jitted calls inline into this trace
                indices, rec = build(bins, bins_T, gdev[0], hdev[0],
                                     fmask, *opt)
                new_score = _partition_score_update(
                    score, jnp.int32(0), rec.leaf_begin,
                    rec.leaf_cnt_part, rec.leaf_value, indices,
                    jnp.int32(n_rows), scale)
                return new_score, indices, rec

            return jax.jit(step, donate_argnums=(0,))

        fn = self._cached_program(key, factory)
        args = [score, self.bins_dev, self.bins_T_dev, jnp.float32(scale),
                self._fmask_arr(feature_mask)]
        if self.quant_bits:
            args.append(jnp.int32(self._next_qseq()))
        if self._cegb_coupled_on:
            args.append(self._cegb_coupled_eff())
        out = fn(*args)
        if self._cegb_coupled_on:
            self._cegb_note_record(out[2])
        return out

    def _level_iter_fused(self, score, objective, scale, feature_mask):
        """Level-mode iteration: program A traces gradients + speculative
        build; the leaf-wise replay runs on host; program B applies the
        block score update. Returns None when the replay was inexact (the
        caller then runs the sequential leaf-wise fused path)."""
        from .level_builder import replay_leafwise
        key = ("level_iterA", objective.trace_signature())

        def factory():
            level = self._level_fn()

            def stepA(score, words, fmask):
                compile_cache.note_trace()
                gdev, hdev = objective.gradients_impl(score)
                return level(words, gdev[0], hdev[0], fmask)

            return jax.jit(stepA)

        fnA = self._cached_program(key, factory)
        spec = fnA(score, self.words_dev, self._fmask_arr(feature_mask))
        host = jax.device_get(spec._replace(rid=None))
        rec, exact = replay_leafwise(host, self.cfg.num_leaves)
        if not exact:
            self._level_fallbacks = getattr(self, "_level_fallbacks", 0) + 1
            return None
        rec = rec._replace(block_begin=spec.block_begin,
                           block_cnt=spec.block_cnt)
        new_score = _partition_score_update(
            score, jnp.int32(0), spec.block_begin, spec.block_cnt,
            jnp.asarray(rec.block_value, jnp.float32), spec.rid,
            jnp.int32(self.n), jnp.float32(scale))
        return new_score, spec.rid, rec

    # ------------------------------------------------------------------
    def record_to_tree(self, rec_host, shrinkage: float = 1.0) -> Tree:
        """Host-side conversion of a pulled TreeRecord into a full Tree
        (bin thresholds -> real values via the BinMappers)."""
        n_splits = int(rec_host.num_splits)
        tree = Tree(self.cfg.num_leaves)
        mt_code = {"none": 0, "zero": 1, "nan": 2}
        for s in range(n_splits):
            leaf = int(rec_host.leaf[s])
            f = int(rec_host.feature[s])
            mapper = self.mappers[f]
            real_feature = int(self.ds.real_feature_idx[f])
            mt = mt_code[mapper.missing_type]
            if bool(rec_host.is_cat[s]):
                words = rec_host.cat_bitset[s]
                bins_list = [b for b in range(min(mapper.num_bin, 256))
                             if (int(words[b // 32]) >> (b % 32)) & 1]
                cats = [mapper.bin_2_categorical[b] for b in bins_list
                        if b < len(mapper.bin_2_categorical)]
                tree.split_categorical(
                    leaf, f, real_feature, bins_list, cats,
                    float(rec_host.left_output[s]),
                    float(rec_host.right_output[s]),
                    int(rec_host.left_count[s]),
                    int(rec_host.right_count[s]),
                    float(rec_host.gain[s]), mt,
                    default_bin=mapper.default_bin, num_bin=mapper.num_bin)
            else:
                thr_bin = int(rec_host.threshold_bin[s])
                tree.split(
                    leaf, f, real_feature, thr_bin,
                    mapper.bin_to_value(thr_bin),
                    float(rec_host.left_output[s]),
                    float(rec_host.right_output[s]),
                    int(rec_host.left_count[s]),
                    int(rec_host.right_count[s]),
                    float(rec_host.gain[s]), mt,
                    bool(rec_host.default_left[s]),
                    default_bin=mapper.default_bin, num_bin=mapper.num_bin)
        if shrinkage != 1.0:
            tree.apply_shrinkage(shrinkage)
        return tree


@functools.partial(jax.jit, donate_argnums=(0,))
def _partition_score_update(score, class_id, leaf_begin, leaf_cnt,
                            leaf_value, indices, count, scale):
    """One fused program: leaf fill over the partition + key-sort back to
    row order + score[class_id] += scale * delta."""
    compile_cache.note_trace()
    n = score.shape[1]
    # leaf slices all live inside [0, n): fill and sort only that prefix
    fill = leaf_value_fill(leaf_begin, leaf_cnt, leaf_value, n)
    delta = unpermute_to_rows(lax.slice(indices, (0,), (n,)), fill, count, n)
    return score.at[class_id].add(scale * delta)


def _masked_sums(indices, gh, count, padded: int, f64: bool = False):
    # Deliberately NOT @jax.jit: the only call site is inside `_build`'s
    # trace, and a nested pjit re-canonicalizes the f64 reduce init to f32
    # when the enclosing program is vmapped (sweep mode), which XLA rejects
    # as mixed precision. Inline tracing keeps the enable_x64 block live.
    idx = lax.dynamic_slice(indices, (jnp.int32(0),), (padded,))
    pos = jnp.arange(padded, dtype=jnp.int32)
    valid = pos < count
    safe = jnp.where(valid, idx, 0)
    # explicit f32: the quantized path passes int8/int16 gh rows (the
    # caller rescales the sums by the pack scale afterwards)
    masked = jnp.where(valid[:, None], gh[safe].astype(jnp.float32), 0.0)
    if f64:
        with jax.experimental.enable_x64():
            s = jnp.sum(masked.astype(jnp.float64), axis=0)
            return s[0], s[1]
    s = jnp.sum(masked, axis=0)
    return s[0], s[1]


# ---------------------------------------------------------------------------
# device score update from a TreeRecord
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("max_nodes",))
def traversal_arrays(rec: TreeRecord, max_nodes: int):
    """Build device traversal arrays (feature/threshold/children) from a
    TreeRecord — the on-device analogue of `stack_trees`."""
    compile_cache.note_trace()
    left, right = record_to_children(rec.leaf, rec.num_splits, max_nodes)
    return {
        "feature": rec.feature, "threshold_bin": rec.threshold_bin,
        "default_left": rec.default_left, "is_cat": rec.is_cat,
        "cat_bitset": rec.cat_bitset, "left": left, "right": right,
        "num_splits": rec.num_splits, "leaf_value": rec.leaf_value,
    }


@jax.jit
def traverse_record(bins: jax.Array, trav: Dict, nb, db, mt,
                    col=None, boff=None, bpk=None) -> jax.Array:
    """[N] leaf index per row for one TreeRecord's tree over binned data.
    nb/db/mt: per-feature num_bin/default_bin/missing arrays; col/boff/bpk
    map features to bundled storage columns (EFB, io/bundling.py)."""
    compile_cache.note_trace()
    n = bins.shape[0]

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        safe = jnp.maximum(node, 0)
        feat = trav["feature"][safe]
        scol = feat if col is None else col[feat]
        fval = bins[jnp.arange(n), scol].astype(jnp.int32)
        if boff is not None:
            from ..ops.partition import bundle_unpack
            fval = bundle_unpack(fval, boff[feat], bpk[feat], db[feat],
                                 nb[feat])
        gl_num = numerical_goes_left(fval, trav["threshold_bin"][safe],
                                     trav["default_left"][safe], mt[feat],
                                     db[feat], nb[feat])
        bitsets = trav["cat_bitset"][safe]  # [N, 8]
        in_words = (fval >> 5) < 8
        word = jnp.clip(fval >> 5, 0, 7)
        w = jnp.take_along_axis(bitsets, word[:, None], axis=1)[:, 0]
        gl_cat = (((w >> (fval & 31).astype(jnp.uint32)) & 1) != 0) & in_words
        goes_left = jnp.where(trav["is_cat"][safe], gl_cat, gl_num)
        nxt = jnp.where(goes_left, trav["left"][safe], trav["right"][safe])
        return jnp.where(node >= 0, nxt, node)

    node0 = jnp.where(trav["num_splits"] > 0, jnp.zeros(n, jnp.int32),
                      jnp.full(n, -1, jnp.int32))
    node = lax.while_loop(cond, body, node0)
    return ~node


@jax.jit
def add_record_score(score_row: jax.Array, bins: jax.Array, trav: Dict,
                     nb, db, mt, scale, col=None, boff=None,
                     bpk=None) -> jax.Array:
    """score += scale * tree(x) for all rows via record traversal."""
    compile_cache.note_trace()
    leaves = traverse_record(bins, trav, nb, db, mt, col, boff, bpk)
    return score_row + scale * trav["leaf_value"][leaves]
