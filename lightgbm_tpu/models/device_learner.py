"""Fused on-device tree builder: ONE jitted program grows a whole tree.

Why: the host-driven `SerialTreeLearner` issues ~15 host<->device syncs per
split; on a tunneled TPU each sync costs ~100ms, dwarfing compute. This
learner keeps the entire leaf-wise loop (reference
`SerialTreeLearner::Train`, serial_tree_learner.cpp:173-237) inside one
`lax.fori_loop`: per-leaf state, the histogram pool
(reference HistogramPool, feature_histogram.hpp:654), the partition, and the
recorded splits all live in device arrays. Dynamic leaf sizes are handled by
a `lax.switch` over power-of-two size buckets — each branch compiles its own
statically-shaped gather + MXU histogram / stable partition.

The host pulls nothing during training; a finished tree is a `TreeRecord`
pytree of device arrays, convertible to a host `Tree` (one batched transfer)
only when the model is exported, and convertible to traversal arrays
on-device for score updates.
"""
from __future__ import annotations

import functools
import math
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import Config
from ..io.dataset import Dataset
from ..ops.histogram import NUM_HIST_STATS, histogram_from_gathered
from ..ops.partition import (categorical_goes_left, numerical_goes_left,
                             split_partition)
from ..ops.split import SplitHyper, make_split_finder
from .tree import Tree

NEG_INF = -jnp.inf


class TreeRecord(NamedTuple):
    """Per-split records of one grown tree (device pytree)."""
    num_splits: jax.Array          # i32 scalar: actual splits made
    leaf: jax.Array                # i32[L-1] leaf id split at step s
    feature: jax.Array             # i32[L-1] inner feature index
    threshold_bin: jax.Array       # i32[L-1]
    default_left: jax.Array        # bool[L-1]
    is_cat: jax.Array              # bool[L-1]
    cat_bitset: jax.Array          # u32[L-1, 8] (bins)
    left_output: jax.Array         # f32[L-1]
    right_output: jax.Array        # f32[L-1]
    left_count: jax.Array          # i32[L-1]
    right_count: jax.Array         # i32[L-1]
    gain: jax.Array                # f32[L-1]
    internal_value: jax.Array      # f32[L-1] (parent output before split)
    leaf_value: jax.Array          # f32[L] final leaf outputs
    leaf_count_arr: jax.Array      # i32[L]
    leaf_begin: jax.Array          # i32[L] partition begins
    leaf_cnt_part: jax.Array       # i32[L] partition counts


def _pow2ceil(n: int) -> int:
    return 1 << max(0, int(math.ceil(math.log2(max(n, 1)))))


@functools.partial(jax.jit, static_argnames=("max_nodes",))
def record_to_children(leaf_rec: jax.Array, num_splits: jax.Array,
                       max_nodes: int) -> Tuple[jax.Array, jax.Array]:
    """Reconstruct left/right child links from the split sequence.

    Node s split leaf `leaf_rec[s]` into left=same leaf id, right=s+1.
    left_child[s] -> the NEXT step that splits leaf_rec[s] (as a node), else
    ~leaf_rec[s]; right_child[s] -> the next step that splits leaf s+1, else
    ~(s+1).  O(L^2) vectorized — trivial next to histogram work.
    """
    s_idx = jnp.arange(max_nodes)
    later = (s_idx[None, :] > s_idx[:, None]) \
        & (s_idx[None, :] < num_splits)

    def next_split_of(target):  # target: [max_nodes] leaf ids
        hit = later & (leaf_rec[None, :] == target[:, None])
        any_hit = hit.any(axis=1)
        first = jnp.argmax(hit, axis=1)
        return any_hit, first

    l_hit, l_first = next_split_of(leaf_rec)
    left = jnp.where(l_hit, l_first, ~leaf_rec)
    r_leaf = s_idx + 1
    r_hit, r_first = next_split_of(r_leaf)
    right = jnp.where(r_hit, r_first, ~r_leaf)
    return left.astype(jnp.int32), right.astype(jnp.int32)


class DeviceTreeLearner:
    """Drop-in replacement for SerialTreeLearner with zero mid-tree syncs.

    With ``axis_name`` set, the same whole-tree program becomes the
    data-parallel learner (reference `DataParallelTreeLearner`,
    `data_parallel_tree_learner.cpp`): rows are sharded over a mesh axis,
    local histograms are `lax.psum`-reduced (the XLA/ICI analogue of
    `Network::ReduceScatter` + best-split allreduce — since every shard then
    holds the GLOBAL histogram, the best split is computed redundantly and
    identically on all shards, so no separate `SyncUpGlobalBestSplit` is
    needed), and leaf counts split into a LOCAL set driving the per-shard
    partition and a GLOBAL set driving split decisions (the reference's
    `global_data_count_in_leaf_`, data_parallel_tree_learner.cpp:251-257).
    Collectives sit at uniform program points (outside `lax.switch`
    branches) so shards never diverge on collective schedules.
    """

    def __init__(self, cfg: Config, dataset: Dataset,
                 axis_name: Optional[str] = None,
                 parallel_mode: Optional[str] = None,
                 feature_pad_to: Optional[int] = None,
                 mesh_size: int = 1) -> None:
        self.cfg = cfg
        self.axis_name = axis_name
        # serial (single program) / data (rows sharded, psum histograms) /
        # feature (rows replicated, feature-block histogram work division) /
        # voting (rows sharded, top-k vote + selected-feature reduce)
        self.parallel_mode = parallel_mode or (
            "data" if axis_name is not None else "serial")
        self.mesh_size = mesh_size
        self.ds = dataset
        self.n = dataset.num_data
        self.num_real_features = dataset.num_features
        meta = dataset.feature_meta_arrays()
        if feature_pad_to and feature_pad_to > len(meta["num_bin"]):
            # pad the feature axis so it divides evenly over the mesh
            # (feature-parallel block slicing); padded features are trivial
            # (num_bin=2, no data) and masked out of every split search
            pad = feature_pad_to - len(meta["num_bin"])
            meta = dict(meta)
            meta["num_bin"] = np.concatenate(
                [meta["num_bin"], np.full(pad, 2, meta["num_bin"].dtype)])
            for key, fill in (("default_bin", 0), ("missing_type", 0),
                              ("bin_type", 0), ("monotone", 0)):
                meta[key] = np.concatenate(
                    [meta[key], np.full(pad, fill, meta[key].dtype)])
            meta["penalty"] = np.concatenate(
                [meta["penalty"], np.ones(pad, meta["penalty"].dtype)])
        self.num_features = len(meta["num_bin"])
        self.meta = meta
        self.max_bin_global = int(meta["num_bin"].max()) \
            if len(meta["num_bin"]) else 2
        self._bins_dev = None  # lazy: the data-parallel wrapper never
        # materializes this second (replicated) device copy of the bins
        self.hyper = SplitHyper.from_config(cfg)
        self.finder = make_split_finder(self.hyper, meta, self.max_bin_global)
        self.mappers = dataset.used_mappers()
        self._feat_rng = np.random.RandomState(cfg.feature_fraction_seed)
        self.hist_precision = ("f32" if cfg.gpu_use_dp or cfg.tpu_use_f64_hist
                               else "bf16x2")
        self.min_pad = int(cfg.tpu_min_pad)
        # device feature metadata for the partition step
        self._nb_dev = jnp.asarray(meta["num_bin"], jnp.int32)
        self._db_dev = jnp.asarray(meta["default_bin"], jnp.int32)
        self._mt_dev = jnp.asarray(meta["missing_type"], jnp.int32)
        self._mono_any = bool(np.any(meta["monotone"] != 0))
        self._build_cache: Dict[int, callable] = {}
        self._depth_limit = cfg.max_depth if cfg.max_depth > 0 else 1 << 30

    @property
    def bins_dev(self) -> jax.Array:
        if self._bins_dev is None:
            self._bins_dev = jnp.asarray(self.ds.bins)
        return self._bins_dev

    def add_score(self, score_row: jax.Array, trav: Dict,
                  scale: float) -> jax.Array:
        """score += scale * tree(x) over the training bins."""
        return add_record_score(score_row, self.bins_dev, trav, self._nb_dev,
                                self._db_dev, self._mt_dev,
                                jnp.float32(scale))

    # ------------------------------------------------------------------
    def feature_mask(self) -> Optional[np.ndarray]:
        frac = self.cfg.feature_fraction
        if frac >= 1.0:
            if self.num_features != self.num_real_features:
                mask = np.zeros(self.num_features, bool)
                mask[:self.num_real_features] = True  # padded features off
                return mask
            return None
        used_cnt = max(1, int(round(self.num_real_features * frac)))
        mask = np.zeros(self.num_features, bool)
        mask[self._feat_rng.choice(self.num_real_features, used_cnt,
                                   replace=False)] = True
        return mask

    # ------------------------------------------------------------------
    def _buckets_for(self, root_count: int) -> List[int]:
        sizes = []
        s = self.min_pad
        top = max(_pow2ceil(root_count), self.min_pad)
        while s <= top:
            sizes.append(s)
            s <<= 1
        return sizes

    def _bucket_index(self, count, n_buckets: int):
        """Smallest bucket with min_pad << b >= count — exact integer
        comparison against the bucket-size table (float log2 would undercount
        near 2^24 and silently drop rows)."""
        sizes = jnp.asarray([self.min_pad << b for b in range(n_buckets)],
                            jnp.int32)
        b = jnp.sum((count > sizes).astype(jnp.int32))
        return jnp.clip(b, 0, n_buckets - 1)

    # ------------------------------------------------------------------
    def _make_build_fn(self, root_padded: int):
        """Build the jitted whole-tree program for a given root size."""
        cfg = self.cfg
        L = cfg.num_leaves
        F = self.num_features
        B = self.max_bin_global
        buckets = self._buckets_for(root_padded)
        nbk = len(buckets)
        finder = self.finder
        nb_dev, db_dev, mt_dev = self._nb_dev, self._db_dev, self._mt_dev
        chunk = int(cfg.tpu_hist_chunk)
        precision = self.hist_precision
        depth_limit = self._depth_limit

        mode = self.parallel_mode
        nd = self.mesh_size if mode == "feature" else 1
        f_block = F // nd if mode == "feature" else F
        if mode == "voting":
            vote_k = max(1, min(int(cfg.top_k), F))
            vote_sel = min(2 * vote_k, F)
            # local searches relax min_data/min_hessian by the machine count
            # (reference voting_parallel_tree_learner.cpp:58-59)
            m = max(1, self.mesh_size)
            hyper_local = self.hyper._replace(
                min_data_in_leaf=max(1, self.hyper.min_data_in_leaf // m),
                min_sum_hessian_in_leaf=(
                    self.hyper.min_sum_hessian_in_leaf / m))
            finder_local = make_split_finder(hyper_local, self.meta, B)

        def hist_bucket(size):
            def fn(bins, indices, grad, hess, begin, count):
                idx = lax.dynamic_slice(indices, (begin,), (size,))
                pos = jnp.arange(size, dtype=jnp.int32)
                valid = pos < count
                safe = jnp.where(valid, idx, 0)
                rows = bins[safe]
                if mode == "feature":
                    # feature-parallel: each shard histograms only its
                    # feature block (reference feature_parallel_tree_
                    # learner.cpp:33-52 work division); the psum that
                    # follows assembles the global histogram, subsuming
                    # SyncUpGlobalBestSplit
                    start = lax.axis_index(self.axis_name) * f_block
                    rows = lax.dynamic_slice(
                        rows, (jnp.int32(0), start), (size, f_block))
                    hb = histogram_from_gathered(rows, grad[safe],
                                                 hess[safe], valid, B,
                                                 chunk, precision)
                    full = jnp.zeros((F, B, NUM_HIST_STATS), jnp.float32)
                    return lax.dynamic_update_slice(
                        full, hb, (start, jnp.int32(0), jnp.int32(0)))
                return histogram_from_gathered(rows, grad[safe],
                                               hess[safe], valid, B, chunk,
                                               precision)
            return fn

        def part_bucket(size):
            def fn(bins_col, indices, begin, count, threshold, default_left,
                   missing_type, default_bin, num_bin, is_cat, bitset):
                return split_partition(indices, bins_col, begin, count, size,
                                       threshold, default_left, missing_type,
                                       default_bin, num_bin, is_cat, bitset)
            return fn

        hist_fns = [hist_bucket(s) for s in buckets]
        part_fns = [part_bucket(s) for s in buckets]
        axis = self.axis_name

        # Collective placement by mode (all ride ICI as XLA all-reduces;
        # they sit at uniform program points so shards never diverge):
        #   data:    histograms psum'd (ReduceScatter analogue); row-local
        #            scalars psum'd (root-sums allreduce)
        #   feature: block histograms psum'd into the global histogram
        #            (subsumes SyncUpGlobalBestSplit); rows replicated so
        #            scalars are already global
        #   voting:  histograms stay LOCAL (only elected features are
        #            reduced, inside eval_leaf); row-local scalars psum'd
        def _gsum_hist(x):
            if axis is not None and mode in ("data", "feature"):
                return lax.psum(x, axis)
            return x

        def _gsum_scalar(x):
            if axis is not None and mode in ("data", "voting"):
                return lax.psum(x, axis)
            return x

        def build(bins, indices, grad, hess, root_count, feature_mask_f32):
            # ---------- state ----------
            leaf_begin = jnp.zeros(L, jnp.int32)
            leaf_count = jnp.zeros(L, jnp.int32).at[0].set(root_count)
            leaf_depth = jnp.zeros(L, jnp.int32)
            leaf_minc = jnp.full(L, -jnp.inf, jnp.float32)
            leaf_maxc = jnp.full(L, jnp.inf, jnp.float32)
            hist_store = jnp.zeros((L, F, B, NUM_HIST_STATS), jnp.float32)

            best = {
                "gain": jnp.full(L, NEG_INF, jnp.float32),
                "feature": jnp.zeros(L, jnp.int32),
                "threshold": jnp.zeros(L, jnp.int32),
                "default_left": jnp.zeros(L, bool),
                "is_cat": jnp.zeros(L, bool),
                "cat_bitset": jnp.zeros((L, 8), jnp.uint32),
                "left_g": jnp.zeros(L, jnp.float32),
                "left_h": jnp.zeros(L, jnp.float32),
                "left_c": jnp.zeros(L, jnp.int32),
                "right_g": jnp.zeros(L, jnp.float32),
                "right_h": jnp.zeros(L, jnp.float32),
                "right_c": jnp.zeros(L, jnp.int32),
                "left_output": jnp.zeros(L, jnp.float32),
                "right_output": jnp.zeros(L, jnp.float32),
            }
            rec = {
                "leaf": jnp.zeros(max(L - 1, 1), jnp.int32),
                "feature": jnp.zeros(max(L - 1, 1), jnp.int32),
                "threshold_bin": jnp.zeros(max(L - 1, 1), jnp.int32),
                "default_left": jnp.zeros(max(L - 1, 1), bool),
                "is_cat": jnp.zeros(max(L - 1, 1), bool),
                "cat_bitset": jnp.zeros((max(L - 1, 1), 8), jnp.uint32),
                "left_output": jnp.zeros(max(L - 1, 1), jnp.float32),
                "right_output": jnp.zeros(max(L - 1, 1), jnp.float32),
                "left_count": jnp.zeros(max(L - 1, 1), jnp.int32),
                "right_count": jnp.zeros(max(L - 1, 1), jnp.int32),
                "gain": jnp.zeros(max(L - 1, 1), jnp.float32),
                "internal_value": jnp.zeros(max(L - 1, 1), jnp.float32),
            }
            leaf_value = jnp.zeros(L, jnp.float32)

            # ---------- root ----------
            bsel = self._bucket_index(root_count, nbk)
            root_hist = lax.switch(
                bsel, hist_fns, bins, indices, grad, hess, jnp.int32(0),
                root_count)
            root_hist = _gsum_hist(root_hist)
            hist_store = hist_store.at[0].set(root_hist)
            # root grad/hess sums by direct reduction (data-parallel: the
            # root-sums allreduce, data_parallel_tree_learner.cpp:120-145)
            root_g, root_h = _masked_sums(indices, grad, hess, root_count,
                                          root_padded)
            root_g, root_h = _gsum_scalar(root_g), _gsum_scalar(root_h)
            root_count_g = _gsum_scalar(root_count)
            leaf_count_glob = jnp.zeros(L, jnp.int32).at[0].set(root_count_g)
            leaf_sum_g = jnp.zeros(L, jnp.float32).at[0].set(root_g)
            leaf_sum_h = jnp.zeros(L, jnp.float32).at[0].set(root_h)

            def _payload(out, gain):
                f = jnp.argmax(gain)
                return {
                    "gain": gain[f],
                    "feature": f.astype(jnp.int32),
                    "threshold": out["threshold"][f],
                    "default_left": out["default_left"][f],
                    "is_cat": out["is_cat"][f],
                    "cat_bitset": out["cat_bitset"][f],
                    "left_g": out["left_g"][f],
                    "left_h": out["left_h"][f],
                    "left_c": out["left_c"][f],
                    "right_g": out["right_g"][f],
                    "right_h": out["right_h"][f],
                    "right_c": out["right_c"][f],
                    "left_output": out["left_output"][f],
                    "right_output": out["right_output"][f],
                }

            def _mask_gain(gain, depth):
                gain = jnp.where(feature_mask_f32 > 0, gain, NEG_INF)
                return jnp.where(depth >= depth_limit,
                                 jnp.full_like(gain, NEG_INF), gain)

            if mode == "voting":
                # PV-Tree (reference voting_parallel_tree_learner.cpp:
                # 262-400): local top-k vote -> global vote -> reduce only
                # the elected features' histograms -> global best split.
                # `hist` here is this shard's LOCAL histogram of the leaf.
                def eval_leaf(hist, sg, sh, cnt, minc, maxc, depth):
                    # local leaf sums: every row lands in exactly one bin of
                    # feature 0, so its histogram column sums to the local
                    # totals (no FixHistogram-style bin skipping here)
                    lsg = jnp.sum(hist[0, :, 0])
                    lsh = jnp.sum(hist[0, :, 1])
                    lcnt = jnp.sum(hist[0, :, 2]).astype(jnp.int32)
                    lout = finder_local(hist, lsg, lsh, lcnt, minc, maxc)
                    lgain = _mask_gain(lout["gain"], depth)
                    _, top_idx = lax.top_k(lgain, vote_k)
                    # votes weighted by local data share (GlobalVoting
                    # weighting, voting_parallel_tree_learner.cpp:170-200)
                    votes = jnp.zeros((F,), jnp.float32).at[top_idx].add(
                        1.0 + lcnt.astype(jnp.float32))
                    votes = lax.psum(votes, axis)
                    _, sel_idx = lax.top_k(votes, vote_sel)  # same on all
                    hist_sel = lax.psum(hist[sel_idx], axis)
                    ghist = jnp.zeros_like(hist).at[sel_idx].set(hist_sel)
                    out = finder(ghist, sg, sh, cnt, minc, maxc)
                    selmask = jnp.zeros((F,), bool).at[sel_idx].set(True)
                    gain = jnp.where(selmask, out["gain"], NEG_INF)
                    return _payload(out, _mask_gain(gain, depth))
            else:
                def eval_leaf(hist, sg, sh, cnt, minc, maxc, depth):
                    out = finder(hist, sg, sh, cnt, minc, maxc)
                    return _payload(out, _mask_gain(out["gain"], depth))

            root_best = eval_leaf(root_hist, root_g, root_h, root_count_g,
                                  jnp.float32(-jnp.inf), jnp.float32(jnp.inf),
                                  jnp.int32(0))
            best = {k: best[k].at[0].set(root_best[k]) for k in best}

            state = (indices, leaf_begin, leaf_count, leaf_count_glob,
                     leaf_sum_g, leaf_sum_h,
                     leaf_depth, leaf_minc, leaf_maxc, hist_store, best, rec,
                     leaf_value, jnp.int32(0), jnp.asarray(False))

            def body(s, state):
                (indices, leaf_begin, leaf_count, leaf_count_glob,
                 leaf_sum_g, leaf_sum_h,
                 leaf_depth, leaf_minc, leaf_maxc, hist_store, best, rec,
                 leaf_value, n_splits, done) = state
                bl = jnp.argmax(best["gain"]).astype(jnp.int32)
                gain_ok = best["gain"][bl] > 0.0
                do_split = gain_ok & ~done

                def no_op(_):
                    return (indices, leaf_begin, leaf_count, leaf_count_glob,
                            leaf_sum_g,
                            leaf_sum_h, leaf_depth, leaf_minc, leaf_maxc,
                            hist_store, best, rec, leaf_value, n_splits,
                            jnp.asarray(True))

                def apply(_):
                    new_leaf = s + 1
                    f = best["feature"][bl]
                    thr = best["threshold"][bl]
                    dleft = best["default_left"][bl]
                    iscat = best["is_cat"][bl]
                    bitset = best["cat_bitset"][bl]
                    begin = leaf_begin[bl]
                    count = leaf_count[bl]
                    bk = self._bucket_index(count, nbk)
                    new_indices, left_cnt = lax.switch(
                        bk, part_fns, bins[:, f], indices, begin, count, thr,
                        dleft, mt_dev[f], db_dev[f], nb_dev[f], iscat, bitset)
                    right_cnt = count - left_cnt
                    # GLOBAL child counts come from the (already psum-reduced)
                    # histogram's count channel — exact integers in f32
                    left_cnt_g = best["left_c"][bl]
                    right_cnt_g = best["right_c"][bl]

                    # record
                    rec2 = dict(rec)
                    rec2["leaf"] = rec["leaf"].at[s].set(bl)
                    rec2["feature"] = rec["feature"].at[s].set(f)
                    rec2["threshold_bin"] = rec["threshold_bin"].at[s].set(thr)
                    rec2["default_left"] = rec["default_left"].at[s].set(dleft)
                    rec2["is_cat"] = rec["is_cat"].at[s].set(iscat)
                    rec2["cat_bitset"] = rec["cat_bitset"].at[s].set(bitset)
                    rec2["left_output"] = rec["left_output"].at[s].set(
                        best["left_output"][bl])
                    rec2["right_output"] = rec["right_output"].at[s].set(
                        best["right_output"][bl])
                    rec2["left_count"] = rec["left_count"].at[s].set(
                        left_cnt_g)
                    rec2["right_count"] = rec["right_count"].at[s].set(
                        right_cnt_g)
                    rec2["gain"] = rec["gain"].at[s].set(best["gain"][bl])
                    rec2["internal_value"] = rec["internal_value"].at[s].set(
                        leaf_value[bl])

                    lv = leaf_value.at[bl].set(best["left_output"][bl])
                    lv = lv.at[new_leaf].set(best["right_output"][bl])

                    # children bookkeeping
                    lb = leaf_begin.at[new_leaf].set(begin + left_cnt)
                    lc_ = leaf_count.at[bl].set(left_cnt)
                    lc_ = lc_.at[new_leaf].set(right_cnt)
                    lcg = leaf_count_glob.at[bl].set(left_cnt_g)
                    lcg = lcg.at[new_leaf].set(right_cnt_g)
                    depth = leaf_depth[bl] + 1
                    ld = leaf_depth.at[bl].set(depth)
                    ld = ld.at[new_leaf].set(depth)
                    lsg = leaf_sum_g.at[bl].set(best["left_g"][bl])
                    lsg = lsg.at[new_leaf].set(best["right_g"][bl])
                    lsh = leaf_sum_h.at[bl].set(best["left_h"][bl])
                    lsh = lsh.at[new_leaf].set(best["right_h"][bl])

                    # monotone constraint propagation
                    if self._mono_any:
                        mono = jnp.asarray(self.meta["monotone"],
                                           jnp.int32)[f]
                        mid = (best["left_output"][bl]
                               + best["right_output"][bl]) / 2.0
                        lmax = jnp.where(mono > 0,
                                         jnp.minimum(leaf_maxc[bl], mid),
                                         leaf_maxc[bl])
                        rmin = jnp.where(mono > 0,
                                         jnp.maximum(leaf_minc[bl], mid),
                                         leaf_minc[bl])
                        lmin = jnp.where(mono < 0,
                                         jnp.maximum(leaf_minc[bl], mid),
                                         leaf_minc[bl])
                        rmax = jnp.where(mono < 0,
                                         jnp.minimum(leaf_maxc[bl], mid),
                                         leaf_maxc[bl])
                        lminc = leaf_minc.at[bl].set(lmin)
                        lminc = lminc.at[new_leaf].set(rmin)
                        lmaxc = leaf_maxc.at[bl].set(lmax)
                        lmaxc = lmaxc.at[new_leaf].set(rmax)
                    else:
                        lminc, lmaxc = leaf_minc, leaf_maxc

                    # histogram: construct smaller child, subtract for larger.
                    # "Smaller" is decided on GLOBAL counts so every shard
                    # histograms the same child (the reference uses
                    # GetGlobalDataCountInLeaf the same way,
                    # data_parallel_tree_learner.cpp:198-220); each shard
                    # gathers its LOCAL slice of that child.
                    smaller_is_left = left_cnt_g <= right_cnt_g
                    sm_begin = jnp.where(smaller_is_left, begin,
                                         begin + left_cnt)
                    sm_count = jnp.where(smaller_is_left, left_cnt, right_cnt)
                    bk2 = self._bucket_index(sm_count, nbk)
                    sm_hist = lax.switch(bk2, hist_fns, bins, new_indices,
                                         grad, hess, sm_begin, sm_count)
                    sm_hist = _gsum_hist(sm_hist)
                    lg_hist = hist_store[bl] - sm_hist
                    left_hist = jnp.where(smaller_is_left, sm_hist, lg_hist)
                    right_hist = jnp.where(smaller_is_left, lg_hist, sm_hist)
                    hs = hist_store.at[bl].set(left_hist)
                    hs = hs.at[new_leaf].set(right_hist)

                    # evaluate both children (global counts)
                    lbst = eval_leaf(left_hist, lsg[bl], lsh[bl], left_cnt_g,
                                     lminc[bl], lmaxc[bl], depth)
                    rbst = eval_leaf(right_hist, lsg[new_leaf],
                                     lsh[new_leaf], right_cnt_g,
                                     lminc[new_leaf], lmaxc[new_leaf], depth)
                    best2 = dict(best)
                    for k in best2:
                        best2[k] = best2[k].at[bl].set(lbst[k])
                        best2[k] = best2[k].at[new_leaf].set(rbst[k])

                    return (new_indices, lb, lc_, lcg, lsg, lsh, ld, lminc,
                            lmaxc, hs, best2, rec2, lv, n_splits + 1, done)

                return lax.cond(do_split, apply, no_op, None)

            (indices, leaf_begin, leaf_count, leaf_count_glob,
             leaf_sum_g, leaf_sum_h,
             leaf_depth, leaf_minc, leaf_maxc, hist_store, best, rec,
             leaf_value, n_splits, done) = lax.fori_loop(
                0, max(L - 1, 0), body, state)

            record = TreeRecord(
                num_splits=n_splits,
                leaf=rec["leaf"], feature=rec["feature"],
                threshold_bin=rec["threshold_bin"],
                default_left=rec["default_left"], is_cat=rec["is_cat"],
                cat_bitset=rec["cat_bitset"],
                left_output=rec["left_output"],
                right_output=rec["right_output"],
                left_count=rec["left_count"], right_count=rec["right_count"],
                gain=rec["gain"], internal_value=rec["internal_value"],
                leaf_value=leaf_value, leaf_count_arr=leaf_count_glob,
                leaf_begin=leaf_begin, leaf_cnt_part=leaf_count)
            return indices, record

        if self.axis_name is not None:
            return build  # caller wraps in shard_map + jit
        return jax.jit(build, donate_argnums=(1,))

    # ------------------------------------------------------------------
    def init_root_partition(self, bag_indices, bag_cnt: int):
        """Fresh root partition for one boosting iteration (the analogue of
        `DataPartition::Init`, data_partition.hpp:59)."""
        from ..ops.partition import init_partition, init_partition_from
        n_pad = self.n + max(_pow2ceil(self.n), self.min_pad)
        if bag_indices is not None:
            return (init_partition_from(jnp.asarray(bag_indices), n_pad),
                    bag_cnt)
        return init_partition(self.n, n_pad), self.n

    def train(self, grad: jax.Array, hess: jax.Array,
              indices: jax.Array, root_count: int,
              feature_mask: Optional[np.ndarray] = None
              ) -> Tuple[jax.Array, TreeRecord]:
        """Grow one tree; returns (new partition indices, TreeRecord).
        `indices` must be padded so begin+bucket_size never overflows
        (length n + pow2ceil(n))."""
        root_padded = max(_pow2ceil(root_count), self.min_pad)
        fn = self._build_cache.get(root_padded)
        if fn is None:
            fn = self._make_build_fn(root_padded)
            self._build_cache[root_padded] = fn
        if feature_mask is None:
            fmask = jnp.ones(self.num_features, jnp.float32)
        else:
            fmask = jnp.asarray(feature_mask.astype(np.float32))
        return fn(self.bins_dev, indices, grad, hess, jnp.int32(root_count),
                  fmask)

    # ------------------------------------------------------------------
    def record_to_tree(self, rec_host, shrinkage: float = 1.0) -> Tree:
        """Host-side conversion of a pulled TreeRecord into a full Tree
        (bin thresholds -> real values via the BinMappers)."""
        n_splits = int(rec_host.num_splits)
        tree = Tree(self.cfg.num_leaves)
        mt_code = {"none": 0, "zero": 1, "nan": 2}
        for s in range(n_splits):
            leaf = int(rec_host.leaf[s])
            f = int(rec_host.feature[s])
            mapper = self.mappers[f]
            real_feature = int(self.ds.real_feature_idx[f])
            mt = mt_code[mapper.missing_type]
            if bool(rec_host.is_cat[s]):
                words = rec_host.cat_bitset[s]
                bins_list = [b for b in range(min(mapper.num_bin, 256))
                             if (int(words[b // 32]) >> (b % 32)) & 1]
                cats = [mapper.bin_2_categorical[b] for b in bins_list
                        if b < len(mapper.bin_2_categorical)]
                tree.split_categorical(
                    leaf, f, real_feature, bins_list, cats,
                    float(rec_host.left_output[s]),
                    float(rec_host.right_output[s]),
                    int(rec_host.left_count[s]),
                    int(rec_host.right_count[s]),
                    float(rec_host.gain[s]), mt,
                    default_bin=mapper.default_bin, num_bin=mapper.num_bin)
            else:
                thr_bin = int(rec_host.threshold_bin[s])
                tree.split(
                    leaf, f, real_feature, thr_bin,
                    mapper.bin_to_value(thr_bin),
                    float(rec_host.left_output[s]),
                    float(rec_host.right_output[s]),
                    int(rec_host.left_count[s]),
                    int(rec_host.right_count[s]),
                    float(rec_host.gain[s]), mt,
                    bool(rec_host.default_left[s]),
                    default_bin=mapper.default_bin, num_bin=mapper.num_bin)
        if shrinkage != 1.0:
            tree.apply_shrinkage(shrinkage)
        return tree


@functools.partial(jax.jit, static_argnames=("padded",))
def _masked_sums(indices, grad, hess, count, padded: int):
    idx = lax.dynamic_slice(indices, (jnp.int32(0),), (padded,))
    pos = jnp.arange(padded, dtype=jnp.int32)
    valid = pos < count
    safe = jnp.where(valid, idx, 0)
    g = jnp.where(valid, grad[safe], 0.0)
    h = jnp.where(valid, hess[safe], 0.0)
    return g.sum(), h.sum()


# ---------------------------------------------------------------------------
# device score update from a TreeRecord
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("max_nodes",))
def traversal_arrays(rec: TreeRecord, max_nodes: int):
    """Build device traversal arrays (feature/threshold/children) from a
    TreeRecord — the on-device analogue of `stack_trees`."""
    left, right = record_to_children(rec.leaf, rec.num_splits, max_nodes)
    return {
        "feature": rec.feature, "threshold_bin": rec.threshold_bin,
        "default_left": rec.default_left, "is_cat": rec.is_cat,
        "cat_bitset": rec.cat_bitset, "left": left, "right": right,
        "num_splits": rec.num_splits, "leaf_value": rec.leaf_value,
    }


@jax.jit
def traverse_record(bins: jax.Array, trav: Dict, nb, db, mt) -> jax.Array:
    """[N] leaf index per row for one TreeRecord's tree over binned data.
    nb/db/mt: per-feature num_bin/default_bin/missing arrays."""
    n = bins.shape[0]

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        safe = jnp.maximum(node, 0)
        feat = trav["feature"][safe]
        fval = bins[jnp.arange(n), feat].astype(jnp.int32)
        gl_num = numerical_goes_left(fval, trav["threshold_bin"][safe],
                                     trav["default_left"][safe], mt[feat],
                                     db[feat], nb[feat])
        bitsets = trav["cat_bitset"][safe]  # [N, 8]
        in_words = (fval >> 5) < 8
        word = jnp.clip(fval >> 5, 0, 7)
        w = jnp.take_along_axis(bitsets, word[:, None], axis=1)[:, 0]
        gl_cat = (((w >> (fval & 31).astype(jnp.uint32)) & 1) != 0) & in_words
        goes_left = jnp.where(trav["is_cat"][safe], gl_cat, gl_num)
        nxt = jnp.where(goes_left, trav["left"][safe], trav["right"][safe])
        return jnp.where(node >= 0, nxt, node)

    node0 = jnp.where(trav["num_splits"] > 0, jnp.zeros(n, jnp.int32),
                      jnp.full(n, -1, jnp.int32))
    node = lax.while_loop(cond, body, node0)
    return ~node


@jax.jit
def add_record_score(score_row: jax.Array, bins: jax.Array, trav: Dict,
                     nb, db, mt, scale) -> jax.Array:
    """score += scale * tree(x) for all rows via record traversal."""
    leaves = traverse_record(bins, trav, nb, db, mt)
    return score_row + scale * trav["leaf_value"][leaves]
