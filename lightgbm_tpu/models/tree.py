"""Decision tree model structure.

Re-creates the reference `Tree` (`include/LightGBM/tree.h`, `src/io/tree.cpp`):
array-of-nodes layout where internal nodes are numbered 0..num_leaves-2 and
leaves are referenced as `~leaf` (negative) in child links, categorical splits
as bitsets with per-node boundaries, decision_type bit packing
(kCategoricalMask=1, kDefaultLeftMask=2, missing type in bits 2-3), and the
reference's text model format (`Tree::ToString`, tree.cpp:206-239) so model
files interoperate.

Tree building happens on host (one Split per boosting step, driven by the
learner); batch prediction is device-side (`ops/predict.py`) over stacked
tree arrays.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2

MISSING_NONE_C, MISSING_ZERO_C, MISSING_NAN_C = 0, 1, 2


def _avoid_inf(x: float) -> float:
    """reference Common::AvoidInf: clamp +-inf/nan to +-1e300."""
    if math.isnan(x):
        return 0.0
    if x >= 1e300:
        return 1e300
    if x <= -1e300:
        return -1e300
    return float(x)


def construct_bitset(values: Sequence[int]) -> np.ndarray:
    """reference Common::ConstructBitset."""
    if len(values) == 0:
        return np.zeros(1, dtype=np.uint32)
    n_words = (max(values) // 32) + 1
    out = np.zeros(n_words, dtype=np.uint32)
    for v in values:
        out[v // 32] |= np.uint32(1) << np.uint32(v % 32)
    return out


def find_in_bitset(bitset: np.ndarray, val: int) -> bool:
    """reference Common::FindInBitset."""
    w = val // 32
    if w >= len(bitset) or val < 0:
        return False
    return bool((int(bitset[w]) >> (val % 32)) & 1)


class Tree:
    """A single decision tree (reference tree.h:25+)."""

    def __init__(self, max_leaves: int) -> None:
        m = max(max_leaves, 2)
        self.max_leaves = m
        self.num_leaves = 1
        self.num_cat = 0
        # internal-node arrays (size max_leaves-1)
        self.left_child = np.zeros(m - 1, dtype=np.int32)
        self.right_child = np.zeros(m - 1, dtype=np.int32)
        self.split_feature_inner = np.zeros(m - 1, dtype=np.int32)
        self.split_feature = np.zeros(m - 1, dtype=np.int32)
        self.threshold_in_bin = np.zeros(m - 1, dtype=np.int32)
        self.threshold = np.zeros(m - 1, dtype=np.float64)
        self.decision_type = np.zeros(m - 1, dtype=np.int8)
        self.split_gain = np.zeros(m - 1, dtype=np.float64)
        self.internal_value = np.zeros(m - 1, dtype=np.float64)
        self.internal_count = np.zeros(m - 1, dtype=np.int32)
        # per-node binned-decision metadata (TPU addition: lets the binned
        # traversal run without dataset lookups; reference threads these from
        # FeatureGroup at predict time)
        self.node_default_bin = np.zeros(m - 1, dtype=np.int32)
        self.node_num_bin = np.zeros(m - 1, dtype=np.int32)
        # leaf arrays (size max_leaves)
        self.leaf_parent = np.zeros(m, dtype=np.int32)
        self.leaf_value = np.zeros(m, dtype=np.float64)
        self.leaf_count = np.zeros(m, dtype=np.int32)
        self.leaf_depth = np.zeros(m, dtype=np.int32)
        # categorical storage
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []          # uint32 bitset words
        self.cat_boundaries_inner: List[int] = [0]
        self.cat_threshold_inner: List[int] = []
        self.shrinkage = 1.0
        self.leaf_parent[0] = -1

    # ------------------------------------------------------------------
    def _split_common(self, leaf: int, feature: int, real_feature: int,
                      left_value: float, right_value: float, left_cnt: int,
                      right_cnt: int, gain: float) -> int:
        new_node = self.num_leaves - 1
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature_inner[new_node] = feature
        self.split_feature[new_node] = real_feature
        self.split_gain[new_node] = _avoid_inf(gain)
        self.left_child[new_node] = ~leaf
        self.right_child[new_node] = ~self.num_leaves
        self.internal_value[new_node] = self.leaf_value[leaf]
        self.internal_count[new_node] = left_cnt + right_cnt
        self.leaf_parent[leaf] = new_node
        self.leaf_parent[self.num_leaves] = new_node
        self.leaf_value[leaf] = left_value if not math.isnan(left_value) else 0.0
        self.leaf_value[self.num_leaves] = (right_value
                                            if not math.isnan(right_value)
                                            else 0.0)
        self.leaf_count[leaf] = left_cnt
        self.leaf_count[self.num_leaves] = right_cnt
        d = self.leaf_depth[leaf] + 1
        self.leaf_depth[leaf] = d
        self.leaf_depth[self.num_leaves] = d
        return new_node

    def split(self, leaf: int, feature: int, real_feature: int,
              threshold_bin: int, threshold_double: float, left_value: float,
              right_value: float, left_cnt: int, right_cnt: int, gain: float,
              missing_type: int, default_left: bool,
              default_bin: int = 0, num_bin: int = 0) -> int:
        """Numerical split (reference tree.cpp:48-67). Returns new leaf id."""
        node = self._split_common(leaf, feature, real_feature, left_value,
                                  right_value, left_cnt, right_cnt, gain)
        dt = 0
        if default_left:
            dt |= K_DEFAULT_LEFT_MASK
        dt |= (missing_type & 3) << 2
        self.decision_type[node] = dt
        self.threshold_in_bin[node] = threshold_bin
        self.threshold[node] = _avoid_inf(threshold_double)
        self.node_default_bin[node] = default_bin
        self.node_num_bin[node] = num_bin
        self.num_leaves += 1
        return self.num_leaves - 1

    def split_categorical(self, leaf: int, feature: int, real_feature: int,
                          threshold_bins: Sequence[int],
                          threshold_cats: Sequence[int], left_value: float,
                          right_value: float, left_cnt: int, right_cnt: int,
                          gain: float, missing_type: int,
                          default_bin: int = 0, num_bin: int = 0) -> int:
        """Categorical split (reference tree.cpp:69-96): thresholds stored as
        bitsets over category values (outer) and bins (inner)."""
        node = self._split_common(leaf, feature, real_feature, left_value,
                                  right_value, left_cnt, right_cnt, gain)
        dt = K_CATEGORICAL_MASK | ((missing_type & 3) << 2)
        self.decision_type[node] = dt
        self.threshold_in_bin[node] = self.num_cat
        self.threshold[node] = self.num_cat
        self.node_default_bin[node] = default_bin
        self.node_num_bin[node] = num_bin
        self.num_cat += 1
        outer = construct_bitset([int(c) for c in threshold_cats])
        inner = construct_bitset([int(b) for b in threshold_bins])
        self.cat_boundaries.append(self.cat_boundaries[-1] + len(outer))
        self.cat_threshold.extend(int(w) for w in outer)
        self.cat_boundaries_inner.append(
            self.cat_boundaries_inner[-1] + len(inner))
        self.cat_threshold_inner.extend(int(w) for w in inner)
        self.num_leaves += 1
        return self.num_leaves - 1

    # ------------------------------------------------------------------
    def apply_shrinkage(self, rate: float) -> None:
        """reference Tree::Shrinkage."""
        self.leaf_value[:self.num_leaves] *= rate
        self.internal_value[:self.num_leaves - 1] *= rate
        self.shrinkage *= rate

    def as_constant_tree(self, val: float) -> None:
        self.num_leaves = 1
        self.leaf_value[0] = val

    def add_bias(self, val: float) -> None:
        """Used by boost_from_average score folding (reference
        GBDT::BoostFromAverage alternative path)."""
        self.leaf_value[:self.num_leaves] += val
        self.internal_value[:self.num_leaves - 1] += val

    @property
    def max_depth(self) -> int:
        if self.num_leaves <= 1:
            return 0
        return int(self.leaf_depth[:self.num_leaves].max())

    # ------------------------------------------------------------------
    def node_missing_type(self, node: int) -> int:
        return (int(self.decision_type[node]) >> 2) & 3

    def node_default_left(self, node: int) -> bool:
        return bool(self.decision_type[node] & K_DEFAULT_LEFT_MASK)

    def node_is_categorical(self, node: int) -> bool:
        return bool(self.decision_type[node] & K_CATEGORICAL_MASK)

    def _decision(self, fval: float, node: int) -> int:
        if self.node_is_categorical(node):
            mt = self.node_missing_type(node)
            if math.isnan(fval):
                if mt == MISSING_NAN_C:
                    return self.right_child[node]
                ival = 0
            else:
                ival = int(fval)
                if ival < 0:
                    return self.right_child[node]
            cat_idx = int(self.threshold_in_bin[node])
            lo = self.cat_boundaries[cat_idx]
            hi = self.cat_boundaries[cat_idx + 1]
            bits = np.asarray(self.cat_threshold[lo:hi], dtype=np.uint32)
            return (self.left_child[node] if find_in_bitset(bits, ival)
                    else self.right_child[node])
        mt = self.node_missing_type(node)
        if math.isnan(fval) and mt != MISSING_NAN_C:
            fval = 0.0
        if ((mt == MISSING_ZERO_C and -1e-35 <= fval <= 1e-35)
                or (mt == MISSING_NAN_C and math.isnan(fval))):
            return (self.left_child[node] if self.node_default_left(node)
                    else self.right_child[node])
        return (self.left_child[node] if fval <= self.threshold[node]
                else self.right_child[node])

    def predict_row(self, features: np.ndarray) -> float:
        """Single-row prediction on raw values (reference Tree::Predict)."""
        return self.leaf_value[self.predict_leaf_row(features)]

    def predict_leaf_row(self, features: np.ndarray) -> int:
        if self.num_leaves <= 1:
            return 0
        node = 0
        while node >= 0:
            node = self._decision(float(features[self.split_feature[node]]),
                                  node)
        return ~node

    # ------------------------------------------------------------------
    # text model round-trip (reference Tree::ToString tree.cpp:206-239 /
    # Tree::Tree(const char*) tree.cpp:472+)
    def to_string(self) -> str:
        nl = self.num_leaves
        lines = [f"num_leaves={nl}", f"num_cat={self.num_cat}"]

        def arr(name, a, n, fmt=str):
            lines.append(f"{name}=" + " ".join(fmt(x) for x in a[:n]))

        def fmt_f(x):
            return repr(float(x))

        arr("split_feature", self.split_feature, nl - 1)
        arr("split_gain", self.split_gain, nl - 1, fmt_f)
        arr("threshold", self.threshold, nl - 1, fmt_f)
        arr("decision_type", self.decision_type, nl - 1)
        arr("left_child", self.left_child, nl - 1)
        arr("right_child", self.right_child, nl - 1)
        arr("leaf_value", self.leaf_value, nl, fmt_f)
        arr("leaf_count", self.leaf_count, nl)
        arr("internal_value", self.internal_value, nl - 1, fmt_f)
        arr("internal_count", self.internal_count, nl - 1)
        # TPU additions required for binned traversal after load
        arr("split_feature_inner", self.split_feature_inner, nl - 1)
        arr("threshold_in_bin", self.threshold_in_bin, nl - 1)
        arr("node_default_bin", self.node_default_bin, nl - 1)
        arr("node_num_bin", self.node_num_bin, nl - 1)
        if self.num_cat > 0:
            arr("cat_boundaries", np.asarray(self.cat_boundaries),
                self.num_cat + 1)
            arr("cat_threshold", np.asarray(self.cat_threshold),
                len(self.cat_threshold))
            arr("cat_boundaries_inner", np.asarray(self.cat_boundaries_inner),
                self.num_cat + 1)
            arr("cat_threshold_inner", np.asarray(self.cat_threshold_inner),
                len(self.cat_threshold_inner))
        lines.append(f"shrinkage={repr(float(self.shrinkage))}")
        lines.append("")
        return "\n".join(lines) + "\n"

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        kv: Dict[str, str] = {}
        for line in text.splitlines():
            line = line.strip()
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v
        nl = int(kv["num_leaves"])
        t = cls(max(nl, 2))
        t.num_leaves = nl
        t.num_cat = int(kv.get("num_cat", "0"))

        def geti(key, n, dtype=np.int32):
            if n <= 0 or key not in kv or not kv[key].strip():
                return np.zeros(max(n, 0), dtype=dtype)
            return np.asarray([int(x) for x in kv[key].split()], dtype=dtype)

        def getf(key, n):
            if n <= 0 or key not in kv or not kv[key].strip():
                return np.zeros(max(n, 0), dtype=np.float64)
            return np.asarray([float(x) for x in kv[key].split()],
                              dtype=np.float64)

        if nl > 1:
            t.split_feature[:nl - 1] = geti("split_feature", nl - 1)
            t.split_gain[:nl - 1] = getf("split_gain", nl - 1)
            t.threshold[:nl - 1] = getf("threshold", nl - 1)
            t.decision_type[:nl - 1] = geti("decision_type", nl - 1, np.int8)
            t.left_child[:nl - 1] = geti("left_child", nl - 1)
            t.right_child[:nl - 1] = geti("right_child", nl - 1)
            t.internal_value[:nl - 1] = getf("internal_value", nl - 1)
            t.internal_count[:nl - 1] = geti("internal_count", nl - 1)
            if "split_feature_inner" in kv:
                t.split_feature_inner[:nl - 1] = geti("split_feature_inner",
                                                      nl - 1)
                t.threshold_in_bin[:nl - 1] = geti("threshold_in_bin", nl - 1)
                t.node_default_bin[:nl - 1] = geti("node_default_bin", nl - 1)
                t.node_num_bin[:nl - 1] = geti("node_num_bin", nl - 1)
            else:
                t.split_feature_inner[:nl - 1] = t.split_feature[:nl - 1]
                # reference files carry the cat-bitset index in `threshold`
                # (tree.cpp Tree::Tree(const char*)); mirror it into
                # threshold_in_bin which the binned/_decision paths read
                cat_nodes = (t.decision_type[:nl - 1]
                             & K_CATEGORICAL_MASK) != 0
                t.threshold_in_bin[:nl - 1][cat_nodes] = \
                    t.threshold[:nl - 1][cat_nodes].astype(np.int32)
        t.leaf_value[:nl] = getf("leaf_value", nl)
        t.leaf_count[:nl] = geti("leaf_count", nl)
        if t.num_cat > 0:
            t.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
            t.cat_threshold = [int(x) for x in kv["cat_threshold"].split()]
            if "cat_boundaries_inner" in kv:
                t.cat_boundaries_inner = [
                    int(x) for x in kv["cat_boundaries_inner"].split()]
                t.cat_threshold_inner = [
                    int(x) for x in kv["cat_threshold_inner"].split()]
            else:
                t.cat_boundaries_inner = list(t.cat_boundaries)
                t.cat_threshold_inner = list(t.cat_threshold)
        t.shrinkage = float(kv.get("shrinkage", "1"))
        # rebuild leaf parents/depths from child links
        if nl > 1:
            for node in range(nl - 1):
                for ch in (t.left_child[node], t.right_child[node]):
                    if ch < 0:
                        t.leaf_parent[~ch] = node
        return t

    def to_json(self) -> dict:
        """reference Tree::ToJSON (tree.cpp:241+)."""
        def node_json(node: int) -> dict:
            if node < 0:
                leaf = ~node
                return {
                    "leaf_index": int(leaf),
                    "leaf_value": float(self.leaf_value[leaf]),
                    "leaf_count": int(self.leaf_count[leaf]),
                }
            is_cat = self.node_is_categorical(node)
            mt = self.node_missing_type(node)
            d = {
                "split_index": int(node),
                "split_feature": int(self.split_feature[node]),
                "split_gain": float(self.split_gain[node]),
                "threshold": float(self.threshold[node]),
                "decision_type": "==" if is_cat else "<=",
                "default_left": self.node_default_left(node),
                "missing_type": ["None", "Zero", "NaN"][mt],
                "internal_value": float(self.internal_value[node]),
                "internal_count": int(self.internal_count[node]),
                "left_child": node_json(int(self.left_child[node])),
                "right_child": node_json(int(self.right_child[node])),
            }
            return d

        return {
            "num_leaves": int(self.num_leaves),
            "num_cat": int(self.num_cat),
            "shrinkage": float(self.shrinkage),
            "tree_structure": node_json(0 if self.num_leaves > 1 else ~0),
        }
