"""Single-device leaf-wise tree learner.

Re-creates the reference `SerialTreeLearner` (`src/treelearner/
serial_tree_learner.cpp:173-892`): best-first growth to `num_leaves`, where
each step histograms the SMALLER child and derives the larger by parent-minus-
smaller subtraction (`BeforeFindBestSplit` smaller/larger assignment
`:364-441`, `FindBestSplits` `:443-595`), applies the split to the row
partition, and propagates monotone mid-constraints (`Split` `:757-851`).

TPU mapping:
- binned matrix + partition indices + grad/hess live in HBM
- histogram = MXU one-hot contraction over the leaf's gathered rows
  (`ops/histogram.py`), jit-cached per power-of-two padded leaf size
- split finding = one vectorized program over all features (`ops/split.py`)
- the only host/device sync per split is the chosen SplitInfo scalars —
  the analogue of the reference's per-leaf best-split argmax on host
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.binning import BIN_CATEGORICAL
from ..io.dataset import Dataset
from ..ops.histogram import leaf_histogram, subtract_histogram
from ..ops.partition import init_partition, init_partition_from, \
    split_partition
from ..ops.split import SplitHyper, make_split_finder
from .tree import Tree

_MISSING_CODE_TO_C = {"none": 0, "zero": 1, "nan": 2}


def _pow2_pad(n: int, min_pad: int) -> int:
    return max(min_pad, 1 << max(int(math.ceil(math.log2(max(n, 1)))), 0))


class _LeafInfo:
    __slots__ = ("begin", "count", "sum_g", "sum_h", "hist", "best",
                 "depth", "min_constraint", "max_constraint")

    def __init__(self, begin, count, sum_g, sum_h, depth=0,
                 min_constraint=-np.inf, max_constraint=np.inf):
        self.begin = begin
        self.count = count
        self.sum_g = sum_g
        self.sum_h = sum_h
        self.hist = None
        self.best = None
        self.depth = depth
        self.min_constraint = min_constraint
        self.max_constraint = max_constraint


class SerialTreeLearner:
    """Reference `TreeLearner` contract (`include/LightGBM/tree_learner.h`)."""

    def __init__(self, cfg: Config, dataset: Dataset) -> None:
        self.cfg = cfg
        self.ds = dataset
        self.n = dataset.num_data
        self.num_features = dataset.num_features
        meta = dataset.feature_meta_arrays()
        self.meta = meta
        self.max_bin_global = int(meta["num_bin"].max()) \
            if len(meta["num_bin"]) else 2
        self.bins_dev = jnp.asarray(dataset.bins)
        self.hyper = SplitHyper.from_config(cfg)
        self.finder = make_split_finder(self.hyper, meta, self.max_bin_global)
        self.mappers = dataset.used_mappers()
        self._feat_rng = np.random.RandomState(cfg.feature_fraction_seed)
        # partition storage: leaf slices stay contiguous; extra tail so a
        # padded dynamic_slice never wraps (see ops/partition.py)
        self.n_pad = self.n + _pow2_pad(self.n, cfg.tpu_min_pad)
        self.indices = init_partition(self.n, self.n_pad)
        self.hist_precision = ("f32" if cfg.gpu_use_dp or cfg.tpu_use_f64_hist
                               else "bf16x2")
        self._monotone_any = bool(np.any(meta["monotone"] != 0))

    # ------------------------------------------------------------------
    def _feature_mask(self) -> Optional[np.ndarray]:
        """Per-tree column sampling (reference BeforeTrain feature sampling,
        serial_tree_learner.cpp:275-296)."""
        frac = self.cfg.feature_fraction
        if frac >= 1.0:
            return None
        used_cnt = max(1, int(round(self.num_features * frac)))
        mask = np.zeros(self.num_features, bool)
        sel = self._feat_rng.choice(self.num_features, used_cnt,
                                    replace=False)
        mask[sel] = True
        return mask

    def _leaf_hist(self, leaf: _LeafInfo, grad, hess):
        padded = _pow2_pad(leaf.count, self.cfg.tpu_min_pad)
        return leaf_histogram(
            self.bins_dev, self.indices, jnp.int32(leaf.begin),
            jnp.int32(leaf.count), grad, hess, padded=padded,
            max_bin=self.max_bin_global, chunk=self.cfg.tpu_hist_chunk,
            precision=self.hist_precision)

    def _find_best(self, leaf: _LeafInfo, feature_mask) -> dict:
        out = self.finder(leaf.hist, jnp.float32(leaf.sum_g),
                          jnp.float32(leaf.sum_h), jnp.int32(leaf.count),
                          jnp.float32(leaf.min_constraint),
                          jnp.float32(leaf.max_constraint))
        gain = np.asarray(out["gain"], np.float64)
        if feature_mask is not None:
            gain = np.where(feature_mask, gain, -np.inf)
        # depth limit (BeforeFindBestSplit, serial_tree_learner.cpp:364-377)
        if 0 < self.cfg.max_depth <= leaf.depth:
            gain = np.full_like(gain, -np.inf)
        best_f = int(np.argmax(gain))
        res = {
            "feature": best_f,
            "gain": float(gain[best_f]),
            "threshold": int(np.asarray(out["threshold"])[best_f]),
            "default_left": bool(np.asarray(out["default_left"])[best_f]),
            "left_g": float(np.asarray(out["left_g"])[best_f]),
            "left_h": float(np.asarray(out["left_h"])[best_f]),
            "left_c": int(np.asarray(out["left_c"])[best_f]),
            "right_g": float(np.asarray(out["right_g"])[best_f]),
            "right_h": float(np.asarray(out["right_h"])[best_f]),
            "right_c": int(np.asarray(out["right_c"])[best_f]),
            "left_output": float(np.asarray(out["left_output"])[best_f]),
            "right_output": float(np.asarray(out["right_output"])[best_f]),
        }
        if "use_onehot" in out and \
                self.meta["bin_type"][best_f] == 1:
            res["is_cat"] = True
            if bool(np.asarray(out["use_onehot"])[best_f]):
                res["cat_bins"] = [res["threshold"]]
            else:
                order = np.asarray(out["sort_order"])[best_f]
                n_elig = int(np.asarray(out["n_elig"])[best_f])
                cdir = int(np.asarray(out["cat_dir"])[best_f])
                k = res["threshold"] + 1
                if cdir == 1:
                    res["cat_bins"] = [int(order[i]) for i in range(k)]
                else:
                    res["cat_bins"] = [int(order[n_elig - 1 - i])
                                       for i in range(k)]
        else:
            res["is_cat"] = False
        return res

    # ------------------------------------------------------------------
    def train(self, grad: jax.Array, hess: jax.Array,
              bag_indices: Optional[np.ndarray] = None,
              bag_count: Optional[int] = None) -> Tuple[Tree, Dict]:
        """Grow one tree (reference SerialTreeLearner::Train,
        serial_tree_learner.cpp:173-237). grad/hess are full-length [N]
        device arrays; bag_indices restricts rows (bagging/GOSS)."""
        cfg = self.cfg
        feature_mask = self._feature_mask()
        if bag_indices is not None:
            count = int(bag_count if bag_count is not None
                        else len(bag_indices))
            self.indices = init_partition_from(bag_indices, self.n_pad)
        else:
            count = self.n
            self.indices = init_partition(self.n, self.n_pad)

        # root sums (BeforeTrain root sumup, serial_tree_learner.cpp:307-316)
        padded_root = _pow2_pad(count, cfg.tpu_min_pad)
        root = _LeafInfo(0, count, 0.0, 0.0)
        root.hist = self._leaf_hist(root, grad, hess)
        # root grad/hess totals from the histogram of feature 0 would drop
        # rows beyond num_bin masking; use a direct masked reduction instead
        sums = _root_sums(self.indices, grad, hess, jnp.int32(count),
                          padded_root)
        root.sum_g = float(np.asarray(sums[0]))
        root.sum_h = float(np.asarray(sums[1]))
        root.best = self._find_best(root, feature_mask)

        tree = Tree(cfg.num_leaves)
        leaves: Dict[int, _LeafInfo] = {0: root}
        leaf_begin_count: Dict[int, Tuple[int, int]] = {}

        for _ in range(cfg.num_leaves - 1):
            # pick max-gain leaf (Train loop, serial_tree_learner.cpp:201-224)
            best_leaf, best_gain = -1, 0.0
            for lid, info in leaves.items():
                if info.best is not None and info.best["gain"] > best_gain \
                        and np.isfinite(info.best["gain"]):
                    best_leaf, best_gain = lid, info.best["gain"]
            if best_leaf < 0:
                break
            info = leaves[best_leaf]
            b = info.best
            f = b["feature"]
            mapper = self.mappers[f]
            mt_c = _MISSING_CODE_TO_C[mapper.missing_type]

            # --- tree update
            real_feature = int(self.ds.real_feature_idx[f])
            if b["is_cat"]:
                cat_bins = b["cat_bins"]
                cats = [mapper.bin_2_categorical[bb] for bb in cat_bins
                        if bb < len(mapper.bin_2_categorical)]
                right_leaf = tree.split_categorical(
                    best_leaf, f, real_feature, cat_bins, cats,
                    b["left_output"], b["right_output"], b["left_c"],
                    b["right_c"], b["gain"], mt_c,
                    default_bin=mapper.default_bin, num_bin=mapper.num_bin)
                cat_bitset = np.zeros(8, np.uint32)
                for bb in cat_bins:
                    cat_bitset[bb // 32] |= np.uint32(1) << np.uint32(bb % 32)
            else:
                thr_double = mapper.bin_to_value(b["threshold"])
                right_leaf = tree.split(
                    best_leaf, f, real_feature, b["threshold"], thr_double,
                    b["left_output"], b["right_output"], b["left_c"],
                    b["right_c"], b["gain"], mt_c, b["default_left"],
                    default_bin=mapper.default_bin, num_bin=mapper.num_bin)
                cat_bitset = np.zeros(8, np.uint32)

            # --- partition update
            padded = _pow2_pad(info.count, cfg.tpu_min_pad)
            self.indices, lcnt_dev = split_partition(
                self.indices, self.bins_dev[:, f], jnp.int32(info.begin),
                jnp.int32(info.count), padded, jnp.int32(b["threshold"]),
                jnp.asarray(b["default_left"]), jnp.int32(mt_c),
                jnp.int32(mapper.default_bin), jnp.int32(mapper.num_bin),
                jnp.asarray(b["is_cat"]), jnp.asarray(cat_bitset))
            left_count = int(np.asarray(lcnt_dev))
            # partition and split-finder counts can differ only by numeric
            # noise in f32 histogram counts; trust the partition
            right_count = info.count - left_count

            # --- child leaf infos + monotone constraint propagation
            # (serial_tree_learner.cpp:826-851)
            lmin, lmax = info.min_constraint, info.max_constraint
            rmin, rmax = info.min_constraint, info.max_constraint
            mono = int(self.meta["monotone"][f]) if self._monotone_any else 0
            if mono != 0:
                mid = (b["left_output"] + b["right_output"]) / 2.0
                if mono > 0:
                    lmax = min(lmax, mid)
                    rmin = max(rmin, mid)
                else:
                    lmin = max(lmin, mid)
                    rmax = min(rmax, mid)
            left = _LeafInfo(info.begin, left_count, b["left_g"],
                             b["left_h"], info.depth + 1, lmin, lmax)
            right = _LeafInfo(info.begin + left_count, right_count,
                              b["right_g"], b["right_h"], info.depth + 1,
                              rmin, rmax)

            # --- histogram: construct smaller, subtract for larger
            if left_count <= right_count:
                smaller, larger = left, right
            else:
                smaller, larger = right, left
            can_split_more = (tree.num_leaves < cfg.num_leaves)
            if can_split_more:
                smaller.hist = self._leaf_hist(smaller, grad, hess)
                larger.hist = subtract_histogram(info.hist, smaller.hist)
                smaller.best = self._find_best(smaller, feature_mask)
                larger.best = self._find_best(larger, feature_mask)
            leaves[best_leaf] = left
            leaves[right_leaf] = right
            info.hist = None  # free parent histogram

        leaf_begin_count = {lid: (inf.begin, inf.count)
                            for lid, inf in leaves.items()}
        return tree, leaf_begin_count

    # ------------------------------------------------------------------
    def renew_tree_output(self, tree: Tree, leaf_begin_count: Dict,
                          objective, scores_np: np.ndarray,
                          label_np: np.ndarray,
                          weights_np: Optional[np.ndarray]) -> None:
        """Percentile leaf renewal for L1-family objectives (reference
        SerialTreeLearner::RenewTreeOutput, serial_tree_learner.cpp:854-892).
        """
        if not getattr(objective, "is_renew_tree_output", False):
            return
        idx_np = np.asarray(self.indices)
        for lid, (begin, count) in leaf_begin_count.items():
            rows = idx_np[begin:begin + count]
            resid = objective.residual(label_np[rows], scores_np[rows])
            if objective.name == "mape":
                w = objective._label_weight_np[rows]
            else:
                w = weights_np[rows] if weights_np is not None else None
            # reference order: renew BEFORE shrinkage (gbdt.cpp:400-408)
            tree.leaf_value[lid] = objective.renew_leaf_output(resid, w)


import functools


@functools.partial(jax.jit, static_argnames=("padded",))
def _root_sums(indices, grad, hess, count, padded: int):
    idx = jax.lax.dynamic_slice(indices, (jnp.int32(0),), (padded,))
    pos = jnp.arange(padded, dtype=jnp.int32)
    valid = pos < count
    safe = jnp.where(valid, idx, 0)
    g = jnp.where(valid, grad[safe], 0.0)
    h = jnp.where(valid, hess[safe], 0.0)
    return jnp.stack([g.sum(), h.sum()])
