"""Single-device leaf-wise tree learner.

Re-creates the reference `SerialTreeLearner` (`src/treelearner/
serial_tree_learner.cpp:173-892`): best-first growth to `num_leaves`, where
each step histograms the SMALLER child and derives the larger by parent-minus-
smaller subtraction (`BeforeFindBestSplit` smaller/larger assignment
`:364-441`, `FindBestSplits` `:443-595`), applies the split to the row
partition, and propagates monotone mid-constraints (`Split` `:757-851`).

TPU mapping:
- binned matrix + partition indices + grad/hess live in HBM
- histogram = MXU one-hot contraction over the leaf's gathered rows
  (`ops/histogram.py`), jit-cached per power-of-two padded leaf size
- split finding = one vectorized program over all features (`ops/split.py`)
- the only host/device sync per split is the chosen SplitInfo scalars —
  the analogue of the reference's per-leaf best-split argmax on host
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.binning import BIN_CATEGORICAL
from ..io.dataset import Dataset
from ..ops.histogram import leaf_histogram, subtract_histogram
from ..ops.partition import init_partition, init_partition_from, \
    split_partition
from ..ops.split import SplitHyper, make_split_finder
from .tree import Tree

_MISSING_CODE_TO_C = {"none": 0, "zero": 1, "nan": 2}


def _pow2_pad(n: int, min_pad: int) -> int:
    return max(min_pad, 1 << max(int(math.ceil(math.log2(max(n, 1)))), 0))


class _LeafInfo:
    __slots__ = ("begin", "count", "sum_g", "sum_h", "hist", "best",
                 "depth", "min_constraint", "max_constraint")

    def __init__(self, begin, count, sum_g, sum_h, depth=0,
                 min_constraint=-np.inf, max_constraint=np.inf):
        self.begin = begin
        self.count = count
        self.sum_g = sum_g
        self.sum_h = sum_h
        self.hist = None
        self.best = None
        self.depth = depth
        self.min_constraint = min_constraint
        self.max_constraint = max_constraint


class SerialTreeLearner:
    """Reference `TreeLearner` contract (`include/LightGBM/tree_learner.h`)."""

    def __init__(self, cfg: Config, dataset: Dataset) -> None:
        self.cfg = cfg
        self.ds = dataset
        self.n = dataset.num_data
        self.num_features = dataset.num_features
        meta = dataset.feature_meta_arrays()
        self.meta = meta
        self.max_bin_global = int(meta["num_bin"].max()) \
            if len(meta["num_bin"]) else 2
        self.bins_dev = jnp.asarray(dataset.bins)
        self.hyper = SplitHyper.from_config(cfg)
        self.finder = make_split_finder(self.hyper, meta, self.max_bin_global)
        self.mappers = dataset.used_mappers()
        self._feat_rng = np.random.RandomState(cfg.feature_fraction_seed)
        # partition storage: leaf slices stay contiguous; extra tail so a
        # padded dynamic_slice never wraps (see ops/partition.py)
        self.n_pad = self.n + _pow2_pad(self.n, cfg.tpu_min_pad)
        self.indices = init_partition(self.n, self.n_pad)
        self.hist_precision = ("f64" if cfg.tpu_use_f64_hist
                               else "f32" if cfg.gpu_use_dp
                               else "bf16x2")
        self._monotone_any = bool(np.any(meta["monotone"] != 0))
        # CEGB state (serial_tree_learner.cpp:110-115,537-568): coupled
        # penalties charge a feature's cost once per MODEL, lazy penalties
        # once per (feature, row)
        self._cegb_on = (cfg.cegb_penalty_split > 0
                         or len(cfg.cegb_penalty_feature_coupled) > 0
                         or len(cfg.cegb_penalty_feature_lazy) > 0)
        self._cegb_feature_used = np.zeros(dataset.num_total_features, bool)
        self._cegb_lazy_marked: Dict[int, np.ndarray] = {}
        self._forced = None
        if cfg.forcedsplits_filename:
            import json as _json
            with open(cfg.forcedsplits_filename) as fh:
                self._forced = _json.load(fh)

    # ------------------------------------------------------------------
    def _feature_mask(self) -> Optional[np.ndarray]:
        """Per-tree column sampling (reference BeforeTrain feature sampling,
        serial_tree_learner.cpp:275-296)."""
        frac = self.cfg.feature_fraction
        if frac >= 1.0:
            return None
        used_cnt = max(1, int(round(self.num_features * frac)))
        mask = np.zeros(self.num_features, bool)
        sel = self._feat_rng.choice(self.num_features, used_cnt,
                                    replace=False)
        mask[sel] = True
        return mask

    def _leaf_hist(self, leaf: _LeafInfo, grad, hess):
        padded = _pow2_pad(leaf.count, self.cfg.tpu_min_pad)
        hist = leaf_histogram(
            self.bins_dev, self.indices, jnp.int32(leaf.begin),
            jnp.int32(leaf.count), grad, hess, padded=padded,
            max_bin=self.max_bin_global, chunk=self.cfg.tpu_hist_chunk,
            precision=self.hist_precision)
        if hist.dtype == jnp.float64:
            # round once, matching the fused path's post-collective seam
            hist = hist.astype(jnp.float32)
        return hist

    def _find_best(self, leaf: _LeafInfo, feature_mask) -> dict:
        out = self.finder(leaf.hist, jnp.float32(leaf.sum_g),
                          jnp.float32(leaf.sum_h), jnp.int32(leaf.count),
                          jnp.float32(leaf.min_constraint),
                          jnp.float32(leaf.max_constraint))
        gain = np.asarray(out["gain"], np.float64)
        if feature_mask is not None:
            gain = np.where(feature_mask, gain, -np.inf)
        # depth limit (BeforeFindBestSplit, serial_tree_learner.cpp:364-377)
        if 0 < self.cfg.max_depth <= leaf.depth:
            gain = np.full_like(gain, -np.inf)
        if self._cegb_on:
            gain = gain - self._cegb_penalties(leaf)
        best_f = int(np.argmax(gain))
        res = {
            "feature": best_f,
            "gain": float(gain[best_f]),
            "threshold": int(np.asarray(out["threshold"])[best_f]),
            "default_left": bool(np.asarray(out["default_left"])[best_f]),
            "left_g": float(np.asarray(out["left_g"])[best_f]),
            "left_h": float(np.asarray(out["left_h"])[best_f]),
            "left_c": int(np.asarray(out["left_c"])[best_f]),
            "right_g": float(np.asarray(out["right_g"])[best_f]),
            "right_h": float(np.asarray(out["right_h"])[best_f]),
            "right_c": int(np.asarray(out["right_c"])[best_f]),
            "left_output": float(np.asarray(out["left_output"])[best_f]),
            "right_output": float(np.asarray(out["right_output"])[best_f]),
        }
        if "use_onehot" in out and \
                self.meta["bin_type"][best_f] == 1:
            res["is_cat"] = True
            if bool(np.asarray(out["use_onehot"])[best_f]):
                res["cat_bins"] = [res["threshold"]]
            else:
                order = np.asarray(out["sort_order"])[best_f]
                n_elig = int(np.asarray(out["n_elig"])[best_f])
                cdir = int(np.asarray(out["cat_dir"])[best_f])
                k = res["threshold"] + 1
                if cdir == 1:
                    res["cat_bins"] = [int(order[i]) for i in range(k)]
                else:
                    res["cat_bins"] = [int(order[n_elig - 1 - i])
                                       for i in range(k)]
        else:
            res["is_cat"] = False
        return res

    # ------------------------------------------------------------------
    def _cegb_penalties(self, leaf: "_LeafInfo") -> np.ndarray:
        """Per-feature CEGB gain penalties for one leaf (reference
        serial_tree_learner.cpp:537-568 + CalculateOndemandCosts :488):
        split penalty scales with leaf rows; coupled penalties charge
        unused features once per model; lazy penalties charge the leaf
        rows that never passed a split on the feature before."""
        cfg = self.cfg
        F = self.num_features
        pen = np.full(F, cfg.cegb_tradeoff * cfg.cegb_penalty_split
                      * leaf.count, np.float64)
        real = self.ds.real_feature_idx
        coupled = cfg.cegb_penalty_feature_coupled
        if len(coupled):
            c = np.asarray(coupled, np.float64)[real]
            pen += cfg.cegb_tradeoff * np.where(
                self._cegb_feature_used[real], 0.0, c)
        lazy = cfg.cegb_penalty_feature_lazy
        if len(lazy):
            lz = np.asarray(lazy, np.float64)[real]
            rows = np.asarray(self.indices[leaf.begin:
                                           leaf.begin + leaf.count])
            for f in range(F):
                if lz[f] == 0.0:
                    continue
                marked = self._cegb_lazy_marked.get(f)
                fresh = leaf.count if marked is None else int(
                    (~marked[rows]).sum())
                pen[f] += cfg.cegb_tradeoff * lz[f] * fresh
        return pen

    def _cegb_commit(self, f: int, begin: int, count: int) -> None:
        if not self._cegb_on:
            return
        self._cegb_feature_used[int(self.ds.real_feature_idx[f])] = True
        if len(self.cfg.cegb_penalty_feature_lazy):
            marked = self._cegb_lazy_marked.get(f)
            if marked is None:
                marked = np.zeros(self.n, bool)
                self._cegb_lazy_marked[f] = marked
            rows = np.asarray(self.indices[begin:begin + count])
            marked[rows] = True

    # ------------------------------------------------------------------
    def _forced_split_info(self, leaf: "_LeafInfo", f: int,
                           thr_bin: int) -> dict:
        """Split info AT a forced threshold from the leaf histogram
        (reference GatherInfoForThreshold, feature_histogram.hpp:290+)."""
        from ..ops.split import threshold_l1_host
        cfg = self.cfg
        hist = np.asarray(leaf.hist[f], np.float64)        # [B, 3]
        mapper = self.mappers[f]
        nb = mapper.num_bin
        mt = mapper.missing_type
        hi = min(thr_bin + 1, nb)
        lg = hist[:hi, 0].sum()
        lh = hist[:hi, 1].sum()
        lc = int(round(hist[:hi, 2].sum()))
        if mt == "nan" and hi > nb - 1:
            # NaN bin routes right under default_left=False
            lg -= hist[nb - 1, 0]
            lh -= hist[nb - 1, 1]
            lc -= int(round(hist[nb - 1, 2]))
        rg, rh = leaf.sum_g - lg, leaf.sum_h - lh
        rc = leaf.count - lc
        l1, l2 = cfg.lambda_l1, cfg.lambda_l2

        def out(sg, sh):
            return float(-threshold_l1_host(np.float64(sg), l1)
                         / (sh + l2)) if sh + l2 > 0 else 0.0

        def part_gain(sg, sh):
            t = threshold_l1_host(np.float64(sg), l1)
            return float(t * t / (sh + l2)) if sh + l2 > 0 else 0.0

        gain = part_gain(lg, lh) + part_gain(rg, rh) \
            - part_gain(leaf.sum_g, leaf.sum_h)
        return {"feature": f, "gain": gain, "threshold": int(thr_bin),
                "default_left": False, "left_g": lg, "left_h": lh,
                "left_c": lc, "right_g": rg, "right_h": rh, "right_c": rc,
                "left_output": out(lg, lh), "right_output": out(rg, rh),
                "is_cat": False}

    def _apply_forced_splits(self, tree: Tree, leaves: Dict, grad, hess,
                             feature_mask) -> None:
        """BFS the forced-splits JSON before gain-driven growth
        (reference ForceSplits, serial_tree_learner.cpp:597-755)."""
        if self._forced is None:
            return
        from collections import deque
        cfg = self.cfg
        q = deque([(0, self._forced)])
        while q and tree.num_leaves < cfg.num_leaves:
            lid, node = q.popleft()
            if not isinstance(node, dict) or "feature" not in node:
                continue
            real_f = int(node["feature"])
            f = int(self.ds.used_feature_map[real_f])
            if f < 0:
                continue
            mapper = self.mappers[f]
            thr_bin = int(mapper.values_to_bins(
                np.asarray([float(node["threshold"])]))[0])
            info = leaves[lid]
            b = self._forced_split_info(info, f, thr_bin)
            if min(b["left_c"], b["right_c"]) < 1:
                continue
            right_leaf = self._commit_split(tree, leaves, lid, info, b,
                                            feature_mask, grad, hess)
            if "left" in node:
                q.append((lid, node["left"]))
            if "right" in node:
                q.append((right_leaf, node["right"]))

    def _commit_split(self, tree: Tree, leaves: Dict, best_leaf: int,
                      info: "_LeafInfo", b: dict, feature_mask, grad,
                      hess) -> int:
        """Apply one chosen split: tree node, partition, CEGB marking,
        children (smaller-histogram + parent-minus-subtract). Shared by
        gain-driven growth and forced splits. Returns the right leaf id."""
        cfg = self.cfg
        f = b["feature"]
        mapper = self.mappers[f]
        mt_c = _MISSING_CODE_TO_C[mapper.missing_type]

        real_feature = int(self.ds.real_feature_idx[f])
        if b["is_cat"]:
            cat_bins = b["cat_bins"]
            cats = [mapper.bin_2_categorical[bb] for bb in cat_bins
                    if bb < len(mapper.bin_2_categorical)]
            right_leaf = tree.split_categorical(
                best_leaf, f, real_feature, cat_bins, cats,
                b["left_output"], b["right_output"], b["left_c"],
                b["right_c"], b["gain"], mt_c,
                default_bin=mapper.default_bin, num_bin=mapper.num_bin)
            cat_bitset = np.zeros(8, np.uint32)
            for bb in cat_bins:
                cat_bitset[bb // 32] |= np.uint32(1) << np.uint32(bb % 32)
        else:
            thr_double = mapper.bin_to_value(b["threshold"])
            right_leaf = tree.split(
                best_leaf, f, real_feature, b["threshold"], thr_double,
                b["left_output"], b["right_output"], b["left_c"],
                b["right_c"], b["gain"], mt_c, b["default_left"],
                default_bin=mapper.default_bin, num_bin=mapper.num_bin)
            cat_bitset = np.zeros(8, np.uint32)

        padded = _pow2_pad(info.count, cfg.tpu_min_pad)
        self.indices, lcnt_dev = split_partition(
            self.indices, self.bins_dev[:, f], jnp.int32(info.begin),
            jnp.int32(info.count), padded, jnp.int32(b["threshold"]),
            jnp.asarray(b["default_left"]), jnp.int32(mt_c),
            jnp.int32(mapper.default_bin), jnp.int32(mapper.num_bin),
            jnp.asarray(b["is_cat"]), jnp.asarray(cat_bitset))
        left_count = int(np.asarray(lcnt_dev))
        right_count = info.count - left_count
        self._cegb_commit(f, info.begin, info.count)

        lmin, lmax = info.min_constraint, info.max_constraint
        rmin, rmax = info.min_constraint, info.max_constraint
        mono = int(self.meta["monotone"][f]) if self._monotone_any else 0
        if mono != 0:
            mid = (b["left_output"] + b["right_output"]) / 2.0
            if mono > 0:
                lmax = min(lmax, mid)
                rmin = max(rmin, mid)
            else:
                lmin = max(lmin, mid)
                rmax = min(rmax, mid)
        left = _LeafInfo(info.begin, left_count, b["left_g"],
                         b["left_h"], info.depth + 1, lmin, lmax)
        right = _LeafInfo(info.begin + left_count, right_count,
                          b["right_g"], b["right_h"], info.depth + 1,
                          rmin, rmax)

        if left_count <= right_count:
            smaller, larger = left, right
        else:
            smaller, larger = right, left
        if tree.num_leaves < cfg.num_leaves:
            smaller.hist = self._leaf_hist(smaller, grad, hess)
            larger.hist = subtract_histogram(info.hist, smaller.hist)
            smaller.best = self._find_best(smaller, feature_mask)
            larger.best = self._find_best(larger, feature_mask)
        leaves[best_leaf] = left
        leaves[right_leaf] = right
        info.hist = None
        return right_leaf

    def train(self, grad: jax.Array, hess: jax.Array,
              bag_indices: Optional[np.ndarray] = None,
              bag_count: Optional[int] = None) -> Tuple[Tree, Dict]:
        """Grow one tree (reference SerialTreeLearner::Train,
        serial_tree_learner.cpp:173-237). grad/hess are full-length [N]
        device arrays; bag_indices restricts rows (bagging/GOSS)."""
        cfg = self.cfg
        feature_mask = self._feature_mask()
        if bag_indices is not None:
            count = int(bag_count if bag_count is not None
                        else len(bag_indices))
            self.indices = init_partition_from(bag_indices, self.n_pad)
        else:
            count = self.n
            self.indices = init_partition(self.n, self.n_pad)

        # root sums (BeforeTrain root sumup, serial_tree_learner.cpp:307-316)
        padded_root = _pow2_pad(count, cfg.tpu_min_pad)
        root = _LeafInfo(0, count, 0.0, 0.0)
        root.hist = self._leaf_hist(root, grad, hess)
        # root grad/hess totals from the histogram of feature 0 would drop
        # rows beyond num_bin masking; use a direct masked reduction instead
        sums = _root_sums(self.indices, grad, hess, jnp.int32(count),
                          padded_root)
        root.sum_g = float(np.asarray(sums[0]))
        root.sum_h = float(np.asarray(sums[1]))
        root.best = self._find_best(root, feature_mask)

        tree = Tree(cfg.num_leaves)
        leaves: Dict[int, _LeafInfo] = {0: root}
        leaf_begin_count: Dict[int, Tuple[int, int]] = {}
        self._apply_forced_splits(tree, leaves, grad, hess, feature_mask)

        while tree.num_leaves < cfg.num_leaves:
            # pick max-gain leaf (Train loop, serial_tree_learner.cpp:201-224)
            best_leaf, best_gain = -1, 0.0
            for lid, info in leaves.items():
                if info.best is not None and info.best["gain"] > best_gain \
                        and np.isfinite(info.best["gain"]):
                    best_leaf, best_gain = lid, info.best["gain"]
            if best_leaf < 0:
                break
            info = leaves[best_leaf]
            self._commit_split(tree, leaves, best_leaf, info, info.best,
                               feature_mask, grad, hess)

        leaf_begin_count = {lid: (inf.begin, inf.count)
                            for lid, inf in leaves.items()}
        return tree, leaf_begin_count

    # ------------------------------------------------------------------
    def renew_tree_output(self, tree: Tree, leaf_begin_count: Dict,
                          objective, scores_np: np.ndarray,
                          label_np: np.ndarray,
                          weights_np: Optional[np.ndarray]) -> None:
        """Percentile leaf renewal for L1-family objectives (reference
        SerialTreeLearner::RenewTreeOutput, serial_tree_learner.cpp:854-892).
        """
        if not getattr(objective, "is_renew_tree_output", False):
            return
        idx_np = np.asarray(self.indices)
        for lid, (begin, count) in leaf_begin_count.items():
            rows = idx_np[begin:begin + count]
            resid = objective.residual(label_np[rows], scores_np[rows])
            if objective.name == "mape":
                w = objective._label_weight_np[rows]
            else:
                w = weights_np[rows] if weights_np is not None else None
            # reference order: renew BEFORE shrinkage (gbdt.cpp:400-408)
            tree.leaf_value[lid] = objective.renew_leaf_output(resid, w)


import functools


@functools.partial(jax.jit, static_argnames=("padded",))
def _root_sums(indices, grad, hess, count, padded: int):
    idx = jax.lax.dynamic_slice(indices, (jnp.int32(0),), (padded,))
    pos = jnp.arange(padded, dtype=jnp.int32)
    valid = pos < count
    safe = jnp.where(valid, idx, 0)
    g = jnp.where(valid, grad[safe], 0.0)
    h = jnp.where(valid, hess[safe], 0.0)
    return jnp.stack([g.sum(), h.sum()])
