"""DatasetLoader: text / binary file -> binned `Dataset`.

Re-creates `src/io/dataset_loader.cpp`: `LoadFromFile` (`:162`) with header
handling + label/weight/group column extraction (`SetHeader` `:25-140`),
sidecar metadata files ``<data>.weight`` / ``<data>.query`` / ``<data>.init``
(`src/io/metadata.cpp:376,400`), validation-set alignment against a
reference dataset (`LoadFromFileAlignWithOtherDataset` `:224`), and the
binary-file fast path (`LoadFromBinFile` `:268` -> `Dataset.save_binary`).
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from .dataset import Dataset
from .file_io import exists as vf_exists
from .file_io import open_file
from .parser import LibSVMParser, create_parser, parse_dense

# rows per streamed chunk for two_round loading (the reference's
# TextReader block size analogue, dataset_loader.cpp:162-266); test hook +
# env override LGBM_TPU_INGEST_CHUNK
DEFAULT_CHUNK_LINES = 1 << 16


def _parse_column_spec(spec: str, names: Optional[List[str]]) -> List[int]:
    """``"0,1,2"`` or ``"name:a,b"`` -> column indices (feature space)."""
    spec = str(spec).strip()
    if not spec:
        return []
    if spec.startswith("name:"):
        if not names:
            raise ValueError(
                f"column spec '{spec}' needs a file header with column names")
        want = [s.strip() for s in spec[5:].split(",") if s.strip()]
        out = []
        for w in want:
            if w not in names:
                raise ValueError(f"column name '{w}' not found in header")
            out.append(names.index(w))
        return out
    return [int(s) for s in spec.split(",") if s.strip()]


def _split_header_line(header_line: str) -> List[str]:
    """Column names from a header line (tab/comma/space sniff — one
    shared implementation for the one-shot and two_round paths)."""
    for sep in ("\t", ",", " "):
        if sep in header_line:
            return [s.strip() for s in header_line.split(sep)]
    return [header_line.strip()]


def _read_sidecar(path: str) -> Optional[np.ndarray]:
    if not vf_exists(path):
        return None
    with open_file(path) as f:
        vals = [float(x) for x in f.read().split()]
    return np.asarray(vals, dtype=np.float64)


class DatasetLoader:
    """Host-side loader (reference `DatasetLoader`, `dataset_loader.h:24-86`)."""

    def __init__(self, config: Optional[Config] = None,
                 predict_fun=None) -> None:
        self.config = config or Config()
        # prior-model predictor hook for continued training: raw scores of
        # the loaded rows become init scores (reference
        # `dataset_loader.h:66-67`, `application.cpp:90-93`)
        self.predict_fun = predict_fun

    # ------------------------------------------------------------------
    def _read_text(self, filename: str) -> Tuple[Optional[List[str]],
                                                 List[str]]:
        if not vf_exists(filename):
            raise FileNotFoundError(f"data file {filename} not found")
        with open_file(filename, errors="replace") as f:
            lines = f.read().splitlines()
        lines = [ln for ln in lines if ln.strip()]
        header = None
        if self.config.header and lines:
            header = lines[0]
            lines = lines[1:]
        return header, lines

    def _resolve_label_idx(self, names: Optional[List[str]]) -> int:
        spec = str(self.config.label_column).strip()
        if not spec:
            return 0
        if spec.startswith("name:"):
            if not names:
                raise ValueError("label_column=name:... requires header=true")
            w = spec[5:].strip()
            if w not in names:
                raise ValueError(f"label column '{w}' not found in header")
            return names.index(w)
        return int(spec)

    # ------------------------------------------------------------------
    def parse_file(self, filename: str
                   ) -> Tuple[np.ndarray, np.ndarray, dict]:
        """Parse a text data file.

        Returns ``(labels [N], features [N, F], extras)`` where extras holds
        feature_names, weights, groups, ignore column indices (feature
        space, label removed — reference `SetHeader` semantics
        `dataset_loader.cpp:62-140`).
        """
        cfg = self.config
        all_names = None
        labels = feats = None
        if not cfg.header and "://" not in str(filename):
            # LOCAL headerless files take the native C++ OpenMP parser
            # when available (reference keeps this whole path in C++:
            # TextReader + Parser + ExtractFeaturesFromMemory); header /
            # name-resolution / virtual-filesystem files go through the
            # Python path below
            from ..native import parse_file as native_parse
            label_idx = self._resolve_label_idx(None)
            if not os.path.isfile(filename):
                raise FileNotFoundError(f"data file {filename} not found")
            native = native_parse(filename, label_idx)
            if native is not None:
                labels, feats, _fmt = native
        if labels is None:
            header_line, lines = self._read_text(filename)
            if header_line is not None:
                all_names = _split_header_line(header_line)
            label_idx = self._resolve_label_idx(all_names)
            parser = create_parser(lines[:32], label_idx)
            labels, feats = parse_dense(lines, parser)

        feat_names = None
        if all_names is not None:
            feat_names = list(all_names)
            if 0 <= label_idx < len(feat_names):
                feat_names.pop(label_idx)

        # weight / group columns (indices don't count the label column)
        weights = None
        groups_raw = None
        ignore: set = set()
        if str(cfg.weight_column).strip():
            (widx,) = _parse_column_spec(cfg.weight_column, feat_names)
            weights = feats[:, widx].copy()
            ignore.add(widx)
        if str(cfg.group_column).strip():
            (gidx,) = _parse_column_spec(cfg.group_column, feat_names)
            groups_raw = feats[:, gidx].copy()
            ignore.add(gidx)
        for c in _parse_column_spec(cfg.ignore_column, feat_names):
            ignore.add(c)

        # sidecar files override in-file columns (reference metadata.cpp)
        side_w = _read_sidecar(filename + ".weight")
        if side_w is not None:
            weights = side_w
        side_q = _read_sidecar(filename + ".query")
        group_sizes = None
        if side_q is not None:
            group_sizes = side_q.astype(np.int64)
        elif groups_raw is not None:
            # in-file query ids -> boundary sizes (reference
            # `Metadata::SetQueryId`): consecutive equal ids form one query
            ids = groups_raw
            change = np.flatnonzero(np.diff(ids) != 0)
            bounds = np.concatenate([[0], change + 1, [len(ids)]])
            group_sizes = np.diff(bounds).astype(np.int64)
        init_score = _read_sidecar(filename + ".init")
        if cfg.initscore_filename and vf_exists(cfg.initscore_filename):
            init_score = _read_sidecar(cfg.initscore_filename)

        extras = dict(feature_names=feat_names, weights=weights,
                      group_sizes=group_sizes, init_score=init_score,
                      ignore=sorted(ignore), label_idx=label_idx)
        return labels, feats, extras

    # ------------------------------------------------------------------
    def _categorical_from_config(self, feat_names) -> Optional[List[int]]:
        spec = str(self.config.categorical_feature).strip()
        if not spec:
            return None
        return _parse_column_spec(spec, feat_names)

    def load_from_file(self, filename: str, rank: int = 0,
                       num_machines: int = 1) -> Dataset:
        """reference `DatasetLoader::LoadFromFile` (`dataset_loader.cpp:162`).

        With ``num_machines > 1`` and no pre-partition, rows are striped
        round-robin across ranks (reference random / in-order partition,
        `dataset_loader.cpp:606-650`)."""
        cfg = self.config
        if cfg.save_binary or filename.endswith(".bin"):
            binpath = filename if filename.endswith(".bin") \
                else filename + ".bin"
            if not cfg.save_binary and vf_exists(binpath):
                return Dataset.load_binary(binpath)
        if getattr(cfg, "two_round", False):
            return self._load_two_round(filename, rank=rank,
                                        num_machines=num_machines)
        labels, feats, ex = self.parse_file(filename)
        if num_machines > 1 and not cfg.pre_partition:
            sel = np.arange(len(labels)) % num_machines == rank
            labels, feats = labels[sel], feats[sel]
            for k in ("weights", "init_score"):
                if ex[k] is not None:
                    ex[k] = ex[k][sel]
        for c in ex["ignore"]:
            feats[:, c] = 0.0  # constant column -> trivial feature, never split
        ds = Dataset.from_matrix(
            feats, label=labels, config=cfg, weight=ex["weights"],
            group=ex["group_sizes"],
            init_score=ex["init_score"],
            feature_names=ex["feature_names"],
            categorical_feature=self._categorical_from_config(
                ex["feature_names"]))
        if self.predict_fun is not None and ds.metadata.init_score is None:
            raw = np.asarray(self.predict_fun(feats), dtype=np.float64)
            ds.metadata.set_init_score(raw.reshape(-1, order="F"))
        if cfg.save_binary:
            ds.save_binary(filename + ".bin")
        return ds

    # ------------------------------------------------------------------
    def _iter_line_chunks(self, filename: str, chunk_lines: int):
        """Yield lists of <= chunk_lines non-empty lines (header skipped);
        peak host memory per chunk is O(chunk_lines)."""
        with open_file(filename, errors="replace") as f:
            if self.config.header:
                f.readline()
            buf: List[str] = []
            for ln in f:
                if not ln.strip():
                    continue
                buf.append(ln)
                if len(buf) >= chunk_lines:
                    self._max_chunk_rows = max(
                        getattr(self, "_max_chunk_rows", 0), len(buf))
                    yield buf
                    buf = []
            if buf:
                self._max_chunk_rows = max(
                    getattr(self, "_max_chunk_rows", 0), len(buf))
                yield buf

    def _header_names(self, filename: str) -> Optional[List[str]]:
        if not self.config.header:
            return None
        with open_file(filename, errors="replace") as f:
            header_line = f.readline().rstrip("\r\n")
        return _split_header_line(header_line)

    def _load_two_round(self, filename: str, rank: int = 0,
                        num_machines: int = 1,
                        reference: Optional[Dataset] = None,
                        chunk_lines: Optional[int] = None) -> Dataset:
        """Two-pass streaming load (reference two_round,
        `dataset_loader.cpp:162-266` + `TextReader::SampleAndFilter`):

        pass 1 streams the file in O(chunk) host memory, reservoir-
        sampling up to ``bin_construct_sample_cnt`` rows for bin finding
        and collecting the per-row metadata columns; pass 2 streams
        again, binning each chunk straight into the preallocated uint8
        matrix via the push-rows flow (`Dataset.create_from_sample` /
        `push_rows` / `finish_load`). The full float matrix never exists
        in host memory.
        """
        cfg = self.config
        if chunk_lines is None:
            chunk_lines = int(os.environ.get("LGBM_TPU_INGEST_CHUNK",
                                             DEFAULT_CHUNK_LINES))
        if not vf_exists(filename):
            raise FileNotFoundError(f"data file {filename} not found")
        all_names = self._header_names(filename)
        label_idx = self._resolve_label_idx(all_names)
        feat_names = None
        if all_names is not None:
            feat_names = list(all_names)
            if 0 <= label_idx < len(feat_names):
                feat_names.pop(label_idx)
        widx = gidx = None
        ignore: set = set()
        if str(cfg.weight_column).strip():
            (widx,) = _parse_column_spec(cfg.weight_column, feat_names)
            ignore.add(widx)
        if str(cfg.group_column).strip():
            (gidx,) = _parse_column_spec(cfg.group_column, feat_names)
            ignore.add(gidx)
        for c in _parse_column_spec(cfg.ignore_column, feat_names):
            ignore.add(c)

        rng = np.random.RandomState(cfg.data_random_seed)
        sample_cap = max(int(cfg.bin_construct_sample_cnt), 1)
        parser = None
        sample_rows: List[np.ndarray] = []
        gid_parts: List[np.ndarray] = []
        n_kept = 0
        max_f = 0

        def _prep_chunk(labs, feats, start_global):
            """striping + metadata-column extraction + ignore zeroing —
            shared by both passes so sampled rows match pushed rows.
            Returns the kept rows' GLOBAL indices so sidecar arrays
            (indexed by global row) slice correctly under striping."""
            gi = start_global + np.arange(len(labs))
            if num_machines > 1 and not cfg.pre_partition:
                sel = gi % num_machines == rank
                labs, feats, gi = labs[sel], feats[sel], gi[sel]
            w = feats[:, widx].copy() if widx is not None \
                and widx < feats.shape[1] else None
            gids = feats[:, gidx].copy() if gidx is not None \
                and gidx < feats.shape[1] else None
            for c in ignore:
                if c < feats.shape[1]:
                    feats[:, c] = 0.0
            return labs, feats, w, gids, gi

        n_global = 0
        for lines in self._iter_line_chunks(filename, chunk_lines):
            if parser is None:
                parser = create_parser(lines[:32], label_idx)
            labs, feats = parse_dense(lines, parser)
            labs, feats, _w, gids, _gi = _prep_chunk(labs, feats, n_global)
            n_global += len(lines)
            max_f = max(max_f, feats.shape[1])
            if gids is not None:
                gid_parts.append(gids)
            # vectorized reservoir sample (uniform without replacement,
            # the reference Random::Sample analogue): fill to cap, then
            # each row t replaces slot j ~ U[0, t] iff j < cap. Skipped
            # entirely with a reference: mappers are shared, so the
            # aligned path keeps its O(chunk) promise
            k = feats.shape[0]
            if reference is None:
                take = min(max(sample_cap - len(sample_rows), 0), k)
                for i in range(take):
                    sample_rows.append(feats[i].copy())
                if take < k:
                    t = n_kept + np.arange(take, k)
                    j = (rng.random_sample(k - take)
                         * (t + 1)).astype(np.int64)
                    for i, slot in zip(np.nonzero(j < sample_cap)[0],
                                       j[j < sample_cap]):
                        sample_rows[slot] = feats[take + i].copy()
            n_kept += k

        if parser is None:
            raise ValueError(f"data file {filename} is empty")

        if reference is not None:
            # the training set may be wider than this file's rows reach
            # (ragged LibSVM): bin at ITS width
            max_f = max(max_f, reference.num_total_features)
            ds = Dataset.create_from_sample(None, n_kept, config=cfg,
                                            reference=reference)
        else:
            sample = np.zeros((len(sample_rows), max_f))
            for i, r in enumerate(sample_rows):
                sample[i, :len(r)] = r
            del sample_rows
            ds = Dataset.create_from_sample(
                sample, n_kept, config=cfg, feature_names=feat_names,
                categorical_feature=self._categorical_from_config(
                    feat_names))
            del sample

        # ---- pass 2: bin chunk-by-chunk straight into the uint8 matrix
        side_w = _read_sidecar(filename + ".weight")
        side_q = _read_sidecar(filename + ".query")
        init_score = _read_sidecar(filename + ".init")
        if cfg.initscore_filename and vf_exists(cfg.initscore_filename):
            init_score = _read_sidecar(cfg.initscore_filename)
        pos = 0
        n_global = 0
        num_cols = max_f if isinstance(parser, LibSVMParser) else None
        raw_parts: List[np.ndarray] = []   # predict_fun chunks (may be 2-D)
        kept_gi: List[np.ndarray] = []     # kept rows' global indices
        for lines in self._iter_line_chunks(filename, chunk_lines):
            labs, feats = parse_dense(lines, parser, num_cols=num_cols)
            labs, feats, w, _, gi = _prep_chunk(labs, feats, n_global)
            n_global += len(lines)
            if feats.shape[1] < max_f:
                feats = np.pad(feats, ((0, 0), (0, max_f - feats.shape[1])))
            k = feats.shape[0]
            if side_w is not None:
                # sidecars are indexed by GLOBAL row: honor striping
                w = side_w[gi]
            ds.push_rows(feats, label=labs, weight=w)
            if init_score is None and self.predict_fun is not None:
                raw_parts.append(np.asarray(self.predict_fun(feats),
                                            np.float64))
            kept_gi.append(gi)
            pos += k

        # pass 2 re-reads the file: a stream-backed virtual filesystem
        # whose content changed between passes would mis-bin silently —
        # push_rows catches growth but only finish_load's late error
        # catches shrinkage, so check the kept-row totals match here
        if pos != n_kept:
            raise ValueError(
                f"two_round pass 2 saw {pos} rows but pass 1 sampled "
                f"{n_kept}: the data file changed between passes (is the "
                f"path a non-rewindable stream?)")

        group_sizes = None
        if side_q is not None:
            group_sizes = side_q.astype(np.int64)
        elif gid_parts:
            ids = np.concatenate(gid_parts)
            change = np.flatnonzero(np.diff(ids) != 0)
            bounds = np.concatenate([[0], change + 1, [len(ids)]])
            group_sizes = np.diff(bounds).astype(np.int64)
        ds.finish_load(group=group_sizes)
        # init scores may be [N*K] column-major (multiclass): set whole
        # (striping-gathered) arrays AFTER the push loop, mirroring the
        # one-shot path's metadata.set_init_score semantics
        if init_score is not None:
            gsel = (np.concatenate(kept_gi) if kept_gi
                    else np.zeros(0, np.int64))
            if n_global and len(init_score) % n_global == 0:
                ncls = len(init_score) // n_global
                ds.metadata.set_init_score(np.concatenate(
                    [init_score[c * n_global + gsel]
                     for c in range(ncls)]))
            else:
                ds.metadata.set_init_score(init_score)
        elif raw_parts:
            raw = np.concatenate(raw_parts, axis=0)
            ds.metadata.set_init_score(raw.reshape(-1, order="F"))
        if cfg.save_binary:
            ds.save_binary(filename + ".bin")
        return ds

    def load_from_file_align_with_other_dataset(
            self, filename: str, reference: Dataset) -> Dataset:
        """Validation data binned with the training set's mappers
        (reference `dataset_loader.cpp:224`)."""
        if getattr(self.config, "two_round", False):
            return self._load_two_round(filename, reference=reference)
        labels, feats, ex = self.parse_file(filename)
        for c in ex["ignore"]:
            feats[:, c] = 0.0
        ds = Dataset.from_matrix(
            feats, label=labels, config=self.config, weight=ex["weights"],
            group=ex["group_sizes"], init_score=ex["init_score"],
            feature_names=ex["feature_names"], reference=reference)
        if self.predict_fun is not None and ds.metadata.init_score is None:
            raw = np.asarray(self.predict_fun(feats), dtype=np.float64)
            ds.metadata.set_init_score(raw.reshape(-1, order="F"))
        return ds
