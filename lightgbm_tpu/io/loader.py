"""DatasetLoader: text / binary file -> binned `Dataset`.

Re-creates `src/io/dataset_loader.cpp`: `LoadFromFile` (`:162`) with header
handling + label/weight/group column extraction (`SetHeader` `:25-140`),
sidecar metadata files ``<data>.weight`` / ``<data>.query`` / ``<data>.init``
(`src/io/metadata.cpp:376,400`), validation-set alignment against a
reference dataset (`LoadFromFileAlignWithOtherDataset` `:224`), and the
binary-file fast path (`LoadFromBinFile` `:268` -> `Dataset.save_binary`).
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from .dataset import Dataset
from .file_io import exists as vf_exists
from .file_io import open_file
from .parser import LibSVMParser, create_parser, parse_dense

# rows per streamed chunk for two_round loading (the reference's
# TextReader block size analogue, dataset_loader.cpp:162-266); test hook +
# env override LGBM_TPU_INGEST_CHUNK
DEFAULT_CHUNK_LINES = 1 << 16

# columnar front-door extensions routed through io/stream.py's pyarrow
# reader (gated: pyarrow is optional)
_COLUMNAR_EXTS = (".parquet", ".arrow", ".feather", ".ipc")


def _parse_column_spec(spec: str, names: Optional[List[str]]) -> List[int]:
    """``"0,1,2"`` or ``"name:a,b"`` -> column indices (feature space)."""
    spec = str(spec).strip()
    if not spec:
        return []
    if spec.startswith("name:"):
        if not names:
            raise ValueError(
                f"column spec '{spec}' needs a file header with column names")
        want = [s.strip() for s in spec[5:].split(",") if s.strip()]
        out = []
        for w in want:
            if w not in names:
                raise ValueError(f"column name '{w}' not found in header")
            out.append(names.index(w))
        return out
    return [int(s) for s in spec.split(",") if s.strip()]


def _split_header_line(header_line: str) -> List[str]:
    """Column names from a header line (tab/comma/space sniff — one
    shared implementation for the one-shot and two_round paths)."""
    for sep in ("\t", ",", " "):
        if sep in header_line:
            return [s.strip() for s in header_line.split(sep)]
    return [header_line.strip()]


def _read_sidecar(path: str) -> Optional[np.ndarray]:
    if not vf_exists(path):
        return None
    with open_file(path) as f:
        vals = [float(x) for x in f.read().split()]
    return np.asarray(vals, dtype=np.float64)


class DatasetLoader:
    """Host-side loader (reference `DatasetLoader`, `dataset_loader.h:24-86`)."""

    def __init__(self, config: Optional[Config] = None,
                 predict_fun=None) -> None:
        self.config = config or Config()
        # prior-model predictor hook for continued training: raw scores of
        # the loaded rows become init scores (reference
        # `dataset_loader.h:66-67`, `application.cpp:90-93`)
        self.predict_fun = predict_fun

    # ------------------------------------------------------------------
    def _read_text(self, filename: str) -> Tuple[Optional[List[str]],
                                                 List[str]]:
        if not vf_exists(filename):
            raise FileNotFoundError(f"data file {filename} not found")
        with open_file(filename, errors="replace") as f:
            lines = f.read().splitlines()
        lines = [ln for ln in lines if ln.strip()]
        header = None
        if self.config.header and lines:
            header = lines[0]
            lines = lines[1:]
        return header, lines

    def _resolve_label_idx(self, names: Optional[List[str]]) -> int:
        spec = str(self.config.label_column).strip()
        if not spec:
            return 0
        if spec.startswith("name:"):
            if not names:
                raise ValueError("label_column=name:... requires header=true")
            w = spec[5:].strip()
            if w not in names:
                raise ValueError(f"label column '{w}' not found in header")
            return names.index(w)
        return int(spec)

    # ------------------------------------------------------------------
    def parse_file(self, filename: str
                   ) -> Tuple[np.ndarray, np.ndarray, dict]:
        """Parse a text data file.

        Returns ``(labels [N], features [N, F], extras)`` where extras holds
        feature_names, weights, groups, ignore column indices (feature
        space, label removed — reference `SetHeader` semantics
        `dataset_loader.cpp:62-140`).
        """
        cfg = self.config
        all_names = None
        labels = feats = None
        if not cfg.header and "://" not in str(filename):
            # LOCAL headerless files take the native C++ OpenMP parser
            # when available (reference keeps this whole path in C++:
            # TextReader + Parser + ExtractFeaturesFromMemory); header /
            # name-resolution / virtual-filesystem files go through the
            # Python path below
            from ..native import parse_file as native_parse
            label_idx = self._resolve_label_idx(None)
            if not os.path.isfile(filename):
                raise FileNotFoundError(f"data file {filename} not found")
            native = native_parse(filename, label_idx)
            if native is not None:
                labels, feats, _fmt = native
        if labels is None:
            header_line, lines = self._read_text(filename)
            if header_line is not None:
                all_names = _split_header_line(header_line)
            label_idx = self._resolve_label_idx(all_names)
            parser = create_parser(lines[:32], label_idx)
            labels, feats = parse_dense(lines, parser)

        feat_names = None
        if all_names is not None:
            feat_names = list(all_names)
            if 0 <= label_idx < len(feat_names):
                feat_names.pop(label_idx)

        # weight / group columns (indices don't count the label column)
        weights = None
        groups_raw = None
        ignore: set = set()
        if str(cfg.weight_column).strip():
            (widx,) = _parse_column_spec(cfg.weight_column, feat_names)
            weights = feats[:, widx].copy()
            ignore.add(widx)
        if str(cfg.group_column).strip():
            (gidx,) = _parse_column_spec(cfg.group_column, feat_names)
            groups_raw = feats[:, gidx].copy()
            ignore.add(gidx)
        for c in _parse_column_spec(cfg.ignore_column, feat_names):
            ignore.add(c)

        # sidecar files override in-file columns (reference metadata.cpp)
        side_w = _read_sidecar(filename + ".weight")
        if side_w is not None:
            weights = side_w
        side_q = _read_sidecar(filename + ".query")
        group_sizes = None
        if side_q is not None:
            group_sizes = side_q.astype(np.int64)
        elif groups_raw is not None:
            # in-file query ids -> boundary sizes (reference
            # `Metadata::SetQueryId`): consecutive equal ids form one query
            ids = groups_raw
            change = np.flatnonzero(np.diff(ids) != 0)
            bounds = np.concatenate([[0], change + 1, [len(ids)]])
            group_sizes = np.diff(bounds).astype(np.int64)
        init_score = _read_sidecar(filename + ".init")
        if cfg.initscore_filename and vf_exists(cfg.initscore_filename):
            init_score = _read_sidecar(cfg.initscore_filename)

        extras = dict(feature_names=feat_names, weights=weights,
                      group_sizes=group_sizes, init_score=init_score,
                      ignore=sorted(ignore), label_idx=label_idx)
        return labels, feats, extras

    # ------------------------------------------------------------------
    def _categorical_from_config(self, feat_names) -> Optional[List[int]]:
        spec = str(self.config.categorical_feature).strip()
        if not spec:
            return None
        return _parse_column_spec(spec, feat_names)

    def load_from_file(self, filename: str, rank: int = 0,
                       num_machines: int = 1) -> Dataset:
        """reference `DatasetLoader::LoadFromFile` (`dataset_loader.cpp:162`).

        With ``num_machines > 1`` and no pre-partition, rows are striped
        round-robin across ranks (reference random / in-order partition,
        `dataset_loader.cpp:606-650`)."""
        cfg = self.config
        if cfg.save_binary or filename.endswith(".bin"):
            binpath = filename if filename.endswith(".bin") \
                else filename + ".bin"
            if not cfg.save_binary and vf_exists(binpath):
                return Dataset.load_binary(binpath)
        if str(filename).endswith(_COLUMNAR_EXTS):
            return self._load_columnar(filename, rank=rank,
                                       num_machines=num_machines)
        if int(getattr(cfg, "tpu_stream_chunk_rows", 0)) > 0:
            return self._load_streamed(filename, rank=rank,
                                       num_machines=num_machines)
        if getattr(cfg, "two_round", False):
            return self._load_two_round(filename, rank=rank,
                                        num_machines=num_machines)
        labels, feats, ex = self.parse_file(filename)
        if num_machines > 1 and not cfg.pre_partition:
            sel = np.arange(len(labels)) % num_machines == rank
            labels, feats = labels[sel], feats[sel]
            for k in ("weights", "init_score"):
                if ex[k] is not None:
                    ex[k] = ex[k][sel]
        for c in ex["ignore"]:
            feats[:, c] = 0.0  # constant column -> trivial feature, never split
        ds = Dataset.from_matrix(
            feats, label=labels, config=cfg, weight=ex["weights"],
            group=ex["group_sizes"],
            init_score=ex["init_score"],
            feature_names=ex["feature_names"],
            categorical_feature=self._categorical_from_config(
                ex["feature_names"]))
        if self.predict_fun is not None and ds.metadata.init_score is None:
            raw = np.asarray(self.predict_fun(feats), dtype=np.float64)
            ds.metadata.set_init_score(raw.reshape(-1, order="F"))
        if cfg.save_binary:
            ds.save_binary(filename + ".bin")
        return ds

    # ------------------------------------------------------------------
    def _iter_line_chunks(self, filename: str, chunk_lines: int):
        """Yield lists of <= chunk_lines non-empty lines (header skipped);
        peak host memory per chunk is O(chunk_lines)."""
        with open_file(filename, errors="replace") as f:
            if self.config.header:
                f.readline()
            buf: List[str] = []
            for ln in f:
                if not ln.strip():
                    continue
                buf.append(ln)
                if len(buf) >= chunk_lines:
                    self._max_chunk_rows = max(
                        getattr(self, "_max_chunk_rows", 0), len(buf))
                    yield buf
                    buf = []
            if buf:
                self._max_chunk_rows = max(
                    getattr(self, "_max_chunk_rows", 0), len(buf))
                yield buf

    def _header_names(self, filename: str) -> Optional[List[str]]:
        if not self.config.header:
            return None
        with open_file(filename, errors="replace") as f:
            header_line = f.readline().rstrip("\r\n")
        return _split_header_line(header_line)

    def _load_two_round(self, filename: str, rank: int = 0,
                        num_machines: int = 1,
                        reference: Optional[Dataset] = None,
                        chunk_lines: Optional[int] = None) -> Dataset:
        """Two-pass streaming load (reference two_round,
        `dataset_loader.cpp:162-266` + `TextReader::SampleAndFilter`):

        pass 1 streams the file in O(chunk) host memory, reservoir-
        sampling up to ``bin_construct_sample_cnt`` rows for bin finding
        and collecting the per-row metadata columns; pass 2 streams
        again, binning each chunk straight into the preallocated uint8
        matrix via the push-rows flow (`Dataset.create_from_sample` /
        `push_rows` / `finish_load`). The full float matrix never exists
        in host memory.
        """
        cfg = self.config
        if chunk_lines is None:
            chunk_lines = int(os.environ.get("LGBM_TPU_INGEST_CHUNK",
                                             DEFAULT_CHUNK_LINES))
        if not vf_exists(filename):
            raise FileNotFoundError(f"data file {filename} not found")
        all_names = self._header_names(filename)
        label_idx = self._resolve_label_idx(all_names)
        feat_names = None
        if all_names is not None:
            feat_names = list(all_names)
            if 0 <= label_idx < len(feat_names):
                feat_names.pop(label_idx)
        widx = gidx = None
        ignore: set = set()
        if str(cfg.weight_column).strip():
            (widx,) = _parse_column_spec(cfg.weight_column, feat_names)
            ignore.add(widx)
        if str(cfg.group_column).strip():
            (gidx,) = _parse_column_spec(cfg.group_column, feat_names)
            ignore.add(gidx)
        for c in _parse_column_spec(cfg.ignore_column, feat_names):
            ignore.add(c)

        rng = np.random.RandomState(cfg.data_random_seed)
        sample_cap = max(int(cfg.bin_construct_sample_cnt), 1)
        parser = None
        sample_rows: List[np.ndarray] = []
        gid_parts: List[np.ndarray] = []
        n_kept = 0
        max_f = 0

        def _prep_chunk(labs, feats, start_global):
            """striping + metadata-column extraction + ignore zeroing —
            shared by both passes so sampled rows match pushed rows.
            Returns the kept rows' GLOBAL indices so sidecar arrays
            (indexed by global row) slice correctly under striping."""
            gi = start_global + np.arange(len(labs))
            if num_machines > 1 and not cfg.pre_partition:
                sel = gi % num_machines == rank
                labs, feats, gi = labs[sel], feats[sel], gi[sel]
            w = feats[:, widx].copy() if widx is not None \
                and widx < feats.shape[1] else None
            gids = feats[:, gidx].copy() if gidx is not None \
                and gidx < feats.shape[1] else None
            for c in ignore:
                if c < feats.shape[1]:
                    feats[:, c] = 0.0
            return labs, feats, w, gids, gi

        n_global = 0
        for lines in self._iter_line_chunks(filename, chunk_lines):
            if parser is None:
                parser = create_parser(lines[:32], label_idx)
            labs, feats = parse_dense(lines, parser)
            labs, feats, _w, gids, _gi = _prep_chunk(labs, feats, n_global)
            n_global += len(lines)
            max_f = max(max_f, feats.shape[1])
            if gids is not None:
                gid_parts.append(gids)
            # vectorized reservoir sample (uniform without replacement,
            # the reference Random::Sample analogue): fill to cap, then
            # each row t replaces slot j ~ U[0, t] iff j < cap. Skipped
            # entirely with a reference: mappers are shared, so the
            # aligned path keeps its O(chunk) promise
            k = feats.shape[0]
            if reference is None:
                take = min(max(sample_cap - len(sample_rows), 0), k)
                for i in range(take):
                    sample_rows.append(feats[i].copy())
                if take < k:
                    t = n_kept + np.arange(take, k)
                    j = (rng.random_sample(k - take)
                         * (t + 1)).astype(np.int64)
                    for i, slot in zip(np.nonzero(j < sample_cap)[0],
                                       j[j < sample_cap]):
                        sample_rows[slot] = feats[take + i].copy()
            n_kept += k

        if parser is None:
            raise ValueError(f"data file {filename} is empty")

        if reference is not None:
            # the training set may be wider than this file's rows reach
            # (ragged LibSVM): bin at ITS width
            max_f = max(max_f, reference.num_total_features)
            ds = Dataset.create_from_sample(None, n_kept, config=cfg,
                                            reference=reference)
        else:
            sample = np.zeros((len(sample_rows), max_f))
            for i, r in enumerate(sample_rows):
                sample[i, :len(r)] = r
            del sample_rows
            ds = Dataset.create_from_sample(
                sample, n_kept, config=cfg, feature_names=feat_names,
                categorical_feature=self._categorical_from_config(
                    feat_names))
            del sample

        # ---- pass 2: bin chunk-by-chunk straight into the uint8 matrix
        side_w = _read_sidecar(filename + ".weight")
        side_q = _read_sidecar(filename + ".query")
        init_score = _read_sidecar(filename + ".init")
        if cfg.initscore_filename and vf_exists(cfg.initscore_filename):
            init_score = _read_sidecar(cfg.initscore_filename)
        pos = 0
        n_global = 0
        num_cols = max_f if isinstance(parser, LibSVMParser) else None
        raw_parts: List[np.ndarray] = []   # predict_fun chunks (may be 2-D)
        kept_gi: List[np.ndarray] = []     # kept rows' global indices
        for lines in self._iter_line_chunks(filename, chunk_lines):
            labs, feats = parse_dense(lines, parser, num_cols=num_cols)
            labs, feats, w, _, gi = _prep_chunk(labs, feats, n_global)
            n_global += len(lines)
            if feats.shape[1] < max_f:
                feats = np.pad(feats, ((0, 0), (0, max_f - feats.shape[1])))
            k = feats.shape[0]
            if side_w is not None:
                # sidecars are indexed by GLOBAL row: honor striping
                w = side_w[gi]
            ds.push_rows(feats, label=labs, weight=w)
            if init_score is None and self.predict_fun is not None:
                raw_parts.append(np.asarray(self.predict_fun(feats),
                                            np.float64))
            kept_gi.append(gi)
            pos += k

        # pass 2 re-reads the file: a stream-backed virtual filesystem
        # whose content changed between passes would mis-bin silently —
        # push_rows catches growth but only finish_load's late error
        # catches shrinkage, so check the kept-row totals match here
        if pos != n_kept:
            raise ValueError(
                f"two_round pass 2 saw {pos} rows but pass 1 sampled "
                f"{n_kept}: the data file changed between passes (is the "
                f"path a non-rewindable stream?)")

        group_sizes = None
        if side_q is not None:
            group_sizes = side_q.astype(np.int64)
        elif gid_parts:
            ids = np.concatenate(gid_parts)
            change = np.flatnonzero(np.diff(ids) != 0)
            bounds = np.concatenate([[0], change + 1, [len(ids)]])
            group_sizes = np.diff(bounds).astype(np.int64)
        ds.finish_load(group=group_sizes)
        # init scores may be [N*K] column-major (multiclass): set whole
        # (striping-gathered) arrays AFTER the push loop, mirroring the
        # one-shot path's metadata.set_init_score semantics
        if init_score is not None:
            gsel = (np.concatenate(kept_gi) if kept_gi
                    else np.zeros(0, np.int64))
            if n_global and len(init_score) % n_global == 0:
                ncls = len(init_score) // n_global
                ds.metadata.set_init_score(np.concatenate(
                    [init_score[c * n_global + gsel]
                     for c in range(ncls)]))
            else:
                ds.metadata.set_init_score(init_score)
        elif raw_parts:
            raw = np.concatenate(raw_parts, axis=0)
            ds.metadata.set_init_score(raw.reshape(-1, order="F"))
        if cfg.save_binary:
            ds.save_binary(filename + ".bin")
        return ds

    # ------------------------------------------------------------------
    def _load_streamed(self, filename: str, rank: int = 0,
                       num_machines: int = 1,
                       reference: Optional[Dataset] = None,
                       chunk_lines: Optional[int] = None) -> Dataset:
        """Streaming out-of-core load (``tpu_stream_chunk_rows > 0``):
        three bounded passes over the text file, model byte-equal to the
        one-shot parse-everything route.

        1. **count pass** — stream chunks to count kept rows (striping
           applied) and, when the format demands it (LibSVM width,
           in-file query ids), parse them; otherwise lines are only
           counted.
        2. **sample pass** — the canonical `from_matrix` index draw
           (`dist.binning.sample_indices`) over the kept rows maps to
           global LINE numbers, and ONLY those lines are parsed: the
           sample matrix is identical to the slice the in-memory path
           takes, so bin boundaries are bitwise-equal.
        3. **bin pass** — each chunk is parsed, binned ON DEVICE
           (`io/stream.DeviceBinner`), appended to the HBM buffer and
           pulled back as uint8 rows into the preallocated host matrix.

        Peak host float memory is O(sample + chunk); the raw matrix
        never exists.
        """
        import time as _time

        from ..dist.binning import sample_indices
        from ..utils import log
        from .stream import (DeviceAppender, DeviceBinner, ShardedAppender,
                             finish_sharded_ingest, run_sharded_pipeline)

        cfg = self.config
        t0 = _time.perf_counter()
        if chunk_lines is None:
            chunk_lines = int(cfg.tpu_stream_chunk_rows) \
                or int(os.environ.get("LGBM_TPU_INGEST_CHUNK",
                                      DEFAULT_CHUNK_LINES))
        chunk_lines = max(int(chunk_lines), 1)
        if not vf_exists(filename):
            raise FileNotFoundError(f"data file {filename} not found")
        all_names = self._header_names(filename)
        label_idx = self._resolve_label_idx(all_names)
        feat_names = None
        if all_names is not None:
            feat_names = list(all_names)
            if 0 <= label_idx < len(feat_names):
                feat_names.pop(label_idx)
        widx = gidx = None
        ignore: set = set()
        if str(cfg.weight_column).strip():
            (widx,) = _parse_column_spec(cfg.weight_column, feat_names)
            ignore.add(widx)
        if str(cfg.group_column).strip():
            (gidx,) = _parse_column_spec(cfg.group_column, feat_names)
            ignore.add(gidx)
        for c in _parse_column_spec(cfg.ignore_column, feat_names):
            ignore.add(c)

        def _prep_chunk(labs, feats, start_global):
            """striping + metadata-column extraction + ignore zeroing —
            identical to the two_round helper so every pass sees the
            same kept rows."""
            gi = start_global + np.arange(len(labs))
            if num_machines > 1 and not cfg.pre_partition:
                sel = gi % num_machines == rank
                labs, feats, gi = labs[sel], feats[sel], gi[sel]
            w = feats[:, widx].copy() if widx is not None \
                and widx < feats.shape[1] else None
            gids = feats[:, gidx].copy() if gidx is not None \
                and gidx < feats.shape[1] else None
            for c in ignore:
                if c < feats.shape[1]:
                    feats[:, c] = 0.0
            return labs, feats, w, gids, gi

        # ---- pass 1: count (parse only when the format demands it)
        parser = None
        gid_parts: List[np.ndarray] = []
        n_global = 0
        n_kept = 0
        max_f = 0
        needs_parse = True
        for lines in self._iter_line_chunks(filename, chunk_lines):
            if parser is None:
                parser = create_parser(lines[:32], label_idx)
                # delimited formats have a fixed width and (unless a
                # group column is in-file) nothing else to extract, so
                # later pass-1 chunks are just counted
                needs_parse = (isinstance(parser, LibSVMParser)
                               or gidx is not None)
                max_f = parser.num_features(lines[0])
            if needs_parse:
                labs, feats = parse_dense(lines, parser)
                labs, feats, _w, gids, _gi = _prep_chunk(labs, feats,
                                                         n_global)
                max_f = max(max_f, feats.shape[1])
                if gids is not None:
                    gid_parts.append(gids)
                kept = feats.shape[0]
            else:
                gi = n_global + np.arange(len(lines))
                kept = len(lines) if num_machines <= 1 \
                    or cfg.pre_partition \
                    else int(np.sum(gi % num_machines == rank))
            n_global += len(lines)
            n_kept += kept
        if parser is None:
            raise ValueError(f"data file {filename} is empty")

        num_cols = max_f if isinstance(parser, LibSVMParser) else None

        # stream-to-shard: when the run is data-parallel, each chunk is
        # binned on its OWNER device and written into that device's
        # shard slice — the [n, U] host matrix is never allocated.
        # Multi-process striping keeps the legacy host path: shard
        # ownership is a per-process concept there.
        shard_mesh = None
        if reference is None and num_machines <= 1:
            from ..dist import runtime as dist_runtime
            shard_mesh = dist_runtime.stream_shard_mesh(cfg)

        # ---- pass 2: bounded sample — the canonical from_matrix draw
        if reference is not None:
            max_f = max(max_f, reference.num_total_features)
            num_cols = max_f if isinstance(parser, LibSVMParser) else None
            ds = Dataset.create_from_sample(None, n_kept, config=cfg,
                                            reference=reference)
        else:
            sample_cnt = min(n_kept, max(cfg.bin_construct_sample_cnt, 1))
            sidx = np.asarray(
                sample_indices(n_kept, sample_cnt, cfg.data_random_seed),
                np.int64)
            # kept row i lives at a computable global line: identity when
            # not striping, rank + i * num_machines otherwise
            if num_machines > 1 and not cfg.pre_partition:
                want_global = sidx * num_machines + rank
            else:
                want_global = sidx
            picked: List[str] = []
            off = 0
            for lines in self._iter_line_chunks(filename, chunk_lines):
                lo = np.searchsorted(want_global, off)
                hi = np.searchsorted(want_global, off + len(lines))
                for g in want_global[lo:hi]:
                    picked.append(lines[int(g - off)])
                off += len(lines)
            _, sample = parse_dense(picked, parser, num_cols=num_cols)
            del picked
            if sample.shape[1] < max_f:
                sample = np.pad(
                    sample, ((0, 0), (0, max_f - sample.shape[1])))
            for c in ignore:
                if c < sample.shape[1]:
                    sample[:, c] = 0.0
            ds = Dataset.create_from_sample(
                sample, n_kept, config=cfg, feature_names=feat_names,
                categorical_feature=self._categorical_from_config(
                    feat_names),
                alloc_bins=shard_mesh is None)
            del sample
        if shard_mesh is not None and len(ds.real_feature_idx) == 0:
            # nothing to device-bin: the trivial [n, 0] host matrix is
            # the simpler path
            ds.bins = np.zeros((n_kept, 0), ds.bins_dtype())
            shard_mesh = None

        # ---- pass 3: parse + device-bin + append chunk-by-chunk
        side_w = _read_sidecar(filename + ".weight")
        side_q = _read_sidecar(filename + ".query")
        init_score = _read_sidecar(filename + ".init")
        if cfg.initscore_filename and vf_exists(cfg.initscore_filename):
            init_score = _read_sidecar(cfg.initscore_filename)
        raw_parts: List[np.ndarray] = []
        kept_gi: List[np.ndarray] = []
        seen = {"n_global": 0}
        sharded_stats = None
        if shard_mesh is not None:
            # stream-to-shard: producer thread parses/preps chunk k+1
            # while chunk k is transferred + binned on its owner device
            # (two staging buffers + async dispatch) — ingest wall
            # approaches max(parse, bin) instead of their sum
            depth = int(getattr(cfg, "tpu_stream_pipeline_depth", 2))
            sh_appender = ShardedAppender(shard_mesh, "data", n_kept, ds,
                                          chunk_lines)

            def _chunks():
                pos = 0
                for lines in self._iter_line_chunks(filename, chunk_lines):
                    labs, feats = parse_dense(lines, parser,
                                              num_cols=num_cols)
                    labs, feats, w, _, gi = _prep_chunk(
                        labs, feats, seen["n_global"])
                    seen["n_global"] += len(lines)
                    if feats.shape[1] < max_f:
                        feats = np.pad(
                            feats, ((0, 0), (0, max_f - feats.shape[1])))
                    k = feats.shape[0]
                    if side_w is not None:
                        w = side_w[gi]
                    segs = [(di, off, b - a,
                             sh_appender.host_prep(feats[a:b]))
                            for di, off, a, b in sh_appender.plan(pos, k)]
                    if init_score is None and self.predict_fun is not None:
                        raw_parts.append(np.asarray(
                            self.predict_fun(feats), np.float64))
                    kept_gi.append(gi)
                    pos += k
                    yield k, labs, w, segs

            parse_s, bin_s, wall_s = run_sharded_pipeline(
                ds, sh_appender, _chunks(), depth)
            if sh_appender.rows_done != n_kept:
                raise ValueError(
                    f"streamed load pass 3 saw {sh_appender.rows_done} "
                    f"rows but pass 1 counted {n_kept}: the data file "
                    f"changed between passes (is the path a "
                    f"non-rewindable stream?)")
            sharded_stats = (sh_appender, parse_s, bin_s, wall_s, depth)
            n_global = seen["n_global"]
        else:
            binner = DeviceBinner(ds, chunk_lines)
            appender = (DeviceAppender(n_kept, binner.num_used,
                                       chunk_lines, ds.bins.dtype)
                        if binner.num_used else None)
            pos = 0
            n_global = 0
            for lines in self._iter_line_chunks(filename, chunk_lines):
                labs, feats = parse_dense(lines, parser, num_cols=num_cols)
                labs, feats, w, _, gi = _prep_chunk(labs, feats, n_global)
                n_global += len(lines)
                if feats.shape[1] < max_f:
                    feats = np.pad(feats,
                                   ((0, 0), (0, max_f - feats.shape[1])))
                k = feats.shape[0]
                if side_w is not None:
                    w = side_w[gi]
                if binner.num_used:
                    dev = binner.bin_chunk(feats)
                    appender.append(dev, k)
                    host_rows = np.asarray(dev)[:k]
                else:
                    host_rows = np.zeros((k, 0), ds.bins.dtype)
                ds.push_binned_rows(host_rows, label=labs, weight=w)
                if init_score is None and self.predict_fun is not None:
                    raw_parts.append(np.asarray(self.predict_fun(feats),
                                                np.float64))
                kept_gi.append(gi)
                pos += k
            if pos != n_kept:
                raise ValueError(
                    f"streamed load pass 3 saw {pos} rows but pass 1 "
                    f"counted {n_kept}: the data file changed between "
                    f"passes (is the path a non-rewindable stream?)")

        group_sizes = None
        if side_q is not None:
            group_sizes = side_q.astype(np.int64)
        elif gid_parts:
            ids = np.concatenate(gid_parts)
            change = np.flatnonzero(np.diff(ids) != 0)
            bounds = np.concatenate([[0], change + 1, [len(ids)]])
            group_sizes = np.diff(bounds).astype(np.int64)
        if sharded_stats is not None:
            sh_appender, parse_s, bin_s, wall_s, depth = sharded_stats
            finish_sharded_ingest(ds, sh_appender, chunk_lines, parse_s,
                                  bin_s, wall_s, depth, source="file")
        elif appender is not None:
            ds.attach_device_bins(appender.finish())
        ds.finish_load(group=group_sizes)
        if init_score is not None:
            gsel = (np.concatenate(kept_gi) if kept_gi
                    else np.zeros(0, np.int64))
            if n_global and len(init_score) % n_global == 0:
                ncls = len(init_score) // n_global
                ds.metadata.set_init_score(np.concatenate(
                    [init_score[c * n_global + gsel]
                     for c in range(ncls)]))
            else:
                ds.metadata.set_init_score(init_score)
        elif raw_parts:
            raw = np.concatenate(raw_parts, axis=0)
            ds.metadata.set_init_score(raw.reshape(-1, order="F"))
        ms = (_time.perf_counter() - t0) * 1e3
        if sharded_stats is not None:
            # pipeline walls (parse/bin/overlap) describe pass 3; the
            # headline ingest wall stays the full three-pass load like
            # the legacy path so bench numbers compare like-for-like
            ds._ingest_ms = ms
            ds._ingest_stats["total_ms"] = round(ms, 1)
        else:
            ds._ingest_ms = ms
            ds._ingest_stats = {
                "rows": int(n_kept), "chunk_rows": int(chunk_lines),
                "device_cols": int(binner.num_used
                                   - len(binner._cat_cols)),
                "host_cols": int(len(binner._cat_cols)),
            }
            log.event("stream_ingest", rows=int(n_kept),
                      chunk_rows=int(chunk_lines),
                      device_cols=ds._ingest_stats["device_cols"],
                      host_cols=ds._ingest_stats["host_cols"],
                      ingest_ms=ms, source="file")
        if cfg.save_binary:
            ds.save_binary(filename + ".bin")
        return ds

    # ------------------------------------------------------------------
    def _load_columnar(self, filename: str, rank: int = 0,
                       num_machines: int = 1,
                       reference: Optional[Dataset] = None) -> Dataset:
        """Parquet / Arrow IPC front door: record batches of
        ``tpu_stream_chunk_rows`` stream through the same sample +
        device-bin + append flow as `_load_streamed`. Requires pyarrow
        (gated — a clear ImportError otherwise)."""
        import time as _time

        from ..dist.binning import sample_indices
        from ..utils import log
        from .stream import (DeviceAppender, DeviceBinner,
                             iter_parquet_batches)

        cfg = self.config
        t0 = _time.perf_counter()
        chunk_rows = max(int(cfg.tpu_stream_chunk_rows)
                         or DEFAULT_CHUNK_LINES, 1)
        if not os.path.exists(filename):
            raise FileNotFoundError(f"data file {filename} not found")

        names: Optional[List[str]] = None
        n_global = 0
        for batch_names, block in iter_parquet_batches(filename,
                                                       chunk_rows):
            names = batch_names
            n_global += block.shape[0]
        if names is None or n_global == 0:
            raise ValueError(f"data file {filename} is empty")
        label_idx = self._resolve_label_idx(names)
        feat_names = list(names)
        if 0 <= label_idx < len(feat_names):
            feat_names.pop(label_idx)
        widx = gidx = None
        ignore: set = set()
        if str(cfg.weight_column).strip():
            (widx,) = _parse_column_spec(cfg.weight_column, feat_names)
            ignore.add(widx)
        if str(cfg.group_column).strip():
            (gidx,) = _parse_column_spec(cfg.group_column, feat_names)
            ignore.add(gidx)
        for c in _parse_column_spec(cfg.ignore_column, feat_names):
            ignore.add(c)

        def _prep_block(block, start_global):
            labs = block[:, label_idx].copy() \
                if 0 <= label_idx < block.shape[1] \
                else np.zeros(block.shape[0])
            feats = np.delete(block, label_idx, axis=1) \
                if 0 <= label_idx < block.shape[1] else block
            gi = start_global + np.arange(len(labs))
            if num_machines > 1 and not cfg.pre_partition:
                sel = gi % num_machines == rank
                labs, feats, gi = labs[sel], feats[sel], gi[sel]
            w = feats[:, widx].copy() if widx is not None else None
            gids = feats[:, gidx].copy() if gidx is not None else None
            for c in ignore:
                if c < feats.shape[1]:
                    feats[:, c] = 0.0
            return labs, feats, w, gids, gi

        stripe = num_machines > 1 and not cfg.pre_partition
        n_kept = (int(np.sum(np.arange(n_global) % num_machines == rank))
                  if stripe else n_global)

        if reference is not None:
            ds = Dataset.create_from_sample(None, n_kept, config=cfg,
                                            reference=reference)
        else:
            sample_cnt = min(n_kept, max(cfg.bin_construct_sample_cnt, 1))
            sidx = np.asarray(
                sample_indices(n_kept, sample_cnt, cfg.data_random_seed),
                np.int64)
            want = sidx * num_machines + rank if stripe else sidx
            rows: List[np.ndarray] = []
            off = 0
            gid_parts: List[np.ndarray] = []
            for _, block in iter_parquet_batches(filename, chunk_rows):
                labs, feats, _w, gids, gi = _prep_block(block, off)
                lo = np.searchsorted(want, off)
                hi = np.searchsorted(want, off + block.shape[0])
                if hi > lo:
                    rows.append(feats[np.searchsorted(gi, want[lo:hi])])
                if gids is not None:
                    gid_parts.append(gids)
                off += block.shape[0]
            sample = (np.concatenate(rows, axis=0) if rows
                      else np.zeros((0, max(len(feat_names), 0))))
            del rows
            ds = Dataset.create_from_sample(
                sample, n_kept, config=cfg, feature_names=feat_names,
                categorical_feature=self._categorical_from_config(
                    feat_names))
            del sample

        side_w = _read_sidecar(filename + ".weight")
        side_q = _read_sidecar(filename + ".query")
        init_score = _read_sidecar(filename + ".init")
        binner = DeviceBinner(ds, chunk_rows)
        appender = (DeviceAppender(n_kept, binner.num_used, chunk_rows,
                                   ds.bins.dtype)
                    if binner.num_used else None)
        pos = 0
        off = 0
        gid_parts = []
        for _, block in iter_parquet_batches(filename, chunk_rows):
            labs, feats, w, gids, gi = _prep_block(block, off)
            off += block.shape[0]
            k = feats.shape[0]
            if side_w is not None:
                w = side_w[gi]
            if gids is not None:
                gid_parts.append(gids)
            if binner.num_used:
                dev = binner.bin_chunk(feats)
                appender.append(dev, k)
                host_rows = np.asarray(dev)[:k]
            else:
                host_rows = np.zeros((k, 0), ds.bins.dtype)
            ds.push_binned_rows(host_rows, label=labs, weight=w)
            pos += k
        if pos != n_kept:
            raise ValueError(
                f"columnar load saw {pos} rows but the count pass saw "
                f"{n_kept}: the file changed between passes")
        group_sizes = None
        if side_q is not None:
            group_sizes = side_q.astype(np.int64)
        elif gid_parts:
            ids = np.concatenate(gid_parts)
            change = np.flatnonzero(np.diff(ids) != 0)
            bounds = np.concatenate([[0], change + 1, [len(ids)]])
            group_sizes = np.diff(bounds).astype(np.int64)
        if appender is not None:
            ds.attach_device_bins(appender.finish())
        ds.finish_load(group=group_sizes)
        if init_score is not None:
            ds.metadata.set_init_score(init_score)
        ms = (_time.perf_counter() - t0) * 1e3
        ds._ingest_ms = ms
        ds._ingest_stats = {
            "rows": int(n_kept), "chunk_rows": int(chunk_rows),
            "device_cols": int(binner.num_used - len(binner._cat_cols)),
            "host_cols": int(len(binner._cat_cols)),
        }
        log.event("stream_ingest", rows=int(n_kept),
                  chunk_rows=int(chunk_rows),
                  device_cols=ds._ingest_stats["device_cols"],
                  host_cols=ds._ingest_stats["host_cols"],
                  ingest_ms=ms, source="columnar")
        return ds

    def load_from_file_align_with_other_dataset(
            self, filename: str, reference: Dataset) -> Dataset:
        """Validation data binned with the training set's mappers
        (reference `dataset_loader.cpp:224`)."""
        if str(filename).endswith(_COLUMNAR_EXTS):
            return self._load_columnar(filename, reference=reference)
        if int(getattr(self.config, "tpu_stream_chunk_rows", 0)) > 0:
            return self._load_streamed(filename, reference=reference)
        if getattr(self.config, "two_round", False):
            return self._load_two_round(filename, reference=reference)
        labels, feats, ex = self.parse_file(filename)
        for c in ex["ignore"]:
            feats[:, c] = 0.0
        ds = Dataset.from_matrix(
            feats, label=labels, config=self.config, weight=ex["weights"],
            group=ex["group_sizes"], init_score=ex["init_score"],
            feature_names=ex["feature_names"], reference=reference)
        if self.predict_fun is not None and ds.metadata.init_score is None:
            raw = np.asarray(self.predict_fun(feats), dtype=np.float64)
            ds.metadata.set_init_score(raw.reshape(-1, order="F"))
        return ds
