"""Streaming out-of-core ingest with device-side binning.

The one-shot construct paths (`Dataset.from_matrix`, the loader's
parse-everything route) materialize the full float matrix on the host —
at Higgs scale that is an 11M x 28 f64 intermediate for a dataset whose
training copy is a 308 MB uint8 matrix. This module replaces that
intermediate with a chunked pipeline:

1. **one bounded sample pass** draws the bin-construction sample with
   the SAME canonical index draw as `Dataset.from_matrix`
   (`dist.binning.sample_indices`), so the resulting bin boundaries are
   bitwise-equal to the in-memory path's — parity by construction, the
   same argument the distributed bin sync makes;
2. **each chunk is binned on device**: a jitted f64 `searchsorted` over
   per-feature upper-bound tables (the device twin of
   `BinMapper.values_to_bins`; categorical columns are dictionary
   lookups and stay host-binned, riding through the kernel untouched);
3. the binned uint8 rows are appended into an HBM-resident buffer
   (donated `dynamic_update_slice`, O(1) reallocation) AND pulled back
   chunk-by-chunk into the host matrix the rest of the stack reads
   (model text, bundling, binary save). The HBM buffer is attached to
   the dataset so the learner's first upload is free.

Peak host memory is O(sample + chunk + uint8 matrix) — the raw float
matrix never exists, so datasets whose FLOAT form exceeds host RAM
load fine as long as their binned form fits.

Arrow/Parquet front door: `iter_parquet_batches` reads record batches
of ~chunk rows through pyarrow when it is installed (gated import — the
toolchain does not bake it in; callers get a clear error otherwise).
"""
from __future__ import annotations

import functools
import time
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import Config
from .binning import BIN_CATEGORICAL, MISSING_NAN
from .dataset import Dataset

__all__ = [
    "DeviceBinner",
    "DeviceAppender",
    "iter_parquet_batches",
    "pyarrow_available",
    "stream_matrix",
]


# ---------------------------------------------------------------------------
# device-side value->bin kernel
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("out_bits",))
def _bin_chunk_kernel(vals_T, bounds, is_cat, nan_override, use_override,
                      out_bits: int):
    """Device twin of `BinMapper.values_to_bins` for one padded chunk.

    vals_T:       f64 [U, C] — chunk values, feature-major (categorical
                  columns already hold HOST bin ids)
    bounds:       f64 [U, Bmax] — truncated `bin_upper_bound[:r]` padded
                  with +inf (past-the-end searches land exactly on r,
                  the first pad index, so padding is bitwise-equivalent
                  to the host's per-column truncation)
    nan_override: int32 [U] — `num_bin - 1` for MISSING_NAN columns
    use_override: bool [U] — whether NaN routes to nan_override (else
                  NaN is binned as 0.0, matching the host)

    The comparisons run in f64 — the exactness of the host parity
    argument lives or dies on the compare precision, so the CALLER must
    trace/lower/run this under `enable_x64` (a ctx inside the traced
    body is not enough: weak constants re-canonicalize to f32 at
    lowering time, which happens after the body ctx has exited).
    """
    nan_mask = jnp.isnan(vals_T)
    v = jnp.where(nan_mask, jnp.zeros((), vals_T.dtype), vals_T)
    idx = jax.vmap(
        lambda b, c: jnp.searchsorted(b, c, side="left"))(bounds, v)
    idx = idx.astype(jnp.int32)
    idx = jnp.where(nan_mask & use_override[:, None],
                    nan_override[:, None], idx)
    # categorical columns arrived host-binned: pass the ids through
    idx = jnp.where(is_cat[:, None], v.astype(jnp.int32), idx)
    out_dtype = jnp.uint8 if out_bits == 8 else jnp.uint16
    return idx.T.astype(out_dtype)


class DeviceBinner:
    """Per-dataset binning tables + the jitted chunk kernel.

    Chunks are padded to a fixed ``chunk_rows`` so ONE trace serves the
    whole ingest; the garbage pad rows are sliced off on the host side
    and overwritten by the next append on the device side.
    """

    def __init__(self, ds: Dataset, chunk_rows: int) -> None:
        self.chunk_rows = int(chunk_rows)
        self.used = np.asarray(ds.real_feature_idx)
        mappers = [ds.mappers[j] for j in self.used]
        self.out_bits = 8 if ds.bins.dtype == np.uint8 else 16
        u = len(mappers)
        self.num_used = u
        self._cat_cols = [i for i, m in enumerate(mappers)
                          if m.bin_type == BIN_CATEGORICAL]
        self._mappers = mappers
        if u == 0:
            return
        rs = []
        for m in mappers:
            if m.bin_type == BIN_CATEGORICAL:
                rs.append(0)
            else:
                r = m.num_bin - 1
                if m.missing_type == MISSING_NAN:
                    r -= 1
                rs.append(max(r, 0))
        bmax = max(max(rs), 1)
        bounds = np.full((u, bmax), np.inf, dtype=np.float64)
        for i, (m, r) in enumerate(zip(mappers, rs)):
            if r > 0:
                bounds[i, :r] = np.asarray(m.bin_upper_bound[:r], np.float64)
        with jax.experimental.enable_x64():
            # f64 on device: created inside enable_x64 so the dtype
            # survives canonicalization (a plain asarray would silently
            # downcast to f32 and break bitwise parity with the host)
            self._bounds = jnp.asarray(bounds, dtype=jnp.float64)
        self._is_cat = jnp.asarray(
            np.asarray([m.bin_type == BIN_CATEGORICAL for m in mappers]))
        self._nan_override = jnp.asarray(
            np.asarray([m.num_bin - 1 for m in mappers], np.int32))
        self._use_override = jnp.asarray(
            np.asarray([m.bin_type != BIN_CATEGORICAL
                        and m.missing_type == MISSING_NAN
                        for m in mappers]))

    def bin_chunk(self, feats: np.ndarray):
        """Bin one [k, F_total] float chunk -> device [chunk_rows, U]
        (rows past k are pad garbage). Returns the DEVICE array; callers
        slice/pull as needed."""
        k = feats.shape[0]
        vals = np.ascontiguousarray(
            np.asarray(feats, np.float64)[:, self.used].T)  # [U, k]
        for i in self._cat_cols:
            # categorical: host dictionary lookup, ids ride through
            vals[i] = self._mappers[i].values_to_bins(vals[i])
        if k < self.chunk_rows:
            vals = np.pad(vals, ((0, 0), (0, self.chunk_rows - k)))
        # trace, lower AND run inside the x64 ctx: the jit cache keys on
        # the x64 flag, so every call staying inside the ctx reuses one
        # genuinely-f64 program
        with jax.experimental.enable_x64():
            vals_dev = jnp.asarray(vals, dtype=jnp.float64)
            return _bin_chunk_kernel(vals_dev, self._bounds, self._is_cat,
                                     self._nan_override,
                                     self._use_override, self.out_bits)


@functools.partial(jax.jit, donate_argnums=(0,))
def _append_kernel(buf, chunk, pos):
    """Donated in-place append: the buffer is over-allocated by one full
    chunk, so `pos + chunk_rows <= buf_rows` always holds and the update
    never clamps; garbage pad rows are overwritten by the next append
    and sliced off at finish."""
    return lax.dynamic_update_slice(buf, chunk, (pos, jnp.int32(0)))


class DeviceAppender:
    """HBM-resident growing copy of the binned matrix ([n + chunk, U]
    buffer, donated fixed-size appends, final [:n] slice)."""

    def __init__(self, n: int, num_used: int, chunk_rows: int,
                 dtype) -> None:
        self.n = int(n)
        self._buf = jnp.zeros((self.n + int(chunk_rows), num_used),
                              dtype=jnp.uint8 if dtype == np.uint8
                              else jnp.uint16)
        self._pos = 0

    def append(self, chunk_dev, k: int) -> None:
        self._buf = _append_kernel(self._buf, chunk_dev,
                                   jnp.int32(self._pos))
        self._pos += int(k)

    def finish(self):
        if self._pos != self.n:
            raise ValueError(
                f"DeviceAppender: {self._pos} rows appended, "
                f"{self.n} declared")
        return self._buf[:self.n]


# ---------------------------------------------------------------------------
# in-memory matrix front door
# ---------------------------------------------------------------------------
def stream_matrix(data, label=None, config: Optional[Config] = None,
                  weight=None, group=None, init_score=None,
                  feature_names: Optional[List[str]] = None,
                  categorical_feature: Optional[Sequence[int]] = None,
                  reference: Optional[Dataset] = None) -> Dataset:
    """Chunked twin of `Dataset.from_matrix`: same sample draw, same bin
    boundaries, same binned matrix — but built chunk-by-chunk through the
    device binning kernel, leaving the HBM copy attached. `data` may be
    any object supporting 2-D shape + row slicing (an `np.memmap` of a
    larger-than-RAM matrix is the intended caller)."""
    from ..dist.binning import sample_indices
    from ..utils import log

    cfg = config or Config()
    chunk_rows = max(int(cfg.tpu_stream_chunk_rows), 1)
    t0 = time.perf_counter()
    n, f = data.shape[0], data.shape[1]

    if reference is not None:
        ds = Dataset.create_from_sample(None, n, config=cfg,
                                        reference=reference)
    else:
        sample_cnt = min(n, max(cfg.bin_construct_sample_cnt, 1))
        sample_idx = sample_indices(n, sample_cnt, cfg.data_random_seed)
        sample = np.asarray(data[sample_idx], np.float64)
        ds = Dataset.create_from_sample(
            sample, n, config=cfg, feature_names=feature_names,
            categorical_feature=categorical_feature)
        del sample

    label = None if label is None else np.asarray(label).reshape(-1)
    weight = None if weight is None else np.asarray(weight).reshape(-1)
    binner = DeviceBinner(ds, chunk_rows)
    appender = (DeviceAppender(n, binner.num_used, chunk_rows,
                               ds.bins.dtype)
                if binner.num_used else None)
    for lo in range(0, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        k = hi - lo
        if binner.num_used:
            dev = binner.bin_chunk(np.asarray(data[lo:hi]))
            appender.append(dev, k)
            host = np.asarray(dev)[:k]
        else:
            host = np.zeros((k, 0), ds.bins.dtype)
        ds.push_binned_rows(
            host,
            label=None if label is None else label[lo:hi],
            weight=None if weight is None else weight[lo:hi])
    if appender is not None:
        ds.attach_device_bins(appender.finish())
    ds.finish_load(group=group)
    if init_score is not None:
        ds.metadata.set_init_score(init_score)
    ms = (time.perf_counter() - t0) * 1e3
    ds._ingest_ms = ms
    ds._ingest_stats = {
        "rows": int(n), "chunk_rows": int(chunk_rows),
        "device_cols": int(binner.num_used - len(binner._cat_cols)),
        "host_cols": int(len(binner._cat_cols)),
    }
    log.event("stream_ingest", rows=int(n), chunk_rows=int(chunk_rows),
              device_cols=ds._ingest_stats["device_cols"],
              host_cols=ds._ingest_stats["host_cols"],
              ingest_ms=ms, source="matrix")
    return ds


# ---------------------------------------------------------------------------
# Arrow / Parquet front door (gated: pyarrow is not baked into the image)
# ---------------------------------------------------------------------------
def pyarrow_available() -> bool:
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet  # noqa: F401
        return True
    except Exception:
        return False


def iter_parquet_batches(path: str, chunk_rows: int
                         ) -> Iterator[Tuple[List[str], np.ndarray]]:
    """Yield ``(column_names, float64 [<=chunk_rows, C] block)`` from a
    Parquet or Arrow IPC file without materializing the whole table."""
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq
    except Exception as e:  # pragma: no cover - exercised via skipif
        raise ImportError(
            "Parquet/Arrow ingest needs pyarrow, which is not installed "
            "in this environment; convert the file to CSV/TSV or install "
            "pyarrow") from e
    if str(path).endswith((".arrow", ".feather", ".ipc")):
        with pa.memory_map(str(path)) as src:
            table = pa.ipc.open_file(src).read_all()
        batches = table.to_batches(max_chunksize=chunk_rows)
    else:
        pf = pq.ParquetFile(str(path))
        batches = pf.iter_batches(batch_size=chunk_rows)
    for batch in batches:
        names = list(batch.schema.names)
        cols = [np.asarray(batch.column(i).to_numpy(zero_copy_only=False),
                           np.float64) for i in range(batch.num_columns)]
        yield names, (np.stack(cols, axis=1) if cols
                      else np.zeros((batch.num_rows, 0)))
