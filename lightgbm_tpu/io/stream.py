"""Streaming out-of-core ingest with device-side binning.

The one-shot construct paths (`Dataset.from_matrix`, the loader's
parse-everything route) materialize the full float matrix on the host —
at Higgs scale that is an 11M x 28 f64 intermediate for a dataset whose
training copy is a 308 MB uint8 matrix. This module replaces that
intermediate with a chunked pipeline:

1. **one bounded sample pass** draws the bin-construction sample with
   the SAME canonical index draw as `Dataset.from_matrix`
   (`dist.binning.sample_indices`), so the resulting bin boundaries are
   bitwise-equal to the in-memory path's — parity by construction, the
   same argument the distributed bin sync makes;
2. **each chunk is binned on device**: a jitted f64 `searchsorted` over
   per-feature upper-bound tables (the device twin of
   `BinMapper.values_to_bins`; categorical columns are dictionary
   lookups and stay host-binned, riding through the kernel untouched);
3. the binned uint8 rows are appended into an HBM-resident buffer
   (donated `dynamic_update_slice`, O(1) reallocation) AND pulled back
   chunk-by-chunk into the host matrix the rest of the stack reads
   (model text, bundling, binary save). The HBM buffer is attached to
   the dataset so the learner's first upload is free.

Peak host memory is O(sample + chunk + uint8 matrix) — the raw float
matrix never exists, so datasets whose FLOAT form exceeds host RAM
load fine as long as their binned form fits.

Arrow/Parquet front door: `iter_parquet_batches` reads record batches
of ~chunk rows through pyarrow when it is installed (gated import — the
toolchain does not bake it in; callers get a clear error otherwise).
"""
from __future__ import annotations

import functools
import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import Config
from .binning import BIN_CATEGORICAL, MISSING_NAN
from .dataset import Dataset

__all__ = [
    "ChunkPrefetcher",
    "DeviceBinner",
    "DeviceAppender",
    "ShardedAppender",
    "finish_sharded_ingest",
    "iter_parquet_batches",
    "pyarrow_available",
    "run_sharded_pipeline",
    "stream_matrix",
]


# ---------------------------------------------------------------------------
# device-side value->bin kernel
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("out_bits",))
def _bin_chunk_kernel(vals_T, bounds, is_cat, nan_override, use_override,
                      out_bits: int):
    """Device twin of `BinMapper.values_to_bins` for one padded chunk.

    vals_T:       f64 [U, C] — chunk values, feature-major (categorical
                  columns already hold HOST bin ids)
    bounds:       f64 [U, Bmax] — truncated `bin_upper_bound[:r]` padded
                  with +inf (past-the-end searches land exactly on r,
                  the first pad index, so padding is bitwise-equivalent
                  to the host's per-column truncation)
    nan_override: int32 [U] — `num_bin - 1` for MISSING_NAN columns
    use_override: bool [U] — whether NaN routes to nan_override (else
                  NaN is binned as 0.0, matching the host)

    The comparisons run in f64 — the exactness of the host parity
    argument lives or dies on the compare precision, so the CALLER must
    trace/lower/run this under `enable_x64` (a ctx inside the traced
    body is not enough: weak constants re-canonicalize to f32 at
    lowering time, which happens after the body ctx has exited).
    """
    nan_mask = jnp.isnan(vals_T)
    v = jnp.where(nan_mask, jnp.zeros((), vals_T.dtype), vals_T)
    idx = jax.vmap(
        lambda b, c: jnp.searchsorted(b, c, side="left"))(bounds, v)
    idx = idx.astype(jnp.int32)
    idx = jnp.where(nan_mask & use_override[:, None],
                    nan_override[:, None], idx)
    # categorical columns arrived host-binned: pass the ids through
    idx = jnp.where(is_cat[:, None], v.astype(jnp.int32), idx)
    out_dtype = jnp.uint8 if out_bits == 8 else jnp.uint16
    return idx.T.astype(out_dtype)


class DeviceBinner:
    """Per-dataset binning tables + the jitted chunk kernel.

    Chunks are padded to a fixed ``chunk_rows`` so ONE trace serves the
    whole ingest; the garbage pad rows are sliced off on the host side
    and overwritten by the next append on the device side.

    With ``device`` the tables are COMMITTED to that device and every
    ``bin_chunk`` runs there — the stream-to-shard path builds one
    binner per mesh device so each row block is binned on the device
    that owns its shard slice (no cross-device hop of binned data).
    """

    def __init__(self, ds: Dataset, chunk_rows: int, device=None) -> None:
        self.chunk_rows = int(chunk_rows)
        self.device = device
        self.used = np.asarray(ds.real_feature_idx)
        mappers = [ds.mappers[j] for j in self.used]
        self.out_bits = 8 if ds.bins_dtype() == np.uint8 else 16
        u = len(mappers)
        self.num_used = u
        self._cat_cols = [i for i, m in enumerate(mappers)
                          if m.bin_type == BIN_CATEGORICAL]
        self._mappers = mappers
        if u == 0:
            return
        rs = []
        for m in mappers:
            if m.bin_type == BIN_CATEGORICAL:
                rs.append(0)
            else:
                r = m.num_bin - 1
                if m.missing_type == MISSING_NAN:
                    r -= 1
                rs.append(max(r, 0))
        bmax = max(max(rs), 1)
        bounds = np.full((u, bmax), np.inf, dtype=np.float64)
        for i, (m, r) in enumerate(zip(mappers, rs)):
            if r > 0:
                bounds[i, :r] = np.asarray(m.bin_upper_bound[:r], np.float64)
        def _place(arr):
            return (jnp.asarray(arr) if device is None
                    else jax.device_put(arr, device))

        with jax.experimental.enable_x64():
            # f64 on device: created inside enable_x64 so the dtype
            # survives canonicalization (a plain asarray would silently
            # downcast to f32 and break bitwise parity with the host)
            self._bounds = _place(np.asarray(bounds, np.float64))
        self._is_cat = _place(
            np.asarray([m.bin_type == BIN_CATEGORICAL for m in mappers]))
        self._nan_override = _place(
            np.asarray([m.num_bin - 1 for m in mappers], np.int32))
        self._use_override = _place(
            np.asarray([m.bin_type != BIN_CATEGORICAL
                        and m.missing_type == MISSING_NAN
                        for m in mappers]))

    def host_prep(self, feats: np.ndarray) -> np.ndarray:
        """Host half of the chunk bin: select used columns, transpose to
        feature-major f64, dictionary-bin categorical columns, pad to
        the fixed ``chunk_rows``. Pure numpy — safe to run on the
        prefetch thread while the previous chunk occupies the device."""
        k = feats.shape[0]
        vals = np.ascontiguousarray(
            np.asarray(feats, np.float64)[:, self.used].T)  # [U, k]
        for i in self._cat_cols:
            # categorical: host dictionary lookup, ids ride through
            vals[i] = self._mappers[i].values_to_bins(vals[i])
        if k < self.chunk_rows:
            vals = np.pad(vals, ((0, 0), (0, self.chunk_rows - k)))
        return vals

    def bin_prepped(self, vals: np.ndarray):
        """Device half: transfer one prepped [U, chunk_rows] block and
        run the searchsorted kernel on this binner's device. Trace,
        lower AND run inside the x64 ctx: the jit cache keys on the x64
        flag, so every call staying inside the ctx reuses one
        genuinely-f64 program."""
        with jax.experimental.enable_x64():
            if self.device is None:
                vals_dev = jnp.asarray(vals, dtype=jnp.float64)
            else:
                vals_dev = jax.device_put(
                    np.asarray(vals, np.float64), self.device)
            return _bin_chunk_kernel(vals_dev, self._bounds, self._is_cat,
                                     self._nan_override,
                                     self._use_override, self.out_bits)

    def bin_chunk(self, feats: np.ndarray):
        """Bin one [k, F_total] float chunk -> device [chunk_rows, U]
        (rows past k are pad garbage). Returns the DEVICE array; callers
        slice/pull as needed."""
        return self.bin_prepped(self.host_prep(feats))


@functools.partial(jax.jit, donate_argnums=(0,))
def _append_kernel(buf, chunk, pos):
    """Donated in-place append: the buffer is over-allocated by one full
    chunk, so `pos + chunk_rows <= buf_rows` always holds and the update
    never clamps; garbage pad rows are overwritten by the next append
    and sliced off at finish."""
    return lax.dynamic_update_slice(buf, chunk, (pos, jnp.int32(0)))


class DeviceAppender:
    """HBM-resident growing copy of the binned matrix ([n + chunk, U]
    buffer, donated fixed-size appends, final [:n] slice)."""

    def __init__(self, n: int, num_used: int, chunk_rows: int,
                 dtype) -> None:
        self.n = int(n)
        self._buf = jnp.zeros((self.n + int(chunk_rows), num_used),
                              dtype=jnp.uint8 if dtype == np.uint8
                              else jnp.uint16)
        self._pos = 0

    def append(self, chunk_dev, k: int) -> None:
        self._buf = _append_kernel(self._buf, chunk_dev,
                                   jnp.int32(self._pos))
        self._pos += int(k)

    def finish(self):
        if self._pos != self.n:
            raise ValueError(
                f"DeviceAppender: {self._pos} rows appended, "
                f"{self.n} declared")
        return self._buf[:self.n]


# ---------------------------------------------------------------------------
# stream-to-shard: per-device shard destinations + pipelined prefetch
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("per_shard",))
def _shard_finish_kernel(buf, cnt, per_shard: int):
    """Seal one device's shard: slice the over-allocated append buffer
    to its [per_shard, U] slot, zero the pad rows past this device's
    real row count (the legacy `shard()` zero-pads, and byte-equality
    across ingest paths extends to the pad bytes the histogram kernels
    read), and emit the transposed copy the split-column reads use.
    No donation: the outputs are smaller than the buffer, so XLA could
    not alias them anyway; the buffer is dropped right after."""
    out = buf[:per_shard]
    rows = lax.iota(jnp.int32, per_shard)[:, None]
    out = jnp.where(rows < cnt, out, jnp.zeros((), out.dtype))
    return out, out.T


class ShardedAppender:
    """Stream-to-shard destination: one over-allocated append buffer
    per mesh device, filled by donated `dynamic_update_slice` on the
    device that OWNS the row block — the `[n, U]` host matrix never
    exists, peak host memory stays O(chunk) regardless of n.

    Row ownership is the contiguous-block layout `Dataset.shard()`
    produces (device d owns global rows [d*per_shard, (d+1)*per_shard));
    `finish()` seals each buffer and assembles the global row-sharded
    matrix + its column-sharded transpose into exactly the cache dict
    `shard()` would have built, so the data-parallel learner's later
    `shard(mesh)` call is a cache hit on buffers the loader already
    filled.

    Appends are paced two-buffers-deep per device: the previous append
    must complete before the next one is enqueued (the donated chain
    would stay correct without the wait — XLA orders the donations —
    but the wait bounds in-flight work and is where the pipeline's
    device time becomes observable as ``bin_s``).
    """

    def __init__(self, mesh, axis_name: str, n: int, ds: Dataset,
                 chunk_rows: int) -> None:
        import math as _math

        self.mesh = mesh
        self.axis_name = axis_name
        self.devices = list(mesh.devices.flat)
        self.nd = len(self.devices)
        self.n = int(n)
        self.per_shard = int(_math.ceil(self.n / self.nd))
        self.pad_rows = self.nd * self.per_shard - self.n
        self.chunk_rows = int(chunk_rows)
        # one binner per device: tables replicated, chunks binned on
        # their owner
        self.binners = [DeviceBinner(ds, chunk_rows, device=d)
                        for d in self.devices]
        self.num_used = self.binners[0].num_used
        self._dtype = np.dtype(ds.bins_dtype())
        # one host zero template, placed once per device ([per_shard +
        # chunk, U] over-allocation: the fixed-size donated append never
        # clamps; garbage pad rows are overwritten by the next append
        # and zeroed at finish)
        host0 = np.zeros((self.per_shard + self.chunk_rows, self.num_used),
                         self._dtype)
        self._bufs = [jax.device_put(host0, d) for d in self.devices]
        del host0
        self._pending: List[Optional[Any]] = [None] * self.nd
        self.rows_done = 0
        self.wait_s = 0.0

    def host_prep(self, feats: np.ndarray) -> np.ndarray:
        """Device-independent host half of the chunk bin (the tables'
        host metadata is identical across the per-device replicas)."""
        return self.binners[0].host_prep(feats)

    def plan(self, pos: int, k: int) -> List[Tuple[int, int, int, int]]:
        """Split chunk rows [pos, pos+k) by owner device: a list of
        ``(device_idx, local_offset, a, b)`` where chunk rows [a, b)
        land at the owner's shard-local ``local_offset``."""
        segs = []
        a = 0
        while a < k:
            di = (pos + a) // self.per_shard
            b = min(k, (di + 1) * self.per_shard - pos)
            segs.append((di, (pos + a) - di * self.per_shard, a, b))
            a = b
        return segs

    def append_prepped(self,
                       segs: List[Tuple[int, int, int, np.ndarray]]) -> None:
        """Dispatch one chunk's owner segments: ``(device_idx,
        local_offset, rows, prepped_vals)`` each → transfer + bin on the
        owner + donated append into its shard buffer. Waits (timed) for
        the owner's PREVIOUS append before enqueueing the next — the
        double-buffer pacing."""
        for di, off, rows, vals in segs:
            prev = self._pending[di]
            if prev is not None:
                t0 = time.perf_counter()
                prev.block_until_ready()  # graftlint: disable=LGT002 ingest pacing wait at load time, not a round-loop fence; obs fences would trip the tier-1 zero-fence assertion
                self.wait_s += time.perf_counter() - t0
            out = self.binners[di].bin_prepped(vals)
            self._bufs[di] = _append_kernel(self._bufs[di], out,
                                            jnp.int32(off))
            self._pending[di] = self._bufs[di]
            self.rows_done += int(rows)

    def drain(self) -> None:
        """Block until every in-flight append has landed."""
        for arr in self._pending:
            if arr is not None:
                arr.block_until_ready()  # graftlint: disable=LGT002 load-time drain before sealing shards, not a round-loop fence

    def finish(self) -> Dict[str, Any]:
        """Seal every shard (pad rows zeroed) and assemble the global
        arrays — returns the `Dataset.shard()`-shaped cache dict for
        `Dataset.attach_shard_cache`."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.rows_done != self.n:
            raise ValueError(
                f"ShardedAppender: {self.rows_done} rows appended, "
                f"{self.n} declared")
        self.drain()
        shards, shards_t = [], []
        for di in range(self.nd):
            cnt = min(self.per_shard,
                      max(self.n - di * self.per_shard, 0))
            out, out_t = _shard_finish_kernel(
                self._bufs[di], jnp.int32(cnt), self.per_shard)
            shards.append(out)
            shards_t.append(out_t)
        self._bufs = []
        self._pending = []
        u = self.num_used
        rows_total = self.nd * self.per_shard
        bins = jax.make_array_from_single_device_arrays(
            (rows_total, u),
            NamedSharding(self.mesh, P(self.axis_name)), shards)
        bins_t = jax.make_array_from_single_device_arrays(
            (u, rows_total),
            NamedSharding(self.mesh, P(None, self.axis_name)), shards_t)
        key = (tuple(int(d.id) for d in self.mesh.devices.flat),
               self.axis_name)
        return {"key": key, "mesh": self.mesh,
                "axis_name": self.axis_name, "nd": self.nd,
                "per_shard": self.per_shard, "pad_rows": self.pad_rows,
                "bins": bins, "bins_T": bins_t}


class ChunkPrefetcher:
    """Bounded producer thread over a chunk generator — the pipeline's
    two host staging buffers: the thread parses chunk k+1 while the
    consumer transfers/bins chunk k (numpy parsing holds the GIL, but
    the consumer's device waits release it, so the two genuinely
    overlap). ``parse_s`` accumulates the producer-side wall."""

    _DONE = object()

    def __init__(self, gen: Iterator, depth: int = 2) -> None:
        self.parse_s = 0.0
        self._gen = gen
        self._exc: Optional[BaseException] = None
        # depth counts staging buffers: the consumer holds one, the
        # queue holds the rest
        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(depth) - 1, 1))
        self._t = threading.Thread(target=self._produce, daemon=True,
                                   name="lgbt-ingest-parse")
        self._t.start()

    def _produce(self) -> None:
        try:
            while True:
                t0 = time.perf_counter()
                try:
                    item = next(self._gen)
                except StopIteration:
                    break
                finally:
                    self.parse_s += time.perf_counter() - t0
                self._q.put(item)
        except BaseException as e:   # surfaces on the consumer side
            self._exc = e
        finally:
            self._q.put(self._DONE)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._DONE:
                self._t.join()
                if self._exc is not None:
                    raise self._exc
                return
            yield item


class _InlineChunks:
    """Sequential twin of ChunkPrefetcher (pipeline depth <= 1): same
    interface, no thread — the honest parse-then-bin baseline."""

    def __init__(self, gen: Iterator) -> None:
        self._gen = gen
        self.parse_s = 0.0

    def __iter__(self):
        while True:
            t0 = time.perf_counter()
            try:
                item = next(self._gen)
            except StopIteration:
                self.parse_s += time.perf_counter() - t0
                return
            self.parse_s += time.perf_counter() - t0
            yield item


def run_sharded_pipeline(ds: Dataset, appender: ShardedAppender,
                         gen: Iterator, depth: int
                         ) -> Tuple[float, float, float]:
    """Drive the stream-to-shard pipeline: items are ``(k, label,
    weight, prepped_segs)``; metadata rides through
    `Dataset.push_meta_rows` (no host bins write). Returns
    ``(parse_s, bin_s, wall_s)`` — producer wall, consumer
    transfer/bin/wait wall, and end-to-end wall; with the prefetch
    thread on, wall approaches max(parse, bin) instead of their sum."""
    t_start = time.perf_counter()
    src = (ChunkPrefetcher(gen, depth) if depth >= 2
           else _InlineChunks(gen))
    bin_s = 0.0
    for k, labs, w, segs in src:
        t0 = time.perf_counter()
        appender.append_prepped(segs)
        bin_s += time.perf_counter() - t0
        ds.push_meta_rows(k, label=labs, weight=w)
    t0 = time.perf_counter()
    appender.drain()
    bin_s += time.perf_counter() - t0
    return src.parse_s, bin_s, time.perf_counter() - t_start


def finish_sharded_ingest(ds: Dataset, appender: ShardedAppender,
                          chunk_rows: int, parse_s: float, bin_s: float,
                          wall_s: float, depth: int, source: str) -> None:
    """Common tail of both stream-to-shard front doors: adopt the shard
    cache, record the pipeline breakdown on the dataset, and announce
    `stream_ingest` + `dist_stream` on the event channel."""
    from ..utils import log

    ds.attach_shard_cache(appender.finish())
    seq_s = parse_s + bin_s
    overlap_eff = round(seq_s / wall_s, 3) if wall_s > 0 else 1.0
    dt = np.dtype(ds.bins_dtype())
    shard_bytes = 2 * appender.per_shard * appender.num_used * dt.itemsize
    b0 = appender.binners[0]
    ms = wall_s * 1e3
    ds._ingest_ms = ms
    ds._ingest_stats = {
        "rows": int(appender.n), "chunk_rows": int(chunk_rows),
        "device_cols": int(b0.num_used - len(b0._cat_cols)),
        "host_cols": int(len(b0._cat_cols)),
        "sharded": True, "shards": int(appender.nd),
        "per_shard": int(appender.per_shard),
        "shard_bytes": int(shard_bytes),
        "parse_ms": round(parse_s * 1e3, 1),
        "bin_ms": round(bin_s * 1e3, 1),
        "seq_ms": round(seq_s * 1e3, 1),
        "overlap_eff": overlap_eff,
        "pipeline_depth": int(depth),
    }
    # ingest started wall_s before now — the timeline merger places the
    # ingest lane span at t_start on the shared perf_counter clock
    t_ingest = round(time.perf_counter() - wall_s, 6)
    log.event("stream_ingest", rows=int(appender.n),
              chunk_rows=int(chunk_rows),
              device_cols=ds._ingest_stats["device_cols"],
              host_cols=ds._ingest_stats["host_cols"],
              ingest_ms=ms, wall_ms=round(wall_s * 1e3, 1),
              t_start=t_ingest, source=source)
    log.event("dist_stream", rows=int(appender.n),
              shards=int(appender.nd),
              per_shard=int(appender.per_shard),
              chunk_rows=int(chunk_rows),
              parse_ms=ds._ingest_stats["parse_ms"],
              bin_ms=ds._ingest_stats["bin_ms"],
              wall_ms=round(wall_s * 1e3, 1), t_start=t_ingest,
              ingest_ms=round(ms, 1), overlap_eff=overlap_eff,
              pipeline_depth=int(depth),
              bytes_per_device=int(shard_bytes),
              owners=",".join(f"dist/shard_bytes/d{i}"
                              for i in range(appender.nd)),
              source=source)


# ---------------------------------------------------------------------------
# in-memory matrix front door
# ---------------------------------------------------------------------------
def stream_matrix(data, label=None, config: Optional[Config] = None,
                  weight=None, group=None, init_score=None,
                  feature_names: Optional[List[str]] = None,
                  categorical_feature: Optional[Sequence[int]] = None,
                  reference: Optional[Dataset] = None) -> Dataset:
    """Chunked twin of `Dataset.from_matrix`: same sample draw, same bin
    boundaries, same binned matrix — but built chunk-by-chunk through the
    device binning kernel, leaving the HBM copy attached. `data` may be
    any object supporting 2-D shape + row slicing (an `np.memmap` of a
    larger-than-RAM matrix is the intended caller)."""
    from ..dist.binning import sample_indices
    from ..utils import log

    cfg = config or Config()
    chunk_rows = max(int(cfg.tpu_stream_chunk_rows), 1)
    t0 = time.perf_counter()
    n, f = data.shape[0], data.shape[1]

    shard_mesh = None
    if reference is None:
        from ..dist import runtime as dist_runtime
        shard_mesh = dist_runtime.stream_shard_mesh(cfg)

    if reference is not None:
        ds = Dataset.create_from_sample(None, n, config=cfg,
                                        reference=reference)
    else:
        sample_cnt = min(n, max(cfg.bin_construct_sample_cnt, 1))
        sample_idx = sample_indices(n, sample_cnt, cfg.data_random_seed)
        sample = np.asarray(data[sample_idx], np.float64)
        ds = Dataset.create_from_sample(
            sample, n, config=cfg, feature_names=feature_names,
            categorical_feature=categorical_feature,
            alloc_bins=shard_mesh is None)
        del sample
    if shard_mesh is not None and len(ds.real_feature_idx) == 0:
        # nothing to bin on device; the trivial [n, 0] host matrix is
        # the simpler path
        ds.bins = np.zeros((n, 0), ds.bins_dtype())
        shard_mesh = None

    label = None if label is None else np.asarray(label).reshape(-1)
    weight = None if weight is None else np.asarray(weight).reshape(-1)

    if shard_mesh is not None:
        # ---- stream-to-shard: rows go straight to their owner device
        depth = int(getattr(cfg, "tpu_stream_pipeline_depth", 2))
        appender = ShardedAppender(shard_mesh, "data", n, ds, chunk_rows)

        def _chunks():
            pos = 0
            for lo in range(0, n, chunk_rows):
                hi = min(lo + chunk_rows, n)
                k = hi - lo
                feats = np.asarray(data[lo:hi])
                segs = [(di, off, b - a,
                         appender.host_prep(feats[a:b]))
                        for di, off, a, b in appender.plan(pos, k)]
                pos += k
                yield (k,
                       None if label is None else label[lo:hi],
                       None if weight is None else weight[lo:hi],
                       segs)

        parse_s, bin_s, wall_s = run_sharded_pipeline(
            ds, appender, _chunks(), depth)
        finish_sharded_ingest(ds, appender, chunk_rows, parse_s, bin_s,
                              wall_s, depth, source="matrix")
        ds.finish_load(group=group)
        if init_score is not None:
            ds.metadata.set_init_score(init_score)
        return ds

    binner = DeviceBinner(ds, chunk_rows)
    appender = (DeviceAppender(n, binner.num_used, chunk_rows,
                               ds.bins.dtype)
                if binner.num_used else None)
    for lo in range(0, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        k = hi - lo
        if binner.num_used:
            dev = binner.bin_chunk(np.asarray(data[lo:hi]))
            appender.append(dev, k)
            host = np.asarray(dev)[:k]
        else:
            host = np.zeros((k, 0), ds.bins.dtype)
        ds.push_binned_rows(
            host,
            label=None if label is None else label[lo:hi],
            weight=None if weight is None else weight[lo:hi])
    if appender is not None:
        ds.attach_device_bins(appender.finish())
    ds.finish_load(group=group)
    if init_score is not None:
        ds.metadata.set_init_score(init_score)
    ms = (time.perf_counter() - t0) * 1e3
    ds._ingest_ms = ms
    ds._ingest_stats = {
        "rows": int(n), "chunk_rows": int(chunk_rows),
        "device_cols": int(binner.num_used - len(binner._cat_cols)),
        "host_cols": int(len(binner._cat_cols)),
    }
    log.event("stream_ingest", rows=int(n), chunk_rows=int(chunk_rows),
              device_cols=ds._ingest_stats["device_cols"],
              host_cols=ds._ingest_stats["host_cols"],
              ingest_ms=ms, wall_ms=round(ms, 1),
              t_start=round(t0, 6), source="matrix")
    return ds


# ---------------------------------------------------------------------------
# Arrow / Parquet front door (gated: pyarrow is not baked into the image)
# ---------------------------------------------------------------------------
def pyarrow_available() -> bool:
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet  # noqa: F401
        return True
    except Exception:
        return False


def iter_parquet_batches(path: str, chunk_rows: int
                         ) -> Iterator[Tuple[List[str], np.ndarray]]:
    """Yield ``(column_names, float64 [<=chunk_rows, C] block)`` from a
    Parquet or Arrow IPC file without materializing the whole table."""
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq
    except Exception as e:  # pragma: no cover - exercised via skipif
        raise ImportError(
            "Parquet/Arrow ingest needs pyarrow, which is not installed "
            "in this environment; convert the file to CSV/TSV or install "
            "pyarrow") from e
    if str(path).endswith((".arrow", ".feather", ".ipc")):
        with pa.memory_map(str(path)) as src:
            table = pa.ipc.open_file(src).read_all()
        batches = table.to_batches(max_chunksize=chunk_rows)
    else:
        pf = pq.ParquetFile(str(path))
        batches = pf.iter_batches(batch_size=chunk_rows)
    for batch in batches:
        names = list(batch.schema.names)
        cols = [np.asarray(batch.column(i).to_numpy(zero_copy_only=False),
                           np.float64) for i in range(batch.num_columns)]
        yield names, (np.stack(cols, axis=1) if cols
                      else np.zeros((batch.num_rows, 0)))
