"""Feature quantization: value -> bin mapping.

Re-creates the behavior of the reference `BinMapper`
(`src/io/bin.cpp:22-419`, `include/LightGBM/bin.h:70-250,461-497`): greedy
equal-ish-count numerical binning with zero isolated into its own bin,
categorical binning by descending count with a rare-category cutoff, and the
three missing-value regimes {None, Zero, NaN}.

This is host-side preprocessing (NumPy); the resulting per-feature bin edges
drive a fully vectorized `values_to_bins` that produces the uint8/int32 binned
matrix living in device HBM.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

K_ZERO_THRESHOLD = 1e-35  # reference kZeroThreshold (bin.cpp:166)

MISSING_NONE = "none"
MISSING_ZERO = "zero"
MISSING_NAN = "nan"

BIN_NUMERICAL = "numerical"
BIN_CATEGORICAL = "categorical"


def _next_after(x: float) -> float:
    """Smallest double > x (reference Common::GetDoubleUpperBound,
    common.h:862)."""
    return math.nextafter(x, math.inf)


def _le_ordered(a: float, b: float) -> bool:
    """b <= nextafter(a) (reference Common::CheckDoubleEqualOrdered,
    common.h:857)."""
    return b <= _next_after(a)


def _greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                     max_bin: int, total_cnt: int,
                     min_data_in_bin: int) -> List[float]:
    """Greedy equal-count bin boundaries over sorted distinct values
    (reference GreedyFindBin, bin.cpp:74-157)."""
    n = len(distinct_values)
    bounds: List[float] = []
    assert max_bin > 0
    if n <= max_bin:
        cur = 0
        for i in range(n - 1):
            cur += int(counts[i])
            if cur >= min_data_in_bin:
                val = _next_after((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bounds or not _le_ordered(bounds[-1], val):
                    bounds.append(val)
                    cur = 0
        bounds.append(math.inf)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin

    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = total_cnt - int(counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    uppers = [math.inf] * max_bin
    lowers = [math.inf] * max_bin
    bin_cnt = 0
    lowers[0] = float(distinct_values[0])
    cur = 0
    for i in range(n - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur += int(counts[i])
        # close the bin when: value itself is heavy; bin is full; or the next
        # value is heavy and this bin is at least half full
        if (is_big[i] or cur >= mean_bin_size or
                (is_big[i + 1] and cur >= max(1.0, mean_bin_size * 0.5))):
            uppers[bin_cnt] = float(distinct_values[i])
            bin_cnt += 1
            lowers[bin_cnt] = float(distinct_values[i + 1])
            if bin_cnt >= max_bin - 1:
                break
            cur = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    bin_cnt += 1
    bounds = []
    for i in range(bin_cnt - 1):
        val = _next_after((uppers[i] + lowers[i + 1]) / 2.0)
        if not bounds or not _le_ordered(bounds[-1], val):
            bounds.append(val)
    bounds.append(math.inf)
    return bounds


def _find_bin_zero_as_one(distinct_values: np.ndarray, counts: np.ndarray,
                          max_bin: int, total_sample_cnt: int,
                          min_data_in_bin: int) -> List[float]:
    """Bin boundaries with the zero region isolated into its own bin
    (reference FindBinWithZeroAsOneBin, bin.cpp:159-215)."""
    neg_mask = distinct_values <= -K_ZERO_THRESHOLD
    pos_mask = distinct_values > K_ZERO_THRESHOLD
    left_cnt_data = int(counts[neg_mask].sum())
    right_cnt_data = int(counts[pos_mask].sum())
    cnt_zero = total_sample_cnt - left_cnt_data - right_cnt_data

    nz = np.nonzero(~neg_mask)[0]
    left_cnt = int(nz[0]) if len(nz) else len(distinct_values)

    bounds: List[float] = []
    if left_cnt > 0:
        denom = max(total_sample_cnt - cnt_zero, 1)
        left_max_bin = max(1, int(left_cnt_data / denom * (max_bin - 1)))
        bounds = _greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                                  left_max_bin, left_cnt_data, min_data_in_bin)
        bounds[-1] = -K_ZERO_THRESHOLD

    pz = np.nonzero(pos_mask[left_cnt:])[0]
    right_start = left_cnt + int(pz[0]) if len(pz) else -1

    if right_start >= 0:
        right_max_bin = max_bin - 1 - len(bounds)
        assert right_max_bin > 0
        right_bounds = _greedy_find_bin(
            distinct_values[right_start:], counts[right_start:],
            right_max_bin, right_cnt_data, min_data_in_bin)
        bounds.append(K_ZERO_THRESHOLD)
        bounds.extend(right_bounds)
    else:
        bounds.append(math.inf)
    return bounds


def _need_filter(cnt_in_bin: Sequence[int], total_cnt: int, filter_cnt: int,
                 bin_type: str) -> bool:
    """True if no split of this feature can satisfy min-data on both sides
    (reference NeedFilter, bin.cpp:50-72)."""
    if bin_type == BIN_NUMERICAL:
        s = 0
        for c in list(cnt_in_bin)[:-1]:
            s += c
            if s >= filter_cnt and total_cnt - s >= filter_cnt:
                return False
        return True
    if len(cnt_in_bin) <= 2:
        for c in list(cnt_in_bin)[:-1]:
            if c >= filter_cnt and total_cnt - c >= filter_cnt:
                return False
        return True
    return False


class BinMapper:
    """Per-feature value->bin mapping (reference BinMapper, bin.h:100+)."""

    def __init__(self) -> None:
        self.num_bin: int = 1
        self.missing_type: str = MISSING_NONE
        self.is_trivial: bool = True
        self.sparse_rate: float = 1.0
        self.bin_type: str = BIN_NUMERICAL
        self.bin_upper_bound: np.ndarray = np.array([math.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0

    # ------------------------------------------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int,
                 max_bin: int, min_data_in_bin: int = 3,
                 min_split_data: int = 20, bin_type: str = BIN_NUMERICAL,
                 use_missing: bool = True,
                 zero_as_missing: bool = False) -> "BinMapper":
        """Learn the binning from sampled values (reference FindBin,
        bin.cpp:217-419). `values` holds the sampled NON-ZERO entries;
        zeros are implied by `total_sample_cnt - len(values)`."""
        values = np.asarray(values, dtype=np.float64)
        nan_mask = np.isnan(values)
        na_cnt = int(nan_mask.sum())
        values = values[~nan_mask]
        if not use_missing:
            self.missing_type = MISSING_NONE
            na_cnt = 0
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            self.missing_type = MISSING_NONE if na_cnt == 0 else MISSING_NAN
        if not use_missing:
            pass
        n_values = len(values)
        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - n_values - na_cnt)

        if bin_type == BIN_NUMERICAL:
            native_bounds = self._native_numerical_bounds(
                values, total_sample_cnt, na_cnt, max_bin, min_data_in_bin)
            if native_bounds is not None:
                return self._finish_numerical(values, native_bounds,
                                              total_sample_cnt, na_cnt,
                                              zero_cnt, min_split_data)

        # distinct values with zero spliced into sorted order
        values = np.sort(values, kind="stable")
        distinct: List[float] = []
        counts: List[int] = []
        if n_values == 0 or (values[0] > 0.0 and zero_cnt > 0):
            distinct.append(0.0)
            counts.append(zero_cnt)
        if n_values > 0:
            distinct.append(float(values[0]))
            counts.append(1)
        for i in range(1, n_values):
            prev, cur = float(values[i - 1]), float(values[i])
            if not _le_ordered(prev, cur):
                # strictly greater beyond one ulp: a new distinct value
                if prev < 0.0 and cur > 0.0:
                    distinct.append(0.0)
                    counts.append(zero_cnt)
                distinct.append(cur)
                counts.append(1)
            else:
                # equal within one ulp: merge, keep the larger value
                distinct[-1] = cur
                counts[-1] += 1
        if n_values > 0 and values[-1] < 0.0 and zero_cnt > 0:
            distinct.append(0.0)
            counts.append(zero_cnt)

        self.min_val = distinct[0] if distinct else 0.0
        self.max_val = distinct[-1] if distinct else 0.0
        dv = np.asarray(distinct, dtype=np.float64)
        cv = np.asarray(counts, dtype=np.int64)
        cnt_in_bin: List[int] = []

        if bin_type == BIN_NUMERICAL:
            if self.missing_type == MISSING_ZERO:
                bounds = _find_bin_zero_as_one(dv, cv, max_bin,
                                               total_sample_cnt, min_data_in_bin)
                if len(bounds) == 2:
                    self.missing_type = MISSING_NONE
            elif self.missing_type == MISSING_NONE:
                bounds = _find_bin_zero_as_one(dv, cv, max_bin,
                                               total_sample_cnt, min_data_in_bin)
            else:  # NaN bin appended last
                bounds = _find_bin_zero_as_one(dv, cv, max_bin - 1,
                                               total_sample_cnt - na_cnt,
                                               min_data_in_bin)
                bounds.append(math.nan)
            self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
            self.num_bin = len(bounds)
            cnt_in_bin = [0] * self.num_bin
            i_bin = 0
            for i in range(len(dv)):
                while dv[i] > self.bin_upper_bound[i_bin]:
                    i_bin += 1
                cnt_in_bin[i_bin] += int(cv[i])
            if self.missing_type == MISSING_NAN:
                cnt_in_bin[self.num_bin - 1] = na_cnt
            assert self.num_bin <= max_bin
        else:
            # categorical: merge as ints, negatives count as NaN
            di: List[int] = []
            ci: List[int] = []
            for v, c in zip(distinct, counts):
                iv = int(v)
                if iv < 0:
                    na_cnt += int(c)
                elif di and iv == di[-1]:
                    ci[-1] += int(c)
                else:
                    di.append(iv)
                    ci.append(int(c))
            self.num_bin = 0
            rest_cnt = total_sample_cnt - na_cnt
            self.categorical_2_bin = {}
            self.bin_2_categorical = []
            cnt_in_bin = []
            if rest_cnt > 0:
                order = np.argsort(np.asarray(ci), kind="stable")[::-1]
                di2 = [di[i] for i in order]
                ci2 = [ci[i] for i in order]
                # bin 0 must not hold category 0 (default_bin must be > 0)
                if di2 and di2[0] == 0:
                    if len(ci2) == 1:
                        ci2.append(0)
                        di2.append(di2[0] + 1)
                    di2[0], di2[1] = di2[1], di2[0]
                    ci2[0], ci2[1] = ci2[1], ci2[0]
                cut_cnt = int((total_sample_cnt - na_cnt) * 0.99)
                used_cnt = 0
                eff_max_bin = min(len(di2), max_bin)
                cur_cat = 0
                while cur_cat < len(di2) and (used_cnt < cut_cnt
                                              or self.num_bin < eff_max_bin):
                    if ci2[cur_cat] < min_data_in_bin and cur_cat > 1:
                        break
                    self.bin_2_categorical.append(di2[cur_cat])
                    self.categorical_2_bin[di2[cur_cat]] = self.num_bin
                    used_cnt += ci2[cur_cat]
                    cnt_in_bin.append(ci2[cur_cat])
                    self.num_bin += 1
                    cur_cat += 1
                if cur_cat == len(di2) and na_cnt > 0:
                    self.bin_2_categorical.append(-1)
                    self.categorical_2_bin[-1] = self.num_bin
                    cnt_in_bin.append(0)
                    self.num_bin += 1
                if cur_cat == len(di2) and na_cnt == 0:
                    self.missing_type = MISSING_NONE
                elif na_cnt == 0:
                    self.missing_type = MISSING_ZERO
                else:
                    self.missing_type = MISSING_NAN
                if cnt_in_bin:
                    cnt_in_bin[-1] += total_sample_cnt - used_cnt

        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and _need_filter(
                cnt_in_bin, total_sample_cnt, min_split_data, bin_type):
            self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(0.0))
            if bin_type == BIN_CATEGORICAL:
                assert self.default_bin > 0
            self.sparse_rate = cnt_in_bin[self.default_bin] / max(
                total_sample_cnt, 1)
        else:
            self.sparse_rate = 1.0
        return self

    # ------------------------------------------------------------------
    def _native_numerical_bounds(self, values: np.ndarray,
                                 total_sample_cnt: int, na_cnt: int,
                                 max_bin: int, min_data_in_bin: int):
        """Numerical bin-boundary search through the C++ core
        (src/native/binning.cpp); None -> pure-Python path."""
        from ..native import find_bin_numerical, native_available
        if not native_available():
            return None
        if self.missing_type == MISSING_NAN:
            bounds = find_bin_numerical(values, total_sample_cnt - na_cnt,
                                        max_bin - 1, min_data_in_bin)
            if bounds is None:
                return None
            return np.concatenate([bounds, [math.nan]])
        bounds = find_bin_numerical(values, total_sample_cnt, max_bin,
                                    min_data_in_bin)
        if bounds is None:
            return None
        if self.missing_type == MISSING_ZERO and len(bounds) == 2:
            self.missing_type = MISSING_NONE
        return bounds

    def _finish_numerical(self, values: np.ndarray, bounds: np.ndarray,
                          total_sample_cnt: int, na_cnt: int, zero_cnt: int,
                          min_split_data: int) -> "BinMapper":
        """Populate mapper state from computed bounds (shared tail of the
        native numerical path): bin counts via vectorized searchsorted
        replace the Python distinct-walk."""
        self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
        self.num_bin = len(bounds)
        if len(values):
            self.min_val = float(values.min())
            self.max_val = float(values.max())
            if zero_cnt > 0:
                self.min_val = min(self.min_val, 0.0)
                self.max_val = max(self.max_val, 0.0)
        else:
            self.min_val = self.max_val = 0.0
        r = self.num_bin - 1 - (1 if self.missing_type == MISSING_NAN else 0)
        idx = np.searchsorted(self.bin_upper_bound[:r], values, side="left")
        cnt_in_bin = np.bincount(idx, minlength=self.num_bin).astype(np.int64)
        zero_bin = int(np.searchsorted(self.bin_upper_bound[:r], 0.0,
                                       side="left"))
        cnt_in_bin[zero_bin] += zero_cnt
        if self.missing_type == MISSING_NAN:
            cnt_in_bin[self.num_bin - 1] = na_cnt
        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and _need_filter(
                cnt_in_bin.tolist(), total_sample_cnt, min_split_data,
                BIN_NUMERICAL):
            self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(0.0))
            self.sparse_rate = float(cnt_in_bin[self.default_bin]) / max(
                total_sample_cnt, 1)
        else:
            self.sparse_rate = 1.0
        return self

    def value_to_bin(self, value: float) -> int:
        """Scalar value->bin (reference bin.h:461-497)."""
        return int(self.values_to_bins(np.asarray([value]))[0])

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin for a whole column."""
        values = np.asarray(values, dtype=np.float64)
        out = np.zeros(len(values), dtype=np.int32)
        nan_mask = np.isnan(values)
        if self.bin_type == BIN_NUMERICAL:
            v = np.where(nan_mask, 0.0, values)
            r = self.num_bin - 1
            if self.missing_type == MISSING_NAN:
                r -= 1
            # bin = first index with value <= upper_bound
            bounds = self.bin_upper_bound[:r]  # exclude last (inf / nan)
            out = np.searchsorted(bounds, v, side="left").astype(np.int32)
            # values equal to a bound belong to that bin (value <= bound)
            # searchsorted 'left' gives idx of first bound >= value: correct.
            if self.missing_type == MISSING_NAN:
                out[nan_mask] = self.num_bin - 1
        else:
            iv = np.where(nan_mask, -1, np.nan_to_num(values, nan=-1.0)).astype(
                np.int64)
            out = np.full(len(values), self.num_bin - 1, dtype=np.int32)
            if self.categorical_2_bin:
                cats = np.fromiter(self.categorical_2_bin.keys(), dtype=np.int64)
                bins = np.fromiter(self.categorical_2_bin.values(), dtype=np.int64)
                sorter = np.argsort(cats)
                cats_sorted, bins_sorted = cats[sorter], bins[sorter]
                pos = np.searchsorted(cats_sorted, iv)
                pos = np.clip(pos, 0, len(cats_sorted) - 1)
                hit = (cats_sorted[pos] == iv) & (iv >= 0)
                out[hit] = bins_sorted[pos[hit]].astype(np.int32)
        return out

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative real value for a bin (reference BinToValue,
        used for model-text thresholds)."""
        if self.bin_type == BIN_NUMERICAL:
            return float(self.bin_upper_bound[bin_idx])
        return float(self.bin_2_categorical[bin_idx])

    # serialization for distributed bin sync & binary dataset files
    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_type": self.bin_type,
            "bin_upper_bound": [repr(float(x)) for x in self.bin_upper_bound],
            "bin_2_categorical": list(self.bin_2_categorical),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.missing_type = d["missing_type"]
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_type = d["bin_type"]
        m.bin_upper_bound = np.asarray([float(x) for x in d["bin_upper_bound"]])
        m.bin_2_categorical = [int(x) for x in d["bin_2_categorical"]]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        return m
