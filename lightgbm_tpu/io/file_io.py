"""Virtual file abstraction (reference `src/io/file_io.cpp`
VirtualFileReader / VirtualFileWriter and the HDFS build flag).

The reference routes every data/model file through a VirtualFile
interface so an HDFS backend can be compiled in; here the same seam is a
SCHEME REGISTRY: paths like ``hdfs://...``, ``gs://...`` or ``s3://...``
dispatch to a registered opener, plain paths use the local filesystem.
``fsspec`` is picked up automatically when importable (it is not baked
into the TPU image — the registry is the supported injection point):

    from lightgbm_tpu.io.file_io import register_filesystem
    register_filesystem("hdfs", my_opener)   # opener(path, mode) -> file

Callers (DatasetLoader, Dataset.save_binary/load_binary, model IO) go
through :func:`open_file` / :func:`exists`, so any registered filesystem
works for datasets, sidecars, and model files alike.
"""
from __future__ import annotations

import inspect
import os
from typing import Callable, Dict

_SCHEMES: Dict[str, Callable] = {}


def register_filesystem(scheme: str, opener: Callable) -> None:
    """Register ``opener(path, mode) -> file object`` for a URI scheme."""
    _SCHEMES[scheme.lower()] = opener


def _scheme_of(path: str) -> str:
    if "://" in str(path):
        return str(path).split("://", 1)[0].lower()
    return ""


_FSSPEC_SCHEMES = ("hdfs", "gs", "s3", "gcs", "abfs", "az")


def _fsspec_open(path: str, mode: str, **kw):
    try:
        import fsspec
    except Exception:
        # NOT FileNotFoundError: a missing backend is a configuration
        # error and must not be mistaken for a missing file (exists()
        # maps only FileNotFoundError to False)
        raise RuntimeError(
            f"path {path!r} uses a remote filesystem scheme but no opener "
            f"is registered for it (register_filesystem) and fsspec is "
            f"not installed")
    return fsspec.open(path, mode, **kw).open()


def _accepts_kwargs(opener: Callable, kw: Dict):
    """True/False when `opener`'s signature (does not) take every keyword
    in `kw`; None when the signature is not introspectable."""
    try:
        sig = inspect.signature(opener)
    except (TypeError, ValueError):
        return None     # not introspectable: caller falls back on retry
    params = sig.parameters.values()
    if any(p.kind == inspect.Parameter.VAR_KEYWORD for p in params):
        return True
    names = {p.name for p in params
             if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                           inspect.Parameter.KEYWORD_ONLY)}
    return all(k in names for k in kw)


def open_file(path: str, mode: str = "r", **kw):
    """Open a local or registered-remote file (reference VirtualFile
    factory, file_io.cpp:21-58). Decode kwargs (errors=, encoding=)
    forward to every backend."""
    scheme = _scheme_of(path)
    if scheme in _SCHEMES:
        opener = _SCHEMES[scheme]
        # kwarg support is detected from the signature, NOT by retrying
        # on TypeError: a TypeError raised inside the opener body must
        # propagate, and silently dropping decode kwargs (errors=,
        # encoding=) on a retry would mask real opener bugs. Openers
        # whose signature is not introspectable (C extensions) keep the
        # old retry behavior — there the ambiguity is unavoidable.
        if kw:
            ok = _accepts_kwargs(opener, kw)
            if ok is False:
                return opener(path, mode)
            if ok is None:
                try:
                    return opener(path, mode, **kw)
                except TypeError:
                    return opener(path, mode)
        return opener(path, mode, **kw)
    if scheme in _FSSPEC_SCHEMES:
        return _fsspec_open(path, mode, **kw)
    return open(path, mode, **kw)


def exists(path: str) -> bool:
    """True when the path opens. Only a missing file maps to False —
    auth/network errors from remote backends PROPAGATE so operators see
    the real failure, not a fake file-not-found."""
    if _scheme_of(path) == "":
        return os.path.isfile(path)
    try:
        open_file(path, "r").close()
        return True
    except FileNotFoundError:
        return False
