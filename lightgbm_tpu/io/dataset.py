"""Binned dataset container.

Re-creates the reference `Dataset` / `Metadata` / `DatasetLoader` roles
(`src/io/dataset.cpp`, `src/io/metadata.cpp`, `src/io/dataset_loader.cpp`) in a
TPU-first layout: instead of per-feature-group `Bin` objects with scatter-add
hot loops, the binned matrix is one dense `uint8[num_data, num_features]`
array destined for HBM, and histogramming is a batched one-hot contraction
(see `ops/histogram.py`).

Host-side responsibilities kept here: sampling for bin finding
(`DatasetLoader::SampleTextDataFromMemory`), per-feature BinMapper
construction (distributed bin-finding allgather seam included), metadata
(label/weight/query/init_score, `src/io/metadata.cpp`), and binary
save/load (`Dataset::SaveBinaryFile`, `dataset_loader.cpp:268`).
"""
from __future__ import annotations

import io
import json
import struct
import warnings
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..config import Config
from .binning import (BIN_CATEGORICAL, BIN_NUMERICAL, MISSING_NAN,
                      MISSING_NONE, MISSING_ZERO, BinMapper)

_BINARY_MAGIC = b"tpu_gbdt_dataset_v1\n"

_MISSING_CODE = {MISSING_NONE: 0, MISSING_ZERO: 1, MISSING_NAN: 2}
_BINTYPE_CODE = {BIN_NUMERICAL: 0, BIN_CATEGORICAL: 1}


class Metadata:
    """Labels, weights, query boundaries, init scores
    (reference `src/io/metadata.cpp`, `dataset.h:40-249`)."""

    def __init__(self, num_data: int) -> None:
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None
        self.query_weights: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label: Sequence[float]) -> None:
        arr = np.asarray(label, dtype=np.float32).reshape(-1)
        if len(arr) != self.num_data:
            raise ValueError(
                f"label length {len(arr)} != num_data {self.num_data}")
        self.label = arr

    def set_weight(self, weight: Optional[Sequence[float]]) -> None:
        if weight is None:
            self.weight = None
            return
        arr = np.asarray(weight, dtype=np.float32).reshape(-1)
        if len(arr) != self.num_data:
            raise ValueError(
                f"weight length {len(arr)} != num_data {self.num_data}")
        self.weight = arr

    def set_group(self, group: Optional[Sequence[int]]) -> None:
        """Accepts group sizes (LightGBM convention) or query boundaries."""
        if group is None:
            self.query_boundaries = None
            return
        arr = np.asarray(group, dtype=np.int64).reshape(-1)
        if arr.sum() == self.num_data:
            self.query_boundaries = np.concatenate(
                [[0], np.cumsum(arr)]).astype(np.int64)
        elif len(arr) > 0 and arr[0] == 0 and arr[-1] == self.num_data:
            self.query_boundaries = arr
        else:
            raise ValueError("group sizes do not sum to num_data")

    def set_init_score(self, init_score: Optional[Sequence[float]]) -> None:
        if init_score is None:
            self.init_score = None
            return
        arr = np.asarray(init_score, dtype=np.float64).reshape(-1)
        if len(arr) % self.num_data != 0:
            raise ValueError("init_score length must be a multiple of num_data")
        self.init_score = arr

    @property
    def num_queries(self) -> int:
        if self.query_boundaries is None:
            return 0
        return len(self.query_boundaries) - 1


def _cat_set_from(cfg, categorical_feature):
    """Union of the categorical_feature argument and the config string
    (reference config categorical_feature handling)."""
    cat_set = set(int(c) for c in (categorical_feature or []))
    if cfg.categorical_feature:
        for tok in str(cfg.categorical_feature).split(","):
            tok = tok.strip()
            if tok.startswith("name:"):
                continue
            if tok:
                cat_set.add(int(tok))
    return cat_set


def _finalize_used_features(self, cfg, f):
    """used-feature map + per-used monotone/penalty arrays (shared by the
    dense and sparse constructors)."""
    self.used_feature_map = np.full(f, -1, dtype=np.int32)
    used = [j for j in range(f) if not self.mappers[j].is_trivial]
    for col_idx, j in enumerate(used):
        self.used_feature_map[j] = col_idx
    self.real_feature_idx = np.asarray(used, dtype=np.int32)
    mono = np.zeros(f, dtype=np.int8)
    for i, v in enumerate(cfg.monotone_constraints[:f]):
        mono[i] = np.int8(v)
    self.monotone_constraints = mono[self.real_feature_idx] \
        if len(used) else np.zeros(0, dtype=np.int8)
    pen = np.ones(f, dtype=np.float64)
    for i, v in enumerate(cfg.feature_contri[:f]):
        pen[i] = float(v)
    self.feature_penalty = pen[self.real_feature_idx] \
        if len(used) else np.zeros(0, dtype=np.float64)
    for j in self.real_feature_idx:
        m = self.mappers[j]
        if m.bin_type == BIN_CATEGORICAL and m.num_bin > 256:
            warnings.warn(
                f"categorical feature {j} has {m.num_bin} bins; only the "
                "256 most frequent categories are split candidates "
                "(device bitset limit)")


class Dataset:
    """Host-side binned dataset (reference `Dataset`, `dataset.h:250+`).

    Attributes
    ----------
    bins : np.ndarray uint8/uint16 [num_data, num_used_features]
        Binned matrix, feature-minor. Uploaded once to HBM by the learner.
    mappers : list[BinMapper]
        One per ORIGINAL feature column (trivial features have
        ``is_trivial=True`` and no column in ``bins``).
    used_feature_map : np.ndarray int32 [num_total_features]
        original feature -> column in bins, or -1 if unused
        (reference ``used_feature_map_``).
    """

    def __init__(self) -> None:
        self.bundles = None
        self._dev_bins = None  # HBM copy left behind by streaming ingest
        self.num_data: int = 0
        self.num_total_features: int = 0
        self._bins: Optional[np.ndarray] = None
        # True when the host matrix was dropped after sharding (the
        # device shards are authoritative); reading `.bins` re-gathers
        self._bins_freed: bool = False
        self.mappers: List[BinMapper] = []
        self.used_feature_map: np.ndarray = np.zeros(0, dtype=np.int32)
        self.real_feature_idx: np.ndarray = np.zeros(0, dtype=np.int32)
        self.feature_names: List[str] = []
        self.metadata: Metadata = Metadata(0)
        self.max_bin: int = 255
        self.min_data_in_bin: int = 3
        self.use_missing: bool = True
        self.zero_as_missing: bool = False
        self.monotone_constraints: np.ndarray = np.zeros(0, dtype=np.int8)
        self.feature_penalty: np.ndarray = np.zeros(0, dtype=np.float64)

    # ------------------------------------------------------------------
    @property
    def bins(self) -> Optional[np.ndarray]:
        """Host binned matrix. After `shard()` / stream-to-shard ingest
        the host copy is freed (the per-device shards are authoritative);
        the first host-side read re-gathers it from the mesh — a
        correctness fallback, not a hot path."""
        if self._bins is None and self._bins_freed:
            self._bins = self._regather_bins()
            self._bins_freed = False
        return self._bins

    @bins.setter
    def bins(self, value) -> None:
        self._bins = value
        self._bins_freed = False

    def _regather_bins(self) -> np.ndarray:
        cache = getattr(self, "_shard_cache", None)
        if cache is None:
            raise RuntimeError(
                "binned matrix was freed but no shard cache exists to "
                "re-gather it from")
        full = np.asarray(cache["bins"])      # [nd*per_shard, U] gather
        return np.ascontiguousarray(full[:self.num_data])

    @property
    def num_features(self) -> int:
        """Number of used (non-trivial) features."""
        if self._bins is not None:
            return self._bins.shape[1]
        cache = getattr(self, "_shard_cache", None)
        if cache is not None:
            return int(cache["bins"].shape[1])
        return 0

    def feature_num_bin(self, sub_feature: int) -> int:
        return self.mappers[self.real_feature_idx[sub_feature]].num_bin

    def used_mappers(self) -> List[BinMapper]:
        return [self.mappers[i] for i in self.real_feature_idx]

    # ------------------------------------------------------------------
    @classmethod
    def from_matrix(cls, data: np.ndarray, label: Optional[Sequence] = None,
                    config: Optional[Config] = None,
                    weight: Optional[Sequence] = None,
                    group: Optional[Sequence] = None,
                    init_score: Optional[Sequence] = None,
                    feature_names: Optional[List[str]] = None,
                    categorical_feature: Optional[Sequence[int]] = None,
                    reference: Optional["Dataset"] = None) -> "Dataset":
        """Build a binned dataset from a dense float matrix (the analogue of
        `LGBM_DatasetCreateFromMat` -> `CostructFromSampleData`,
        `src/c_api.cpp` / `dataset_loader.cpp:535`).

        When `reference` is given, reuse its bin mappers so validation data
        aligns with the training set (reference
        `LoadFromFileAlignWithOtherDataset`, `dataset_loader.cpp:224`).
        """
        cfg = config or Config()
        data = np.asarray(data)
        if data.dtype not in (np.float32, np.float64):
            data = data.astype(np.float64)
        if data.ndim != 2:
            raise ValueError("data must be 2-D")
        n, f = data.shape
        self = cls()
        self.num_data = n
        self.num_total_features = f
        self.metadata = Metadata(n)
        self.max_bin = cfg.max_bin
        self.min_data_in_bin = cfg.min_data_in_bin
        self.use_missing = cfg.use_missing
        self.zero_as_missing = cfg.zero_as_missing
        self.feature_names = (list(feature_names) if feature_names
                              else [f"Column_{i}" for i in range(f)])

        cat_set = set(int(c) for c in (categorical_feature or []))
        if cfg.categorical_feature:
            for tok in str(cfg.categorical_feature).split(","):
                tok = tok.strip()
                if tok.startswith("name:"):
                    continue
                if tok:
                    cat_set.add(int(tok))

        if reference is not None:
            self.mappers = reference.mappers
            self.used_feature_map = reference.used_feature_map
            self.real_feature_idx = reference.real_feature_idx
            self.max_bin = reference.max_bin
            self.monotone_constraints = reference.monotone_constraints
            self.feature_penalty = reference.feature_penalty
            self.feature_names = reference.feature_names
        elif getattr(cfg, "is_parallel_find_bin", False):
            # --- distributed global-sync bin finding: per-shard sample
            #     contributions merged in block order (dist/binning.py);
            #     boundaries are bitwise-equal to the single-host path
            from ..dist import runtime as dist_runtime
            from ..dist.binning import find_bin_mappers_distributed
            self.mappers, sync_stats = find_bin_mappers_distributed(
                data, cfg, cat_set, dist_runtime.num_shards(cfg))
            self._bin_sync_ms = float(sync_stats["bin_sync_ms"])
            _finalize_used_features(self, cfg, f)
        else:
            # --- sample rows for bin finding (reference
            #     bin_construct_sample_cnt, dataset_loader.cpp:162+)
            rng = np.random.RandomState(cfg.data_random_seed)
            sample_cnt = min(n, max(cfg.bin_construct_sample_cnt, 1))
            if sample_cnt < n:
                sample_idx = np.sort(rng.choice(n, sample_cnt, replace=False))
                sample = data[sample_idx]
            else:
                sample = data
            self.mappers = []
            for j in range(f):
                col = np.asarray(sample[:, j], dtype=np.float64)
                # keep only non-zero entries; zeros are implied by count
                nonzero = col[~((col >= -1e-35) & (col <= 1e-35))]
                m = BinMapper()
                bt = BIN_CATEGORICAL if j in cat_set else BIN_NUMERICAL
                m.find_bin(nonzero, total_sample_cnt=len(col),
                           max_bin=cfg.max_bin,
                           min_data_in_bin=cfg.min_data_in_bin,
                           min_split_data=cfg.min_data_in_leaf,
                           bin_type=bt, use_missing=cfg.use_missing,
                           zero_as_missing=cfg.zero_as_missing)
                self.mappers.append(m)
            self.used_feature_map = np.full(f, -1, dtype=np.int32)
            used = [j for j in range(f) if not self.mappers[j].is_trivial]
            for col_idx, j in enumerate(used):
                self.used_feature_map[j] = col_idx
            self.real_feature_idx = np.asarray(used, dtype=np.int32)
            # monotone constraints / feature penalties follow original index
            mono = np.zeros(f, dtype=np.int8)
            for i, v in enumerate(cfg.monotone_constraints[:f]):
                mono[i] = np.int8(v)
            self.monotone_constraints = mono[self.real_feature_idx] \
                if len(used) else np.zeros(0, dtype=np.int8)
            pen = np.ones(f, dtype=np.float64)
            for i, v in enumerate(cfg.feature_contri[:f]):
                pen[i] = float(v)
            self.feature_penalty = pen[self.real_feature_idx] \
                if len(used) else np.zeros(0, dtype=np.float64)

        # --- full binned ingest
        used = self.real_feature_idx
        for j in used:
            m = self.mappers[j]
            if m.bin_type == BIN_CATEGORICAL and m.num_bin > 256:
                warnings.warn(
                    f"categorical feature {j} has {m.num_bin} bins; only the "
                    "256 most frequent categories are split candidates "
                    "(device bitset limit)")
        max_nb = max((self.mappers[j].num_bin for j in used), default=2)
        dtype = np.uint8 if max_nb <= 256 else np.uint16
        bins = self._native_bin_matrix(data, used, dtype)
        if bins is None:
            bins = np.empty((n, len(used)), dtype=dtype)
            for col_idx, j in enumerate(used):
                bins[:, col_idx] = self.mappers[j].values_to_bins(
                    np.asarray(data[:, j], dtype=np.float64)).astype(dtype)
        self.bins = bins
        self._maybe_bundle(cfg, reference)

        if label is not None:
            self.metadata.set_label(label)
        self.metadata.set_weight(weight)
        self.metadata.set_group(group)
        self.metadata.set_init_score(init_score)
        return self

    # ------------------------------------------------------------------
    @classmethod
    def from_sparse(cls, data, label: Optional[Sequence] = None,
                    config: Optional[Config] = None,
                    weight: Optional[Sequence] = None,
                    group: Optional[Sequence] = None,
                    init_score: Optional[Sequence] = None,
                    feature_names: Optional[List[str]] = None,
                    categorical_feature: Optional[Sequence[int]] = None,
                    reference: Optional["Dataset"] = None) -> "Dataset":
        """Build a binned dataset from a scipy CSR/CSC matrix WITHOUT a
        dense float intermediate (the reference's CSR/CSC ingest,
        `LGBM_DatasetCreateFromCSR/CSC`, c_api.h:52-256; our analogue of
        `PushOneRow` keeps only per-column nonzeros + the uint8 output).

        Bin finding runs on each column's nonzeros (zeros are implied by
        count, exactly like the dense path's zero filter); the full
        ingest scatters per-column nonzero bins over a zero-bin
        background, so peak memory is nnz + the uint8 binned matrix.
        """
        cfg = config or Config()
        csc = data.tocsc()
        n, f = csc.shape
        self = cls()
        self.num_data = n
        self.num_total_features = f
        self.metadata = Metadata(n)
        self.max_bin = cfg.max_bin
        self.min_data_in_bin = cfg.min_data_in_bin
        self.use_missing = cfg.use_missing
        self.zero_as_missing = cfg.zero_as_missing
        self.feature_names = (list(feature_names) if feature_names
                              else [f"Column_{i}" for i in range(f)])
        cat_set = _cat_set_from(cfg, categorical_feature)

        if reference is not None:
            self.mappers = reference.mappers
            self.used_feature_map = reference.used_feature_map
            self.real_feature_idx = reference.real_feature_idx
            self.max_bin = reference.max_bin
            self.monotone_constraints = reference.monotone_constraints
            self.feature_penalty = reference.feature_penalty
            self.feature_names = reference.feature_names
        else:
            rng = np.random.RandomState(cfg.data_random_seed)
            sample_cnt = min(n, max(cfg.bin_construct_sample_cnt, 1))
            srows = (np.sort(rng.choice(n, sample_cnt, replace=False))
                     if sample_cnt < n else None)
            self.mappers = []
            for j in range(f):
                lo, hi = csc.indptr[j], csc.indptr[j + 1]
                vals = np.asarray(csc.data[lo:hi], np.float64)
                if srows is not None:
                    rows_j = csc.indices[lo:hi]
                    sel = np.isin(rows_j, srows, assume_unique=False)
                    vals = vals[sel]
                vals = vals[~((vals >= -1e-35) & (vals <= 1e-35))]
                m = BinMapper()
                bt = BIN_CATEGORICAL if j in cat_set else BIN_NUMERICAL
                m.find_bin(vals, total_sample_cnt=sample_cnt,
                           max_bin=cfg.max_bin,
                           min_data_in_bin=cfg.min_data_in_bin,
                           min_split_data=cfg.min_data_in_leaf,
                           bin_type=bt, use_missing=cfg.use_missing,
                           zero_as_missing=cfg.zero_as_missing)
                self.mappers.append(m)
            _finalize_used_features(self, cfg, f)

        used = self.real_feature_idx
        max_nb = max((self.mappers[j].num_bin for j in used), default=2)
        dtype = np.uint8 if max_nb <= 256 else np.uint16
        bins = np.zeros((n, len(used)), dtype=dtype)
        for col_idx, j in enumerate(used):
            m = self.mappers[j]
            zero_bin = int(m.values_to_bins(np.zeros(1))[0])
            if zero_bin:
                bins[:, col_idx] = zero_bin
            lo, hi = csc.indptr[j], csc.indptr[j + 1]
            if hi > lo:
                nz_bins = m.values_to_bins(
                    np.asarray(csc.data[lo:hi], np.float64))
                bins[csc.indices[lo:hi], col_idx] = nz_bins.astype(dtype)
        self.bins = bins
        self._maybe_bundle(cfg, reference)
        if label is not None:
            self.metadata.set_label(label)
        self.metadata.set_weight(weight)
        self.metadata.set_group(group)
        self.metadata.set_init_score(init_score)
        return self

    # ------------------------------------------------------------------
    @classmethod
    def create_from_sample(cls, sample: np.ndarray, n_total: int,
                           config: Optional[Config] = None,
                           feature_names: Optional[List[str]] = None,
                           categorical_feature: Optional[Sequence[int]]
                           = None,
                           reference: Optional["Dataset"] = None,
                           alloc_bins: bool = True) -> "Dataset":
        """Streaming creation, step 1 of 3 (the reference's push-rows
        flow: `LGBM_DatasetCreateFromSampledColumn` + `PushRows`,
        c_api.h:52-256): bin mappers are found from a row SAMPLE, the
        binned matrix is preallocated for ``n_total`` rows, and callers
        fill it incrementally with :meth:`push_rows` before sealing the
        dataset with :meth:`finish_load`. Peak host memory is the sample
        plus the uint8 binned matrix — the full float matrix never
        exists.

        With ``reference`` the sample may be None: mappers are shared so
        a streamed validation set aligns with the training set.
        """
        cfg = config or Config()
        self = cls()
        self.num_data = int(n_total)
        self.metadata = Metadata(self.num_data)
        self.max_bin = cfg.max_bin
        self.min_data_in_bin = cfg.min_data_in_bin
        self.use_missing = cfg.use_missing
        self.zero_as_missing = cfg.zero_as_missing

        if reference is not None:
            f = reference.num_total_features
            self.num_total_features = f
            self.mappers = reference.mappers
            self.used_feature_map = reference.used_feature_map
            self.real_feature_idx = reference.real_feature_idx
            self.max_bin = reference.max_bin
            self.monotone_constraints = reference.monotone_constraints
            self.feature_penalty = reference.feature_penalty
            self.feature_names = reference.feature_names
        else:
            sample = np.asarray(sample, np.float64)
            f = sample.shape[1]
            self.num_total_features = f
            self.feature_names = (list(feature_names) if feature_names
                                  else [f"Column_{i}" for i in range(f)])
            cat_set = _cat_set_from(cfg, categorical_feature)
            self.mappers = []
            for j in range(f):
                col = sample[:, j]
                nonzero = col[~((col >= -1e-35) & (col <= 1e-35))]
                m = BinMapper()
                bt = BIN_CATEGORICAL if j in cat_set else BIN_NUMERICAL
                m.find_bin(nonzero, total_sample_cnt=len(col),
                           max_bin=cfg.max_bin,
                           min_data_in_bin=cfg.min_data_in_bin,
                           min_split_data=cfg.min_data_in_leaf,
                           bin_type=bt, use_missing=cfg.use_missing,
                           zero_as_missing=cfg.zero_as_missing)
                self.mappers.append(m)
            _finalize_used_features(self, cfg, f)

        used = self.real_feature_idx
        max_nb = max((self.mappers[j].num_bin for j in used), default=2)
        dtype = np.uint8 if max_nb <= 256 else np.uint16
        self._bins_dtype = dtype
        if alloc_bins:
            self.bins = np.zeros((self.num_data, len(used)), dtype=dtype)
        # else: stream-to-shard ingest — rows go straight to their owner
        # device's shard slice and the [n, U] host matrix never exists
        self._push_cfg = cfg
        self._push_ref = reference
        self._push_pos = 0
        self._push_label = None
        self._push_weight = None
        self._push_init = None
        return self

    def push_rows(self, data: np.ndarray, label=None, weight=None,
                  init_score=None) -> None:
        """Streaming creation, step 2: bin one chunk of raw rows into the
        preallocated matrix (reference `Dataset::PushOneRow` via
        `LGBM_DatasetPushRows`, c_api.h:199-226). Chunks arrive in row
        order; per-chunk label/weight/init_score slices ride along."""
        if getattr(self, "_push_pos", None) is None:
            raise RuntimeError(
                "push_rows requires a dataset made by create_from_sample")
        data = np.asarray(data)
        if data.dtype not in (np.float32, np.float64):
            data = data.astype(np.float64)
        k = data.shape[0]
        pos = self._push_pos
        if pos + k > self.num_data:
            raise ValueError(
                f"push_rows overflow: {pos + k} > n_total={self.num_data}")
        used = self.real_feature_idx
        dtype = self.bins.dtype
        chunk = self._native_bin_matrix(data, used, dtype)
        if chunk is None:
            chunk = np.empty((k, len(used)), dtype=dtype)
            for col_idx, j in enumerate(used):
                chunk[:, col_idx] = self.mappers[j].values_to_bins(
                    np.asarray(data[:, j], np.float64)).astype(dtype)
        self.bins[pos:pos + k] = chunk
        if label is not None:
            if self._push_label is None:
                self._push_label = np.zeros(self.num_data, np.float64)
            self._push_label[pos:pos + k] = np.asarray(label, np.float64)
        if weight is not None:
            if self._push_weight is None:
                self._push_weight = np.ones(self.num_data, np.float64)
            self._push_weight[pos:pos + k] = np.asarray(weight, np.float64)
        if init_score is not None:
            if self._push_init is None:
                self._push_init = np.zeros(self.num_data, np.float64)
            self._push_init[pos:pos + k] = np.asarray(init_score,
                                                      np.float64)
        self._push_pos = pos + k

    def push_binned_rows(self, binned: np.ndarray, label=None, weight=None,
                         init_score=None) -> None:
        """Streaming creation, step 2 (pre-binned variant): append a chunk
        that was ALREADY binned — the streaming ingest path
        (`io/stream.py`) bins each chunk on device and pulls back uint8
        rows, so the host never holds the raw float chunk AND its binned
        copy twice. Same ordering/sidecar contract as :meth:`push_rows`."""
        if getattr(self, "_push_pos", None) is None:
            raise RuntimeError(
                "push_binned_rows requires a dataset made by "
                "create_from_sample")
        binned = np.asarray(binned)
        k = binned.shape[0]
        pos = self._push_pos
        if pos + k > self.num_data:
            raise ValueError(
                f"push_binned_rows overflow: {pos + k} > "
                f"n_total={self.num_data}")
        if binned.shape[1] != self.bins.shape[1]:
            raise ValueError(
                f"push_binned_rows width {binned.shape[1]} != "
                f"{self.bins.shape[1]} used features")
        self.bins[pos:pos + k] = binned.astype(self.bins.dtype, copy=False)
        if label is not None:
            if self._push_label is None:
                self._push_label = np.zeros(self.num_data, np.float64)
            self._push_label[pos:pos + k] = np.asarray(label, np.float64)
        if weight is not None:
            if self._push_weight is None:
                self._push_weight = np.ones(self.num_data, np.float64)
            self._push_weight[pos:pos + k] = np.asarray(weight, np.float64)
        if init_score is not None:
            if self._push_init is None:
                self._push_init = np.zeros(self.num_data, np.float64)
            self._push_init[pos:pos + k] = np.asarray(init_score,
                                                      np.float64)
        self._push_pos = pos + k

    def push_meta_rows(self, k: int, label=None, weight=None,
                       init_score=None) -> None:
        """Streaming creation, step 2 (stream-to-shard variant): advance
        the push cursor and record the chunk's metadata WITHOUT a host
        bins write — the binned rows were appended directly into their
        owner device's shard slice (io/stream.ShardedAppender), so there
        is no host matrix to fill. Same ordering contract as
        :meth:`push_binned_rows`."""
        if getattr(self, "_push_pos", None) is None:
            raise RuntimeError(
                "push_meta_rows requires a dataset made by "
                "create_from_sample")
        k = int(k)
        pos = self._push_pos
        if pos + k > self.num_data:
            raise ValueError(
                f"push_meta_rows overflow: {pos + k} > "
                f"n_total={self.num_data}")
        if label is not None:
            if self._push_label is None:
                self._push_label = np.zeros(self.num_data, np.float64)
            self._push_label[pos:pos + k] = np.asarray(label, np.float64)
        if weight is not None:
            if self._push_weight is None:
                self._push_weight = np.ones(self.num_data, np.float64)
            self._push_weight[pos:pos + k] = np.asarray(weight, np.float64)
        if init_score is not None:
            if self._push_init is None:
                self._push_init = np.zeros(self.num_data, np.float64)
            self._push_init[pos:pos + k] = np.asarray(init_score,
                                                      np.float64)
        self._push_pos = pos + k

    def bins_dtype(self) -> Optional[np.dtype]:
        """dtype of the binned matrix WITHOUT materializing a freed host
        copy (gate checks on the distributed path must stay O(1))."""
        if self._bins is not None:
            return self._bins.dtype
        cache = getattr(self, "_shard_cache", None)
        if cache is not None:
            return np.dtype(cache["bins"].dtype)
        dt = getattr(self, "_bins_dtype", None)
        return np.dtype(dt) if dt is not None else None

    def attach_device_bins(self, dev_bins) -> None:
        """Adopt an HBM-resident copy of ``bins`` built during streaming
        ingest (io/stream.py) so the serial learner's first upload is a
        no-op. Invalidated whenever the host matrix is rewritten (EFB
        bundling, column merges)."""
        self._dev_bins = dev_bins

    def device_bins(self):
        """The HBM copy of ``bins``: the streamed buffer when one is
        attached and still valid, else a lazy upload of the host matrix."""
        if getattr(self, "_dev_bins", None) is None:
            import jax.numpy as jnp
            self._dev_bins = jnp.asarray(self.bins)
        return self._dev_bins

    def finish_load(self, group=None) -> "Dataset":
        """Streaming creation, step 3: seal the dataset (reference
        `Dataset::FinishLoad`, dataset.cpp:330): check the declared row
        count, attach metadata, and apply feature bundling."""
        pos = self._push_pos
        if pos != self.num_data:
            raise ValueError(
                f"finish_load: {pos} rows pushed, {self.num_data} declared")
        if self._push_label is not None:
            self.metadata.set_label(self._push_label)
        self.metadata.set_weight(self._push_weight)
        self.metadata.set_group(group)
        self.metadata.set_init_score(self._push_init)
        self._maybe_bundle(self._push_cfg, self._push_ref)
        self._push_cfg = self._push_ref = None
        self._push_pos = None
        self._push_label = self._push_weight = self._push_init = None
        return self

    # ------------------------------------------------------------------
    def _maybe_bundle(self, cfg, reference) -> None:
        """Exclusive Feature Bundling (reference dataset.cpp:68-213): the
        binned matrix shrinks to one storage column per bundle; the
        per-feature view is reconstructed on device (io/bundling.py)."""
        # every construct path funnels through here once bins are final:
        # register the binned matrix with the HBM accountant (the
        # closure reads live state, so the post-bundle shrink is what a
        # snapshot reports)
        from ..obs import memory as obs_memory
        # the closure reads RAW storage (`_bins`), never the property: a
        # freed-after-shard matrix must report 0 bytes, not silently
        # re-gather the full host copy on every accountant snapshot
        obs_memory.track(
            "dataset/bins", self,
            lambda d: 0 if d._bins is None else int(d._bins.nbytes))
        from .bundling import apply_bundles, plan_bundles
        if reference is not None:
            # valid sets reuse the training set's bundling so binned
            # matrices stay aligned
            self.bundles = getattr(reference, "bundles", None)
            if self.bundles is not None:
                used = self.real_feature_idx
                db = np.asarray([self.mappers[j].default_bin for j in used],
                                np.int32)
                self.bins = apply_bundles(self.bins, self.bundles, db)
                self._dev_bins = None  # streamed HBM copy is pre-bundle
            return
        self.bundles = None
        # Supported surface (v1): fused serial device learner with
        # pointwise non-renewal objectives — the paths whose histogram /
        # partition / traversal kernels understand the bundled layout.
        renew = {"regression_l1", "l1", "mae", "huber", "fair", "quantile",
                 "mape", "poisson", "gamma", "tweedie"}
        if (not getattr(cfg, "enable_bundle", True) or self._bins is None
                or self._bins.dtype != np.uint8 or self.num_features < 3
                or cfg.tree_learner != "serial"
                or str(cfg.boosting) not in ("gbdt", "goss")
                or str(cfg.objective) in renew
                # the host SerialTreeLearner reads per-FEATURE bins — its
                # split/histogram code has no bundled view
                or cfg.forces_host_learner):
            return
        used = self.real_feature_idx
        nb = np.asarray([self.mappers[j].num_bin for j in used], np.int32)
        db = np.asarray([self.mappers[j].default_bin for j in used],
                        np.int32)
        cats = any(self.mappers[j].bin_type == BIN_CATEGORICAL
                   for j in used)
        if cats:
            return    # categorical routing through bundles not supported
        info = plan_bundles(self.bins, nb, db,
                            float(getattr(cfg, "max_conflict_rate", 0.0)),
                            seed=cfg.data_random_seed)
        if info is None or info.num_groups > 0.75 * self.num_features:
            return    # not worth the indirection
        self.bundles = info
        self.bins = apply_bundles(self.bins, info, db)
        self._dev_bins = None  # streamed HBM copy is pre-bundle

    # ------------------------------------------------------------------
    def shard(self, mesh, axis_name: str = "data") -> Dict[str, Any]:
        """Mesh-sharded HBM placement of the binned matrix: contiguous row
        blocks per device via `NamedSharding` (the layout the data-parallel
        learner assumes, parallel/data_parallel.py). The placement is
        cached per mesh so the loader/CLI can shard EARLY and the learner
        reuses the same device buffers instead of re-uploading.

        Returns the cache dict: ``mesh``, ``axis_name``, ``nd``,
        ``per_shard``, ``pad_rows``, row-sharded ``bins`` and its
        column-sharded transpose ``bins_T``.
        """
        import math as _math

        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (tuple(int(d.id) for d in mesh.devices.flat), axis_name)
        cached = getattr(self, "_shard_cache", None)
        if cached is not None and cached["key"] == key:
            return cached
        if self.bins is None:
            raise ValueError("shard() needs a constructed dataset "
                             "(bins is None)")
        nd = int(mesh.devices.size)
        n = self.num_data
        per_shard = int(_math.ceil(n / nd))
        pad_rows = nd * per_shard - n
        bins_np = np.asarray(self.bins)
        if pad_rows:
            bins_np = np.pad(bins_np, ((0, pad_rows), (0, 0)))
        bins_sharded = jax.device_put(
            bins_np, NamedSharding(mesh, P(axis_name)))
        # transposed copy, row-sharded along its second axis, for the
        # contiguous split-column reads inside the tree build
        bins_t = jax.device_put(
            np.ascontiguousarray(bins_np.T),
            NamedSharding(mesh, P(None, axis_name)))
        cache = {"key": key, "mesh": mesh, "axis_name": axis_name,
                 "nd": nd, "per_shard": per_shard, "pad_rows": pad_rows,
                 "bins": bins_sharded, "bins_T": bins_t}
        self._shard_cache = cache
        self._register_shard_owners(cache)
        # the placement is complete and authoritative: drop the host
        # copy (it was doubling peak memory next to the device shards).
        # A later host-side read re-gathers through the `bins` property.
        self._bins = None
        self._bins_freed = True
        self._dev_bins = None
        return cache

    def _register_shard_owners(self, cache: Dict[str, Any]) -> None:
        """Per-device HBM owners for a freshly placed shard cache (each
        device holds per_shard rows of the binned matrix plus its slice
        of the transpose), and the `dist_shard` announcement."""
        nd = cache["nd"]
        per_shard = cache["per_shard"]
        dt = np.dtype(cache["bins"].dtype)
        per_dev = 2 * per_shard * int(cache["bins"].shape[1]) * dt.itemsize
        from ..obs import memory as obs_memory
        for i in range(nd):
            obs_memory.track(
                f"dist/shard_bytes/d{i}", self,
                lambda d, nb=per_dev, k=cache["key"]: (
                    nb if (getattr(d, "_shard_cache", None) is not None
                           and d._shard_cache["key"] == k) else 0))
        from ..utils import log
        log.event("dist_shard", shards=nd, rows_per_shard=per_shard,
                  pad_rows=cache["pad_rows"], bytes_per_device=per_dev,
                  bin_sync_ms=getattr(self, "_bin_sync_ms", None))

    def attach_shard_cache(self, cache: Dict[str, Any]) -> None:
        """Adopt a shard placement assembled by stream-to-shard ingest
        (io/stream.ShardedAppender.finish): the cache dict has exactly
        the shape `shard()` builds, so a later `shard(mesh)` call with
        the same mesh is a cache hit and the learner reuses the buffers
        the loader already filled. The host matrix never existed; the
        `bins` property re-gathers on demand if a host-side consumer
        asks."""
        self._shard_cache = cache
        self._register_shard_owners(cache)
        self._bins = None
        self._bins_freed = True
        self._dev_bins = None

    def _native_bin_matrix(self, data: np.ndarray, used: np.ndarray,
                           dtype) -> Optional[np.ndarray]:
        """Full-matrix ingest through the native OpenMP binner
        (src/native/binning.cpp lgbt_bin_matrix); None -> Python loop."""
        from ..native import bin_matrix, native_available
        if not native_available() or len(used) == 0:
            return None
        ms = [self.mappers[j] for j in used]
        bin_type = np.asarray([_BINTYPE_CODE[m.bin_type] for m in ms],
                              np.int32)
        missing = np.asarray([_MISSING_CODE[m.missing_type] for m in ms],
                             np.int32)
        num_bin = np.asarray([m.num_bin for m in ms], np.int32)
        bounds_list = [m.bin_upper_bound if m.bin_type == BIN_NUMERICAL
                       else np.zeros(0) for m in ms]
        bounds_off = np.concatenate(
            [[0], np.cumsum([len(b) for b in bounds_list])]).astype(np.int64)
        bounds = (np.concatenate(bounds_list) if bounds_list
                  else np.zeros(0))
        cats_list, cat_bins_list = [], []
        for m in ms:
            if m.bin_type == BIN_CATEGORICAL and m.categorical_2_bin:
                ck = np.fromiter(m.categorical_2_bin.keys(), np.int64)
                cv = np.fromiter(m.categorical_2_bin.values(), np.int64)
                order = np.argsort(ck)
                cats_list.append(ck[order])
                cat_bins_list.append(cv[order].astype(np.int32))
            else:
                cats_list.append(np.zeros(0, np.int64))
                cat_bins_list.append(np.zeros(0, np.int32))
        cats_off = np.concatenate(
            [[0], np.cumsum([len(c) for c in cats_list])]).astype(np.int64)
        cats = (np.concatenate(cats_list) if cats_list
                else np.zeros(0, np.int64))
        cat_bins = (np.concatenate(cat_bins_list) if cat_bins_list
                    else np.zeros(0, np.int32))
        return bin_matrix(data, np.asarray(used, np.int32), bin_type,
                          missing, num_bin, bounds, bounds_off,
                          cats.astype(np.int64), cat_bins, cats_off, dtype)

    # ------------------------------------------------------------------
    def subset(self, row_indices: np.ndarray) -> "Dataset":
        """Row subset sharing bin mappers (reference `Dataset::CopySubset`,
        used by `lgb.cv` fold construction)."""
        idx = np.asarray(row_indices, dtype=np.int64)
        out = Dataset()
        out.num_data = len(idx)
        out.num_total_features = self.num_total_features
        out.bins = None if self.bins is None else self.bins[idx]
        out.bundles = self.bundles
        out.mappers = self.mappers
        out.used_feature_map = self.used_feature_map
        out.real_feature_idx = self.real_feature_idx
        out.feature_names = self.feature_names
        out.max_bin = self.max_bin
        out.min_data_in_bin = self.min_data_in_bin
        out.use_missing = self.use_missing
        out.zero_as_missing = self.zero_as_missing
        out.monotone_constraints = self.monotone_constraints
        out.feature_penalty = self.feature_penalty
        out.metadata = Metadata(len(idx))
        if self.metadata.label is not None:
            out.metadata.label = self.metadata.label[idx]
        if self.metadata.weight is not None:
            out.metadata.weight = self.metadata.weight[idx]
        if self.metadata.init_score is not None:
            ns = len(self.metadata.init_score) // self.num_data
            out.metadata.init_score = self.metadata.init_score.reshape(
                ns, self.num_data)[:, idx].reshape(-1)
        # query boundaries cannot survive arbitrary subsetting; only keep if
        # the subset respects query blocks
        return out

    # ------------------------------------------------------------------
    def add_features_from(self, other: "Dataset") -> None:
        """Column-wise merge of another constructed dataset into this one
        (reference `Dataset::AddFeaturesFrom`, dataset.cpp:349-437 /
        python basic.py add_features_from, covered by the reference
        test_basic.py:96-219). Both datasets must hold the same rows;
        `other`'s metadata is discarded, its features are appended."""
        if self.num_data != other.num_data:
            raise ValueError(
                f"Cannot add features from a dataset with {other.num_data} "
                f"rows to one with {self.num_data} rows")
        self._dev_bins = None  # column merge rewrites the binned matrix
        off = self.num_total_features
        self.mappers = self.mappers + other.mappers
        self.feature_names = self.feature_names + other.feature_names
        self.num_total_features += other.num_total_features
        other_map = other.used_feature_map.copy()
        shift = self.num_features
        other_map[other_map >= 0] += shift
        self.used_feature_map = np.concatenate(
            [self.used_feature_map, other_map])
        self.real_feature_idx = np.concatenate(
            [self.real_feature_idx, other.real_feature_idx + off])
        if self.bins is None:
            self.bins = other.bins
        elif other.bins is not None:
            dtype = (np.uint16 if np.uint16 in (self.bins.dtype,
                                                other.bins.dtype)
                     else np.uint8)
            self.bins = np.concatenate(
                [self.bins.astype(dtype), other.bins.astype(dtype)], axis=1)
        self.monotone_constraints = np.concatenate(
            [self.monotone_constraints,
             other.monotone_constraints]).astype(np.int8)
        self.feature_penalty = np.concatenate(
            [self.feature_penalty, other.feature_penalty])

    # ------------------------------------------------------------------
    # binary serialization (reference Dataset::SaveBinaryFile /
    # DatasetLoader::LoadFromBinFile)
    def save_binary(self, path: str) -> None:
        header = {
            "num_data": self.num_data,
            "num_total_features": self.num_total_features,
            "max_bin": self.max_bin,
            "min_data_in_bin": self.min_data_in_bin,
            "use_missing": self.use_missing,
            "zero_as_missing": self.zero_as_missing,
            "feature_names": self.feature_names,
            "used_feature_map": self.used_feature_map.tolist(),
            "real_feature_idx": self.real_feature_idx.tolist(),
            "monotone": self.monotone_constraints.tolist(),
            "penalty": self.feature_penalty.tolist(),
            "mappers": [m.to_dict() for m in self.mappers],
            "bins_dtype": str(self.bins.dtype) if self.bins is not None else "",
            "bundles": (None if self.bundles is None else {
                "num_groups": int(self.bundles.num_groups),
                "col": self.bundles.col.tolist(),
                "off": self.bundles.off.tolist(),
                "packed": self.bundles.packed.tolist(),
                "group_num_bin": self.bundles.group_num_bin.tolist(),
            }),
            "has_label": self.metadata.label is not None,
            "has_weight": self.metadata.weight is not None,
            "has_query": self.metadata.query_boundaries is not None,
            "has_init_score": self.metadata.init_score is not None,
        }
        from .file_io import open_file
        with open_file(path, "wb") as fh:
            fh.write(_BINARY_MAGIC)
            hb = json.dumps(header).encode()
            fh.write(struct.pack("<q", len(hb)))
            fh.write(hb)
            if self.bins is not None:
                np.save(fh, self.bins, allow_pickle=False)
            for arr in (self.metadata.label, self.metadata.weight,
                        self.metadata.query_boundaries,
                        self.metadata.init_score):
                if arr is not None:
                    np.save(fh, arr, allow_pickle=False)

    @classmethod
    def load_binary(cls, path: str) -> "Dataset":
        from .file_io import open_file
        with open_file(path, "rb") as fh:
            magic = fh.read(len(_BINARY_MAGIC))
            if magic != _BINARY_MAGIC:
                raise ValueError(f"{path} is not a tpu_gbdt binary dataset")
            (hlen,) = struct.unpack("<q", fh.read(8))
            header = json.loads(fh.read(hlen).decode())
            self = cls()
            self.num_data = header["num_data"]
            self.num_total_features = header["num_total_features"]
            self.max_bin = header["max_bin"]
            self.min_data_in_bin = header["min_data_in_bin"]
            self.use_missing = header["use_missing"]
            self.zero_as_missing = header["zero_as_missing"]
            self.feature_names = header["feature_names"]
            self.used_feature_map = np.asarray(header["used_feature_map"],
                                               dtype=np.int32)
            self.real_feature_idx = np.asarray(header["real_feature_idx"],
                                               dtype=np.int32)
            self.monotone_constraints = np.asarray(header["monotone"],
                                                   dtype=np.int8)
            self.feature_penalty = np.asarray(header["penalty"])
            self.mappers = [BinMapper.from_dict(d) for d in header["mappers"]]
            bd = header.get("bundles")
            if bd is not None:
                from .bundling import BundleInfo
                self.bundles = BundleInfo(
                    num_groups=int(bd["num_groups"]),
                    col=np.asarray(bd["col"], np.int32),
                    off=np.asarray(bd["off"], np.int32),
                    packed=np.asarray(bd["packed"], bool),
                    group_num_bin=np.asarray(bd["group_num_bin"], np.int32))
            self.metadata = Metadata(self.num_data)
            if header["bins_dtype"]:
                self.bins = np.load(fh, allow_pickle=False)
            if header["has_label"]:
                self.metadata.label = np.load(fh, allow_pickle=False)
            if header["has_weight"]:
                self.metadata.weight = np.load(fh, allow_pickle=False)
            if header["has_query"]:
                self.metadata.query_boundaries = np.load(fh, allow_pickle=False)
            if header["has_init_score"]:
                self.metadata.init_score = np.load(fh, allow_pickle=False)
        return self

    # ------------------------------------------------------------------
    def feature_meta_arrays(self) -> Dict[str, np.ndarray]:
        """Per-used-feature metadata arrays consumed by the device split
        finder (`ops/split.py`)."""
        ms = self.used_mappers()
        fcount = len(ms)
        num_bin = np.asarray([m.num_bin for m in ms], dtype=np.int32)
        default_bin = np.asarray([m.default_bin for m in ms], dtype=np.int32)
        missing = np.asarray([_MISSING_CODE[m.missing_type] for m in ms],
                             dtype=np.int32)
        bin_type = np.asarray([_BINTYPE_CODE[m.bin_type] for m in ms],
                              dtype=np.int32)
        mono = (self.monotone_constraints.astype(np.int32)
                if len(self.monotone_constraints) == fcount
                else np.zeros(fcount, dtype=np.int32))
        penalty = (self.feature_penalty.astype(np.float32)
                   if len(self.feature_penalty) == fcount
                   else np.ones(fcount, dtype=np.float32))
        return {
            "num_bin": num_bin,
            "default_bin": default_bin,
            "missing_type": missing,
            "bin_type": bin_type,
            "monotone": mono,
            "penalty": penalty,
        }
