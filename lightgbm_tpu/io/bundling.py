"""Exclusive Feature Bundling — the reference's EFB
(`src/io/dataset.cpp:68-213` FindGroups/FastFeatureBundling,
`include/LightGBM/feature_group.h:21`) re-designed for the TPU layout.

Sparse features that are almost never non-default in the same row share
ONE uint8 storage column: feature i of a bundle owns the bundle-bin range
[off_i, off_i + num_bin_i - 1) holding its NON-default bins packed with
the default bin skipped; bundle bin 0 means "every member at its
default". Bundles are capped at 256 bins (the reference's GPU constraint,
`dataset.cpp:78,92-93` — the same cap keeps our one-hot histogram tiles
at one uint8 lane).

Unlike the reference's FeatureGroup (which owns Bin objects), the TPU
design keeps bundling a pure STORAGE + HISTOGRAM transform: the learner
still sees every original feature (split finding, model export and raw
prediction are unchanged); per-feature histograms are sliced out of the
bundle histogram on device, with the skipped default bin reconstructed
from leaf totals (the reference's FixHistogram, `dataset.cpp:928-947`).

Singleton groups keep their original column untouched (off = 0,
packed = False) so dense datasets pay nothing.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np


class BundleInfo(NamedTuple):
    """Bundling tables, all indexed by USED (virtual) feature."""
    num_groups: int
    col: np.ndarray        # i32[F] storage column of the feature
    off: np.ndarray        # i32[F] bundle-bin offset (0 = unpacked)
    packed: np.ndarray     # bool[F] True when default-skip packing applies
    group_num_bin: np.ndarray  # i32[G] total bins of each storage column


def find_groups(nondefault_masks: List[np.ndarray], num_bins: List[int],
                default_bins: List[int], max_error_cnt: int,
                max_group_bins: int = 256, seed: int = 0):
    """Greedy conflict-bounded grouping (reference `FindGroups`,
    dataset.cpp:68-138). nondefault_masks[i] is a bool sample mask of rows
    where feature i is non-default. Returns a list of feature-index
    groups."""
    order = np.argsort([-int(m.sum()) for m in nondefault_masks])
    rng = np.random.RandomState(seed)

    def run(order):
        groups: List[List[int]] = []
        marks: List[np.ndarray] = []
        conflict_cnt: List[int] = []
        group_bins: List[int] = []
        for fi in order:
            m = nondefault_masks[fi]
            nb = num_bins[fi] - 1          # packed width (default skipped)
            placed = False
            cand = [g for g in range(len(groups))
                    if group_bins[g] + nb <= max_group_bins]
            if len(cand) > 100:
                cand = list(rng.choice(cand, 100, replace=False))
            for g in cand:
                cnt = int((marks[g] & m).sum())
                if conflict_cnt[g] + cnt <= max_error_cnt:
                    groups[g].append(int(fi))
                    marks[g] |= m
                    conflict_cnt[g] += cnt
                    group_bins[g] += nb
                    placed = True
                    break
            if not placed:
                groups.append([int(fi)])
                marks.append(m.copy())
                conflict_cnt.append(0)
                group_bins.append(1 + nb)
        return groups

    g1 = run(order)
    g2 = run(rng.permutation(len(nondefault_masks)))
    return g1 if len(g1) <= len(g2) else g2


def plan_bundles(bins: np.ndarray, num_bins: np.ndarray,
                 default_bins: np.ndarray, max_conflict_rate: float,
                 sample_cnt: int = 50_000,
                 seed: int = 0) -> Optional[BundleInfo]:
    """Decide the bundling for a binned [N, F] matrix; None when bundling
    would not reduce the column count."""
    n, f = bins.shape
    if f < 3:
        return None
    rng = np.random.RandomState(seed)
    rows = (np.sort(rng.choice(n, sample_cnt, replace=False))
            if n > sample_cnt else np.arange(n))
    sample = bins[rows]
    masks = [sample[:, j] != default_bins[j] for j in range(f)]
    # only bundle genuinely sparse features; dense ones stay singleton
    # (the reference's sampled non-zero counts play the same role)
    sparse = [j for j in range(f)
              if masks[j].mean() < 0.5 and num_bins[j] <= 128]
    if len(sparse) < 2:
        return None
    max_err = int(max_conflict_rate * len(rows))
    groups = find_groups([masks[j] for j in sparse],
                         [int(num_bins[j]) for j in sparse],
                         [int(default_bins[j]) for j in sparse],
                         max_err, seed=seed)
    groups = [[sparse[i] for i in g] for g in groups]
    dense = [j for j in range(f) if j not in set(sparse)]
    all_groups = [[j] for j in dense] + groups
    if len(all_groups) >= f:
        return None
    col = np.zeros(f, np.int32)
    off = np.zeros(f, np.int32)
    packed = np.zeros(f, bool)
    gnb = np.zeros(len(all_groups), np.int32)
    for g, feats in enumerate(all_groups):
        if len(feats) == 1:
            j = feats[0]
            col[j] = g
            gnb[g] = num_bins[j]
            continue
        cur = 1                      # bundle bin 0 = all-default
        for j in feats:
            col[j] = g
            off[j] = cur
            packed[j] = True
            cur += int(num_bins[j]) - 1
        gnb[g] = cur
    return BundleInfo(num_groups=len(all_groups), col=col, off=off,
                      packed=packed, group_num_bin=gnb)


def apply_bundles(bins: np.ndarray, info: BundleInfo,
                  default_bins: np.ndarray) -> np.ndarray:
    """[N, F] -> [N, G] bundled storage. Conflicting rows (two members
    non-default) keep the LAST member's value, mirroring the reference's
    conflict-tolerant push (`dataset.cpp:140-213`)."""
    n, f = bins.shape
    out = np.zeros((n, info.num_groups), np.uint8)
    for j in range(f):
        g = info.col[j]
        b = bins[:, j].astype(np.int32)
        if not info.packed[j]:
            out[:, g] = b.astype(np.uint8)
            continue
        nd = b != default_bins[j]
        pb = info.off[j] + np.where(b > default_bins[j], b - 1, b)
        np.copyto(out[:, g], pb.astype(np.uint8), where=nd)
    return out


def unbundle_bin(bundle_bin, off, packed, default_bin, num_bin):
    """Inverse mapping for one feature: bundle-bin column value -> the
    feature's own bin. Single source of truth lives in
    ops/partition.bundle_unpack (the routing/traversal hot path); this
    NumPy-friendly alias delegates to it."""
    from ..ops.partition import bundle_unpack
    return np.asarray(bundle_unpack(jnp_compat(bundle_bin), off, packed,
                                    default_bin, num_bin))


def jnp_compat(x):
    import jax.numpy as jnp
    return jnp.asarray(x)


def expansion_map(info: BundleInfo, num_bins: np.ndarray,
                  default_bins: np.ndarray, b_cap: int):
    """(map_idx [F, b_cap] i32, default_mask [F, b_cap] bool) for the
    device-side histogram expansion: hist_f[b] = hist_flat[map_idx] when
    map_idx >= 0; entries with default_mask get leaf-total minus the
    feature's other bins (FixHistogram, dataset.cpp:928-947)."""
    f = len(info.col)
    map_idx = np.full((f, b_cap), -1, np.int32)
    dmask = np.zeros((f, b_cap), bool)
    for j in range(f):
        g = info.col[j]
        nb = int(num_bins[j])
        if not info.packed[j]:
            bs = np.arange(min(nb, b_cap))
            map_idx[j, bs] = g * b_cap + bs
            continue
        db = int(default_bins[j])
        for b in range(min(nb, b_cap)):
            if b == db:
                dmask[j, b] = True
            else:
                pb = info.off[j] + (b - 1 if b > db else b)
                map_idx[j, b] = g * b_cap + pb
    return map_idx, dmask
