"""Text parsers: CSV / TSV / LibSVM with format auto-detection.

Re-creates the reference parser layer (`src/io/parser.cpp`,
`src/io/parser.hpp`): `create_parser` sniffs a few lines to decide the
format (reference `Parser::CreateParser`, `parser.cpp:103-172`) and each
parser turns one line into ``(label, [(col, val), ...])`` sparse pairs
(reference `ParseOneLine`, `parser.hpp:30-129`).

The hot bulk path (`parse_dense`) vectorizes whole-file parsing with NumPy
instead of the reference's per-line OMP loop; a native C++ fast path can be
slotted underneath without changing callers.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

_NA_TOKENS = {"", "na", "nan", "null", "n/a", "none", "?"}


def _atof(tok: str) -> float:
    """Tolerant float parse (reference `Common::Atof`): NA tokens -> NaN."""
    tok = tok.strip()
    if tok.lower() in _NA_TOKENS:
        return math.nan
    try:
        return float(tok)
    except ValueError:
        return math.nan


class Parser:
    """Base parser: one line -> (label, sparse (col,val) pairs)."""

    def __init__(self, label_idx: int = 0):
        self.label_idx = label_idx

    def parse_one_line(self, line: str) -> Tuple[float, List[Tuple[int, float]]]:
        raise NotImplementedError

    def num_features(self, line: str) -> int:
        raise NotImplementedError


class _DelimitedParser(Parser):
    sep: str = "\t"

    def parse_one_line(self, line):
        toks = line.rstrip("\r\n").split(self.sep)
        label = 0.0
        pairs: List[Tuple[int, float]] = []
        col = 0
        for i, tok in enumerate(toks):
            v = _atof(tok)
            if i == self.label_idx:
                label = v
            else:
                pairs.append((col, v))
                col += 1
        return label, pairs

    def num_features(self, line):
        n = len(line.rstrip("\r\n").split(self.sep))
        return n - 1 if self.label_idx >= 0 else n


class TSVParser(_DelimitedParser):
    sep = "\t"


class CSVParser(_DelimitedParser):
    sep = ","


class SpaceParser(_DelimitedParser):
    sep = " "


class LibSVMParser(Parser):
    """``label idx:val idx:val ...``; absent indices are 0 (reference
    `parser.hpp:88-129`)."""

    def parse_one_line(self, line):
        toks = line.split()
        label = 0.0
        pairs: List[Tuple[int, float]] = []
        start = 0
        if self.label_idx >= 0 and toks and ":" not in toks[0]:
            label = _atof(toks[0])
            start = 1
        for tok in toks[start:]:
            if ":" not in tok:
                continue
            k, v = tok.split(":", 1)
            try:
                pairs.append((int(k), _atof(v)))
            except ValueError:
                continue
        return label, pairs

    def num_features(self, line):
        _, pairs = self.parse_one_line(line)
        return (max(c for c, _ in pairs) + 1) if pairs else 0


def detect_format(sample_lines: Sequence[str]) -> str:
    """Sniff the file format from a few lines (reference
    `Parser::CreateParser`, `src/io/parser.cpp:103-172`): colon pairs ->
    libsvm, else the delimiter that splits consistently across lines."""
    lines = [ln for ln in sample_lines if ln.strip()]
    if not lines:
        return "tsv"

    def is_libsvm(ln):
        toks = ln.split()
        pairs = [t for t in toks if ":" in t]
        return len(pairs) >= max(1, len(toks) - 1)

    if all(is_libsvm(ln) for ln in lines):
        return "libsvm"
    for name, sep in (("tsv", "\t"), ("csv", ","), ("space", " ")):
        counts = {len(ln.rstrip("\r\n").split(sep)) for ln in lines}
        if len(counts) == 1 and counts.pop() > 1:
            return name
    raise ValueError("Unknown data format: not CSV/TSV/LibSVM")


_PARSERS = {"tsv": TSVParser, "csv": CSVParser, "space": SpaceParser,
            "libsvm": LibSVMParser}


def create_parser(sample_lines: Sequence[str], label_idx: int = 0,
                  fmt: Optional[str] = None) -> Parser:
    fmt = fmt or detect_format(sample_lines)
    return _PARSERS[fmt](label_idx)


def parse_dense(lines: Sequence[str], parser: Parser,
                num_cols: Optional[int] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Bulk-parse lines into ``(labels [N], features [N, F])``.

    Delimited formats take a vectorized NumPy path; LibSVM falls back to
    the per-line parser (absent entries = 0.0, matching the reference's
    sparse semantics)."""
    lines = [ln for ln in lines if ln.strip()]
    n = len(lines)
    if n == 0:
        return np.zeros(0), np.zeros((0, num_cols or 0))
    if isinstance(parser, _DelimitedParser):
        sep = parser.sep
        first = lines[0].rstrip("\r\n").split(sep)
        ncol = len(first)
        flat = np.empty(n * ncol, dtype=np.float64)
        bad_rows = []
        try:
            for i, ln in enumerate(lines):
                toks = ln.rstrip("\r\n").split(sep)
                if len(toks) != ncol:
                    raise ValueError
                flat[i * ncol:(i + 1) * ncol] = toks
        except ValueError:
            # NA tokens or ragged rows: tolerant row-by-row path
            for i, ln in enumerate(lines):
                toks = ln.rstrip("\r\n").split(sep)
                row = [_atof(t) for t in toks[:ncol]]
                row += [math.nan] * (ncol - len(row))
                flat[i * ncol:(i + 1) * ncol] = row
            del bad_rows
        mat = flat.reshape(n, ncol)
        li = parser.label_idx
        if li >= 0 and ncol > 0:
            labels = mat[:, li].copy()
            feats = np.delete(mat, li, axis=1)
        else:
            labels = np.zeros(n)
            feats = mat
        return labels, feats
    # libsvm path
    if num_cols is None:
        num_cols = 0
        parsed = []
        for ln in lines:
            lab, pairs = parser.parse_one_line(ln)
            parsed.append((lab, pairs))
            if pairs:
                num_cols = max(num_cols, max(c for c, _ in pairs) + 1)
    else:
        parsed = [parser.parse_one_line(ln) for ln in lines]
    labels = np.zeros(n)
    feats = np.zeros((n, num_cols))
    for i, (lab, pairs) in enumerate(parsed):
        labels[i] = lab
        for c, v in pairs:
            if c < num_cols:
                feats[i, c] = v
    return labels, feats
