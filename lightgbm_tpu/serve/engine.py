"""Compiled, cached, shape-bucketed batch inference for forest scoring.

The training-era `TreePredictor` re-stacks the forest on host per call and
walks trees one at a time (`lax.scan` + per-tree `while_loop`). This engine
is the serving path the reference covers with `Predictor` /
`Tree::AddPredictionToScore` (predictor.hpp:66-115, tree.cpp:112-204):

* the stacked forest lives on device and is reused across calls — appended
  trees are stacked incrementally and concatenated on device instead of
  re-uploading the whole forest;
* traversal is depth-synchronized: a `[T, N]` node frontier advances one
  level per step for ALL trees at once (`fori_loop` over the forest's exact
  max depth), and the leaf-value gather + per-class accumulation fuse into
  the same jit;
* batch shapes are bucketed to powers of two (and large batches chunked to
  a fixed row count), so repeated predicts with varying N reuse one
  compiled program per bucket.

Raw-feature mode compares f64 thresholds exactly WITHOUT enabling jax x64:
doubles are encoded host-side into monotonic uint64 total-order keys split
into two uint32 planes, so `x <= t` becomes a two-limb unsigned compare.
Leaf routing is therefore bit-exact vs the host f64 walk
(`predict_raw_values`); only the final leaf-value sum runs in f32.

Two serving-density extensions ride on the same traversal:

* **compact dtype plans** (``compact="f16"/"int8"``): thresholds stored
  as f16 (or per-feature affine int8, the `ops/histogram.quantize_gh`
  per-column scale discipline applied to split thresholds), leaf values
  as f16 de-quantized to f32 on output, and the int32 topology arrays
  (children / split features) narrowed to int16. Routing then compares
  f32 values against the de-quantized threshold instead of the exact
  key planes, so compact engines are gated behind a parity check
  against the f64 oracle (serving/registry.py) — never silently wrong;
* **AOT artifacts** (serve/aot.py): the bucketed traversal program can
  be `jax.export`ed ahead of time and re-attached in a fresh process
  (``attach_aot``), so the first scored request performs zero new jax
  traces — `compile_cache.note_trace` is the probe (every `_run` body
  bumps it; a deserialized artifact call never runs the body).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import compile_cache
from ..models.tree import Tree
from ..ops.predict import stack_trees

__all__ = ["ForestEngine", "stack_forest", "compact_stack",
           "COMPACT_PLANS"]


# ---------------------------------------------------------------------------
# f64 total-order key encoding (host side, exact)

def _f64_key_u64(a: np.ndarray) -> np.ndarray:
    """Map float64 -> uint64 preserving numeric order: flip the sign bit for
    non-negatives, bit-complement negatives. -0.0 must be normalized to
    +0.0 by the caller; NaN must be masked out beforehand."""
    b = np.ascontiguousarray(a, np.float64).view(np.int64)
    ub = b.astype(np.uint64)
    return np.where(b >= 0, ub + np.uint64(1 << 63), ~ub)


def _f64_key_planes(a: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    u = _f64_key_u64(a)
    return ((u >> np.uint64(32)).astype(np.uint32),
            (u & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def _scalar_key(v: float) -> Tuple[int, int]:
    hi, lo = _f64_key_planes(np.array([v], np.float64))
    return int(hi[0]), int(lo[0])


# |fv| <= 1e-35 (the reference kZeroThreshold test, tree.h:216-270) in key
# space: key(-1e-35) <= key(fv) <= key(+1e-35)
_KZP = _scalar_key(1e-35)
_KZN = _scalar_key(-1e-35)


def _key_le(ah, al, bh, bl):
    return (ah < bh) | ((ah == bh) & (al <= bl))


def _pow2_ceil(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length()


def _pow2_floor(x: int) -> int:
    return 1 << (max(int(x), 1).bit_length() - 1)


# ---------------------------------------------------------------------------
# forest stacking

def stack_forest(trees: List[Tree], num_class: int = 1,
                 binned: bool = False,
                 class_offset: int = 0) -> Dict[str, object]:
    """Host-side stacking for the serve engine: `stack_trees` plus the
    per-tree class assignment, f32 leaf values, and (raw mode) the uint32
    threshold key planes."""
    stk = stack_trees(trees, binned=binned)
    t_count = len(trees)
    stk["tree_class"] = ((np.arange(t_count, dtype=np.int32) + class_offset)
                         % max(num_class, 1))
    stk["leaf_value_f32"] = stk["leaf_value"].astype(np.float32)
    if not binned:
        thr = stk["threshold"]
        thr = np.where(thr == 0.0, 0.0, thr)      # -0.0 -> +0.0
        stk["thr_hi"], stk["thr_lo"] = _f64_key_planes(thr)
    stk["has_cat"] = bool(np.any(stk["cat_len"] > 0))
    return stk


COMPACT_PLANS = ("off", "f16", "int8")


def _narrow_i16(a: np.ndarray) -> np.ndarray:
    """int16 when the values fit, else the array unchanged (a >32k-leaf
    tree or a >32k-word cat bitset keeps exact int32 addressing)."""
    if a.size and np.int64(a.min()) >= -32768 and np.int64(a.max()) <= 32767:
        return a.astype(np.int16)
    return a


def compact_stack(host: Dict[str, object], plan: str) -> Dict[str, object]:
    """Rewrite a raw-mode host stack (`stack_forest` output) under a
    compact dtype plan.

    ``f16``: thresholds as float16, compared in f32 after upcast.
    ``int8``: per-feature affine quantization — for feature ``j`` with
    numerical-split thresholds ``ts``, ``off = mid(ts)`` and ``scale =
    range(ts) / 254`` (the `quantize_gh` per-column absmax/qmax scale
    discipline, recentered), so a feature whose thresholds span <= 254
    distinct affine steps round-trips near-exactly. Both plans store
    leaf values as f16 (de-quantized to f32 at the output gather) and
    narrow the int32 topology arrays to int16. Exactness is NOT
    promised — the serving registry's parity gate is the contract.
    """
    if plan not in ("f16", "int8"):
        raise ValueError(f"unknown compact plan {plan!r}")
    out = dict(host)
    for key in ("split_feature", "left_child", "right_child",
                "cat_start", "cat_len"):
        out[key] = _narrow_i16(np.asarray(host[key]))
    thr = np.asarray(host["threshold"], np.float64)
    if plan == "f16":
        out["thr_f16"] = thr.astype(np.float16)
    else:
        sf = np.asarray(host["split_feature"], np.int64)
        dt = np.asarray(host["decision_type"], np.int32)
        nl = np.asarray(host["num_leaves"], np.int32)
        m = thr.shape[1]
        # only real numerical internal nodes feed the per-feature
        # stats: zero-padding rows and categorical nodes would drag
        # feature 0's range toward 0.0 for nothing (their threshold is
        # never compared)
        valid = (np.arange(m, dtype=np.int32)[None, :]
                 < np.maximum(nl[:, None] - 1, 0)) & ((dt & 1) == 0)
        nfeat = int(sf.max()) + 1 if sf.size else 1
        t_lo = np.full(nfeat, np.inf)
        t_hi = np.full(nfeat, -np.inf)
        np.minimum.at(t_lo, sf[valid], thr[valid])
        np.maximum.at(t_hi, sf[valid], thr[valid])
        unused = ~np.isfinite(t_lo)
        t_lo[unused] = 0.0
        t_hi[unused] = 0.0
        off = (t_lo + t_hi) / 2.0
        scale = np.maximum((t_hi - t_lo) / 254.0, 1e-30)
        q = np.clip(np.rint((thr - off[sf]) / scale[sf]), -127, 127)
        out["thr_q"] = q.astype(np.int8)
        out["thr_scale"] = scale.astype(np.float32)
        out["thr_off"] = off.astype(np.float32)
    out["leaf_value_f16"] = np.asarray(host["leaf_value"],
                                       np.float64).astype(np.float16)
    return out


_DEVICE_KEYS_RAW = ("split_feature", "decision_type", "left_child",
                    "right_child", "thr_hi", "thr_lo", "cat_start",
                    "cat_len", "cat_words", "leaf_value_f32", "num_leaves",
                    "tree_class")
_DEVICE_KEYS_COMPACT_COMMON = (
    "split_feature", "decision_type", "left_child", "right_child",
    "cat_start", "cat_len", "cat_words", "leaf_value_f16", "num_leaves",
    "tree_class")
_DEVICE_KEYS_COMPACT = {
    "f16": _DEVICE_KEYS_COMPACT_COMMON + ("thr_f16",),
    "int8": _DEVICE_KEYS_COMPACT_COMMON + ("thr_q", "thr_scale",
                                           "thr_off"),
}
# what the same stacked forest costs under compact=off, per element of
# each raw-plan array (f32_device_bytes reports the counterfactual so
# the registry/exporter can say how many bytes a compact plan saved)
_RAW_PLAN_ITEMSIZE = {
    "split_feature": 4, "decision_type": 1, "left_child": 4,
    "right_child": 4, "thr_hi": 4, "thr_lo": 4, "cat_start": 4,
    "cat_len": 4, "cat_words": 4, "leaf_value_f32": 4, "num_leaves": 4,
    "tree_class": 4,
}
_DEVICE_KEYS_BINNED = ("split_feature", "decision_type", "left_child",
                       "right_child", "threshold_in_bin", "default_bin",
                       "num_bin", "cat_start", "cat_len", "cat_words",
                       "leaf_value_f32", "num_leaves", "tree_class")

# packed-route fast path: total decision-table elements (T * nodes * bins)
# above this are not worth the host build / device memory
_ROUTE_TABLE_MAX = 1 << 24
_ROUTE_CHUNK = 256          # microchunk rows; keeps the [T, C] frontier in cache


def _build_packed_route(host: Dict[str, object]) -> Optional[Dict[str, object]]:
    """Precompute, per (tree, node, bin value), the packed int32
    ``(next_slot << k) | split_feature[next_slot]`` so binned traversal is
    two gathers per level (bin lookup + route lookup) instead of eight.

    A node's binned decision — numerical compare, categorical bitset, and
    the missing-bin default — is a pure function of (node, bin value), so
    the whole decision collapses into this table. Slots [0, m) are internal
    nodes; slot m + j is leaf j and routes to itself, so the frontier needs
    no done-row masking — every row just keeps gathering until the loop
    bound. Returns None when the forest doesn't fit the packing (table too
    large, or the slot/feature ids overflow the 31-bit pack)."""
    lc = host["left_child"]
    rc = host["right_child"]
    t_count, m = lc.shape
    leaves = int(host["leaf_value"].shape[1])
    slots = m + leaves
    nbin = host["num_bin"]
    b = int(max(int(nbin.max()) if nbin.size else 0, 2))
    sf = host["split_feature"]
    f_hi = int(sf.max()) + 1 if sf.size else 1
    k = max(int(f_hi - 1).bit_length(), 1)
    if t_count * slots * b > _ROUTE_TABLE_MAX \
            or ((slots + 1) << k) >= (1 << 30):
        return None
    v = np.arange(b, dtype=np.int32)[None, None, :]
    dt = host["decision_type"].astype(np.int32)[:, :, None]
    mt = (dt >> 2) & 3
    default_left = (dt & 2) != 0
    is_default = np.where(mt == 1, v == host["default_bin"][:, :, None],
                          np.where(mt == 2,
                                   v == host["num_bin"][:, :, None] - 1,
                                   False))
    go = np.where(is_default, default_left,
                  v <= host["threshold_in_bin"][:, :, None])
    cat = (dt & 1) != 0
    if cat.any():
        cwords = np.asarray(host["cat_words"], np.uint32)
        widx = host["cat_start"][:, :, None] + (v >> 5)
        w = cwords[np.clip(widx, 0, len(cwords) - 1)]
        cat_go = (((w >> (v & 31).astype(np.uint32)) & 1) != 0) \
            & ((v >> 5) < host["cat_len"][:, :, None])
        go = np.where(cat, cat_go, go)
    nxt = np.where(go, lc[:, :, None], rc[:, :, None]).astype(np.int32)
    # stumps never leave the (zero-filled) root row: send them to leaf 0
    nxt[host["num_leaves"] <= 1] = -1
    slot = np.where(nxt >= 0, nxt, m + ~nxt)
    feat_next = np.where(
        nxt >= 0,
        np.take_along_axis(sf, np.maximum(nxt.reshape(t_count, -1), 0),
                           axis=1).reshape(t_count, m, b),
        0)
    packed = np.empty((t_count, slots, b), np.int32)
    packed[:, :m] = (slot << k) | feat_next
    # leaf slots are fixed points (feature 0 — the gathered bin is unused)
    packed[:, m:] = (np.arange(m, slots, dtype=np.int32)
                     << k)[None, :, None]
    return {
        "packed": packed.reshape(-1),
        "root_sf": sf[:, 0].astype(np.int32),
        "bins": b, "kbits": k, "slots": slots, "leaf_base": m,
    }


class ForestEngine:
    """Device-resident forest + bucketed jit cache for batch scoring.

    `mode="raw"` scores float feature matrices with exact f64 routing;
    `mode="binned"` scores pre-binned uint8 matrices (no EFB bundle — use
    the training-side `TreePredictor` for bundled replay).
    """

    def __init__(self, trees: List[Tree], num_class: int = 1,
                 mode: str = "raw", chunk_rows: Optional[int] = None,
                 min_bucket: int = 256, compact: str = "off") -> None:
        if mode not in ("raw", "binned"):
            raise ValueError(f"unknown engine mode {mode!r}")
        if compact not in COMPACT_PLANS:
            raise ValueError(f"unknown compact plan {compact!r}")
        if compact != "off" and mode == "binned":
            raise ValueError("compact plans require mode='raw' (binned "
                             "thresholds are already uint8)")
        if not trees:
            raise ValueError("ForestEngine needs at least one tree")
        self.mode = mode
        self.compact = compact
        self.num_class = max(int(num_class), 1)
        self.min_bucket = int(min_bucket)
        self._chunk_rows_opt = chunk_rows
        self.compile_count = 0          # bumped at TRACE time only
        self.cache_hits = 0             # chunk dispatches with no new trace
        self.predict_calls = 0
        self.aot_hits = 0               # chunk dispatches via AOT artifact
        self.aot_source: Optional[str] = None
        self.device = None              # jax device pin (to_device); None
                                        # = default-device placement
        self.early_stop_exits = 0       # chunks that exited before all trees
        self._jit_run = jax.jit(self._run)
        self._jit_run_routed = jax.jit(self._run_routed)
        self._sharded_cache: dict = {}
        self._install(trees)
        # HBM accountant owner: one row per live engine, read via
        # device_bytes() (shape metadata only) at snapshot time; a
        # GC'd engine drops off the ledger automatically
        from ..obs import memory as obs_memory
        obs_memory.track("serve/forest", self,
                         lambda e: e.device_bytes())

    # -- forest cache ------------------------------------------------------
    def _install(self, trees: List[Tree]) -> None:
        host = stack_forest(trees, self.num_class, binned=(
            self.mode == "binned"))
        if self.mode == "binned":
            keys = _DEVICE_KEYS_BINNED
            self._f32_bytes = None
        else:
            # counterfactual f32-plan footprint: what this forest would
            # occupy under compact="off" (exporter reports the delta)
            self._f32_bytes = sum(
                int(np.asarray(host[k]).size) * _RAW_PLAN_ITEMSIZE[k]
                for k in _DEVICE_KEYS_RAW)
            if self.compact != "off":
                host = compact_stack(host, self.compact)
                keys = _DEVICE_KEYS_COMPACT[self.compact]
            else:
                keys = _DEVICE_KEYS_RAW
        self._stk = {k: jnp.asarray(host[k]) for k in keys}
        # forest arrays changed shape/content: exported programs and the
        # early-stop sub-stack slices are stale
        self._aot_calls: Dict[int, object] = {}
        self._es_cache: Dict[int, list] = {}
        # engine holds strong refs: tree ids stay unique while cached, so
        # the id-prefix check in update() cannot alias a freed tree
        self.trees = list(trees)
        self._ids = [id(t) for t in trees]
        self.max_depth = int(host["max_depth"])
        self.has_cat = bool(host["has_cat"])
        self.num_trees = len(trees)
        self.chunk_rows = self._chunk_rows_opt or min(
            1 << 16, max(1 << 9,
                         _pow2_floor((1 << 24) // max(self.num_trees, 1))))
        # binned CPU scoring gets the packed-route table (gather-throughput
        # bound there; TPU keeps the dense compare traversal)
        self._route = None
        if self.mode == "binned" and jax.default_backend() == "cpu":
            rt = _build_packed_route(host)
            if rt is not None:
                self._route = {
                    "packed": jnp.asarray(rt["packed"]),
                    "root_sf": jnp.asarray(rt["root_sf"]),
                    "lv_flat": jnp.asarray(
                        host["leaf_value_f32"].reshape(-1)),
                    "tree_class": self._stk["tree_class"],
                }
                self._route_bins = rt["bins"]
                self._route_kbits = rt["kbits"]
                self._route_slots = rt["slots"]
                self._route_leaf_base = rt["leaf_base"]
                self._route_leaves = int(host["leaf_value_f32"].shape[1])
                self.chunk_rows = max(
                    _ROUTE_CHUNK,
                    (self.chunk_rows // _ROUTE_CHUNK) * _ROUTE_CHUNK)

    def device_bytes(self) -> int:
        """Bytes of device memory the resident forest occupies (the
        stacked arrays plus, on the CPU binned path, the packed-route
        table). This is what the serving registry's HBM budget accounts
        against — `.nbytes` on a jax array is shape metadata, no
        transfer happens."""
        total = sum(int(v.nbytes) for v in self._stk.values())
        if self._route is not None:
            total += sum(int(v.nbytes) for v in self._route.values())
        return total

    def f32_device_bytes(self) -> int:
        """What this forest WOULD occupy under ``compact="off"`` — the
        baseline the exporter/registry quote compaction savings against.
        Equals `device_bytes()` when no compact plan is active."""
        if self._f32_bytes is None:
            return self.device_bytes()
        return int(self._f32_bytes)

    def to_device(self, device) -> "ForestEngine":
        """Pin the resident forest onto one jax device (the serving
        placer's per-device replica residency). The stacked arrays are
        committed to `device`; chunk dispatches then run there because
        jit follows the committed operand. Early-stop sub-stacks and
        AOT executables are device-bound state, so both caches are
        dropped (AOT artifacts re-attach only on the default device)."""
        self._stk = {k: jax.device_put(v, device)
                     for k, v in self._stk.items()}
        if self._route is not None:
            self._route = {k: jax.device_put(v, device)
                           for k, v in self._route.items()}
        self._es_cache = {}
        self._aot_calls = {}
        self.device = device
        return self

    def attach_aot(self, calls: Dict[int, object],
                   source: Optional[str] = None) -> None:
        """Install ahead-of-time exported traversal programs, one per shape
        bucket (serve/aot.py `load_artifact`). An attached bucket's chunk
        dispatch goes through the deserialized executable instead of
        `jax.jit(self._run)` — no Python re-trace in a fresh process."""
        self._aot_calls = dict(calls)
        self.aot_source = source

    def update(self, trees: List[Tree]) -> "ForestEngine":
        """Refresh the device forest for a (possibly mutated) tree list.

        When `trees` extends the cached list (training appended trees), only
        the new suffix is stacked on host; the device arrays are padded and
        concatenated in place of a full re-upload. Any other change
        invalidates the cache and restacks from scratch."""
        ids = [id(t) for t in trees]
        if ids == self._ids:
            return self
        n_old = len(self._ids)
        if len(ids) > n_old and ids[:n_old] == self._ids:
            self._append(trees[n_old:])
        else:
            self._install(trees)
        return self

    def _append(self, new_trees: List[Tree]) -> None:
        if self._route is not None or self.compact != "off":
            # the packed-route table (and the per-feature affine scales of
            # a compact plan) mix every per-node field; rebuilding host-side
            # costs about as much as a full restack
            self._install(self.trees + list(new_trees))
            return
        # shapes grow: exported programs and early-stop slices are stale
        self._aot_calls = {}
        self._es_cache = {}
        host = stack_forest(new_trees, self.num_class,
                            binned=(self.mode == "binned"),
                            class_offset=self.num_trees)
        old_words = int(self._stk["cat_words"].shape[0])
        # flat-bitset offsets of the new trees shift past the old words
        host["cat_start"] = np.where(host["cat_len"] > 0,
                                     host["cat_start"] + old_words, 0)
        stk = dict(self._stk)
        m_old = int(stk["left_child"].shape[1])
        l_old = int(stk["leaf_value_f32"].shape[1])

        def cat2(key, new, axis1_old, axis1_new):
            old = stk[key]
            width = max(axis1_old, axis1_new)
            if axis1_old < width:
                old = jnp.pad(old, ((0, 0), (0, width - axis1_old)))
            if axis1_new < width:
                new = np.pad(new, ((0, 0), (0, width - axis1_new)))
            return jnp.concatenate([old, jnp.asarray(new)], axis=0)

        m_new = int(host["left_child"].shape[1])
        l_new = int(host["leaf_value_f32"].shape[1])
        for key in ("split_feature", "decision_type", "left_child",
                    "right_child", "threshold_in_bin", "default_bin",
                    "num_bin", "cat_start", "cat_len", "thr_hi", "thr_lo"):
            if key in stk:
                stk[key] = cat2(key, host[key], m_old, m_new)
        stk["leaf_value_f32"] = cat2("leaf_value_f32",
                                     host["leaf_value_f32"], l_old, l_new)
        for key in ("num_leaves", "tree_class"):
            stk[key] = jnp.concatenate(
                [stk[key], jnp.asarray(host[key])], axis=0)
        stk["cat_words"] = jnp.concatenate(
            [stk["cat_words"], jnp.asarray(host["cat_words"])], axis=0)
        self._stk = stk
        self.trees = self.trees + list(new_trees)
        self._ids = self._ids + [id(t) for t in new_trees]
        self.max_depth = max(self.max_depth, int(host["max_depth"]))
        self.has_cat = self.has_cat or bool(host["has_cat"])
        self.num_trees += len(new_trees)

    # -- traversal ---------------------------------------------------------
    def _go_left_raw(self, stk, planes, feat, safe, d, rows):
        xhi, xlo, xnan = planes[0], planes[1], planes[2]
        th = jnp.take_along_axis(stk["thr_hi"], safe, axis=1)
        tl = jnp.take_along_axis(stk["thr_lo"], safe, axis=1)
        xh = xhi[feat, rows]
        xl = xlo[feat, rows]
        nn = xnan[feat, rows]
        default_left = (d & 2) != 0
        mt = (d >> 2) & 3
        le = _key_le(xh, xl, th, tl)
        near_zero = (_key_le(jnp.uint32(_KZN[0]), jnp.uint32(_KZN[1]),
                             xh, xl)
                     & _key_le(xh, xl, jnp.uint32(_KZP[0]),
                               jnp.uint32(_KZP[1])))
        is_default = ((mt == 1) & near_zero) | ((mt == 2) & nn)
        go = jnp.where(is_default, default_left, le)
        if self.has_cat:
            iv = planes[3][feat, rows]
            cs = jnp.take_along_axis(stk["cat_start"], safe, axis=1)
            cl = jnp.take_along_axis(stk["cat_len"], safe, axis=1)
            w = iv >> 5
            cwords = stk["cat_words"]
            widx = jnp.clip(cs + w, 0, cwords.shape[0] - 1)
            bit = ((cwords[widx] >> (iv & 31).astype(jnp.uint32)) & 1) != 0
            cat_left = bit & (w < cl) & (iv >= 0) & ~(nn & (mt == 2))
            go = jnp.where((d & 1) != 0, cat_left, go)
        return go

    def _go_left_raw_compact(self, stk, planes, feat, safe, d, rows):
        """Compact-plan routing: de-quantized f32 threshold compare on an
        f32 feature plane (no u64 key planes — compactness trades the
        bit-exactness guarantee for bytes; the registry parity gate is
        what stands behind the trade)."""
        xval, xnan = planes[0], planes[1]
        if self.compact == "f16":
            thr = jnp.take_along_axis(stk["thr_f16"], safe,
                                      axis=1).astype(jnp.float32)
        else:
            q = jnp.take_along_axis(stk["thr_q"], safe,
                                    axis=1).astype(jnp.float32)
            thr = q * stk["thr_scale"][feat] + stk["thr_off"][feat]
        x = xval[feat, rows]
        nn = xnan[feat, rows]
        default_left = (d & 2) != 0
        mt = (d >> 2) & 3
        le = x <= thr
        near_zero = jnp.abs(x) <= jnp.float32(1e-35)
        is_default = ((mt == 1) & near_zero) | ((mt == 2) & nn)
        go = jnp.where(is_default, default_left, le)
        if self.has_cat:
            iv = planes[2][feat, rows]
            cs = jnp.take_along_axis(stk["cat_start"], safe,
                                     axis=1).astype(jnp.int32)
            cl = jnp.take_along_axis(stk["cat_len"], safe,
                                     axis=1).astype(jnp.int32)
            w = iv >> 5
            cwords = stk["cat_words"]
            widx = jnp.clip(cs + w, 0, cwords.shape[0] - 1)
            bit = ((cwords[widx] >> (iv & 31).astype(jnp.uint32)) & 1) != 0
            cat_left = bit & (w < cl) & (iv >= 0) & ~(nn & (mt == 2))
            go = jnp.where((d & 1) != 0, cat_left, go)
        return go

    def _go_left_binned(self, stk, planes, feat, safe, d, rows):
        fval = planes[0][feat, rows].astype(jnp.int32)
        tb = jnp.take_along_axis(stk["threshold_in_bin"], safe, axis=1)
        db = jnp.take_along_axis(stk["default_bin"], safe, axis=1)
        nb = jnp.take_along_axis(stk["num_bin"], safe, axis=1)
        default_left = (d & 2) != 0
        mt = (d >> 2) & 3
        is_default = jnp.where(mt == 1, fval == db,
                               jnp.where(mt == 2, fval == nb - 1, False))
        go = jnp.where(is_default, default_left, fval <= tb)
        if self.has_cat:
            cs = jnp.take_along_axis(stk["cat_start"], safe, axis=1)
            cl = jnp.take_along_axis(stk["cat_len"], safe, axis=1)
            cwords = stk["cat_words"]
            widx = jnp.clip(cs + (fval >> 5), 0, cwords.shape[0] - 1)
            bit = ((cwords[widx] >> (fval & 31).astype(jnp.uint32)) & 1) != 0
            cat_left = bit & ((fval >> 5) < cl)
            go = jnp.where((d & 1) != 0, cat_left, go)
        return go

    def _traverse(self, stk, planes):
        n = planes[0].shape[1]
        rows = jnp.arange(n, dtype=jnp.int32)[None, :]
        if self.mode == "binned":
            go_left = self._go_left_binned
        elif self.compact != "off":
            go_left = self._go_left_raw_compact
        else:
            go_left = self._go_left_raw

        def body(_, node):
            safe = jnp.maximum(node, 0)
            # compact plans narrow split_feature to int16; index in int32
            feat = jnp.take_along_axis(stk["split_feature"], safe,
                                       axis=1).astype(jnp.int32)
            d = jnp.take_along_axis(stk["decision_type"], safe,
                                    axis=1).astype(jnp.int32)
            go = go_left(stk, planes, feat, safe, d, rows)
            nxt = jnp.where(go,
                            jnp.take_along_axis(stk["left_child"], safe,
                                                axis=1),
                            jnp.take_along_axis(stk["right_child"], safe,
                                                axis=1))
            return jnp.where(node >= 0, nxt, node)

        node0 = jnp.where(stk["num_leaves"][:, None] <= 1,
                          jnp.full((stk["num_leaves"].shape[0], n), -1,
                                   jnp.int32),
                          jnp.zeros((stk["num_leaves"].shape[0], n),
                                    jnp.int32))
        # depth is read at trace time; any forest change that could grow it
        # also changes T (a shape), forcing the retrace that re-reads it
        node = lax.fori_loop(0, self.max_depth, body, node0)
        return ~node                                   # [T, N] leaf ids

    def _run(self, stk, planes):
        self.compile_count += 1
        compile_cache.note_trace()      # AOT zero-trace probe (ISSUE 16)
        leaf = self._traverse(stk, planes)
        if "leaf_value_f16" in stk:
            # compact plan: de-quantize leaves to f32 at the gather, so
            # the per-class accumulation runs full-precision
            vals = jnp.take_along_axis(stk["leaf_value_f16"], leaf,
                                       axis=1).astype(jnp.float32)
        else:
            vals = jnp.take_along_axis(stk["leaf_value_f32"], leaf, axis=1)
        acc = jnp.zeros((self.num_class, vals.shape[1]), jnp.float32)
        acc = acc.at[stk["tree_class"]].add(vals)
        return acc, leaf

    def _run_routed(self, rt, planes):
        """Packed-route binned scoring: two gathers per level per microchunk
        (bin value, then the fused decision+child+next-feature table), with
        the chunk loop inside the jit (`lax.scan`) so small microchunks —
        which keep the [T, C] frontier cache-resident — cost no dispatch."""
        self.compile_count += 1
        compile_cache.note_trace()
        bt = planes[0]                                   # [F, bucket] uint8
        t_count = self.num_trees
        s, b, k = self._route_slots, self._route_bins, self._route_kbits
        lo_mask = (1 << k) - 1
        chunk = min(_ROUTE_CHUNK, bt.shape[1])
        nch = bt.shape[1] // chunk
        tmb = (jnp.arange(t_count, dtype=jnp.int32) * s * b)[:, None]
        # fold the per-tree leaf-row offset and the leaf-slot base together
        tl = (jnp.arange(t_count, dtype=jnp.int32) * self._route_leaves
              - self._route_leaf_base)[:, None]
        rows = jnp.arange(chunk, dtype=jnp.int32)[None, :]
        packed = rt["packed"]
        lv_flat = rt["lv_flat"]

        def one(carry, ci):
            bc = lax.dynamic_slice(bt, (0, ci * chunk),
                                   (bt.shape[0], chunk))
            bflat = bc.reshape(-1)
            # root level peeled: every tree is at node 0, so its bin values
            # are a per-tree row copy instead of a scalar gather
            v0 = jnp.take(bc, rt["root_sf"], axis=0).astype(jnp.int32)
            p = packed[tmb + v0]

            def body(_, p):
                fval = bflat[(p & lo_mask) * chunk + rows].astype(jnp.int32)
                return packed[tmb + (p >> k) * b + fval]

            p = lax.fori_loop(1, self.max_depth, body, p)
            vals = lv_flat[tl + (p >> k)]
            kc = self.num_class
            if t_count % kc == 0:
                # tree_class is cyclic (i % K) at install time, so the
                # per-class sum is a reshape + reduction, not a scatter
                acc = vals.reshape(-1, kc, chunk).sum(axis=0)
            else:
                acc = jnp.zeros((kc, chunk), jnp.float32)
                acc = acc.at[rt["tree_class"]].add(vals)
            return carry, acc

        _, outs = lax.scan(one, 0, jnp.arange(nch, dtype=jnp.int32))
        return outs.transpose(1, 0, 2).reshape(self.num_class, -1)

    # -- encoding + bucketed driver ---------------------------------------
    def _encode(self, X) -> Tuple[np.ndarray, ...]:
        if self.mode == "binned":
            b = np.asarray(X)
            return (np.ascontiguousarray(b.T),)
        X = np.asarray(X, np.float64)
        nanmask = np.isnan(X)
        Xz = np.where(nanmask, 0.0, X)
        Xz = np.where(Xz == 0.0, 0.0, Xz)             # -0.0 -> +0.0
        if self.compact != "off":
            # compact routing compares plain f32 values, not key planes
            planes = [np.ascontiguousarray(Xz.T.astype(np.float32)),
                      np.ascontiguousarray(nanmask.T)]
            if self.has_cat:
                iv = np.where(Xz < 0, -1.0,
                              np.minimum(np.trunc(Xz), float(2 ** 31 - 2)))
                planes.append(np.ascontiguousarray(iv.T.astype(np.int32)))
            return tuple(planes)
        hi, lo = _f64_key_planes(Xz)
        planes = [np.ascontiguousarray(hi.T), np.ascontiguousarray(lo.T),
                  np.ascontiguousarray(nanmask.T)]
        if self.has_cat:
            # int truncation for categorical codes; huge values clip high
            # and fail the bitset range check, negatives route right
            iv = np.where(Xz < 0, -1.0,
                          np.minimum(np.trunc(Xz), float(2 ** 31 - 2)))
            planes.append(np.ascontiguousarray(iv.T.astype(np.int32)))
        return tuple(planes)

    def _bucket(self, m: int) -> int:
        return min(self.chunk_rows, max(self.min_bucket, _pow2_ceil(m)))

    @staticmethod
    def _pad_cols(p: np.ndarray, width: int) -> np.ndarray:
        m = p.shape[1]
        if m == width:
            return p
        return np.pad(p, ((0, 0), (0, width - m)))

    def _es_segments(self, freq: int) -> list:
        """Device sub-stacks [t0, t1) for chunked early-exit: tree-axis
        slices of the resident arrays (zero-copy views on CPU; a slice of
        a device array on TPU). Shared planes (`cat_words`, the int8
        per-feature scales) stay whole — `cat_start` offsets index the
        global bitset."""
        freq = max(int(freq), 1)
        if freq not in self._es_cache:
            shared = ("cat_words", "thr_scale", "thr_off")
            segs = []
            t0 = 0
            while t0 < self.num_trees:
                t1 = min(t0 + freq, self.num_trees)
                sub = {k: (v if k in shared else v[t0:t1])
                       for k, v in self._stk.items()}
                segs.append(sub)
                t0 = t1
            self._es_cache[freq] = segs
        return self._es_cache[freq]

    def _es_satisfied(self, acc: np.ndarray, margin: float) -> bool:
        """Reference `prediction_early_stop.cpp`: binary stops when every
        row's |margin| clears the threshold, multiclass when every row's
        top1-top2 gap does. Chunk-granular — the whole chunk must agree
        before the remaining trees are skipped."""
        if self.num_class == 1:
            return bool(np.all(np.abs(acc) > margin))
        part = np.sort(acc, axis=0)
        return bool(np.all(part[-1] - part[-2] > margin))

    def predict(self, X, pred_leaf: bool = False,
                early_stop: Optional[Tuple[int, float]] = None
                ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Score a batch. Returns (margins [N, num_class] f64,
        leaves [N, T] int32 or None). Large batches stream through
        fixed-size chunks; small ones pad to a power-of-two bucket, so any
        N inside a bucket reuses the same compiled program.

        `early_stop=(freq_trees, margin)` scores the forest in
        `freq_trees`-tree segments and skips the remainder once the whole
        chunk clears the margin criterion (reference
        `prediction_early_stop.cpp` semantics, chunk-granular).
        """
        from .. import compile_cache
        from ..obs import trace as obs_trace
        from ..utils import log
        planes = self._encode(X)
        n = planes[0].shape[1]
        acc = np.empty((n, self.num_class), np.float64)
        leaves = np.empty((n, self.num_trees), np.int32) if pred_leaf \
            else None
        if pred_leaf:
            early_stop = None           # leaf ids need every tree
        step = self.chunk_rows
        self.predict_calls += 1
        with obs_trace.span("serve.predict", rows=n,
                            trees=self.num_trees):
            for lo in range(0, max(n, 1), step):
                hi = min(lo + step, n)
                m = hi - lo
                bucket = self._bucket(m)   # tail chunks drop to their own
                chunk = tuple(self._pad_cols(p[:, lo:hi], bucket)
                              for p in planes)
                cc0 = self.compile_count
                aot_fn = (self._aot_calls.get(bucket)
                          if early_stop is None and self._route is None
                          else None)
                with obs_trace.span("serve.score", bucket=bucket,
                                    rows=m), \
                        compile_cache.attribution(
                            f"serve:T{self.num_trees}:b{bucket}"):
                    if early_stop is not None and self._route is None:
                        out = self._predict_early_stop(chunk, m, early_stop)
                    elif aot_fn is not None:
                        # deserialized export: dispatch never re-runs the
                        # _run body, so note_trace/compile_count stay put
                        try:
                            out, lf = aot_fn(self._stk, chunk)
                            self.aot_hits += 1
                        except ValueError:
                            # caller planes disagree with the exported
                            # avals (e.g. fewer feature rows than the
                            # artifact was traced with): retire the
                            # bucket's program and serve via the engine
                            # jit — identical to a cold process
                            self._aot_calls.pop(bucket, None)
                            log.event("serve_aot",
                                      status="shape_mismatch",
                                      bucket=bucket)
                            out, lf = self._jit_run(self._stk, chunk)
                        if pred_leaf:
                            leaves[lo:hi] = np.asarray(lf)[:, :m].T
                    elif self._route is not None and not pred_leaf:
                        out = self._jit_run_routed(self._route, chunk)
                    else:
                        out, lf = self._jit_run(self._stk, chunk)
                        if pred_leaf:
                            leaves[lo:hi] = np.asarray(lf)[:, :m].T
                if self.compile_count == cc0:
                    self.cache_hits += 1   # bucket program already compiled
                else:
                    log.event("serve_compile", bucket=bucket,
                              routed=self._route is not None
                              and not pred_leaf,
                              compile_count=self.compile_count)
                acc[lo:hi] = np.asarray(out)[:, :m].T
        return acc, leaves

    def _predict_early_stop(self, chunk, m: int,
                            early_stop: Tuple[int, float]) -> np.ndarray:
        freq, margin = early_stop
        segs = self._es_segments(freq)
        total = np.zeros((self.num_class, chunk[0].shape[1]), np.float32)
        for si, sub in enumerate(segs):
            out, _ = self._jit_run(sub, chunk)
            total += np.asarray(out)
            if si < len(segs) - 1 and self._es_satisfied(
                    total[:, :m], margin):
                self.early_stop_exits += 1
                from ..obs import metrics as obs_metrics
                obs_metrics.note_early_stop()
                break
        return total

    # -- bulk row-sharded scoring -----------------------------------------
    def predict_sharded(self, X, devices=None) -> np.ndarray:
        """Offline/bulk scoring sharded over rows across devices
        (`shard_map` over a 1-D 'rows' mesh). Returns margins
        [N, num_class] f64. Forest arrays are replicated; the traversal is
        embarrassingly row-parallel so no collective runs."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        devices = list(devices if devices is not None else jax.devices())
        nd = len(devices)
        if nd <= 1:
            return self.predict(X)[0]
        planes = self._encode(X)
        n = planes[0].shape[1]
        padded = max(_pow2_ceil(n), nd * self.min_bucket)
        padded = ((padded + nd - 1) // nd) * nd   # shardable row count
        planes = tuple(self._pad_cols(p, padded) for p in planes)
        key = (padded, nd)
        if key not in self._sharded_cache:
            mesh = Mesh(np.asarray(devices), ("rows",))
            spec_in = tuple(P(None, "rows") for _ in planes)
            fn = shard_map(lambda stk, pl: self._run(stk, pl)[0],
                           mesh=mesh,
                           in_specs=(jax.tree_util.tree_map(
                               lambda _: P(), self._stk), spec_in),
                           out_specs=P(None, "rows"), check_rep=False)
            self._sharded_cache[key] = jax.jit(fn)
        out = self._sharded_cache[key](self._stk, planes)
        return np.asarray(out)[:, :n].T.astype(np.float64)
