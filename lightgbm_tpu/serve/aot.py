"""Ahead-of-time serving artifacts: export the bucketed forest-traversal
programs of a `ForestEngine` to disk and re-attach them in a fresh process
with ZERO new jax traces before the first scored request.

The artifact directory holds one `jax.export` serialized executable per
shape bucket plus a MANIFEST.json carrying the **artifact signature** —
everything that must match for a deserialized program to be valid for a
given engine:

    (jax version, backend, engine mode, compact dtype plan, num_class,
     num_trees, max_depth, has_cat, num_features, and the exact
     (key, shape, dtype) plan of the device-resident stack)

Shape buckets are deliberately NOT in the signature: a manifest maps each
exported bucket to its blob, and an engine simply falls back to its own
`jax.jit` for buckets the artifact doesn't cover. A signature mismatch is
a clean rebuild (structured ``serve_aot`` event, engine keeps its jit
path), never a crash — artifacts are a warm-start cache, not a format the
server depends on.

Where `jax.export` is unavailable (older jax, exotic backends) the
exporter degrades to prefilling the persistent compilation cache
(`compile_cache.init_persistent_cache`): first-score then pays a trace
but no XLA compile. `tools/serve_export.py` is the CLI wrapper;
`serving/registry.py` calls `load_artifact` at model-load time when
`tpu_serve_aot_dir` is set.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Iterable, Optional, Tuple

import jax
import numpy as np

from ..utils import log

ARTIFACT_MANIFEST = "MANIFEST.json"
SCHEMA_VERSION = 1

__all__ = ["ARTIFACT_MANIFEST", "SCHEMA_VERSION", "artifact_signature",
           "export_artifact", "load_artifact"]


def _export_module():
    """`jax.export` if this jax has it, else None (degrade to
    persistent-cache prefill)."""
    try:
        from jax import export as jax_export
        if hasattr(jax_export, "export") and hasattr(jax_export,
                                                     "deserialize"):
            return jax_export
    except ImportError:
        pass
    return None


def artifact_signature(engine, num_features: int) -> Dict[str, object]:
    """Everything a serialized traversal program is specialized on. Two
    engines with equal signatures accept each other's exported buckets;
    any difference (model shape, dtype plan, jax version...) must force a
    clean rebuild."""
    return {
        "schema": SCHEMA_VERSION,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "mode": engine.mode,
        "compact": engine.compact,
        "num_class": int(engine.num_class),
        "num_trees": int(engine.num_trees),
        "max_depth": int(engine.max_depth),
        "has_cat": bool(engine.has_cat),
        "num_features": int(num_features),
        # lists, not tuples: the signature must compare equal after a
        # JSON round-trip through the manifest
        "stack": sorted(
            [k, [int(s) for s in v.shape], str(v.dtype)]
            for k, v in engine._stk.items()),
    }


def _specs(engine, num_features: int, bucket: int):
    """(stack specs, plane specs) for one bucket: ShapeDtypeStructs
    mirroring exactly what `predict` passes to `_run`. Plane dtypes come
    from a probe encode of a zero row so the spec tracks the engine's
    encoding (key planes vs compact f32 plane vs extra cat plane)."""
    stk_specs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for k, v in engine._stk.items()}
    probe = engine._encode(np.zeros((1, num_features)))
    plane_specs = tuple(
        jax.ShapeDtypeStruct((num_features, bucket), p.dtype)
        for p in probe)
    return stk_specs, plane_specs


def export_artifact(engine, out_dir: str, buckets: Iterable[int],
                    num_features: int) -> Dict[str, object]:
    """Write an AOT artifact directory for `engine` covering `buckets`.

    Returns the manifest dict. With `jax.export` available, each bucket's
    traversal program is serialized to ``bucket_<b>.bin``; otherwise the
    manifest records ``"prefill"`` and first-load warms through the
    persistent compile cache instead.
    """
    os.makedirs(out_dir, exist_ok=True)
    buckets = sorted({int(b) for b in buckets if int(b) > 0})
    exp_mod = _export_module()
    manifest: Dict[str, object] = {
        "schema": SCHEMA_VERSION,
        "signature": artifact_signature(engine, num_features),
        "kind": "export" if exp_mod is not None else "prefill",
        "buckets": {},
    }
    for b in buckets:
        stk_specs, plane_specs = _specs(engine, num_features, b)
        if exp_mod is not None:
            exp = exp_mod.export(jax.jit(engine._run))(stk_specs,
                                                       plane_specs)
            blob = exp.serialize()
            name = f"bucket_{b}.bin"
            with open(os.path.join(out_dir, name), "wb") as fh:
                fh.write(blob)
            manifest["buckets"][str(b)] = name
        else:
            # no export support: at least populate the persistent XLA
            # cache (if one is configured) so a fresh process pays a
            # trace but not a compile
            engine._jit_run.lower(stk_specs, plane_specs).compile()
            manifest["buckets"][str(b)] = "prefill"
    with open(os.path.join(out_dir, ARTIFACT_MANIFEST), "w") as fh:
        json.dump(manifest, fh, indent=1, sort_keys=True)
    log.event("serve_aot", status="export", dir=out_dir,
              buckets=len(buckets), artifact=manifest["kind"])
    return manifest


def _signature_diff(want: Dict[str, object],
                    have: Dict[str, object]) -> list:
    keys = sorted(set(want) | set(have))
    return [k for k in keys if want.get(k) != have.get(k)]


def load_artifact(engine, aot_dir: str, num_features: int,
                  model: str = "") -> int:
    """Attach an artifact directory's exported programs to `engine`.

    Returns the number of buckets attached (0 on any miss). Every outcome
    emits one structured ``serve_aot`` event; a signature mismatch or a
    corrupt blob is a clean fall-through to the engine's own jit path.
    """
    path = os.path.join(aot_dir, ARTIFACT_MANIFEST)
    if not os.path.isfile(path):
        log.event("serve_aot", status="miss", dir=aot_dir, model=model)
        return 0
    try:
        with open(path) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as exc:
        log.event("serve_aot", status="bad_manifest", dir=aot_dir,
                  model=model, error=str(exc))
        return 0
    want = artifact_signature(engine, num_features)
    have = manifest.get("signature", {})
    diff = _signature_diff(want, have)
    if diff:
        log.event("serve_aot", status="signature_mismatch", dir=aot_dir,
                  model=model, mismatch=diff)
        return 0
    if manifest.get("kind") != "export":
        # prefill artifacts carry no blobs; the persistent cache (if
        # configured) already holds the compiled programs
        log.event("serve_aot", status="prefill", dir=aot_dir, model=model,
                  buckets=len(manifest.get("buckets", {})))
        return 0
    exp_mod = _export_module()
    if exp_mod is None:
        log.event("serve_aot", status="no_export_support", dir=aot_dir,
                  model=model)
        return 0
    calls: Dict[int, object] = {}
    for b_str, name in manifest.get("buckets", {}).items():
        try:
            with open(os.path.join(aot_dir, name), "rb") as fh:
                blob = fh.read()
            exp = exp_mod.deserialize(blob)
            # jit the deserialized call for dispatch caching; this traces
            # only exp.call's thin wrapper, never the _run body, so the
            # note_trace probe stays untouched
            calls[int(b_str)] = jax.jit(exp.call)
        except Exception as exc:   # corrupt blob -> skip, engine jit covers
            log.event("serve_aot", status="bad_blob", dir=aot_dir,
                      model=model, bucket=b_str, error=str(exc))
    if not calls:
        return 0
    engine.attach_aot(calls, source=aot_dir)
    log.event("serve_aot", status="hit", dir=aot_dir, model=model,
              buckets=len(calls))
    return len(calls)
