"""Batch inference engine: device-resident stacked forests, depth-
synchronized traversal, shape-bucketed jit cache, compact dtype plans,
and AOT artifact export/load (ROADMAP serving path)."""
from .engine import (COMPACT_PLANS, ForestEngine, compact_stack,  # noqa: F401
                     stack_forest)
from . import aot  # noqa: F401
