"""Batch inference engine: device-resident stacked forests, depth-
synchronized traversal, shape-bucketed jit cache (ROADMAP serving path)."""
from .engine import ForestEngine, stack_forest  # noqa: F401
