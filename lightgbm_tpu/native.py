"""ctypes binding for the native C++ helpers (src/native/).

The reference keeps its whole ingest pipeline in C++ (TextReader /
Parser / DatasetLoader with OpenMP); the Python package is a thin ctypes
wrapper over `lib_lightgbm.so` (python-package/lightgbm/basic.py:25-36).
This module is the same seam for the tpu build: `liblgbt_native.so` is
loaded via ctypes, built lazily from source with the system toolchain when
missing, and every caller has a pure-Python fallback, so the package works
without a compiler.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
# repo checkout layout first; installed-package layout (_native_src is
# staged into the package by setup.py) as the fallback
_SRC_DIR = os.path.join(os.path.dirname(_PKG_DIR), "src", "native")
if not os.path.isdir(_SRC_DIR):
    _SRC_DIR = os.path.join(_PKG_DIR, "_native_src")
_LIB_NAME = "liblgbt_native.so"
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False

FMT_CSV, FMT_TSV, FMT_LIBSVM = 0, 1, 2
_FMT_NAMES = {FMT_CSV: "csv", FMT_TSV: "tsv", FMT_LIBSVM: "libsvm"}


def _build():
    """(path-or-None, reason): locate or build the .so; `reason` explains
    a None path (sources absent vs an actual make/compiler failure)."""
    path = os.path.join(_SRC_DIR, _LIB_NAME)
    src = os.path.join(_SRC_DIR, "text_parser.cpp")
    if not os.path.isfile(src):
        if os.path.isfile(path):
            return path, ""
        return None, "native sources not present and no prebuilt .so"
    try:
        # make is a no-op when the .so is newer than every source
        subprocess.run(["make", "-C", _SRC_DIR], check=True,
                       capture_output=True, timeout=120)
    except Exception as e:
        # a prebuilt .so (if any) still works
        if os.path.isfile(path):
            return path, ""
        return None, f"build failed ({e})"
    return (path, "") if os.path.isfile(path) else \
        (None, "build produced no library")


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None when unavailable.
    Warns ONCE at default verbosity when the .so fails to build/load —
    ingest and batch predict silently degrading to the Python path was
    too easy to miss otherwise."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    path, reason = _build()
    if path is None:
        _warn_unavailable(reason)
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        _warn_unavailable(f"load failed: {e}")
        return None
    lib.lgbt_scan.restype = ctypes.c_int32
    lib.lgbt_scan.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32)]
    lib.lgbt_parse.restype = ctypes.c_int32
    lib.lgbt_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double)]
    lib.lgbt_num_threads.restype = ctypes.c_int32
    lib.lgbt_num_threads.argtypes = []
    c = ctypes
    p64, pf64, p32, p8, pu32 = (c.POINTER(c.c_int64), c.POINTER(c.c_double),
                                c.POINTER(c.c_int32), c.POINTER(c.c_int8),
                                c.POINTER(c.c_uint32))
    try:  # a stale prebuilt .so may predate these symbols
        lib.lgbt_find_bin_numerical.restype = c.c_int32
        lib.lgbt_find_bin_numerical.argtypes = [
            pf64, c.c_int64, c.c_int64, c.c_int32, c.c_int32, pf64]
        lib.lgbt_bin_matrix.restype = c.c_int32
        lib.lgbt_bin_matrix.argtypes = [
            c.c_void_p, c.c_int32, c.c_int64, c.c_int64, p32, c.c_int64, p32,
            p32, p32, pf64, p64, p64, p32, p64, c.c_int32, c.c_void_p]
        lib.lgbt_predict.restype = c.c_int32
        lib.lgbt_predict.argtypes = [
            pf64, c.c_int64, c.c_int64, c.c_int32, p64, p64, p32, p32, p32,
            pf64, p8, pf64, p64, p32, p64, pu32, p32, p32, c.c_int32,
            c.c_int32, c.c_int32, c.c_double, pf64]
    except AttributeError:
        pass
    _lib = lib
    return _lib


def _warn_unavailable(reason: str) -> None:
    from .utils import log
    log.warning(
        f"native helper library ({_LIB_NAME}) unavailable — {reason}; "
        f"text parsing, bin finding, and batch prediction fall back to "
        f"the (slower) pure-Python path")


def native_available() -> bool:
    return get_lib() is not None


def parse_file(path: str, label_idx: int = 0
               ) -> Optional[Tuple[np.ndarray, np.ndarray, str]]:
    """Parse a CSV/TSV/LibSVM data file with the native OpenMP parser.

    Returns (labels[f64 N], features[f64 N x F], format_name), or None when
    the native library is unavailable (caller falls back to the Python
    parser). Matches `ops.parser.parse_dense` semantics: NA tokens -> NaN,
    absent libsvm entries -> 0.0.
    """
    lib = get_lib()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    fmt = ctypes.c_int32()
    rc = lib.lgbt_scan(path.encode(), ctypes.byref(rows), ctypes.byref(cols),
                       ctypes.byref(fmt))
    if rc != 0:
        raise FileNotFoundError(f"data file {path} not found")
    n = rows.value
    if fmt.value == FMT_LIBSVM:
        f = cols.value
        eff_label = -1
    else:
        f = cols.value - (1 if label_idx >= 0 else 0)
        eff_label = label_idx
    f = max(f, 0)
    labels = np.zeros(n, np.float64)
    feats = np.zeros((n, f), np.float64)
    rc = lib.lgbt_parse(
        path.encode(), fmt.value, eff_label, f,
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        feats.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    if rc != 0:
        raise IOError(f"native parse of {path} failed")
    return labels, feats, _FMT_NAMES[fmt.value]


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def find_bin_numerical(values: np.ndarray, total_sample_cnt: int,
                       max_bin: int, min_data_in_bin: int
                       ) -> Optional[np.ndarray]:
    """Numerical bin-boundary search in C++ (binning.cpp); None when the
    native library is unavailable or the search degenerates (caller falls
    back to the Python implementation)."""
    lib = get_lib()
    if lib is None or max_bin < 2 or not hasattr(lib, "lgbt_find_bin_numerical"):
        return None
    values = np.ascontiguousarray(values, dtype=np.float64)
    out = np.empty(max_bin + 1, np.float64)
    n = lib.lgbt_find_bin_numerical(
        _ptr(values, ctypes.c_double), len(values), int(total_sample_cnt),
        int(max_bin), int(min_data_in_bin), _ptr(out, ctypes.c_double))
    if n < 0:
        return None
    return out[:n].copy()


def bin_matrix(data: np.ndarray, col_idx: np.ndarray, bin_type: np.ndarray,
               missing: np.ndarray, num_bin: np.ndarray,
               bounds: np.ndarray, bounds_off: np.ndarray,
               cats: np.ndarray, cat_bins: np.ndarray, cats_off: np.ndarray,
               out_dtype) -> Optional[np.ndarray]:
    """Full-matrix value->bin ingest in C++ with OpenMP over rows
    (binning.cpp lgbt_bin_matrix); None when unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lgbt_bin_matrix"):
        return None
    if data.dtype == np.float64:
        dtype_code = 0
    elif data.dtype == np.float32:
        dtype_code = 1
    else:
        return None
    data = np.ascontiguousarray(data)
    n, f_total = data.shape
    f_used = len(col_idx)
    out = np.empty((n, f_used), dtype=out_dtype)
    rc = lib.lgbt_bin_matrix(
        data.ctypes.data_as(ctypes.c_void_p), dtype_code, n, f_total,
        _ptr(np.ascontiguousarray(col_idx, np.int32), ctypes.c_int32),
        f_used,
        _ptr(np.ascontiguousarray(bin_type, np.int32), ctypes.c_int32),
        _ptr(np.ascontiguousarray(missing, np.int32), ctypes.c_int32),
        _ptr(np.ascontiguousarray(num_bin, np.int32), ctypes.c_int32),
        _ptr(np.ascontiguousarray(bounds, np.float64), ctypes.c_double),
        _ptr(np.ascontiguousarray(bounds_off, np.int64), ctypes.c_int64),
        _ptr(np.ascontiguousarray(cats, np.int64), ctypes.c_int64),
        _ptr(np.ascontiguousarray(cat_bins, np.int32), ctypes.c_int32),
        _ptr(np.ascontiguousarray(cats_off, np.int64), ctypes.c_int64),
        1 if out_dtype == np.uint16 else 0,
        out.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        return None
    return out


def predict_forest(X: np.ndarray, flat: dict, num_class: int,
                   pred_leaf: bool = False, early_stop_freq: int = 0,
                   early_stop_margin: float = 0.0) -> Optional[np.ndarray]:
    """Batch raw prediction over a flattened forest (predictor.cpp),
    OpenMP over rows; None when the native library is unavailable.
    `flat` is `ops.predict.flatten_forest(trees)`."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lgbt_predict"):
        return None
    X = np.ascontiguousarray(X, dtype=np.float64)
    n, num_feat = X.shape
    t_count = len(flat["num_leaves"])
    if pred_leaf:
        out = np.empty((n, t_count), np.float64)
    else:
        out = np.zeros((n, num_class), np.float64)
    rc = lib.lgbt_predict(
        _ptr(X, ctypes.c_double), n, num_feat, t_count,
        _ptr(flat["node_off"], ctypes.c_int64),
        _ptr(flat["leaf_off"], ctypes.c_int64),
        _ptr(flat["left"], ctypes.c_int32),
        _ptr(flat["right"], ctypes.c_int32),
        _ptr(flat["feat"], ctypes.c_int32),
        _ptr(flat["thresh"], ctypes.c_double),
        _ptr(flat["dtype"], ctypes.c_int8),
        _ptr(flat["leaf_value"], ctypes.c_double),
        _ptr(flat["cat_bnd_off"], ctypes.c_int64),
        _ptr(flat["cat_boundaries"], ctypes.c_int32),
        _ptr(flat["cat_words_off"], ctypes.c_int64),
        _ptr(flat["cat_words"], ctypes.c_uint32),
        _ptr(flat["num_leaves"], ctypes.c_int32),
        _ptr(flat["tree_class"], ctypes.c_int32),
        num_class, 1 if pred_leaf else 0, int(early_stop_freq),
        float(early_stop_margin), _ptr(out, ctypes.c_double))
    if rc != 0:
        return None
    return out if pred_leaf or num_class > 1 else out[:, 0]
