"""ctypes binding for the native C++ helpers (src/native/).

The reference keeps its whole ingest pipeline in C++ (TextReader /
Parser / DatasetLoader with OpenMP); the Python package is a thin ctypes
wrapper over `lib_lightgbm.so` (python-package/lightgbm/basic.py:25-36).
This module is the same seam for the tpu build: `liblgbt_native.so` is
loaded via ctypes, built lazily from source with the system toolchain when
missing, and every caller has a pure-Python fallback, so the package works
without a compiler.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src", "native")
_LIB_NAME = "liblgbt_native.so"
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False

FMT_CSV, FMT_TSV, FMT_LIBSVM = 0, 1, 2
_FMT_NAMES = {FMT_CSV: "csv", FMT_TSV: "tsv", FMT_LIBSVM: "libsvm"}


def _build() -> Optional[str]:
    path = os.path.join(_SRC_DIR, _LIB_NAME)
    if os.path.isfile(path):
        return path
    src = os.path.join(_SRC_DIR, "text_parser.cpp")
    if not os.path.isfile(src):
        return None
    try:
        subprocess.run(["make", "-C", _SRC_DIR], check=True,
                       capture_output=True, timeout=120)
    except Exception:
        return None
    return path if os.path.isfile(path) else None


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None when unavailable."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.lgbt_scan.restype = ctypes.c_int32
    lib.lgbt_scan.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32)]
    lib.lgbt_parse.restype = ctypes.c_int32
    lib.lgbt_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double)]
    lib.lgbt_num_threads.restype = ctypes.c_int32
    lib.lgbt_num_threads.argtypes = []
    _lib = lib
    return _lib


def native_available() -> bool:
    return get_lib() is not None


def parse_file(path: str, label_idx: int = 0
               ) -> Optional[Tuple[np.ndarray, np.ndarray, str]]:
    """Parse a CSV/TSV/LibSVM data file with the native OpenMP parser.

    Returns (labels[f64 N], features[f64 N x F], format_name), or None when
    the native library is unavailable (caller falls back to the Python
    parser). Matches `ops.parser.parse_dense` semantics: NA tokens -> NaN,
    absent libsvm entries -> 0.0.
    """
    lib = get_lib()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    fmt = ctypes.c_int32()
    rc = lib.lgbt_scan(path.encode(), ctypes.byref(rows), ctypes.byref(cols),
                       ctypes.byref(fmt))
    if rc != 0:
        raise FileNotFoundError(f"data file {path} not found")
    n = rows.value
    if fmt.value == FMT_LIBSVM:
        f = cols.value
        eff_label = -1
    else:
        f = cols.value - (1 if label_idx >= 0 else 0)
        eff_label = label_idx
    f = max(f, 0)
    labels = np.zeros(n, np.float64)
    feats = np.zeros((n, f), np.float64)
    rc = lib.lgbt_parse(
        path.encode(), fmt.value, eff_label, f,
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        feats.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    if rc != 0:
        raise IOError(f"native parse of {path} failed")
    return labels, feats, _FMT_NAMES[fmt.value]
