"""Shape-bucketed sub-fleet planning for the batched sweep.

A fleet whose members disagree outside the sweep grid (different
num_leaves, objective, quantization, ...) cannot share ONE vmapped
round program — but it does not have to fall back to interleaved
round-robin either. ``plan_subfleets`` partitions the fleet:

1. **Shape buckets** — members are grouped by
   ``shared_grid_signature`` (first-appearance order, original model
   order preserved inside a bucket), the same pow2-bucketing idiom the
   serving ForestEngine uses for mixed-shape forests: few distinct
   program shapes, each reused across every sub-fleet of that shape.
2. **HBM packing** — each bucket is chunked greedily by the device
   headroom the ``obs/memory`` accountant reports (or the
   ``tpu_sweep_hbm_budget_mb`` / ``tpu_sweep_max_fleet`` knobs when
   set, e.g. on CPU CI where the runtime has no memory_stats): the
   ``[M, K, N]`` score stack plus working headroom must fit, so
   M-in-the-hundreds fleets split into pow2-sized chunks (program reuse
   again: a 128-model bucket at cap 48 becomes four M=32 sub-fleets,
   ONE trace).

The trainer gates each sub-fleet independently and steps them
round-robin per round, so the async dispatch queue stays full across
sub-fleets exactly like the interleaved fallback keeps it full across
models. The plan is a pure function of (signatures, shapes, caps) —
deterministic across runs, asserted by tests/test_sweep_variants.py.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

# score stack + record log / operand growth allowance per model
_SCORE_HEADROOM = 2.0
# fraction of the accountant's free HBM a fleet may claim
_HBM_FRACTION = 0.8


@dataclass(frozen=True)
class SubfleetPlan:
    """One batched sub-fleet: global model indices (fleet order), the
    per-round score-stack bytes, and why the boundary exists."""
    indices: Tuple[int, ...]
    score_bytes: int
    reason: str        # "single" | "shape" | "hbm" | "cap"


def _model_bytes(gbdt) -> int:
    """Per-model resident estimate for fleet packing: the [K, N] f32
    score plane times a working-headroom factor (the record log and the
    per-round operand stacks grow with the same M)."""
    k = gbdt.num_tree_per_iteration
    return int(k * gbdt.num_data * 4 * _SCORE_HEADROOM)


def _budget_bytes(cfg) -> Tuple[Optional[int], str]:
    """(budget, source): the explicit knob when set, else the device
    accountant's free HBM, else None (unbounded — CPU emulation with no
    memory_stats and no knob)."""
    mb = int(getattr(cfg, "tpu_sweep_hbm_budget_mb", 0) or 0)
    if mb > 0:
        return mb * (1 << 20), "knob"
    from ..obs import memory as obs_memory
    stats = obs_memory.device_memory_stats()
    if stats and stats.get("bytes_limit"):
        free = int(stats["bytes_limit"]) - int(stats.get("bytes_in_use", 0))
        return max(int(free * _HBM_FRACTION), 0), "hbm"
    return None, "none"


def _chunk_sizes(count: int, cap: int) -> List[int]:
    """Greedy pow2 chunking: largest power of two <= cap repeatedly,
    remainder as-is. Pow2 sizes keep the set of distinct (M, shape)
    program keys small, so sub-fleet #2.. of a bucket reuse sub-fleet
    #1's trace."""
    if count <= cap:
        return [count]
    size = 1 << (cap.bit_length() - 1)
    sizes = []
    left = count
    while left > cap:
        sizes.append(size)
        left -= size
    if left:
        sizes.append(left)
    return sizes


def plan_subfleets(gbdts, cfgs) -> List[SubfleetPlan]:
    """Partition the fleet into batched sub-fleets: shape buckets first,
    then HBM/cap chunking inside each bucket. One plan covering the
    whole fleet (reason "single") is the homogeneous fast path."""
    from .batched import shared_grid_signature
    groups: Dict[Tuple, List[int]] = {}
    order: List[Tuple] = []
    for m, cfg in enumerate(cfgs):
        sig = shared_grid_signature(cfg)
        if sig not in groups:
            groups[sig] = []
            order.append(sig)
        groups[sig].append(m)

    budget, source = _budget_bytes(cfgs[0])
    max_fleet = int(getattr(cfgs[0], "tpu_sweep_max_fleet", 0) or 0)

    plans: List[SubfleetPlan] = []
    for sig in order:
        idx = groups[sig]
        per_model = _model_bytes(gbdts[idx[0]])
        cap = len(idx)
        reason = "shape" if len(order) > 1 else "single"
        if budget is not None and budget // per_model < cap:
            cap = max(int(budget // per_model), 1)
            reason = "hbm"
        if 0 < max_fleet < cap:
            cap = max_fleet
            reason = "cap"
        pos = 0
        for size in _chunk_sizes(len(idx), cap):
            plans.append(SubfleetPlan(
                indices=tuple(idx[pos:pos + size]),
                score_bytes=per_model * size,
                reason=reason))
            pos += size
    if len(plans) == 1:
        plans = [SubfleetPlan(plans[0].indices, plans[0].score_bytes,
                              "single")]
    return plans
