"""Many-model vectorized training (the "fleet" trainer).

``train_many`` trains M boosters against ONE shared binned Dataset in a
single jitted program per round: the per-round stack (gradients ->
histogram accumulation -> split evaluation -> partition -> leaf values)
is vmapped over a leading model axis, with the per-model learning rate,
split lambdas, bagging subsets and feature masks threaded as traced
operands so one program covers a whole hyperparameter grid. Configs the
batched program cannot express fall back to an interleaved round-robin
of ordinary per-booster round dispatches (the device queue stays full;
jax dispatch is async).

``refresh_many`` closes the production loop: a continual warm-start
refresh (``train_many(init_models=...)``) whose per-model serving
checkpoints the existing serving watcher hot-swaps live.

See docs/Sweep.md for the batching model and the parity contract.
"""
from .batched import SWEEP_VARYING, batched_gate, shared_grid_signature
from .refresh import (RefreshTrigger, refresh_due, refresh_many,
                      write_serving_checkpoint)
from .subfleet import SubfleetPlan, plan_subfleets
from .trainer import train_many

__all__ = ["train_many", "refresh_many", "write_serving_checkpoint",
           "batched_gate", "shared_grid_signature", "SWEEP_VARYING",
           "plan_subfleets", "SubfleetPlan", "RefreshTrigger",
           "refresh_due"]
