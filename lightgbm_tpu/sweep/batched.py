"""Batched sweep mode: one jitted round program for M boosters.

The round program is ``jit(vmap(one_model))`` over a leading model axis,
where ``one_model`` is the RAW python body of one booster's fused round:
objective gradients -> per-class whole-tree build (via
``DeviceTreeLearner.sweep_build_fn``, which threads the split lambdas as
traced scalars) -> score update (partition fill for fresh trees, record
traversal for bagged ones, both from ``ops.sweep_ops``). Raw bodies are
mandatory: vmapping the registered jitted programs re-canonicalizes
their f64 reduce-init constants to f32 under the global x64-off config,
which XLA rejects as mixed precision — the raw bodies keep the
``enable_x64`` blocks live during the vmap trace, so the batched math is
the exact expression tree the sequential programs trace, and model text
stays byte-equal per booster under ``tpu_use_f64_hist``.

Registry discipline: the program enters the process-wide compile cache
keyed by the learner/objective trace signatures with the swept fields
normalized out, so model #2..M cost zero traces by construction (one
program) and a SECOND fleet at the same shapes — any grid — costs zero
traces too (asserted by tests/test_sweep.py).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import compile_cache
from ..models.device_learner import (DeviceTreeLearner, _pow2ceil,
                                     traversal_arrays)
from ..ops.sweep_ops import (partition_score_update_lane,
                             record_score_lane)

# Config fields the batched program may vary PER MODEL (everything else
# must be equal across the fleet — they are traced operands or host-side
# schedule inputs, never trace constants):
#   learning_rate            -> score-update scale operand
#   lambda_l1/lambda_l2      -> split-finder operands (sweep_build_fn)
#   bagging_seed/bagging_freq-> host RNG schedule; bag partitions are
#                               per-model index operands
#   feature_fraction_seed    -> host RNG; masks are per-model operands
SWEEP_VARYING = frozenset({
    "learning_rate", "lambda_l1", "lambda_l2",
    "bagging_seed", "bagging_freq", "feature_fraction_seed",
})

# The sweep trainer's own knobs: runtime infrastructure, never part of
# the training math (also excluded from model text / checkpoint
# signatures — see models/model_text.py, resilience/checkpoint.py).
SWEEP_RUNTIME = frozenset({
    "tpu_sweep_mode", "tpu_sweep_checkpoint_dir",
    "tpu_sweep_checkpoint_freq",
})

_NORM = "<swept>"


def _normalized_config_items(cfg) -> Tuple:
    """``config_signature`` with swept + sweep-runtime fields pinned to a
    sentinel: the grid-independent part of a model's config."""
    return tuple(
        (k, _NORM if (k in SWEEP_VARYING or k in SWEEP_RUNTIME) else v)
        for k, v in compile_cache.config_signature(cfg))


def shared_grid_signature(cfg) -> Tuple:
    """The config signature every fleet member must share for batched
    mode (grid fields and sweep-runtime knobs normalized out)."""
    return _normalized_config_items(cfg)


def _normalized_learner_sig(learner) -> Tuple:
    """Learner trace signature with the swept config fields normalized —
    the registry key part that makes two fleets with different grids hit
    the same program."""
    raw_cfg = compile_cache.config_signature(learner.cfg)
    norm_cfg = _normalized_config_items(learner.cfg)
    return tuple(norm_cfg if item == raw_cfg else item
                 for item in learner.trace_signature())


def batched_gate(gbdts, cfgs) -> Optional[str]:
    """None when the fleet can train in batched mode; else the first
    failing reason (the trainer then runs the interleaved fallback).

    The gate admits exactly the configs whose sequential twin takes the
    leaf-wise ``_train_one_iter_fused`` path with uniform shapes across
    models — what the vmapped round program replicates bit-for-bit."""
    from ..models.gbdt import GBDT
    from ..ops.objectives import ObjectiveFunction
    g0 = gbdts[0]
    cfg0 = cfgs[0]
    if type(g0) is not GBDT:
        return f"boosting type {type(g0).__name__} (DART/GOSS/RF reshape " \
               "scores or sampling host-side)"
    if not g0.use_fused or type(g0.learner) is not DeviceTreeLearner:
        return "fleet needs the single-device fused learner"
    if cfg0.tpu_grow_mode not in ("leafwise", "auto"):
        return f"tpu_grow_mode={cfg0.tpu_grow_mode!r} (the batched round " \
               "replicates the leaf-wise fused path; set 'leafwise')"
    if cfg0.tpu_grow_mode == "auto" \
            and g0.learner.aligned_mode_ok(g0.objective):
        return "tpu_grow_mode=auto resolves to the aligned pipeline " \
               "here; set 'leafwise' to batch the fleet"
    if cfg0.tpu_fuse_iteration:
        return "tpu_fuse_iteration routes to the mega-fused single-model " \
               "program"
    if g0.objective is None:
        return "custom-objective training has no device gradient program"
    if type(g0.objective).get_gradients is not ObjectiveFunction.get_gradients:
        return f"objective {g0.objective.name!r} composes gradients " \
               "host-side"
    if getattr(g0.objective, "is_renew_tree_output", False):
        return "renew-tree-output objectives rewrite leaves host-side"
    if not all(g0._class_need_train) or g0.train_data.num_features == 0:
        return "constant-class iterations need the host constant-tree path"
    if getattr(g0.learner, "quant_bits", 0):
        return "quantized-histogram path threads a host qseq counter"
    if cfg0.sequential_device_only:
        return "forced splits / CEGB depend on host commit order"
    if g0._balanced_bagging:
        return "balanced bagging draws per-class counts (non-uniform " \
               "partition shapes)"
    base = shared_grid_signature(cfg0)
    for m, cfg in enumerate(cfgs[1:], start=1):
        if shared_grid_signature(cfg) != base:
            diff = [k for (k, a), (_, b) in
                    zip(shared_grid_signature(cfg), base) if a != b]
            return f"model {m} differs outside the sweep grid: {diff[:4]}"
    bag0 = gbdts[0]._will_bag()
    if any(g._will_bag() != bag0 for g in gbdts):
        return "mixed bagged/unbagged fleet (bagging_fraction uniform " \
               "with varying freq/seed is supported)"
    return None


def make_round_program(learner: DeviceTreeLearner, objective,
                       M: int, K: int, num_leaves: int,
                       bagged: bool, bag_cnt: int):
    """The fleet's per-round program ``fn(scores, fmasks, lr, l1, l2,
    l2c[, idx, bc], bins, bins_T) -> (scores', (rec_0..rec_{K-1}))``,
    registered process-wide.

    Operand shapes: scores [M, K, N] (donated), fmasks [M, K, F] f32,
    lr/l1/l2/l2c [M] f32, idx [M, n_pad] int32 + bc [M] int32 (bagged
    only). Returned records are TreeRecords with a leading model axis.
    """
    n = learner.n
    root_count = bag_cnt if bagged else n
    root_padded = max(_pow2ceil(root_count), learner.min_pad)
    key = ("sweep_round", M, K, bagged, root_padded,
           _normalized_learner_sig(learner), objective.trace_signature())

    def factory():
        Lm1 = max(num_leaves - 1, 1)
        nb, db, mt = learner._nb_dev, learner._db_dev, learner._mt_dev
        bundled = getattr(learner, "bundled", False)
        col = learner._col_dev if bundled else None
        boff = learner._boff_dev if bundled else None
        bpk = learner._bpk_dev if bundled else None

        def classes(score, fmask, lr, l1, l2, l2c, bins, bins_T,
                    idx=None, bc=None):
            """One model's full round: gradients once (pre-update score,
            like the sequential round), then the per-class build +
            score-update chain in class order."""
            compile_cache.note_trace()
            g, h = objective.gradients_impl(score)
            recs = []
            new_score = score
            for k in range(K):
                build = learner.sweep_build_fn(root_padded, not bagged,
                                               l1, l2, l2c)
                if bagged:
                    idxs, rec = build(bins, bins_T, idx, g[k], h[k], bc,
                                      fmask[k])
                    # out-of-bag rows also need scores -> traversal
                    trav = traversal_arrays.__wrapped__(rec, Lm1)
                    new_score = new_score.at[k].set(record_score_lane(
                        new_score[k], bins, trav, nb, db, mt, lr,
                        col, boff, bpk))
                else:
                    idxs, rec = build(bins, bins_T, g[k], h[k], fmask[k])
                    new_score = partition_score_update_lane(
                        new_score, k, rec.leaf_begin, rec.leaf_cnt_part,
                        rec.leaf_value, idxs, jnp.int32(n), lr)
                recs.append(rec)
            return new_score, tuple(recs)

        if bagged:
            def one_model(score, fmask, lr, l1, l2, l2c, idx, bc,
                          bins, bins_T):
                return classes(score, fmask, lr, l1, l2, l2c, bins,
                               bins_T, idx=idx, bc=bc)
            axes = (0, 0, 0, 0, 0, 0, 0, 0, None, None)
        else:
            def one_model(score, fmask, lr, l1, l2, l2c, bins, bins_T):
                return classes(score, fmask, lr, l1, l2, l2c, bins,
                               bins_T)
            axes = (0, 0, 0, 0, 0, 0, None, None)
        return jax.jit(jax.vmap(one_model, in_axes=axes),
                       donate_argnums=(0,))

    return compile_cache.program(key, factory), key


def lambda_operands(cfgs) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-model (l1, l2, l2 + cat_l2) f32 operand vectors. The cat sum
    is computed in HOST DOUBLE per model — the same rounding the static
    ``SplitHyper.from_config`` path bakes in (split.py lambda_l2_cat),
    so sorted-categorical gains match the sequential twin bitwise."""
    l1 = np.asarray([np.float32(c.lambda_l1) for c in cfgs], np.float32)
    l2 = np.asarray([np.float32(c.lambda_l2) for c in cfgs], np.float32)
    l2c = np.asarray(
        [np.float32(float(c.lambda_l2) + float(c.cat_l2)) for c in cfgs],
        np.float32)
    return l1, l2, l2c
