"""Batched sweep mode: one jitted round program for M boosters.

The round program is ``jit(vmap(one_model))`` over a leading model axis,
where ``one_model`` is the RAW python body of one booster's fused round:
objective gradients -> per-class whole-tree build (via
``DeviceTreeLearner.sweep_build_fn``, which threads the split lambdas as
traced scalars) -> score update (partition fill for fresh trees, record
traversal for bagged ones, both from ``ops.sweep_ops``). Raw bodies are
mandatory: vmapping the registered jitted programs re-canonicalizes
their f64 reduce-init constants to f32 under the global x64-off config,
which XLA rejects as mixed precision — the raw bodies keep the
``enable_x64`` blocks live during the vmap trace, so the batched math is
the exact expression tree the sequential programs trace, and model text
stays byte-equal per booster under ``tpu_use_f64_hist``.

Boosting variants ride the same program. GOSS adds a per-model
``[M, N]`` gradient multiplier operand plus a warm-up flag that selects
between the partition-fill score lane (warm rounds train on the full
data like fresh trees) and the traversal lane (sampled rounds) — the
top-k selection itself is a separate small registered program
(``make_goss_select_program``). DART needs NO program change at all:
its drop/renormalize machinery is host-double leaf mutation, so the
trainer reuses the sequential methods verbatim per model and only the
per-round shrinkage operand moves. Quantized histograms thread the host
``qseq`` counter as a traced per-model ``[M]`` round counter so
``ops/histogram.quantize_gh`` composes with the vmapped round.

Registry discipline: the program enters the process-wide compile cache
keyed by the learner/objective trace signatures with the swept fields
normalized out, so model #2..M cost zero traces by construction (one
program) and a SECOND fleet at the same shapes — any grid — costs zero
traces too (asserted by tests/test_sweep.py).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import compile_cache
from ..models.device_learner import (DeviceTreeLearner, _pow2ceil,
                                     traversal_arrays)
from ..ops.sweep_ops import (partition_score_update_lane,
                             record_score_lane)

# Config fields the batched program may vary PER MODEL (everything else
# must be equal across the fleet — they are traced operands or host-side
# schedule inputs, never trace constants):
#   learning_rate            -> score-update scale operand
#   lambda_l1/lambda_l2      -> split-finder operands (sweep_build_fn)
#   bagging_seed/bagging_freq-> host RNG schedule; bag partitions are
#                               per-model index operands
#   feature_fraction_seed    -> host RNG; masks are per-model operands
#   drop_seed/drop_rate/     -> DART's drop plan is drawn on HOST per
#     skip_drop                 model (never traced); only the resulting
#                               per-round shrinkage is an operand
SWEEP_VARYING = frozenset({
    "learning_rate", "lambda_l1", "lambda_l2",
    "bagging_seed", "bagging_freq", "feature_fraction_seed",
    "drop_seed", "drop_rate", "skip_drop",
})

# The sweep trainer's own knobs: runtime infrastructure, never part of
# the training math (also excluded from model text / checkpoint
# signatures — see models/model_text.py, resilience/checkpoint.py).
SWEEP_RUNTIME = frozenset({
    "tpu_sweep_mode", "tpu_sweep_checkpoint_dir",
    "tpu_sweep_checkpoint_freq", "tpu_sweep_hbm_budget_mb",
    "tpu_sweep_max_fleet",
})

_NORM = "<swept>"


def _normalized_config_items(cfg) -> Tuple:
    """``config_signature`` with swept + sweep-runtime fields pinned to a
    sentinel: the grid-independent part of a model's config."""
    return tuple(
        (k, _NORM if (k in SWEEP_VARYING or k in SWEEP_RUNTIME) else v)
        for k, v in compile_cache.config_signature(cfg))


def shared_grid_signature(cfg) -> Tuple:
    """The config signature every member of one batched SUB-FLEET must
    share (grid fields and sweep-runtime knobs normalized out). Mixed
    signatures across the whole fleet are fine — the trainer buckets
    them into sub-fleets (sweep/subfleet.py)."""
    return _normalized_config_items(cfg)


def _normalized_learner_sig(learner) -> Tuple:
    """Learner trace signature with the swept config fields normalized —
    the registry key part that makes two fleets with different grids hit
    the same program."""
    raw_cfg = compile_cache.config_signature(learner.cfg)
    norm_cfg = _normalized_config_items(learner.cfg)
    return tuple(norm_cfg if item == raw_cfg else item
                 for item in learner.trace_signature())


def sweep_variant(gbdt) -> str:
    """The batched-round flavor of one booster: ``"gbdt"`` (plain and
    DART — DART's round program IS the plain one, the drop machinery is
    host-side) or ``"goss"`` (extra multiplier/warm-up operands)."""
    from ..models.boosting_variants import GOSS
    return "goss" if type(gbdt) is GOSS else "gbdt"


def batched_gate(gbdts, cfgs) -> Optional[str]:
    """None when this member set can train as ONE batched sub-fleet;
    else the first failing reason (the trainer then buckets by shape
    signature, and only if a bucket still fails runs the interleaved
    fallback).

    The gate admits exactly the configs whose sequential twin takes the
    leaf-wise ``_train_one_iter_fused`` path with uniform shapes across
    members — plain GBDT, GOSS, and DART alike, with or without
    quantized histograms (their batched rounds replicate the sequential
    twins bit-for-bit; RF reshapes scores host-side and stays out).
    EVERY member is validated, not just member 0: a warm-started fleet
    where model k diverges must be rejected, never silently trained
    wrong in batched mode."""
    from ..models.boosting_variants import DART, GOSS
    from ..models.gbdt import GBDT
    from ..ops.objectives import ObjectiveFunction
    base = shared_grid_signature(cfgs[0])
    for m, cfg in enumerate(cfgs[1:], start=1):
        if shared_grid_signature(cfg) != base:
            diff = [k for (k, a), (_, b) in
                    zip(shared_grid_signature(cfg), base) if a != b]
            return f"model {m} differs outside the sweep grid: {diff[:4]}"
    kind = type(gbdts[0])
    for m, (g, cfg) in enumerate(zip(gbdts, cfgs)):
        if type(g) not in (GBDT, GOSS, DART):
            return f"model {m}: boosting type {type(g).__name__} " \
                   "(RF reshapes scores host-side)"
        if type(g) is not kind:
            return f"model {m}: mixed boosting types across the fleet"
        if not g.use_fused or type(g.learner) is not DeviceTreeLearner:
            return f"model {m}: fleet needs the single-device fused " \
                   "learner"
        if cfg.tpu_grow_mode not in ("leafwise", "auto"):
            return f"model {m}: tpu_grow_mode={cfg.tpu_grow_mode!r} " \
                   "(the batched round replicates the leaf-wise fused " \
                   "path; set 'leafwise')"
        if cfg.tpu_grow_mode == "auto" \
                and g.learner.aligned_mode_ok(g.objective):
            return f"model {m}: tpu_grow_mode=auto resolves to the " \
                   "aligned pipeline here; set 'leafwise' to batch the " \
                   "fleet"
        if cfg.tpu_fuse_iteration:
            return f"model {m}: tpu_fuse_iteration routes to the " \
                   "mega-fused single-model program"
        if g.objective is None:
            return f"model {m}: custom-objective training has no device " \
                   "gradient program"
        gg = g.objective.get_gradients
        if getattr(gg, "__func__", gg) \
                is not ObjectiveFunction.get_gradients:
            return f"model {m}: objective {g.objective.name!r} composes " \
                   "gradients host-side"
        if getattr(g.objective, "is_renew_tree_output", False):
            return f"model {m}: renew-tree-output objectives rewrite " \
                   "leaves host-side"
        if not all(g._class_need_train) or g.train_data.num_features == 0:
            return f"model {m}: constant-class iterations need the host " \
                   "constant-tree path"
        if cfg.sequential_device_only:
            return f"model {m}: forced splits / CEGB depend on host " \
                   "commit order"
        if type(g) is not GOSS and g._balanced_bagging:
            return f"model {m}: balanced bagging draws per-class counts " \
                   "(non-uniform partition shapes)"
    if kind is not GOSS:
        # GOSS ignores bagging_fraction/freq entirely (its sampling is
        # the per-round top-k selection), so the uniformity requirement
        # only applies to the standard bagging path
        bag0 = gbdts[0]._will_bag()
        if any(g._will_bag() != bag0 for g in gbdts):
            return "mixed bagged/unbagged fleet (bagging_fraction " \
                   "uniform with varying freq/seed is supported)"
    return None


def make_round_program(learner: DeviceTreeLearner, objective,
                       M: int, K: int, num_leaves: int,
                       bagged: bool, bag_cnt: int,
                       variant: str = "gbdt", quant: bool = False):
    """The fleet's per-round program ``fn(scores, fmasks, lr, l1, l2,
    l2c[, idx, bc][, mult, warm][, qs], bins, bins_T) -> (scores',
    (rec_0..rec_{K-1}))``, registered process-wide.

    Operand shapes: scores [M, K, N] (donated), fmasks [M, K, F] f32,
    lr/l1/l2/l2c [M] f32, idx [M, n_pad] int32 + bc [M] int32 (bagged
    only), mult [M, N] f32 + warm [M] bool (GOSS only), qs [M] int32
    (quantized histograms only — the per-model round counter; class k's
    build consumes ``qs + k + 1``, the exact sequence the sequential
    host counter hands out). Returned records are TreeRecords with a
    leading model axis.

    GOSS runs the BAGGED program shape at ``root_padded = pow2ceil(n)``:
    the whole-tree build is bitwise invariant to root padding (the
    routing masks ``pos < count`` everywhere), so one static program
    covers every per-round sampled count AND the warm-up rounds (raw
    identity partitions), with ``warm`` selecting the fresh-tree
    partition-fill score lane those rounds use sequentially.
    """
    n = learner.n
    goss = variant == "goss"
    root_count = n if goss else (bag_cnt if bagged else n)
    root_padded = max(_pow2ceil(root_count), learner.min_pad)
    key = ("sweep_round", M, K, bagged, root_padded, variant, quant,
           _normalized_learner_sig(learner), objective.trace_signature())

    def factory():
        Lm1 = max(num_leaves - 1, 1)
        nb, db, mt = learner._nb_dev, learner._db_dev, learner._mt_dev
        bundled = getattr(learner, "bundled", False)
        col = learner._col_dev if bundled else None
        boff = learner._boff_dev if bundled else None
        bpk = learner._bpk_dev if bundled else None

        # operand names after the fixed (score, fmask, lr, l1, l2, l2c)
        # prefix; bins/bins_T close the list unbatched
        extra = (["idx", "bc"] if bagged else []) \
            + (["mult", "warm"] if goss else []) \
            + (["qs"] if quant else [])

        def classes(score, fmask, lr, l1, l2, l2c, bins, bins_T,
                    idx=None, bc=None, mult=None, warm=None, qs=None):
            """One model's full round: gradients once (pre-update score,
            like the sequential round), then the per-class build +
            score-update chain in class order."""
            compile_cache.note_trace()
            g, h = objective.gradients_impl(score)
            if mult is not None:
                # GOSS re-weights the sampled small-gradient rows; warm
                # rounds arrive with mult == 1.0 (x * 1.0 is bitwise x)
                g = g * mult[None, :]
                h = h * mult[None, :]
            recs = []
            new_score = score
            for k in range(K):
                build = learner.sweep_build_fn(root_padded, not bagged,
                                               l1, l2, l2c)
                opt = (qs + jnp.int32(k + 1),) if qs is not None else ()
                if bagged:
                    idxs, rec = build(bins, bins_T, idx, g[k], h[k], bc,
                                      fmask[k], *opt)
                    # out-of-bag rows also need scores -> traversal
                    trav = traversal_arrays.__wrapped__(rec, Lm1)
                    s_bag = record_score_lane(
                        new_score[k], bins, trav, nb, db, mt, lr,
                        col, boff, bpk)
                    if warm is not None:
                        # GOSS warm-up rounds are fresh full-data trees
                        # sequentially: partition fill, not traversal
                        s_fresh = partition_score_update_lane(
                            new_score, k, rec.leaf_begin,
                            rec.leaf_cnt_part, rec.leaf_value, idxs,
                            jnp.int32(n), lr)
                        new_score = jnp.where(warm, s_fresh,
                                              new_score.at[k].set(s_bag))
                    else:
                        new_score = new_score.at[k].set(s_bag)
                else:
                    idxs, rec = build(bins, bins_T, g[k], h[k], fmask[k],
                                      *opt)
                    new_score = partition_score_update_lane(
                        new_score, k, rec.leaf_begin, rec.leaf_cnt_part,
                        rec.leaf_value, idxs, jnp.int32(n), lr)
                recs.append(rec)
            return new_score, tuple(recs)

        def one_model(*args):
            score, fmask, lr, l1, l2, l2c = args[:6]
            rest = dict(zip(extra, args[6:6 + len(extra)]))
            bins, bins_T = args[6 + len(extra):]
            return classes(score, fmask, lr, l1, l2, l2c, bins, bins_T,
                           **rest)

        axes = (0,) * (6 + len(extra)) + (None, None)
        return jax.jit(jax.vmap(one_model, in_axes=axes),
                       donate_argnums=(0,))

    return compile_cache.program(key, factory), key


def make_goss_select_program(learner: DeviceTreeLearner, objective,
                             M: int, top_k: int, other_k: int):
    """The fleet's GOSS selection program ``fn(scores, seeds, warm) ->
    (mask [M, N] bool, mult [M, N] f32)``, registered process-wide.

    One model's lane is the raw body of the sequential device select
    (``boosting_variants.GOSS._bagging``) fed from the fleet score stack
    — gradients recomputed from the pre-round score (same values the
    round program derives), |g*h| ranked, threshold at top_k, the rest
    sampled by the other_k smallest uniform keys under the per-model
    ``PRNGKey(seed)`` (seeds come from each model's host bagging RNG
    stream in model order, preserving the sequential draw sequence).
    Warm-up lanes (``warm[m]``, models still inside their
    1/learning_rate ramp) neutralize to the full-data identity: mask
    all-true, mult all-ones, and the host draws no seed for them —
    exactly the rounds the sequential twin skips sampling. Scores are
    NOT donated (the round program still consumes them)."""
    n = learner.n
    key = ("sweep_goss_select", M, n, top_k, other_k,
           _normalized_learner_sig(learner), objective.trace_signature())

    def factory():
        from ..models.boosting_variants import goss_select_body

        def select(score, seed, warm):
            compile_cache.note_trace()
            g, h = objective.gradients_impl(score)
            mask, mult = goss_select_body(g, h, seed, n, top_k, other_k)
            mask = jnp.where(warm, True, mask)
            mult = jnp.where(warm, jnp.float32(1.0), mult)
            return mask, mult
        return jax.jit(jax.vmap(select, in_axes=(0, 0, 0)))

    return compile_cache.program(key, factory), key


def lambda_operands(cfgs) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-model (l1, l2, l2 + cat_l2) f32 operand vectors. The cat sum
    is computed in HOST DOUBLE per model — the same rounding the static
    ``SplitHyper.from_config`` path bakes in (split.py lambda_l2_cat),
    so sorted-categorical gains match the sequential twin bitwise."""
    l1 = np.asarray([np.float32(c.lambda_l1) for c in cfgs], np.float32)
    l2 = np.asarray([np.float32(c.lambda_l2) for c in cfgs], np.float32)
    l2c = np.asarray(
        [np.float32(float(c.lambda_l2) + float(c.cat_l2)) for c in cfgs],
        np.float32)
    return l1, l2, l2c
