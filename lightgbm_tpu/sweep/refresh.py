"""Continual fleet refresh: warm-start retrain -> serving hot-swap.

The production loop the sweep trainer closes: a fleet of live models is
periodically retrained (``train_many(init_models=...)`` — every member
warm-starts from its currently-served predecessor) and each refreshed
model is published as a serving checkpoint the serving plane already
knows how to consume. ``write_serving_checkpoint`` emits exactly the
layout ``serving.registry.load_checkpoint_model_text`` reads — a
``MANIFEST.json {"latest": ...}`` pointer next to versioned
``ckpt_NNNNNN/model.txt`` dirs, manifest written LAST so a concurrent
watcher poll can never observe a torn model — which means the existing
``serving.watcher`` hot-swaps the refreshed fleet live with no new
serving-side code.

``RefreshTrigger`` closes the observe->retrain edge of the loop on two
signals. The LATENCY signal: it watches the per-model
``serve_slo_burn_rate`` the request tracer aggregates (obs/reqtrace.py)
and enqueues models whose burn rate crosses the high watermark. The
QUALITY signal: fed a held-out reference window per model
(``set_reference``) and the live scores the serving plane emits
(``observe_scores``), it tracks the drift between the live score
distribution and the reference — quantile-profile distance, scale-free
— and enqueues a model whose drift stays above ``drift_threshold`` for
``drift_sustain`` consecutive full windows (sustained, so one odd
batch never triggers a retrain). Both paths emit one
``sweep_refresh_triggered`` event per enqueue, tagged with
``reason="slo_burn"`` or ``reason="score_drift"``. ``refresh_due``
drains the queue into a ``refresh_many`` call covering only the
enqueued members.
"""
from __future__ import annotations

import json
import os
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..basic import Booster, Dataset, LightGBMError
from ..utils import log
from .trainer import train_many

__all__ = ["refresh_many", "write_serving_checkpoint", "RefreshTrigger",
           "refresh_due"]


class RefreshTrigger:
    """Serving-signal watcher: burn-rate crossings -> refresh queue.

    ``models[i]`` is fleet member i's serving-plane model name (the key
    ``RequestTracer.burn_rates()`` reports). Feed it burn-rate
    snapshots via ``observe`` (or ``poll(tracer)``); a member whose
    rate reaches ``threshold`` is enqueued once (edge-triggered — it
    re-arms when ``drain`` empties the queue, matching the tracer's own
    ``serve_slo_burn`` hysteresis discipline) and announced with a
    ``sweep_refresh_triggered`` event. ``drain`` hands the due fleet
    indices to the next refresh cycle."""

    # live-score window sizing: drift is judged over the most recent
    # _DRIFT_WINDOW scores, and only once at least _DRIFT_MIN_N have
    # arrived (matching the burn window's warm-up discipline)
    _DRIFT_WINDOW = 256
    _DRIFT_MIN_N = 64
    # quantile grid the live/reference score profiles are compared on
    _DRIFT_QUANTS = np.linspace(0.05, 0.95, 19)

    def __init__(self, models: Sequence[str],
                 threshold: Optional[float] = None,
                 drift_threshold: float = 0.0,
                 drift_sustain: int = 3) -> None:
        from ..obs.reqtrace import SLO_BURN_HIGH
        self.models = list(models)
        self.threshold = float(SLO_BURN_HIGH if threshold is None
                               else threshold)
        self._index = {name: i for i, name in enumerate(self.models)}
        self._due: Dict[int, float] = {}
        # score-drift detection (0 disables): per-model reference
        # quantile profile + rolling live window + consecutive-hot count
        self.drift_threshold = float(drift_threshold)
        self.drift_sustain = max(int(drift_sustain), 1)
        self._ref_q: Dict[str, np.ndarray] = {}
        self._ref_scale: Dict[str, float] = {}
        self._live: Dict[str, deque] = {}
        self._hot: Dict[str, int] = {}

    def observe(self, burn_rates: Dict[str, float]) -> List[int]:
        """Ingest one burn-rate snapshot; returns newly-enqueued fleet
        indices (already-due members don't re-trigger)."""
        fresh = []
        for name, rate in burn_rates.items():
            i = self._index.get(name)
            if i is None or i in self._due or rate < self.threshold:
                continue
            self._due[i] = float(rate)
            fresh.append(i)
            log.event("sweep_refresh_triggered", model=name, index=i,
                      reason="slo_burn",
                      burn_rate=round(float(rate), 4),
                      threshold=self.threshold)
        return fresh

    # -- score drift -------------------------------------------------------
    def set_reference(self, name: str, scores) -> None:
        """Install a model's held-out reference window: the raw-margin
        distribution its live traffic is expected to follow (typically
        the model's scores over a held-out validation slice at deploy
        time). Resets any live window collected so far."""
        s = np.asarray(scores, np.float64).reshape(-1)
        if s.size < 2:
            raise ValueError(
                f"reference window for {name!r} needs >= 2 scores")
        self._ref_q[name] = np.quantile(s, self._DRIFT_QUANTS)
        # scale-free drift: quantile gaps are normalized by the
        # reference spread so one threshold works across objectives
        self._ref_scale[name] = max(float(np.std(s)), 1e-12)
        self._live[name] = deque(maxlen=self._DRIFT_WINDOW)
        self._hot[name] = 0

    def drift_of(self, name: str) -> Optional[float]:
        """Current live-vs-reference drift (mean quantile distance over
        the rolling window, in reference-spread units); None before the
        window warms up or without a reference."""
        ref = self._ref_q.get(name)
        live = self._live.get(name)
        if ref is None or live is None or len(live) < self._DRIFT_MIN_N:
            return None
        lq = np.quantile(np.asarray(live, np.float64),
                         self._DRIFT_QUANTS)
        return float(np.mean(np.abs(lq - ref)) / self._ref_scale[name])

    def observe_scores(self, name: str, scores) -> bool:
        """Feed live scores (raw margins) for one model; returns True
        when this observation enqueued it. Sustained drift — above
        ``drift_threshold`` on ``drift_sustain`` consecutive full-window
        observations — triggers; a single hot window never does."""
        if self.drift_threshold <= 0 or name not in self._ref_q:
            return False
        i = self._index.get(name)
        if i is None:
            return False
        self._live[name].extend(
            np.asarray(scores, np.float64).reshape(-1).tolist())
        drift = self.drift_of(name)
        if drift is None:
            return False
        if drift < self.drift_threshold:
            self._hot[name] = 0
            return False
        self._hot[name] += 1
        if self._hot[name] < self.drift_sustain or i in self._due:
            return False
        self._due[i] = float(drift)
        log.event("sweep_refresh_triggered", model=name, index=i,
                  reason="score_drift", drift=round(drift, 4),
                  threshold=self.drift_threshold,
                  sustained=self._hot[name])
        return True

    def poll(self, tracer) -> List[int]:
        """``observe`` straight off a live ``RequestTracer``."""
        return self.observe(tracer.burn_rates())

    def due(self) -> List[int]:
        return sorted(self._due)

    def drain(self) -> List[int]:
        """Pop the queue (re-arming every drained member — including
        the drift counters, so a refreshed model must drift anew)."""
        out = sorted(self._due)
        self._due.clear()
        for name in self._hot:
            self._hot[name] = 0
        return out


def refresh_due(trigger: RefreshTrigger,
                params_list: Sequence[Dict[str, Any]],
                train_set: Dataset, serve_dirs: Sequence[str],
                num_boost_round: int = 100
                ) -> Tuple[List[int], List[Booster]]:
    """Drain ``trigger`` and refresh exactly the burning members: the
    due indices select the params/serve_dir subset handed to
    ``refresh_many`` (warm-starting from the served versions as usual).
    Returns ``(indices, refreshed boosters)`` — empty when nothing is
    due, without touching the trainer."""
    idx = trigger.drain()
    if not idx:
        return [], []
    boosters = refresh_many([params_list[i] for i in idx], train_set,
                            [serve_dirs[i] for i in idx],
                            num_boost_round)
    return idx, boosters


def write_serving_checkpoint(directory: str, model_text: str) -> str:
    """Publish one model text as the next serving checkpoint version in
    ``directory``; returns the version name (``ckpt_NNNNNN``).

    Versions continue from the directory's manifest (fresh dirs start
    at ``ckpt_000001``). The model file is written atomically first and
    the manifest pointer flipped after, so readers polling through
    ``load_checkpoint_model_text`` see either the old complete version
    or the new complete version, never a partial write."""
    from ..resilience.checkpoint import (MANIFEST_NAME, atomic_write_text,
                                         read_manifest)
    man = read_manifest(directory)
    version = 0
    if man is not None:
        latest = str(man.get("latest") or "")
        tail = latest.rsplit("_", 1)[-1]
        if tail.isdigit():
            version = int(tail)
    name = f"ckpt_{version + 1:06d}"
    atomic_write_text(os.path.join(directory, name, "model.txt"),
                      model_text)
    atomic_write_text(os.path.join(directory, MANIFEST_NAME),
                      json.dumps({"latest": name}))
    return name


def refresh_many(params_list: Sequence[Dict[str, Any]],
                 train_set: Dataset, serve_dirs: Sequence[str],
                 num_boost_round: int = 100,
                 init_models: Optional[Sequence[
                     Union[str, Booster, None]]] = None) -> List[Booster]:
    """One refresh cycle for a served fleet.

    ``serve_dirs[m]`` is model m's serving checkpoint directory (what a
    ``serving.watcher`` entry polls). When ``init_models`` is None the
    warm starts are read from those directories' CURRENT versions —
    the continual-learning default: each cycle extends the trees being
    served right now. Members whose directory is still empty train from
    scratch. Returns the refreshed Boosters after publishing each as
    its directory's next version."""
    if len(serve_dirs) != len(params_list):
        raise LightGBMError("refresh_many needs one serve_dir per model")
    if init_models is None:
        from ..serving.registry import load_checkpoint_model_text
        seeds: List[Optional[Booster]] = []
        for d in serve_dirs:
            cur = load_checkpoint_model_text(d)
            seeds.append(None if cur is None
                         else Booster(model_str=cur[0]))
        init_models = seeds
    boosters = train_many(params_list, train_set, num_boost_round,
                          init_models=init_models)
    versions = []
    for bst, d in zip(boosters, serve_dirs):
        versions.append(write_serving_checkpoint(d, bst.model_to_string()))
    log.event("sweep_refresh", models=len(boosters),
              rounds=int(num_boost_round), versions=versions)
    return boosters
