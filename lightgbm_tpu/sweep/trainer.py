"""The fleet trainer: M boosters, one shared Dataset, batched rounds.

``train_many`` is ``engine.train``'s many-model sibling. M "probe"
Boosters are constructed exactly like sequential training boosters —
they own the per-model Config, the host RNG streams (bagging / feature
fraction / DART drops), warm-start trees, and the round-0
``boost_from_average`` mutation — but in batched mode they never
dispatch a per-model training program. The fleet is first partitioned
into shape-bucketed SUB-FLEETS (``sweep/subfleet.py``: members sharing
``shared_grid_signature`` group together; the ``obs/memory`` accountant
or the ``tpu_sweep_hbm_budget_mb`` / ``tpu_sweep_max_fleet`` knobs chunk
groups whose ``[M, K, N]`` score stack would blow the HBM budget, in
pow2 sizes so programs are reused across chunks). Each sub-fleet runs
one registered round program (``sweep/batched.py``) advancing all its
score planes per round; sub-fleets step round-robin per round so the
async dispatch queue stays full across them.

Boosting variants train batched too:

- **GOSS** — the per-round top-k selection is one extra registered
  vmapped program over the fleet score stack; the keep-mask comes back
  in a single pull, the re-weight multiplier stays on device as a round
  operand, and per-model warm-up flags (models still inside their
  1/learning_rate ramp draw no sample sequentially) select the
  fresh-tree score lane inside the round program.
- **DART** — the round program is the PLAIN one: drops and
  renormalization are host-double leaf-value mutations whose rounding
  is association-order sensitive, so byte-equality forces reusing the
  sequential ``_dropping_trees``/``_normalize`` machinery verbatim per
  model, with the model's fleet score slice swapped in around each
  call. Records materialize every round (one batched pull for the
  whole sub-fleet — M times fewer pulls than sequential DART) and the
  per-round shrinkage is rebuilt into the LR operand.
- **Quantized histograms** — the host qseq counter becomes a per-model
  ``[M]`` round-counter operand.

The batched TreeRecords land in a central device log and
``probe._gbdt.models`` holds lightweight ``_RecRef`` entries into it
(DART holds host Trees directly). Because the refs live in the probe's
own model list, the sequential bookkeeping applies to the fleet
unchanged: ``boost_from_average``'s empty-models gate closes after
round 0, warm-start prepends stay ahead of new trees, and the 16-round
deferred trailing-empty trim deletes from the same list with the same
arithmetic. Export is ONE device_get of the whole log followed by the
same model-string round-trip ``engine.train`` performs.

Parity contract: under ``tpu_use_f64_hist`` the model text of fleet
member m is byte-equal to ``engine.train`` with the same params
(tests/test_sweep.py + tests/test_sweep_variants.py assert it for
plain / bagged / multiclass / GOSS / DART / quantized fleets).

Configs the batched gate rejects for every sub-fleet fall back to
INTERLEAVED mode: the probes train for real, one round each in
round-robin order. Both modes share the fleet checkpoint format
(``tpu_sweep_checkpoint_dir`` / ``tpu_sweep_checkpoint_freq``): model
texts + per-model score planes + host RNG + pending trim counters (+
DART tree weights) per model, so a preempted sweep resumes bitwise on
either path.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import compile_cache
from ..basic import Booster, Dataset, LightGBMError
from ..utils import log
from .batched import (batched_gate, lambda_operands,
                      make_goss_select_program, make_round_program)
from .subfleet import SubfleetPlan, plan_subfleets

_FLEET_SCHEMA = 2

# trainer-level aliases engine.train also honors (reference sklearn.py
# alias table); they must not leak into Config.from_params
_ROUND_ALIASES = ("num_boost_round", "num_iterations", "num_iteration",
                  "n_iter", "num_tree", "num_trees", "num_round",
                  "num_rounds", "n_estimators")


class _RecRef:
    """A fleet tree still on device: an index into the central record
    log (one ``[M]``-leading TreeRecord tuple per round) plus the
    per-model shrinkage/bias — the model-axis analogue of
    ``gbdt.LazyTree``. Lives in ``probe._gbdt.models`` so the
    sequential bookkeeping (boost_from_average gating, warm-start
    prepends, trailing-empty trim) applies unchanged."""

    __slots__ = ("entry", "k", "shrinkage", "bias")

    def __init__(self, entry: int, k: int, shrinkage: float,
                 bias: float) -> None:
        self.entry = entry
        self.k = k
        self.shrinkage = shrinkage
        self.bias = bias


class _Fleet:
    """One sub-fleet's batched device state; also the HBM-accountant
    owner for the stacked score buffer (obs/memory.py
    ``sweep/scores``)."""

    def __init__(self, scores: jax.Array) -> None:
        self.scores = scores          # [M, K, N] f32, donated per round
        self.rec_log: List[Tuple] = []  # one K-tuple of batched recs/round


def train_many(params_list: Sequence[Dict[str, Any]], train_set: Dataset,
               num_boost_round: int = 100,
               init_models: Optional[Sequence[
                   Union[str, Booster, None]]] = None) -> List[Booster]:
    """Train ``len(params_list)`` boosters against one shared Dataset.

    Every params dict may vary the sweep grid fields
    (``sweep.SWEEP_VARYING``: learning_rate, lambda_l1/l2, bagging seed
    and freq, feature_fraction_seed, DART drop seed/rate/skip) freely;
    members that differ elsewhere (num_leaves, objective, boosting
    variant, ...) are bucketed into shape-shared sub-fleets, each its
    own batched program. Only configs no sub-fleet can express fall
    back to the interleaved path under ``tpu_sweep_mode="auto"``
    (``"batched"`` raises with the gate's reason). ``init_models``
    (per-model Booster / model file / None) warm-starts members like
    ``engine.train(init_model=...)``; it is ignored when resuming from
    ``tpu_sweep_checkpoint_dir`` (the checkpointed texts already
    contain the seed trees). Returns M independent Boosters
    round-tripped through their model strings, exactly like
    ``engine.train``.
    """
    if not params_list:
        raise LightGBMError("train_many needs at least one params dict")
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    t_start = time.perf_counter()
    traces0 = compile_cache.trace_count()

    probes: List[Booster] = []
    clean_params: List[Dict[str, Any]] = []
    for params in params_list:
        params = dict(params)
        for alias in _ROUND_ALIASES:
            if alias in params:
                num_boost_round = int(params.pop(alias))
        train_set._update_params(params)
        clean_params.append(params)
        probes.append(Booster(params=params, train_set=train_set))
    gbdts = [b._gbdt for b in probes]
    cfgs = [b._cfg for b in probes]
    cfg0 = cfgs[0]
    M = len(probes)

    ledger = None
    if cfg0.tpu_trace:
        from ..obs import ledger as obs_ledger
        tdir = cfg0.tpu_trace_dir or "lgbt_trace"
        ledger = obs_ledger.RoundLedger.for_training(tdir, cfg0)

    ckpt_dir = cfg0.tpu_sweep_checkpoint_dir
    loaded = _fleet_ckpt_load(ckpt_dir) if ckpt_dir else None
    if loaded is not None and int(loaded[0]["models"]) != M:
        raise LightGBMError(
            f"sweep resume: checkpoint holds {loaded[0]['models']} models, "
            f"fleet has {M}")

    if init_models is not None and loaded is None:
        if len(init_models) != M:
            raise LightGBMError("init_models must have one entry per model")
        from ..engine import _seed_from_model
        for probe, init in zip(probes, init_models):
            if init is None:
                continue
            ib = Booster(model_file=init) if isinstance(init, str) else init
            _seed_from_model(probe, ib)

    mode = (cfg0.tpu_sweep_mode or "auto").lower()
    if mode not in ("auto", "batched", "interleaved"):
        raise LightGBMError(f"unknown tpu_sweep_mode={mode!r}")
    plans = plan_subfleets(gbdts, cfgs)
    reason = None
    for plan in plans:
        reason = batched_gate([gbdts[i] for i in plan.indices],
                              [cfgs[i] for i in plan.indices])
        if reason is not None:
            break
    if mode == "batched" and reason is not None:
        raise LightGBMError(f"tpu_sweep_mode=batched rejected: {reason}")
    use_batched = mode != "interleaved" and reason is None
    chosen = "batched" if use_batched else "interleaved"
    if loaded is not None and loaded[0].get("mode") != chosen:
        raise LightGBMError(
            f"sweep resume: checkpoint was written in "
            f"{loaded[0].get('mode')!r} mode, this run chose {chosen!r}")

    fields: Dict[str, Any] = {"models": M, "mode": chosen,
                              "rounds": int(num_boost_round)}
    if use_batched:
        fields["subfleets"] = len(plans)
    if not use_batched and reason is not None:
        fields["fallback_reason"] = reason
    log.event("sweep_init", **fields)
    if ledger is not None:
        ledger.commit({"kind": "note", "note": "sweep_init", **fields})

    try:
        if use_batched:
            out = _train_batched(probes, gbdts, cfgs, clean_params,
                                 int(num_boost_round), ledger, loaded,
                                 plans)
        else:
            out = _train_interleaved(probes, gbdts, cfgs, clean_params,
                                     int(num_boost_round), loaded)
    finally:
        if ledger is not None:
            ledger.close()
    if ledger is not None:
        for bst in out:
            # same carry engine.train does: the ledger lives on the
            # training probes, which the fresh boosters no longer hold
            bst._telemetry = ledger
    log.event("sweep_train", models=M, mode=chosen,
              rounds=int(num_boost_round),
              wall_s=round(time.perf_counter() - t_start, 3),
              traces=compile_cache.trace_count() - traces0)
    return out


# ----------------------------------------------------------------------
# batched path
# ----------------------------------------------------------------------

class _BatchedRun:
    """One sub-fleet's stepping state: its registered round program,
    stacked score buffer, per-model round bookkeeping, and the
    variant-specific host schedule. ``step(r)`` advances every member
    one round; the trainer steps all runs round-robin so sub-fleet #2's
    host work overlaps sub-fleet #1's device work."""

    def __init__(self, sid: int, plan: SubfleetPlan, probes, gbdts,
                 cfgs, ledger) -> None:
        from ..models.boosting_variants import DART, GOSS
        from ..models.device_learner import _pow2ceil
        self.sid = sid
        self.plan = plan
        self.idx = list(plan.indices)   # global model indices
        self.probes, self.gbdts, self.cfgs = probes, gbdts, cfgs
        g0 = gbdts[0]
        self.lrn = g0.learner
        self.cfg0 = cfgs[0]
        self.M = len(gbdts)
        self.K = g0.num_tree_per_iteration
        self.F = self.lrn.num_features
        self.n = g0.num_data
        self.dart = type(g0) is DART
        self.goss = type(g0) is GOSS
        # vmap over a size-1 model axis lets XLA collapse the batch dim
        # and re-associate the arithmetic, breaking bitwise parity with
        # the M>=2 programs AND the sequential twin. Pad single-model
        # sub-fleets with a ghost lane (lane 0's operands duplicated,
        # outputs ignored) — which also makes them share the real M=2
        # program's trace.
        self.ghost = self.M == 1
        self.Mp = 2 if self.ghost else self.M
        self.variant = "goss" if self.goss else "gbdt"
        self.quant = bool(getattr(self.lrn, "quant_bits", 0))
        self.bagged = True if self.goss else g0._will_bag()
        self.bag_cnt = self.n if self.goss or not self.bagged \
            else int(self.cfg0.bagging_fraction * self.n)
        self.iters = [0] * self.M
        self.pending: List[Any] = []
        self.stopped = [False] * self.M
        self.biases = [[0.0] * self.K for _ in range(self.M)]
        self.first_fresh = True
        self.ledger = ledger
        self.fleet: Optional[_Fleet] = None
        self.select_fn = None
        self._ones_mult = None
        self._identity = np.arange(self.n, dtype=np.int32)
        if self.goss:
            self.warm_limits = [int(1.0 / c.learning_rate) for c in cfgs]
            self.top_k = max(1, int(self.n * self.cfg0.top_rate))
            self.other_k = max(1, int(self.n * self.cfg0.other_rate))
        self.idx_pad = self.lrn.n + max(_pow2ceil(self.lrn.n),
                                        self.lrn.min_pad)

    # -- lifecycle ------------------------------------------------------
    def init_fresh(self) -> None:
        """Round-0 init exactly like the sequential loop head: the
        boost_from_average gate self-closes once refs land in
        probe.models."""
        for m, g in enumerate(self.gbdts):
            for k in range(self.K):
                self.biases[m][k] = g.boost_from_average(k)

    def resume(self, state) -> None:
        """Per-run slices of the global checkpoint state (scores / RNG /
        trees were already installed on the probes by _fleet_resume)."""
        self.first_fresh = False
        self.iters = [int(state["iters"][i]) for i in self.idx]
        self.stopped = [bool(s) for s in
                        [state.get("stopped", [False] * 10 ** 6)[i]
                         for i in self.idx]]
        per_model = state["pending"]
        depth = len(per_model[self.idx[0]])
        self.pending = [
            np.asarray([int(per_model[i][d]) for i in self.idx], np.int32)
            for d in range(depth)]

    def start(self) -> None:
        from ..obs import memory as obs_memory
        g0 = self.gbdts[0]
        self.fn, _key = make_round_program(
            self.lrn, g0.objective, self.Mp, self.K,
            self.cfg0.num_leaves, self.bagged, self.bag_cnt,
            variant=self.variant, quant=self.quant)
        self.fleet = _Fleet(self._pad(jnp.stack(
            [g.train_score.score for g in self.gbdts])))
        for g in self.gbdts:
            # the fleet buffer owns the training scores now; drop the
            # per-probe planes so HBM holds one fleet copy, not two
            g.train_score.score = g.train_score.score[:, :0]
        name = "sweep/scores" if self.sid == 0 \
            else f"sweep/scores/{self.sid}"
        obs_memory.track(name, self.fleet,
                         lambda fl: int(fl.scores.nbytes))
        self.LR = self._pad(jnp.asarray(
            [np.float32(g.shrinkage_rate) for g in self.gbdts],
            jnp.float32))
        l1, l2, l2c = lambda_operands(self.cfgs)
        self.L1, self.L2, self.L2C = (self._pad(jnp.asarray(l1)),
                                      self._pad(jnp.asarray(l2)),
                                      self._pad(jnp.asarray(l2c)))
        self.bins, self.bins_T = self.lrn.bins_dev, self.lrn.bins_T_dev
        log.event("sweep_subfleet", index=self.sid, models=self.idx,
                  size=self.M, reason=self.plan.reason,
                  score_mb=round(self.plan.score_bytes / (1 << 20), 2),
                  variant="dart" if self.dart else self.variant,
                  quant=self.quant)

    def _pad(self, a):
        """Duplicate lane 0 into the ghost lane of an [M]-leading
        operand (no-op for real M>=2 sub-fleets)."""
        if not self.ghost:
            return a
        a = jnp.asarray(a)
        return jnp.concatenate([a, a[:1]], axis=0)

    # -- per-round host schedules --------------------------------------
    def _feature_masks(self, skip=None) -> np.ndarray:
        FM = np.empty((self.M, self.K, self.F), np.float32)
        for m, g in enumerate(self.gbdts):
            if skip is not None and skip[m]:
                # stopped members draw no RNG (sequential twins stopped
                # training); their lane trains on a full mask, discarded
                FM[m] = 1.0
                continue
            for k in range(self.K):
                fm = g.learner.feature_mask()
                FM[m, k, :] = 1.0 if fm is None \
                    else fm.astype(np.float32)
        return FM

    def _goss_operands(self, r) -> List[Any]:
        """GOSS host schedule, sequential order per model: the warm-up
        check against this model's 1/learning_rate ramp, one bag-RNG
        seed draw for sampling models only, then the device top-k
        select (one program for the sub-fleet, one mask pull)."""
        from ..ops.sweep_ops import stacked_bag_partitions
        gbdts = self.gbdts
        warm = np.asarray([self.iters[m] < self.warm_limits[m]
                           for m in range(self.M)], bool)
        seeds = np.zeros(self.M, np.uint32)
        for m, g in enumerate(gbdts):
            g._goss_multiplier = None
            if warm[m]:
                g.bag_data_indices = None
                g.bag_data_cnt = self.n
            else:
                seeds[m] = np.uint32(g._bag_rng.randint(0, 2 ** 31 - 1))
        WARM = self._pad(jnp.asarray(warm))
        if bool(warm.all()):
            if self._ones_mult is None:
                self._ones_mult = jnp.ones((self.Mp, self.n),
                                           jnp.float32)
            MULT = self._ones_mult
            idx_list = [self._identity] * self.M
            bc = [self.n] * self.M
        else:
            if self.select_fn is None:
                self.select_fn, _ = make_goss_select_program(
                    self.lrn, gbdts[0].objective, self.Mp, self.top_k,
                    self.other_k)
            mask_dev, MULT = self.select_fn(
                self.fleet.scores, self._pad(jnp.asarray(seeds)), WARM)
            masks = np.asarray(jax.device_get(mask_dev))
            idx_list, bc = [], []
            for m, g in enumerate(gbdts):
                if warm[m]:
                    idx_list.append(self._identity)
                    bc.append(self.n)
                else:
                    sel = np.nonzero(masks[m])[0].astype(np.int32)
                    g.bag_data_indices = sel
                    g.bag_data_cnt = len(sel)
                    idx_list.append(sel)
                    bc.append(len(sel))
        IDX = self._pad(stacked_bag_partitions(idx_list, self.idx_pad))
        return [IDX, self._pad(jnp.asarray(bc, jnp.int32)), MULT, WARM]

    def _bag_operands(self) -> List[Any]:
        from ..ops.sweep_ops import stacked_bag_partitions
        # host RNG schedule in sequential order: bag redraw first, then
        # the per-class feature masks (_train_one_iter_impl)
        for m, g in enumerate(self.gbdts):
            if not self.stopped[m]:
                g._bagging(self.iters[m])
        IDX = self._pad(stacked_bag_partitions(
            [g.bag_data_indices for g in self.gbdts], self.idx_pad))
        BC = self._pad(jnp.asarray(
            [int(g.bag_data_cnt) for g in self.gbdts], jnp.int32))
        return [IDX, BC]

    # -- stepping -------------------------------------------------------
    def step(self, r: int) -> None:
        if self.dart:
            self._step_dart(r)
        else:
            self._step_plain(r)

    def _step_plain(self, r: int) -> None:
        gbdts = self.gbdts
        rnd_iters = list(self.iters)
        traces_before = compile_cache.trace_count()
        t0 = time.perf_counter()
        if self.goss:
            extras = self._goss_operands(r)
        elif self.bagged:
            extras = self._bag_operands()
        else:
            extras = []
        FM = self._pad(jnp.asarray(self._feature_masks()))
        if self.quant:
            extras.append(jnp.full((self.Mp,), r * self.K, jnp.int32))
        self.fleet.scores, recs = self.fn(
            self.fleet.scores, FM, self.LR, self.L1,
            self.L2, self.L2C, *extras, self.bins, self.bins_T)
        self.fleet.rec_log.append(recs)
        entry = len(self.fleet.rec_log) - 1
        for m, g in enumerate(gbdts):
            for k in range(self.K):
                g.models.append(_RecRef(
                    entry, k, float(g.shrinkage_rate),
                    self.biases[m][k] if self.first_fresh else 0.0))
            self.iters[m] += 1
        self.first_fresh = False
        for k in range(self.K):
            self.pending.append(recs[k].num_splits)
        t_host = time.perf_counter()

        fenced = False
        if len(self.pending) >= 16 * self.K:
            # deferred trailing-empty trim, per model (the same batched
            # pull + arithmetic as gbdt._trim_trailing_empty)
            ns = [np.asarray(x) for x in jax.device_get(self.pending)]
            self.pending = []
            fenced = True
            for m, g in enumerate(gbdts):
                col = [int(x[m]) for x in ns]
                empty_trailing = 0
                for it in range(len(col) // self.K - 1, -1, -1):
                    if max(col[it * self.K:(it + 1) * self.K]) == 0:
                        empty_trailing += 1
                    else:
                        break
                if empty_trailing and len(g.models) > self.K:
                    drop = min(empty_trailing * self.K,
                               len(g.models) - self.K)
                    del g.models[-drop:]
                    self.iters[m] -= drop // self.K
        t1 = time.perf_counter()
        self._commit_ledger(rnd_iters, t0, t_host, t1, fenced,
                            traces_before)

    def _step_dart(self, r: int) -> None:
        """One DART round: per-model host drops against the fleet score
        slices, the PLAIN batched build with this round's shrinkage
        operand, immediate materialization (one batched pull for the
        sub-fleet), then per-model normalization — the sequential
        dart.hpp machinery verbatim, so the host-double leaf mutation
        chains stay byte-equal."""
        from ..models.gbdt import K_EPSILON
        gbdts = self.gbdts
        rnd_iters = list(self.iters)
        traces_before = compile_cache.trace_count()
        t0 = time.perf_counter()
        for m, g in enumerate(gbdts):
            if self.stopped[m]:
                continue
            g.iter = self.iters[m]
            g.train_score.score = self.fleet.scores[m]
            g._dropping_trees()
            self.fleet.scores = self.fleet.scores.at[m].set(
                g.train_score.score)
            g.train_score.score = g.train_score.score[:, :0]
        LR = self._pad(jnp.asarray(
            [np.float32(g.shrinkage_rate) for g in gbdts], jnp.float32))
        extras = self._bag_operands() if self.bagged else []
        FM = self._pad(jnp.asarray(self._feature_masks(skip=self.stopped)))
        if self.quant:
            extras.append(jnp.full((self.Mp,), r * self.K, jnp.int32))
        self.fleet.scores, recs = self.fn(
            self.fleet.scores, FM, LR, self.L1, self.L2,
            self.L2C, *extras, self.bins, self.bins_T)
        t_host = time.perf_counter()
        host_recs = jax.device_get(recs)
        for m, g in enumerate(gbdts):
            if self.stopped[m]:
                continue
            shrink = float(g.shrinkage_rate)
            trees = []
            ns_max = 0
            for k in range(self.K):
                rec_m = jax.tree_util.tree_map(lambda a: a[m],
                                               host_recs[k])
                ns_max = max(ns_max, int(rec_m.num_splits))
                tree = g.learner.record_to_tree(rec_m, shrink)
                bias = self.biases[m][k] if self.first_fresh else 0.0
                if abs(bias) > K_EPSILON:
                    tree.add_bias(bias)
                trees.append(tree)
            if ns_max == 0 and len(g.models) > 0:
                # reference immediate stop (dart train_one_iter): the
                # no-split iteration is deleted and _normalize skipped —
                # dropped trees stay negated, bug-compatibly
                self.stopped[m] = True
                continue
            g.models.extend(trees)
            self.iters[m] += 1
            g.iter = self.iters[m]
            g.train_score.score = self.fleet.scores[m]
            g._normalize()
            self.fleet.scores = self.fleet.scores.at[m].set(
                g.train_score.score)
            g.train_score.score = g.train_score.score[:, :0]
            if not g.cfg.uniform_drop:
                g.tree_weight.append(g.shrinkage_rate)
                g.sum_weight += g.shrinkage_rate
        self.first_fresh = False
        t1 = time.perf_counter()
        self._commit_ledger(rnd_iters, t0, t_host, t1, True,
                            traces_before)

    def _commit_ledger(self, rnd_iters, t0, t_host, t1, fenced,
                       traces_before) -> None:
        if self.ledger is None:
            return
        wall = round((t1 - t0) * 1e3, 3)
        dev = round((t1 - t_host) * 1e3, 3) if fenced else 0.0
        traces_delta = compile_cache.trace_count() - traces_before
        for m, g in enumerate(self.gbdts):
            rec = {"kind": "round", "round": rnd_iters[m],
                   "wall_ms": wall, "device_ms": dev,
                   "t0": round(t0, 6), "subfleet": self.sid,
                   "traces": traces_delta if m == 0 else 0,
                   "path": "sweep", "aligned": False, "fallbacks": 0,
                   "trees": len(g.models), "model": self.idx[m],
                   "bag_cnt": int(g.bag_data_cnt)
                   if self.bagged and g.bag_data_indices is not None
                   else int(self.n)}
            if fenced:
                rec["timing"] = "fenced"
                rec["terms_ms"] = {"sweep": dev}
            self.ledger.commit(rec)

    # -- export ---------------------------------------------------------
    def finish(self) -> None:
        """Resolve refs and hand each probe its final state; packaging
        happens fleet-wide in _train_batched."""
        trees_per_model = _materialize_fleet(self.gbdts,
                                             self.fleet.rec_log)
        for m, g in enumerate(self.gbdts):
            g.models = trees_per_model[m]
            g.iter = self.iters[m]
            g._pending_numsplits = []
            g.train_score.score = self.fleet.scores[m]


def _train_batched(probes, gbdts, cfgs, clean_params, num_boost_round,
                   ledger, loaded, plans) -> List[Booster]:
    cfg0 = cfgs[0]
    runs = [
        _BatchedRun(s, plan,
                    [probes[i] for i in plan.indices],
                    [gbdts[i] for i in plan.indices],
                    [cfgs[i] for i in plan.indices], ledger)
        for s, plan in enumerate(plans)]

    start_round = 0
    if loaded is not None:
        state, texts, arrays = loaded
        layout = [list(p.indices) for p in plans]
        if state.get("subfleets") != layout:
            raise LightGBMError(
                "sweep resume: checkpoint sub-fleet layout "
                f"{state.get('subfleets')} does not match this run's "
                f"{layout} (HBM budget / fleet knobs changed?)")
        start_round = _fleet_resume(state, texts, arrays, gbdts, cfgs)
        for run in runs:
            run.resume(state)
    else:
        for run in runs:
            run.init_fresh()
    for run in runs:
        run.start()

    watch = None
    if len(runs) >= 2:
        from ..obs.straggler import ImbalanceWatch
        from ..obs.timeline import timeline_on
        if timeline_on(cfg0):
            watch = ImbalanceWatch(
                threshold=float(cfg0.tpu_straggler_threshold),
                rounds=int(cfg0.tpu_straggler_rounds))
    ckpt_freq = int(cfg0.tpu_sweep_checkpoint_freq or 0)
    for r in range(start_round, num_boost_round):
        # interleaved dispatch across sub-fleets: run #2's host schedule
        # overlaps run #1's device round (async dispatch)
        walls = []
        for run in runs:
            t_step = time.perf_counter()
            run.step(r)
            walls.append((time.perf_counter() - t_step) * 1e3)
        if watch is not None:
            _watch_subfleets(watch, walls, r, len(runs), ledger)
        if ckpt_freq > 0 and cfg0.tpu_sweep_checkpoint_dir \
                and (r + 1) % ckpt_freq == 0:
            _write_batched_ckpt(cfg0.tpu_sweep_checkpoint_dir, r + 1,
                                probes, gbdts, cfgs, runs, plans)

    scores_nbytes = 0
    for run in runs:
        run.finish()
        scores_nbytes += int(run.fleet.scores.nbytes)
    out = []
    for m, (probe, g) in enumerate(zip(probes, gbdts)):
        bst = _package(probe, clean_params[m])
        # the fleet (and its sweep/scores HBM owner rows) dies with this
        # frame; the stack size survives on the outputs for bench
        bst._sweep_scores_bytes = scores_nbytes
        out.append(bst)
    return out


def _watch_subfleets(watch, walls, r, n_runs, ledger) -> None:
    """Per-round sub-fleet imbalance: step walls are mostly host
    schedule time under async dispatch, but a sub-fleet whose dispatch
    queue backs up (HBM pressure, recompiles) shows up here without
    adding a single fence. Edge-triggered like the dist straggler."""
    from ..obs import metrics as obs_metrics
    from ..obs.straggler import imbalance_ratio
    ratio = imbalance_ratio(walls)
    if ratio is None:
        return
    if obs_metrics.enabled():
        obs_metrics.registry().gauge(
            "sweep_subfleet_imbalance",
            "max/median sub-fleet round-step wall ratio").set(ratio)
    edge = watch.update(ratio)
    if edge is None:
        return
    slowest = int(max(range(len(walls)), key=walls.__getitem__))
    if ledger is not None:
        ledger.commit({"kind": "note", "note": "sweep_subfleet_imbalance",
                       "round": r, "state": edge,
                       "imbalance": round(ratio, 3), "subfleet": slowest,
                       "t0": round(time.perf_counter(), 6)})
    log.event("sweep_subfleet_imbalance", round=r, state=edge,
              imbalance=round(ratio, 3), subfleet=slowest,
              subfleets=n_runs)


def _materialize_fleet(gbdts, rec_log) -> List[List[Any]]:
    """Resolve every _RecRef in every probe's model list to a host Tree
    with one batched device->host transfer of the whole record log.
    Entries that are already host Trees (DART materializes per round;
    warm-start seeds) pass through untouched."""
    host_log = jax.device_get(rec_log) if rec_log else []
    from ..models.gbdt import K_EPSILON
    out = []
    for m, g in enumerate(gbdts):
        trees = []
        for entry in g.models:
            if isinstance(entry, _RecRef):
                rec = host_log[entry.entry][entry.k]
                rec_m = jax.tree_util.tree_map(lambda a: a[m], rec)
                tree = g.learner.record_to_tree(rec_m, entry.shrinkage)
                if abs(entry.bias) > K_EPSILON:
                    tree.add_bias(entry.bias)
                trees.append(tree)
            else:
                trees.append(entry)
        out.append(trees)
    return out


def _package(probe: Booster, params: Dict[str, Any]) -> Booster:
    """engine.train's final round-trip: model string -> fresh Booster."""
    fresh = Booster(model_str=probe.model_to_string())
    fresh.params = dict(params)
    return fresh


# ----------------------------------------------------------------------
# interleaved fallback
# ----------------------------------------------------------------------

def _train_interleaved(probes, gbdts, cfgs, clean_params, num_boost_round,
                       loaded) -> List[Booster]:
    cfg0 = cfgs[0]
    start_round = 0
    if loaded is not None:
        state, texts, arrays = loaded
        start_round = _fleet_resume(state, texts, arrays, gbdts, cfgs)
        for m, g in enumerate(gbdts):
            g.iter = int(state["iters"][m])
            g._pending_numsplits = [int(x) for x in state["pending"][m]]
    ckpt_freq = int(cfg0.tpu_sweep_checkpoint_freq or 0)
    for r in range(start_round, num_boost_round):
        # round-robin one round per model: jax dispatch is async, so
        # model m+1's host work overlaps model m's device work
        for probe in probes:
            probe.update()
        if ckpt_freq > 0 and cfg0.tpu_sweep_checkpoint_dir \
                and (r + 1) % ckpt_freq == 0:
            texts = [p.model_to_string() for p in probes]
            scores = [g.train_score.score for g in gbdts]
            pend = [[int(x) for x in
                     jax.device_get(list(g._pending_numsplits))]
                    for g in gbdts]
            _fleet_ckpt_write(cfg0.tpu_sweep_checkpoint_dir, r + 1,
                              gbdts, cfgs, [g.iter for g in gbdts],
                              pend, scores, "interleaved", texts)
    return [_package(p, params)
            for p, params in zip(probes, clean_params)]


# ----------------------------------------------------------------------
# fleet checkpoint (shared by both modes)
# ----------------------------------------------------------------------

def _write_batched_ckpt(directory, round_next, probes, gbdts, cfgs,
                        runs, plans) -> None:
    """Snapshot mid-sweep batched state. Trees are materialized into
    COPIES (the live _RecRef entries stay untouched) and serialized per
    model; pending trim counters are pulled but NOT cleared, so the
    trim cadence after resume matches the uninterrupted run."""
    M = len(gbdts)
    texts: List[str] = [""] * M
    scores: List[np.ndarray] = [None] * M
    iters = [0] * M
    pend: List[List[int]] = [[] for _ in range(M)]
    stopped = [False] * M
    for run in runs:
        trees_per_model = _materialize_fleet(run.gbdts, run.fleet.rec_log)
        host_stack = np.asarray(jax.device_get(run.fleet.scores),
                                np.float32)
        ns = [np.asarray(x) for x in jax.device_get(list(run.pending))]
        for j, i in enumerate(run.idx):
            probe, g = run.probes[j], run.gbdts[j]
            live = g.models
            g.models = trees_per_model[j]
            try:
                texts[i] = probe.model_to_string()
            finally:
                g.models = live
            scores[i] = host_stack[j]
            iters[i] = run.iters[j]
            pend[i] = [int(x[j]) for x in ns]
            stopped[i] = run.stopped[j]
    _fleet_ckpt_write(directory, round_next, gbdts, cfgs, iters, pend,
                      scores, "batched", texts, stopped=stopped,
                      subfleets=[list(p.indices) for p in plans])


def _fleet_ckpt_write(directory, round_next, gbdts, cfgs, iters, pend,
                      scores, mode, texts, stopped=None,
                      subfleets=None) -> None:
    from ..models.boosting_variants import DART, GOSS
    from ..resilience.checkpoint import (MANIFEST_NAME, atomic_write_text,
                                         capture_rng_states,
                                         training_signature)
    name = f"ckpt_{round_next:06d}"
    cdir = os.path.join(directory, name)
    os.makedirs(cdir, exist_ok=True)
    for m, text in enumerate(texts):
        atomic_write_text(os.path.join(cdir, f"model_{m:04d}.txt"), text)
    # per-model score planes (sub-fleets may have different [K, N])
    arrays = {f"score_{m:04d}": np.asarray(jax.device_get(s), np.float32)
              for m, s in enumerate(scores)}
    for m, g in enumerate(gbdts):
        # standard bagging carries its subset across rounds (freq > 1);
        # GOSS redraws every round, so nothing to persist
        if not isinstance(g, GOSS) and g.bag_data_indices is not None:
            arrays[f"bag_idx_{m:04d}"] = np.asarray(g.bag_data_indices,
                                                    np.int32)
            arrays[f"bag_cnt_{m:04d}"] = np.asarray(
                [int(g.bag_data_cnt)], np.int32)
    tmp = os.path.join(cdir, ".arrays.npz.tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, os.path.join(cdir, "arrays.npz"))
    dart_state = [
        {"tree_weight": [float(w) for w in g.tree_weight],
         "sum_weight": float(g.sum_weight)}
        if isinstance(g, DART) else None for g in gbdts]
    state = {"sweep_schema": _FLEET_SCHEMA, "round": int(round_next),
             "mode": mode, "models": len(gbdts),
             "iters": [int(x) for x in iters],
             "pending": pend,
             "stopped": [bool(x) for x in (stopped or [False] * len(gbdts))],
             "subfleets": subfleets,
             "dart": dart_state,
             "rng": [capture_rng_states(g) for g in gbdts],
             "signatures": [training_signature(cfg) for cfg in cfgs]}
    atomic_write_text(os.path.join(cdir, "state.json"),
                      json.dumps(state, sort_keys=True))
    # manifest last: readers only ever see complete checkpoints
    atomic_write_text(os.path.join(directory, MANIFEST_NAME),
                      json.dumps({"latest": name, "kind": "sweep_fleet",
                                  "models": len(gbdts)}))


def _fleet_ckpt_load(directory):
    """(state, texts, arrays) of the latest fleet checkpoint, or None."""
    from ..resilience.checkpoint import read_manifest
    man = read_manifest(directory)
    if man is None:
        return None
    cdir = os.path.join(directory, str(man["latest"]))
    with open(os.path.join(cdir, "state.json")) as f:
        state = json.load(f)
    if int(state.get("sweep_schema", -1)) != _FLEET_SCHEMA:
        raise LightGBMError(
            f"sweep resume: unknown checkpoint schema in {cdir}")
    texts = []
    for m in range(int(state["models"])):
        with open(os.path.join(cdir, f"model_{m:04d}.txt")) as f:
            texts.append(f.read())
    arrays = dict(np.load(os.path.join(cdir, "arrays.npz")))
    return state, texts, arrays


def _fleet_resume(state, texts, arrays, gbdts, cfgs) -> int:
    """Install checkpointed per-model state onto the probe GBDTs; the
    caller restores mode-specific extras (iters/pending/stopped).
    Returns the round index to continue from."""
    from ..models.boosting_variants import DART
    from ..resilience.checkpoint import (install_rng_states,
                                         training_signature)
    for m, cfg in enumerate(cfgs):
        if state["signatures"][m] != training_signature(cfg):
            raise LightGBMError(
                f"sweep resume: model {m}'s config no longer matches the "
                "checkpoint's training signature")
    dart_state = state.get("dart") or [None] * len(gbdts)
    for m, g in enumerate(gbdts):
        g.models = list(Booster(model_str=texts[m]).trees)
        g.train_score.score = jnp.asarray(arrays[f"score_{m:04d}"])
        install_rng_states(g, state["rng"][m])
        if f"bag_idx_{m:04d}" in arrays:
            g.bag_data_indices = np.asarray(arrays[f"bag_idx_{m:04d}"],
                                            np.int32)
            g.bag_data_cnt = int(arrays[f"bag_cnt_{m:04d}"][0])
        if isinstance(g, DART) and dart_state[m] is not None:
            g.tree_weight = [float(w)
                             for w in dart_state[m]["tree_weight"]]
            g.sum_weight = float(dart_state[m]["sum_weight"])
    return int(state["round"])
