"""The fleet trainer: M boosters, one shared Dataset, one jitted round.

``train_many`` is ``engine.train``'s many-model sibling. M "probe"
Boosters are constructed exactly like sequential training boosters —
they own the per-model Config, the host RNG streams (bagging / feature
fraction), warm-start trees, and the round-0 ``boost_from_average``
mutation — but in batched mode they never dispatch a training program.
One registered round program (``sweep/batched.py``) advances ALL M
score planes ``[M, K, N]`` per round, with the per-model learning rate,
split lambdas, bagging partitions, and feature masks threaded as traced
operands; the batched TreeRecords land in a central device log and
``probe._gbdt.models`` holds lightweight ``_RecRef`` entries into it.
Because the refs live in the probe's own model list, the sequential
bookkeeping applies to the fleet unchanged: ``boost_from_average``'s
empty-models gate closes after round 0, warm-start prepends stay ahead
of new trees, and the 16-round deferred trailing-empty trim deletes
from the same list with the same arithmetic. Export is ONE device_get
of the whole log followed by the same model-string round-trip
``engine.train`` performs.

Parity contract: under ``tpu_use_f64_hist`` the model text of fleet
member m is byte-equal to ``engine.train`` with the same params
(tests/test_sweep.py asserts it for plain / bagged / multiclass).

Configs the batched gate rejects fall back to INTERLEAVED mode: the
probes train for real, one round each in round-robin order, so the
async dispatch queue stays full across models while per-model programs
keep their own shapes. Both modes share the fleet checkpoint format
(``tpu_sweep_checkpoint_dir`` / ``tpu_sweep_checkpoint_freq``): model
texts + score planes + host RNG + pending trim counters per model, so a
preempted sweep resumes bitwise on either path.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import compile_cache
from ..basic import Booster, Dataset, LightGBMError
from ..utils import log
from .batched import batched_gate, lambda_operands, make_round_program

_FLEET_SCHEMA = 1

# trainer-level aliases engine.train also honors (reference sklearn.py
# alias table); they must not leak into Config.from_params
_ROUND_ALIASES = ("num_boost_round", "num_iterations", "num_iteration",
                  "n_iter", "num_tree", "num_trees", "num_round",
                  "num_rounds", "n_estimators")


class _RecRef:
    """A fleet tree still on device: an index into the central record
    log (one ``[M]``-leading TreeRecord tuple per round) plus the
    per-model shrinkage/bias — the model-axis analogue of
    ``gbdt.LazyTree``. Lives in ``probe._gbdt.models`` so the
    sequential bookkeeping (boost_from_average gating, warm-start
    prepends, trailing-empty trim) applies unchanged."""

    __slots__ = ("entry", "k", "shrinkage", "bias")

    def __init__(self, entry: int, k: int, shrinkage: float,
                 bias: float) -> None:
        self.entry = entry
        self.k = k
        self.shrinkage = shrinkage
        self.bias = bias


class _Fleet:
    """Batched-run device state; also the HBM-accountant owner for the
    stacked score buffer (obs/memory.py ``sweep/scores``)."""

    def __init__(self, scores: jax.Array) -> None:
        self.scores = scores          # [M, K, N] f32, donated per round
        self.rec_log: List[Tuple] = []  # one K-tuple of batched recs/round


def train_many(params_list: Sequence[Dict[str, Any]], train_set: Dataset,
               num_boost_round: int = 100,
               init_models: Optional[Sequence[
                   Union[str, Booster, None]]] = None) -> List[Booster]:
    """Train ``len(params_list)`` boosters against one shared Dataset.

    Every params dict may vary the sweep grid fields
    (``sweep.SWEEP_VARYING``: learning_rate, lambda_l1/l2, bagging seed
    and freq, feature_fraction_seed) freely; everything else must agree
    across the fleet for batched mode — ``tpu_sweep_mode="auto"`` falls
    back to the interleaved path otherwise, ``"batched"`` raises with
    the gate's reason. ``init_models`` (per-model Booster / model file /
    None) warm-starts members like ``engine.train(init_model=...)``;
    it is ignored when resuming from ``tpu_sweep_checkpoint_dir`` (the
    checkpointed texts already contain the seed trees). Returns M
    independent Boosters round-tripped through their model strings,
    exactly like ``engine.train``.
    """
    if not params_list:
        raise LightGBMError("train_many needs at least one params dict")
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    t_start = time.perf_counter()
    traces0 = compile_cache.trace_count()

    probes: List[Booster] = []
    clean_params: List[Dict[str, Any]] = []
    for params in params_list:
        params = dict(params)
        for alias in _ROUND_ALIASES:
            if alias in params:
                num_boost_round = int(params.pop(alias))
        train_set._update_params(params)
        clean_params.append(params)
        probes.append(Booster(params=params, train_set=train_set))
    gbdts = [b._gbdt for b in probes]
    cfgs = [b._cfg for b in probes]
    cfg0 = cfgs[0]
    M = len(probes)

    ledger = None
    if cfg0.tpu_trace:
        from ..obs import ledger as obs_ledger
        tdir = cfg0.tpu_trace_dir or "lgbt_trace"
        ledger = obs_ledger.RoundLedger.for_training(tdir, cfg0)

    ckpt_dir = cfg0.tpu_sweep_checkpoint_dir
    loaded = _fleet_ckpt_load(ckpt_dir) if ckpt_dir else None
    if loaded is not None and int(loaded[0]["models"]) != M:
        raise LightGBMError(
            f"sweep resume: checkpoint holds {loaded[0]['models']} models, "
            f"fleet has {M}")

    if init_models is not None and loaded is None:
        if len(init_models) != M:
            raise LightGBMError("init_models must have one entry per model")
        from ..engine import _seed_from_model
        for probe, init in zip(probes, init_models):
            if init is None:
                continue
            ib = Booster(model_file=init) if isinstance(init, str) else init
            _seed_from_model(probe, ib)

    mode = (cfg0.tpu_sweep_mode or "auto").lower()
    if mode not in ("auto", "batched", "interleaved"):
        raise LightGBMError(f"unknown tpu_sweep_mode={mode!r}")
    reason = batched_gate(gbdts, cfgs)
    if mode == "batched" and reason is not None:
        raise LightGBMError(f"tpu_sweep_mode=batched rejected: {reason}")
    use_batched = mode != "interleaved" and reason is None
    chosen = "batched" if use_batched else "interleaved"
    if loaded is not None and loaded[0].get("mode") != chosen:
        raise LightGBMError(
            f"sweep resume: checkpoint was written in "
            f"{loaded[0].get('mode')!r} mode, this run chose {chosen!r}")

    fields: Dict[str, Any] = {"models": M, "mode": chosen,
                              "rounds": int(num_boost_round)}
    if not use_batched and reason is not None:
        fields["fallback_reason"] = reason
    log.event("sweep_init", **fields)
    if ledger is not None:
        ledger.commit({"kind": "note", "note": "sweep_init", **fields})

    try:
        if use_batched:
            out = _train_batched(probes, gbdts, cfgs, clean_params,
                                 int(num_boost_round), ledger, loaded)
        else:
            out = _train_interleaved(probes, gbdts, cfgs, clean_params,
                                     int(num_boost_round), loaded)
    finally:
        if ledger is not None:
            ledger.close()
    if ledger is not None:
        for bst in out:
            # same carry engine.train does: the ledger lives on the
            # training probes, which the fresh boosters no longer hold
            bst._telemetry = ledger
    log.event("sweep_train", models=M, mode=chosen,
              rounds=int(num_boost_round),
              wall_s=round(time.perf_counter() - t_start, 3),
              traces=compile_cache.trace_count() - traces0)
    return out


# ----------------------------------------------------------------------
# batched path
# ----------------------------------------------------------------------

def _train_batched(probes, gbdts, cfgs, clean_params, num_boost_round,
                   ledger, loaded) -> List[Booster]:
    from ..models.device_learner import _pow2ceil
    from ..obs import memory as obs_memory
    from ..ops.sweep_ops import stacked_bag_partitions
    g0 = gbdts[0]
    lrn = g0.learner
    cfg0 = cfgs[0]
    M, K, F = len(probes), g0.num_tree_per_iteration, lrn.num_features
    bagged = g0._will_bag()
    bag_cnt = int(cfg0.bagging_fraction * g0.num_data) if bagged \
        else g0.num_data
    fn, _key = make_round_program(lrn, g0.objective, M, K,
                                  cfg0.num_leaves, bagged, bag_cnt)

    start_round = 0
    iters = [0] * M
    pending: List[Any] = []     # one [M] num_splits vector per (round, k)
    biases = [[0.0] * K for _ in range(M)]
    first_fresh = loaded is None
    if loaded is not None:
        state, texts, arrays = loaded
        start_round = _fleet_resume(state, texts, arrays, gbdts, cfgs)
        iters = [int(x) for x in state["iters"]]
        per_model = state["pending"]
        depth = len(per_model[0]) if per_model and per_model[0] else 0
        pending = [np.asarray([int(per_model[m][i]) for m in range(M)],
                              np.int32) for i in range(depth)]
    else:
        # round-0 init exactly like the sequential loop head: the gate
        # self-closes once the refs land in probe.models
        for m, g in enumerate(gbdts):
            for k in range(K):
                biases[m][k] = g.boost_from_average(k)

    fleet = _Fleet(jnp.stack([g.train_score.score for g in gbdts]))
    for g in gbdts:
        # the fleet buffer owns the training scores now; drop the
        # per-probe planes so HBM holds one fleet copy, not two
        g.train_score.score = g.train_score.score[:, :0]
    obs_memory.track("sweep/scores", fleet,
                     lambda fl: int(fl.scores.nbytes))

    LR = jnp.asarray([np.float32(g.shrinkage_rate) for g in gbdts],
                     jnp.float32)
    l1, l2, l2c = lambda_operands(cfgs)
    L1, L2, L2C = jnp.asarray(l1), jnp.asarray(l2), jnp.asarray(l2c)
    bins, bins_T = lrn.bins_dev, lrn.bins_T_dev
    idx_pad = lrn.n + max(_pow2ceil(lrn.n), lrn.min_pad)
    ckpt_freq = int(cfg0.tpu_sweep_checkpoint_freq or 0)

    for r in range(start_round, num_boost_round):
        rnd_iters = list(iters)
        traces_before = compile_cache.trace_count()
        t0 = time.perf_counter()
        if bagged:
            # host RNG schedule in sequential order: bag redraw first,
            # then the per-class feature masks (\_train_one_iter_impl)
            for m, g in enumerate(gbdts):
                g._bagging(iters[m])
            IDX = stacked_bag_partitions(
                [g.bag_data_indices for g in gbdts], idx_pad)
            BC = jnp.asarray([int(g.bag_data_cnt) for g in gbdts],
                             jnp.int32)
        FM = np.empty((M, K, F), np.float32)
        for m, g in enumerate(gbdts):
            for k in range(K):
                fm = g.learner.feature_mask()
                FM[m, k, :] = 1.0 if fm is None \
                    else fm.astype(np.float32)
        if bagged:
            fleet.scores, recs = fn(fleet.scores, jnp.asarray(FM), LR,
                                    L1, L2, L2C, IDX, BC, bins, bins_T)
        else:
            fleet.scores, recs = fn(fleet.scores, jnp.asarray(FM), LR,
                                    L1, L2, L2C, bins, bins_T)
        fleet.rec_log.append(recs)
        entry = len(fleet.rec_log) - 1
        for m, g in enumerate(gbdts):
            for k in range(K):
                g.models.append(_RecRef(
                    entry, k, float(g.shrinkage_rate),
                    biases[m][k] if first_fresh else 0.0))
            iters[m] += 1
        first_fresh = False
        for k in range(K):
            pending.append(recs[k].num_splits)
        t_host = time.perf_counter()

        fenced = False
        if len(pending) >= 16 * K:
            # deferred trailing-empty trim, per model (the same batched
            # pull + arithmetic as gbdt._trim_trailing_empty)
            ns = [np.asarray(x) for x in jax.device_get(pending)]
            pending = []
            fenced = True
            for m, g in enumerate(gbdts):
                col = [int(x[m]) for x in ns]
                empty_trailing = 0
                for it in range(len(col) // K - 1, -1, -1):
                    if max(col[it * K:(it + 1) * K]) == 0:
                        empty_trailing += 1
                    else:
                        break
                if empty_trailing and len(g.models) > K:
                    drop = min(empty_trailing * K, len(g.models) - K)
                    del g.models[-drop:]
                    iters[m] -= drop // K
        t1 = time.perf_counter()

        if ledger is not None:
            wall = round((t1 - t0) * 1e3, 3)
            dev = round((t1 - t_host) * 1e3, 3) if fenced else 0.0
            traces_delta = compile_cache.trace_count() - traces_before
            for m, g in enumerate(gbdts):
                rec = {"kind": "round", "round": rnd_iters[m],
                       "wall_ms": wall, "device_ms": dev,
                       "traces": traces_delta if m == 0 else 0,
                       "path": "sweep", "aligned": False, "fallbacks": 0,
                       "trees": len(g.models), "model": m,
                       "bag_cnt": int(g.bag_data_cnt) if bagged
                       else int(g0.num_data)}
                if fenced:
                    rec["timing"] = "fenced"
                    rec["terms_ms"] = {"sweep": dev}
                ledger.commit(rec)

        if ckpt_freq > 0 and cfg0.tpu_sweep_checkpoint_dir \
                and (r + 1) % ckpt_freq == 0:
            _write_batched_ckpt(cfg0.tpu_sweep_checkpoint_dir, r + 1,
                                probes, gbdts, cfgs, iters, pending,
                                fleet)

    # ONE device pull for every logged record, then the sequential
    # export path per model
    trees_per_model = _materialize_fleet(gbdts, fleet.rec_log)
    scores_nbytes = int(fleet.scores.nbytes)
    out = []
    for m, (probe, g) in enumerate(zip(probes, gbdts)):
        g.models = trees_per_model[m]
        g.iter = iters[m]
        g._pending_numsplits = []
        g.train_score.score = fleet.scores[m]
        bst = _package(probe, clean_params[m])
        # the fleet (and its sweep/scores HBM owner row) dies with this
        # frame; the stack size survives on the outputs for bench
        bst._sweep_scores_bytes = scores_nbytes
        out.append(bst)
    return out


def _materialize_fleet(gbdts, rec_log) -> List[List[Any]]:
    """Resolve every _RecRef in every probe's model list to a host Tree
    with one batched device->host transfer of the whole record log."""
    host_log = jax.device_get(rec_log) if rec_log else []
    from ..models.gbdt import K_EPSILON
    out = []
    for m, g in enumerate(gbdts):
        trees = []
        for entry in g.models:
            if isinstance(entry, _RecRef):
                rec = host_log[entry.entry][entry.k]
                rec_m = jax.tree_util.tree_map(lambda a: a[m], rec)
                tree = g.learner.record_to_tree(rec_m, entry.shrinkage)
                if abs(entry.bias) > K_EPSILON:
                    tree.add_bias(entry.bias)
                trees.append(tree)
            else:
                trees.append(entry)
        out.append(trees)
    return out


def _package(probe: Booster, params: Dict[str, Any]) -> Booster:
    """engine.train's final round-trip: model string -> fresh Booster."""
    fresh = Booster(model_str=probe.model_to_string())
    fresh.params = dict(params)
    return fresh


# ----------------------------------------------------------------------
# interleaved fallback
# ----------------------------------------------------------------------

def _train_interleaved(probes, gbdts, cfgs, clean_params, num_boost_round,
                       loaded) -> List[Booster]:
    cfg0 = cfgs[0]
    start_round = 0
    if loaded is not None:
        state, texts, arrays = loaded
        start_round = _fleet_resume(state, texts, arrays, gbdts, cfgs)
        for m, g in enumerate(gbdts):
            g.iter = int(state["iters"][m])
            g._pending_numsplits = [int(x) for x in state["pending"][m]]
    ckpt_freq = int(cfg0.tpu_sweep_checkpoint_freq or 0)
    for r in range(start_round, num_boost_round):
        # round-robin one round per model: jax dispatch is async, so
        # model m+1's host work overlaps model m's device work
        for probe in probes:
            probe.update()
        if ckpt_freq > 0 and cfg0.tpu_sweep_checkpoint_dir \
                and (r + 1) % ckpt_freq == 0:
            texts = [p.model_to_string() for p in probes]
            scores = jnp.stack([g.train_score.score for g in gbdts])
            pend = [[int(x) for x in
                     jax.device_get(list(g._pending_numsplits))]
                    for g in gbdts]
            _fleet_ckpt_write(cfg0.tpu_sweep_checkpoint_dir, r + 1,
                              gbdts, cfgs, [g.iter for g in gbdts],
                              pend, scores, "interleaved", texts)
    return [_package(p, params)
            for p, params in zip(probes, clean_params)]


# ----------------------------------------------------------------------
# fleet checkpoint (shared by both modes)
# ----------------------------------------------------------------------

def _write_batched_ckpt(directory, round_next, probes, gbdts, cfgs,
                        iters, pending, fleet) -> None:
    """Snapshot mid-sweep batched state. Trees are materialized into
    COPIES (the live _RecRef entries stay untouched) and serialized per
    model; pending trim counters are pulled but NOT cleared, so the
    trim cadence after resume matches the uninterrupted run."""
    trees_per_model = _materialize_fleet(gbdts, fleet.rec_log)
    texts = []
    for probe, g, trees in zip(probes, gbdts, trees_per_model):
        live = g.models
        g.models = trees
        try:
            texts.append(probe.model_to_string())
        finally:
            g.models = live
    ns = [np.asarray(x) for x in jax.device_get(list(pending))]
    pend = [[int(x[m]) for x in ns] for m in range(len(gbdts))]
    _fleet_ckpt_write(directory, round_next, gbdts, cfgs, iters, pend,
                      fleet.scores, "batched", texts)


def _fleet_ckpt_write(directory, round_next, gbdts, cfgs, iters, pend,
                      scores, mode, texts) -> None:
    from ..resilience.checkpoint import (MANIFEST_NAME, atomic_write_text,
                                         capture_rng_states,
                                         training_signature)
    name = f"ckpt_{round_next:06d}"
    cdir = os.path.join(directory, name)
    os.makedirs(cdir, exist_ok=True)
    for m, text in enumerate(texts):
        atomic_write_text(os.path.join(cdir, f"model_{m:02d}.txt"), text)
    arrays = {"scores": np.asarray(jax.device_get(scores), np.float32)}
    if gbdts[0].bag_data_indices is not None:
        arrays["bag_indices"] = np.stack(
            [np.asarray(g.bag_data_indices, np.int32) for g in gbdts])
        arrays["bag_cnt"] = np.asarray(
            [int(g.bag_data_cnt) for g in gbdts], np.int32)
    tmp = os.path.join(cdir, ".arrays.npz.tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, os.path.join(cdir, "arrays.npz"))
    state = {"sweep_schema": _FLEET_SCHEMA, "round": int(round_next),
             "mode": mode, "models": len(gbdts),
             "iters": [int(x) for x in iters],
             "pending": pend,
             "rng": [capture_rng_states(g) for g in gbdts],
             "signatures": [training_signature(cfg) for cfg in cfgs]}
    atomic_write_text(os.path.join(cdir, "state.json"),
                      json.dumps(state, sort_keys=True))
    # manifest last: readers only ever see complete checkpoints
    atomic_write_text(os.path.join(directory, MANIFEST_NAME),
                      json.dumps({"latest": name, "kind": "sweep_fleet",
                                  "models": len(gbdts)}))


def _fleet_ckpt_load(directory):
    """(state, texts, arrays) of the latest fleet checkpoint, or None."""
    from ..resilience.checkpoint import read_manifest
    man = read_manifest(directory)
    if man is None:
        return None
    cdir = os.path.join(directory, str(man["latest"]))
    with open(os.path.join(cdir, "state.json")) as f:
        state = json.load(f)
    if int(state.get("sweep_schema", -1)) != _FLEET_SCHEMA:
        raise LightGBMError(
            f"sweep resume: unknown checkpoint schema in {cdir}")
    texts = []
    for m in range(int(state["models"])):
        with open(os.path.join(cdir, f"model_{m:02d}.txt")) as f:
            texts.append(f.read())
    arrays = dict(np.load(os.path.join(cdir, "arrays.npz")))
    return state, texts, arrays


def _fleet_resume(state, texts, arrays, gbdts, cfgs) -> int:
    """Install checkpointed per-model state onto the probe GBDTs; the
    caller restores mode-specific extras (iters/pending). Returns the
    round index to continue from."""
    from ..resilience.checkpoint import (install_rng_states,
                                         training_signature)
    for m, cfg in enumerate(cfgs):
        if state["signatures"][m] != training_signature(cfg):
            raise LightGBMError(
                f"sweep resume: model {m}'s config no longer matches the "
                "checkpoint's training signature")
    scores = arrays["scores"]
    for m, g in enumerate(gbdts):
        g.models = list(Booster(model_str=texts[m]).trees)
        g.train_score.score = jnp.asarray(scores[m])
        install_rng_states(g, state["rng"][m])
        if "bag_indices" in arrays:
            g.bag_data_indices = np.asarray(arrays["bag_indices"][m],
                                            np.int32)
            g.bag_data_cnt = int(arrays["bag_cnt"][m])
    return int(state["round"])
