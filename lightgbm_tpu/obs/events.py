"""Canonical structured-event vocabulary.

One table names every ``log.event(kind, ...)`` record the framework can
emit — the same role ``obs/terms.py`` plays for device-time terms. The
emit side validates against THIS dict when ``__debug__`` (utils/log.py),
graftlint's LGT005 checker validates every literal kind at lint time,
and ``parse_event`` consumers can rely on the catalog being closed: a
kind that is not here is a bug, not a new feature.

Why a catalog and not grep: event kinds are the join key between the
ledger, the bench record, CI assertions (e.g. the serving smoke counts
``serve_swap`` notes) and offline tooling. A renamed or misspelled kind
silently breaks those joins — drift used to be caught only by whichever
test happened to parse the affected line, or not at all.

Adding an event: add the kind + one-line description here, then emit it.
``tools/lint`` fails the build on an uncatalogued literal kind; dynamic
kinds (f-strings) are rejected outright unless suppressed with a reason.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

# kind -> one-line description (keep alphabetized within each block)
EVENTS: Dict[str, str] = {
    # training path + compile plane
    "aligned_fallback": "aligned engine exact-replay fallback count for "
                        "a finished training run",
    "compile_cache_miss": "persistent-compile-cache miss, with the "
                          "traced program signature (warm-up forensics)",
    "quant_hist": "quantized-histogram path resolution: active bits "
                  "and payload dtype, or why the f32 oracle ran "
                  "instead",
    "round_anomaly": "a traced round's wall time deviated past the "
                     "anomaly factor from the trailing-window median "
                     "(in-run anomaly watch; edge-triggered)",
    "stream_ingest": "streaming out-of-core ingest finished: rows, "
                     "chunk size, device-vs-host binning split, wall "
                     "time",
    "telemetry": "per-round ledger record mirrored onto the event "
                 "channel by the telemetry callback",
    "train_path": "which training path a run took (fused / aligned / "
                  "level / host) plus the gate notes that routed it",
    # ranking
    "rank_buckets": "bucketed lambdarank pad ladder: per-bucket query/"
                    "doc counts and pair-padding waste",
    "rank_fused": "segment-fused lambdarank kernel status: tile stats "
                  "on build, or a fallback with its reason",
    # prediction / serving
    "predict_route": "Booster.predict routing decision (device engine "
                     "vs native host walk) and why",
    "serve_aot": "AOT artifact export/load outcome (hit / miss / "
                 "signature_mismatch / export / prefill / bad blob)",
    "serve_compact": "compact dtype plan passed the parity gate at model "
                     "load: plan, bytes, bytes saved vs f32",
    "serve_compact_fallback": "compact plan FAILED the parity gate; the "
                              "load fell back to the f32 engine",
    "serve_compile": "ForestEngine compiled a new shape-bucket program",
    "serve_deadline": "front-door request expired its X-Deadline-Ms "
                      "budget in the admission queue and was answered "
                      "without an engine dispatch (rate-limited)",
    "serve_evict": "registry evicted an LRU entry over the HBM budget",
    "serve_frontend": "scoring front door started or stopped: bind "
                      "address, QoS map, shed mode, request totals",
    "serve_load": "registry loaded (or replaced) a named model",
    "serve_place": "placer assigned/replicated/evicted a model replica "
                   "on a device (HBM-headroom placement; per-device "
                   "LRU budget)",
    "serve_over_budget": "a single protected entry alone exceeds the "
                         "HBM budget (load proceeds with a warning)",
    "serve_request_slow": "a coalesced request breached tpu_serve_slo_ms "
                          "(rate-limited pointer; the full span is in "
                          "the request-trace ring/JSONL)",
    "serve_route": "placer first routed a model's traffic to a replica "
                   "on a device (edge-triggered per model/device pair)",
    "serve_shed": "front-door load shedding tripped or cleared for a "
                  "model (burn-rate hysteresis) with the running shed "
                  "count; shed requests get fast 429s",
    "serve_slo_burn": "a model's rolling SLO burn rate crossed the high "
                      "watermark — the load-shedding trip signal",
    "serve_swap": "registry hot-swapped a named model to a new version",
    "serve_trace_dump": "request tracer closed: kept-row / breach / "
                        "error totals and the JSONL path",
    "serve_watch_bad_model": "checkpoint watcher skipped a torn/invalid "
                             "model version (retried next tick)",
    "serve_watch_error": "checkpoint watcher poll raised; the thread "
                         "survives and retries",
    # many-model sweep trainer (sweep/)
    "sweep_init": "train_many chose its execution mode: fleet size, "
                  "batched vs interleaved, and the gate's fallback "
                  "reason when batching was rejected",
    "sweep_refresh": "continual-refresh cycle published the retrained "
                     "fleet's serving checkpoint versions",
    "sweep_refresh_triggered": "a serving model's SLO burn rate crossed "
                               "the trigger threshold; it is enqueued "
                               "for the next refresh fleet",
    "sweep_subfleet": "one shape-bucketed batched sub-fleet started: "
                      "member indices, size, split reason (shape / hbm "
                      "/ cap), score-stack MiB, variant",
    "sweep_subfleet_imbalance": "sustained per-sub-fleet round-wall "
                                "imbalance (max/median) crossed or "
                                "cleared the straggler threshold "
                                "(edge-triggered)",
    "sweep_train": "train_many finished: fleet size, mode, rounds, "
                   "wall time, trace count",
    # distributed runtime (dist/)
    "dist_init": "distributed runtime activated: tree_learner mode, mesh "
                 "shard count, device kinds",
    "dist_resume": "resumed distributed run rescattered the gathered "
                   "score buffers back onto the mesh",
    "dist_shard": "dataset sharded across the mesh: rows per shard, "
                  "per-device HBM bytes, bin-sync wall time",
    "dist_straggler": "sustained per-device round-time imbalance "
                      "(max/median over fenced per-shard segments) on "
                      "profiled distributed rounds crossed or cleared "
                      "the straggler threshold (edge-triggered)",
    "dist_stream": "stream-to-shard ingest finished: rows, mesh width, "
                   "chunk size, parse/bin walls + overlap efficiency of "
                   "the double-buffered pipeline, per-device shard "
                   "bytes and their HBM-accountant owner names",
    # resilience
    "checkpoint": "full-training-state checkpoint written (iter, path, "
                  "reason, write cost)",
    "fault": "deterministic fault injection fired (tests/CI)",
    "preempt": "SIGTERM/SIGINT observed; training will checkpoint and "
               "exit 75 after the in-flight round",
    "resume": "training resumed from a checkpoint (iter, source)",
    "retry": "transient device-dispatch error; retrying with backoff",
    "retry_exhausted": "dispatch retries exhausted; error propagates",
    "retry_recovered": "dispatch succeeded after transient-error "
                       "retries",
}


def validate_kind(kind: Any) -> Optional[str]:
    """None when `kind` is a catalogued event kind; else a reason
    string (utils/log.event asserts on this under ``__debug__``)."""
    if not isinstance(kind, str):
        return f"event kind must be a str, got {type(kind).__name__}"
    if kind not in EVENTS:
        return (f"unknown event kind {kind!r} — add it to "
                f"obs/events.py EVENTS")
    return None
