"""Per-round metrics ledger: one JSONL record per boosting round,
flushed as it happens so a killed run still leaves rounds 0..k on disk.

Record kinds:

- ``run``   — one header per ledger: schema version, pid, config digest.
- ``round`` — one per boosting round. Required fields: ``round``,
  ``wall_ms`` (fence-to-fence host wall time), ``device_ms`` (the
  residual device drain after host dispatch returned — i.e. the time
  spent blocked in the tracing fence), ``traces`` (new XLA traces this
  round, from ``compile_cache.trace_count`` deltas), ``path`` (the
  training path string from ``_log_train_path``), ``aligned`` bool,
  ``fallbacks`` (aligned exact-replay fallbacks this round), ``trees``.
  Optional: ``gate_notes`` (e.g. "slot-hist spilled to HBM"),
  ``hist_spill`` bool, ``bag_cnt`` (bagging/GOSS sample size),
  ``finished`` (no-split stop flag), ``eval`` (folded in by the
  ``log_telemetry`` callback after metrics run), and — on
  profiler-sampled rounds only — ``profiled`` bool, ``terms_ms``
  (canonical per-term device ms, keys from ``obs.terms.TERMS``) and
  ``timing``, which names the round's device-time convention:
  ``"residual"`` (the default: ONE end-of-round fence, ``device_ms``
  is the pipelined residual drain) vs ``"fenced"`` (profiler-sampled:
  every dispatch site fenced individually, ``device_ms`` is the SUM of
  fenced site times). The two are NOT comparable — a fenced round
  serializes the pipeline — so readers (``tools/bench_compare.py``,
  round-wall histograms) must split on ``timing``/``profiled`` before
  aggregating; records without the field are ``"residual"``.
  Traced/profiled rounds also carry ``t0`` (raw ``perf_counter`` at
  round start — the timeline's clock anchor, obs/timeline.py), and
  profiler-sampled rounds of DISTRIBUTED runs with the timeline on
  add ``device_ids``, ``device_terms_ms`` (per-term columns, one per
  mesh device: fenced wait-attribution segments summing to the term's
  aggregate), ``device_round_ms``, ``imbalance`` (max/median of the
  per-device totals) and ``allreduce_split_ms`` (compute-vs-wait
  split of the allreduce probe).
- ``eval``  — per-round metric values, appended by the callback seam
  (the round record is already flushed by then; the eval record carries
  the same ``round`` index so readers can join them).

Readers: ``read_ledger(path)`` -> list of dicts (a ``LedgerRows`` whose
``torn_tail`` flag marks a dropped torn final line after a mid-flush
kill); ``validate_record`` raises on schema violations (used by tests
and the CI telemetry smoke).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1

ROUND_REQUIRED = ("round", "wall_ms", "device_ms", "traces", "path",
                  "aligned", "fallbacks", "trees")
_KINDS = ("run", "round", "eval", "note")

_seq = 0


def _validate_device_terms(dterms: Any) -> Optional[str]:
    """None when `dterms` is a well-formed ``device_terms_ms`` dict —
    canonical term keys, equal-length lists of non-negative numbers
    (one column per mesh device, in ``device_ids`` order); else a
    reason string. Committed only on profiler-sampled rounds of
    distributed runs with the timeline on."""
    if not isinstance(dterms, dict):
        return f"must be a dict, got {type(dterms).__name__}"
    from .terms import TERMS
    width = None
    for k, v in dterms.items():
        if k not in TERMS:
            return f"unknown term {k!r} (not in obs.terms.TERMS)"
        if not isinstance(v, list) or not v:
            return f"term {k!r} must map to a non-empty list"
        if width is None:
            width = len(v)
        elif len(v) != width:
            return (f"ragged device columns: term {k!r} has {len(v)} "
                    f"entries, expected {width}")
        for ms in v:
            if not isinstance(ms, (int, float)) or isinstance(ms, bool) \
                    or ms < 0:
                return f"bad value for term {k!r}: {ms!r}"
    return None


def validate_record(rec: Dict[str, Any]) -> None:
    """Raise ValueError unless `rec` is a well-formed ledger record."""
    if not isinstance(rec, dict):
        raise ValueError(f"ledger record must be a dict, got {type(rec)}")
    kind = rec.get("kind")
    if kind not in _KINDS:
        raise ValueError(f"ledger record kind {kind!r} not in {_KINDS}")
    if kind == "round":
        missing = [k for k in ROUND_REQUIRED if k not in rec]
        if missing:
            raise ValueError(f"round record missing fields: {missing}")
        if not isinstance(rec["round"], int) or rec["round"] < 0:
            raise ValueError(f"bad round index: {rec['round']!r}")
        for k in ("wall_ms", "device_ms"):
            if not isinstance(rec[k], (int, float)) or rec[k] < 0:
                raise ValueError(f"bad {k}: {rec[k]!r}")
        if not isinstance(rec["aligned"], bool):
            raise ValueError(f"bad aligned flag: {rec['aligned']!r}")
        if "terms_ms" in rec:
            from .terms import validate_terms_ms
            why = validate_terms_ms(rec["terms_ms"])
            if why is not None:
                raise ValueError(f"bad terms_ms: {why}")
        if "device_terms_ms" in rec:
            why = _validate_device_terms(rec["device_terms_ms"])
            if why is not None:
                raise ValueError(f"bad device_terms_ms: {why}")
        if "imbalance" in rec:
            v = rec["imbalance"]
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                raise ValueError(f"bad imbalance: {v!r}")
        timing = rec.get("timing")
        if timing is not None and timing not in ("residual", "fenced"):
            raise ValueError(f"bad timing mode: {timing!r} "
                             f"(must be 'residual' or 'fenced')")
        if "profiled" in rec and not isinstance(rec["profiled"], bool):
            raise ValueError(f"bad profiled flag: {rec['profiled']!r}")
    if kind == "eval" and "round" not in rec:
        raise ValueError("eval record missing round index")


class LedgerRows(List[Dict[str, Any]]):
    """`read_ledger` result: a plain list of records plus a `torn_tail`
    flag — True when the file's LAST line was a torn partial record
    (SIGKILL mid-flush) and was dropped rather than parsed."""

    torn_tail: bool = False


def read_ledger(path: str) -> LedgerRows:
    """Parse a ledger JSONL file.

    A process killed mid-`flush` leaves a torn final line; every record
    before it is intact (one record per line, flushed per commit), so
    the torn tail is dropped and reported via `rows.torn_tail` instead
    of making the whole ledger unreadable. A malformed line anywhere
    BUT the tail still raises — that is corruption, not a crash
    artifact."""
    out = LedgerRows()
    with open(path) as fh:
        lines = [ln.strip() for ln in fh]
    nonempty = [(i, ln) for i, ln in enumerate(lines) if ln]
    for pos, (_i, line) in enumerate(nonempty):
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            if pos == len(nonempty) - 1:
                out.torn_tail = True
                break
            raise
    return out


class RoundLedger:
    """Append-only JSONL metrics ledger with an in-memory mirror."""

    def __init__(self, path: Optional[str] = None,
                 meta: Optional[Dict[str, Any]] = None) -> None:
        self.path = path
        self.records: List[Dict[str, Any]] = []
        self._fh = None
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a")
        head = {"kind": "run", "schema": SCHEMA_VERSION, "pid": os.getpid()}
        if meta:
            head.update(meta)
        self.commit(head)

    @classmethod
    def for_training(cls, trace_dir: str,
                     cfg: Any = None) -> "RoundLedger":
        """A training ledger at ``<dir>/ledger-<pid>-<seq>.jsonl`` with
        a config-digest header (so a trace directory holding several
        runs stays attributable)."""
        global _seq
        _seq += 1
        path = os.path.join(trace_dir,
                            f"ledger-{os.getpid()}-{_seq}.jsonl")
        meta: Dict[str, Any] = {}
        if cfg is not None:
            try:
                import hashlib

                from ..compile_cache import config_signature
                sig = json.dumps(config_signature(cfg), sort_keys=True,
                                 default=str)
                meta["config_sig"] = hashlib.sha1(
                    sig.encode()).hexdigest()[:16]
                meta["objective"] = cfg.objective
            except Exception:
                pass
        return cls(path, meta)

    def commit(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        """Validate, mirror in memory, and flush one JSONL line."""
        validate_record(rec)
        self.records.append(rec)
        if self._fh is not None:
            self._fh.write(json.dumps(rec, sort_keys=True, default=str)
                           + "\n")
            self._fh.flush()
        return rec

    def record_eval(self, round_idx: int, results) -> None:
        """Fold per-round metric values in via the callback seam:
        annotate the in-memory round record AND append an `eval` line
        (the round line is already durable by the time metrics run)."""
        vals = {f"{dn}:{mn}": float(v) for dn, mn, v, _ in results}
        for rec in reversed(self.records):
            if rec.get("kind") == "round" and rec["round"] == round_idx:
                rec["eval"] = vals
                break
        self.commit({"kind": "eval", "round": round_idx, "values": vals})

    def last_round(self) -> Optional[Dict[str, Any]]:
        for rec in reversed(self.records):
            if rec.get("kind") == "round":
                return rec
        return None

    def round_records(self) -> List[Dict[str, Any]]:
        return [r for r in self.records if r.get("kind") == "round"]

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
