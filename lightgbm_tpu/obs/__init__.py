"""Observability: span tracer, per-round metrics ledger, device-time
measurement protocol, and crash-proof incremental bench records.

The subsystem is OFF by default and costs nothing when off: `trace.span`
returns a shared null context, `trace.fence` returns its argument without
importing jax, and the GBDT round loop takes a single attribute-is-None
branch. Enable with the `tpu_trace` / `tpu_trace_dir` params (both enter
`compile_cache.config_signature`, so toggling tracing retraces rather
than silently reusing a differently-fenced program).
"""
from . import (bench_record, devicetime, ledger, memory,  # noqa: F401
               metrics, profiler, reqtrace, terms, trace)

__all__ = ["bench_record", "devicetime", "ledger", "memory", "metrics",
           "profiler", "reqtrace", "terms", "trace"]
