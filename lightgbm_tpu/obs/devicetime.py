"""The chained-k DEVICE-time measurement protocol, hoisted out of
``tools/device_time_r4.py`` / ``tools/device_time_255.py`` so both are
thin CLIs over one implementation.

Protocol: build the kernel chained ``k`` times inside ONE jitted
``fori_loop`` program, warm both the k=1 and k=K variants, time each
over ``reps`` executions ending in a single device_get probe, and report
per-exec seconds as ``(t_K - t_1) / (K - 1)`` — host dispatch and tunnel
overhead appear identically in both variants and cancel in the delta.

Every measurement runs inside a ``trace.span("devtime.<name>")`` so a
traced process folds the per-term numbers into the span stream, and
``TermTimer`` both logs the human line and accumulates the machine
``terms_ms`` dict the tools print.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import trace

DEFAULT_CHAIN = 8
DEFAULT_REPS = 3


def device_get_probe(x):
    """Pull ONE scalar off the first leaf of `x` — the cheapest full
    device sync (forces every queued program to finish)."""
    import jax
    import numpy as np
    return np.asarray(jax.device_get(
        jax.tree_util.tree_leaves(x)[0].reshape(-1)[:1]))


def chained_device_time(mk_fn: Callable[[int], Callable], *args,
                        chain: int = DEFAULT_CHAIN,
                        reps: int = DEFAULT_REPS
                        ) -> Tuple[float, List[float]]:
    """``mk_fn(k)`` -> jitted fn running the kernel k times; returns
    (per-exec seconds from the k=1 vs k=chain delta, [t_1, t_K] rep
    means). Clamped at 0 — scheduling noise can invert tiny deltas."""
    f1, fK = mk_fn(1), mk_fn(chain)
    for f in (f1, fK):          # compile + warm
        device_get_probe(f(*args))
    ts = []
    for f in (f1, fK):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = f(*args)
        device_get_probe(out)
        ts.append((time.perf_counter() - t0) / reps)
    return max((ts[1] - ts[0]) / (chain - 1), 0.0), ts


class TermTimer:
    """Measure named terms under the chained-k protocol, collecting a
    ``terms_ms`` dict (ms, rounded; None for failed terms) plus stderr
    progress lines — the shared shape of both device-time CLIs."""

    def __init__(self, meta: Optional[Dict[str, Any]] = None,
                 chain: int = DEFAULT_CHAIN, reps: int = DEFAULT_REPS,
                 log: Optional[Callable[[str], None]] = None,
                 catalog: Optional[Dict[str, str]] = None) -> None:
        self.out: Dict[str, Any] = dict(meta or {})
        self.out["terms_ms"] = {}
        self.chain = chain
        self.reps = reps
        self._log = log or (lambda msg: None)
        self._ts: Dict[str, List[float]] = {}
        # term-name registry (obs/terms.py TERMS): when provided, a
        # measure() under a name outside the canonical vocabulary is a
        # programming error, not data — tools pass it so their JSON
        # lines can never drift from the ledger terms_ms vocabulary
        self._catalog = catalog

    def measure(self, name: str, mk_fn: Callable[[int], Callable],
                *args, rows: Optional[int] = None) -> Optional[float]:
        """Time one term; returns per-exec seconds or None on failure
        (failures are logged and recorded as null, never raised — a
        faulting term must not void the other terms' numbers)."""
        if self._catalog is not None and name not in self._catalog:
            raise ValueError(
                f"term {name!r} not in the canonical term table "
                f"(obs/terms.py TERMS: {sorted(self._catalog)})")
        try:
            with trace.span(f"devtime.{name}", chain=self.chain):
                per, ts = chained_device_time(
                    mk_fn, *args, chain=self.chain, reps=self.reps)
        except Exception as e:  # noqa: BLE001 — tool must keep going
            self._log(f"# {name} FAILED {type(e).__name__} "
                      f"{str(e)[:200]}")
            self.out["terms_ms"][name] = None
            return None
        self.out["terms_ms"][name] = round(per * 1e3, 2)
        self._ts[name] = ts
        line = f"# {name}: {per * 1e3:.1f}ms"
        if rows:
            line += f" ({per / rows * 1e9:.2f}ns/row)"
        self._log(line)
        return per

    def derive(self, name: str, minuend: str, subtrahend: str) -> None:
        """terms_ms[name] = max(minuend - subtrahend, 0); the minuend is
        REMOVED (it was only measured to isolate the marginal term)."""
        if self._catalog is not None and name not in self._catalog:
            raise ValueError(
                f"derived term {name!r} not in the canonical term table")
        terms = self.out["terms_ms"]
        if terms.get(minuend) is not None \
                and terms.get(subtrahend) is not None:
            terms[name] = round(
                max(terms.pop(minuend) - terms[subtrahend], 0.0), 2)

    def rep_times(self, name: str) -> Optional[List[float]]:
        return self._ts.get(name)
