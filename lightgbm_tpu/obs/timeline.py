"""Unified run timeline: every JSONL/event stream the framework emits,
joined onto ONE monotonic clock as Chrome-trace / Perfetto JSON.

The observability planes grew up siloed — span trace
(``spans-<pid>.jsonl``), per-round ledger records with fenced
``terms_ms`` (``ledger-*.jsonl``), request traces
(``reqtrace-*.jsonl``), the streaming-ingest pipeline walls, sweep
per-sub-fleet round dispatches, bench stage boundaries
(``bench-*.jsonl`` notes + the BENCH record), and compile-cache miss
events. Each answers its own question; none answers "where did the
WALL-CLOCK of this run go, across subsystems, per device". This module
answers that: ``build_timeline`` reads whichever streams exist and
emits one ``trace_events``-format document loadable in
``chrome://tracing`` or https://ui.perfetto.dev.

**Clock model.** Every producer stamps ``t0`` with
``time.perf_counter()``. On Linux that is CLOCK_MONOTONIC — a single
system-wide epoch shared by every process on the host — so spans from
the trainer, the prefetch thread, a bench parent, and its multichip
children all join WITHOUT cross-stream alignment: the timeline anchors
at the earliest ``t0`` seen and emits ``ts`` in microseconds relative
to it. Rows from old producers that lack ``t0`` are placed
end-to-start after their lane's cursor (ordered, not aligned) and
marked ``args.placed: "sequential"``.

**Lane map** (one Chrome-trace ``pid`` per subsystem; ``tid`` splits a
subsystem into parallel actors):

====== ========= ==================================================
pid    lane      tid semantics
====== ========= ==================================================
1      train     0 = round loop; 1+k = device k (per-device fenced
                 segments of profiled distributed rounds)
2      spans     host span trace (tid = span depth)
3      serving   request spans (tid 0)
4      ingest    0 = chunk wall, 1 = parse (prefetch thread),
                 2 = bin (device side)
5      sweep     tid = sub-fleet id (per-sub-fleet round dispatches)
6      bench     stage boundaries (tid 0)
7      events    instant events (compile-cache misses, straggler /
                 anomaly raises, ...) (tid 0)
====== ========= ==================================================

Reading is tolerant by construction: torn JSONL tails are dropped
(mirroring ``obs.ledger.read_ledger``), absent streams contribute no
lane, and a BENCH record may be the raw parsed dict or the driver
wrapper (``{"n", "cmd", "rc", "tail", "parsed"}``). Building a
timeline never touches jax and never fences — it is pure host-side
file merging, usable on a machine that never ran the job.
"""
from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["LANES", "build_timeline", "collect_streams", "has_data",
           "lane_counts", "read_jsonl", "timeline_on",
           "write_timeline"]

# lane name -> Chrome-trace pid (stable: Perfetto sorts by pid)
LANES: Dict[str, int] = {
    "train": 1, "spans": 2, "serving": 3, "ingest": 4,
    "sweep": 5, "bench": 6, "events": 7,
}

# ingest tids within the ingest lane
_TID_INGEST_WALL, _TID_INGEST_PARSE, _TID_INGEST_BIN = 0, 1, 2


def timeline_on(cfg: Any) -> bool:
    """Resolve the ``tpu_timeline`` knob: ``on`` unconditional, ``off``
    never, ``auto`` (default) piggybacks on ``tpu_trace`` — a traced
    run gets its timeline for free, an untraced run pays nothing."""
    mode = str(getattr(cfg, "tpu_timeline", "auto")).lower()
    if mode == "on":
        return True
    if mode == "off":
        return False
    return bool(getattr(cfg, "tpu_trace", False))


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL stream, dropping a torn final line (SIGKILL
    mid-flush) instead of failing — same contract as
    ``obs.ledger.read_ledger`` but returning [] for a missing file."""
    if not os.path.isfile(path):
        return []
    try:
        with open(path) as fh:
            lines = [ln.strip() for ln in fh]
    except OSError:
        return []
    rows: List[Dict[str, Any]] = []
    nonempty = [ln for ln in lines if ln]
    for pos, line in enumerate(nonempty):
        try:
            rec = json.loads(line)
        except ValueError:
            if pos == len(nonempty) - 1:
                break           # torn tail: keep everything before it
            raise
        if isinstance(rec, dict):
            rows.append(rec)
    return rows


def _load_bench(bench: Any) -> Optional[Dict[str, Any]]:
    """Normalize a BENCH input (path / parsed dict / driver wrapper)
    to the parsed record dict, or None."""
    if bench is None:
        return None
    if isinstance(bench, str):
        try:
            with open(bench) as fh:
                bench = json.load(fh)
        except (OSError, ValueError):
            return None
    if not isinstance(bench, dict):
        return None
    if "parsed" in bench and "rc" in bench:     # driver wrapper
        bench = bench.get("parsed")
    return bench if isinstance(bench, dict) else None


def collect_streams(trace_dir: Optional[str] = None,
                    ledger_path: Optional[str] = None,
                    bench: Any = None) -> Dict[str, Any]:
    """Gather every source stream that exists.

    ``trace_dir`` is scanned for ``spans-*.jsonl``, ``ledger-*.jsonl``,
    ``reqtrace-*.jsonl``, ``events-*.jsonl`` and ``bench-*.jsonl``;
    ``ledger_path`` adds one explicit ledger (deduplicated against the
    scan); ``bench`` is a BENCH record (path, parsed dict, or driver
    wrapper)."""
    streams: Dict[str, Any] = {
        "spans": [], "ledger": [], "reqtrace": [], "events": [],
        "bench_ledger": [], "bench_record": _load_bench(bench),
    }
    ledger_files: List[str] = []
    if trace_dir and os.path.isdir(trace_dir):
        for f in sorted(glob.glob(os.path.join(trace_dir,
                                               "spans-*.jsonl"))):
            streams["spans"].extend(read_jsonl(f))
        ledger_files.extend(sorted(glob.glob(
            os.path.join(trace_dir, "ledger-*.jsonl"))))
        for f in sorted(glob.glob(os.path.join(trace_dir,
                                               "reqtrace-*.jsonl"))):
            streams["reqtrace"].extend(read_jsonl(f))
        for f in sorted(glob.glob(os.path.join(trace_dir,
                                               "events-*.jsonl"))):
            streams["events"].extend(read_jsonl(f))
        for f in sorted(glob.glob(os.path.join(trace_dir,
                                               "bench-*.jsonl"))):
            streams["bench_ledger"].extend(read_jsonl(f))
    if ledger_path and os.path.abspath(ledger_path) not in (
            os.path.abspath(f) for f in ledger_files):
        ledger_files.append(ledger_path)
    for f in ledger_files:
        streams["ledger"].extend(read_jsonl(f))
    return streams


# ---------------------------------------------------------------------------
def _meta(pid: int, name: str,
          tids: Dict[int, str]) -> List[Dict[str, Any]]:
    evs = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": name}},
           {"ph": "M", "pid": pid, "tid": 0, "name":
            "process_sort_index", "args": {"sort_index": pid}}]
    for tid, tname in sorted(tids.items()):
        evs.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name", "args": {"name": tname}})
    return evs


class _Builder:
    """Accumulates trace events against a shared anchor; rows without a
    ``t0`` are placed sequentially after their lane cursor."""

    def __init__(self, anchor: float) -> None:
        self.anchor = anchor
        self.events: List[Dict[str, Any]] = []
        self.tids: Dict[int, Dict[int, str]] = {}
        self._cursor: Dict[Tuple[int, int], float] = {}

    def name_tid(self, pid: int, tid: int, name: str) -> None:
        self.tids.setdefault(pid, {}).setdefault(tid, name)

    def _ts_us(self, t0: Optional[float], pid: int, tid: int,
               dur_ms: float) -> Tuple[float, bool]:
        """(start µs, placed-sequentially?) for one row."""
        if isinstance(t0, (int, float)):
            return (float(t0) - self.anchor) * 1e6, False
        cur = self._cursor.get((pid, tid), 0.0)
        return cur, True

    def span(self, pid: int, tid: int, name: str,
             t0: Optional[float], dur_ms: float, src: str,
             args: Optional[Dict[str, Any]] = None) -> None:
        dur_ms = max(float(dur_ms or 0.0), 0.0)
        ts, seq = self._ts_us(t0, pid, tid, dur_ms)
        ev: Dict[str, Any] = {
            "ph": "X", "pid": pid, "tid": tid, "name": name,
            "ts": round(ts, 3), "dur": round(dur_ms * 1e3, 3),
            "cat": src, "args": {"src": src}}
        if seq:
            ev["args"]["placed"] = "sequential"
        if args:
            ev["args"].update(args)
        self.events.append(ev)
        self._cursor[(pid, tid)] = max(
            self._cursor.get((pid, tid), 0.0), ts + dur_ms * 1e3)

    def instant(self, pid: int, tid: int, name: str,
                t0: Optional[float], src: str,
                args: Optional[Dict[str, Any]] = None) -> None:
        ts, seq = self._ts_us(t0, pid, tid, 0.0)
        ev: Dict[str, Any] = {
            "ph": "i", "pid": pid, "tid": tid, "name": name,
            "ts": round(ts, 3), "s": "p", "cat": src,
            "args": {"src": src}}
        if seq:
            ev["args"]["placed"] = "sequential"
        if args:
            ev["args"].update(args)
        self.events.append(ev)


def _find_anchor(streams: Dict[str, Any]) -> float:
    """Earliest monotonic timestamp across every stream (0.0 when no
    stream carries one — everything then places sequentially)."""
    t0s: List[float] = []
    for key in ("spans", "ledger", "events", "bench_ledger"):
        for r in streams.get(key, ()):
            v = r.get("t0")
            if isinstance(v, (int, float)):
                t0s.append(float(v))
    for r in streams.get("reqtrace", ()):
        v = r.get("t_submit")
        if isinstance(v, (int, float)):
            t0s.append(float(v))
    return min(t0s) if t0s else 0.0


# -- per-stream folds -------------------------------------------------------
def _fold_spans(b: _Builder, rows: List[Dict[str, Any]]) -> int:
    pid = LANES["spans"]
    n = 0
    for r in rows:
        if r.get("kind") != "span":
            continue
        tid = int(r.get("depth", 0) or 0)
        b.name_tid(pid, tid, f"depth {tid}")
        b.span(pid, tid, str(r.get("name", "span")), r.get("t0"),
               r.get("dur_ms", 0.0), "spans")
        n += 1
    return n


def _fold_ledger(b: _Builder, rows: List[Dict[str, Any]]
                 ) -> Tuple[int, int, int]:
    """Round records -> train lane (tid 0) + per-device lanes; sweep
    records -> sweep lane per sub-fleet; bench-style stage notes ->
    bench lane. Returns (train_rows, sweep_rows, device_lanes)."""
    pid_t, pid_s = LANES["train"], LANES["sweep"]
    b.name_tid(pid_t, 0, "round loop")
    n_train = n_sweep = 0
    dev_lanes: set = set()
    for r in rows:
        kind = r.get("kind")
        if kind == "round":
            args = {"path": r.get("path"),
                    "timing": r.get("timing", "residual")}
            if "terms_ms" in r:
                args["terms_ms"] = r["terms_ms"]
            if "imbalance" in r:
                args["imbalance"] = r["imbalance"]
            if "allreduce_split_ms" in r:
                args["allreduce_split_ms"] = r["allreduce_split_ms"]
            if r.get("path") == "sweep":
                sid = int(r.get("subfleet", 0) or 0)
                b.name_tid(pid_s, sid, f"sub-fleet {sid}")
                name = f"round {r.get('round')}"
                if "model" in r:
                    name += f" m{r['model']}"
                b.span(pid_s, sid, name, r.get("t0"),
                       r.get("wall_ms", 0.0), "ledger", args)
                n_sweep += 1
            else:
                b.span(pid_t, 0, f"round {r.get('round')}", r.get("t0"),
                       r.get("wall_ms", 0.0), "ledger", args)
                n_train += 1
                # derived per-device segments: device k's fenced
                # wait-attribution share of this profiled round,
                # stacked end-to-start so the lane tiles the round wall
                devs = r.get("device_round_ms")
                ids = r.get("device_ids")
                if isinstance(devs, list) and devs:
                    t0 = r.get("t0")
                    off = 0.0
                    for k, ms in enumerate(devs):
                        did = (ids[k] if isinstance(ids, list)
                               and k < len(ids) else k)
                        tid = 1 + int(did)
                        dev_lanes.add(tid)
                        b.name_tid(pid_t, tid, f"device {did}")
                        start = (t0 + off / 1e3
                                 if isinstance(t0, (int, float))
                                 else None)
                        b.span(pid_t, tid,
                               f"round {r.get('round')} d{did}",
                               start, ms, "ledger.device",
                               {"device": did})
                        off += float(ms or 0.0)
        elif kind == "note" and r.get("note") in (
                "round_anomaly", "dist_straggler"):
            b.instant(LANES["events"], 0, str(r["note"]), r.get("t0"),
                      "ledger.note",
                      {k: v for k, v in r.items()
                       if k not in ("kind", "note", "t0")})
    return n_train, n_sweep, len(dev_lanes)


def _fold_reqtrace(b: _Builder, rows: List[Dict[str, Any]]) -> int:
    pid = LANES["serving"]
    b.name_tid(pid, 0, "requests")
    n = 0
    for r in rows:
        if r.get("kind") != "request":
            continue
        args = {k: r.get(k) for k in
                ("trace_id", "model", "rows", "queue_wait_ms",
                 "flush_reason", "dispatch_ms", "status")
                if r.get(k) is not None}
        b.span(pid, 0, f"req {r.get('model', '?')}", r.get("t_submit"),
               r.get("total_ms", 0.0), "reqtrace", args)
        n += 1
    return n


def _fold_events(b: _Builder, rows: List[Dict[str, Any]]
                 ) -> Tuple[int, int]:
    """Tee'd structured events -> instants, with the ingest events
    additionally expanded into pipeline-wall spans (the parse and bin
    bars OVERLAP — they are thread totals, not exclusive segments).
    Returns (instants, ingest_spans)."""
    pid_e, pid_i = LANES["events"], LANES["ingest"]
    b.name_tid(pid_e, 0, "events")
    n_ev = n_ing = 0
    for r in rows:
        if r.get("kind") != "event":
            continue
        ev = str(r.get("event", "?"))
        t0 = r.get("t0")
        args = {k: v for k, v in r.items()
                if k not in ("kind", "event", "t0")}
        b.instant(pid_e, 0, ev, t0, "events", args)
        n_ev += 1
        if ev in ("stream_ingest", "dist_stream"):
            wall = r.get("wall_ms")
            if not isinstance(wall, (int, float)):
                continue
            # the event fires at ingest END unless the producer gave
            # an explicit start; the sub-bars start with the wall
            start = r.get("t_start")
            if not isinstance(start, (int, float)):
                start = (t0 - wall / 1e3
                         if isinstance(t0, (int, float)) else None)
            b.name_tid(pid_i, _TID_INGEST_WALL, "chunk wall")
            b.span(pid_i, _TID_INGEST_WALL, ev, start, wall,
                   "ingest", {"rows": r.get("rows")})
            n_ing += 1
            for key, tid, nm in (
                    ("parse_ms", _TID_INGEST_PARSE,
                     "parse (prefetch thread)"),
                    ("bin_ms", _TID_INGEST_BIN, "bin (device)")):
                ms = r.get(key)
                if isinstance(ms, (int, float)):
                    b.name_tid(pid_i, tid, nm)
                    b.span(pid_i, tid, key[:-3], start, ms, "ingest",
                           {"overlapped": True})
                    n_ing += 1
    return n_ev, n_ing


def _fold_bench(b: _Builder, notes: List[Dict[str, Any]],
                record: Optional[Dict[str, Any]]) -> int:
    """Bench stage boundaries: prefer the bench ledger's per-stage
    notes (they carry monotonic t0/t1); fall back to the BENCH record's
    ``stage_wall`` dict placed sequentially."""
    pid = LANES["bench"]
    b.name_tid(pid, 0, "stages")
    n = 0
    staged: set = set()
    for r in notes:
        if r.get("kind") != "note" or "stage" not in r:
            continue
        wall_ms = None
        if isinstance(r.get("wall_s"), (int, float)):
            wall_ms = float(r["wall_s"]) * 1e3
        elif isinstance(r.get("t1"), (int, float)) and \
                isinstance(r.get("t0"), (int, float)):
            wall_ms = (r["t1"] - r["t0"]) * 1e3
        b.span(pid, 0, str(r["stage"]), r.get("t0"), wall_ms or 0.0,
               "bench", {"t_s": r.get("t_s")})
        staged.add(r["stage"])
        n += 1
    walls = (record or {}).get("stage_wall")
    if isinstance(walls, dict):
        for stage, wall_s in walls.items():
            if stage in staged or not isinstance(wall_s, (int, float)):
                continue
            b.span(pid, 0, str(stage), None, wall_s * 1e3,
                   "bench.record")
            n += 1
    return n


# ---------------------------------------------------------------------------
def build_timeline(trace_dir: Optional[str] = None,
                   ledger_path: Optional[str] = None,
                   bench: Any = None) -> Dict[str, Any]:
    """The whole merge: collect streams, anchor the clock, fold every
    row into its lane. Returns the Chrome-trace document; inspect
    ``otherData.lanes`` for per-lane row counts (``has_data`` gates
    on them)."""
    streams = collect_streams(trace_dir, ledger_path, bench)
    anchor = _find_anchor(streams)
    b = _Builder(anchor)
    n_spans = _fold_spans(b, streams["spans"])
    n_train, n_sweep, n_dev = _fold_ledger(b, streams["ledger"])
    n_req = _fold_reqtrace(b, streams["reqtrace"])
    n_ev, n_ing = _fold_events(b, streams["events"])
    n_bench = _fold_bench(b, streams["bench_ledger"],
                          streams["bench_record"])
    meta: List[Dict[str, Any]] = []
    lanes = {"spans": n_spans, "train": n_train, "sweep": n_sweep,
             "serving": n_req, "events": n_ev, "ingest": n_ing,
             "bench": n_bench}
    for name, pid in LANES.items():
        if lanes.get(name):
            meta.extend(_meta(pid, name, b.tids.get(pid, {})))
    return {
        "traceEvents": meta + sorted(b.events,
                                     key=lambda e: e.get("ts", 0.0)),
        "displayTimeUnit": "ms",
        "otherData": {
            "schema": 1,
            "clock": "time.perf_counter (CLOCK_MONOTONIC)",
            "anchor_t0": anchor,
            "lanes": lanes,
            "device_lanes": n_dev,
        },
    }


def lane_counts(doc: Dict[str, Any]) -> Dict[str, int]:
    return dict(doc.get("otherData", {}).get("lanes", {}))


def has_data(doc: Dict[str, Any]) -> bool:
    """True iff any lane folded at least one source row."""
    return any(v > 0 for v in lane_counts(doc).values())


def write_timeline(path: str, doc: Dict[str, Any]) -> str:
    """Atomic write (tmp + rename), mirroring ``obs.trace.write``."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, sort_keys=True, default=str)
    os.replace(tmp, path)
    return path
