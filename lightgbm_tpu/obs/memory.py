"""HBM accountant: one ledger of who owns device memory, reconciled
against the backend's own numbers.

Before this module each subsystem tracked bytes privately — the serving
registry budget reads `ForestEngine.device_bytes()`, the aligned engine
knows its record buffers, the spill ring logs its slot bytes once — and
nothing summed them or compared the sum to what the device ACTUALLY
holds. The accountant closes that loop:

* owners self-register with `track(name, obj, fn)`: a weakref to the
  owning object plus a bytes-callback run only at snapshot time. A
  garbage-collected owner silently drops off the ledger (no unregister
  bookkeeping at del time), and registration is a dict insert — cheap
  enough to do unconditionally at object construction, so enabling the
  metrics plane late still sees every live owner.
* `aggregate=True` owners (the serving registry pool, which SUMS its
  entries' engines) are reported but excluded from `claimed_total` —
  otherwise pool + per-engine owners would double-count.
* `snapshot()` reconciles: claimed per owner, claimed total, the
  backend's `jax.local_devices()[0].memory_stats()` where the platform
  provides one (TPU does; CPU returns nothing and the device fields are
  None), and the residual `hbm_unattributed_bytes = bytes_in_use -
  claimed_total` — a growing residual is the leak/under-accounting
  signal. Live + peak gauges land in the metrics registry on every
  snapshot, so a /metrics scrape is always current.

Zero-overhead discipline: nothing here touches jax except inside
`device_memory_stats()` at snapshot time; the hot paths never call into
this module per round/request.
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, Dict, Optional

__all__ = ["track", "untrack", "owners_bytes", "claimed_total",
           "device_memory_stats", "snapshot", "peaks", "reset"]

_lock = threading.Lock()
# name -> (weakref-or-None, fn, aggregate). fn takes the live object (or
# no argument when obj was registered as None) and returns bytes.
_owners: Dict[str, Any] = {}                        # guarded-by: _lock
_peak_claimed = 0                                   # guarded-by: _lock
_peak_in_use = 0                                    # guarded-by: _lock


def track(name: str, obj: Optional[Any], fn: Callable[..., int],
          aggregate: bool = False) -> str:
    """Register `obj` as a named HBM owner; returns the ledger name
    actually used (a `#k` suffix disambiguates same-named live owners).
    Re-tracking the same (name, obj) pair replaces the callback instead
    of duplicating the row. `obj=None` registers a static owner whose
    `fn()` takes no argument (e.g. a fixed-size kernel store)."""
    ref = None if obj is None else weakref.ref(obj)
    with _lock:
        use = name
        k = 1
        while use in _owners:
            old_ref, _fn, _agg = _owners[use]
            old = old_ref() if old_ref is not None else None
            if old_ref is None and obj is None:
                break                      # static owner: replace
            if old is obj and obj is not None:
                break                      # same object: replace
            if old_ref is not None and old is None:
                break                      # dead row: reuse the slot
            k += 1
            use = f"{name}#{k}"
        _owners[use] = (ref, fn, aggregate)
        return use


def untrack(name: str) -> None:
    with _lock:
        _owners.pop(name, None)


def reset() -> None:
    """Drop every owner and both peaks (tests)."""
    global _peak_claimed, _peak_in_use
    with _lock:
        _owners.clear()
        _peak_claimed = 0
        _peak_in_use = 0


def _read_owner(ref, fn) -> Optional[int]:
    """Bytes for one row; None when the owner is dead or the callback
    fails (a snapshot must never raise out of a scrape)."""
    if ref is None:
        args = ()
    else:
        obj = ref()
        if obj is None:
            return None
        args = (obj,)
    try:
        return int(fn(*args))
    except Exception:
        return None


def owners_bytes() -> Dict[str, Dict[str, Any]]:
    """{name: {"bytes": int, "aggregate": bool}} for every live owner;
    dead rows are pruned as a side effect."""
    with _lock:
        items = list(_owners.items())
    out: Dict[str, Dict[str, Any]] = {}
    dead = []
    for name, (ref, fn, agg) in items:
        b = _read_owner(ref, fn)
        if b is None and ref is not None and ref() is None:
            dead.append(name)
            continue
        out[name] = {"bytes": 0 if b is None else b, "aggregate": agg}
    if dead:
        with _lock:
            for name in dead:
                _owners.pop(name, None)
    return out


def claimed_total(owners: Optional[Dict[str, Dict[str, Any]]] = None) -> int:
    owners = owners_bytes() if owners is None else owners
    return sum(o["bytes"] for o in owners.values() if not o["aggregate"])


def device_memory_stats() -> Optional[Dict[str, int]]:
    """The first local device's memory_stats() with int-valued keys, or
    None when the backend has no allocator stats (CPU) or jax is not
    importable yet. Never raises."""
    try:
        import jax
        dev = jax.local_devices()[0]
        stats = dev.memory_stats()
        if not stats:
            return None
        return {k: int(v) for k, v in stats.items()
                if isinstance(v, (int, float))}
    except Exception:
        return None


def peaks() -> Dict[str, int]:
    return {"claimed": _peak_claimed, "in_use": _peak_in_use}


def snapshot() -> Dict[str, Any]:
    """Reconcile and publish: per-owner bytes, claimed total, backend
    bytes-in-use/peak where available, the unattributed residual, and
    process-lifetime peaks (high-water marks over snapshots taken).
    Also refreshes the `hbm_*` gauges in the metrics registry."""
    global _peak_claimed, _peak_in_use
    owners = owners_bytes()
    claimed = claimed_total(owners)
    dev = device_memory_stats()
    in_use = dev.get("bytes_in_use") if dev else None
    dev_peak = dev.get("peak_bytes_in_use") if dev else None
    unattributed = (in_use - claimed) if in_use is not None else None
    with _lock:
        _peak_claimed = max(_peak_claimed, claimed)
        if in_use is not None:
            _peak_in_use = max(_peak_in_use, in_use)
        if dev_peak is not None:
            _peak_in_use = max(_peak_in_use, dev_peak)
        peak_claimed, peak_in_use = _peak_claimed, _peak_in_use
    _publish_gauges(owners, claimed, in_use, unattributed,
                    peak_claimed, peak_in_use)
    return {
        "schema": 1,
        "owners": {n: o["bytes"] for n, o in owners.items()},
        "aggregates": sorted(n for n, o in owners.items()
                             if o["aggregate"]),
        "claimed_bytes": claimed,
        "peak_claimed_bytes": peak_claimed,
        "device_bytes_in_use": in_use,
        "device_peak_bytes_in_use": dev_peak,
        "peak_bytes": peak_in_use or peak_claimed,
        "hbm_unattributed_bytes": unattributed,
    }


def _publish_gauges(owners, claimed, in_use, unattributed,
                    peak_claimed, peak_in_use) -> None:
    from . import metrics as obs_metrics
    r = obs_metrics.registry()
    fam = r.gauge("hbm_claimed_bytes",
                  "device bytes claimed by a registered owner",
                  labelnames=("owner",))
    for name, o in owners.items():
        fam.labels(owner=name).set(o["bytes"])
    r.gauge("hbm_claimed_total_bytes",
            "sum of non-aggregate owner claims").set(claimed)
    r.gauge("hbm_peak_claimed_bytes",
            "high-water mark of claimed bytes over snapshots"
            ).set(peak_claimed)
    if in_use is not None:
        r.gauge("hbm_bytes_in_use",
                "backend allocator bytes_in_use").set(in_use)
        r.gauge("hbm_peak_bytes_in_use",
                "backend allocator peak bytes_in_use").set(peak_in_use)
    if unattributed is not None:
        r.gauge("hbm_unattributed_bytes",
                "bytes_in_use minus claimed (under-accounting residual)"
                ).set(unattributed)
