"""In-run bottleneck profiler: sampled per-term device time, static
XLA cost/roofline attribution, and programmatic capture windows.

Three planes, all opt-in via ``tpu_profile`` (see ``config.py``):

**Sampled per-term device time.** On a sampled round (``round > 0`` and
``round % tpu_profile_every == 0`` — round 0 pays XLA compiles and
would report them as kernel time) the round loop fences EVERY device
dispatch site individually instead of issuing the single end-of-round
residual fence: ``GBDT._dispatch_device`` routes each dispatch through
``RoundSample.timed`` (dispatch, then ``trace.force_fence`` on the
output pytree), and the gradient / score-update / eval sites do the
same. Site times aggregate into a ``terms_ms`` dict over the canonical
vocabulary (``obs/terms.py``) which lands in the ledger round record
(``timing: "fenced"``), in per-term gauges on the metrics registry
(scraped by the serving ``/metrics`` exporter), and — via bench.py —
in ``terms_by_stage`` in bench JSON. Because fencing serializes the
pipelined round, a sampled round's ``device_ms`` is the SUM of fenced
site times, not the residual drain; sampled rounds are excluded from
the ``train_round_ms`` histogram so they cannot pollute p50/p99, and
the record carries ``profiled: true`` so readers never mix the two
timing modes (see docs/Profiling.md).

**Chained-k build calibration.** The aligned path's whole-tree build
is ONE fused program, so fencing can only see its total. On the first
sampled round the profiler reuses the ``obs/devicetime.py`` chained-k
protocol to measure the per-pass cost of the build's constituent
kernels (``hist`` / ``route`` / ``flush`` / ``split_eval``) over the
LIVE engine's record store at its real shapes — the same closures
``tools/device_time_255.py`` runs offline at guessed shapes. The
calibration lands once as a ledger note (``profile_calibration``) and
``tools/bottleneck_report.py`` uses its shares to decompose the fenced
``build`` total in the ranked report. A calibration failure degrades
to the unsplit ``build`` term — it never voids the fenced numbers.

**Static cost attribution.** With the profiler on, ``compile_cache``
captures the abstract arg shapes of every registered program at first
dispatch; ``write_program_costs`` lowers each against those specs and
records XLA ``cost_analysis()`` (flops, bytes accessed) into
``program_costs.json``, classifying each program compute- vs
bandwidth-bound against the device roofline and pairing the estimate
with the measured per-call dispatch wall.

**Capture windows.** ``tpu_profile_capture=start:stop`` brackets those
rounds in a programmatic ``jax.profiler`` trace whose artifact path
lands in ``trace_summary.json``.

Off (``tpu_profile=off``, the default) the round loop pays one is-None
attribute check and adds ZERO fences — asserted by tier-1 alongside
the ``tpu_metrics`` discipline.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import trace
from .terms import TERMS, term_for_site

# ---------------------------------------------------------------------------
# device roofline table: (peak dense f32-ish TFLOP/s, HBM GB/s) by
# device_kind substring. Numbers are nominal public peaks — the
# classification (compute- vs bandwidth-bound) only needs the RATIO to
# be in the right regime, and program_costs.json records which row was
# used so a reader can re-derive with better constants.
_ROOFLINES: Tuple[Tuple[str, float, float], ...] = (
    ("v6", 918.0, 1640.0),         # Trillium (bf16 peak / HBM)
    ("v5p", 459.0, 2765.0),
    ("v5", 197.0, 819.0),          # v5e
    ("v4", 275.0, 1228.0),
    ("v3", 123.0, 900.0),
    ("v2", 45.0, 700.0),
    ("cpu", 0.1, 50.0),            # nominal host core: keeps the ratio
                                   # meaningful for CPU smoke runs
)
_DEFAULT_ROOFLINE = ("unknown", 100.0, 800.0)


def device_roofline() -> Dict[str, Any]:
    """{kind, peak_tflops, hbm_gbps, ridge_flops_per_byte} for the
    first visible jax device (table above; "unknown" fallback)."""
    kind = "unknown"
    try:
        import jax
        kind = str(jax.devices()[0].device_kind).lower()
    except Exception:
        pass
    name, tflops, gbps = _DEFAULT_ROOFLINE
    for sub, tf, gb in _ROOFLINES:
        if sub in kind:
            name, tflops, gbps = sub, tf, gb
            break
    return {"kind": kind, "matched": name, "peak_tflops": tflops,
            "hbm_gbps": gbps,
            "ridge_flops_per_byte": round(tflops * 1e12 / (gbps * 1e9),
                                          2)}


def classify_program(flops: float, bytes_accessed: float,
                     roofline: Dict[str, Any]) -> Dict[str, Any]:
    """Roofline classification of one program: estimated compute and
    bandwidth times, arithmetic intensity, and which bound wins."""
    t_compute_ms = flops / (roofline["peak_tflops"] * 1e12) * 1e3
    t_bw_ms = bytes_accessed / (roofline["hbm_gbps"] * 1e9) * 1e3
    ai = flops / bytes_accessed if bytes_accessed > 0 else float("inf")
    return {
        "est_compute_ms": round(t_compute_ms, 4),
        "est_bandwidth_ms": round(t_bw_ms, 4),
        "est_ms": round(max(t_compute_ms, t_bw_ms), 4),
        "arithmetic_intensity": (round(ai, 3)
                                 if ai != float("inf") else None),
        "bound": ("compute" if t_compute_ms >= t_bw_ms
                  else "bandwidth"),
    }


def _cost_scalars(cost: Any) -> Dict[str, float]:
    """Normalize jax `compiled.cost_analysis()` across versions (dict
    or [dict]) to {flops, bytes_accessed, transcendentals}."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out = {}
    for want, keys in (("flops", ("flops",)),
                       ("bytes_accessed", ("bytes accessed",
                                           "bytes_accessed")),
                       ("transcendentals", ("transcendentals",))):
        for k in keys:
            v = cost.get(k)
            if isinstance(v, (int, float)):
                out[want] = float(v)
                break
    return out


def collect_program_costs() -> Dict[str, Any]:
    """XLA ``cost_analysis()`` for every compile_cache program whose
    arg specs were captured (the profiler enables capture at
    construction): ``{schema, device, programs: {tag: {...}}}``.
    Programs that fail to lower record an ``error`` entry instead of
    voiding the artifact."""
    from .. import compile_cache
    roofline = device_roofline()
    doc: Dict[str, Any] = {"schema": 1, "device": roofline,
                           "programs": {}}
    for ent in compile_cache.captured_programs().values():
        tag = ent["tag"]
        row: Dict[str, Any] = {
            "calls": ent["calls"],
            # host-side dispatch wall (async on TPU — a lower bound on
            # nothing, an upper bound on host cost; on CPU effectively
            # the measured run time). Paired with est_ms below.
            "dispatch_ms_total": round(ent["dispatch_ms"], 2),
            "dispatch_ms_per_call": round(
                ent["dispatch_ms"] / max(ent["calls"], 1), 3),
        }
        try:
            lowered = ent["fn"].lower(*ent["spec_args"],
                                      **ent["spec_kwargs"])
            cost = _cost_scalars(lowered.compile().cost_analysis())
            flops = cost.get("flops", 0.0)
            byts = cost.get("bytes_accessed", 0.0)
            row.update({"flops": flops, "bytes_accessed": byts})
            if flops or byts:
                row.update(classify_program(flops, byts, roofline))
        except Exception as e:  # noqa: BLE001 — per-program, keep going
            row["error"] = f"{type(e).__name__}: {str(e)[:200]}"
        doc["programs"][tag] = row
    return doc


def write_program_costs(path: str) -> str:
    """Write the ``collect_program_costs`` artifact atomically."""
    doc = collect_program_costs()
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True, default=str)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
def _per_device_segments(out: Any, t_start: float
                         ) -> Optional[List[Tuple[int, float]]]:
    """Per-shard wait-attribution of one dispatch: find the first
    multi-shard jax.Array in the output pytree and block its
    addressable shards one by one in device-id order, charging each
    device the INCREMENT of wall spent until its shard was ready
    (the first segment starts at `t_start`, the site's dispatch start,
    so host dispatch wall lands in the first-ready device's column).

    The increments tile the site's wall — device k's column is
    "additional wall spent waiting on shard k after shard k-1 was
    ready", so the columns SUM to the aggregate fenced site time by
    construction (the straggler shard absorbs the skew; earlier-ready
    shards read ~0 once the slowest has been paid for). None when the
    output has no multi-shard array (single-device run, host-only
    site) — the caller falls back to the aggregate fence."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(out)
        target = None
        for leaf in leaves:
            if isinstance(leaf, jax.Array):
                try:
                    shards = leaf.addressable_shards
                except Exception:  # noqa: BLE001 — committed-elsewhere
                    continue
                if len(shards) > 1:
                    target = shards
                    break
        if target is None:
            return None
        segs: List[Tuple[int, float]] = []
        t_prev = t_start
        for sh in sorted(target, key=lambda s: s.device.id):
            trace.force_fence(sh.data)
            now = time.perf_counter()
            segs.append((int(sh.device.id), (now - t_prev) * 1e3))
            t_prev = now
        return segs
    except Exception:  # noqa: BLE001 — attribution must not break a round
        return None


class RoundSample:
    """Per-site fenced times of ONE sampled round. ``timed`` is the
    seam ``GBDT._dispatch_device`` (and the gradient / score-update /
    eval sites) routes through while ``_prof_round`` is set. With
    ``per_device`` (profiled distributed rounds under the timeline),
    each site's drain is additionally attributed per shard — see
    ``_per_device_segments``."""

    __slots__ = ("round", "sites", "t0", "per_device", "device_sites")

    def __init__(self, rnd: int, per_device: bool = False) -> None:
        self.round = rnd
        self.sites: Dict[str, float] = {}
        self.t0 = time.perf_counter()
        self.per_device = per_device
        # site -> {device_id: ms} (only sites whose output was sharded)
        self.device_sites: Dict[str, Dict[int, float]] = {}

    def timed(self, site: str, fn: Callable, *args):
        """Run one dispatch, fence its output pytree, and charge the
        dispatch+drain wall to `site` (sites accumulate — the aligned
        valid walk hits score_update once per valid set)."""
        t0 = time.perf_counter()
        out = fn(*args)
        if self.per_device:
            segs = _per_device_segments(out, t0)
            if segs is not None:
                acc = self.device_sites.setdefault(site, {})
                for did, ms in segs:
                    acc[did] = acc.get(did, 0.0) + ms
        trace.force_fence(out)
        self.sites[site] = self.sites.get(site, 0.0) \
            + (time.perf_counter() - t0) * 1e3
        return out

    def device_total_ms(self) -> float:
        return sum(self.sites.values())

    def device_columns(self, objective: str = ""
                       ) -> Optional[Dict[str, Any]]:
        """Fold ``device_sites`` into the ledger's per-device block:
        ``{device_ids, device_terms_ms, device_round_ms, imbalance,
        allreduce_split_ms?}`` — or None when no site produced
        shard-level segments."""
        if not self.device_sites:
            return None
        ids = sorted({did for per in self.device_sites.values()
                      for did in per})
        dterms: Dict[str, List[float]] = {}
        for site, per in self.device_sites.items():
            term = term_for_site(site, objective)
            col = dterms.setdefault(term, [0.0] * len(ids))
            for k, did in enumerate(ids):
                col[k] += per.get(did, 0.0)
        dterms = {t: [round(v, 3) for v in col]
                  for t, col in dterms.items()}
        totals = [round(sum(col[k] for col in dterms.values()), 3)
                  for k in range(len(ids))]
        out: Dict[str, Any] = {"device_ids": ids,
                               "device_terms_ms": dterms,
                               "device_round_ms": totals}
        med = sorted(totals)[len(totals) // 2] if len(totals) % 2 \
            else sum(sorted(totals)[len(totals) // 2 - 1:
                                    len(totals) // 2 + 1]) / 2.0
        if med > 0:
            out["imbalance"] = round(max(totals) / med, 3)
        ar = self.device_sites.get("dist.allreduce")
        if ar:
            # first-ready shard ~ everyone computing; the rest is the
            # skew the slow shard made the collective wait for
            vals = [ar.get(d, 0.0) for d in ids]
            compute = min(v for v in vals if v > 0) if any(
                v > 0 for v in vals) else 0.0
            out["allreduce_split_ms"] = {
                "compute": round(compute, 3),
                "wait": round(max(sum(vals) - compute, 0.0), 3)}
        return out


class RoundProfiler:
    """The booster-held profiler object (``GBDT._profiler``; None when
    off). Holds sampling state, the one-time build calibration, capture
    windows, and the last sampled ``terms_ms`` (bench reads it)."""

    def __init__(self, every: int = 50,
                 capture: Optional[Tuple[int, int]] = None,
                 capture_dir: str = "", objective: str = "") -> None:
        self.every = max(int(every), 1)
        self.capture = capture
        self.capture_dir = capture_dir
        self.objective = objective
        self.calibration: Optional[Dict[str, Any]] = None
        self.calibration_committed = False   # ledger-note latch (gbdt)
        self._calibrated = False
        self.history: List[Dict[str, Any]] = []   # [{round, terms_ms}]
        self.last_terms: Optional[Dict[str, float]] = None
        self._capturing = False
        self.capture_paths: List[str] = []
        self._force_next = False

    # -- construction -------------------------------------------------
    @classmethod
    def from_config(cls, cfg: Any) -> Optional["RoundProfiler"]:
        """None unless profiling should be live for this booster:
        ``on`` is unconditional, ``auto`` piggybacks on an observability
        plane already being enabled (tpu_trace or tpu_metrics), ``off``
        never."""
        mode = str(getattr(cfg, "tpu_profile", "off")).lower()
        if mode not in ("on", "auto"):
            return None
        if mode == "auto" and not (getattr(cfg, "tpu_trace", False)
                                   or getattr(cfg, "tpu_metrics",
                                              False)):
            return None
        capture = None
        spec = str(getattr(cfg, "tpu_profile_capture", "") or "")
        if spec:
            try:
                a, b = spec.split(":")
                capture = (int(a), int(b))
                if capture[1] <= capture[0]:
                    raise ValueError(spec)
            except ValueError:
                from ..utils import log
                log.warning(f"tpu_profile_capture={spec!r} is not "
                            f"'start:stop'; capture disabled")
                capture = None
        every = int(getattr(cfg, "tpu_profile_every", 0) or 0) or 50
        cdir = getattr(cfg, "tpu_trace_dir", "") or "lgbt_trace"
        return cls(every=every, capture=capture, capture_dir=cdir,
                   objective=getattr(cfg, "objective", ""))

    # -- sampling -----------------------------------------------------
    def should_sample(self, rnd: int) -> bool:
        """Round 0 is never sampled: it pays the XLA compiles, and a
        fence there would book compile wall as kernel time."""
        if self._force_next:
            return True
        return rnd > 0 and rnd % self.every == 0

    def force_next(self) -> None:
        """Make the next round a sampled round regardless of cadence
        (bench profiles ONE round after its timed loop so the timed
        loop itself stays fence-free)."""
        self._force_next = True

    def begin_round(self, rnd: int,
                    per_device: bool = False) -> RoundSample:
        self._force_next = False
        return RoundSample(rnd, per_device=per_device)

    def finish_round(self, sample: RoundSample,
                     engine: Any = None,
                     cfg: Any = None) -> Dict[str, Optional[float]]:
        """Fold a completed sample into canonical ``terms_ms`` (site ->
        term aggregation) and run the one-time build calibration while
        the engine is live."""
        terms: Dict[str, float] = {}
        for site, ms in sample.sites.items():
            term = term_for_site(site, self.objective)
            terms[term] = terms.get(term, 0.0) + ms
        out = {k: round(v, 3) for k, v in terms.items()}
        self.last_terms = out
        self.history.append({"round": sample.round, "terms_ms": out})
        if engine is not None and not self._calibrated:
            self._calibrated = True
            self.calibration = calibrate_build_terms(engine, cfg)
        return out

    # -- capture windows ----------------------------------------------
    def maybe_capture(self, rnd: int) -> None:
        """Start/stop the programmatic ``jax.profiler`` trace at the
        configured round window. Failures disable capture rather than
        break training (the profiler is observability, not the
        product)."""
        if self.capture is None:
            return
        start, stop = self.capture
        if not self._capturing and rnd == start:
            path = os.path.join(self.capture_dir,
                                f"xprof-r{start}-r{stop}")
            try:
                import jax
                os.makedirs(path, exist_ok=True)
                jax.profiler.start_trace(path)
                self._capturing = True
                self.capture_paths.append(path)
            except Exception as e:  # noqa: BLE001
                from ..utils import log
                log.warning(f"profiler capture failed to start: {e}")
                self.capture = None
        elif self._capturing and rnd >= stop:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._capturing = False

    def close(self) -> None:
        """End-of-training hook: close a still-open capture window
        (stop round beyond num_iterations)."""
        if self._capturing:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._capturing = False

    # -- artifacts ----------------------------------------------------
    def summary(self, out_dir: str) -> Dict[str, Any]:
        """Write ``program_costs.json`` under `out_dir` and return the
        summary block the CLI folds into ``trace_summary.json``."""
        self.close()
        os.makedirs(out_dir, exist_ok=True)
        costs_path = os.path.join(out_dir, "program_costs.json")
        try:
            write_program_costs(costs_path)
        except Exception as e:  # noqa: BLE001
            costs_path = None
            from ..utils import log
            log.warning(f"program_costs.json failed: {e}")
        return {
            "sampled_rounds": [h["round"] for h in self.history],
            "every": self.every,
            "last_terms_ms": self.last_terms,
            "calibration": self.calibration,
            "program_costs": costs_path,
            "captures": list(self.capture_paths),
        }

    def mean_terms(self) -> Dict[str, float]:
        """Mean per-term ms over all sampled rounds (bench's
        ``terms_by_stage`` entry)."""
        acc: Dict[str, List[float]] = {}
        for h in self.history:
            for k, v in h["terms_ms"].items():
                if v is not None:
                    acc.setdefault(k, []).append(v)
        return {k: round(sum(v) / len(v), 3) for k, v in acc.items()}


# ---------------------------------------------------------------------------
def calibrate_build_terms(eng: Any, cfg: Any = None,
                          chain: int = 4, reps: int = 2
                          ) -> Optional[Dict[str, Any]]:
    """Chained-k per-pass cost of the fused build's constituent kernels
    over the LIVE aligned engine's record store — the in-process
    version of ``tools/device_time_255.py`` at the REAL shapes instead
    of guessed ones. Returns ``{terms_ms: {hist, route, flush,
    split_eval}, shares: {...}, shapes: {...}}`` or None when the
    engine's layout defeats the closures (every term measured under
    ``TermTimer`` — individual failures go null, a total failure
    returns None)."""
    try:
        return _calibrate_build_terms(eng, cfg, chain, reps)
    except Exception as e:  # noqa: BLE001 — calibration must not break
        from ..utils import log
        log.warning(f"profiler build calibration failed: "
                    f"{type(e).__name__}: {str(e)[:200]}")
        return None


def _calibrate_build_terms(eng: Any, cfg: Any, chain: int,
                           reps: int) -> Optional[Dict[str, Any]]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from ..ops.aligned import move_pass, pack_route2, slot_hist_pass
    from .devicetime import TermTimer

    lr = eng.learner
    C, W, wcnt, NC = eng.C, eng.W, eng.wcnt, eng.NC
    G = eng.ncols
    BH = lr.hist_bins if lr.bundled else lr.max_bin_global
    group = 8 if BH <= 64 else 4
    K = min(max(eng.S - 1, 1), 256)
    subbin = bool(getattr(eng, "hist_subbin", False))
    spill = bool(getattr(eng, "hist_spill", False))
    gfn = eng._pgrad if eng.compact else None
    bag_lane = (-2 if eng.compact
                else eng.lanes.get("bag", -1)) if eng.bagged else -1
    nc_data = int(jax.device_get(jnp.sum(eng.cnts > 0))) or 1
    mid_bin = max(BH // 2, 1)

    tt = TermTimer({"shapes": {"NC": NC, "W": W, "C": C, "G": G,
                               "BH": BH, "K": K, "subbin": subbin,
                               "spill": spill}},
                   chain=chain, reps=reps, catalog=TERMS)

    meta_cnt = np.asarray(jax.device_get(eng.cnts), np.int32)
    # every data chunk splits at mid-bin of feature 0 — the same
    # synthetic routing device_time_255 uses, now over the live store
    r1 = np.full(NC, mid_bin | (1 << 13), np.int32)
    meta = meta_cnt.copy()
    meta[0] |= 1 << 20
    meta[max(nc_data - 1, 0)] |= 1 << 21
    r2 = np.full(NC, pack_route2(0, BH), np.int32)
    basel = np.zeros(NC, np.int32)
    baser = np.full(NC, max(nc_data // 2, 1), np.int32)
    wsel = np.zeros(NC, np.int32)
    nohist = np.full(NC, K, np.int32)
    cb0 = jnp.zeros((eng.S + 2) * 8, jnp.int32)
    rec0 = eng.rec        # read-only input; move_pass returns a copy

    def mk_move(hsl):
        a = tuple(jnp.asarray(x) for x in
                  (r1, r2, basel, baser, meta, wsel, hsl))

        def mk(k):
            @jax.jit
            def f(r):
                def body(i, r):
                    r2_, _ = move_pass(
                        r, *a, cb0, C, W, wcnt, K, G, BH, group,
                        bag_lane=bag_lane, bits=eng.bits, grad_fn=gfn,
                        num_class=eng.num_class, w_used=eng.w_used,
                        gh_off=eng.gh_off, bundled=lr.bundled,
                        interpret=eng.interpret, subbin=subbin,
                        spill=spill)
                    return r2_
                return lax.fori_loop(0, k, body, r)
            return f
        return mk

    tt.measure("route", mk_move(nohist), rec0, rows=eng.n)
    tt.measure("hist_move", mk_move(np.zeros(NC, np.int32)), rec0,
               rows=eng.n)
    tt.derive("flush", "hist_move", "route")

    slots = np.where(meta_cnt > 0, 0, 1).astype(np.int32)
    sl_j = jnp.asarray(slots)
    mc_j = jnp.asarray(meta_cnt)

    def mk_hist(k):
        @jax.jit
        def f(r):
            def body(i, carry):
                r, acc = carry
                h = slot_hist_pass(
                    r, sl_j, mc_j, 1, G, BH, C, group, wcnt,
                    bag_lane=bag_lane, bits=eng.bits, grad_fn=gfn,
                    num_class=eng.num_class, gh_off=eng.gh_off,
                    interpret=eng.interpret, subbin=subbin)
                r = r.at[0, 0, 0].add(1)
                return (r, acc + h[0, 0, 0, 0])
            return lax.fori_loop(0, k, body, (r, jnp.float32(0.0)))
        return f

    tt.measure("hist", mk_hist, rec0, rows=eng.n)

    # split finder over a changed-children histogram batch (the
    # learner's REAL finder, random histograms at its real [F, B])
    try:
        F = lr.num_features
        B = lr.max_bin_global
        finder = lr.finder
        rng = np.random.RandomState(0)
        splitk = 8
        hist_b = jnp.asarray(
            rng.rand(splitk, F, B, 3).astype(np.float32))
        sg = jnp.sum(hist_b[..., 0], axis=(1, 2)) / F
        sh = jnp.sum(hist_b[..., 1], axis=(1, 2)) / F
        cntv = jnp.full((splitk,), np.float32(eng.n))
        minc = jnp.full((splitk,), np.float32(-1e30))
        maxc = jnp.full((splitk,), np.float32(1e30))
        vf = jax.vmap(lambda h, g, hh, c, lo, hi:
                      finder(h, g, hh, c, lo, hi)["gain"])

        def mk_split(k):
            @jax.jit
            def f(h):
                def body(i, carry):
                    h, acc = carry
                    gain = vf(h, sg, sh, cntv, minc, maxc)
                    return (h + 1e-6, acc + gain[0, 0])
                return lax.fori_loop(0, k, body, (h, jnp.float32(0.0)))
            return f

        tt.measure("split_eval", mk_split, hist_b)
    except Exception as e:  # noqa: BLE001
        tt.out["terms_ms"]["split_eval"] = None
        tt.out["split_eval_error"] = f"{type(e).__name__}"

    terms = {k: v for k, v in tt.out["terms_ms"].items()}
    measured = {k: v for k, v in terms.items() if v}
    if not measured:
        return None
    total = sum(measured.values())
    return {
        "terms_ms": terms,
        "shares": {k: round(v / total, 4) for k, v in measured.items()},
        "shapes": tt.out["shapes"],
        "protocol": {"chain": chain, "reps": reps},
    }
