"""Request-scoped tracing for the serving plane: why was THIS request
slow, and which model is burning its SLO budget right now?

The metrics plane (obs/metrics.py) aggregates into histograms and the
profiler (obs/profiler.py) samples training rounds — neither can answer
a per-request question. This module is the missing layer: every
`RequestCoalescer.submit()` mints a trace ID whose span record
accumulates, across the request's whole life,

* queue wait (submit -> flusher pickup),
* the batch it rode in (id, flush reason full/deadline, rows, requests,
  padded-bucket fill ratio),
* the engine dispatch wall and its share of the request's total
  latency, and
* the total submit-to-result latency and outcome (the error path
  delivers a trace row too — a request that died in a failed batch is
  exactly the one worth reading about).

Finished records land in two places:

* a fixed-size in-memory **ring** (every record, oldest overwritten
  first) served live at the exporter's ``/debug/requests`` endpoint,
  interleaved with registry load/swap/evict **markers** so a slow
  request can be eyeballed against the hot swap that stalled it;
* a **tail-sampled JSONL stream** (``reqtrace-<pid>.jsonl``): requests
  breaching ``tpu_serve_slo_ms`` and errored requests are ALWAYS kept;
  a non-breaching request is kept when a deterministic hash of its
  trace ID falls under ``tpu_serve_trace_sample`` — no RNG, so the same
  traffic keeps the same rows on every run, and sample=0.0 is pure tail
  sampling. One row per line, flushed per line: a killed host keeps
  every finished request so far.

On top of the stream sit the aggregate SLO signals ROADMAP item 4's
load-shedder will consume, registered in the PR-8 metrics registry when
the plane is on: per-model ``serve_slo_burn_rate`` gauges (rolling
bad/total ratio over the last `_BURN_WINDOW` outcomes vs the SLO),
``serve_slo_breaches_total`` counters, a rate-limited
``serve_request_slow`` event per breach burst, and an edge-triggered
``serve_slo_burn`` event when a model's burn rate crosses the high
watermark.

Zero-overhead-off discipline (same as obs/trace.py): the coalescer
holds a tracer handle that is ``None`` when ``tpu_serve_trace`` is off,
so the disabled hot path pays one is-None branch and zero device
fences — tier-1 asserted in tests/test_reqtrace.py.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils import locks, log

__all__ = ["TraceSpan", "RequestTracer",
           "SLO_BURN_HIGH", "SLO_BURN_CLEAR"]

# rolling per-model outcome window feeding the burn gauge
_BURN_WINDOW = 256
# burn-rate hysteresis: serve_slo_burn fires crossing HIGH upward (with
# at least _BURN_MIN_N outcomes observed) and re-arms below CLEAR
SLO_BURN_HIGH = 0.5
SLO_BURN_CLEAR = 0.25
_BURN_MIN_N = 16
# serve_request_slow is a rate-limited POINTER (at most one per model
# per this interval) — the full span is in the ring/JSONL
_SLOW_EVENT_INTERVAL_S = 1.0


class TraceSpan:
    """One request's span record. Minted by `RequestTracer.start` at
    submit time; the coalescer's flusher fills the batch-side fields via
    `RequestTracer.finish` exactly once, success or failure."""

    __slots__ = ("trace_id", "model", "rows", "ts", "t_submit",
                 "queue_wait_ms", "batch_id", "flush_reason",
                 "batch_rows", "batch_requests", "fill_ratio",
                 "dispatch_ms", "dispatch_share", "total_ms", "status",
                 "error", "slo_breach", "kept")

    def __init__(self, trace_id: str, model: str, rows: int,
                 t_submit: float) -> None:
        self.trace_id = trace_id
        self.model = model
        self.rows = rows
        self.ts = time.time()            # epoch at submit (reporting)
        self.t_submit = t_submit         # perf_counter at submit
        self.queue_wait_ms: Optional[float] = None
        self.batch_id: Optional[str] = None
        self.flush_reason: Optional[str] = None
        self.batch_rows: Optional[int] = None
        self.batch_requests: Optional[int] = None
        self.fill_ratio: Optional[float] = None
        self.dispatch_ms: Optional[float] = None
        self.dispatch_share: Optional[float] = None
        self.total_ms: Optional[float] = None
        self.status = "pending"
        self.error: Optional[str] = None
        self.slo_breach = False
        self.kept = False

    def row(self) -> Dict[str, Any]:
        """The span as one JSON-able trace row."""
        r3 = lambda v: None if v is None else round(v, 3)  # noqa: E731
        return {
            "kind": "request", "trace_id": self.trace_id,
            "model": self.model, "rows": self.rows,
            "ts": round(self.ts, 6),
            # monotonic submit time: what the unified timeline
            # (obs/timeline.py) joins on — epoch ts is reporting-only
            "t_submit": round(self.t_submit, 6),
            "queue_wait_ms": r3(self.queue_wait_ms),
            "batch_id": self.batch_id,
            "flush_reason": self.flush_reason,
            "batch_rows": self.batch_rows,
            "batch_requests": self.batch_requests,
            "fill_ratio": None if self.fill_ratio is None
            else round(self.fill_ratio, 4),
            "dispatch_ms": r3(self.dispatch_ms),
            "dispatch_share": None if self.dispatch_share is None
            else round(self.dispatch_share, 4),
            "total_ms": r3(self.total_ms),
            "status": self.status, "error": self.error,
            "slo_breach": self.slo_breach, "kept": self.kept,
        }


def _sample_keep(trace_id: str, sample: float) -> bool:
    """Deterministic head-sampling decision: hash the trace ID into
    [0, 1) and keep when under `sample`. No RNG — replayable, and
    test-assertable without seeding anything."""
    if sample >= 1.0:
        return True
    if sample <= 0.0:
        return False
    h = hashlib.sha1(trace_id.encode()).digest()
    frac = int.from_bytes(h[:8], "big") / float(1 << 64)
    return frac < sample


@locks.guarded
class RequestTracer:
    """Ring + tail-sampled JSONL + SLO burn accounting for one serving
    host. Thread-safe; every method is a leaf with respect to the
    serving locks (the coalescer/registry may call in while holding
    their own locks, never vice versa)."""

    def __init__(self, slo_ms: float = 0.0, sample: float = 0.0,
                 ring_size: int = 512, out_dir: str = "") -> None:
        self.slo_ms = max(float(slo_ms), 0.0)
        self.sample = min(max(float(sample), 0.0), 1.0)
        self.ring_size = max(int(ring_size), 1)
        self._lock = threading.Lock()
        self._ring: List[Optional[Dict[str, Any]]] = \
            [None] * self.ring_size                 # guarded-by: _lock
        self._ring_next = 0                         # guarded-by: _lock
        self._seq = 0                               # guarded-by: _lock
        self._batch_seq = 0                         # guarded-by: _lock
        self.started = 0                            # guarded-by: _lock
        self.finished = 0                           # guarded-by: _lock
        self.breaches = 0                           # guarded-by: _lock
        self.errors = 0                             # guarded-by: _lock
        self.kept_rows = 0                          # guarded-by: _lock
        self.markers = 0                            # guarded-by: _lock
        self._burn: Dict[str, deque] = {}           # guarded-by: _lock
        self._burn_high: Dict[str, bool] = {}       # guarded-by: _lock
        self._last_slow_emit: Dict[str, float] = {}  # guarded-by: _lock
        self._closed = False                        # guarded-by: _lock
        self.path: Optional[str] = None
        fh = None
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            self.path = os.path.join(out_dir,
                                     f"reqtrace-{os.getpid()}.jsonl")
            fh = open(self.path, "a")
            fh.write(json.dumps(
                {"kind": "header", "pid": os.getpid(),
                 "ts": round(time.time(), 6), "slo_ms": self.slo_ms,
                 "sample": self.sample, "ring_size": self.ring_size},
                sort_keys=True) + "\n")
            fh.flush()
        self._fh = fh                               # guarded-by: _lock
        # live SLO instruments: resolved once, None when the metrics
        # plane is off (finish then skips the registry entirely)
        from . import metrics as obs_metrics
        self._metrics = (obs_metrics.serving_instruments()
                         if obs_metrics.enabled() else None)

    # -- span lifecycle ----------------------------------------------------
    def start(self, model: str, rows: int,
              t_submit: Optional[float] = None) -> TraceSpan:
        """Mint a trace ID + span at submit time (called by
        `RequestCoalescer.submit` under its condition lock; this lock is
        a leaf below it)."""
        with self._lock:
            self._seq += 1
            self.started += 1
            trace_id = f"r{os.getpid():05d}-{self._seq:08d}"
        return TraceSpan(trace_id, model, int(rows),
                         time.perf_counter() if t_submit is None
                         else t_submit)

    def next_batch_id(self) -> str:
        with self._lock:
            self._batch_seq += 1
            return f"b{self._batch_seq:06d}"

    def finish(self, span: TraceSpan, *, queue_wait_ms: float,
               batch_id: Optional[str], flush_reason: str,
               batch_rows: Optional[int], batch_requests: Optional[int],
               fill_ratio: Optional[float], dispatch_ms: Optional[float],
               total_ms: float, status: str = "ok",
               error: Optional[str] = None) -> Dict[str, Any]:
        """Complete one span exactly once: ring insert, burn update,
        sampling decision, JSONL append. Returns the trace row."""
        span.queue_wait_ms = queue_wait_ms
        span.batch_id = batch_id
        span.flush_reason = flush_reason
        span.batch_rows = batch_rows
        span.batch_requests = batch_requests
        span.fill_ratio = fill_ratio
        span.dispatch_ms = dispatch_ms
        span.total_ms = total_ms
        if dispatch_ms is not None and total_ms > 0:
            span.dispatch_share = min(dispatch_ms / total_ms, 1.0)
        span.status = status
        span.error = error
        bad = status != "ok"
        breach = self.slo_ms > 0 and total_ms > self.slo_ms
        span.slo_breach = breach
        span.kept = (breach or bad
                     or _sample_keep(span.trace_id, self.sample))
        row = span.row()
        slow_fields = None
        burn_fields = None
        burn_rate = None
        with self._lock:
            self.finished += 1
            if breach:
                self.breaches += 1
            if bad:
                self.errors += 1
            if span.kept:
                self.kept_rows += 1
                if self._fh is not None and not self._closed:
                    self._fh.write(json.dumps(row, sort_keys=True) + "\n")
                    self._fh.flush()
            self._ring[self._ring_next % self.ring_size] = row
            self._ring_next += 1
            if self.slo_ms > 0:
                win = self._burn.setdefault(
                    span.model, deque(maxlen=_BURN_WINDOW))
                win.append(bool(breach or bad))
                burn_rate = sum(win) / len(win)
                if breach:
                    now = time.monotonic()
                    last = self._last_slow_emit.get(span.model, -1e18)
                    if now - last >= _SLOW_EVENT_INTERVAL_S:
                        self._last_slow_emit[span.model] = now
                        slow_fields = {
                            "trace_id": span.trace_id,
                            "model": span.model,
                            "total_ms": row["total_ms"],
                            "queue_wait_ms": row["queue_wait_ms"],
                            "dispatch_ms": row["dispatch_ms"],
                            "flush_reason": flush_reason,
                            "slo_ms": self.slo_ms,
                        }
                high = self._burn_high.get(span.model, False)
                if not high and burn_rate >= SLO_BURN_HIGH \
                        and len(win) >= _BURN_MIN_N:
                    self._burn_high[span.model] = True
                    burn_fields = {"model": span.model,
                                   "burn_rate": round(burn_rate, 4),
                                   "window": len(win),
                                   "slo_ms": self.slo_ms}
                elif high and burn_rate <= SLO_BURN_CLEAR:
                    self._burn_high[span.model] = False
        # events + metrics OUTSIDE the tracer lock (leaf discipline:
        # the metrics instruments take their own locks)
        m = self._metrics
        if m is not None and burn_rate is not None:
            if breach:
                m.slo_breaches.labels(model=span.model).inc()
            m.slo_burn.labels(model=span.model).set(burn_rate)
        if slow_fields is not None:
            log.event("serve_request_slow", **slow_fields)
        if burn_fields is not None:
            log.event("serve_slo_burn", **burn_fields)
        return row

    # -- markers -----------------------------------------------------------
    def note(self, kind: str, **fields: Any) -> None:
        """Interleave a serving-plane event (load/swap/evict/bad-model)
        into the ring + stream so /debug/requests and trace_report can
        correlate request latency with registry churn. The caller has
        already emitted the catalogued log.event — this is the ring's
        copy, not a second event."""
        row = dict({"kind": "marker", "marker": kind,
                    "ts": round(time.time(), 6)}, **fields)
        with self._lock:
            if self._closed:
                return
            self.markers += 1
            self._ring[self._ring_next % self.ring_size] = row
            self._ring_next += 1
            if self._fh is not None:
                self._fh.write(json.dumps(row, sort_keys=True,
                                          default=str) + "\n")
                self._fh.flush()

    # -- views -------------------------------------------------------------
    def recent(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """Ring contents oldest -> newest (requests + markers)."""
        with self._lock:
            total = self._ring_next
            size = self.ring_size
            start = max(total - size, 0)
            out = [self._ring[i % size] for i in range(start, total)]
        if n is not None:
            out = out[-n:]
        return [r for r in out if r is not None]

    def slow_requests(self, n: int = 20) -> List[Dict[str, Any]]:
        """Slowest request rows still in the ring, worst first."""
        rows = [r for r in self.recent() if r.get("kind") == "request"]
        rows.sort(key=lambda r: -(r.get("total_ms") or 0.0))
        return rows[:n]

    def burn_rates(self) -> Dict[str, float]:
        with self._lock:
            return {m: round(sum(w) / len(w), 4)
                    for m, w in self._burn.items() if w}

    def totals(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "started": self.started, "finished": self.finished,
                "breaches": self.breaches, "errors": self.errors,
                "kept_rows": self.kept_rows, "markers": self.markers,
                "slo_ms": self.slo_ms, "sample": self.sample,
                "ring_size": self.ring_size, "path": self.path,
            }

    def snapshot(self, slow_n: int = 20) -> Dict[str, Any]:
        """The /debug/requests document."""
        return {"schema": 1, "totals": self.totals(),
                "burn_rates": self.burn_rates(),
                "recent": self.recent(),
                "slow": self.slow_requests(slow_n)}

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Flush + close the stream and emit the `serve_trace_dump`
        summary event. Idempotent; the ring stays readable after."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            fields = {"requests": self.finished,
                      "kept_rows": self.kept_rows,
                      "breaches": self.breaches, "errors": self.errors,
                      "markers": self.markers, "path": self.path}
        log.event("serve_trace_dump", **fields)

    def __enter__(self) -> "RequestTracer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
