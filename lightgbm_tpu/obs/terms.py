"""Canonical device-time term vocabulary.

One table names every term the framework can attribute device time to —
the in-run profiler's ledger ``terms_ms`` keys, the metrics registry's
per-term gauges, the bench record's ``terms_by_stage``, and the offline
chained-k tools (``tools/device_time_r4.py`` / ``device_time_255.py`` /
``profile_mslr.py``) all draw from THIS dict, so a number labelled
"rank_grad" in a ledger and one in an offline tool's JSON line are the
same quantity by construction (asserted by ``tests/test_profiler.py``).

Two kinds of terms share the vocabulary:

- **fenced terms** — measured in-run by fencing one dispatch site on a
  sampled round (``SITE_TERMS`` maps the ``_dispatch_device`` site
  string to its term). These are disjoint and sum to the sampled
  round's fenced device total.
- **calibration terms** — per-pass kernel costs measured standalone
  under the chained-k protocol (offline tools, or the profiler's
  in-run calibration over the live record store). They decompose the
  fused ``build`` term in the report; they are rates, not round totals.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

# term -> one-line description (the docs table in docs/Profiling.md is
# generated from the same text)
TERMS: Dict[str, str] = {
    # fenced (round-level, disjoint)
    "grad": "pointwise objective gradient + hessian pass",
    "rank_grad": "lambdarank pair-gradient + NDCG-delta pass "
                 "(segment-fused Pallas kernel or bucketed fallback)",
    "build": "whole-tree build program (root hist + move/route + "
             "split eval fused into one dispatch on the aligned path)",
    "score_update": "tree score application to train/valid score lanes",
    "eval": "device metric programs queued for per-round evaluation",
    "collective": "cross-device psum/all-reduce time on parallel "
                  "learners",
    "allreduce": "standalone histogram-shaped all-reduce probe on a "
                 "sampled round (per-round collective visibility for "
                 "the distributed runtime)",
    "other": "residual device drain not attributed to a fenced site",
    # calibration (per-pass kernel rates)
    "bin_sync": "host wall time of distributed bin-boundary finding "
                "(per-shard sample pass + global merge) at dataset "
                "construction",
    "hist": "slot histogram accumulation over the full record store",
    "route": "partition/routing move pass (decode + compact store), "
             "no hist slots",
    "flush": "marginal fused sub-binned hist accumulate + slot flush "
             "in the move pass (hist_move minus route)",
    "hist_move": "hist-accumulating move pass (minuend for flush; "
                 "removed by TermTimer.derive)",
    "copy": "record-store copy move pass (no split, no hist)",
    "split_eval": "split finder over a changed-children histogram "
                  "batch",
    "ingest": "streaming out-of-core ingest wall time (sample pass + "
              "on-device chunk binning + HBM append) at dataset "
              "construction",
    "ingest_parse": "host side of the pipelined stream-to-shard ingest: "
                    "text parse + used-column select/transpose/pad on "
                    "the prefetch thread (overlaps ingest_bin; the two "
                    "sum to MORE than the ingest wall when the pipeline "
                    "overlaps)",
    "ingest_bin": "device side of the pipelined stream-to-shard ingest: "
                  "chunk transfer + owner-device searchsorted binning + "
                  "donated shard append, including the double-buffer "
                  "pacing waits",
    "quant_pack": "stochastic-rounded gradient quantization pass of "
                  "the quantized-histogram path (per-tree int8/int16 "
                  "pack + scale)",
    "sweep": "batched fleet round program (all M models' gradients + "
             "builds + score updates in one dispatch); fenced only on "
             "trim rounds, where the sweep loop drains anyway",
}

# _dispatch_device site string -> fenced term. Sites not listed fall
# back to "other" (they still count; the vocabulary stays closed).
SITE_TERMS: Dict[str, str] = {
    "objective.grad": "grad",
    "engine.train_iter": "build",
    "engine.train_iter_mc": "build",
    "learner.train": "build",
    "learner.train_fresh": "build",
    "learner.train_iter_fused": "build",
    "score_update": "score_update",
    "eval": "eval",
    "dist.allreduce": "allreduce",
    "round_tail": "other",
    "sweep.round": "sweep",
}

# objectives whose gradient pass is the ranking pair term
RANKING_OBJECTIVES = frozenset({"lambdarank", "rank_xendcg"})


def term_for_site(site: str, objective: str = "") -> str:
    """Fenced term for a dispatch site; the gradient site promotes to
    ``rank_grad`` for ranking objectives."""
    term = SITE_TERMS.get(site, "other")
    if term == "grad" and objective in RANKING_OBJECTIVES:
        return "rank_grad"
    return term


def validate_terms_ms(terms: Any) -> Optional[str]:
    """None when `terms` is a well-formed ``terms_ms`` dict (canonical
    keys, numeric-or-null values); else a reason string."""
    if not isinstance(terms, dict):
        return f"terms_ms must be a dict, got {type(terms).__name__}"
    for k, v in terms.items():
        if k not in TERMS:
            return f"unknown term {k!r} (not in obs.terms.TERMS)"
        if v is not None and (not isinstance(v, (int, float))
                              or isinstance(v, bool) or v < 0):
            return f"bad value for term {k!r}: {v!r}"
    return None
