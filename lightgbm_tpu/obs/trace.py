"""Low-overhead span tracer: nested host spans, device-time fences, and
`jax.profiler` annotations, all gated on a single module-level flag.

Design constraints (ISSUE 4):

- Disabled cost is NIL. `span()` hands back one shared null context
  (no allocation), `fence()` returns its argument untouched (jax is not
  even imported), and callers guard everything else behind
  ``trace.enabled()``.
- Device time is only observable at a fence. ``fence(x)`` calls
  ``jax.block_until_ready`` on the pytree ONLY while tracing is on and
  counts every such call in ``fence_count`` — the tier-1 zero-fence test
  monkeypatches ``_block`` with a counting wrapper and asserts it never
  fires on an untraced run.
- Spans also enter XLA profiles: each span wraps a
  ``jax.profiler.TraceAnnotation`` and the round loop wraps each round
  in ``jax.profiler.StepTraceAnnotation`` (via ``step()``), so attaching
  the jax profiler to a traced run yields named regions for free.

Completed spans accumulate in memory and — when a trace directory is
configured — append to ``<dir>/spans-<pid>.jsonl`` one JSON record per
span, flushed per line so a killed process keeps everything closed so
far.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

fence_count = 0          # fences issued while tracing (test probe)
_enabled = False
_dir: Optional[str] = None
_fh = None
_spans: List[Dict[str, Any]] = []
_depth = 0
_lock = threading.Lock()
_block = None            # resolved lazily to jax.block_until_ready
_efh = None              # events-<pid>.jsonl tee (timeline join)


def enabled() -> bool:
    return _enabled


def enable(trace_dir: Optional[str] = None) -> None:
    """Turn the tracer on, optionally appending span JSONL under
    `trace_dir` (created if missing). Idempotent; a later call with a
    directory upgrades a memory-only tracer to a file-backed one."""
    global _enabled, _dir, _fh, _efh
    with _lock:
        _enabled = True
        if trace_dir and trace_dir != _dir:
            if _fh is not None:
                _fh.close()
            if _efh is not None:
                _efh.close()
                _efh = None
            os.makedirs(trace_dir, exist_ok=True)
            _dir = trace_dir
            _fh = open(os.path.join(trace_dir,
                                    f"spans-{os.getpid()}.jsonl"), "a")


def disable() -> None:
    global _enabled, _fh, _dir, _efh
    with _lock:
        _enabled = False
        if _fh is not None:
            _fh.close()
            _fh = None
        if _efh is not None:
            _efh.close()
            _efh = None
        _dir = None


def tee_event(kind: str, fields: Dict[str, Any]) -> None:
    """Mirror one structured event (utils/log.event) into
    ``<dir>/events-<pid>.jsonl``, stamped with a monotonic ``t0`` so
    the timeline (obs/timeline.py) can place compile-cache misses,
    straggler raises, ingest completions etc. on the run's shared
    clock. No-op unless a file-backed trace directory is configured —
    the untraced path pays one bool check in utils/log.event and never
    reaches here."""
    global _efh
    if not _enabled or _dir is None:
        return
    rec = {"kind": "event", "event": kind, "t0": time.perf_counter()}
    rec.update(fields)
    with _lock:
        if _dir is None:
            return
        if _efh is None:
            _efh = open(os.path.join(_dir,
                                     f"events-{os.getpid()}.jsonl"),
                        "a")
        _efh.write(json.dumps(rec, sort_keys=True, default=str) + "\n")
        _efh.flush()


def reset() -> None:
    """Clear accumulated spans and the fence counter (tests)."""
    global fence_count
    with _lock:
        _spans.clear()
        fence_count = 0


def spans() -> List[Dict[str, Any]]:
    """Completed span records, in completion order."""
    return list(_spans)


def trace_dir() -> Optional[str]:
    return _dir


class _NullSpan:
    """Shared do-nothing context for the disabled path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


def _profiler_annotation(name: str):
    """A jax.profiler.TraceAnnotation when the profiler is importable;
    None otherwise (the tracer must not force a jax import ordering)."""
    try:
        from jax import profiler
        return profiler.TraceAnnotation(name)
    except Exception:
        return None


class _Span:
    __slots__ = ("name", "attrs", "t0", "_ann")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0
        self._ann = None

    def __enter__(self):
        global _depth
        self._ann = _profiler_annotation(self.name)
        if self._ann is not None:
            self._ann.__enter__()
        with _lock:
            _depth += 1
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        global _depth
        dur = time.perf_counter() - self.t0
        if self._ann is not None:
            self._ann.__exit__(*exc)
        rec = {"kind": "span", "name": self.name, "t0": self.t0,
               "dur_ms": round(dur * 1e3, 4)}
        if self.attrs:
            rec.update(self.attrs)
        with _lock:
            _depth -= 1
            rec["depth"] = _depth
            _spans.append(rec)
            if _fh is not None:
                _fh.write(json.dumps(rec, sort_keys=True, default=str)
                          + "\n")
                _fh.flush()
        return False


def span(name: str, **attrs):
    """Context manager timing a named region. Free when tracing is off
    (returns one shared null context, no allocation)."""
    if not _enabled:
        return _NULL
    return _Span(name, attrs)


def step(step_num: int):
    """Round boundary: wraps `jax.profiler.StepTraceAnnotation` so XLA
    profiles group work per boosting round. Null context when off."""
    if not _enabled:
        return _NULL
    try:
        from jax import profiler
        return profiler.StepTraceAnnotation("train_round",
                                            step_num=step_num)
    except Exception:
        return _NULL


def fence(x):
    """Drain device work hanging off pytree `x` — ONLY while tracing.

    Disabled: returns `x` untouched without importing jax (this is the
    round loop's guarantee of zero added fences). Enabled: blocks until
    every jax array leaf is ready and bumps `fence_count`.
    """
    global fence_count, _block
    if not _enabled:
        return x
    if _block is None:
        import jax
        _block = jax.block_until_ready
    fence_count += 1
    return _block(x)


def force_fence(x):
    """Drain device work hanging off pytree `x` REGARDLESS of the
    tracing flag — the in-run profiler's per-site fence on sampled
    rounds (obs/profiler.py). Shares `_block` and `fence_count` with
    `fence()` so the tier-1 zero-fence assertion (monkeypatching
    `_block`) covers profiler fences too: a run with the profiler off
    must never reach here."""
    global fence_count, _block
    if _block is None:
        import jax
        _block = jax.block_until_ready
    fence_count += 1
    return _block(x)


def write(path: str, extra: Optional[Dict[str, Any]] = None) -> str:
    """Dump all completed spans (plus a summary header) to `path` as one
    JSON document — the CLI's end-of-training trace dump. `extra` keys
    merge into the top level (the CLI folds compile-cache hit/miss
    totals and per-program miss attribution in here, so warm-up
    forensics don't require a bench run)."""
    by_name: Dict[str, Dict[str, float]] = {}
    for s in _spans:
        agg = by_name.setdefault(s["name"], {"count": 0, "total_ms": 0.0})
        agg["count"] += 1
        agg["total_ms"] = round(agg["total_ms"] + s["dur_ms"], 4)
    doc = {"pid": os.getpid(), "fences": fence_count,
           "summary": by_name, "spans": _spans}
    if extra:
        doc.update(extra)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, sort_keys=True, default=str)
    os.replace(tmp, path)
    return path
