"""Crash-proof incremental benchmark records.

The round-5 bench regression (`BENCH_r05.json: rc 124, parsed: null`)
happened because bench.py printed its JSON summary only as the very last
line — a driver timeout voided the whole record. `BenchRecorder` makes
that impossible:

- the cumulative record is RE-EMITTED to stdout after every completed
  stage (the driver's "last JSON line wins" parse stays valid at any
  kill point);
- every flush also atomically rewrites a sidecar file (tmp + rename), so
  partial results survive even a SIGKILL between stages;
- SIGTERM/SIGINT traps and an atexit hook flush one final time with
  ``incomplete: true`` plus the stage reached — `timeout -k` sends
  SIGTERM first, which gives the trap a window before the follow-up
  SIGKILL;
- ``finalize()`` clears the incomplete marker and writes the same schema
  as before (the new keys are additive, so BENCH_r01–r05 parsers keep
  working).
"""
from __future__ import annotations

import atexit
import json
import os
import signal
import sys
from typing import Any, Dict, Optional


class BenchRecorder:
    """Owns the cumulative bench `out` dict and its durability."""

    def __init__(self, out: Dict[str, Any], path: Optional[str] = None,
                 install_traps: bool = True) -> None:
        self.out = out
        self.path = path
        self.finalized = False
        out.setdefault("incomplete", True)
        out.setdefault("stage_reached", None)
        out.setdefault("stages_done", [])
        if install_traps:
            self._install_traps()

    # -- stage protocol ----------------------------------------------------
    def start_stage(self, name: str) -> None:
        self.out["stage_reached"] = name
        # sidecar-only flush (no stdout line): even an untrappable
        # SIGKILL mid-stage leaves the stage name on disk
        self.flush_file()

    def stage_done(self, name: str) -> None:
        if name not in self.out["stages_done"]:
            self.out["stages_done"].append(name)
        self.emit()

    # -- durability --------------------------------------------------------
    def emit(self) -> None:
        """Print the cumulative record as one stdout JSON line AND flush
        the sidecar file. Call after every stage (and on any skip that
        mutates the record) — the last line printed is always complete."""
        print(json.dumps(self.out, default=str), flush=True)
        self.flush_file()

    def flush_file(self) -> None:
        """Atomic tmp+rename rewrite of the sidecar (no-op without a
        path). A reader never observes a torn file."""
        if not self.path:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json.dump(self.out, fh, default=str)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def finalize(self) -> Dict[str, Any]:
        """Mark the run complete and emit the final record."""
        self.finalized = True
        self.out["incomplete"] = False
        self.emit()
        return self.out

    # -- interruption ------------------------------------------------------
    def flush_incomplete(self, reason: Optional[str] = None) -> None:
        """One last durable emit with the incomplete marker set — the
        SIGTERM/atexit path."""
        if self.finalized:
            return
        self.out["incomplete"] = True
        if reason:
            self.out["interrupted_by"] = reason
        self.emit()

    def _install_traps(self) -> None:
        atexit.register(self._atexit_flush)
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, self._on_signal)
            except (ValueError, OSError):
                pass  # non-main thread / restricted environment

    def _atexit_flush(self) -> None:
        if not self.finalized:
            self.flush_incomplete("exit")

    def _on_signal(self, signum, frame) -> None:
        self.flush_incomplete(signal.Signals(signum).name)
        self.finalized = True        # the atexit hook need not re-flush
        sys.stdout.flush()
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)  # preserve the caller-visible rc
