"""Crash-proof incremental benchmark records.

The round-5 bench regression (`BENCH_r05.json: rc 124, parsed: null`)
happened because bench.py printed its JSON summary only as the very last
line — a driver timeout voided the whole record. `BenchRecorder` makes
that impossible:

- the cumulative record is RE-EMITTED to stdout after every completed
  stage (the driver's "last JSON line wins" parse stays valid at any
  kill point);
- every flush also atomically rewrites a sidecar file (tmp + rename), so
  partial results survive even a SIGKILL between stages;
- SIGTERM/SIGINT traps and an atexit hook flush one final time with
  ``incomplete: true`` plus the stage reached — `timeout -k` sends
  SIGTERM first, which gives the trap a window before the follow-up
  SIGKILL;
- ``finalize()`` clears the incomplete marker and writes the same schema
  as before (the new keys are additive, so BENCH_r01–r05 parsers keep
  working).
"""
from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import time
from typing import Any, Dict, Optional, Tuple


class BudgetGate:
    """Adaptive wall-budget gating for staged benchmark runs.

    The r05 failure mode: per-stage gating existed but only checked
    "budget not yet exhausted" — a stage could START with 10 s left,
    need 500 s, and the driver's `timeout -k` killed the whole run at
    rc=124. The gate closes that hole two ways:

    * `allow(stage, est_s)` consults the REMAINING budget against an
      estimate of the stage's cost (callers derive estimates from the
      measured walls of earlier stages, via `wall("name")`), skipping a
      stage that cannot finish instead of starting it;
    * `scale_iters(base, per_iter_s)` shrinks a stage's iteration count
      to what fits the remaining budget, so expensive stages degrade to
      smaller measurements rather than disappearing.

    A `reserve` slice of the budget (default 5%, capped 45 s) is held
    back for finalize/flush so the complete record always lands before
    the driver's SIGKILL. With no budget (total_s <= 0) every query
    returns "unbounded" and nothing is ever skipped or shrunk.
    """

    def __init__(self, total_s: float, reserve_frac: float = 0.05,
                 reserve_max_s: float = 45.0, clock=time.perf_counter,
                 t0: Optional[float] = None) -> None:
        self.total = max(float(total_s or 0.0), 0.0)
        self.clock = clock
        self.t0 = clock() if t0 is None else t0
        self.reserve = min(self.total * reserve_frac, reserve_max_s)
        self.stage_wall: Dict[str, float] = {}
        self._stage_t0: Dict[str, float] = {}

    # -- accounting --------------------------------------------------------
    def elapsed(self) -> float:
        return self.clock() - self.t0

    def left(self) -> Optional[float]:
        """Usable seconds remaining (reserve already held back), or None
        when unbudgeted."""
        if self.total <= 0:
            return None
        return self.total - self.reserve - self.elapsed()

    def start(self, stage: str) -> None:
        self._stage_t0[stage] = self.clock()

    def done(self, stage: str) -> float:
        dt = self.clock() - self._stage_t0.pop(stage, self.clock())
        self.stage_wall[stage] = round(dt, 2)
        return dt

    def wall(self, stage: str, default: float = 0.0) -> float:
        """Measured wall of a completed stage (the raw material for
        estimating later stages)."""
        return self.stage_wall.get(stage, default)

    # -- decisions ---------------------------------------------------------
    def allow(self, stage: str, est_s: float = 0.0
              ) -> Tuple[bool, Optional[str]]:
        """(run?, skip_reason). Skips when the budget is exhausted OR the
        estimated stage cost no longer fits what remains."""
        left = self.left()
        if left is None:
            return True, None
        if left <= 0:
            return False, (f"budget exhausted "
                           f"({self.elapsed():.0f}s elapsed of "
                           f"{self.total:.0f}s)")
        if est_s > 0 and est_s > left:
            return False, (f"adaptive skip: stage needs ~{est_s:.0f}s, "
                           f"{left:.0f}s left of {self.total:.0f}s budget")
        return True, None

    def scale_iters(self, base_iters: int, per_iter_s: float,
                    overhead_s: float = 0.0, floor: int = 1,
                    frac: float = 0.5) -> int:
        """Largest iteration count <= base that fits `frac` of the
        remaining budget after `overhead_s` fixed cost (never below
        `floor` — the stage runs small rather than lying with a zero
        measurement; pair with `allow` to skip entirely)."""
        left = self.left()
        if left is None or per_iter_s <= 0:
            return base_iters
        usable = max(left * frac - overhead_s, 0.0)
        fit = int(usable // per_iter_s)
        return max(min(base_iters, fit), min(floor, base_iters))


class BenchRecorder:
    """Owns the cumulative bench `out` dict and its durability."""

    def __init__(self, out: Dict[str, Any], path: Optional[str] = None,
                 install_traps: bool = True,
                 gate: Optional[BudgetGate] = None) -> None:
        self.out = out
        self.path = path
        self.finalized = False
        # the gate shares the run's t0 and owns the per-stage walls, so
        # the START emit can say how deep into the run the kill landed
        self.gate = gate
        self.t0 = gate.t0 if gate is not None else time.perf_counter()
        out.setdefault("incomplete", True)
        out.setdefault("stage_reached", None)
        out.setdefault("stages_done", [])
        if install_traps:
            self._install_traps()

    # -- stage protocol ----------------------------------------------------
    def start_stage(self, name: str) -> None:
        self.out["stage_reached"] = name
        # full emit (stdout + sidecar) at stage START, not only at stage
        # end: a run SIGKILLed mid-stage — including during a long
        # C-level XLA compile, where Python signal traps never run —
        # still has a parseable cumulative record as its last stdout
        # line (plus the stage name on disk). elapsed_s + the cumulative
        # stage walls turn that record into "killed N s in, inside
        # <stage>, after these completed stages cost this much" without
        # any stderr scraping (tools/bottleneck_report.py reads both).
        self.out["elapsed_s"] = round(time.perf_counter() - self.t0, 1)
        if self.gate is not None and self.gate.stage_wall:
            self.out["stage_wall_s"] = dict(self.gate.stage_wall)
        self.emit()

    def stage_done(self, name: str) -> None:
        if name not in self.out["stages_done"]:
            self.out["stages_done"].append(name)
        self.emit()

    # -- durability --------------------------------------------------------
    def emit(self) -> None:
        """Print the cumulative record as one stdout JSON line AND flush
        the sidecar file. Call after every stage (and on any skip that
        mutates the record) — the last line printed is always complete."""
        print(json.dumps(self.out, default=str), flush=True)
        self.flush_file()

    def flush_file(self) -> None:
        """Atomic tmp+rename rewrite of the sidecar (no-op without a
        path). A reader never observes a torn file."""
        if not self.path:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json.dump(self.out, fh, default=str)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def finalize(self) -> Dict[str, Any]:
        """Mark the run complete and emit the final record."""
        self.finalized = True
        self.out["incomplete"] = False
        self.emit()
        return self.out

    # -- interruption ------------------------------------------------------
    def flush_incomplete(self, reason: Optional[str] = None) -> None:
        """One last durable emit with the incomplete marker set — the
        SIGTERM/atexit path."""
        if self.finalized:
            return
        self.out["incomplete"] = True
        if reason:
            self.out["interrupted_by"] = reason
        self.emit()

    def _install_traps(self) -> None:
        atexit.register(self._atexit_flush)
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, self._on_signal)
            except (ValueError, OSError):
                pass  # non-main thread / restricted environment

    def _atexit_flush(self) -> None:
        if not self.finalized:
            self.flush_incomplete("exit")

    def _on_signal(self, signum, frame) -> None:
        self.flush_incomplete(signal.Signals(signum).name)
        self.finalized = True        # the atexit hook need not re-flush
        sys.stdout.flush()
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)  # preserve the caller-visible rc
