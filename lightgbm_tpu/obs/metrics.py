"""Process-wide metrics plane: counters, gauges, and latency histograms
behind one thread-safe registry, scrapeable while the process runs.

The one-shot artifacts (span JSONL, round ledger, bench records) answer
"what happened"; this module answers "what is happening" — the serving
exporter (`serving/exporter.py`) renders the same registry as Prometheus
text on every scrape, `bst.metrics_snapshot()` returns it as a dict, and
`bench.py` folds per-stage snapshots into the bench JSON.

Design constraints (same discipline as `obs/trace.py`):

- Disabled cost is NIL on the hot paths. Instruments are plain Python
  ints/floats behind a lock — no jax import, no device fences — and the
  GBDT round loop / serving flusher hold a pre-resolved handle that is
  ``None`` when off, so the per-round cost of the default path is one
  attribute check.
- Histograms use fixed log2 bucket bounds in milliseconds
  (2^-6 .. 2^14 ms), so p50/p99 estimates come from bucket
  interpolation with no per-observation allocation.
- ``snapshot()`` emits a versioned schema (``SCHEMA_VERSION``) so the
  CI scrape and bench_compare can validate shape, not just presence.

Labeled families: ``registry().counter(name, help, labelnames=("model",))``
returns a family whose ``labels(model="ctr")`` child is created on first
use and cached — label cardinality is the caller's responsibility.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..utils import locks

__all__ = ["SCHEMA_VERSION", "Counter", "Gauge", "Histogram",
           "MetricsRegistry", "registry", "enable", "disable", "enabled",
           "reset", "snapshot", "to_prometheus", "train_instruments",
           "serving_instruments", "note_retry_event"]

SCHEMA_VERSION = 1

# log2 latency bucket upper bounds in milliseconds: 0.015625 ms .. 16.4 s,
# plus +Inf. Fixed (not configurable) so histograms from any two
# processes/stages merge bucket-for-bucket.
BUCKET_BOUNDS_MS: Tuple[float, ...] = tuple(2.0 ** e for e in range(-6, 15))

_enabled = False
_lock = threading.Lock()


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Turn the metrics plane on (idempotent). Instrument handles held
    by hot paths are resolved at construction time (GBDT.__init__,
    ServingService.__init__), so enable BEFORE building the object that
    should feed the registry."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def _label_key(labelnames: Sequence[str],
               labels: Dict[str, str]) -> Tuple[str, ...]:
    if sorted(labels) != sorted(labelnames):
        raise ValueError(f"labels {sorted(labels)} != declared "
                         f"{sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


def _fmt_labels(labelnames: Sequence[str], key: Tuple[str, ...]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(f'{n}="{v}"' for n, v in zip(labelnames, key))
    return "{" + inner + "}"


@locks.guarded
class Counter:
    """Monotone float counter. `inc` only — a decrement is a bug."""

    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._value = 0.0                           # guarded-by: _lock
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


@locks.guarded
class Gauge:
    """Point-in-time value; optionally backed by a callback (`set_fn`)
    read at snapshot/scrape time — how the HBM accountant exposes live
    occupancy without a sampling thread."""

    __slots__ = ("name", "help", "_value", "_fn", "_lock")

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._value = 0.0                           # guarded-by: _lock
        self._fn: Optional[Callable[[], float]] = None  # guarded-by: _lock
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            self._fn = None

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def set_fn(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return float("nan")
        return self._value


@locks.guarded
class Histogram:
    """Fixed log2-bucket latency histogram (milliseconds).

    `observe(ms)` is one bisect + two adds under a lock; `quantile(q)`
    interpolates linearly inside the covering bucket (the standard
    Prometheus `histogram_quantile` estimate), so p50/p99 are available
    host-side without retaining observations.

    Exemplars: `observe(ms, exemplar="r...-...")` stamps the bucket the
    observation lands in with that trace ID (last write wins per
    bucket), so "what is p99" comes with "here is a request AT p99" —
    the join key into the request-trace ring/JSONL (obs/reqtrace.py).
    Cost without an exemplar is one extra is-None check.
    """

    __slots__ = ("name", "help", "bounds", "_counts", "_sum", "_count",
                 "_exemplars", "_lock")

    def __init__(self, name: str, help: str = "",
                 bounds: Sequence[float] = BUCKET_BOUNDS_MS) -> None:
        self.name = name
        self.help = help
        self.bounds = tuple(float(b) for b in bounds)
        # last slot = +Inf
        self._counts = [0] * (len(self.bounds) + 1)  # guarded-by: _lock
        self._sum = 0.0                               # guarded-by: _lock
        self._count = 0                               # guarded-by: _lock
        # bucket index -> (exemplar_id, value_ms), last write wins
        self._exemplars: Dict[int, Tuple[str, float]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def observe(self, ms: float, exemplar: Optional[str] = None) -> None:
        ms = float(ms)
        import bisect
        i = bisect.bisect_left(self.bounds, ms)
        with self._lock:
            self._counts[i] += 1
            self._sum += ms
            self._count += 1
            if exemplar is not None:
                self._exemplars[i] = (str(exemplar), ms)

    def _le_key(self, i: int) -> str:
        """JSON bucket key for bucket index `i` — same convention as
        snapshot()'s cumulative-bucket keys."""
        return "+Inf" if i >= len(self.bounds) else repr(self.bounds[i])

    def exemplars(self) -> Dict[str, Dict[str, Any]]:
        """{le_key: {trace_id, value_ms}} for buckets with an exemplar."""
        with self._lock:
            items = sorted(self._exemplars.items())
        return {self._le_key(i): {"trace_id": tid, "value_ms": round(v, 4)}
                for i, (tid, v) in items}

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le_bound, cumulative_count)] including (+Inf, total)."""
        out, acc = [], 0
        with self._lock:
            counts = list(self._counts)
        for b, c in zip(self.bounds, counts):
            acc += c
            out.append((b, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile in ms; None with no observations. The
        +Inf bucket clamps to the largest finite bound."""
        cum = self.cumulative()
        total = cum[-1][1]
        if total == 0:
            return None
        target = q * total
        lo = 0.0
        prev_cum = 0
        for b, c in cum:
            if c >= target:
                if b == float("inf"):
                    return self.bounds[-1]
                span = c - prev_cum
                frac = (target - prev_cum) / span if span else 1.0
                return lo + (b - lo) * frac
            lo, prev_cum = b, c
        return self.bounds[-1]


@locks.guarded
class _Family:
    """Labeled instrument family: children cached per label-value tuple."""

    __slots__ = ("name", "help", "labelnames", "_cls", "_children", "_lock")

    def __init__(self, cls, name: str, help: str,
                 labelnames: Sequence[str]) -> None:
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._cls = cls
        self._children: Dict[Tuple[str, ...], Any] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def labels(self, **labels) -> Any:
        key = _label_key(self.labelnames, labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._cls(self.name, self.help)
                    self._children[key] = child
        return child

    def children(self) -> Dict[Tuple[str, ...], Any]:
        with self._lock:
            return dict(self._children)


_KIND = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}


@locks.guarded
class MetricsRegistry:
    """Ordered name -> instrument/family map with get-or-create semantics
    (re-declaring the same name with the same type returns the existing
    instrument; a type change raises)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, Any] = {}          # guarded-by: _lock

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str]):
        with self._lock:
            ent = self._entries.get(name)
            if ent is not None:
                want = cls if not labelnames else _Family
                got_cls = ent._cls if isinstance(ent, _Family) else type(ent)
                if got_cls is not cls or isinstance(ent, _Family) != bool(
                        labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{_KIND.get(got_cls, got_cls)}"
                        f"{' family' if isinstance(ent, _Family) else ''}, "
                        f"not {want}")
                return ent
            ent = (_Family(cls, name, help, labelnames) if labelnames
                   else cls(name, help))
            self._entries[name] = ent
            return ent

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Any:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Any:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = ()) -> Any:
        return self._get_or_create(Histogram, name, help, labelnames)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    # -- export ------------------------------------------------------------
    def _items(self) -> List[Tuple[str, Any]]:
        with self._lock:
            return list(self._entries.items())

    @staticmethod
    def _each(ent) -> List[Tuple[str, Any]]:
        """(label_suffix, instrument) pairs for one entry."""
        if isinstance(ent, _Family):
            return [(_fmt_labels(ent.labelnames, key), child)
                    for key, child in sorted(ent.children().items())]
        return [("", ent)]

    def snapshot(self) -> Dict[str, Any]:
        """Versioned dict of everything: counters/gauges as scalars,
        histograms as {count, sum_ms, p50_ms, p99_ms, buckets} with
        cumulative bucket counts keyed by the le bound."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, Any] = {}
        for name, ent in self._items():
            for suffix, inst in self._each(ent):
                key = name + suffix
                if isinstance(inst, Counter):
                    counters[key] = inst.value
                elif isinstance(inst, Gauge):
                    gauges[key] = inst.value
                else:
                    hists[key] = {
                        "count": inst.count,
                        "sum_ms": round(inst.sum, 4),
                        "p50_ms": inst.quantile(0.50),
                        "p99_ms": inst.quantile(0.99),
                        "buckets": {("+Inf" if b == float("inf")
                                     else repr(b)): c
                                    for b, c in inst.cumulative()},
                    }
                    ex = inst.exemplars()
                    if ex:
                        hists[key]["exemplars"] = ex
        return {"schema": SCHEMA_VERSION, "counters": counters,
                "gauges": gauges, "histograms": hists}

    def to_prometheus(self) -> str:
        """Prometheus text exposition format v0.0.4. Histograms emit the
        standard _bucket/_sum/_count series plus _p50/_p99 gauges
        (bucket-interpolated) so a plain curl shows tail latency without
        a query engine."""
        lines: List[str] = []
        for name, ent in self._items():
            kind = _KIND[ent._cls if isinstance(ent, _Family)
                         else type(ent)]
            help_ = ent.help
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            for suffix, inst in self._each(ent):
                if isinstance(inst, (Counter, Gauge)):
                    v = inst.value
                    lines.append(f"{name}{suffix} {v:g}")
                    continue
                base = suffix[1:-1] if suffix else ""
                ex = inst.exemplars()
                for i, (b, c) in enumerate(inst.cumulative()):
                    le = "+Inf" if b == float("inf") else f"{b:g}"
                    joined = ",".join(x for x in (base, f'le="{le}"') if x)
                    line = f"{name}_bucket{{{joined}}} {c}"
                    # OpenMetrics exemplar suffix, appended ONLY to
                    # _bucket lines (non-bucket series stay parseable
                    # as `last token is the value`)
                    e = ex.get(inst._le_key(i))
                    if e is not None:
                        line += (f' # {{trace_id="{e["trace_id"]}"}} '
                                 f'{e["value_ms"]:g}')
                    lines.append(line)
                lines.append(f"{name}_sum{suffix} {inst.sum:g}")
                lines.append(f"{name}_count{suffix} {inst.count}")
                for q, tag in ((0.50, "p50"), (0.99, "p99")):
                    v = inst.quantile(q)
                    if v is not None:
                        lines.append(f"{name}_{tag}{suffix} {v:g}")
        return "\n".join(lines) + "\n"


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def reset() -> None:
    """Drop every instrument and disable (tests)."""
    global _enabled
    _REGISTRY.clear()
    _enabled = False


def snapshot() -> Dict[str, Any]:
    return _REGISTRY.snapshot()


def to_prometheus() -> str:
    return _REGISTRY.to_prometheus()


# -- instrument catalogues --------------------------------------------------
# Hot paths hold one of these namespaces (resolved once at object
# construction) instead of re-looking instruments up per round/request.

class _Namespace:
    pass


def train_instruments() -> Any:
    """The training round loop's instrument bundle (models/gbdt.py holds
    one when `tpu_metrics` is on; resilience/retry.py bumps the retry
    family through `note_retry_event`)."""
    r = _REGISTRY
    ns = _Namespace()
    ns.rounds = r.counter(
        "train_rounds_total", "boosting rounds completed")
    ns.trees = r.counter(
        "train_trees_total", "trees appended to the ensemble")
    ns.retraces = r.counter(
        "train_retraces_total",
        "new XLA traces observed by compile_cache.note_trace")
    ns.fallbacks = r.counter(
        "train_aligned_fallbacks_total",
        "aligned-engine exact-replay fallbacks")
    ns.round_ms = r.histogram(
        "train_round_ms", "host wall time per boosting round (ms)")
    ns.retry_events = r.counter(
        "train_retry_events_total",
        "resilience retry events by outcome",
        labelnames=("event",))
    ns.term_ms = r.gauge(
        "train_term_ms",
        "per-term fenced device ms of the last profiler-sampled round "
        "(obs/profiler.py; term names from obs/terms.py)",
        labelnames=("term",))
    return ns


def serving_instruments() -> Any:
    """The serving plane's instrument bundle (coalescer + registry hold
    one when the metrics plane is enabled)."""
    r = _REGISTRY
    ns = _Namespace()
    ns.requests = r.counter(
        "serve_requests_total", "predict requests submitted")
    ns.batches = r.counter(
        "serve_batches_total", "coalesced engine dispatches by trigger",
        labelnames=("reason",))
    ns.rows = r.counter(
        "serve_rows_total", "real rows dispatched to engines")
    ns.padded_rows = r.counter(
        "serve_padded_rows_total",
        "padded bucket rows dispatched (>= serve_rows_total)")
    ns.failures = r.counter(
        "serve_failures_total", "requests completed with an exception")
    ns.fill = r.gauge(
        "serve_batch_fill_ratio",
        "lifetime real-rows / padded-rows of engine dispatches")
    ns.latency = r.histogram(
        "serve_request_latency_ms",
        "submit-to-result latency per request (ms)",
        labelnames=("model",))
    ns.completed = r.counter(
        "serve_requests_completed_total",
        "requests completed by outcome — ok + error sums to "
        "serve_requests_total once the queue drains",
        labelnames=("model", "status"))
    ns.slo_breaches = r.counter(
        "serve_slo_breaches_total",
        "requests whose total latency breached tpu_serve_slo_ms",
        labelnames=("model",))
    ns.slo_burn = r.gauge(
        "serve_slo_burn_rate",
        "rolling fraction of SLO-breaching/errored requests over the "
        "last 256 outcomes (obs/reqtrace.py burn window)",
        labelnames=("model",))
    ns.loads = r.counter(
        "serve_model_loads_total", "registry model loads")
    ns.swaps = r.counter(
        "serve_model_swaps_total", "registry hot swaps")
    ns.evictions = r.counter(
        "serve_model_evictions_total", "registry LRU evictions")
    ns.early_stop = r.counter(
        "serve_early_stop_total",
        "prediction chunks that exited before scoring every tree "
        "(pred_early_stop on the batched engine path)")
    # network front door (serving/frontend/): admission, shedding,
    # placement. Same zero-overhead-off discipline — the bundle is
    # resolved once at construction, None when the plane is off.
    ns.http_requests = r.counter(
        "serve_http_requests_total",
        "front-door HTTP requests by response code",
        labelnames=("code",))
    ns.shed = r.counter(
        "serve_shed_total",
        "front-door requests load-shed with a 429 while the model's "
        "SLO burn rate was above the shed watermark",
        labelnames=("model", "qos"))
    ns.deadline_expired = r.counter(
        "serve_deadline_expired_total",
        "front-door requests that expired their X-Deadline-Ms budget "
        "in the admission queue (answered without dispatch)",
        labelnames=("model",))
    ns.admit_depth = r.gauge(
        "serve_admit_queue_depth",
        "front-door admission queue depth (requests waiting) per QoS "
        "class",
        labelnames=("qos",))
    ns.device_queue = r.gauge(
        "serve_device_queue_rows",
        "rows in flight toward each device's replicas (the placer's "
        "shallowest-queue routing signal)",
        labelnames=("device",))
    ns.replicas = r.gauge(
        "serve_model_replicas",
        "device replicas resident per model (placer hot-model "
        "replication)",
        labelnames=("model",))
    return ns


def note_early_stop() -> None:
    """One chunk exited the forest early (`ForestEngine` pred_early_stop).
    No-op when the metrics plane is off — the engine calls this
    unconditionally because exits are bounded by chunk count."""
    if not _enabled:
        return
    _REGISTRY.counter(
        "serve_early_stop_total",
        "prediction chunks that exited before scoring every tree "
        "(pred_early_stop on the batched engine path)").inc()


def note_retry_event(event: str) -> None:
    """One resilience retry event ('retry' / 'recovered' / 'exhausted').
    No-op when the metrics plane is off — retry sites call this
    unconditionally because the events are rare by construction."""
    if not _enabled:
        return
    _REGISTRY.counter("train_retry_events_total",
                      "resilience retry events by outcome",
                      labelnames=("event",)).labels(event=event).inc()
