"""Straggler + anomaly watches: pure-host detectors over walls the run
already measured. Zero fences by construction — every input is a float
some existing fence or ``perf_counter`` delta produced.

Two detectors:

``ImbalanceWatch`` — sustained cross-actor imbalance with hysteresis.
Fed the max/median ratio of per-device round times (profiled
distributed rounds) or per-sub-fleet round walls (the batched sweep).
``update(ratio)`` returns ``"raised"`` exactly once after K
consecutive samples at/above the threshold, ``"cleared"`` exactly once
after K consecutive samples at/below the clear ratio, and ``None``
otherwise — edge-triggered, so the ledger/event stream carries state
TRANSITIONS, not one line per sampled round.

``AnomalyWatch`` — rolling-median round-wall deviation. Fed every
traced round's ``wall_ms``; fires when a wall exceeds ``factor`` x the
trailing-window median. Anomalous walls are NOT folded into the window
(a burst must not drag the median up to meet itself), and consecutive
anomalies fire once (edge-triggered) — a run drifting into trouble
says so near the FIRST bad round, while its bench budget still has
room to react.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["AnomalyWatch", "ImbalanceWatch", "imbalance_ratio"]


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def imbalance_ratio(walls: Sequence[float]) -> Optional[float]:
    """max/median over per-actor round times; None when fewer than two
    actors reported or the median is degenerate (all-idle sample)."""
    vals = [float(w) for w in walls
            if isinstance(w, (int, float)) and w >= 0]
    if len(vals) < 2:
        return None
    med = _median(vals)
    if med <= 0:
        return None
    return max(vals) / med


class ImbalanceWatch:
    """Edge-triggered sustained-imbalance detector with hysteresis."""

    def __init__(self, threshold: float = 1.5, rounds: int = 3,
                 clear_ratio: Optional[float] = None) -> None:
        self.threshold = max(float(threshold), 1.0)
        self.rounds = max(int(rounds), 1)
        # default clear level: halfway back from the threshold to 1.0,
        # so a ratio oscillating AT the threshold cannot flap
        self.clear = (float(clear_ratio) if clear_ratio is not None
                      else 1.0 + (self.threshold - 1.0) * 0.5)
        self.raised = False
        self.last: Optional[float] = None
        self._high = 0
        self._low = 0

    def update(self, ratio: Optional[float]) -> Optional[str]:
        """Fold one sampled ratio; "raised"/"cleared" on a state
        transition, else None. A None ratio (degenerate sample) leaves
        the counters untouched."""
        if ratio is None:
            return None
        self.last = float(ratio)
        if not self.raised:
            self._high = self._high + 1 if ratio >= self.threshold else 0
            if self._high >= self.rounds:
                self.raised = True
                self._high = 0
                self._low = 0
                return "raised"
        else:
            self._low = self._low + 1 if ratio <= self.clear else 0
            if self._low >= self.rounds:
                self.raised = False
                self._high = 0
                self._low = 0
                return "cleared"
        return None


class AnomalyWatch:
    """Rolling-median round-wall anomaly detector (edge-triggered)."""

    def __init__(self, factor: float = 3.0, window: int = 32,
                 min_rounds: Optional[int] = None) -> None:
        self.factor = max(float(factor), 0.0)
        self.window = max(int(window), 2)
        # arm only once the window holds enough normal rounds for the
        # median to mean something
        self.min_rounds = (int(min_rounds) if min_rounds is not None
                           else max(self.window // 4, 3))
        self._walls: deque = deque(maxlen=self.window)
        self._in_anomaly = False
        self.fired: List[Dict[str, Any]] = []

    def update(self, wall_ms: float) -> Optional[Dict[str, float]]:
        """Fold one round wall. Returns ``{"ratio", "median_ms"}`` when
        this wall opens an anomaly (previous round was normal and this
        one deviates > factor x trailing median); None otherwise.
        Anomalous walls never enter the trailing window."""
        wall = float(wall_ms)
        if wall < 0:
            return None
        if len(self._walls) >= self.min_rounds and self.factor > 0:
            med = _median(self._walls)
            if med > 0 and wall > self.factor * med:
                was = self._in_anomaly
                self._in_anomaly = True
                if was:
                    return None          # still inside the same burst
                hit = {"ratio": round(wall / med, 3),
                       "median_ms": round(med, 3)}
                self.fired.append(hit)
                return hit
        self._in_anomaly = False
        self._walls.append(wall)
        return None
