"""Vectorized best-split search over per-feature histograms.

Re-creates the reference `FeatureHistogram` split-gain machinery
(`src/treelearner/feature_histogram.hpp:91-644`) as one XLA program over all
features at once: the two sequential threshold scans (dir=-1 / dir=+1 with
missing-value routing) become masked prefix/suffix sums over the bin axis,
and the scan's `continue`/`break` guards become validity masks (they are
monotone along the scan, so masking is exactly equivalent).

Semantics carried over exactly:
- threshold t means "bin <= t goes left"; `default_left` = (chosen dir == -1)
  (`feature_histogram.hpp:560-561,642`)
- missing Zero: the default bin is excluded from the accumulating side and
  from the candidate set (`:529,:587` — note the skipped *candidate* is
  threshold `default_bin-1` in dir=-1 and `default_bin` in dir=+1)
- missing NaN: the last bin (NaN bin) is excluded from the dir=-1 accumulation
  range so NaN rows ride with the leaf-total remainder (`:523,571-583`)
- two scans only when num_bin > 2 and missing != None; otherwise a single
  dir=-1 scan, with default_left forced False for NaN (`:97-111`)
- kEpsilon hessian seeding: parent hessian + 2e-15, each side + 1e-15
  (`:87,520,567`)
- L1-thresholded leaf outputs, max_delta_step clamp, monotone-constraint veto
  with constraint-clamped outputs (`:446-506`)
- categorical one-hot and CTR-sorted subset scans with cat_smooth / cat_l2 /
  max_cat_threshold / min_data_per_group (`:118-258`)
"""
from __future__ import annotations

import functools
from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

K_EPSILON = 1e-15
NEG_INF = -jnp.inf
# device category bitsets are 8 u32 words; categorical split candidates are
# limited to the first (most frequent) 256 category bins
CAT_BITSET_BINS = 256


class SplitHyper(NamedTuple):
    """Static split hyper-parameters (subset of Config used by the finder)."""
    lambda_l1: float
    lambda_l2: float
    max_delta_step: float
    min_data_in_leaf: int
    min_sum_hessian_in_leaf: float
    min_gain_to_split: float
    cat_smooth: float
    cat_l2: float
    max_cat_threshold: int
    max_cat_to_onehot: int
    min_data_per_group: int
    # lambda_l2 + cat_l2, precomputed in double so the sorted-categorical
    # path sees the same rounding whether the lambdas are static floats or
    # per-model traced scalars (sweep mode threads all three as operands).
    lambda_l2_cat: float = 0.0

    @classmethod
    def from_config(cls, cfg) -> "SplitHyper":
        return cls(
            lambda_l1=float(cfg.lambda_l1),
            lambda_l2=float(cfg.lambda_l2),
            max_delta_step=float(cfg.max_delta_step),
            min_data_in_leaf=int(cfg.min_data_in_leaf),
            min_sum_hessian_in_leaf=float(cfg.min_sum_hessian_in_leaf),
            min_gain_to_split=float(cfg.min_gain_to_split),
            cat_smooth=float(cfg.cat_smooth),
            cat_l2=float(cfg.cat_l2),
            max_cat_threshold=int(cfg.max_cat_threshold),
            max_cat_to_onehot=int(cfg.max_cat_to_onehot),
            min_data_per_group=int(cfg.min_data_per_group),
            lambda_l2_cat=float(cfg.lambda_l2) + float(cfg.cat_l2),
        )


def _threshold_l1(s, l1):
    """reference ThresholdL1 (feature_histogram.hpp:446)."""
    reg = jnp.maximum(jnp.abs(s) - l1, 0.0)
    return jnp.sign(s) * reg


def threshold_l1_host(s: "np.ndarray", l1: float):
    """NumPy twin of `_threshold_l1` for host-side paths (refit)."""
    import numpy as np
    return np.sign(s) * np.maximum(np.abs(s) - l1, 0.0)


def _leaf_output(sg, sh, l1, l2, mds):
    """reference CalculateSplittedLeafOutput (feature_histogram.hpp:451)."""
    ret = -_threshold_l1(sg, l1) / (sh + l2)
    if mds > 0.0:
        ret = jnp.clip(ret, -mds, mds)
    return ret


def _leaf_gain_given_output(sg, sh, l1, l2, out):
    """reference GetLeafSplitGainGivenOutput (feature_histogram.hpp:503)."""
    reg = _threshold_l1(sg, l1)
    return -(2.0 * reg * out + (sh + l2) * out * out)


def _leaf_gain(sg, sh, l1, l2, mds):
    out = _leaf_output(sg, sh, l1, l2, mds)
    return _leaf_gain_given_output(sg, sh, l1, l2, out)


def _split_gains(lg, lh, rg, rh, l1, l2, mds, min_c, max_c, mono):
    """reference GetSplitGains (feature_histogram.hpp:461-473): clamped
    outputs, monotone veto -> gain 0."""
    lo = jnp.clip(_leaf_output(lg, lh, l1, l2, mds), min_c, max_c)
    ro = jnp.clip(_leaf_output(rg, rh, l1, l2, mds), min_c, max_c)
    gain = (_leaf_gain_given_output(lg, lh, l1, l2, lo)
            + _leaf_gain_given_output(rg, rh, l1, l2, ro))
    veto = ((mono > 0) & (lo > ro)) | ((mono < 0) & (lo < ro))
    return jnp.where(veto, 0.0, gain)


def _first_argmax(values, axis=-1):
    """argmax returning the first occurrence (ties -> lowest index)."""
    return jnp.argmax(values, axis=axis)


def _last_argmax(values, axis=-1):
    """argmax returning the last occurrence (ties -> highest index)."""
    b = values.shape[axis]
    rev = jnp.flip(values, axis=axis)
    return b - 1 - jnp.argmax(rev, axis=axis)


def make_split_finder(hyper: SplitHyper, feature_meta: Dict[str, np.ndarray],
                      max_bin: int):
    """Build the jitted split finder for a fixed dataset + config.

    feature_meta arrays (length F): num_bin, default_bin, missing_type
    (0 none / 1 zero / 2 nan), bin_type (0 numerical / 1 categorical),
    monotone, penalty.

    Returns fn(hist[F,B,3], sum_grad, sum_hess, num_data, min_constr,
    max_constr) -> dict of per-feature arrays + 'best_feature'.
    """
    nb = jnp.asarray(feature_meta["num_bin"], jnp.int32)[:, None]     # [F,1]
    db = jnp.asarray(feature_meta["default_bin"], jnp.int32)[:, None]
    mt = jnp.asarray(feature_meta["missing_type"], jnp.int32)[:, None]
    bt = jnp.asarray(feature_meta["bin_type"], jnp.int32)[:, None]
    mono = jnp.asarray(feature_meta["monotone"], jnp.int32)
    penalty = jnp.asarray(feature_meta["penalty"], jnp.float32)
    F = int(nb.shape[0])
    has_cat = bool((feature_meta["bin_type"] == 1).any())
    h = hyper

    bins = jnp.arange(max_bin, dtype=jnp.int32)[None, :]              # [1,B]
    in_range = bins < nb

    # effective flags (reference feature_histogram.hpp:97-111)
    two_scan = (nb > 2) & (mt != 0)
    skip_def = (mt == 1) & two_scan
    use_na = (mt == 2) & two_scan
    is_cat = bt == 1

    min_data_f = float(h.min_data_in_leaf)
    min_hess = float(h.min_sum_hessian_in_leaf)

    def _numerical(hist, sum_grad, sum_hess, num_data_f, min_c, max_c,
                   min_gain_shift):
        g = hist[..., 0]
        hs = hist[..., 1]
        c = hist[..., 2]

        # ---- dir = +1: accumulate from the left; missing/default -> right
        inc1 = in_range & ~(skip_def & (bins == db))
        pg = jnp.cumsum(jnp.where(inc1, g, 0.0), axis=1)
        ph = jnp.cumsum(jnp.where(inc1, hs, 0.0), axis=1)
        pc = jnp.cumsum(jnp.where(inc1, c, 0.0), axis=1)
        lg1, lh1, lc1 = pg, ph + K_EPSILON, pc
        rg1 = sum_grad - lg1
        rh1 = sum_hess - lh1          # sum_hess already carries +2*kEps
        rc1 = num_data_f - lc1
        valid1 = (two_scan & (bins <= nb - 2) & ~(skip_def & (bins == db))
                  & (lc1 >= min_data_f) & (rc1 >= min_data_f)
                  & (lh1 >= min_hess) & (rh1 >= min_hess))
        gain1 = _split_gains(lg1, lh1, rg1, rh1, h.lambda_l1, h.lambda_l2,
                             h.max_delta_step, min_c, max_c, mono[:, None])
        gain1 = jnp.where(valid1 & (gain1 > min_gain_shift), gain1, NEG_INF)

        # ---- dir = -1: accumulate from the right; missing/default -> left
        # NaN bin (last) excluded from the accumulation range; candidate
        # threshold default_bin-1 is skipped under missing-Zero
        inc2 = (in_range & ~(skip_def & (bins == db))
                & (bins <= nb - 1 - use_na.astype(jnp.int32)))
        pg2 = jnp.cumsum(jnp.where(inc2, g, 0.0), axis=1)
        ph2 = jnp.cumsum(jnp.where(inc2, hs, 0.0), axis=1)
        pc2 = jnp.cumsum(jnp.where(inc2, c, 0.0), axis=1)
        tg2, th2, tc2 = pg2[:, -1:], ph2[:, -1:], pc2[:, -1:]
        rg2 = tg2 - pg2
        rh2 = (th2 - ph2) + K_EPSILON
        rc2 = tc2 - pc2
        lg2 = sum_grad - rg2
        lh2 = sum_hess - rh2
        lc2 = num_data_f - rc2
        valid2 = ((bins <= nb - 2 - use_na.astype(jnp.int32))
                  & ~(skip_def & (bins + 1 == db))
                  & (rc2 >= min_data_f) & (lc2 >= min_data_f)
                  & (rh2 >= min_hess) & (lh2 >= min_hess))
        gain2 = _split_gains(lg2, lh2, rg2, rh2, h.lambda_l1, h.lambda_l2,
                             h.max_delta_step, min_c, max_c, mono[:, None])
        gain2 = jnp.where(valid2 & (gain2 > min_gain_shift), gain2, NEG_INF)

        # ---- per-direction winners with the reference tie-break order
        t1 = _first_argmax(gain1, axis=1)       # dir=+1 scans low->high
        t2 = _last_argmax(gain2, axis=1)        # dir=-1 scans high->low
        g1b = jnp.take_along_axis(gain1, t1[:, None], 1)[:, 0]
        g2b = jnp.take_along_axis(gain2, t2[:, None], 1)[:, 0]
        use1 = g1b > g2b                        # dir=-1 first, strict >
        thr = jnp.where(use1, t1, t2).astype(jnp.int32)
        best_gain = jnp.where(use1, g1b, g2b)
        default_left = ~use1
        # NaN-with-2-bins direction fix (feature_histogram.hpp:108-110)
        default_left = jnp.where((nb[:, 0] <= 2) & (mt[:, 0] == 2),
                                 False, default_left)

        def pick(arr1, arr2, t1=t1, t2=t2, use1=use1):
            a1 = jnp.take_along_axis(arr1, t1[:, None], 1)[:, 0]
            a2 = jnp.take_along_axis(arr2, t2[:, None], 1)[:, 0]
            return jnp.where(use1, a1, a2)

        lg = pick(lg1, lg2)
        lh = pick(lh1, lh2)
        lc = pick(lc1, lc2)
        lo = jnp.clip(_leaf_output(lg, lh, h.lambda_l1, h.lambda_l2,
                                   h.max_delta_step), min_c, max_c)
        ro = jnp.clip(_leaf_output(sum_grad - lg, sum_hess - lh, h.lambda_l1,
                                   h.lambda_l2, h.max_delta_step),
                      min_c, max_c)
        return dict(gain=best_gain, threshold=thr, default_left=default_left,
                    left_g=lg, left_h=lh, left_c=lc,
                    left_output=lo, right_output=ro)

    def _categorical(hist, sum_grad, sum_hess, num_data_f, min_c, max_c,
                     min_gain_shift):
        """One-hot and CTR-sorted categorical splits
        (feature_histogram.hpp:118-240)."""
        g = hist[..., 0]
        hs = hist[..., 1]
        c = hist[..., 2]
        # used_bin = num_bin - 1 + (missing == none)  (:129-130)
        used_bin = nb - 1 + (mt == 0).astype(jnp.int32)
        # the device-side category bitset spans 8 u32 words = 256 bins
        # (mirroring the reference GPU path's <=256-bins-per-group
        # constraint, dataset.cpp:78); bins beyond it — categories rarer
        # than the 256 most frequent — are not split candidates, keeping
        # the chosen-left stats consistent with the partition routing
        cand = (bins < used_bin) & (bins < CAT_BITSET_BINS)

        # ---- one-hot: left = single bin t (:138-169); uses plain lambda_l2
        lh_oh = hs + K_EPSILON
        rg_oh = sum_grad - g
        rh_oh = sum_hess - hs - K_EPSILON
        rc_oh = num_data_f - c
        valid_oh = (cand & (c >= min_data_f) & (hs >= min_hess)
                    & (rc_oh >= min_data_f) & (rh_oh >= min_hess))
        # gain computed as (other, t) but symmetric without monotone
        gain_oh = _split_gains(rg_oh, rh_oh, g, lh_oh, h.lambda_l1,
                               h.lambda_l2, h.max_delta_step, min_c, max_c, 0)
        gain_oh = jnp.where(valid_oh & (gain_oh > min_gain_shift),
                            gain_oh, NEG_INF)
        t_oh = _first_argmax(gain_oh, axis=1)
        gain_oh_best = jnp.take_along_axis(gain_oh, t_oh[:, None], 1)[:, 0]
        lg_oh_best = jnp.take_along_axis(g, t_oh[:, None], 1)[:, 0]
        lh_oh_best = jnp.take_along_axis(lh_oh, t_oh[:, None], 1)[:, 0]
        lc_oh_best = jnp.take_along_axis(c, t_oh[:, None], 1)[:, 0]

        # ---- CTR-sorted many-vs-many (:170-240); l2 += cat_l2
        l2c = h.lambda_l2_cat
        elig = cand & (c >= h.cat_smooth)
        ctr = g / (hs + h.cat_smooth)
        sort_key = jnp.where(elig, ctr, jnp.inf)
        order = jnp.argsort(sort_key, axis=1)                  # [F,B]
        sg = jnp.take_along_axis(g, order, 1)
        sh_ = jnp.take_along_axis(hs, order, 1)
        sc = jnp.take_along_axis(c, order, 1)
        n_elig = jnp.sum(elig, axis=1).astype(jnp.int32)       # [F]
        max_num_cat = jnp.minimum(h.max_cat_threshold,
                                  (n_elig + 1) // 2)           # [F]
        pos = jnp.arange(max_bin, dtype=jnp.int32)[None, :]

        def scan_dir(fwd: bool):
            # forward: positions 0..; backward: from n_elig-1 downward
            if fwd:
                gg, hh, cc = sg, sh_, sc
                in_elig = pos < n_elig[:, None]
            else:
                # reverse the eligible prefix per feature: position i reads
                # sorted index n_elig-1-i
                ridx = jnp.clip(n_elig[:, None] - 1 - pos, 0, max_bin - 1)
                gg = jnp.take_along_axis(sg, ridx, 1)
                hh = jnp.take_along_axis(sh_, ridx, 1)
                cc = jnp.take_along_axis(sc, ridx, 1)
                in_elig = pos < n_elig[:, None]
            step_ok = in_elig & (pos < max_num_cat[:, None])
            lg = jnp.cumsum(jnp.where(step_ok, gg, 0.0), axis=1)
            lh = jnp.cumsum(jnp.where(step_ok, hh, 0.0), axis=1) + K_EPSILON
            lc = jnp.cumsum(jnp.where(step_ok, cc, 0.0), axis=1)
            rg = sum_grad - lg
            rh = sum_hess - lh
            rc = num_data_f - lc
            left_ok = (lc >= min_data_f) & (lh >= min_hess)
            right_ok = (rc >= min_data_f) & (rc >= h.min_data_per_group) \
                & (rh >= min_hess)

            # sequential min_data_per_group grouping (:198-222): a candidate
            # is evaluated only when the count accumulated since the last
            # evaluated candidate reaches min_data_per_group
            def body(cnt_group, xs):
                cc_i, lok, rok, sok = xs
                cnt_group = cnt_group + jnp.where(sok, cc_i, 0.0)
                evalable = lok & rok & sok
                do_eval = evalable & (cnt_group >= h.min_data_per_group)
                cnt_group = jnp.where(do_eval, 0.0, cnt_group)
                return cnt_group, do_eval

            xs = (cc.T, left_ok.T, right_ok.T, step_ok.T)
            _, do_eval_T = lax.scan(body, jnp.zeros((F,), jnp.float32), xs)
            do_eval = do_eval_T.T
            gain = _split_gains(lg, lh, rg, rh, h.lambda_l1, l2c,
                                h.max_delta_step, min_c, max_c, 0)
            gain = jnp.where(do_eval & (gain > min_gain_shift), gain, NEG_INF)
            t = _first_argmax(gain, axis=1)
            gb = jnp.take_along_axis(gain, t[:, None], 1)[:, 0]
            lgb = jnp.take_along_axis(lg, t[:, None], 1)[:, 0]
            lhb = jnp.take_along_axis(lh, t[:, None], 1)[:, 0]
            lcb = jnp.take_along_axis(lc, t[:, None], 1)[:, 0]
            return dict(gain=gb, t=t, lg=lgb, lh=lhb, lc=lcb)

        fw = scan_dir(True)
        bw = scan_dir(False)
        use_bw = bw["gain"] > fw["gain"]   # forward evaluated first (:188-195)
        gain_sorted = jnp.where(use_bw, bw["gain"], fw["gain"])
        t_sorted = jnp.where(use_bw, bw["t"], fw["t"]).astype(jnp.int32)

        use_onehot = nb[:, 0] <= h.max_cat_to_onehot
        gain_cat = jnp.where(use_onehot, gain_oh_best, gain_sorted)
        lg = jnp.where(use_onehot, lg_oh_best,
                       jnp.where(use_bw, bw["lg"], fw["lg"]))
        lh = jnp.where(use_onehot, lh_oh_best,
                       jnp.where(use_bw, bw["lh"], fw["lh"]))
        lc = jnp.where(use_onehot, lc_oh_best,
                       jnp.where(use_bw, bw["lc"], fw["lc"]))
        # device-side bitset over BINS for the chosen threshold set — used by
        # the fused on-device learner's partition step (8 u32 words = 256
        # bins). Bins are unique, so a sum equals the bitwise OR.
        k_sel = (jnp.where(use_onehot, 1, t_sorted + 1))[:, None]  # [F,1]
        sorted_sel = jnp.where(
            use_bw[:, None],
            (pos >= (n_elig[:, None] - k_sel)) & (pos < n_elig[:, None]),
            pos < k_sel)
        sel_bins = jnp.where(use_onehot[:, None],
                             jnp.where(pos == t_oh[:, None], bins, -1),
                             jnp.where(sorted_sel, order, -1))  # [F,B]
        word_oh = (sel_bins >> 5)[:, :, None] == jnp.arange(8)[None, None, :]
        bitval = jnp.where(sel_bins >= 0,
                           jnp.uint32(1) << (sel_bins & 31).astype(jnp.uint32),
                           jnp.uint32(0))
        cat_bitset = jnp.sum(
            jnp.where(word_oh, bitval[:, :, None], jnp.uint32(0)),
            axis=1, dtype=jnp.uint32)  # [F, 8]
        # outputs use plain lambda_l2 for one-hot, lambda_l2 + cat_l2 for the
        # sorted path (feature_histogram.hpp:133,178,243-252)
        l2_eff = jnp.where(use_onehot, h.lambda_l2, l2c)
        lo = jnp.clip(_leaf_output(lg, lh, h.lambda_l1, l2_eff,
                                   h.max_delta_step), min_c, max_c)
        ro = jnp.clip(_leaf_output(sum_grad - lg, sum_hess - lh, h.lambda_l1,
                                   l2_eff, h.max_delta_step), min_c, max_c)
        return dict(
            gain=gain_cat,
            threshold=jnp.where(use_onehot, t_oh.astype(jnp.int32), t_sorted),
            default_left=jnp.zeros((F,), bool),
            left_g=lg, left_h=lh, left_c=lc,
            left_output=lo, right_output=ro,
            cat_dir=jnp.where(use_bw, -1, 1).astype(jnp.int32),
            sort_order=order,
            n_elig=n_elig,
            use_onehot=use_onehot,
            cat_bitset=cat_bitset,
        )

    @jax.jit
    def find_best_splits(hist, sum_grad, sum_hess, num_data, min_constraint,
                         max_constraint):
        sum_grad = sum_grad.astype(jnp.float32)
        sum_hess = sum_hess.astype(jnp.float32) + 2 * K_EPSILON
        num_data_f = num_data.astype(jnp.float32)
        min_c = min_constraint.astype(jnp.float32)
        max_c = max_constraint.astype(jnp.float32)
        # gain_shift from the epsilon-adjusted parent hessian and plain L2
        # (feature_histogram.hpp:94-96); categorical gain_shift is identical
        # (:126-128)
        gain_shift = _leaf_gain(sum_grad, sum_hess, h.lambda_l1, h.lambda_l2,
                                h.max_delta_step)
        min_gain_shift = gain_shift + h.min_gain_to_split

        num = _numerical(hist, sum_grad, sum_hess, num_data_f, min_c, max_c,
                         min_gain_shift)
        if has_cat:
            cat = _categorical(hist, sum_grad, sum_hess, num_data_f, min_c,
                               max_c, min_gain_shift)
            sel = lambda k: jnp.where(is_cat[:, 0], cat[k], num[k])
        else:
            cat = None
            sel = lambda k: num[k]

        gain = sel("gain")
        out = {
            "gain": jnp.where(jnp.isfinite(gain),
                              (gain - min_gain_shift) * penalty, NEG_INF),
            "threshold": sel("threshold"),
            "default_left": sel("default_left"),
            "left_g": sel("left_g"),
            "left_h": sel("left_h") - K_EPSILON,
            "left_c": sel("left_c").astype(jnp.int32),
        }
        out["right_g"] = sum_grad - sel("left_g")
        out["right_h"] = sum_hess - sel("left_h") - K_EPSILON
        out["right_c"] = num_data - out["left_c"]
        out["left_output"] = sel("left_output")
        out["right_output"] = sel("right_output")
        if cat is not None:
            out["cat_dir"] = cat["cat_dir"]
            out["sort_order"] = cat["sort_order"]
            out["n_elig"] = cat["n_elig"]
            out["use_onehot"] = cat["use_onehot"]
            out["cat_bitset"] = cat["cat_bitset"]
        else:
            out["cat_bitset"] = jnp.zeros((F, 8), jnp.uint32)
        out["is_cat"] = is_cat[:, 0]
        out["best_feature"] = jnp.argmax(out["gain"]).astype(jnp.int32)
        return out

    return find_best_splits
