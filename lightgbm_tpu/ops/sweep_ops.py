"""Batched (model-axis) score-update lanes for the sweep trainer.

The sweep round program (``sweep/batched.py``) vmaps one model's whole
boosting round over a leading model axis. Inside that vmap trace the
per-model score updates must be the RAW python bodies of the existing
jitted programs — calling the jitted wrappers re-enters pjit under vmap,
which re-canonicalizes the f64 reduce-init constants of the hist path to
f32 (XLA rejects the resulting HLO as mixed precision) and hides the
``enable_x64`` blocks from the enclosing trace.

This module provides those raw lanes, built from the same ``ops``
primitives the single-model programs use, so the math is the same
expression tree and the bitwise-parity contract (batched model text ==
sequential model text under ``tpu_use_f64_hist``) holds by construction:

- ``partition_score_update_lane`` — the fresh-tree (no bagging) update:
  leaf fill over the final partition + one key-sort back to row order,
  mirroring ``device_learner._partition_score_update``.
- ``record_score_lane`` — the bagged update: record traversal over the
  full binned matrix (out-of-bag rows also need scores), mirroring
  ``device_learner.add_record_score``/``add_score``.

Both take the per-model ``scale`` (learning rate) as a traced operand so
one program covers a learning-rate grid.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .partition import leaf_value_fill, unpermute_to_rows


def partition_score_update_lane(score: jax.Array, class_id: int,
                                leaf_begin: jax.Array, leaf_cnt: jax.Array,
                                leaf_value: jax.Array, indices: jax.Array,
                                count, scale) -> jax.Array:
    """score[class_id] += scale * leaf values scattered via the final
    partition — the raw body of ``_partition_score_update`` (the fused
    fresh-tree update), valid only for full-data trees. ``class_id`` is
    a python int (the per-class loop is unrolled inside the sweep round
    trace); ``scale`` may be a traced per-model scalar."""
    n = score.shape[1]
    # leaf slices all live inside [0, n): fill and sort only that prefix
    fill = leaf_value_fill(leaf_begin, leaf_cnt, leaf_value, n)
    delta = unpermute_to_rows(lax.slice(indices, (0,), (n,)), fill,
                              count, n)
    return score.at[class_id].add(scale * delta)


def record_score_lane(score_row: jax.Array, bins: jax.Array, trav: Dict,
                      nb, db, mt, scale,
                      col: Optional[jax.Array] = None,
                      boff: Optional[jax.Array] = None,
                      bpk: Optional[jax.Array] = None) -> jax.Array:
    """score_row += scale * tree(x) via record traversal (raw body of
    ``add_record_score`` — the bagged-iteration update, covering
    out-of-bag rows). Imported lazily from models.device_learner to keep
    ops -> models a call-time edge, not an import-time cycle."""
    from ..models.device_learner import add_record_score
    return add_record_score.__wrapped__(score_row, bins, trav, nb, db,
                                        mt, scale, col, boff, bpk)


def stacked_bag_partitions(bag_indices_list, n_pad: int) -> jax.Array:
    """[M, n_pad] root partitions from M per-model bagging subsets — the
    model-axis analogue of ``partition.init_partition_from``. Built on
    host in one shot (one transfer for the whole fleet instead of M
    eager pad/concat dispatches per round)."""
    import numpy as np
    out = np.empty((len(bag_indices_list), n_pad), np.int32)
    for m, idx in enumerate(bag_indices_list):
        idx = np.asarray(idx, np.int32)
        n = idx.shape[0]
        if n >= n_pad:
            out[m] = idx[:n_pad]
        else:
            out[m, :n] = idx
            out[m, n:] = idx[-1] if n else 0
    return jnp.asarray(out)
