"""Chunk-aligned record pipeline — the Pallas kernels behind the aligned
tree builder (`models/aligned_builder.py`).

Replaces the reference's two hot loops with streaming TPU kernels over ONE
persistent record matrix:

- `DataPartition::Split` + `DenseBin::Split` (data_partition.hpp,
  dense_bin.hpp:195-283) -> `move_pass`: a stable two-way partition of
  EVERY tree block in one pass over the rows.
- `DenseBin::ConstructHistogram` / the OpenCL kernels
  (dense_bin.hpp:71-137, ocl/histogram256.cl:350) -> `slot_hist_pass`: one
  streaming pass accumulating per-leaf histograms into data-dependent
  output blocks.

Record layout: `[NC, W, C] int32` — chunk-blocked and TRANSPOSED so rows
sit in the 128-lane dimension (Mosaic only allows dynamic slicing at
128-aligned lane offsets; with rows on lanes, whole chunks move as
`ref.at[chunk]` DMAs and in-chunk permutations become matmuls). Lanes of
one row live at the same lane index across the W sublanes; the first
wcnt sublanes are packed bin words (4/5/8 bins per word at 8/6/4-bit
widths — under EFB the columns are BUNDLE storage), the rest are the
layout's value lanes (see `lane_layout`: STANDARD score/label/grad/
hess/rid/weight, COMPACT score(+prob)/meta, EXT score/grad/hess/rid).

Tree blocks own disjoint CHUNK-ALIGNED ranges of the record matrix, so
every chunk belongs to exactly one block and per-chunk routing parameters
arrive as scalar-prefetched 1-D arrays (SMEM is 1 MB; 2-D prefetch arrays
lane-pad to 128 and blow it).

The in-chunk permutation is exact: the byte-plane one-hot matmul
(bf16 0/1 one-hot x byte planes, f32 accumulate) produces outputs that are
each a SINGLE term < 256, so record bits survive the MXU untouched.

Measured v5e floors at n=10.5M, F=28 (tools/proto_aligned.py): move
4.5 ns/row, hist 3.5 ns/row at B=64 / 6.4 at B=256 — vs 18 ns/row for the
11-op lax.sort partition and ~19 ns/row for the einsum histogram.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import compile_cache

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
    # jax renamed TPUCompilerParams -> CompilerParams (and grew fields
    # like has_side_effects along the way); HBM was addressed as the ANY
    # memory space before it got its own name. Accept either vintage.
    _CP_CLS = getattr(pltpu, "CompilerParams",
                      getattr(pltpu, "TPUCompilerParams", None))
    _HBM = getattr(pltpu, "HBM", getattr(pltpu, "ANY", None))

    def _CompilerParams(**kw):
        import dataclasses
        known = {f.name for f in dataclasses.fields(_CP_CLS)}
        return _CP_CLS(**{k: v for k, v in kw.items() if k in known})
except Exception:  # pragma: no cover
    HAS_PALLAS = False

NUM_STATS = 3          # grad, hess, count
MISSING_NONE_C, MISSING_ZERO_C, MISSING_NAN_C = 0, 1, 2

# route word 1 bit layout (per chunk)
R_THR = 0          # bits 0..7   threshold bin
R_SHIFT = 8        # bits 8..12  shift within word (0/8/16/24)
R_DL = 13          # bit 13      default_left
R_MT = 14          # bits 14..15 missing type
R_COPY = 16        # bit 16      copy-through (unsplit block)
R_WSEL = 17        # bits 17..24 split word lane of the block
R_CAT = 25         # bit 25      categorical split (bitset routing)
# route word 2: default_bin | (num_bin - 1) << 8 | boff << 16 | bpk << 24
# (8-bit bin fields — num_bin <= 256 stores as num_bin - 1, so the whole
# word fits 25 bits; boff/bpk are the EFB bundle unpack params — one
# packed word keeps the scalar-prefetch SMEM budget at 6 x NC words,
# bounding NC ~40K chunks = ~40M rows at C=1024). pack_route2 is the
# single encode point; _unpack_bundle/_goes_left decode.
# meta word: cnt | first << 20 | last << 21


def effective_chunk(cfg, num_features: int = 0) -> int:
    """The chunk size the aligned engine will actually run at. 1024
    measured best on v5e at the HIGGS shape (10.5M x 28) once the route
    one-hot was factored to [C, C] — per-chunk fixed costs dominate the
    split path, so halving the chunk count beats the narrower one-hot —
    but WIDE records regress hard at 1024 (F=137: 2.0 s/iter vs 0.66 at
    512; per-chunk VMEM temps scale with W*C), so records wider than
    ~40 features stay at 512. 2048 regresses on VMEM pressure
    everywhere. tpu_chunk overrides."""
    C = int(getattr(cfg, "tpu_chunk", 0) or 0)
    if C > 0:
        return C
    return 1024 if num_features <= 40 else 512


def chunk_for(cfg, num_features: int, n: int) -> int:
    """effective_chunk, scaled up so the move pass's 6 per-chunk route
    words fit the 1 MB scalar-prefetch SMEM budget (NC <= ~40K): very
    large n doubles the chunk until NC fits — slower per row (wider
    one-hots) but the only way a 50M+-row dataset trains aligned on one
    chip at all. An explicit tpu_chunk is escalated the same way (the
    pinned size would fail SMEM allocation outright), with a warning so
    a user who benchmarked at the pinned size knows why timing moved."""
    C0 = C = effective_chunk(cfg, num_features)
    while n // C > 40_000:
        C *= 2
    if C != C0 and int(getattr(cfg, "tpu_chunk", 0) or 0):
        from ..utils import log
        log.warning(
            f"tpu_chunk={C0} cannot hold {n} rows within the kernel's "
            f"scalar-prefetch budget; using tpu_chunk={C} instead")
    return C


def aligned_num_chunks(n: int, cfg, spec_slots: int,
                       num_features: int = 0) -> int:
    """NC of the engine's record matrix: data chunks + one fresh chunk
    per speculative slot + 2 (must mirror AlignedEngine.__init__)."""
    C = chunk_for(cfg, num_features, n)
    return (n + C - 1) // C + spec_slots + 2


# compact meta-lane bit layout: rid | label << 24 (7 bits: 0/1 binary
# label, or the integer class id for multiclass, K <= 127) | bag << 31
META_RID_MASK = (1 << 24) - 1
META_LABEL = 24
META_LABEL_MASK = 127
META_BAG = 31


def _bpw_for_bits(bits: int) -> int:
    """Bins per 32-bit word at a given bin bit-width: COMPACT records
    pack 8 four-bit bins (max_bin <= 16, the reference's
    dense_nbits_bin.hpp:42 2-bins/byte analogue) or 5 six-bit bins
    (max_bin <= 64); standard records pack 4 eight-bit bins."""
    return {4: 8, 6: 5, 8: 4}[bits]


def lane_layout(wcnt: int, with_bag: bool = False, compact: bool = False,
                num_class: int = 1, with_prob: bool = False,
                ext: bool = False):
    """(lane indices, padded W) for a record with `wcnt` bin words.

    COMPACT layout (lane-wise objectives with small-integer labels,
    unweighted, n <= 2^24): bin words + num_class score lanes + meta,
    where meta packs rid | label << 24 | bag << 31 — gradients are
    recomputed in-kernel from (scores, label) instead of riding as
    lanes, halving the record (W 16 -> 8 at HIGGS shape) and with it
    every DMA and the route matmul of the move pass. `score` is the
    FIRST of the num_class score lanes (class k at score + k).

    EXT layout (external-gradient objectives — ranking): the label and
    weight lanes are dropped (the objective computes g/h in row order
    with weights folded in; nothing in the kernels reads them), so the
    record is bins + score + grad + hess + rid (+bag)."""
    ls = wcnt
    if ext:
        lanes = dict(score=ls, grad=ls + 1, hess=ls + 2, rid=ls + 3)
        w = wcnt + 4
        if with_bag:
            lanes["bag"] = w
            w += 1
    elif compact:
        lanes = dict(score=ls)
        w = wcnt + num_class
        if with_prob:
            # softmax multiclass: per-class PROBABILITY lanes, written
            # once per iteration from the pre-iteration score lanes (the
            # reference computes gradients once then trains K trees,
            # gbdt.cpp:415-444); class gradients derive lane-locally
            # from p_k, immune to the same-iteration deferred score
            # applications
            lanes["prob"] = w
            w += num_class
        lanes["meta"] = w
        w += 1
    else:
        lanes = dict(score=ls, label=ls + 1, grad=ls + 2, hess=ls + 3,
                     rid=ls + 4, weight=ls + 5)
        w = wcnt + 6
        if with_bag:
            lanes["bag"] = w
            w += 1
    w_pad = ((w + 7) // 8) * 8
    return lanes, w_pad


def pack_records(bins: np.ndarray, label: np.ndarray,
                 weight, chunk: int, with_bag: bool = False,
                 compact: bool = False, num_class: int = 1,
                 with_prob: bool = False, max_bin: int = 0,
                 ext: bool = False, rid_base: int = 0):
    """Host-side ingest: [N, F] uint8 bins -> [NC, W, C] int32 records.

    Returns (records, wcnt, W, cnts) where cnts[i] is the number of valid
    rows in chunk i (C except the last). rid_base offsets the stored row
    ids (data-parallel shards pack their local rows with GLOBAL ids).
    """
    n, f = bins.shape
    # bin words pack at the narrowest width the MAPPERS' bin range
    # allows (max_bin = max num_bin over used mappers; falls back to the
    # observed data max when the caller has no mappers): 4-bit (8/word,
    # the reference's dense_nbits_bin.hpp:42 two-bins-per-byte at twice
    # the density) under 16 bins, 6-bit (5/word) under 64, 8-bit (4/word)
    # otherwise — for EVERY lane layout; the kernels parameterize on
    # `bits` throughout. Deriving from num_bin rather than bins.max()
    # means a split threshold in the (possibly data-empty) upper bin
    # range is always representable in-width.
    bmax = max(int(bins.max(initial=0)), max_bin - 1)
    if bmax < 16:
        bits = 4
    elif bmax < 64:
        bits = 6
    else:
        bits = 8
    bpw = _bpw_for_bits(bits)
    wcnt = (f + bpw - 1) // bpw
    lanes, w_pad = lane_layout(wcnt, with_bag, compact, num_class,
                               with_prob, ext=ext)
    nc = (n + chunk - 1) // chunk
    n_pad = nc * chunk
    padded = np.zeros((n_pad, wcnt * bpw), np.uint8)
    padded[:n, :f] = bins
    words = padded.reshape(n_pad, wcnt, bpw).astype(np.uint32)
    packed = np.zeros((n_pad, wcnt), np.uint32)
    for i in range(bpw):
        packed |= words[:, :, i] << (bits * i)
    rec = np.zeros((n_pad, w_pad), np.int32)
    rec[:, :wcnt] = packed.astype(np.int64).astype(np.int32)
    if ext:
        rec[:, lanes["rid"]] = rid_base + np.arange(n_pad, dtype=np.int32)
        if with_bag:
            rec[:n, lanes["bag"]] = np.ones(n, np.float32).view(np.int32)
    elif compact:
        if num_class > 1:
            lab = np.asarray(label).astype(np.int64) & META_LABEL_MASK
        else:
            lab = (np.asarray(label) > 0).astype(np.int64)
        meta = (rid_base + np.arange(n_pad, dtype=np.int64)) \
            & META_RID_MASK
        meta[:n] |= lab << META_LABEL
        meta[:n] |= 1 << META_BAG     # all rows in-bag initially
        rec[:, lanes["meta"]] = meta.astype(np.int64).astype(np.uint32) \
            .view(np.int32)
    else:
        rec[:n, lanes["label"]] = np.asarray(label, np.float32) \
            .view(np.int32)
        rec[:, lanes["rid"]] = rid_base + np.arange(n_pad, dtype=np.int32)
        wv = np.ones(n, np.float32) if weight is None \
            else np.asarray(weight, np.float32)
        rec[:n, lanes["weight"]] = wv.view(np.int32)
        if with_bag:
            rec[:n, lanes["bag"]] = np.ones(n, np.float32).view(np.int32)
    rec3 = np.ascontiguousarray(
        rec.reshape(nc, chunk, w_pad).transpose(0, 2, 1))
    cnts = np.full(nc, chunk, np.int32)
    if nc:      # zero-row shards (uneven DP split) pack an empty grid
        cnts[-1] = n - (nc - 1) * chunk
    return rec3, wcnt, w_pad, cnts, bits


# ---------------------------------------------------------------------------
# move pass
# ---------------------------------------------------------------------------
def pack_route2(db, nb, boff=0, bpk=0):
    """Encode route word 2: db | (nb - 1) << 8 | boff << 16 | bpk << 24.

    num_bin stores BIASED (nb - 1 <= 255) so every field is 8 bits and
    the word stays within 25 bits — the narrow fields are what lets the
    split threshold/bin arithmetic stay 8-bit end to end at
    max_bin = 255. Single encode point: the aligned builder and the
    kernel-parity tests both construct r2 through this helper, so the
    layout can never drift between encoder and the in-kernel decoders
    (_unpack_bundle/_goes_left). Works on python ints, numpy and jax
    arrays alike."""
    return ((db & 255) | (((nb - 1) & 255) << 8) | ((boff & 255) << 16)
            | ((bpk & 1) << 24))


def _unpack_bundle(binv, r2):
    """EFB: BUNDLE column value -> the split feature's own bin — MUST
    stay bit-identical to ops/partition.bundle_unpack (the valid-set
    walker and fused partition path route through that helper;
    tests/test_efb.py::test_kernel_unpack_matches_bundle_unpack pins the
    equivalence over the full domain). This arithmetic-select form
    exists because Mosaic cannot broadcast the scalar bpk bool into a
    vector select (arith.trunci to i1 fails in-kernel). r2 packs the
    feature-space default_bin/num_bin plus boff/bpk (see pack_route2).
    Must run BEFORE _cat_word/_goes_left — both consume feature-space
    bins."""
    db = r2 & 255
    nb = ((r2 >> 8) & 255) + 1
    boff = (r2 >> 16) & 255
    bpk = (r2 >> 24) & 1
    p = binv - boff
    in_range = ((p >= 0) & (p < nb - 1)).astype(jnp.int32)
    b = jnp.where(p >= db, p + 1, p)
    unpacked = in_range * b + (1 - in_range) * db
    return bpk * unpacked + (1 - bpk) * binv


def _goes_left(binv, r1, r2, valid, catw=None):
    """Reference DenseBin::Split routing (dense_bin.hpp:195-283):
    numerical with missing None/Zero/NaN, categorical by bitset
    membership (Common::FindInBitset); copy-through routes all left.

    Pure i32 arithmetic — Mosaic can't broadcast scalar bools into vector
    selects (arith.trunci to i1 fails), so the scalar route bits enter as
    0/1 integers and the final bool comes from one vector comparison.
    `catw` = per-row selected bitset word (vector, from _cat_word)."""
    thr = r1 & 255
    dl = (r1 >> R_DL) & 1                      # scalar 0/1
    mt = (r1 >> R_MT) & 3
    copy = (r1 >> R_COPY) & 1
    db = r2 & 255
    nb = ((r2 >> 8) & 255) + 1
    base = (binv <= thr).astype(jnp.int32)     # vector 0/1
    mtz = jnp.int32(0) + ((mt == MISSING_ZERO_C).astype(jnp.int32))
    mtn = (mt == MISSING_NAN_C).astype(jnp.int32)
    is_def = (mtz * (binv == db).astype(jnp.int32)
              + mtn * (binv == nb - 1).astype(jnp.int32))
    left_i = is_def * dl + (1 - is_def) * base
    if catw is not None:
        iscat = (r1 >> R_CAT) & 1              # scalar 0/1
        cat_i = (catw >> (binv & 31)) & 1      # vector bit test
        left_i = iscat * cat_i + (1 - iscat) * left_i
    vi = valid.astype(jnp.int32)
    out = copy * vi + (1 - copy) * left_i * vi
    return out != 0


def _cat_word(cbits_ref, ks, binv):
    """Per-row bitset word for a categorical split: cbits_ref is the
    round's compact [K*8] flat bitset table (SMEM prefetch), ks the
    block's compact split id."""
    bw = binv >> 5
    w = jnp.zeros_like(binv)
    for j in range(8):
        w = jnp.where(bw == j, cbits_ref[ks * 8 + j], w)
    return w



def _payload_gh(rows, nvalid, chunk, wcnt, grad_fn, bag_lane,
                num_class=1, gh_off=2):
    """(g, h, take) for a [W, C] row block: lane-resident gradients
    (standard layout, or multiclass compact where per-class g/h were
    written from pre-iteration scores) or recomputed in-kernel
    (single-class compact, grad_fn not None — the objective's pointwise
    gradient inlined into the Pallas kernel). bag_lane: >= 0 an f32 0/1
    lane, -2 the meta-lane bag BIT, -1 none. gh_off: grad lane offset
    from wcnt (2 in the standard layout, 1 in the ext layout)."""
    posh = lax.broadcasted_iota(jnp.int32, (1, chunk), 1)[0]
    take = posh < nvalid
    if grad_fn is not None and num_class > 1:
        # multiclass: engine-built closure with lane indices baked in,
        # reading the class's prob/score lane + the meta label bits
        g, h, bagmask = grad_fn(rows)
        if bag_lane == -2 and bagmask is not None:
            take = take & bagmask
    elif grad_fn is not None:
        meta = rows[wcnt + 1, :]
        score = lax.bitcast_convert_type(rows[wcnt, :], jnp.float32)
        label = ((meta >> META_LABEL) & META_LABEL_MASK) \
            .astype(jnp.float32)
        g, h = grad_fn(score, label, None)
        if bag_lane == -2:     # compact bagging: bag bit masks stats
            take = take & (((meta >> META_BAG) & 1) != 0)
    else:
        g = lax.bitcast_convert_type(rows[wcnt + gh_off, :], jnp.float32)
        h = lax.bitcast_convert_type(rows[wcnt + gh_off + 1, :],
                                     jnp.float32)
        if bag_lane >= 0:
            bagv = lax.bitcast_convert_type(rows[bag_lane, :],
                                            jnp.float32)
            take = take & (bagv > 0.5)
    return g, h, take


def _nibble_hist(b_pad: int) -> bool:
    """True when the histogram accumulates via the hi/lo NIBBLE
    factorization instead of a full-width one-hot: at B=256 the one-hot
    build is 256 compares per (row, feature) on the VPU; factoring the
    bin into two 4-bit halves needs 32 compares + 96 bf16 products and
    the same MAC count (measured 7.37 -> 5.99 ns/row full-data pass).
    The store keeps the kernel-friendly [F, 6, lo, hi] layout; callers
    remap to [F, bin, 3] outside the kernel."""
    return b_pad > 128


def _hist_mode(b_pad: int, subbin: bool = False) -> str:
    """Histogram accumulation mode for a bin width.

    "group": full-width one-hot, features batched per MXU issue
    (b_pad <= 128). Above 128 bins the one-hot build cost forces a
    factored form: "nibble" (legacy bit-3 payload split x 128-wide
    one-hot — 130 compares per row/feature) or "subbin" (hi/lo 4-bit
    halves: TWO 16-wide one-hots, 32 compares, one [16,C]x[128,C] MXU
    issue into a [16, 128] = [lo, pay*16+hi] tile — exactly two f32
    VMEM tiles). subbin is the tpu_hist_subbin knob resolved by the
    caller; it only applies where the factored form is needed."""
    if b_pad > 128:
        return "subbin" if subbin else "nibble"
    return "group"


def _hist_accum(pay6, bin_of, accum, num_features, b_pad, group, C,
                subbin=False):
    """Accumulate one chunk's histogram contributions.

    pay6: [6, C] hi/lo payload; bin_of(f) -> [C] i32 bin values;
    accum(idx, contrib) adds into the store — grouped one-hot indexes by
    group id with [6, group*b_pad] blocks, nibble mode by feature with
    [96, 16] = [6*lo, hi] blocks, subbin mode by feature with [16, 128]
    = [lo, pay*16 + hi] blocks (cols >= 96 stay zero)."""
    mode = _hist_mode(b_pad, subbin)
    if mode == "subbin":
        # sub-binned accumulation: bin = hi*16 + lo. The payload rides
        # the HI one-hot (Z = pay6 x oh_hi -> [96, C], zero-padded to a
        # full [128, C] tile) and ONE MXU contraction against the 16-wide
        # LO one-hot lands the whole [16, 128] sub-bin tile — 32 VPU
        # compares per (row, feature) vs the nibble form's 130, and the
        # tile folds to [256, 3] once per store finalize instead of
        # per-chunk repacking.
        iota16 = lax.broadcasted_iota(jnp.int32, (16, C), 0)
        for f in range(num_features):
            bv = bin_of(f)
            oh_hi = ((bv >> 4)[None, :] == iota16).astype(jnp.bfloat16)
            oh_lo = ((bv & 15)[None, :] == iota16).astype(jnp.bfloat16)
            Z = (pay6[:, None, :] * oh_hi[None, :, :]).reshape(96, C)
            Zp = jnp.concatenate(
                [Z, jnp.zeros((32, C), jnp.bfloat16)], axis=0)
            contrib = lax.dot_general(oh_lo, Zp, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            accum(f, contrib)
        return
    if mode == "nibble":
        # factor bin = hi*16 + b3*8 + lo3 into a 2-row payload split
        # (bit 3) and a 128-wide one-hot (lo3*16 + hi): the [12, 128]
        # contrib tiles VMEM exactly (no 16-lane padding, no in-kernel
        # repack) and Z is only 12 rows of products
        iota2 = lax.broadcasted_iota(jnp.int32, (2, C), 0)
        iota128 = lax.broadcasted_iota(jnp.int32, (128, C), 0)
        for f in range(num_features):
            bv = bin_of(f)
            oh2 = (((bv >> 3) & 1)[None, :] == iota2).astype(jnp.bfloat16)
            col = (bv & 7) * 16 + (bv >> 4)
            ohc = (col[None, :] == iota128).astype(jnp.bfloat16)
            Z = (pay6[:, None, :] * oh2[None, :, :]).reshape(12, C)
            contrib = lax.dot_general(Z, ohc, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            accum(f, contrib)
        return
    iota_b = lax.broadcasted_iota(jnp.int32, (b_pad, C), 0)
    ngroups = (num_features + group - 1) // group
    for gi in range(ngroups):
        ohs = []
        for j in range(group):
            f = min(gi * group + j, num_features - 1)
            ohs.append((bin_of(f)[None, :] == iota_b)
                       .astype(jnp.bfloat16))
        onehot = jnp.concatenate(ohs, axis=0)
        contrib = lax.dot_general(pay6, onehot, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        accum(gi, contrib)


def slot_hist_bytes(ncols: int, b_pad: int, subbin: bool = False) -> int:
    """Bytes of ONE slot's histogram block in the engine's histogram
    stores — the single source of truth for the per-round VMEM budget
    check that decides between the VMEM-resident store and the HBM
    spill ring (aligned_builder / device_learner)."""
    group = 8 if b_pad <= 64 else 4
    return 4 * int(np.prod(
        _hist_store_shape(0, ncols, b_pad, group, subbin)[1:]))


def hist_layout(cfg, ncols: int, bh: int, K: int):
    """Resolve the aligned histogram store layout for a K-split round:
    (subbin, spill, slot_bytes, budget_bytes).

    subbin: the tpu_hist_subbin knob ("auto"/"on" enable the sub-binned
    accumulation wherever the factored form applies, i.e. bh > 128;
    "off" keeps the legacy nibble form). spill: True when the
    [K+1]-slot store exceeds the tpu_hist_spill_vmem_mb VMEM budget —
    the move pass then keeps the store in HBM behind the 2-deep DMA
    staging ring instead of shrinking K. Shared between
    AlignedEngine._build_program and the device learner's gate notes so
    the logged path always matches the compiled kernel."""
    knob = str(getattr(cfg, "tpu_hist_subbin", "auto") or "auto").lower()
    subbin = knob != "off"
    slot_bytes = slot_hist_bytes(ncols, bh, subbin)
    budget = int(float(getattr(cfg, "tpu_hist_spill_vmem_mb", 48) or 48)
                 * (1 << 20))
    spill = slot_bytes * (K + 1) > budget
    return subbin, spill, slot_bytes, budget


def _hist_store_shape(num_slots, num_features, b_pad, group,
                      subbin=False):
    """Per-pass histogram store shape (see _hist_accum layouts). The
    nibble layout's [12, 128] and the subbin layout's [16, 128] blocks
    fill 128-lane tiles exactly — a narrow minor dim would pad 8x in
    VMEM (353 MB at 257 slots)."""
    mode = _hist_mode(b_pad, subbin)
    if mode == "subbin":
        return (num_slots + 1, num_features, 16, 128)
    if mode == "nibble":
        return (num_slots + 1, num_features, 12, 128)
    ngroups = (num_features + group - 1) // group
    return (num_slots + 1, ngroups, 6, group * b_pad)


def _hist_store_finalize(out, num_slots, num_features, b_pad, group,
                         subbin=False):
    """Store -> hist[num_slots, F, b_pad, 3] (hi+lo payload halves
    combined; nibble/subbin modes also remap bin = hi*16 + lo)."""
    mode = _hist_mode(b_pad, subbin)
    if mode == "subbin":
        # [ns+1, F, lo, pay*16 + hi] -> drop the 32 zero pad cols, fold
        # the hi/lo payload halves, land bin = hi*16 + lo
        h = out[..., :96].reshape(num_slots + 1, num_features, 16, 6, 16)
        h = h[:, :, :, :3] + h[:, :, :, 3:]        # [ns,F,lo,3,hi]
        h = jnp.transpose(h, (0, 1, 4, 2, 3))      # [ns,F,hi,lo,3]
        h = h.reshape(num_slots + 1, num_features, 256, 3)
        return h[:num_slots, :, :b_pad]
    if mode == "nibble":
        h = out.reshape(num_slots + 1, num_features, 6, 2, 8, 16)
        h = h[:, :, :3] + h[:, :, 3:]              # [ns,F,3,b3,lo3,hi]
        h = jnp.transpose(h, (0, 1, 5, 3, 4, 2))   # [ns,F,hi,b3,lo3,3]
        h = h.reshape(num_slots + 1, num_features, 256, 3)
        return h[:num_slots, :, :b_pad]
    ngroups = (num_features + group - 1) // group
    h = out.reshape(num_slots + 1, ngroups, 6, group, b_pad)
    h = h[:, :, :3] + h[:, :, 3:]
    h = jnp.moveaxis(h, 2, 4)
    h = h.reshape(num_slots + 1, ngroups * group, b_pad, 3)
    return h[:num_slots, :num_features]


def _hi_lo6(pay):
    """Split [3, C] f32 payload rows into an exact [6, C] bf16 (hi, lo)
    pair via mantissa TRUNCATION: hi = pay with the low 16 mantissa bits
    zeroed (exactly bf16-representable), lo = bf16(pay - hi). The naive
    round-to-nearest form `bf16(pay - f32(bf16(pay)))` is silently
    simplified to 0 by XLA's convert-folding pass, dropping the
    compensation term and leaving raw bf16 rounding error in the
    histogram sums (~1e-3 absolute on value-concentrated data); the bit
    mask is opaque to that pass, and hi + lo reconstructs ~23 bits."""
    pi = lax.bitcast_convert_type(pay, jnp.int32)
    hi_f = lax.bitcast_convert_type(pi & jnp.int32(-65536), jnp.float32)
    lo = (pay - hi_f).astype(jnp.bfloat16)
    hi = hi_f.astype(jnp.bfloat16)     # exact: low bits already zero
    return jnp.concatenate([hi, lo], axis=0)


def _move_kernel(r1_ref, r2_ref, blbr_ref, meta_ref,
                 hslot_ref, cbits_ref, fetch_ref, rec_ref, rec_hbm_ref,
                 out_ref, hist_ref, stag,
                 fbuf, hacc, hstage, cur_ref, sems, *, chunk, w_pad,
                 w_used, wcnt, num_features, b_pad, group, dummy,
                 bag_lane, bits, grad_fn, num_class, gh_off, bundled,
                 subbin, spill):
    """One grid step of the fused move+hist pass.

    SPLIT chunks: partition rows into the block's left/right staging
    rings (exact byte-plane one-hot matmul), flush full chunks to dynamic
    destination chunks, and accumulate the smaller child's histogram
    DIRECTLY from the chunk's smaller-side rows into a VMEM-resident
    store indexed by COMPACT per-round slot ids (constant out-spec: the
    whole [K+1, ...] store lives in VMEM across the grid and flushes
    once). COPY chunks (unsplit blocks): one direct HBM->HBM DMA to the
    prefetched destination — no VMEM staging, and the blocked input
    pipeline SKIPS the fetch (fetch_ref holds the last split chunk's
    index, so the block index doesn't change on copy runs).

    Flushes are ASYNC: each staging half is copied to one of two per-side
    flush buffers and DMA'd without waiting; a buffer/semaphore is reused
    only after its previous DMA is waited on (pending flags in SMEM),
    and the final grid step drains all outstanding DMAs.

    SPILL mode (static `spill`): the [K+1, ...] store is HBM-resident
    instead of VMEM-resident — only the per-block hacc accumulator and
    a 2-deep staging ring (hstage) live in VMEM. A slotted block's
    finished hacc is copied to hstage[p] (p ping-pongs per slotted
    block) and DMA'd to its HBM slot without waiting, overlapping the
    next block's accumulation with the previous block's writeback. Each
    slot is written by exactly ONE block per pass, so the DMA is a plain
    overwrite; unvisited slots stay uninitialized and the wrapper masks
    them to zero from hslots.

    cur_ref: [cur_l, cur_r, fl_l, fl_r, pend 4..15, dst 16..27,
    src 28..39, spill_blk 40, spill_pend 41..42, spill_dst 43..44];
    sems: slots 0-3 = VMEM flush, 4-11 = HBM->HBM copy,
    12-13 = hist spill."""
    i = pl.program_id(0)
    C = chunk
    r1 = r1_ref[i]
    meta = meta_ref[i]
    is_last = (meta >> 21) & 1

    @pl.when(i == 0)
    def _():
        # SMEM scratch is NOT zero-initialized: clear the DMA pending
        # flags and saved src/dst indices before any use
        for j in range(48):
            cur_ref[j] = 0
        if not spill:
            hist_ref[...] = jnp.zeros_like(hist_ref)

    @pl.when(((meta >> 20) & 1) != 0)     # first chunk of block
    def _():
        cur_ref[0] = 0
        cur_ref[1] = 0
        cur_ref[2] = 0
        cur_ref[3] = 0
        # per-block hist accumulator: STATIC address per chunk (a
        # dynamic-index RMW per chunk measured 3x slower); flushed to
        # the compact store once per block on its last chunk
        hacc[...] = jnp.zeros_like(hacc)

    rec = rec_ref[0]                                  # [W, C]
    pos = lax.broadcasted_iota(jnp.int32, (1, C), 1)[0]
    cntv = meta & ((1 << 20) - 1)
    valid = pos < cntv
    is_copy = (r1 >> R_COPY) & 1
    hs = hslot_ref[i]

    def wait_slot(slot):
        if slot < 4:            # static: flush slots DMA from VMEM
            pltpu.make_async_copy(fbuf.at[slot],
                                  out_ref.at[cur_ref[16 + slot]],
                                  sems.at[slot]).wait()
        else:                   # copy slots DMA HBM->HBM
            pltpu.make_async_copy(rec_hbm_ref.at[cur_ref[28 + slot]],
                                  out_ref.at[cur_ref[16 + slot]],
                                  sems.at[slot]).wait()
        cur_ref[4 + slot] = 0

    def wait_spill(p):
        pltpu.make_async_copy(hstage.at[p],
                              hist_ref.at[cur_ref[43 + p]],
                              sems.at[12 + p]).wait()
        cur_ref[41 + p] = 0

    bpw = _bpw_for_bits(bits)
    bmask = (1 << bits) - 1

    def hist_flushed(rows, nvalid):
        """Accumulate a flushed [W, C] chunk of the smaller child (first
        nvalid rows valid) into the per-block accumulator: flushed
        buffers hold the side's rows COMPACTED, so the one-hot work runs
        at full density on exactly the smaller child's rows. Bagged
        stats cover IN-BAG rows only (gbdt.cpp:209-275)."""
        g, h, take = _payload_gh(rows, nvalid, C, wcnt, grad_fn,
                                 bag_lane, num_class, gh_off)
        gm = jnp.where(take, g, 0.0)
        hm = jnp.where(take, h, 0.0)
        cntp = take.astype(jnp.float32)
        pay = jnp.stack([gm, hm, cntp], axis=0)
        pay6 = _hi_lo6(pay)

        def bin_of(f):
            return (rows[f // bpw, :] >> ((f % bpw) * bits)) & bmask

        def accum(idx, contrib):
            hacc[idx] += contrib

        _hist_accum(pay6, bin_of, accum, num_features, b_pad, group, C,
                    subbin)

    # ---- copy fast-path: unsplit blocks shift as whole chunks — one
    # direct HBM->HBM DMA to the prefetched destination (bl): no fetch,
    # no VMEM staging, 8 DMAs in flight
    bl_i = blbr_ref[i] & 0xFFFF
    br_i = (blbr_ref[i] >> 16) & 0xFFFF

    @pl.when((is_copy != 0) & (cntv > 0))
    def _():
        for cp in range(8):
            @pl.when((i % 8) == cp)
            def _():
                slot = 4 + cp

                @pl.when(cur_ref[4 + slot] != 0)
                def _():
                    wait_slot(slot)
                pltpu.make_async_copy(
                    rec_hbm_ref.at[i], out_ref.at[bl_i],
                    sems.at[slot]).start()
                cur_ref[4 + slot] = 1
                cur_ref[16 + slot] = bl_i
                cur_ref[28 + slot] = i

    # ---- split path
    @pl.when(is_copy == 0)
    def _():
        wsel = (r1 >> R_WSEL) & 255
        word = rec[0, :]
        for wj in range(1, wcnt):
            word = jnp.where(wsel == wj, rec[wj, :], word)
        binv = (word >> ((r1 >> R_SHIFT) & 31)) & bmask
        if bundled:
            binv = _unpack_bundle(binv, r2_ref[i])
        catw = _cat_word(cbits_ref, hs & 0xFFFFFF, binv)
        left = _goes_left(binv, r1, r2_ref[i], valid, catw)

        # ranks via one triangular matmul (measured FASTER on the MXU
        # than log2(C) pltpu.roll prefix sums: 3.33 vs 3.82 ns/row)
        li = left.astype(jnp.bfloat16)[None, :]
        vi = valid.astype(jnp.bfloat16)[None, :]
        both = jnp.concatenate([li, vi], axis=0)          # [2, C]
        iota_s = lax.broadcasted_iota(jnp.int32, (C, C), 0)
        iota_d = lax.broadcasted_iota(jnp.int32, (C, C), 1)
        tri = (iota_s < iota_d).astype(jnp.bfloat16)
        ranks = lax.dot_general(both, tri, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        rank_l = ranks[0].astype(jnp.int32)
        rank_v = ranks[1].astype(jnp.int32)
        k_l = jnp.sum(left.astype(jnp.int32))
        k_v = jnp.sum(valid.astype(jnp.int32))
        rank_r = rank_v - rank_l

        cur_l = cur_ref[0]
        cur_r = cur_ref[1]
        dst = jnp.where(left, (cur_l + rank_l) % (2 * C),
                        2 * C + (cur_r + rank_r) % (2 * C))
        dst = jnp.where(valid, dst, 4 * C + 5)

        # only the USED lanes ride the route matmul (w_used <= w_pad:
        # 8-sublane padding and, under the compact layout, the unused
        # tail lanes carry no data — pad lanes of the output stay stale,
        # which is fine because no kernel reads past w_used)
        U = w_used
        # int8 byte planes: the MXU takes s8 x s8 -> s32 at twice the
        # bf16 rate and the f32 -> i32 output converts disappear; byte
        # values wrap to signed but `& 255` after the single-term
        # selection recovers them exactly
        planes = jnp.concatenate(
            [((rec[:U] >> (8 * b)) & 255).astype(jnp.int8)
             for b in range(4)], axis=0)                  # [4U, C]
        # FACTORED route: dst = sc*C + lo (sc = staging chunk 0..3).
        # A flat [C, 4C] one-hot costs 4C int32 compares per row on the
        # VPU (2048 at C=512 — measured the dominant term of the split
        # path); factoring into a per-sc payload split (4 compares +
        # 4*4U products per row) times ONE [C, C] one-hot (C compares)
        # cuts the VPU work ~3x at identical MXU MACs, and the sc blocks
        # of the output are exactly the 4 staging chunks. Exact: each
        # output (sc, lo) receives a single term < 256.
        sc_of = dst // C                                  # 4 = invalid
        lo_of = dst % C
        Z = jnp.concatenate(
            [jnp.where((sc_of == sc)[None, :], planes, 0)
             for sc in range(4)], axis=0)                 # [4*4U, C]
        iota_c2 = lax.broadcasted_iota(jnp.int32, (C, C), 1)
        oh_lo = (lo_of[:, None] == iota_c2).astype(jnp.int8)
        moved = lax.dot_general(Z, oh_lo, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.int32)

        posc = lax.broadcasted_iota(jnp.int32, (1, C), 1)[0]
        lo_l = cur_l % (2 * C)
        hi_l = lo_l + k_l
        lo_r = cur_r % (2 * C)
        hi_r = lo_r + k_v - k_l
        for sc in range(4):
            blk = moved[sc * 4 * U:(sc + 1) * 4 * U] & 255
            mrows = (blk[:U] | (blk[U:2 * U] << 8)
                     | (blk[2 * U:3 * U] << 16) | (blk[3 * U:] << 24))
            if U < w_pad:
                mrows = jnp.concatenate(
                    [mrows, jnp.zeros((w_pad - U, C), jnp.int32)], axis=0)
            if sc < 2:
                pos = sc * C + posc
                m = ((pos >= lo_l) & (pos < hi_l)) \
                    | ((pos + 2 * C >= lo_l) & (pos + 2 * C < hi_l))
            else:
                pr = (sc - 2) * C + posc
                m = ((pr >= lo_r) & (pr < hi_r)) \
                    | ((pr + 2 * C >= lo_r) & (pr + 2 * C < hi_r))
            stag[sc] = jnp.where(m[None, :], mrows, stag[sc])

        new_l = cur_l + k_l
        new_r = cur_r + k_v - k_l
        cur_ref[0] = jnp.where(is_last != 0, 0, new_l)
        cur_ref[1] = jnp.where(is_last != 0, 0, new_r)

        def flush_side(side, fl_slot, base, cur_val):
            for _ in range(2):    # at most 2 flushes per side per step
                fl = cur_ref[fl_slot]
                full = cur_val - fl * C >= C
                fin = (is_last != 0) & (cur_val - fl * C > 0) & ~full

                @pl.when(full | fin)
                def _():
                    for p in range(2):
                        @pl.when((fl % 2) == p)
                        def _():
                            slot = side * 2 + p

                            @pl.when(cur_ref[4 + slot] != 0)
                            def _():
                                wait_slot(slot)
                            fbuf[slot] = stag[side * 2 + p]
                            pltpu.make_async_copy(
                                fbuf.at[slot], out_ref.at[base + fl],
                                sems.at[slot]).start()
                            cur_ref[4 + slot] = 1
                            cur_ref[16 + slot] = base + fl

                            @pl.when(((hs & 0xFFFFFF) != dummy)
                                     & (((hs >> 24) & 1) == side))
                            def _():
                                hist_flushed(
                                    fbuf[slot],
                                    jnp.minimum(cur_val - fl * C, C))
                    cur_ref[fl_slot] = fl + 1

        flush_side(0, 2, bl_i, new_l)
        flush_side(1, 3, br_i, new_r)

        @pl.when((is_last != 0) & ((hs & 0xFFFFFF) != dummy))
        def _():
            if not spill:
                hist_ref[hs & 0xFFFFFF] += hacc[...]
            else:
                # 2-deep spill ring: stage the finished block histogram
                # and DMA it to its HBM slot WITHOUT waiting — the next
                # block accumulates into hacc while this one drains.
                # The staging buffer/semaphore is reused only after its
                # previous DMA completed.
                for p in range(2):
                    @pl.when((cur_ref[40] & 1) == p)
                    def _(p=p):
                        @pl.when(cur_ref[41 + p] != 0)
                        def _():
                            wait_spill(p)
                        hstage[p] = hacc[...]
                        cur_ref[43 + p] = hs & 0xFFFFFF
                        pltpu.make_async_copy(
                            hstage.at[p],
                            hist_ref.at[hs & 0xFFFFFF],
                            sems.at[12 + p]).start()
                        cur_ref[41 + p] = 1
                cur_ref[40] = cur_ref[40] + 1

        @pl.when(is_last != 0)
        def _():
            cur_ref[2] = 0
            cur_ref[3] = 0

    @pl.when(i == pl.num_programs(0) - 1)   # drain outstanding DMAs
    def _():
        for slot in range(12):
            @pl.when(cur_ref[4 + slot] != 0)
            def _():
                wait_slot(slot)
        if spill:
            for p in range(2):
                @pl.when(cur_ref[41 + p] != 0)
                def _(p=p):
                    wait_spill(p)


@functools.partial(jax.jit, static_argnames=(
    "chunk", "w_pad", "wcnt", "num_slots", "num_features", "b_pad",
    "group", "bag_lane", "bits", "grad_fn", "num_class", "w_used",
    "gh_off", "bundled", "interpret", "subbin", "spill"))
def move_pass(records, r1, r2, basel, baser, meta, wsel, hslots, cbits,
              chunk, w_pad, wcnt, num_slots, num_features, b_pad, group,
              bag_lane=-1, bits=8, grad_fn=None, num_class=1,
              w_used=0, gh_off=2, bundled=False,
              interpret=False, subbin=False, spill=False):
    """Stable two-way partition of every block in one streaming pass,
    with the smaller-child histograms FUSED into the same pass.

    SMEM packing (the prefetch budget is 1 MB): wsel rides in r1 bits
    R_WSEL..R_WSEL+7 (so features <= 1020) and basel/baser pack into one
    16+16-bit word (so <= 65535 chunks) — callers must respect both
    bounds (aligned_mode_ok does).

    records: [NC, W, C] i32; r1/r2/basel/baser/meta/wsel: [NC] i32
    per-chunk routing (see module docstring bit layouts; wsel = split
    word lane index of the chunk's block). hslots[i] packs the smaller
    child's accumulation slot | side << 24 (side 0 = left rows of the
    chunk are the smaller child); slot == num_slots skips. Slots are
    COMPACT per-round ids (0..k-1): the whole [num_slots+1, ...] store
    stays VMEM-resident across the grid (num_slots <= ~256 so it fits
    at B=256), so callers must remap tree slots to the round's selected
    split ranks.

    Returns (records_out, hist[num_slots, F, b_pad, 3]). Chunks not
    covered by the new layout keep stale rows; hist slots never present
    in hslots are zero.

    `spill` keeps the [num_slots+1, ...] store in HBM (streamed through
    the kernel's 2-deep VMEM staging ring) instead of VMEM-resident —
    the shape that lets wide-F x 255-bin rounds run with K well past
    the VMEM budget. `subbin` selects the sub-binned accumulation at
    b_pad > 128 (see _hist_mode).
    """
    compile_cache.note_trace()
    nc = records.shape[0]
    dummy = num_slots
    store_shape = _hist_store_shape(num_slots, num_features, b_pad,
                                    group, subbin)
    hacc_shape = store_shape[1:]
    # spill stages through a 2-deep ring; non-spill keeps a tiny dummy
    # so the kernel signature is mode-independent
    hstage_shape = (2,) + hacc_shape if spill else (2, 8, 128)
    kernel = functools.partial(_move_kernel, chunk=chunk, w_pad=w_pad,
                               w_used=w_used or w_pad,
                               wcnt=wcnt, num_features=num_features,
                               b_pad=b_pad, group=group, dummy=dummy,
                               bag_lane=bag_lane, bits=bits,
                               grad_fn=grad_fn, num_class=num_class,
                               gh_off=gh_off, bundled=bundled,
                               subbin=subbin, spill=spill)
    r1p = r1 | (wsel << R_WSEL)
    blbr = basel | (baser << 16)
    # copy chunks SKIP the blocked fetch: the block index carries the
    # last split chunk's index forward, so the pipeline only fetches
    # when the index changes (i.e. at split chunks)
    iota_nc = jnp.arange(nc, dtype=jnp.int32)
    is_split = ((r1 >> R_COPY) & 1) == 0
    fetch_idx = lax.cummax(jnp.where(is_split, iota_nc, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=7,
        grid=(nc,),
        in_specs=[
            pl.BlockSpec((1, w_pad, chunk),
                         lambda i, a, b, c, d, e, f, g: (g[i], 0, 0)),
            pl.BlockSpec(memory_space=_HBM),   # DMA src for copies
        ],
        out_specs=[
            pl.BlockSpec(memory_space=_HBM),
            # spill: the store stays in HBM, written slot-by-slot by the
            # kernel's DMA ring. Otherwise a constant index map keeps
            # the compact store resident in VMEM for the whole pass,
            # written back once at the end.
            pl.BlockSpec(memory_space=_HBM) if spill else
            pl.BlockSpec(store_shape,
                         lambda i, a, b, c, d, e, f, g:
                         tuple(0 for _ in store_shape)),
        ],
        scratch_shapes=[
            pltpu.VMEM((4, w_pad, chunk), jnp.int32),
            pltpu.VMEM((4, w_pad, chunk), jnp.int32),   # flush bufs
            pltpu.VMEM(hacc_shape, jnp.float32),
            pltpu.VMEM(hstage_shape, jnp.float32),      # spill ring
            pltpu.SMEM((48,), jnp.int32),
            pltpu.SemaphoreType.DMA((14,)),
        ],
    )
    out, hist = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(records.shape, jnp.int32),
            jax.ShapeDtypeStruct(store_shape, jnp.float32),
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 << 20, has_side_effects=True),
        interpret=interpret,
    )(r1p, r2, blbr, meta, hslots, cbits, fetch_idx, records, records)
    hist = _hist_store_finalize(hist, num_slots, num_features,
                                b_pad, group, subbin)
    if spill:
        # HBM store slots are only written by visited blocks; mask the
        # rest to zero (non-spill zeroes the whole store in-kernel)
        visited = jnp.zeros((num_slots + 1,), jnp.int32) \
            .at[hslots & 0xFFFFFF].max(1)
        hist = jnp.where((visited[:num_slots] > 0)[:, None, None, None],
                         hist, 0.0)
    return out, hist


# ---------------------------------------------------------------------------
# physical left-count pass
# ---------------------------------------------------------------------------
def _count_kernel(r1_ref, r2_ref, meta_ref, wsel_ref, ks_ref,
                  cbits_ref, rec_ref, out_ref, cacc, *, chunk, dummy,
                  bits, bundled):
    """Exact i32 count of PHYSICAL rows routed left per selected split.

    Streams only each block's split-word sublane (4 B/row). Needed when
    the histogram count channel cannot drive the physical layout: bagging
    (counts there are in-bag only, gbdt.cpp:209-275) or n > 2^24 (f32
    count sums lose exactness)."""
    i = pl.program_id(0)
    meta = meta_ref[i]

    @pl.when(i == 0)
    def _():
        for k in range(out_ref.shape[0]):     # SMEM table: scalar clears
            out_ref[k] = 0

    @pl.when(((meta >> 20) & 1) != 0)
    def _():
        cacc[0] = 0

    @pl.when(ks_ref[i] != dummy)
    def _():
        # the fetched block is an 8-sublane window containing the split
        # word (TPU blocks must be 8-sublane-divisible); pick the word
        # with a static select chain on wsel & 7
        wsub = wsel_ref[i] & 7
        word = rec_ref[0, 0]
        for wj in range(1, 8):
            word = jnp.where(wsub == wj, rec_ref[0, wj], word)
        r1 = r1_ref[i]
        binv = (word >> ((r1 >> R_SHIFT) & 31)) & ((1 << bits) - 1)
        if bundled:
            binv = _unpack_bundle(binv, r2_ref[i])
        pos = lax.broadcasted_iota(jnp.int32, (1, chunk), 1)[0]
        valid = pos < (meta & ((1 << 20) - 1))
        catw = _cat_word(cbits_ref, ks_ref[i], binv)
        left = _goes_left(binv, r1, r2_ref[i], valid, catw)
        cacc[0] = cacc[0] + jnp.sum(left.astype(jnp.int32))

        @pl.when(((meta >> 21) & 1) != 0)          # block's last chunk
        def _():
            out_ref[ks_ref[i]] += cacc[0]


@functools.partial(jax.jit, static_argnames=("num_slots", "chunk",
                                             "bits", "bundled",
                                             "interpret"))
def count_pass(records, r1, r2, meta, wsel, kslots, cbits, num_slots,
               chunk, bits=8, bundled=False, interpret=False):
    """[num_slots] i32 physical left counts per compact slot id.

    kslots[i] = compact id of chunk i's selected split (num_slots =
    skip); r1/r2/meta/wsel as for move_pass (copy bit must be CLEAR for
    counted chunks)."""
    compile_cache.note_trace()
    nc = records.shape[0]
    w_pad = records.shape[1]
    kernel = functools.partial(_count_kernel, chunk=chunk,
                               dummy=num_slots, bits=bits,
                               bundled=bundled)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(nc,),
        in_specs=[pl.BlockSpec((1, 8, chunk),
                               lambda i, a, b, m, w, k, cb:
                               (i, w[i] >> 3, 0))],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        scratch_shapes=[pltpu.SMEM((8,), jnp.int32)],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_slots + 1,), jnp.int32),
        compiler_params=_CompilerParams(vmem_limit_bytes=100 << 20),
        interpret=interpret,
    )(r1, r2, meta, wsel, kslots, cbits, records)
    return out[:num_slots]


# ---------------------------------------------------------------------------
# slot-mapped histogram pass
# ---------------------------------------------------------------------------
def _slot_hist_kernel(slots_ref, meta_ref, rec_ref, out_ref, *,
                      num_features, b_pad, group, chunk, wcnt, dummy,
                      bag_lane, bits, grad_fn, num_class, gh_off,
                      subbin):
    i = pl.program_id(0)
    bpw = _bpw_for_bits(bits)
    bmask = (1 << bits) - 1

    @pl.when(i == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(slots_ref[i] != dummy)
    def _():
        rec = rec_ref[0]                              # [W, C]
        ks = slots_ref[i]
        g, h, valid = _payload_gh(rec, meta_ref[i] & ((1 << 20) - 1),
                                  chunk, wcnt, grad_fn, bag_lane,
                                  num_class, gh_off)
        gm = jnp.where(valid, g, 0.0)
        hm = jnp.where(valid, h, 0.0)
        cnt = valid.astype(jnp.float32)
        pay = jnp.stack([gm, hm, cnt], axis=0)
        pay6 = _hi_lo6(pay)                           # [6, C]

        def bin_of(f):
            return (rec[f // bpw, :] >> ((f % bpw) * bits)) & bmask

        def accum(idx, contrib):
            out_ref[ks, idx] += contrib

        _hist_accum(pay6, bin_of, accum, num_features, b_pad, group,
                    chunk, subbin)


@functools.partial(jax.jit, static_argnames=(
    "num_slots", "num_features", "b_pad", "chunk", "group", "wcnt",
    "bag_lane", "bits", "grad_fn", "num_class", "gh_off", "interpret",
    "subbin"))
def slot_hist_pass(records, slots, meta, num_slots, num_features, b_pad,
                   chunk, group, wcnt, bag_lane=-1, bits=8, grad_fn=None,
                   num_class=1, gh_off=2, interpret=False, subbin=False):
    """hist[num_slots, F, b_pad, 3] over the record matrix.

    slots[i] maps chunk i to its accumulation slot (a COMPACT id —
    num_slots must be small enough that the whole store fits VMEM, which
    holds for the root pass and per-round selections); chunks mapped to
    the DUMMY slot (== num_slots) are skipped. The store is VMEM-resident
    across the grid (constant out-spec) and zeroed once, so unvisited
    slots read as zero and chunk order is unconstrained.
    """
    compile_cache.note_trace()
    nc = records.shape[0]
    dummy = num_slots
    store_shape = _hist_store_shape(num_slots, num_features, b_pad,
                                    group, subbin)
    kernel = functools.partial(_slot_hist_kernel, num_features=num_features,
                               b_pad=b_pad, group=group, chunk=chunk,
                               wcnt=wcnt, dummy=dummy, bag_lane=bag_lane,
                               bits=bits, grad_fn=grad_fn,
                               num_class=num_class, gh_off=gh_off,
                               subbin=subbin)
    w_pad = records.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nc,),
        in_specs=[pl.BlockSpec((1, w_pad, chunk),
                               lambda i, s, m: (i, 0, 0))],
        out_specs=pl.BlockSpec(store_shape,
                               lambda i, s, m:
                               tuple(0 for _ in store_shape)),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(store_shape, jnp.float32),
        compiler_params=_CompilerParams(vmem_limit_bytes=100 << 20),
        interpret=interpret,
    )(slots, meta, records)
    return _hist_store_finalize(out, num_slots, num_features, b_pad,
                                group, subbin)


def aligned_available() -> bool:
    """True when the aligned pipeline's kernels can run natively."""
    if not HAS_PALLAS:
        return False
    try:
        return jax.devices()[0].platform in ("tpu", "axon") or \
            "TPU" in str(jax.devices()[0])
    except Exception:  # pragma: no cover
        return False
