"""Objective functions (gradients/hessians as jitted device programs).

Re-creates the reference objective zoo (`src/objective/*.hpp`, factory
`src/objective/objective_function.cpp:15`): regression L2/L1/huber/fair/
poisson/quantile/mape/gamma/tweedie, binary logloss, multiclass softmax/OVA,
cross-entropy (xentropy/xentlambda), and lambdarank. Interface mirrors
`include/LightGBM/objective_function.h:19-91`: `get_gradients`,
`boost_from_score`, `convert_output`, `is_constant_hessian`,
`num_model_per_iteration`, and the percentile-based `renew_tree_output` used
by L1/quantile/MAPE.

Scores are laid out `[num_tree_per_iteration, num_data]` (the reference's
flat `num_data * k + i` indexing, e.g. `multiclass_objective.hpp:80`).
"""
from __future__ import annotations

import functools
import math
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import compile_cache
from ..config import Config
from ..io.dataset import Metadata


def _sign(x):
    return jnp.sign(x)


class ObjectiveFunction:
    """Base class (reference objective_function.h:19)."""

    name = "none"
    is_constant_hessian = False
    is_renew_tree_output = False
    need_query = False

    def __init__(self, cfg: Config) -> None:
        self.cfg = cfg
        self.num_class = 1
        self.label: Optional[jax.Array] = None
        self.weight: Optional[jax.Array] = None
        self._label_np: Optional[np.ndarray] = None
        self._weight_np: Optional[np.ndarray] = None

    @property
    def num_model_per_iteration(self) -> int:
        return 1

    def init(self, metadata: Metadata, num_data: int) -> None:
        self._label_np = np.asarray(metadata.label, np.float32) \
            if metadata.label is not None else np.zeros(num_data, np.float32)
        self.label = jnp.asarray(self._label_np)
        if metadata.weight is not None:
            self._weight_np = np.asarray(metadata.weight, np.float32)
            self.weight = jnp.asarray(self._weight_np)

    def trace_signature(self) -> Tuple:
        """Hashable key covering everything this objective's gradient
        closures bake into a jax trace: the concrete class, its scalar
        parameters, and fingerprints of the label/weight/query data the
        closures capture as device constants. Two objectives with equal
        signatures may share one compiled gradient program."""
        sig = self.__dict__.get("_trace_sig")
        if sig is None:
            scalars = tuple(
                (k, v) for k, v in sorted(self.__dict__.items())
                if isinstance(v, (int, float, bool, str)))
            sig = ("obj", type(self).__name__, self.num_class,
                   self.weight is not None, scalars,
                   compile_cache.array_fingerprint(
                       self._label_np, self._weight_np,
                       getattr(self, "query_boundaries", None)))
            self.__dict__["_trace_sig"] = sig
        return sig

    # grad/hess: [K, N] given scores [K, N]. The public entry jits the
    # per-class `gradients_impl` once so the whole gradient computation
    # is ONE device program, not a chain of eager ops (each eager
    # dispatch costs a host round-trip on a tunneled TPU). The jitted
    # program lives in the process-wide registry keyed by the
    # objective's trace signature, so a second model over the same data
    # reuses it instead of retracing.
    def get_gradients(self, scores: jax.Array) -> Tuple[jax.Array, jax.Array]:
        fn = self.__dict__.get("_jit_gradients")
        if fn is None:
            impl = self.gradients_impl

            def traced(scores):
                compile_cache.note_trace()
                return impl(scores)

            fn = compile_cache.program(
                ("gradients", self.trace_signature()),
                lambda: jax.jit(traced))
            self.__dict__["_jit_gradients"] = fn
        return fn(scores)

    def gradients_impl(self, scores: jax.Array) -> Tuple[jax.Array, jax.Array]:
        g, h = self._point_grad(scores[0], self.label)
        if self.weight is not None:
            g = g * self.weight
            h = h * self.weight
        return g[None, :], h[None, :]

    def _point_grad(self, score, label):
        raise NotImplementedError

    def point_grad_fn(self):
        """Pure elementwise (score, label, weight|None) -> (g, h), or
        None when gradients are not pointwise (ranking, multiclass).
        The aligned builder (models/aligned_builder.py) evaluates
        gradients in PERMUTED row order, so the function must depend only
        on the per-row values, not on stored row-order arrays."""
        if type(self)._point_grad is ObjectiveFunction._point_grad:
            return None

        def fn(score, label, weight):
            g, h = self._point_grad(score, label)
            if weight is not None:
                g = g * weight
                h = h * weight
            return g, h
        return fn

    def mc_lane_mode(self):
        """How a K-class objective's per-class gradients read the
        aligned record (the engine's in-kernel multiclass hook):
        "prob" — from a per-class PROBABILITY lane written once per
        iteration from pre-iteration scores (softmax: cross-class
        coupling lives in the prob computation); "score" — from the
        class's own score lane (OVA: no cross-class coupling); None —
        not lane-wise (single-class, weighted)."""
        return None

    def prob_point_grad(self):
        """mc_lane_mode()=="prob": elementwise (p_k, is_label_k) ->
        (g, h), Pallas-traceable."""
        return None

    def score_point_grad(self, k: int):
        """mc_lane_mode()=="score": elementwise (s_k, is_label_k) ->
        (g, h) for class k, Pallas-traceable."""
        return None

    def boost_from_score(self, class_id: int) -> float:
        return 0.0

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        return raw

    def renew_tree_output(self, leaf_pred_values, row_leaf, scores) -> None:
        """Optional per-leaf output renewal (reference RenewTreeOutput)."""
        raise NotImplementedError

    def __str__(self) -> str:
        return self.name


# ---------------------------------------------------------------------------
# regression family (src/objective/regression_objective.hpp)
# ---------------------------------------------------------------------------
class RegressionL2(ObjectiveFunction):
    name = "regression"
    is_constant_hessian = True  # false when weighted; handled below

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.cfg.reg_sqrt:
            # sqrt transform of label (regression_objective.hpp:88-100)
            self._sqrt_sign = np.sign(self._label_np)
            self._label_np = (np.sign(self._label_np)
                              * np.sqrt(np.abs(self._label_np))).astype(
                                  np.float32)
            self.label = jnp.asarray(self._label_np)
        if self.weight is not None:
            self.is_constant_hessian = False

    def _point_grad(self, score, label):
        return score - label, jnp.ones_like(score)

    def boost_from_score(self, class_id):
        # weighted mean (regression_objective.hpp:156-177)
        if self._weight_np is not None:
            return float(np.sum(self._label_np * self._weight_np)
                         / np.sum(self._weight_np))
        return float(np.mean(self._label_np))

    def convert_output(self, raw):
        if self.cfg.reg_sqrt:
            return np.sign(raw) * raw * raw
        return raw


def _percentile(data: np.ndarray, alpha: float) -> float:
    """reference PercentileFun (regression_objective.hpp:18-44)."""
    n = len(data)
    if n <= 1:
        return float(data[0]) if n else 0.0
    s = np.sort(data)
    float_pos = (1.0 - alpha) * n
    pos = int(float_pos)
    if pos < 1:
        return float(s[-1])
    if pos >= n:
        return float(s[0])
    bias = float_pos - pos
    v1 = s[n - pos]
    v2 = s[n - pos - 1]
    # reference scans from the top for alpha-percentile of residuals
    return float(v1 - (v1 - v2) * bias)


def _weighted_percentile(data: np.ndarray, w: np.ndarray,
                         alpha: float) -> float:
    """reference WeightedPercentileFun (regression_objective.hpp:46-76)."""
    n = len(data)
    if n <= 1:
        return float(data[0]) if n else 0.0
    order = np.argsort(data, kind="stable")
    cdf = np.cumsum(w[order])
    threshold = cdf[-1] * alpha
    pos = int(np.searchsorted(cdf, threshold, side="right"))
    pos = min(pos, n - 1)
    if pos == 0 or pos == n - 1:
        return float(data[order[pos]])
    v1 = data[order[pos - 1]]
    v2 = data[order[pos]]
    if cdf[pos] <= cdf[pos - 1]:
        return float(v2)
    return float(v1 + (v2 - v1) * (threshold - cdf[pos - 1])
                 / (cdf[pos] - cdf[pos - 1]))


class _PercentileRenewMixin:
    """Leaf-output renewal by residual percentile (reference
    RegressionL1loss::RenewTreeOutput, regression_objective.hpp:233-268)."""
    is_renew_tree_output = True
    renew_alpha = 0.5

    def renew_leaf_output(self, residuals: np.ndarray,
                          weights: Optional[np.ndarray]) -> float:
        if len(residuals) == 0:
            return 0.0
        if weights is None:
            return _percentile(residuals, self.renew_alpha)
        return _weighted_percentile(residuals, weights, self.renew_alpha)

    def residual(self, label: np.ndarray, score: np.ndarray) -> np.ndarray:
        return label - score


class RegressionL1(_PercentileRenewMixin, RegressionL2):
    name = "regression_l1"
    is_constant_hessian = True

    def _point_grad(self, score, label):
        return _sign(score - label), jnp.ones_like(score)

    def boost_from_score(self, class_id):
        if self._weight_np is not None:
            return _weighted_percentile(self._label_np, self._weight_np, 0.5)
        return _percentile(self._label_np, 0.5)


class RegressionHuber(RegressionL2):
    name = "huber"
    is_constant_hessian = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.weight is not None:
            self.is_constant_hessian = False

    def _point_grad(self, score, label):
        a = self.cfg.alpha
        diff = score - label
        g = jnp.where(jnp.abs(diff) <= a, diff, _sign(diff) * a)
        return g, jnp.ones_like(score)


class RegressionFair(ObjectiveFunction):
    name = "fair"

    def _point_grad(self, score, label):
        c = self.cfg.fair_c
        x = score - label
        g = c * x / (jnp.abs(x) + c)
        h = c * c / ((jnp.abs(x) + c) ** 2)
        return g, h

    def boost_from_score(self, class_id):
        # fair: mean like L2? reference uses 0 (no BoostFromScore override ->
        # percentile? RegressionFairLoss overrides with 0 via base) — the
        # reference RegressionFairLoss inherits L2's mean boost.
        if self._weight_np is not None:
            return float(np.sum(self._label_np * self._weight_np)
                         / np.sum(self._weight_np))
        return float(np.mean(self._label_np))


class RegressionPoisson(ObjectiveFunction):
    name = "poisson"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(self._label_np < 0):
            raise ValueError("[poisson]: at least one target label is "
                             "negative")

    def _point_grad(self, score, label):
        g = jnp.exp(score) - label
        h = jnp.exp(score + self.cfg.poisson_max_delta_step)
        return g, h

    def boost_from_score(self, class_id):
        if self._weight_np is not None:
            mean = float(np.sum(self._label_np * self._weight_np)
                         / np.sum(self._weight_np))
        else:
            mean = float(np.mean(self._label_np))
        return math.log(max(mean, 1e-20))

    def convert_output(self, raw):
        return np.exp(raw)


class RegressionQuantile(_PercentileRenewMixin, ObjectiveFunction):
    name = "quantile"
    is_constant_hessian = True

    @property
    def renew_alpha(self):
        return self.cfg.alpha

    def _point_grad(self, score, label):
        a = self.cfg.alpha
        delta = score - label
        g = jnp.where(delta >= 0, 1.0 - a, -a)
        return g, jnp.ones_like(score)

    def boost_from_score(self, class_id):
        if self._weight_np is not None:
            return _weighted_percentile(self._label_np, self._weight_np,
                                        self.cfg.alpha)
        return _percentile(self._label_np, self.cfg.alpha)


class RegressionMAPE(_PercentileRenewMixin, ObjectiveFunction):
    name = "mape"
    is_constant_hessian = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        # label_weight = w / max(1, |label|) (regression_objective.hpp:575-589)
        w = (self._weight_np if self._weight_np is not None
             else np.ones(num_data, np.float32))
        self._label_weight_np = (w / np.maximum(1.0, np.abs(self._label_np))
                                 ).astype(np.float32)
        self._label_weight = jnp.asarray(self._label_weight_np)

    def gradients_impl(self, scores):
        diff = scores[0] - self.label
        g = _sign(diff) * self._label_weight
        h = self._label_weight
        return g[None, :], h[None, :]

    def boost_from_score(self, class_id):
        return _weighted_percentile(self._label_np, self._label_weight_np, 0.5)

    def renew_leaf_output(self, residuals, weights):
        # weights here are the label weights (hpp:640-658)
        return _weighted_percentile(residuals, weights, 0.5)


class RegressionGamma(ObjectiveFunction):
    name = "gamma"

    def _point_grad(self, score, label):
        g = 1.0 - label * jnp.exp(-score)
        h = label * jnp.exp(-score)
        return g, h

    def boost_from_score(self, class_id):
        if self._weight_np is not None:
            mean = float(np.sum(self._label_np * self._weight_np)
                         / np.sum(self._weight_np))
        else:
            mean = float(np.mean(self._label_np))
        return math.log(max(mean, 1e-20))

    def convert_output(self, raw):
        return np.exp(raw)


class RegressionTweedie(ObjectiveFunction):
    name = "tweedie"

    def _point_grad(self, score, label):
        rho = self.cfg.tweedie_variance_power
        e1 = jnp.exp((1 - rho) * score)
        e2 = jnp.exp((2 - rho) * score)
        g = -label * e1 + e2
        h = -label * (1 - rho) * e1 + (2 - rho) * e2
        return g, h

    def boost_from_score(self, class_id):
        if self._weight_np is not None:
            mean = float(np.sum(self._label_np * self._weight_np)
                         / np.sum(self._weight_np))
        else:
            mean = float(np.mean(self._label_np))
        return math.log(max(mean, 1e-20))

    def convert_output(self, raw):
        return np.exp(raw)


# ---------------------------------------------------------------------------
# binary (src/objective/binary_objective.hpp)
# ---------------------------------------------------------------------------
class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        pos = self._label_np > 0
        cnt_pos = int(pos.sum())
        cnt_neg = num_data - cnt_pos
        self._cnt_pos, self._cnt_neg = cnt_pos, cnt_neg
        # label weights (binary_objective.hpp:79-100)
        w_pos, w_neg = 1.0, 1.0
        if self.cfg.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_pos = 1.0
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
                w_neg = 1.0
        w_pos *= self.cfg.scale_pos_weight
        self._w_pos, self._w_neg = float(w_pos), float(w_neg)
        self._sign_label = jnp.where(jnp.asarray(pos), 1.0, -1.0)
        self._label_weight = jnp.where(jnp.asarray(pos), w_pos, w_neg)
        self.need_train = cnt_pos > 0 and cnt_neg > 0

    def point_grad_fn(self):
        sig = float(self.cfg.sigmoid)
        wp, wn = self._w_pos, self._w_neg

        def fn(score, label, weight):
            sl = jnp.where(label > 0, 1.0, -1.0)
            lw = jnp.where(label > 0, wp, wn)
            response = -sl * sig / (1.0 + jnp.exp(sl * sig * score))
            absr = jnp.abs(response)
            g = response * lw
            h = absr * (sig - absr) * lw
            if weight is not None:
                g = g * weight
                h = h * weight
            return g, h
        return fn

    def gradients_impl(self, scores):
        sig = self.cfg.sigmoid
        score = scores[0]
        label = self._sign_label
        response = -label * sig / (1.0 + jnp.exp(label * sig * score))
        absr = jnp.abs(response)
        g = response * self._label_weight
        h = absr * (sig - absr) * self._label_weight
        if self.weight is not None:
            g = g * self.weight
            h = h * self.weight
        return g[None, :], h[None, :]

    def boost_from_score(self, class_id):
        # weighted average prob -> log odds / sigmoid
        # (binary_objective.hpp:136-153)
        if self._weight_np is not None:
            suml = float(np.sum((self._label_np > 0) * self._weight_np))
            sumw = float(np.sum(self._weight_np))
        else:
            suml = float(self._cnt_pos)
            sumw = float(self._cnt_pos + self._cnt_neg)
        pavg = min(max(suml / max(sumw, 1e-20), 1e-15), 1 - 1e-15)
        return math.log(pavg / (1.0 - pavg)) / self.cfg.sigmoid

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.cfg.sigmoid * raw))


# ---------------------------------------------------------------------------
# multiclass (src/objective/multiclass_objective.hpp)
# ---------------------------------------------------------------------------
class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.num_class = cfg.num_class

    @property
    def num_model_per_iteration(self):
        return self.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        li = self._label_np.astype(np.int32)
        if li.min() < 0 or li.max() >= self.num_class:
            raise ValueError(f"Label must be in [0, {self.num_class})")
        self._label_int = jnp.asarray(li)
        probs = np.zeros(self.num_class)
        w = (self._weight_np if self._weight_np is not None
             else np.ones(num_data, np.float32))
        np.add.at(probs, li, w)
        self._class_init_probs = probs / probs.sum()

    def gradients_impl(self, scores):
        # scores [K, N]
        p = jax.nn.softmax(scores, axis=0)
        onehot = (jnp.arange(self.num_class)[:, None]
                  == self._label_int[None, :])
        g = p - onehot.astype(p.dtype)
        h = 2.0 * p * (1.0 - p)
        if self.weight is not None:
            g = g * self.weight[None, :]
            h = h * self.weight[None, :]
        return g, h

    def mc_lane_mode(self):
        """Softmax couples classes through p = softmax(s)
        (multiclass_objective.hpp:77-97): the engine writes per-class
        PROB lanes once per iteration from pre-iteration scores, so
        per-class gradients stay lane-local. Unweighted only (weights
        would need a weight lane the compact record does not carry)."""
        return None if self.weight is not None else "prob"

    def prob_point_grad(self):
        def fn(pk, is_label):
            g = pk - is_label.astype(pk.dtype)
            h = 2.0 * pk * (1.0 - pk)
            return g, h
        return fn

    def boost_from_score(self, class_id):
        # avg_output = log(class prob) (multiclass_objective.hpp:118-126)
        return math.log(max(self._class_init_probs[class_id], 1e-300))

    def convert_output(self, raw):
        # raw: [..., K] -> softmax over classes
        e = np.exp(raw - raw.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.num_class = cfg.num_class
        self._binary = [BinaryLogloss(cfg) for _ in range(cfg.num_class)]

    @property
    def num_model_per_iteration(self):
        return self.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        li = self._label_np.astype(np.int32)
        for k, b in enumerate(self._binary):
            md = Metadata(num_data)
            md.set_label((li == k).astype(np.float32))
            md.weight = metadata.weight
            b.init(md, num_data)

    def get_gradients(self, scores):
        gs, hs = [], []
        for k, b in enumerate(self._binary):
            g, h = b.get_gradients(scores[k:k + 1])
            gs.append(g[0])
            hs.append(h[0])
        return jnp.stack(gs), jnp.stack(hs)

    def mc_lane_mode(self):
        """One-vs-all: class k's binary logloss reads ONLY its own
        score lane (multiclass_objective.hpp:160-199) — no cross-class
        coupling, so gradients come straight from the score lane."""
        return None if self.weight is not None else "score"

    def score_point_grad(self, k):
        b = self._binary[k]
        sig = float(b.cfg.sigmoid)
        wp, wn = b._w_pos, b._w_neg

        def fn(sk, is_label):
            sl = jnp.where(is_label, 1.0, -1.0)
            lw = jnp.where(is_label, wp, wn)
            response = -sl * sig / (1.0 + jnp.exp(sl * sig * sk))
            absr = jnp.abs(response)
            return response * lw, absr * (sig - absr) * lw
        return fn

    def boost_from_score(self, class_id):
        return self._binary[class_id].boost_from_score(0)

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.cfg.sigmoid * raw))


# ---------------------------------------------------------------------------
# cross-entropy (src/objective/xentropy_objective.hpp)
# ---------------------------------------------------------------------------
class CrossEntropy(ObjectiveFunction):
    name = "xentropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(self._label_np < 0) or np.any(self._label_np > 1):
            raise ValueError("[xentropy]: labels must be in [0, 1]")

    def _point_grad(self, score, label):
        z = 1.0 / (1.0 + jnp.exp(-score))
        return z - label, z * (1.0 - z)

    def boost_from_score(self, class_id):
        # (xentropy_objective.hpp:116-133): log-odds of weighted mean label
        if self._weight_np is not None:
            suml = float(np.sum(self._label_np * self._weight_np))
            sumw = float(np.sum(self._weight_np))
        else:
            suml = float(np.sum(self._label_np))
            sumw = float(len(self._label_np))
        pavg = min(max(suml / max(sumw, 1e-20), 1e-15), 1 - 1e-15)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-raw))


class CrossEntropyLambda(ObjectiveFunction):
    name = "xentlambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(self._label_np < 0) or np.any(self._label_np > 1):
            raise ValueError("[xentlambda]: labels must be in [0, 1]")

    def gradients_impl(self, scores):
        """(xentropy_objective.hpp:185-224): weights act as exposure/trials
        under the log(1+exp(score)) link."""
        score = scores[0]
        label = self.label
        if self.weight is None:
            z = 1.0 / (1.0 + jnp.exp(-score))
            g = z - label
            h = z * (1.0 - z)
        else:
            # exact reference formulas (xentropy_objective.hpp:196-211)
            w = self.weight
            y = label
            epf = jnp.exp(score)
            hhat = jnp.log1p(epf)
            z = 1.0 - jnp.exp(-w * hhat)
            enf = 1.0 / epf
            g = (1.0 - y / z) * w / (1.0 + enf)
            c = 1.0 / (1.0 - z)
            d = 1.0 + epf
            a = w * epf / (d * d)
            d = c - 1.0
            b = (c / (d * d)) * (1.0 + w * epf - c)
            h = a * (1.0 + y * b)
        return g[None, :], h[None, :]

    def boost_from_score(self, class_id):
        if self._weight_np is not None:
            suml = float(np.sum(self._label_np * self._weight_np))
            sumw = float(np.sum(self._weight_np))
        else:
            suml = float(np.sum(self._label_np))
            sumw = float(len(self._label_np))
        pavg = min(max(suml / max(sumw, 1e-20), 1e-15), 1 - 1e-15)
        return math.log(math.log1p(pavg / (1.0 - pavg)))

    def convert_output(self, raw):
        return np.log1p(np.exp(raw))


# ---------------------------------------------------------------------------
# lambdarank (src/objective/rank_objective.hpp)
# ---------------------------------------------------------------------------
from . import pallas_rank
from .pallas_hist import pallas_available
from .ranking import (bucket_queries, dcg_discounts, max_dcg_at_k)


class LambdarankNDCG(ObjectiveFunction):
    name = "lambdarank"
    need_query = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("Lambdarank tasks require query information")
        self.query_boundaries = np.asarray(metadata.query_boundaries,
                                           np.int64)
        self.num_queries = len(self.query_boundaries) - 1
        label_gain = np.asarray(self.cfg.label_gain, np.float64)
        max_label = int(self._label_np.max())
        if max_label >= len(label_gain):
            raise ValueError("label_gain too short for labels")
        self.label_gain = label_gain
        # cached inverse max DCG at optimize position (rank_objective.hpp:60-69)
        k = self.cfg.max_position
        inv = np.zeros(self.num_queries, np.float64)
        for q in range(self.num_queries):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            m = max_dcg_at_k(k, self._label_np[lo:hi].astype(np.int64),
                             label_gain)
            inv[q] = 1.0 / m if m > 0 else 0.0
        self._inv_max_dcg = inv
        self._grad_fns: Dict[int, Callable] = {}
        self.num_data = num_data
        # --- segment-fused Pallas gradient path (ops/pallas_rank.py).
        # Mode resolution: "off" -> bucketed; "auto" -> fused iff a real
        # TPU is attached; "on" -> fused everywhere (interpret-mode
        # kernel on CPU, for tests/CI). Queries longer than
        # tpu_rank_tile stay on the bucketed path; a kernel failure at
        # first dispatch falls back wholesale (see get_gradients).
        self._fused_pack = None
        self._fused_dev = None
        self._fused_fn = None
        self._fused_interpret = False
        self.rank_fused_active = False
        self.rank_fused_fallback_queries = 0
        include = None
        mode = str(getattr(self.cfg, "tpu_rank_fused", "auto")).lower()
        on_tpu = pallas_available()
        if pallas_rank.HAS_PALLAS and (
                mode == "on" or (mode == "auto" and on_tpu)):
            tile = max(pallas_rank.SUBTILE,
                       int(getattr(self.cfg, "tpu_rank_tile", 512)))
            tile = -(-tile // pallas_rank.SUBTILE) * pallas_rank.SUBTILE
            pack = pallas_rank.pack_query_tiles(self.query_boundaries,
                                                tile)
            if pack.num_tiles > 0:
                self._fused_pack = pack
                self._fused_interpret = not on_tpu
                self.rank_fused_active = True
                self.rank_fused_fallback_queries = int(
                    pack.leftover.sum())
                from ..utils import log
                log.event("rank_fused", tiles=pack.num_tiles,
                          tile=pack.tile, band=int(pack.band),
                          fill_pct=round(100.0 * pack.fill, 1),
                          fallback_queries=self.rank_fused_fallback_queries,
                          interpret=self._fused_interpret)
                # only oversize leftovers keep a bucket ladder
                include = pack.leftover
        self._buckets = bucket_queries(self.query_boundaries,
                                       include=include)

    def _make_grad_fn(self, size: int):
        sig = float(self.cfg.sigmoid)
        gains = jnp.asarray(self.label_gain, jnp.float32)
        disc = jnp.asarray(dcg_discounts(size), jnp.float32)

        @jax.jit
        def per_bucket(scores_q, labels_q, mask_q, inv_q):
            # scores_q [Q, S]; labels_q int32; mask_q bool; inv_q [Q]
            compile_cache.note_trace()
            neg_inf = jnp.float32(-np.inf)
            s = jnp.where(mask_q, scores_q, neg_inf)
            order = jnp.argsort(-s, axis=1, stable=True)   # desc, pads last
            ss = jnp.take_along_axis(s, order, 1)          # sorted scores
            sl = jnp.take_along_axis(
                jnp.where(mask_q, labels_q, -1), order, 1)  # sorted labels
            cnt = mask_q.sum(axis=1).astype(jnp.int32)
            valid_s = jnp.arange(size)[None, :] < cnt[:, None]
            best = ss[:, 0]
            worst_pos = jnp.maximum(cnt - 1, 0)
            worst = jnp.take_along_axis(ss, worst_pos[:, None], 1)[:, 0]
            norm_on = best != worst
            gain_s = gains[jnp.clip(sl, 0, gains.shape[0] - 1)]
            # pair tensors [Q, S(high), S(low)] in BF16: the O(S^2) exp +
            # divide chain is the per-iteration hot spot at MSLR scale
            # (measured ~270 ms/iter in f32); the reference itself
            # quantizes the sigmoid through a lookup table
            # (rank_objective.hpp:71), so ~8-bit pair factors are within
            # its own tolerance. Reductions accumulate in f32. Score
            # DIFFERENCES are formed in f32 first (bf16 subtraction of
            # near-equal scores would cancel catastrophically), only the
            # results are narrowed.
            bf = jnp.bfloat16
            ds = (ss[:, :, None] - ss[:, None, :]).astype(bf)
            gain_b = gain_s.astype(bf)
            dgap = gain_b[:, :, None] - gain_b[:, None, :]
            pd = jnp.abs(disc[None, :, None]
                         - disc[None, None, :]).astype(bf)
            delta_ndcg = dgap * pd * inv_q[:, None, None].astype(bf)
            delta_ndcg = jnp.where(norm_on[:, None, None],
                                   delta_ndcg / (0.01 + jnp.abs(ds)),
                                   delta_ndcg)
            p_lambda = (2.0 / (1.0 + jnp.exp(
                (2.0 * sig) * ds.astype(jnp.float32)))).astype(bf)
            p_hess = p_lambda * (2.0 - p_lambda)
            pair_valid = ((sl[:, :, None] > sl[:, None, :])
                          & valid_s[:, :, None] & valid_s[:, None, :])
            lam = jnp.where(pair_valid, -p_lambda * delta_ndcg,
                            jnp.asarray(0.0, bf))
            hes = jnp.where(pair_valid, p_hess * 2.0 * delta_ndcg,
                            jnp.asarray(0.0, bf))
            # high gets +lam, low gets -lam; both get +hes
            g_sorted = (lam.sum(axis=2, dtype=jnp.float32)
                        - lam.sum(axis=1, dtype=jnp.float32))
            h_sorted = (hes.sum(axis=2, dtype=jnp.float32)
                        + hes.sum(axis=1, dtype=jnp.float32))
            # unsort back to doc positions
            inv_order = jnp.argsort(order, axis=1)
            g = jnp.take_along_axis(g_sorted, inv_order, 1)
            hh = jnp.take_along_axis(h_sorted, inv_order, 1)
            return (jnp.where(mask_q, g, 0.0), jnp.where(mask_q, hh, 0.0))

        return per_bucket

    def _bucket_dev_tables(self):
        """Device-resident per-bucket constants (doc ids, labels, masks,
        inv max DCG) — uploaded ONCE; re-uploading them per iteration put
        ~30 MB/iter on the host link and dominated ranking training."""
        tabs = getattr(self, "_bucket_dev", None)
        if tabs is None:
            tabs = {}
            # the first get_gradients call may run under an outer jit
            # trace (the device-time harness chains it in a fori_loop);
            # without the eval guard these "constants" would be cached
            # as that trace's tracers and leak into the next one
            with jax.ensure_compile_time_eval():
                for size, (qids, doc_idx, mask) in self._buckets.items():
                    tabs[size] = (
                        jnp.asarray(doc_idx),
                        jnp.asarray(
                            self._label_np[doc_idx].astype(np.int32)),
                        jnp.asarray(mask),
                        jnp.asarray(self._inv_max_dcg[qids],
                                    jnp.float32))
            self._bucket_dev = tabs
        return tabs

    def _fused_dev_tables(self):
        """Device-resident per-slot constants for the fused kernel
        (doc ids, query ids, label gains, labels, inv max DCG, discount
        table) — uploaded once, like `_bucket_dev_tables`."""
        tabs = self._fused_dev
        if tabs is None:
            pack = self._fused_pack
            real = pack.qid >= 0
            lab = np.where(
                real, self._label_np[pack.doc_idx].astype(np.int32), -1)
            gain = np.where(
                real,
                self.label_gain[np.clip(lab, 0, None)].astype(np.float32),
                0.0).astype(np.float32)
            inv = np.where(
                real,
                self._inv_max_dcg[np.clip(pack.qid, 0, None)],
                0.0).astype(np.float32)
            # see _bucket_dev_tables: cached constants must be concrete
            # even when the first call runs under an outer trace
            with jax.ensure_compile_time_eval():
                tabs = (jnp.asarray(pack.doc_idx),
                        jnp.asarray(pack.qid),
                        jnp.asarray(gain), jnp.asarray(lab),
                        jnp.asarray(inv),
                        jnp.asarray(
                            pallas_rank.discount_table(pack.tile)))
            self._fused_dev = tabs
        return tabs

    def _fused_grads(self, score):
        pack = self._fused_pack
        fn = self._fused_fn
        if fn is None:
            lut = int(getattr(self.cfg, "tpu_rank_sigmoid_bins", 0))
            fn = compile_cache.program(
                pallas_rank.fused_program_key(
                    self.num_data, pack, float(self.cfg.sigmoid), lut,
                    self._fused_interpret),
                lambda: pallas_rank.make_fused_grad_fn(
                    self.num_data, pack.num_tiles, pack.tile,
                    int(pack.band), float(self.cfg.sigmoid), lut,
                    interpret=self._fused_interpret))
            self._fused_fn = fn
        return fn(score, *self._fused_dev_tables())

    def _fused_disable(self, err):
        """Kernel build/dispatch failed: fall back to the bucketed path
        wholesale (rebuild the full ladder) and keep training."""
        from ..utils import log
        log.warning(f"fused lambdarank kernel failed "
                    f"({type(err).__name__}: {err}); falling back to "
                    f"the bucketed path")
        log.event("rank_fused", fallback="kernel_error",
                  error=type(err).__name__)
        self.rank_fused_active = False
        self._fused_pack = None
        self._fused_dev = None
        self._fused_fn = None
        self._buckets = bucket_queries(self.query_boundaries)
        self._bucket_dev = None

    def get_gradients(self, scores):
        score = scores[0]
        g = h = None
        if self.rank_fused_active:
            try:
                g, h = self._fused_grads(score)
            except Exception as err:  # noqa: BLE001 - wholesale fallback
                self._fused_disable(err)
        if g is None:
            g = jnp.zeros_like(score)
            h = jnp.zeros_like(score)
        for size, (didx, labels_q, mask, inv) in \
                self._bucket_dev_tables().items():
            fn = self._grad_fns.get(size)
            if fn is None:
                # per-bucket programs capture only cfg-derived constants
                # (sigmoid, label_gain, discounts) — bucket data arrives
                # as runtime args — so they dedup across models by size.
                fn = compile_cache.program(
                    ("rank_bucket", size, float(self.cfg.sigmoid),
                     tuple(float(g) for g in self.label_gain)),
                    lambda: self._make_grad_fn(size))
                self._grad_fns[size] = fn
            sc = score[didx] * mask  # [Q, S]
            gq, hq = fn(sc, labels_q, mask, inv)
            flat_idx = didx.reshape(-1)
            g = g.at[flat_idx].add(gq.reshape(-1))
            h = h.at[flat_idx].add(hq.reshape(-1))
        if self.weight is not None:
            g = g * self.weight
            h = h * self.weight
        return g[None, :], h[None, :]


# ---------------------------------------------------------------------------
_OBJECTIVES = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "quantile": RegressionQuantile,
    "mape": RegressionMAPE,
    "gamma": RegressionGamma,
    "tweedie": RegressionTweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "xentropy": CrossEntropy,
    "xentlambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
}


def create_objective(cfg: Config) -> Optional[ObjectiveFunction]:
    """reference ObjectiveFunction::CreateObjectiveFunction
    (objective_function.cpp:15)."""
    if cfg.objective in ("none", ""):
        return None
    cls = _OBJECTIVES.get(cfg.objective)
    if cls is None:
        raise ValueError(f"Unknown objective: {cfg.objective}")
    return cls(cfg)
