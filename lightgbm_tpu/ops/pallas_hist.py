"""Pallas TPU histogram kernel — the `ocl/histogram256.cl` analogue.

The reference GPU learner builds per-leaf gradient/hessian histograms with
hand-written OpenCL kernels using workgroup-local memory and float atomics
(`src/treelearner/ocl/histogram256.cl:100-125,350`). TPU has no fast
scatter-add, so the kernel keeps the histogram accumulator **resident in
VMEM across the whole row stream** and converts the scatter into per-feature
one-hot contractions on the MXU:

    for each row-chunk (grid dim, pipelined HBM->VMEM by pallas):
        for each feature f (static unroll):
            onehot[c, b] = (bins[c, f] == b)          # VPU compare vs iota
            hist[f] += onehot^T @ payload[c, {g,h,1}]  # MXU [B,C]x[C,W]

Unlike the XLA einsum formulation (`ops/histogram.py`), the one-hot tile
never leaves VMEM and the accumulator is written to HBM exactly once, at the
last grid step. Numerics: the one-hot is exact in bf16; payload rides as
hi/lo bf16 pairs (two extra columns) so the f32-accumulated result matches
the reference's single-precision GPU histograms (`gpu_use_dp=0`) or better.

Used via `Config.tpu_use_pallas`; the einsum path stays the fallback (and
the only path on CPU test meshes, where pallas TPU kernels can't lower).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:  # pallas is TPU-only here; import lazily-guarded for CPU test runs
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
    # jax renamed TPUCompilerParams -> CompilerParams (and grew fields
    # like has_side_effects along the way). Accept either vintage.
    _CP_CLS = getattr(pltpu, "CompilerParams",
                      getattr(pltpu, "TPUCompilerParams", None))

    def _CompilerParams(**kw):
        import dataclasses
        known = {f.name for f in dataclasses.fields(_CP_CLS)}
        return _CP_CLS(**{k: v for k, v in kw.items() if k in known})
except Exception:  # pragma: no cover
    HAS_PALLAS = False

NUM_STATS = 3  # grad, hess, count


def _hist_kernel(bins_ref, pay_ref, out_ref, *, num_features: int,
                 max_bin: int, payload_width: int):
    """One grid step: accumulate a row-chunk into the VMEM-resident
    histogram. bins_ref [C, F] uint8; pay_ref [C, W]; out_ref [F, B, W].

    Invalid rows need no bin masking: their payload columns (g, h, count)
    are all zero, so whatever bin they land in receives zeros.
    """
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bins = bins_ref[...].astype(jnp.int32)
    pay_f32 = pay_ref[...]                      # [C, 3] f32 (g, h, cnt)
    # hi/lo bf16 split INSIDE the kernel: done outside, XLA's algebraic
    # simplifier cancels the f32->bf16->f32 round-trip and silently drops
    # the low parts; Mosaic keeps the conversions explicit
    p_hi = pay_f32.astype(jnp.bfloat16)
    p_lo = (pay_f32 - p_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    pay = jnp.concatenate([p_hi, p_lo], axis=1)  # [C, 6] bf16
    chunk = bins.shape[0]
    iota = lax.broadcasted_iota(jnp.int32, (chunk, max_bin), 1)
    for f in range(num_features):
        onehot = (bins[:, f][:, None] == iota).astype(jnp.bfloat16)
        # [B, 2W] = [C, B]^T x [C, 2W] on the MXU, f32 accumulation
        contrib = lax.dot_general(
            onehot, pay, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        out_ref[f, :, :] += contrib


def _subbin_body(bin_of, pay_ref, out_ref, num_features: int):
    """Shared sub-binned accumulation body (max_bin > 128): bin =
    hi*16 + lo. Instead of a B-wide one-hot (256 VPU compares per
    row/feature), the payload rides the 16-wide HI one-hot
    (Z = pay6 x oh_hi -> [96, C], zero-padded to a full [128, C] tile)
    and ONE MXU contraction against the 16-wide LO one-hot lands the
    whole [16, 128] = [lo, pay*16 + hi] sub-bin tile — 32 compares and
    exactly two f32 VMEM tiles per feature. `bin_of(f)` -> [C] i32
    lane-oriented bin values; pay_ref [3, C] (payload TRANSPOSED so the
    hi/lo split concatenates on sublanes, no in-kernel relayout)."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    pay_f32 = pay_ref[...]                       # [3, C]
    p_hi = pay_f32.astype(jnp.bfloat16)
    p_lo = (pay_f32 - p_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    pay6 = jnp.concatenate([p_hi, p_lo], axis=0)  # [6, C]
    C = pay_f32.shape[1]
    iota16 = lax.broadcasted_iota(jnp.int32, (16, C), 0)
    for f in range(num_features):
        bv = bin_of(f)
        oh_hi = ((bv >> 4)[None, :] == iota16).astype(jnp.bfloat16)
        oh_lo = ((bv & 15)[None, :] == iota16).astype(jnp.bfloat16)
        Z = (pay6[:, None, :] * oh_hi[None, :, :]).reshape(96, C)
        Zp = jnp.concatenate(
            [Z, jnp.zeros((32, C), jnp.bfloat16)], axis=0)
        contrib = lax.dot_general(oh_lo, Zp, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        out_ref[f] += contrib


def _subbin_rows_kernel(bins_ref, pay_ref, out_ref, *,
                        num_features: int):
    """Sub-binned kernel over gathered [C, F] uint8 rows."""
    bins = bins_ref[...].astype(jnp.int32)
    _subbin_body(lambda f: bins[:, f], pay_ref, out_ref, num_features)


def _subbin_words_kernel(*refs, num_features: int, wcnt: int):
    """Sub-binned kernel over packed lane-oriented bin words."""
    word_refs = refs[:wcnt]
    pay_ref = refs[wcnt]
    out_ref = refs[wcnt + 1]

    def bin_of(f):
        w = word_refs[f >> 2][0, :]
        return (w >> ((f & 3) * 8)) & 255

    _subbin_body(bin_of, pay_ref, out_ref, num_features)


def _subbin_finalize(out, num_features: int, max_bin: int) -> jax.Array:
    """[F, 16, 128] = [lo, pay*16 + hi] sub-bin tiles -> [F, max_bin, 3]
    (fold hi/lo payload halves, land bin = hi*16 + lo) — once per call,
    not per chunk."""
    h = out[..., :96].reshape(num_features, 16, 6, 16)
    h = h[:, :, :NUM_STATS] + h[:, :, NUM_STATS:]    # [F, lo, 3, hi]
    h = jnp.transpose(h, (0, 3, 1, 2))               # [F, hi, lo, 3]
    return h.reshape(num_features, 256, NUM_STATS)[:, :max_bin]


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "chunk", "subbin",
                                    "interpret"))
def pallas_histogram(bins_rows: jax.Array, gh: jax.Array, valid: jax.Array,
                     max_bin: int, chunk: int = 1 << 11,
                     subbin: bool = True, interpret: bool = False
                     ) -> jax.Array:
    """hist[F, max_bin, 3] over contiguous (already gathered) rows.

    bins_rows: uint8 [P, F]; gh: f32 [P, 2]; valid: bool [P].
    Same contract as `histogram_from_gathered_gh`. The kernel reads the
    uint8 matrix directly (no int32 copy of the full array — at 10M rows
    that copy alone quadruples HBM traffic and can OOM); rows are processed
    in VMEM-sized chunks with the accumulator resident in VMEM.
    """
    p, f = bins_rows.shape
    if bins_rows.dtype != jnp.uint8:
        bins_rows = bins_rows.astype(jnp.uint8)
    if jnp.issubdtype(gh.dtype, jnp.integer):
        # quantized int8/int16 payload (ops/histogram.quantize_gh): the
        # bandwidth win already happened at the per-leaf gather; the
        # kernel accumulates the exact integer values in f32
        gh = gh.astype(jnp.float32)
    g = jnp.where(valid, gh[:, 0], 0.0)
    h = jnp.where(valid, gh[:, 1], 0.0)
    cnt = valid.astype(jnp.float32)
    pay = jnp.stack([g, h, cnt], axis=1)         # f32; hi/lo split in-kernel
    # bin axis padded to a 128-lane multiple: unaligned one-hot tiles force
    # awkward VMEM layouts (scoped-vmem OOM at max_bin=255)
    b_pad = max(128, ((max_bin + 127) // 128) * 128)
    n_chunks = max(1, (p + chunk - 1) // chunk)
    pad = n_chunks * chunk - p
    if pad:
        # pad rows as INVALID (zero payload) — bins may be any in-range value
        bins_rows = jnp.pad(bins_rows, ((0, pad), (0, 0)))
        pay = jnp.pad(pay, ((0, pad), (0, 0)))

    if subbin and b_pad > 128:
        kernel = functools.partial(_subbin_rows_kernel, num_features=f)
        out = pl.pallas_call(
            kernel,
            grid=(n_chunks,),
            in_specs=[
                pl.BlockSpec((chunk, f), lambda i: (i, 0)),
                pl.BlockSpec((NUM_STATS, chunk), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((f, 16, 128), lambda i: (0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((f, 16, 128), jnp.float32),
            compiler_params=_CompilerParams(vmem_limit_bytes=100 << 20),
            interpret=interpret,
        )(bins_rows, pay.T)
        return _subbin_finalize(out, f, max_bin)

    w = 2 * NUM_STATS
    kernel = functools.partial(_hist_kernel, num_features=f, max_bin=b_pad,
                               payload_width=w)
    out = pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((chunk, f), lambda i: (i, 0)),
            pl.BlockSpec((chunk, NUM_STATS), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((f, b_pad, w), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((f, b_pad, w), jnp.float32),
        compiler_params=_CompilerParams(vmem_limit_bytes=100 << 20),
        interpret=interpret,
    )(bins_rows, pay)
    # fold the lo-parts back into the hi sums; drop the bin padding
    return (out[..., :NUM_STATS] + out[..., NUM_STATS:])[:, :max_bin, :]


def _hist_words_kernel(*refs, num_features: int, max_bin: int,
                       wcnt: int):
    """Transposed-layout word kernel: per feature, a lane-oriented row
    slice of the packed words is unpacked with shift/mask (no column
    relayout), compared against a sublane iota into a [B, C] one-hot, and
    contracted on the MXU against the [C, 6] hi/lo payload."""
    word_refs = refs[:wcnt]
    pay_ref = refs[wcnt]
    out_ref = refs[wcnt + 1]
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    pay_f32 = pay_ref[...]                       # [C, 3]
    p_hi = pay_f32.astype(jnp.bfloat16)
    p_lo = (pay_f32 - p_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    pay = jnp.concatenate([p_hi, p_lo], axis=1)  # [C, 6]
    chunk = pay_f32.shape[0]
    iota = lax.broadcasted_iota(jnp.int32, (max_bin, chunk), 0)
    for f in range(num_features):
        w = word_refs[f >> 2][0, :]              # [C] int32, lane-oriented
        col = (w >> ((f & 3) * 8)) & 255
        onehot = (col[None, :] == iota).astype(jnp.bfloat16)   # [B, C]
        contrib = lax.dot_general(onehot, pay, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        out_ref[f] += contrib                    # [B, 6]


@functools.partial(jax.jit, static_argnames=("num_features", "max_bin",
                                             "chunk", "subbin",
                                             "interpret"))
def pallas_histogram_words(words, g: jax.Array, h: jax.Array,
                           valid: jax.Array, num_features: int,
                           max_bin: int, chunk: int = 1 << 11,
                           subbin: bool = True, interpret: bool = False
                           ) -> jax.Array:
    """hist[F, max_bin, 3] over packed bin words (see
    `histogram.histogram_from_words` for the layout contract)."""
    p = g.shape[0]
    wcnt = len(words)
    gm = jnp.where(valid, g, 0.0)
    hm = jnp.where(valid, h, 0.0)
    pay = jnp.stack([gm, hm, valid.astype(jnp.float32)], axis=1)
    b_pad = max(128, ((max_bin + 127) // 128) * 128)
    n_chunks = max(1, (p + chunk - 1) // chunk)
    pad = n_chunks * chunk - p
    words2 = [w.reshape(1, p) for w in words]
    if pad:
        words2 = [jnp.pad(w, ((0, 0), (0, pad))) for w in words2]
        pay = jnp.pad(pay, ((0, pad), (0, 0)))
    if subbin and b_pad > 128:
        kernel = functools.partial(_subbin_words_kernel,
                                   num_features=num_features, wcnt=wcnt)
        out = pl.pallas_call(
            kernel,
            grid=(n_chunks,),
            in_specs=[pl.BlockSpec((1, chunk), lambda i: (0, i))
                      for _ in range(wcnt)]
            + [pl.BlockSpec((NUM_STATS, chunk), lambda i: (0, i))],
            out_specs=pl.BlockSpec((num_features, 16, 128),
                                   lambda i: (0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((num_features, 16, 128),
                                           jnp.float32),
            compiler_params=_CompilerParams(vmem_limit_bytes=100 << 20),
            interpret=interpret,
        )(*words2, pay.T)
        return _subbin_finalize(out, num_features, max_bin)
    kernel = functools.partial(_hist_words_kernel,
                               num_features=num_features, max_bin=b_pad,
                               wcnt=wcnt)
    out = pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=[pl.BlockSpec((1, chunk), lambda i: (0, i))
                  for _ in range(wcnt)]
        + [pl.BlockSpec((chunk, NUM_STATS), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((num_features, b_pad, 6),
                               lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((num_features, b_pad, 6),
                                       jnp.float32),
        compiler_params=_CompilerParams(vmem_limit_bytes=100 << 20),
        interpret=interpret,
    )(*words2, pay)
    return (out[..., :NUM_STATS] + out[..., NUM_STATS:])[:, :max_bin, :]


def pallas_available() -> bool:
    """True when a TPU backend is attached and pallas can lower."""
    if not HAS_PALLAS:
        return False
    try:
        return jax.devices()[0].platform in ("tpu", "axon") or \
            "TPU" in str(jax.devices()[0])
    except Exception:  # pragma: no cover
        return False
