"""SHAP feature contributions for tree ensembles.

Re-creates the reference `PredictContrib` path (`tree.h:123`,
`tree.cpp TreeSHAP` — the Lundberg & Lee exact TreeSHAP recursion the
reference vendored): per-row, per-tree recursive path-weight computation,
plus the expected-value base term in the last output column.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..models.tree import Tree


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction",
                 "pweight")

    def __init__(self, f=-1, z=0.0, o=0.0, w=0.0):
        self.feature_index = f
        self.zero_fraction = z
        self.one_fraction = o
        self.pweight = w


def _extend_path(path: List[_PathElement], unique_depth: int,
                 zero_fraction: float, one_fraction: float,
                 feature_index: int) -> None:
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) \
            / (unique_depth + 1)
        path[i].pweight = zero_fraction * path[i].pweight \
            * (unique_depth - i) / (unique_depth + 1)


def _unwind_path(path: List[_PathElement], unique_depth: int,
                 path_index: int) -> None:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (unique_depth + 1) \
                / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction \
                * (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = path[i].pweight * (unique_depth + 1) \
                / (zero_fraction * (unique_depth - i))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int,
                      path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (unique_depth + 1) \
                / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction \
                * (unique_depth - i) / (unique_depth + 1)
        else:
            total += path[i].pweight / (zero_fraction
                                        * (unique_depth - i)
                                        / (unique_depth + 1))
    return total


def _expected_value(tree: Tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_value[~node])
    lc = int(tree.left_child[node])
    rc = int(tree.right_child[node])
    lcount = _node_count(tree, lc)
    rcount = _node_count(tree, rc)
    total = lcount + rcount
    if total <= 0:
        return 0.0
    return (_expected_value(tree, lc) * lcount
            + _expected_value(tree, rc) * rcount) / total


def _node_count(tree: Tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_count[~node])
    return float(tree.internal_count[node])


def _tree_shap(tree: Tree, x: np.ndarray, phi: np.ndarray, node: int,
               unique_depth: int, parent_path: List[_PathElement],
               parent_zero_fraction: float, parent_one_fraction: float,
               parent_feature_index: int,
               mean_values: dict) -> None:
    path = [_PathElement(p.feature_index, p.zero_fraction, p.one_fraction,
                         p.pweight) for p in parent_path[:unique_depth]]
    path += [_PathElement() for _ in range(unique_depth, tree.num_leaves + 2)]
    _extend_path(path, unique_depth, parent_zero_fraction,
                 parent_one_fraction, parent_feature_index)

    if node < 0:  # leaf
        leaf_value = float(tree.leaf_value[~node])
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction
                                          - el.zero_fraction) * leaf_value
        return

    # internal node: which child does x go to?
    hot = _decide(tree, node, x)
    cold = (tree.right_child[node] if hot == tree.left_child[node]
            else tree.left_child[node])
    hot_count = _node_count(tree, int(hot))
    cold_count = _node_count(tree, int(cold))
    total = _node_count(tree, node)
    hot_zero = hot_count / total if total > 0 else 0.0
    cold_zero = cold_count / total if total > 0 else 0.0
    incoming_zero, incoming_one = 1.0, 1.0
    feature = int(tree.split_feature[node])
    # undo duplicated feature on the path
    path_index = next((i for i in range(1, unique_depth + 1)
                       if path[i].feature_index == feature), -1)
    if path_index >= 0:
        incoming_zero = path[path_index].zero_fraction
        incoming_one = path[path_index].one_fraction
        _unwind_path(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap(tree, x, phi, int(hot), unique_depth + 1, path,
               hot_zero * incoming_zero, incoming_one, feature, mean_values)
    _tree_shap(tree, x, phi, int(cold), unique_depth + 1, path,
               cold_zero * incoming_zero, 0.0, feature, mean_values)


def _decide(tree: Tree, node: int, x: np.ndarray) -> int:
    return tree._decision(float(x[tree.split_feature[node]]), node)


def predict_contrib(trees: List[Tree], X: np.ndarray,
                    num_class: int = 1) -> np.ndarray:
    """Returns [N, (F+1)] (or [N, K*(F+1)] for multiclass): per-feature SHAP
    values plus the expected-value column (reference c_api predict contrib
    layout)."""
    X = np.asarray(X, np.float64)
    n, f = X.shape
    out = np.zeros((n, num_class, f + 1), np.float64)
    for ti, tree in enumerate(trees):
        cls = ti % num_class
        if tree.num_leaves <= 1:
            out[:, cls, f] += tree.leaf_value[0]
            continue
        base = _expected_value(tree, 0)
        for r in range(n):
            phi = np.zeros(f + 1)
            phi[f] += base
            _tree_shap(tree, X[r], phi, 0, 0, [], 1.0, 1.0, -1, {})
            out[r, cls] += phi
    if num_class == 1:
        return out[:, 0, :]
    return out.reshape(n, num_class * (f + 1))
