"""Leaf partition of the training rows.

Re-creates the reference `DataPartition` (`src/treelearner/data_partition.hpp`)
+ `DenseBin::Split` routing (`src/io/dense_bin.hpp:195-255`): a permuted
row-index array where each leaf's rows are contiguous, with host-side
(begin, count) bookkeeping. The split is a stable two-way partition done on
device via a 3-key stable argsort, so rows belonging to other leaves inside
the padded slice keep their position.

Routing semantics (unpacked single-feature bins; reference offsets/bias
collapse away):
- numerical, missing None : bin <= threshold -> left
- numerical, missing Zero : bin == default_bin -> default side; else <= thr
- numerical, missing NaN  : bin == num_bin-1 (NaN bin) -> default side;
                            else <= thr
- categorical             : bin in threshold-set -> left (bitset,
                            `SplitCategorical`, dense_bin.hpp:256-283)
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

MISSING_NONE_C, MISSING_ZERO_C, MISSING_NAN_C = 0, 1, 2


def numerical_goes_left(binvals: jax.Array, threshold, default_left,
                        missing_type, default_bin, num_bin) -> jax.Array:
    base = binvals <= threshold
    is_default = jnp.where(
        missing_type == MISSING_ZERO_C, binvals == default_bin,
        jnp.where(missing_type == MISSING_NAN_C, binvals == num_bin - 1,
                  False))
    return jnp.where(is_default, default_left, base)


def categorical_goes_left(binvals: jax.Array, bitset: jax.Array) -> jax.Array:
    """bitset: uint32[words]; left iff bit `bin` set (reference
    Common::FindInBitset, utils/common.h)."""
    word = (binvals >> 5).astype(jnp.int32)
    bit = (binvals & 31).astype(jnp.uint32)
    w = bitset[jnp.clip(word, 0, bitset.shape[0] - 1)]
    hit = ((w >> bit) & jnp.uint32(1)) != 0
    return hit & (word < bitset.shape[0])


def bundle_unpack(raw, boff, bpk, default_bin, num_bin):
    """Bundled storage column -> the feature's own bin (io/bundling.py
    layout: the feature owns [boff, boff + num_bin - 1) with the default
    bin skipped; anything outside its range means default)."""
    p = raw - boff
    in_range = (p >= 0) & (p < num_bin - 1)
    b = jnp.where(p >= default_bin, p + 1, p)
    unpacked = jnp.where(in_range, b, default_bin)
    return jnp.where(bpk != 0, unpacked, raw)


@functools.partial(jax.jit, static_argnames=("padded",))
def split_partition(indices: jax.Array, bins_col: jax.Array,
                    begin: jax.Array,
                    count: jax.Array, padded: int, threshold: jax.Array,
                    default_left: jax.Array, missing_type: jax.Array,
                    default_bin: jax.Array, num_bin: jax.Array,
                    is_categorical: jax.Array,
                    cat_bitset: jax.Array,
                    bundle_off: jax.Array = 0,
                    bundle_packed: jax.Array = 0
                    ) -> Tuple[jax.Array, jax.Array]:
    """Stable-partition one leaf's slice of the global index array.

    indices:  int32 [N_pad] permuted row ids (leaf rows contiguous)
    bins_col: uint8/int32 [N] the split feature's bin column (a contiguous
        dynamic_slice row of the transposed bins)
    begin/count: dynamic scalars; padded: static slice length >= count
    cat_bitset: uint32[8] (covers 256 bins) — ignored for numerical

    Returns (new_indices, left_count).
    """
    idx = lax.dynamic_slice(indices, (begin,), (padded,))
    pos = jnp.arange(padded, dtype=jnp.int32)
    valid = pos < count
    safe = jnp.where(valid, idx, 0)
    b = bins_col[safe].astype(jnp.int32)
    b = bundle_unpack(b, bundle_off, bundle_packed, default_bin, num_bin)
    gl_num = numerical_goes_left(b, threshold, default_left, missing_type,
                                 default_bin, num_bin)
    gl_cat = categorical_goes_left(b, cat_bitset)
    goes_left = jnp.where(is_categorical, gl_cat, gl_num)
    # stable 3-key sort: left rows (0), right rows (1), out-of-leaf tail (2).
    # The row ids ride through the sort network as a payload operand —
    # regular compare-exchange data movement instead of the random
    # idx[argsort(key)] gather (gathers are the expensive op on TPU).
    key = jnp.where(valid, jnp.where(goes_left, 0, 1), 2).astype(jnp.int32)
    _, new_slice = lax.sort([key, idx], num_keys=1, is_stable=True)
    left_count = jnp.sum((key == 0).astype(jnp.int32))
    new_indices = lax.dynamic_update_slice(indices, new_slice, (begin,))
    return new_indices, left_count


@functools.partial(jax.jit, static_argnames=("n_pad",))
def leaf_value_fill(leaf_begin: jax.Array, leaf_count: jax.Array,
                    leaf_value: jax.Array, n_pad: int) -> jax.Array:
    """Per-POSITION leaf values from the final partition: leaves are disjoint
    contiguous [begin, begin+count) segments, so a difference array with
    +(id+1) at each begin and -(id+1) at each end, cumsum'd, yields the id
    of the covering leaf at every position — L tiny scatters + one integer
    prefix sum + one gather instead of a per-row tree traversal.

    The cover ids are INTEGER so the fill is exact: a float ±value cumsum
    telescopes rounding noise that depends on where the segment sits in the
    partition, which breaks bitwise score parity between the global (serial)
    and per-shard (data-parallel) partition layouts of the same tree.
    """
    live = leaf_count > 0
    ids = jnp.arange(leaf_value.shape[0], dtype=jnp.int32) + 1
    d = jnp.zeros(n_pad + 1, jnp.int32)
    d = d.at[jnp.where(live, leaf_begin, n_pad)].add(jnp.where(live, ids, 0))
    d = d.at[jnp.where(live, leaf_begin + leaf_count, n_pad)].add(
        jnp.where(live, -ids, 0))
    cover = jnp.cumsum(d[:-1])  # 0 outside every leaf, id+1 inside leaf id
    vpad = jnp.concatenate(
        [jnp.zeros((1,), leaf_value.dtype), leaf_value])
    return vpad[cover]


@functools.partial(jax.jit, static_argnames=("n",))
def unpermute_to_rows(indices: jax.Array, values: jax.Array,
                      count: jax.Array, n: int) -> jax.Array:
    """Map per-POSITION values back to per-ROW order: position p holds row id
    `indices[p]`, so sorting (key=row id, payload=value) recovers row order.
    A key-sort moves data through regular compare-exchange networks — far
    faster on TPU than a 1-element random scatter/gather per row.

    Requires `indices[:count]` to be a permutation of [0, n) (fresh
    no-bagging partition); positions beyond `count` get key n+p so they sort
    to the tail. Bagged iterations must use the traversal path instead
    (out-of-bag rows also need scores, reference gbdt.cpp:487-506).

    Only the live prefix [0, n) is sorted: every leaf slice lives inside
    [0, root_count) and root_count <= n, so the pow2 padding tail never
    holds data.
    """
    head = lax.slice(indices, (0,), (n,))
    vals = lax.slice(values, (0,), (n,))
    pos = jnp.arange(n, dtype=jnp.int32)
    key = jnp.where(pos < count, head, n + pos)
    _, sval = lax.sort([key, vals], num_keys=1)
    return sval


@functools.partial(jax.jit, static_argnames=("n", "n_pad"))
def init_partition(n: int, n_pad: int) -> jax.Array:
    """Root partition: identity permutation; the tail repeats row n-1 (tail
    entries are never addressed — leaf (begin, count) bookkeeping keeps all
    real slices inside [0, n))."""
    idx = jnp.arange(n_pad, dtype=jnp.int32)
    return jnp.where(idx < n, idx, n - 1)


def init_partition_from(indices, n_pad: int) -> jax.Array:
    """Root partition from a bagging subset (reference
    `DataPartition::Init` with used_indices, data_partition.hpp:59)."""
    idx = jnp.asarray(indices, jnp.int32)
    n = idx.shape[0]
    if n >= n_pad:
        return idx[:n_pad]
    pad_val = idx[-1] if n else jnp.int32(0)
    return jnp.concatenate(
        [idx, jnp.full((n_pad - n,), pad_val, jnp.int32)])
