"""Histogram construction — the hottest loop of the framework.

Reference semantics: `DenseBin::ConstructHistogram` (4-way unrolled CPU
scatter-add, `src/io/dense_bin.hpp:71-137`) and the OpenCL kernels with
local-memory float atomics (`src/treelearner/ocl/histogram256.cl:100-125`).

TPU has no fast scatter-add, so the formulation is flipped into an MXU
contraction: for a chunk of rows, build the exact {0,1} one-hot of
(feature, bin) and contract it against the per-row payload
``[grad, hess, 1]``.  ``hist[f, b, w] = Σ_rows onehot[row, f, b] * w[row, w]``
— a batched matmul XLA tiles onto the systolic array.  bf16 one-hots are
exact; payload precision is recovered with a hi/lo split (two bf16 matmuls
≈ f32 accuracy), the TPU analogue of the reference's `gpu_use_dp` choice
(`gpu_tree_learner.cpp:306`).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

# payload columns: gradient, hessian, count
NUM_HIST_STATS = 3


def _chunk_histogram(bins_chunk: jax.Array, payload: jax.Array,
                     max_bin: int, precision: str) -> jax.Array:
    """Histogram of one row-chunk.

    bins_chunk: int32 [K, F] (out-of-range bin == masked row)
    payload:    f32 [K, 3]  (grad, hess, 1/0-mask)
    returns     f32 [F, max_bin, 3]
    """
    iota = lax.broadcasted_iota(jnp.int32, (1, 1, max_bin), 2)
    onehot = (bins_chunk[:, :, None] == iota)  # [K, F, B] bool
    if precision == "f64":
        # Exact accumulation: f64 sums of f32 payloads are order-independent
        # at any realistic leaf size (24-bit mantissa + log2(n) << 53 bits),
        # so psum-of-shard-partials == serial total bit-for-bit. This is the
        # topology-invariance anchor of the distributed runtime (the
        # reference's hist_t is double for the same reason).
        with jax.experimental.enable_x64():
            oh = onehot.astype(jnp.float64)
            return jnp.einsum("kfb,kw->fbw", oh,
                              payload.astype(jnp.float64),
                              precision=lax.Precision.HIGHEST)
    if precision == "f32":
        oh = onehot.astype(jnp.float32)
        return jnp.einsum("kfb,kw->fbw", oh, payload,
                          precision=lax.Precision.HIGHEST)
    oh = onehot.astype(jnp.bfloat16)
    if precision == "bf16":
        return jnp.einsum("kfb,kw->fbw", oh, payload.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    # bf16x2 (default): split payload into hi + lo bf16 parts; the one-hot is
    # exact in bf16, so two MXU passes recover ~f32 accuracy. The parts ride
    # as extra payload columns of ONE matmul and are summed in f32 afterwards
    # — two separate einsums would be re-fused by XLA's algebraic simplifier
    # into a single bf16 contraction, silently dropping the low part.
    p_hi = payload.astype(jnp.bfloat16)
    p_lo = (payload - p_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    both = jnp.concatenate([p_hi, p_lo], axis=1)            # [K, 2W]
    res = jnp.einsum("kfb,kw->fbw", oh, both,
                     preferred_element_type=jnp.float32)     # [F, B, 2W]
    w = payload.shape[1]
    return res[..., :w] + res[..., w:]


@functools.partial(jax.jit, static_argnames=("max_bin", "chunk", "precision"))
def histogram_from_gathered(bins_rows: jax.Array, grad: jax.Array,
                            hess: jax.Array, valid: jax.Array,
                            max_bin: int, chunk: int = 1 << 13,
                            precision: str = "bf16x2") -> jax.Array:
    """Build hist[F, max_bin, 3] from already-gathered (padded) leaf rows.

    bins_rows: uint8/int32 [P, F] — rows of the leaf, padded
    grad/hess: f32 [P]
    valid:     bool [P] — False for padding
    """
    return histogram_from_gathered_gh(
        bins_rows, jnp.stack([grad, hess], axis=1), valid, max_bin, chunk,
        precision)


@functools.partial(jax.jit, static_argnames=("max_bin", "chunk", "precision"))
def histogram_from_gathered_gh(bins_rows: jax.Array, gh: jax.Array,
                               valid: jax.Array, max_bin: int,
                               chunk: int = 1 << 13,
                               precision: str = "bf16x2") -> jax.Array:
    """Like `histogram_from_gathered` but with a pre-packed [P, 2]
    grad/hess payload — the caller gathers ONE wide array per leaf instead
    of two (random row gathers are the dominant cost on TPU)."""
    if jnp.issubdtype(gh.dtype, jnp.integer):
        # quantized payload (quantize_gh): the int8/int16 rows were
        # gathered at quarter/half the f32 bytes; accumulation runs in
        # f32 on the exact integer values (int16 |q| <= 32767 is exact
        # under the bf16 hi/lo split, int8 in a single bf16 pass), and
        # the caller rescales the finished histogram by the pack scale
        gh = gh.astype(jnp.float32)
    if precision == "pallas":
        from .pallas_hist import pallas_histogram
        return pallas_histogram(bins_rows, gh, valid, max_bin)
    p, f = bins_rows.shape
    bins_i = bins_rows.astype(jnp.int32)
    vmask = valid[:, None]
    payload = jnp.concatenate(
        [jnp.where(vmask, gh, 0.0),
         valid[:, None].astype(jnp.float32)], axis=1)  # [P, 3]
    if p <= chunk:
        return _chunk_histogram(bins_i, payload, max_bin, precision)
    # pad rows to a multiple of chunk, then accumulate chunk-wise so the
    # one-hot is only ever materialized chunk-wise
    n_chunks = (p + chunk - 1) // chunk
    pad = n_chunks * chunk - p
    if pad:
        bins_i = jnp.pad(bins_i, ((0, pad), (0, 0)), constant_values=-1)
        payload = jnp.pad(payload, ((0, pad), (0, 0)))
    bins_c = bins_i.reshape(n_chunks, chunk, f)
    pay_c = payload.reshape(n_chunks, chunk, NUM_HIST_STATS)

    def body(acc, xs):
        b, w = xs
        return acc + _chunk_histogram(b, w, max_bin, precision), None

    if precision == "f64":
        # the scan carry must be f64 too — a f32 carry would round every
        # chunk boundary and break the order-independence argument above
        with jax.experimental.enable_x64():
            init = jnp.zeros((f, max_bin, NUM_HIST_STATS), dtype=jnp.float64)
            acc, _ = lax.scan(body, init, (bins_c, pay_c))
        return acc
    init = jnp.zeros((f, max_bin, NUM_HIST_STATS), dtype=jnp.float32)
    acc, _ = lax.scan(body, init, (bins_c, pay_c))
    return acc


@functools.partial(jax.jit, static_argnames=("padded", "max_bin", "chunk",
                                             "precision"))
def leaf_histogram(bins: jax.Array, indices: jax.Array, begin: jax.Array,
                   count: jax.Array, grad: jax.Array, hess: jax.Array,
                   padded: int, max_bin: int, chunk: int = 1 << 13,
                   precision: str = "bf16x2") -> jax.Array:
    """Histogram of one leaf's rows out of the global partition.

    Mirrors the reference's ordered-gradient gather + per-group construct
    (`Dataset::ConstructHistograms`, `dataset.cpp:758-926`): gather the
    leaf's row ids from the partition ``indices[begin:begin+padded]``, then
    gather grad/hess/bins by row id and contract.

    bins:    uint8 [N_pad, F] full binned matrix in HBM
    indices: int32 [N_pad] partition array (leaf rows contiguous)
    begin:   scalar int32 — leaf start offset in `indices`
    count:   scalar int32 — actual number of rows in the leaf (≤ padded)
    padded:  static python int — padded slice length
    """
    idx = lax.dynamic_slice(indices, (begin,), (padded,))
    pos = jnp.arange(padded, dtype=jnp.int32)
    valid = pos < count
    safe_idx = jnp.where(valid, idx, 0)
    rows = bins[safe_idx]                      # [P, F]
    g = grad[safe_idx]
    h = hess[safe_idx]
    return histogram_from_gathered(rows, g, h, valid, max_bin, chunk,
                                   precision)


@functools.partial(jax.jit, static_argnames=("bits",))
def quantize_gh(gh: jax.Array, bits: int, key: jax.Array
                ) -> Tuple[jax.Array, jax.Array]:
    """Stochastic-rounded per-column quantization of the [N, 2]
    grad/hess payload (the TPU analogue of the reference's quantized
    gradient work, `gradient_discretizer.cpp`): ``q = clip(floor(gh /
    scale + u), -qmax, qmax)`` with ``u ~ U[0, 1)`` per element, so
    ``E[q * scale] == gh`` — the rounding noise is unbiased and a fresh
    key per tree keeps it independent across boosting rounds.

    Returns ``(q int8/int16 [N, 2], scale f32 [2])``. Scales are the
    per-column absmax over qmax (floored so all-zero hessians stay
    finite); the caller multiplies finished histograms and leaf sums by
    ``scale`` to return to f32 gradient units.
    """
    qmax = 127.0 if bits == 8 else 32767.0
    absmax = jnp.max(jnp.abs(gh), axis=0)
    scale = jnp.maximum(absmax / qmax, 1e-30).astype(jnp.float32)
    u = jax.random.uniform(key, gh.shape, dtype=jnp.float32)
    q = jnp.clip(jnp.floor(gh / scale + u), -qmax, qmax)
    return q.astype(jnp.int8 if bits == 8 else jnp.int16), scale


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """larger-child = parent − smaller-child (reference
    `FeatureHistogram::Subtract`, `feature_histogram.hpp:75`)."""
    return parent - child


def histogram_from_words(words, g: jax.Array, h: jax.Array,
                         valid: jax.Array, num_features: int, max_bin: int,
                         chunk: int = 1 << 16,
                         precision: str = "bf16x2") -> jax.Array:
    """Histogram over PACKED bin words (level builder record layout:
    4 uint8 bins per int32, word w bits 8j..8j+7 = feature 4w+j).

    words: list of int32 [P] (ceil(F/4) arrays); g/h: f32 [P];
    valid: bool [P]. Returns f32 [F, max_bin, 3].

    On TPU this runs as a Pallas kernel that unpacks the words in VMEM
    (contiguous lane-oriented reads — the replacement for the leaf-wise
    path's random row gather); elsewhere the words are unpacked in XLA and
    the einsum path is reused.
    """
    if precision == "pallas":
        from .pallas_hist import pallas_histogram_words
        return pallas_histogram_words(words, g, h, valid, num_features,
                                      max_bin)
    cols = []
    for f in range(num_features):
        w = words[f >> 2]
        cols.append((w >> ((f & 3) * 8)) & 255)
    bins = jnp.stack(cols, axis=1)
    gh = jnp.stack([g, h], axis=1)
    return histogram_from_gathered_gh(bins, gh, valid, max_bin, chunk,
                                      precision)
