"""Evaluation metrics.

Re-creates the reference metric zoo (`src/metric/*.hpp`, factory
`src/metric/metric.cpp:16-60`) with the same interface: `eval(raw_scores,
objective)` applying the objective's `ConvertOutput` when present, returning
named values plus `bigger_is_better` for early stopping
(`include/LightGBM/metric.h`).

Host NumPy (f64) implementations: metrics run once per iteration over the
label vector — bandwidth-trivial next to histogram work — and exact f64
averages match the reference's double accumulators.
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from .ranking import dcg_at_k, dcg_discounts, max_dcg_at_k

K_EPSILON = 1e-15


def _safe_log(x):
    return np.log(np.maximum(x, 1e-308))


class Metric:
    name: str = ""
    bigger_is_better: bool = False

    def __init__(self, cfg: Config) -> None:
        self.cfg = cfg

    def init(self, metadata, num_data: int) -> None:
        self.label = np.asarray(metadata.label, np.float64) \
            if metadata.label is not None else np.zeros(num_data)
        self.weight = (np.asarray(metadata.weight, np.float64)
                       if metadata.weight is not None else None)
        self.num_data = num_data
        self.sum_weights = (float(self.weight.sum()) if self.weight is not None
                            else float(num_data))

    def eval(self, scores: np.ndarray, objective) -> List[Tuple[str, float]]:
        raise NotImplementedError

    def eval_dev(self, scores_dev, objective):
        """Device-side eval over a DEVICE score matrix, returning
        [(name, device_scalar)] — or None when this metric has no device
        implementation (the caller falls back to the host path). Lets
        per-iteration valid evals avoid pulling full score arrays over
        the host link."""
        return None


class _PointwiseMetric(Metric):
    """Weighted mean of a pointwise loss with ConvertOutput applied
    (reference RegressionMetric::Eval, regression_metric.hpp:50-95)."""
    use_convert = True

    def loss(self, label: np.ndarray, pred: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def average(self, sum_loss: float) -> float:
        return sum_loss / self.sum_weights

    def eval(self, scores, objective):
        pred = scores[0].astype(np.float64)
        if self.use_convert and objective is not None:
            pred = objective.convert_output(pred)
        pt = self.loss(self.label, pred)
        if self.weight is not None:
            s = float(np.sum(pt * self.weight))
        else:
            s = float(np.sum(pt))
        return [(self.name, self.average(s))]


class L2Metric(_PointwiseMetric):
    name = "l2"

    def loss(self, y, p):
        return (p - y) ** 2


class RMSEMetric(L2Metric):
    name = "rmse"

    def average(self, s):
        return math.sqrt(s / self.sum_weights)


class L1Metric(_PointwiseMetric):
    name = "l1"

    def loss(self, y, p):
        return np.abs(p - y)


class QuantileMetric(_PointwiseMetric):
    name = "quantile"

    def loss(self, y, p):
        delta = y - p
        return np.where(delta < 0, (self.cfg.alpha - 1.0) * delta,
                        self.cfg.alpha * delta)


class HuberMetric(_PointwiseMetric):
    name = "huber"

    def loss(self, y, p):
        d = p - y
        a = self.cfg.alpha
        return np.where(np.abs(d) <= a, 0.5 * d * d,
                        a * (np.abs(d) - 0.5 * a))


class FairMetric(_PointwiseMetric):
    name = "fair"

    def loss(self, y, p):
        x = np.abs(p - y)
        c = self.cfg.fair_c
        return c * x - c * c * np.log(1.0 + x / c)


class PoissonMetric(_PointwiseMetric):
    name = "poisson"

    def loss(self, y, p):
        p = np.maximum(p, 1e-10)
        return p - y * np.log(p)


class MAPEMetric(_PointwiseMetric):
    name = "mape"

    def loss(self, y, p):
        return np.abs(y - p) / np.maximum(1.0, np.abs(y))


class GammaMetric(_PointwiseMetric):
    name = "gamma"

    def loss(self, y, p):
        # (regression_metric.hpp:261-268)
        theta = -1.0 / p
        b = -_safe_log(-theta)
        c = _safe_log(y) - _safe_log(y)  # psi=1: log(y/1) - log(y) = 0
        return -((y * theta - b) + c)


class GammaDevianceMetric(_PointwiseMetric):
    name = "gamma_deviance"

    def loss(self, y, p):
        tmp = y / (p + 1e-9)
        return tmp - _safe_log(tmp) - 1.0

    def average(self, s):
        return s * 2.0


class TweedieMetric(_PointwiseMetric):
    name = "tweedie"

    def loss(self, y, p):
        rho = self.cfg.tweedie_variance_power
        eps = 1e-10
        p = np.maximum(p, eps)
        a = y * np.exp((1 - rho) * np.log(p)) / (1 - rho)
        b = np.exp((2 - rho) * np.log(p)) / (2 - rho)
        return -a + b


class BinaryLoglossMetric(_PointwiseMetric):
    name = "binary_logloss"

    def loss(self, y, p):
        # (binary_metric.hpp:119-131)
        pos = y > 0
        out = np.zeros_like(p)
        neg_ok = (1.0 - p) > K_EPSILON
        pos_ok = p > K_EPSILON
        out = np.where(pos, np.where(pos_ok, -np.log(np.maximum(p, 1e-300)),
                                     -np.log(K_EPSILON)),
                       np.where(neg_ok, -np.log(np.maximum(1 - p, 1e-300)),
                                -np.log(K_EPSILON)))
        return out


class BinaryErrorMetric(_PointwiseMetric):
    name = "binary_error"

    def loss(self, y, p):
        return np.where(p <= 0.5, (y > 0).astype(float),
                        (y <= 0).astype(float))


class AUCMetric(Metric):
    """Weighted rank-sum AUC on raw scores (binary_metric.hpp:159-240)."""
    name = "auc"
    bigger_is_better = True

    def eval(self, scores, objective):
        score = scores[0].astype(np.float64)
        y = self.label > 0
        w = (self.weight if self.weight is not None
             else np.ones_like(score))
        order = np.argsort(score, kind="mergesort")
        s, ys, ws = score[order], y[order], w[order]
        # tie groups share the average rank: accumulate per distinct score
        pos_w = ws * ys
        neg_w = ws * (~ys)
        # cumulative negative weight strictly below each element + half ties
        boundaries = np.nonzero(np.diff(s))[0]
        group_id = np.zeros(len(s), np.int64)
        group_id[1:] = np.cumsum(np.diff(s) != 0)
        n_groups = group_id[-1] + 1 if len(s) else 0
        gsum_neg = np.bincount(group_id, weights=neg_w, minlength=n_groups)
        gsum_pos = np.bincount(group_id, weights=pos_w, minlength=n_groups)
        cum_neg_before = np.concatenate([[0], np.cumsum(gsum_neg)[:-1]])
        acc = float(np.sum(gsum_pos * (cum_neg_before + 0.5 * gsum_neg)))
        total_pos = float(pos_w.sum())
        total_neg = float(neg_w.sum())
        if total_pos <= 0 or total_neg <= 0:
            return [(self.name, 1.0)]
        return [(self.name, acc / (total_pos * total_neg))]

    def eval_dev(self, scores_dev, objective):
        import jax
        import jax.numpy as jnp
        fn = getattr(self, "_dev_fn", None)
        if fn is None:
            weighted = self.weight is not None

            @jax.jit
            def fn(score, y, w):
                order = jnp.argsort(score)
                s = score[order]
                yo = y[order]
                newg = jnp.concatenate(
                    [jnp.zeros(1, jnp.int32),
                     (s[1:] != s[:-1]).astype(jnp.int32)])
                gid = jnp.cumsum(newg)
                n = s.shape[0]
                if weighted:
                    # f32 scatter/scan path: log-depth reductions keep
                    # relative error ~1e-6 — consistent across
                    # iterations, so early-stopping comparisons are
                    # stable even where the absolute value drifts from
                    # the host f64 metric in the 6th decimal
                    wo = w[order]
                    pos_w = wo * yo
                    neg_w = wo * (1.0 - yo)
                    gneg = jnp.zeros(n, jnp.float32).at[gid].add(neg_w)
                    gpos = jnp.zeros(n, jnp.float32).at[gid].add(pos_w)
                    cumneg = jnp.cumsum(gneg)
                else:
                    # unweighted: integer counts — scatter-adds and the
                    # cumsum are EXACT (counts < 2^31); only the final
                    # per-group products drop to f32
                    yi = yo.astype(jnp.int32)
                    gpos = jnp.zeros(n, jnp.int32).at[gid].add(yi)
                    gneg = jnp.zeros(n, jnp.int32).at[gid].add(1 - yi)
                    cumneg = jnp.cumsum(gneg)
                before = (cumneg - gneg).astype(jnp.float32)
                acc = jnp.sum(gpos.astype(jnp.float32)
                              * (before
                                 + 0.5 * gneg.astype(jnp.float32)))
                tp = jnp.sum(gpos).astype(jnp.float32)
                tn = jnp.sum(gneg).astype(jnp.float32)
                bad = (tp <= 0) | (tn <= 0)
                return jnp.where(bad, 1.0,
                                 acc / jnp.maximum(tp * tn, 1e-30))
            self._dev_fn = fn
            self._y_dev = jnp.asarray(
                (self.label > 0).astype(np.float32))
            self._w_dev = (jnp.asarray(self.weight, jnp.float32)
                           if self.weight is not None
                           else jnp.zeros(1, jnp.float32))
        return [(self.name, self._dev_fn(scores_dev[0], self._y_dev,
                                         self._w_dev))]


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, scores, objective):
        # scores [K, N] raw
        k, n = scores.shape
        raw = scores.astype(np.float64).T  # [N, K]
        if objective is not None:
            p = objective.convert_output(raw)
        else:
            p = raw
        li = self.label.astype(np.int64)
        pl = np.maximum(p[np.arange(n), li], K_EPSILON)
        pt = -np.log(pl)
        if self.weight is not None:
            s = float(np.sum(pt * self.weight))
        else:
            s = float(np.sum(pt))
        return [(self.name, s / self.sum_weights)]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, scores, objective):
        k, n = scores.shape
        raw = scores.astype(np.float64).T
        li = self.label.astype(np.int64)
        topk = self.cfg.multi_error_top_k
        # error when the true class is not within top-k scores
        # (multiclass_metric.hpp:158+)
        true_score = raw[np.arange(n), li]
        rank = np.sum(raw > true_score[:, None], axis=1)
        pt = (rank >= topk).astype(np.float64)
        if self.weight is not None:
            s = float(np.sum(pt * self.weight))
        else:
            s = float(np.sum(pt))
        return [(self.name, s / self.sum_weights)]


class _RankMetric(Metric):
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError(f"{self.name} metric requires query information")
        self.qb = np.asarray(metadata.query_boundaries, np.int64)
        self.num_queries = len(self.qb) - 1
        # per-query weights (sum to num_queries by default)
        self.query_weights = metadata.query_weights


class NDCGMetric(_RankMetric):
    name = "ndcg"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.label_gain = np.asarray(self.cfg.label_gain, np.float64)
        self.eval_at = list(self.cfg.eval_at)
        li = self.label.astype(np.int64)
        self.max_dcgs = {
            k: np.asarray([
                max_dcg_at_k(k, li[self.qb[q]:self.qb[q + 1]],
                             self.label_gain)
                for q in range(self.num_queries)])
            for k in self.eval_at
        }

    def eval(self, scores, objective):
        score = scores[0].astype(np.float64)
        li = self.label.astype(np.int64)
        out = []
        for k in self.eval_at:
            accum = 0.0
            for q in range(self.num_queries):
                lo, hi = self.qb[q], self.qb[q + 1]
                m = self.max_dcgs[k][q]
                if m <= 0:
                    accum += 1.0
                else:
                    accum += dcg_at_k(k, li[lo:hi], score[lo:hi],
                                      self.label_gain) / m
            out.append((f"{self.name}@{k}", accum / self.num_queries))
        return out


class MAPMetric(_RankMetric):
    name = "map"

    def eval(self, scores, objective):
        score = scores[0].astype(np.float64)
        y = (self.label > 0).astype(np.float64)
        out = []
        for k in self.cfg.eval_at:
            accum = 0.0
            for q in range(self.num_queries):
                lo, hi = self.qb[q], self.qb[q + 1]
                order = np.argsort(-score[lo:hi], kind="stable")
                rel = y[lo:hi][order][:k]
                hits = np.cumsum(rel)
                denom = np.arange(1, len(rel) + 1)
                npos = y[lo:hi].sum()
                if npos > 0:
                    accum += float(np.sum(rel * hits / denom)
                                   / min(npos, k))
                else:
                    accum += 1.0
            out.append((f"{self.name}@{k}", accum / self.num_queries))
        return out


class CrossEntropyMetric(_PointwiseMetric):
    name = "xentropy"

    def loss(self, y, p):
        p = np.clip(p, K_EPSILON, 1 - K_EPSILON)
        return -y * np.log(p) - (1 - y) * np.log(1 - p)


class CrossEntropyLambdaMetric(Metric):
    name = "xentlambda"

    def eval(self, scores, objective):
        # (xentropy_metric.hpp:166+): scores converted via lambda link
        raw = scores[0].astype(np.float64)
        if objective is not None and objective.name == "xentlambda":
            lam = objective.convert_output(raw)
        else:
            lam = np.log1p(np.exp(raw))
        w = self.weight if self.weight is not None else np.ones_like(raw)
        y = self.label
        hhat = lam * w
        p = 1.0 - np.exp(-hhat)
        p = np.clip(p, K_EPSILON, 1 - K_EPSILON)
        pt = -y * np.log(p) - (1 - y) * np.log(1 - p)
        return [(self.name, float(np.sum(pt)) / self.num_data)]


class KLDivMetric(_PointwiseMetric):
    name = "kldiv"

    def loss(self, y, p):
        p = np.clip(p, K_EPSILON, 1 - K_EPSILON)
        yy = np.clip(y, K_EPSILON, 1 - K_EPSILON)
        # KL(y||p) = xent(y,p) - entropy(y)
        return (yy * np.log(yy) + (1 - yy) * np.log(1 - yy)
                - y * np.log(p) - (1 - y) * np.log(1 - p))


_METRICS = {
    "l1": L1Metric, "l2": L2Metric, "rmse": RMSEMetric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MAPEMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "multi_logloss": MultiLoglossMetric,
    "multi_error": MultiErrorMetric, "ndcg": NDCGMetric, "map": MAPMetric,
    "xentropy": CrossEntropyMetric, "xentlambda": CrossEntropyLambdaMetric,
    "kldiv": KLDivMetric,
}

_DEFAULT_METRIC_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber",
    "fair": "fair", "poisson": "poisson", "quantile": "quantile",
    "mape": "mape", "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary_logloss", "multiclass": "multi_logloss",
    "multiclassova": "multi_logloss", "xentropy": "xentropy",
    "xentlambda": "xentlambda", "lambdarank": "ndcg",
}


def metric_names(cfg: Config) -> List[str]:
    """Resolve configured metric list with the objective default
    (reference Config::CheckParamConflict + metric.cpp:16)."""
    names = [m for m in cfg.metric if m]
    if not names:
        default = _DEFAULT_METRIC_FOR_OBJECTIVE.get(cfg.objective)
        if default:
            names = [default]
    return [n for n in names if n != "none"]


def create_metrics(cfg: Config, names: Optional[Sequence[str]] = None
                   ) -> List[Metric]:
    out = []
    for n in (names if names is not None else metric_names(cfg)):
        cls = _METRICS.get(n)
        if cls is None:
            raise ValueError(f"Unknown metric: {n}")
        out.append(cls(cfg))
    return out
