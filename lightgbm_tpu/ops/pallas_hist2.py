"""Transposed-orientation Pallas histogram kernel (v2).

The v1 kernel (`pallas_hist.py`) contracts ``onehot[C,B]^T x payload[C,6]``
per feature: the matmul's OUTPUT is only 6 lanes wide, so the MXU runs at
a few percent of peak (measured 18-20 ns/row at B=256 on v5e). This kernel
flips the orientation:

    out[6, F*B] += payT[6, C] @ onehot[C, F*B]

The output now spans the full flattened (feature, bin) lane axis, the
contraction runs over the row-chunk, and the 6 payload rows (g/h/count as
bf16 hi+lo pairs) ride the sublane axis whose minimum tile is 8 anyway —
nothing is wasted. The one-hot block is generated in VMEM lane-tile by
lane-tile from the packed bin words and never touches HBM.

Data layout contract (shared with the level builder):
  words_rm: int32 [P, wcnt] row-major packed bins — word w bits 8j..8j+7
            hold feature 4w+j (see `level_builder.pack_bin_words`; this
            kernel wants the PRE-transposed [P, wcnt] layout so a row-chunk
            block puts rows on sublanes).
  payT:     f32 [3, P] (g, h, valid) — transposed so the chunk block is
            [3, C] with rows on lanes, ready to be the matmul LHS.

Reference analogue: `src/treelearner/ocl/histogram256.cl:350` (workgroup
histograms with local-memory atomics); numerics match the exact-bf16
one-hot + hi/lo payload argument of `ops/histogram.py`.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False

NUM_STATS = 3


def _hist2_kernel(words_ref, pay_ref, out_ref, *, num_features: int,
                  max_bin: int, fb_pad: int, chunk: int):
    """Grid step: out[8, FB] += payT_hi_lo[8, C] @ onehot[C, FB]."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    pay = pay_ref[...]                             # [3, C] f32
    p_hi = pay.astype(jnp.bfloat16)
    p_lo = (pay - p_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    zero = jnp.zeros_like(p_hi[:1])
    lhs = jnp.concatenate([p_hi, p_lo, zero, zero], axis=0)   # [8, C] bf16

    # one-hot [C, FB]: for flat lane l = f*max_bin + b the row's value is
    # bin(f) + f*max_bin; build a per-lane "selector" from the word columns
    # and compare against a flat iota. Lane tiles are 128 wide; max_bin is
    # a power of two, so each 128-lane tile covers ≥1 whole features
    # (max_bin ≤ 128) or a slice of one feature (max_bin = 256).
    oh_tiles = []
    lanes_per_feat = max_bin
    for t in range(fb_pad // 128):
        lane0 = t * 128
        if lanes_per_feat >= 128:
            f = lane0 // lanes_per_feat
            boff = lane0 % lanes_per_feat
            if f >= num_features:
                oh_tiles.append(jnp.zeros((chunk, 128), jnp.bfloat16))
                continue
            w = words_ref[:, f // 4][:, None]      # [C, 1] int32
            col = (w >> ((f % 4) * 8)) & 255
            iota = lax.broadcasted_iota(jnp.int32, (chunk, 128), 1) + boff
            oh_tiles.append((col == iota).astype(jnp.bfloat16))
        else:
            nf = 128 // lanes_per_feat
            f0 = lane0 // lanes_per_feat
            iota = lax.broadcasted_iota(jnp.int32, (chunk, 128), 1)
            sel = jnp.full((chunk, 128), -1, jnp.int32)
            for k in range(nf):
                f = f0 + k
                if f >= num_features:
                    continue
                w = words_ref[:, f // 4][:, None]
                col = ((w >> ((f % 4) * 8)) & 255) + k * lanes_per_feat
                lane_lo = k * lanes_per_feat
                in_feat = (iota >= lane_lo) & (iota < lane_lo
                                               + lanes_per_feat)
                sel = jnp.where(in_feat, col, sel)
            oh_tiles.append((sel == iota).astype(jnp.bfloat16))
    onehot = jnp.concatenate(oh_tiles, axis=1)     # [C, FBpad] bf16
    out_ref[...] += lax.dot_general(
        lhs, onehot, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("num_features", "max_bin",
                                             "chunk"))
def hist2_words(words_rm: jax.Array, payT: jax.Array, num_features: int,
                max_bin: int, chunk: int = 1024) -> jax.Array:
    """hist[F, max_bin, 3] from row-major packed words + transposed payload.

    words_rm: int32 [P, wcnt]; payT: f32 [3, P] (g, h, valid-count).
    Rows beyond the real count must carry zero payload columns.
    """
    p, wcnt = words_rm.shape
    b_pad = max(64, 1 << (max_bin - 1).bit_length())
    fb = num_features * b_pad
    fb_pad = ((fb + 127) // 128) * 128
    n_chunks = max(1, (p + chunk - 1) // chunk)
    pad = n_chunks * chunk - p
    if pad:
        words_rm = jnp.pad(words_rm, ((0, pad), (0, 0)))
        payT = jnp.pad(payT, ((0, 0), (0, pad)))
    kernel = functools.partial(_hist2_kernel, num_features=num_features,
                               max_bin=b_pad, fb_pad=fb_pad, chunk=chunk)
    out = pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((chunk, wcnt), lambda i: (i, 0)),
            pl.BlockSpec((NUM_STATS, chunk), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((8, fb_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, fb_pad), jnp.float32),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=100 << 20),
    )(words_rm, payT)
    hist = (out[:NUM_STATS] + out[NUM_STATS:2 * NUM_STATS])  # [3, FBpad]
    hist = hist[:, :fb].reshape(NUM_STATS, num_features, b_pad)
    return jnp.transpose(hist, (1, 2, 0))[:, :max_bin, :]


def pack_words_rowmajor(bins: np.ndarray) -> np.ndarray:
    """uint8 bins [N, F] -> row-major packed int32 words [N, ceil(F/4)]."""
    n, f = bins.shape
    wcnt = (f + 3) // 4
    padded = np.zeros((n, wcnt * 4), np.uint8)
    padded[:, :f] = bins
    w = padded.reshape(n, wcnt, 4).astype(np.uint32)
    packed = (w[:, :, 0] | (w[:, :, 1] << 8) | (w[:, :, 2] << 16)
              | (w[:, :, 3] << 24))
    return packed.astype(np.int64).astype(np.int32)
