"""Ranking utilities shared by the lambdarank objective and NDCG/MAP metrics.

Re-creates the reference `DCGCalculator` (`src/metric/dcg_calculator.cpp`):
discount 1/log2(2+i), label gains 2^label-1 (configurable), max-DCG from
label counts. Adds the TPU-side query bucketing: queries padded to
power-of-two document counts so per-query pairwise work is batched into a few
fixed-shape device programs instead of a ragged host loop.
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import numpy as np

from ..utils import log


def dcg_discounts(n: int) -> np.ndarray:
    """discount[i] = 1/log2(2+i) (reference dcg_calculator.cpp:Init)."""
    return 1.0 / np.log2(2.0 + np.arange(n, dtype=np.float64))


def max_dcg_at_k(k: int, labels: np.ndarray, label_gain: np.ndarray) -> float:
    """reference DCGCalculator::CalMaxDCGAtK (dcg_calculator.cpp:53-77):
    accumulate discounts over labels sorted descending."""
    n = len(labels)
    k = min(k, n)
    if k <= 0:
        return 0.0
    sorted_gains = np.sort(label_gain[labels])[::-1]
    disc = dcg_discounts(k)
    return float(np.sum(sorted_gains[:k] * disc))


def dcg_at_k(k: int, labels: np.ndarray, scores: np.ndarray,
             label_gain: np.ndarray) -> float:
    """reference DCGCalculator::CalDCGAtK: DCG of score-sorted order."""
    n = len(labels)
    k = min(k, n)
    if k <= 0:
        return 0.0
    order = np.argsort(-scores, kind="stable")
    disc = dcg_discounts(k)
    return float(np.sum(label_gain[labels[order[:k]]] * disc))


def bucket_queries(query_boundaries: np.ndarray, min_size: int = 8,
                   include: Optional[np.ndarray] = None
                   ) -> Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Group queries by padded (power-of-two) document count.

    Returns {padded_size: (query_ids [Q], doc_idx [Q, S] int32,
    mask [Q, S] bool)} where doc_idx are global row ids (pads point at the
    query's first doc and are masked out). `include` (bool per query)
    restricts bucketing to a subset — the fused-kernel path uses it to
    route only its oversize leftovers here.

    Emits a `rank_buckets` log event (docs-per-bucket histogram and
    padded-pair waste %) at dataset construct time so ladder re-tuning
    is data-driven instead of hand-derived each bench round.
    """
    qb = np.asarray(query_boundaries, np.int64)
    counts = np.diff(qb)
    # pairwise work is O(S^2), so ladder spacing is pure padding waste
    # vs compiled-program count. From 32 to 256 docs — where real
    # ranking sets concentrate (MSLR queries are ~40..200 docs) — the
    # ladder runs QUARTER steps (pow2 + 1.25x/1.5x/1.75x): a 161-doc
    # query pads to 192 not 256 (1.78x fewer pairs), a 130-doc one to
    # 160 not 192, for at most ~9 extra compiled programs. BELOW 32 the
    # steps are pow2 only: the quarter rungs at 10/12/14/20/24/28 held
    # <2% of MSLR's pair work yet 6 of the ladder's ~15 compiled
    # programs — measured cold-start XLA compiles for nothing (the r05
    # mb=255 warm-up cliff; see ROUND7_NOTES.md). Above 256 the
    # ladder falls back to ~sqrt(2) spacing (pow2 + 1.5x midpoints) —
    # giant queries are rare enough that halved pair tensors no longer
    # pay for the extra compiles.
    ladder = []
    s = max(8, min_size)
    while s <= (1 << 20):
        ladder.append(s)
        if 32 <= s <= 256:
            ladder.extend([s + s // 4, s + s // 2, s + 3 * s // 4])
        elif s > 256:
            ladder.append(s + s // 2)
        s <<= 1
    ladder = sorted(set(ladder))
    sizes = {}
    for q, c in enumerate(counts):
        if include is not None and not include[q]:
            continue
        c = max(int(c), 1)
        need = max(c, min_size)
        s = next((x for x in ladder if x >= need), None)
        if s is None:       # beyond the ladder: plain pow2 rounding
            s = 1 << int(math.ceil(math.log2(need)))
        sizes.setdefault(s, []).append(q)
    if sizes:
        real_pairs = sum(int(counts[q]) ** 2
                         for qs in sizes.values() for q in qs)
        padded_pairs = sum(s * s * len(qs) for s, qs in sizes.items())
        log.event(
            "rank_buckets",
            queries=sum(len(qs) for qs in sizes.values()),
            docs=int(sum(counts[q] for qs in sizes.values() for q in qs)),
            buckets={str(s): [len(qs),
                              int(sum(counts[q] for q in qs))]
                     for s, qs in sorted(sizes.items())},
            pair_waste_pct=round(
                100.0 * (padded_pairs - real_pairs) / max(real_pairs, 1),
                1),
            subset=include is not None)
    out = {}
    for s, qids in sizes.items():
        qids = np.asarray(qids, np.int64)
        doc_idx = np.zeros((len(qids), s), np.int32)
        mask = np.zeros((len(qids), s), bool)
        for row, q in enumerate(qids):
            lo, hi = int(qb[q]), int(qb[q + 1])
            c = hi - lo
            doc_idx[row, :c] = np.arange(lo, hi, dtype=np.int32)
            doc_idx[row, c:] = lo
            mask[row, :c] = True
        out[s] = (qids, doc_idx, mask)
    return out
