"""Vectorized tree-ensemble prediction on device.

Re-creates the reference prediction paths — per-row node walk
(`Tree::Predict`, `tree.h:112-130`, `gbdt_prediction.cpp`) and bulk binned
scoring (`Tree::AddPredictionToScore`, `tree.cpp:112-204`) — as a batched
gather traversal: all rows advance one level per step through stacked node
arrays until every row reaches a leaf. Leaves are encoded as negative child
ids (`~leaf`), matching the reference layout.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.tree import Tree

MISSING_NONE_C, MISSING_ZERO_C, MISSING_NAN_C = 0, 1, 2


def stack_trees(trees: List[Tree], binned: bool) -> Dict[str, np.ndarray]:
    """Stack per-tree node arrays into [T, max_nodes] matrices (+ flat
    categorical bitsets) for batched traversal."""
    t_count = len(trees)
    max_nodes = max(max(t.num_leaves - 1, 1) for t in trees)
    max_leaves = max(t.num_leaves for t in trees)

    def zeros(dtype):
        return np.zeros((t_count, max_nodes), dtype=dtype)

    sf = zeros(np.int32)
    thr = np.zeros((t_count, max_nodes), np.float64)
    thr_bin = zeros(np.int32)
    dt = zeros(np.int8)
    lc = zeros(np.int32)
    rc = zeros(np.int32)
    dbin = zeros(np.int32)
    nbin = zeros(np.int32)
    cat_start = zeros(np.int32)
    cat_len = zeros(np.int32)
    leaf_val = np.zeros((t_count, max_leaves), np.float64)
    # flat bitset words across all trees
    words: List[int] = []
    word_tree_start = np.zeros(t_count, np.int32)
    num_leaves = np.zeros(t_count, np.int32)
    max_depth = 1
    for i, t in enumerate(trees):
        n = t.num_leaves - 1
        num_leaves[i] = t.num_leaves
        if n > 0:
            sf[i, :n] = (t.split_feature_inner if binned
                         else t.split_feature)[:n]
            thr[i, :n] = t.threshold[:n]
            thr_bin[i, :n] = t.threshold_in_bin[:n]
            dt[i, :n] = t.decision_type[:n]
            lc[i, :n] = t.left_child[:n]
            rc[i, :n] = t.right_child[:n]
            dbin[i, :n] = t.node_default_bin[:n]
            nbin[i, :n] = t.node_num_bin[:n]
            # exact depth from the child arrays (Tree.leaf_depth is only
            # populated by some builders; a static traversal bound must
            # never undershoot)
            stack = [(0, 1)]
            while stack:
                node, d = stack.pop()
                max_depth = max(max_depth, d)
                for c in (t.left_child[node], t.right_child[node]):
                    if c >= 0:
                        stack.append((int(c), d + 1))
        leaf_val[i, :t.num_leaves] = t.leaf_value[:t.num_leaves]
        word_tree_start[i] = len(words)
        bounds = t.cat_boundaries_inner if binned else t.cat_boundaries
        cats = t.cat_threshold_inner if binned else t.cat_threshold
        words.extend(int(w) for w in cats)
        for node in range(n):
            if t.node_is_categorical(node):
                ci = int(t.threshold_in_bin[node])
                cat_start[i, node] = word_tree_start[i] + bounds[ci]
                cat_len[i, node] = bounds[ci + 1] - bounds[ci]
    if not words:
        words = [0]
    return {
        "split_feature": sf, "threshold": thr, "threshold_in_bin": thr_bin,
        "decision_type": dt, "left_child": lc, "right_child": rc,
        "default_bin": dbin, "num_bin": nbin,
        "cat_start": cat_start, "cat_len": cat_len,
        "cat_words": np.asarray(words, np.uint32),
        "leaf_value": leaf_val, "num_leaves": num_leaves,
        "max_depth": max_depth,
    }


@jax.jit
def _predict_binned_stacked(bins, stk, bundle=None):
    """Depth-synchronized traversal of all trees over the binned matrix:
    a [T, N] node frontier advances one level per step for every tree at
    once (vs the seed per-tree `lax.scan` kept below as
    `_predict_binned_stacked_serial`). Returns [T, N] leaf indices.
    `bundle` = (col, boff, bpk) per-feature arrays under EFB."""
    n = bins.shape[0]
    dt = stk["decision_type"]
    thr_bin = stk["threshold_in_bin"]
    sf = stk["split_feature"]
    dbin = stk["default_bin"]
    nbin = stk["num_bin"]
    cstart = stk["cat_start"]
    clen = stk["cat_len"]
    cwords = stk["cat_words"]
    lc = stk["left_child"]
    rc = stk["right_child"]
    t_count = lc.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)[None, :]

    def take(a, idx):
        return jnp.take_along_axis(a, idx, axis=1)

    def body(node):
        safe = jnp.maximum(node, 0)                       # [T, N]
        feat = take(sf, safe)
        scol = feat if bundle is None else bundle[0][feat]
        fval = bins[rows, scol].astype(jnp.int32)
        d = take(dt, safe).astype(jnp.int32)
        default_left = (d & 2) != 0
        mt = (d >> 2) & 3
        tb = take(thr_bin, safe)
        db = take(dbin, safe)
        nb = take(nbin, safe)
        if bundle is not None:
            from .partition import bundle_unpack
            fval = bundle_unpack(fval, bundle[1][feat], bundle[2][feat],
                                 db, nb)
        is_default = jnp.where(mt == MISSING_ZERO_C, fval == db,
                               jnp.where(mt == MISSING_NAN_C,
                                         fval == nb - 1, False))
        num_left = jnp.where(is_default, default_left, fval <= tb)
        widx = jnp.clip(take(cstart, safe) + (fval >> 5), 0,
                        cwords.shape[0] - 1)
        cat_left = ((((cwords[widx] >> (fval & 31).astype(jnp.uint32))
                      & 1) != 0)
                    & ((fval >> 5) < take(clen, safe)))
        go_left = jnp.where((d & 1) != 0, cat_left, num_left)
        nxt = jnp.where(go_left, take(lc, safe), take(rc, safe))
        return jnp.where(node >= 0, nxt, node)

    node0 = jnp.where(stk["num_leaves"][:, None] <= 1,
                      jnp.full((t_count, n), -1, jnp.int32),
                      jnp.zeros((t_count, n), jnp.int32))
    node = lax.while_loop(lambda s: jnp.any(s >= 0), body, node0)
    return ~node  # [T, N]


@jax.jit
def _predict_binned_stacked_serial(bins, stk, bundle=None):
    """The seed traversal — one tree at a time (`lax.scan` + per-tree
    `while_loop`). Kept as the baseline `tools/bench_predict.py` measures
    the depth-synchronized paths against."""
    n = bins.shape[0]
    dt = stk["decision_type"]
    thr_bin = stk["threshold_in_bin"]
    sf = stk["split_feature"]
    dbin = stk["default_bin"]
    nbin = stk["num_bin"]
    cstart = stk["cat_start"]
    clen = stk["cat_len"]
    cwords = stk["cat_words"]

    def decide(tree_idx, node, fval, feat):
        d = dt[tree_idx, node].astype(jnp.int32)
        is_cat = (d & 1) != 0
        default_left = (d & 2) != 0
        mt = (d >> 2) & 3
        tb = thr_bin[tree_idx, node]
        db = dbin[tree_idx, node]
        nb = nbin[tree_idx, node]
        if bundle is not None:
            from .partition import bundle_unpack
            fval = bundle_unpack(fval, bundle[1][feat], bundle[2][feat],
                                 db, nb)
        base = fval <= tb
        is_default = jnp.where(mt == MISSING_ZERO_C, fval == db,
                               jnp.where(mt == MISSING_NAN_C,
                                         fval == nb - 1, False))
        num_left = jnp.where(is_default, default_left, base)
        # categorical: bit lookup in flat words
        word_idx = cstart[tree_idx, node] + (fval >> 5)
        in_range = (fval >> 5) < clen[tree_idx, node]
        w = cwords[jnp.clip(word_idx, 0, cwords.shape[0] - 1)]
        cat_left = (((w >> (fval & 31).astype(jnp.uint32)) & 1) != 0) \
            & in_range
        return jnp.where(is_cat, cat_left, num_left)

    lc = stk["left_child"]
    rc = stk["right_child"]
    t_count = lc.shape[0]

    def one_tree(carry, tree_idx):
        def cond(state):
            return jnp.any(state >= 0)

        def body(node):
            safe = jnp.maximum(node, 0)
            feat = sf[tree_idx, safe]                     # [N]
            scol = feat if bundle is None else bundle[0][feat]
            fval = bins[jnp.arange(n), scol].astype(jnp.int32)
            go_left = decide(tree_idx, safe, fval, feat)
            nxt = jnp.where(go_left, lc[tree_idx, safe], rc[tree_idx, safe])
            return jnp.where(node >= 0, nxt, node)

        node0 = jnp.where(stk["num_leaves"][tree_idx] <= 1,
                          jnp.full(n, -1, jnp.int32),
                          jnp.zeros(n, jnp.int32))
        node = lax.while_loop(cond, body, node0)
        return carry, ~node

    _, leaves = lax.scan(one_tree, 0, jnp.arange(t_count))
    return leaves  # [T, N]


class TreePredictor:
    """Batched prediction over a list of trees. The stacked forest is
    built (and uploaded) at most once per (instance, binned) pair — the
    serving path's cross-call cache is `serve.ForestEngine`."""

    def __init__(self, trees: List[Tree]) -> None:
        self.trees = trees
        self._stk_cache: Dict[bool, Dict[str, jax.Array]] = {}

    def _stacked(self, binned: bool):
        stk = self._stk_cache.get(binned)
        if stk is None:
            host = stack_trees(self.trees, binned)
            stk = {k: jnp.asarray(v) for k, v in host.items()
                   if isinstance(v, np.ndarray)}
            self._stk_cache[binned] = stk
        return stk

    def predict_binned_leaves(self, bins, bundle=None) -> jax.Array:
        """[T, N] leaf indices over binned data. `bundle` = (col, boff,
        bpk) device arrays when the matrix is EFB-bundled."""
        stk = self._stacked(binned=True)
        return _predict_binned_stacked(jnp.asarray(bins), stk, bundle)

    def predict_binned_score(self, bins) -> jax.Array:
        """[T, N] -> summed leaf values [N] (f64 on host for exactness is the
        caller's choice; device f32 here)."""
        leaves = self.predict_binned_leaves(bins)
        lv = self._stacked(binned=True)["leaf_value"].astype(jnp.float32)
        vals = jnp.take_along_axis(lv, leaves, axis=1)
        return vals.sum(axis=0)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """Raw-value prediction [N] (vectorized host traversal, f64 exact —
        the reference's Predictor path, predictor.hpp:66-115)."""
        return predict_raw_values(self.trees, X, leaf_index=False)


def flatten_forest(trees: List[Tree], num_class: int = 1) -> Dict[str, np.ndarray]:
    """Concatenate per-tree node arrays for the native batch predictor
    (src/native/predictor.cpp — the reference Predictor's flattened-walk
    layout). Returns contiguous arrays keyed as the C ABI expects."""
    t_count = len(trees)
    node_off = np.zeros(t_count + 1, np.int64)
    leaf_off = np.zeros(t_count + 1, np.int64)
    cat_bnd_off = np.zeros(t_count + 1, np.int64)
    cat_words_off = np.zeros(t_count + 1, np.int64)
    for i, t in enumerate(trees):
        node_off[i + 1] = node_off[i] + max(t.num_leaves - 1, 0)
        leaf_off[i + 1] = leaf_off[i] + t.num_leaves
        cat_bnd_off[i + 1] = cat_bnd_off[i] + len(t.cat_boundaries)
        cat_words_off[i + 1] = cat_words_off[i] + len(t.cat_threshold)
    total_nodes = int(node_off[-1])
    left = np.empty(max(total_nodes, 1), np.int32)
    right = np.empty(max(total_nodes, 1), np.int32)
    feat = np.zeros(max(total_nodes, 1), np.int32)
    thresh = np.zeros(max(total_nodes, 1), np.float64)
    dtype = np.zeros(max(total_nodes, 1), np.int8)
    leaf_value = np.zeros(max(int(leaf_off[-1]), 1), np.float64)
    cat_boundaries = np.zeros(max(int(cat_bnd_off[-1]), 1), np.int32)
    cat_words = np.zeros(max(int(cat_words_off[-1]), 1), np.uint32)
    num_leaves = np.asarray([t.num_leaves for t in trees], np.int32)
    for i, t in enumerate(trees):
        n = t.num_leaves - 1
        a, b = int(node_off[i]), int(node_off[i + 1])
        if n > 0:
            left[a:b] = t.left_child[:n]
            right[a:b] = t.right_child[:n]
            feat[a:b] = t.split_feature[:n]
            thresh[a:b] = t.threshold[:n]
            dtype[a:b] = t.decision_type[:n]
        la, lb = int(leaf_off[i]), int(leaf_off[i + 1])
        leaf_value[la:lb] = t.leaf_value[:t.num_leaves]
        ca, cb = int(cat_bnd_off[i]), int(cat_bnd_off[i + 1])
        cat_boundaries[ca:cb] = np.asarray(t.cat_boundaries, np.int32)
        wa, wb = int(cat_words_off[i]), int(cat_words_off[i + 1])
        if wb > wa:
            cat_words[wa:wb] = np.asarray(t.cat_threshold, np.uint32)
    return {
        "node_off": node_off, "leaf_off": leaf_off,
        "left": left, "right": right, "feat": feat, "thresh": thresh,
        "dtype": dtype, "leaf_value": leaf_value,
        "cat_bnd_off": cat_bnd_off, "cat_boundaries": cat_boundaries,
        "cat_words_off": cat_words_off, "cat_words": cat_words,
        "num_leaves": num_leaves,
        "tree_class": (np.arange(t_count, dtype=np.int32)
                       % max(num_class, 1)),
    }


def predict_raw_values(trees: List[Tree], X: np.ndarray,
                       leaf_index: bool = False) -> np.ndarray:
    """Vectorized NumPy traversal over raw feature values.

    Returns [N] summed values, or [N, T] leaf indices when leaf_index.
    Decision semantics mirror Tree::NumericalDecision / CategoricalDecision
    (tree.h:216-270) in f64.
    """
    X = np.asarray(X, np.float64)
    n = len(X)
    out = np.zeros(n, np.float64)
    leaves_out = np.zeros((n, len(trees)), np.int32) if leaf_index else None
    for ti, t in enumerate(trees):
        if t.num_leaves <= 1:
            if leaf_index:
                leaves_out[:, ti] = 0
            else:
                out += t.leaf_value[0]
            continue
        node = np.zeros(n, np.int32)
        active = np.ones(n, bool)
        while active.any():
            nd = node[active]
            feat = t.split_feature[nd]
            fval = X[active, feat]
            dt = t.decision_type[nd].astype(np.int32)
            is_cat = (dt & 1) != 0
            default_left = (dt & 2) != 0
            mt = (dt >> 2) & 3
            isnan = np.isnan(fval)
            # NaN -> 0 unless missing type is NaN (tree.h:218-222)
            fv = np.where(isnan & (mt != 2), 0.0, fval)
            is_default = ((mt == 1) & (np.abs(fv) <= 1e-35)) | \
                         ((mt == 2) & np.isnan(fv))
            go_left = np.where(is_default, default_left,
                               fv <= t.threshold[nd])
            if is_cat.any():
                cat_left = np.zeros(len(nd), bool)
                for j in np.nonzero(is_cat)[0]:
                    v = fval[j]
                    if np.isnan(v):
                        # NaN -> right only under missing_type NaN; else it
                        # degrades to category 0 (tree.h CategoricalDecision)
                        if mt[j] == 2:
                            cat_left[j] = False
                            continue
                        v = 0.0
                    iv = int(v)
                    if iv < 0:
                        cat_left[j] = False
                        continue
                    ci = int(t.threshold_in_bin[nd[j]])
                    lo, hi = t.cat_boundaries[ci], t.cat_boundaries[ci + 1]
                    w = iv // 32
                    cat_left[j] = (w < hi - lo and
                                   (t.cat_threshold[lo + w] >> (iv % 32)) & 1)
                go_left = np.where(is_cat, cat_left, go_left)
            nxt = np.where(go_left, t.left_child[nd], t.right_child[nd])
            node[active] = nxt
            active = node >= 0
        leaf = ~node
        if leaf_index:
            leaves_out[:, ti] = leaf
        else:
            out += t.leaf_value[leaf]
    return leaves_out if leaf_index else out
