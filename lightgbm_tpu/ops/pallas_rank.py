"""Segment-fused lambdarank gradient kernel (Pallas TPU).

The bucketed lambdarank path (`ops/objectives.py` + `ops/ranking.py`)
pads every query to a size ladder and materializes `[Q, S, S]` pair
tensors per bucket — O(S^2) HBM traffic, up to ~1.8x pure padding waste,
and one compiled program per ladder size. This module is the reference's
fused per-query pair loop (`rank_objective.hpp:GetGradientsForOneQuery`,
with its quantized sigmoid table at `rank_objective.hpp:71`) recast for
the TPU's vector memory:

* queries (CSR doc offsets) are packed host-side into fixed-size row
  TILES of `tile` doc slots, aligned so that no query straddles a
  128-slot SUBTILE boundary unless it is itself longer than a subtile
  (long queries get an exclusive, boundary-aligned run of subtiles);
* one Pallas program per dataset streams the score / label-gain /
  rank-position lanes of each tile through VMEM: rank positions come
  from a stable descending pair-count (no sort), DCG discounts from an
  exact one-hot MXU lookup against the same f64-derived table as the
  bucketed path, sigmoid pair factors are bf16 with f32 accumulation
  (score DIFFERENCES are formed in f32 first — bf16 subtraction of
  near-equal scores cancels catastrophically), and per-doc
  lambda/hessian column+row sums are scatter-accumulated once;
* pair math runs only on the static block BAND |subtile_i - subtile_j|
  < band implied by the packing (band = the longest packed query's
  subtile span), so cross-query slots cost a masked compare, not a
  padded pair tensor — and nothing `[Q, S, S]`-shaped ever exists in
  HBM.

`tpu_rank_sigmoid_bins > 0` reproduces the reference's quantized sigmoid
table semantics exactly: the sigmoid *input* is clamped to the table
range [-50, 50] and floored to the left edge of one of `bins` cells
before the (exact) sigmoid evaluates — identical values to looking up a
table built at cell left edges, without a memory-bound gather.

Used via `Config.tpu_rank_fused`; the bucketed path stays the
fallback/oracle (and handles queries longer than `tpu_rank_tile`).
Interpret mode (`interpret=True`) runs the kernel on CPU for tier-1.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import compile_cache
from .ranking import dcg_discounts

try:  # pallas is TPU-only here; import lazily-guarded for CPU test runs
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
    # jax renamed TPUCompilerParams -> CompilerParams (and grew fields
    # along the way). Accept either vintage.
    _CP_CLS = getattr(pltpu, "CompilerParams",
                      getattr(pltpu, "TPUCompilerParams", None))

    def _CompilerParams(**kw):
        import dataclasses
        known = {f.name for f in dataclasses.fields(_CP_CLS)}
        return _CP_CLS(**{k: v for k, v in kw.items() if k in known})
except Exception:  # pragma: no cover
    HAS_PALLAS = False

SUBTILE = 128   # query alignment quantum = one lane register width


class QueryTilePack(NamedTuple):
    """Host-side tile packing of a query CSR layout.

    doc_idx  [NT, tile] int32 — global row ids (pads point at row 0)
    qid      [NT, tile] int32 — global query id per slot, -1 for pads
    band     int — max subtile span of any packed query (static kernel
             constant: pair math runs on block pairs |a - b| < band)
    leftover [num_queries] bool — queries LONGER than a tile, left for
             the bucketed fallback path
    fill     float — fraction of slots holding real docs
    """
    doc_idx: np.ndarray
    qid: np.ndarray
    band: int
    leftover: np.ndarray
    fill: float

    @property
    def num_tiles(self) -> int:
        return int(self.doc_idx.shape[0])

    @property
    def tile(self) -> int:
        return int(self.doc_idx.shape[1])


def pack_query_tiles(query_boundaries: np.ndarray, tile: int,
                     sub: int = SUBTILE) -> QueryTilePack:
    """Greedy in-order packing of queries into fixed `tile`-slot tiles.

    Placement rules (they are what make the kernel's static block band
    correct): a query that fits the current subtile's remaining space is
    appended; one that does not starts at the next subtile boundary; one
    longer than a subtile starts at a boundary and owns ceil(c/sub)
    subtiles exclusively. Queries longer than `tile` are returned in
    `leftover` for the bucketed path.
    """
    assert tile % sub == 0 and tile >= sub, (tile, sub)
    qb = np.asarray(query_boundaries, np.int64)
    counts = np.diff(qb)
    nq = len(counts)
    leftover = counts > tile
    tiles_doc, tiles_qid = [], []
    cur_doc = np.zeros(tile, np.int32)
    cur_qid = np.full(tile, -1, np.int32)
    p = 0
    used = False
    band = 1
    docs_packed = 0

    def _flush():
        nonlocal cur_doc, cur_qid, p, used
        tiles_doc.append(cur_doc)
        tiles_qid.append(cur_qid)
        cur_doc = np.zeros(tile, np.int32)
        cur_qid = np.full(tile, -1, np.int32)
        p = 0
        used = False

    for q in range(nq):
        c = int(counts[q])
        if c <= 0 or leftover[q]:
            continue
        if c > sub:
            start = -(-p // sub) * sub          # align up to a subtile
        elif (p % sub) + c <= sub:
            start = p                           # fits the current subtile
        else:
            start = -(-p // sub) * sub
        if start + c > tile:
            _flush()
            start = 0
        cur_doc[start:start + c] = np.arange(qb[q], qb[q + 1],
                                             dtype=np.int32)
        cur_qid[start:start + c] = q
        band = max(band, -(-c // sub))
        p = start + c
        if c > sub:                             # exclusive subtile run
            p = -(-p // sub) * sub
        used = True
        docs_packed += c
    if used:
        _flush()
    if not tiles_doc:
        return QueryTilePack(np.zeros((0, tile), np.int32),
                             np.full((0, tile), -1, np.int32),
                             1, leftover, 0.0)
    doc_idx = np.stack(tiles_doc)
    qid = np.stack(tiles_qid)
    return QueryTilePack(doc_idx, qid, band, leftover,
                         docs_packed / float(doc_idx.size))


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------
def _band_range(c: int, nb: int, band: int):
    return range(max(0, c - band + 1), min(nb, c + band))


def _rank_tile_kernel(sr_ref, sc_ref, gr_ref, gc_ref, lr_ref, lc_ref,
                      qr_ref, qc_ref, invc_ref, disc_ref,
                      ga_ref, ha_ref, gb_ref, hb_ref, *,
                      tile: int, sub: int, band: int, sigmoid: float,
                      lut_bins: int):
    """One grid step = one tile. Row-layout refs are [1, tile] blocks,
    col-layout refs [tile, 1]; outputs split the per-doc sums into a
    column side ([tile, 1]: doc as the HIGHER-labelled pair member) and
    a row side ([1, tile]: doc as the lower member) so no in-kernel
    transpose is needed — the caller combines g = colsum.T - rowsum.

    Numerics mirror the bucketed oracle op-for-op: bf16 pair factors,
    f32 score differences and f32 accumulation, exact discount values
    via a one-hot MXU lookup of the f64-derived table.
    """
    f32 = jnp.float32
    bf = jnp.bfloat16
    nb = tile // sub
    s_row = sr_ref[...]
    s_col = sc_ref[...]
    q_row = qr_ref[...]
    q_col = qc_ref[...]
    l_row = lr_ref[...]
    l_col = lc_ref[...]
    g_row = gr_ref[...]
    g_col = gc_ref[...]
    inv_col = invc_ref[...]
    disc_tab = disc_ref[...]                      # [1, tile] f32

    def blk_r(x, b):                              # [1, sub]
        return x[:, b * sub:(b + 1) * sub]

    def blk_c(x, a):                              # [sub, 1]
        return x[a * sub:(a + 1) * sub, :]

    iota_i = lax.broadcasted_iota(jnp.int32, (sub, sub), 0)
    iota_j = lax.broadcasted_iota(jnp.int32, (sub, sub), 1)
    NEG = jnp.float32(-3.4e38)
    POS = jnp.float32(3.4e38)

    # ---- pass 1a: rank / best / worst per COLUMN block (doc as i) ----
    ranks_c, norm_c = [], []
    for a in range(nb):
        sa = blk_c(s_col, a)
        qa = blk_c(q_col, a)
        rank = jnp.zeros((sub, 1), jnp.int32)
        best = jnp.full((sub, 1), NEG, f32)
        worst = jnp.full((sub, 1), POS, f32)
        for b in _band_range(a, nb, band):
            sb = blk_r(s_row, b)
            qb = blk_r(q_row, b)
            same = (qa == qb) & (qa >= 0)
            gi = iota_i + a * sub
            gj = iota_j + b * sub
            # "j sorts before i" under stable descending order (pads
            # have qid -1 and never match)
            before = same & ((sb > sa) | ((sb == sa) & (gj < gi)))
            rank = rank + jnp.sum(before.astype(jnp.int32), axis=1,
                                  keepdims=True)
            best = jnp.maximum(best, jnp.max(
                jnp.where(same, sb, NEG), axis=1, keepdims=True))
            worst = jnp.minimum(worst, jnp.min(
                jnp.where(same, sb, POS), axis=1, keepdims=True))
        ranks_c.append(rank)
        norm_c.append(best != worst)

    # ---- pass 1b: rank per ROW block (doc as j) ----------------------
    ranks_r = []
    for b in range(nb):
        sb = blk_r(s_row, b)
        qb = blk_r(q_row, b)
        rank = jnp.zeros((1, sub), jnp.int32)
        for a in _band_range(b, nb, band):
            sa = blk_c(s_col, a)
            qa = blk_c(q_col, a)
            same = (qa == qb) & (qb >= 0)
            gi = iota_i + a * sub
            gj = iota_j + b * sub
            before = same & ((sa > sb) | ((sa == sb) & (gi < gj)))
            rank = rank + jnp.sum(before.astype(jnp.int32), axis=0,
                                  keepdims=True)
        ranks_r.append(rank)

    # ---- exact discount lookup (one-hot against the f64-derived
    # table: bitwise-identical values to the bucketed path) ------------
    iota_lane = lax.broadcasted_iota(jnp.int32, (sub, tile), 1)
    iota_subl = lax.broadcasted_iota(jnp.int32, (tile, sub), 0)
    disc_c = []
    for a in range(nb):
        oh = (ranks_c[a] == iota_lane).astype(f32)          # [sub, tile]
        disc_c.append(lax.dot_general(
            oh, disc_tab, (((1,), (1,)), ((), ())),
            preferred_element_type=f32))                    # [sub, 1]
    disc_r = []
    for b in range(nb):
        oh = (ranks_r[b] == iota_subl).astype(f32)          # [tile, sub]
        disc_r.append(lax.dot_general(
            disc_tab, oh, (((1,), (0,)), ((), ())),
            preferred_element_type=f32))                    # [1, sub]

    # ---- pass 2: banded pair math, bf16 factors / f32 sums -----------
    two_sig = jnp.float32(2.0 * sigmoid)
    zero = jnp.asarray(0.0, bf)
    acc_ga = [jnp.zeros((sub, 1), f32) for _ in range(nb)]
    acc_ha = [jnp.zeros((sub, 1), f32) for _ in range(nb)]
    acc_gb = [jnp.zeros((1, sub), f32) for _ in range(nb)]
    acc_hb = [jnp.zeros((1, sub), f32) for _ in range(nb)]
    for a in range(nb):
        sa = blk_c(s_col, a)
        qa = blk_c(q_col, a)
        la = blk_c(l_col, a)
        gna = blk_c(g_col, a).astype(bf)
        inva = blk_c(inv_col, a).astype(bf)
        dca = disc_c[a]
        na = norm_c[a]
        for b in _band_range(a, nb, band):
            sb = blk_r(s_row, b)
            qb = blk_r(q_row, b)
            lb = blk_r(l_row, b)
            gnb = blk_r(g_row, b).astype(bf)
            same = (qa == qb) & (qa >= 0)
            ds = (sa - sb).astype(bf)             # diff in f32 FIRST
            dgap = gna - gnb
            pd = jnp.abs(dca - disc_r[b]).astype(bf)
            delta = dgap * pd * inva
            delta = jnp.where(na, delta / (0.01 + jnp.abs(ds)), delta)
            x = ds.astype(f32)
            if lut_bins > 0:
                # reference quantized sigmoid table semantics
                # (rank_objective.hpp:71): clamp to [-50, 50], floor to
                # the cell's left edge, then evaluate exactly there
                factor = jnp.float32(lut_bins / 100.0)
                idx = jnp.clip(jnp.floor((jnp.clip(x, -50.0, 50.0)
                                          + 50.0) * factor),
                               0.0, float(lut_bins - 1))
                x = idx / factor - 50.0
            p_lambda = (2.0 / (1.0 + jnp.exp(two_sig * x))).astype(bf)
            p_hess = p_lambda * (2.0 - p_lambda)
            pv = (la > lb) & same
            lam = jnp.where(pv, -p_lambda * delta, zero)
            hes = jnp.where(pv, p_hess * 2.0 * delta, zero)
            acc_ga[a] = acc_ga[a] + jnp.sum(lam.astype(f32), axis=1,
                                            keepdims=True)
            acc_ha[a] = acc_ha[a] + jnp.sum(hes.astype(f32), axis=1,
                                            keepdims=True)
            acc_gb[b] = acc_gb[b] + jnp.sum(lam.astype(f32), axis=0,
                                            keepdims=True)
            acc_hb[b] = acc_hb[b] + jnp.sum(hes.astype(f32), axis=0,
                                            keepdims=True)
    ga_ref[...] = jnp.concatenate(acc_ga, axis=0)
    ha_ref[...] = jnp.concatenate(acc_ha, axis=0)
    gb_ref[...] = jnp.concatenate(acc_gb, axis=1)
    hb_ref[...] = jnp.concatenate(acc_hb, axis=1)


def make_fused_grad_fn(num_data: int, num_tiles: int, tile: int,
                       band: int, sigmoid: float, lut_bins: int = 0,
                       sub: int = SUBTILE, interpret: bool = False):
    """Jitted (score[n], doc_idx, qid, gain, label, inv, disc_tab) ->
    (g[n], h[n]). All tables are runtime args, so one compiled program
    serves every booster at the same shapes; register the result under
    `compile_cache.program` keyed by `fused_program_key(...)`."""
    if not HAS_PALLAS:  # pragma: no cover - import guard
        raise RuntimeError("pallas unavailable")
    kernel = functools.partial(
        _rank_tile_kernel, tile=tile, sub=sub, band=band,
        sigmoid=float(sigmoid), lut_bins=int(lut_bins))
    NT, T = num_tiles, tile

    def grad_fn(score, doc_idx, qid, gain, label, inv, disc_tab):
        compile_cache.note_trace()
        sc = jnp.where(qid >= 0, score[doc_idx], 0.0).astype(jnp.float32)
        row = pl.BlockSpec((1, T), lambda i: (i, 0))
        col = pl.BlockSpec((T, 1), lambda i: (0, i))
        gA, hA, gB, hB = pl.pallas_call(
            kernel,
            grid=(NT,),
            in_specs=[row, col, row, col, row, col, row, col, col,
                      pl.BlockSpec((1, T), lambda i: (0, 0))],
            out_specs=[col, col, row, row],
            out_shape=[
                jax.ShapeDtypeStruct((T, NT), jnp.float32),
                jax.ShapeDtypeStruct((T, NT), jnp.float32),
                jax.ShapeDtypeStruct((NT, T), jnp.float32),
                jax.ShapeDtypeStruct((NT, T), jnp.float32),
            ],
            compiler_params=_CompilerParams(vmem_limit_bytes=128 << 20),
            interpret=interpret,
        )(sc, sc.T, gain, gain.T, label, label.T, qid, qid.T,
          inv.T, disc_tab)
        g_t = jnp.where(qid >= 0, gA.T - gB, 0.0)
        h_t = jnp.where(qid >= 0, hA.T + hB, 0.0)
        flat = doc_idx.reshape(-1)
        g = jnp.zeros((num_data,), jnp.float32).at[flat].add(
            g_t.reshape(-1))
        h = jnp.zeros((num_data,), jnp.float32).at[flat].add(
            h_t.reshape(-1))
        return g, h

    return jax.jit(grad_fn)


def fused_program_key(num_data: int, pack: QueryTilePack, sigmoid: float,
                      lut_bins: int, interpret: bool):
    return ("rank_fused", num_data, pack.num_tiles, pack.tile,
            int(pack.band), SUBTILE, float(sigmoid), int(lut_bins),
            bool(interpret))


def discount_table(tile: int) -> np.ndarray:
    """[1, tile] f32 rank-position discounts — the same f64-derived
    values the bucketed path tabulates (dcg_calculator.cpp:Init)."""
    return dcg_discounts(tile).astype(np.float32)[None, :]
